"""Executor — persistent local actor pool with the launcher env contract.

Re-conception of ref: ray/runner.py RayExecutor (actor pool that starts
once and dispatches many functions) without requiring Ray: workers are
subprocesses running ``orchestrate.worker_loop``, coordinated through the
launcher's HMAC-authed HTTP KV (runner/http_kv.py), with the same env
contract the CLI launcher uses (HVDT_RANK/SIZE/...).  Results and
exceptions round-trip pickled per rank per call epoch.

Workers import only the light KV client — no JAX — so dispatched
functions decide their own runtime (and can hvd.init() themselves).
"""

from __future__ import annotations

import os
import pickle
import socket
import subprocess
import sys
import traceback
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..runner.http_kv import KVClient, RendezvousServer, new_secret

__all__ = ["Executor", "WorkerError"]


class WorkerError(RuntimeError):
    """A dispatched function raised on a worker; carries rank + traceback."""

    def __init__(self, rank: int, message: str):
        super().__init__(f"worker rank {rank} failed:\n{message}")
        self.rank = rank


def _dumps(obj: Any) -> bytes:
    try:
        import cloudpickle

        return cloudpickle.dumps(obj)
    except ImportError:
        return pickle.dumps(obj)


class Executor:
    """Start N persistent workers; run functions on all of them.

    Usage (mirrors ref RayExecutor::

        ex = Executor(num_workers=4)
        ex.start()
        results = ex.run(train_fn, args=(cfg,))
        ex.shutdown()

    Dispatched callables run as ``fn(*args, **kwargs)`` in the worker
    process with the HVDT_* env contract set, so ``hvd.init()`` inside the
    function sees the right rank/size.
    """

    def __init__(self, num_workers: int = 1,
                 env: Optional[Dict[str, str]] = None,
                 start_timeout: float = 60.0):
        self.num_workers = num_workers
        self._extra_env = dict(env or {})
        self._timeout = start_timeout
        self._server: Optional[RendezvousServer] = None
        self._procs: List[subprocess.Popen] = []
        self._epoch = 0
        self._started = False

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if self._started:
            return
        self._server = RendezvousServer(secret=new_secret())
        port = self._server.start()
        addr = "127.0.0.1"
        # Workers must be able to unpickle functions defined in modules
        # the driver imported from non-installed paths (tests, scripts):
        # propagate the driver's sys.path (ref: ray/spark ship the code
        # via cloudpickle-by-value / executor archives).
        py_path = os.pathsep.join(
            [p for p in sys.path if p] +
            [p for p in os.environ.get("PYTHONPATH", "").split(os.pathsep)
             if p])
        for rank in range(self.num_workers):
            env = dict(os.environ)
            env.update(self._extra_env)
            env["PYTHONPATH"] = py_path
            env.update({
                "HVDT_RANK": str(rank),
                "HVDT_SIZE": str(self.num_workers),
                "HVDT_LOCAL_RANK": str(rank),
                "HVDT_LOCAL_SIZE": str(self.num_workers),
                "HVDT_CROSS_RANK": "0",
                "HVDT_CROSS_SIZE": "1",
                "HVDT_HOSTNAME": socket.gethostname(),
                "HVDT_EXEC_ADDR": addr,
                "HVDT_EXEC_PORT": str(port),
                "HVDT_EXEC_SECRET": self._server.secret.hex(),
            })
            self._procs.append(subprocess.Popen(
                [sys.executable, "-m", "horovod_tpu.orchestrate.worker_loop"],
                env=env))
        client = self._client()
        try:
            for rank in range(self.num_workers):
                self._wait_key(client, f"/exec/ready/{rank}", rank,
                               self._timeout,
                               f"worker {rank} did not come up")
        except Exception:
            self.shutdown()
            raise
        self._started = True

    def _wait_key(self, client: KVClient, key: str, rank: int,
                  timeout: float, timeout_msg: str) -> bytes:
        """Wait for a key in short slices, failing fast if the worker
        process dies (a crashed worker would otherwise stall the driver
        for the whole timeout; ref: RayExecutor surfaces actor death)."""
        import time

        deadline = time.monotonic() + timeout
        while True:
            try:
                return client.wait(key, timeout=min(
                    1.0, max(0.05, deadline - time.monotonic())))
            except TimeoutError:
                proc = self._procs[rank] if rank < len(self._procs) else None
                if proc is not None and proc.poll() is not None:
                    raise WorkerError(
                        rank, f"worker process exited with code "
                              f"{proc.returncode} before answering") from None
                if time.monotonic() >= deadline:
                    raise TimeoutError(timeout_msg) from None

    def _client(self) -> KVClient:
        return KVClient("127.0.0.1", self._server.server_address[1],
                        self._server.secret)

    # -- dispatch ----------------------------------------------------------

    def run(self, fn: Callable, args: Sequence = (),
            kwargs: Optional[Dict] = None,
            timeout: float = 600.0,
            per_rank_args: Optional[Sequence[Sequence]] = None
            ) -> List[Any]:
        """Run ``fn(*args, **kwargs)`` on every worker; rank-ordered
        results (ref: RayExecutor.run).

        ``per_rank_args``: optional rank-indexed extra positional args,
        shipped under per-rank KV keys so each worker downloads only its
        own payload (the data-sharding path — fit() shards ride this).
        Workers call ``fn(*args, *per_rank_args[rank], **kwargs)``.
        """
        if not self._started:
            raise RuntimeError("Executor not started")
        if per_rank_args is not None and len(per_rank_args) != self.num_workers:
            raise ValueError("per_rank_args must have one entry per worker")
        client = self._client()
        e = self._epoch
        self._epoch += 1
        if per_rank_args is not None:
            for rank, extra in enumerate(per_rank_args):
                client.put(f"/exec/{e}/arg/{rank}", _dumps(tuple(extra)))
        client.put(f"/exec/{e}/fn",
                   _dumps((fn, tuple(args), kwargs or {},
                           per_rank_args is not None)))
        results: List[Any] = [None] * self.num_workers
        for rank in range(self.num_workers):
            raw = self._wait_key(
                client, f"/exec/{e}/result/{rank}", rank, timeout,
                f"worker {rank} did not answer call {e}")
            status, payload = pickle.loads(raw)
            if status == "err":
                raise WorkerError(rank, payload)
            results[rank] = payload
        return results

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        """Alias with positional-args convenience (ref: execute)."""
        return self.run(fn, args=args, kwargs=kwargs)

    def run_single(self, fn: Callable, rank: int = 0,
                   args: Sequence = (), kwargs: Optional[Dict] = None,
                   timeout: float = 600.0) -> Any:
        """Run on one rank only (others no-op; ref: execute_single)."""
        def gated(*a, **kw):
            import os as _os

            if int(_os.environ.get("HVDT_RANK", 0)) == rank:
                return fn(*a, **kw)
            return None

        return self.run(gated, args=args, kwargs=kwargs,
                        timeout=timeout)[rank]

    # -- teardown ----------------------------------------------------------

    def shutdown(self) -> None:
        if self._server is not None:
            try:
                self._client().put(f"/exec/{self._epoch}/stop", b"1")
            except Exception:
                pass
        for p in self._procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
                p.wait(timeout=5)
        self._procs.clear()
        if self._server is not None:
            self._server.stop()
            self._server = None
        self._started = False

    def __enter__(self):
        self.start()
        return self

    def __exit__(self, *exc):
        self.shutdown()
