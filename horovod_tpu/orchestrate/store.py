"""Storage abstraction for estimator artifacts.

Minimal re-conception of ref: spark/common/store.py (Store/LocalStore/
HDFSStore, 553 LoC): one prefix-disciplined object answering "where do
train data, checkpoints and logs live, and how do I read/write them",
so estimators never hard-code filesystem calls.  The reference ships
HDFS/S3/DBFS backends over pyarrow filesystems; here LocalStore is
fully functional and remote prefixes (gs://, s3://, hdfs://) resolve
through fsspec when it is importable (this image carries fsspec+gcsfs,
so ``Store.create("gs://...")`` constructs a working GCS-backed store —
IO then needs real credentials); without fsspec the constructor raises
a clear gating error instead of pretending.
"""

from __future__ import annotations

import os
from typing import Optional

__all__ = ["Store", "LocalStore", "FilesystemStore"]

_REMOTE_SCHEMES = ("gs://", "s3://", "hdfs://", "abfs://", "dbfs:/")


class Store:
    """Prefix + path discipline (ref: store.py Store.get_*_path)."""

    def __init__(self, prefix: str):
        self.prefix = prefix.rstrip("/")

    @staticmethod
    def create(prefix: "str | Store") -> "Store":
        if isinstance(prefix, Store):
            return prefix
        if prefix.startswith(_REMOTE_SCHEMES):
            return FilesystemStore(prefix)
        return LocalStore(prefix)

    # -- path discipline ---------------------------------------------------

    def get_train_data_path(self, idx: Optional[int] = None) -> str:
        base = f"{self.prefix}/intermediate_train_data"
        return f"{base}.{idx}" if idx is not None else base

    def get_val_data_path(self, idx: Optional[int] = None) -> str:
        base = f"{self.prefix}/intermediate_val_data"
        return f"{base}.{idx}" if idx is not None else base

    def get_checkpoint_path(self, run_id: str = "default") -> str:
        return f"{self.prefix}/runs/{run_id}/checkpoints"

    def get_logs_path(self, run_id: str = "default") -> str:
        return f"{self.prefix}/runs/{run_id}/logs"

    # -- IO (backend-specific) ---------------------------------------------

    def exists(self, path: str) -> bool:
        raise NotImplementedError

    def mkdirs(self, path: str) -> None:
        raise NotImplementedError

    def write_bytes(self, path: str, data: bytes) -> None:
        raise NotImplementedError

    def read_bytes(self, path: str) -> bytes:
        raise NotImplementedError


class LocalStore(Store):
    """Plain-filesystem backend (ref: store.py LocalStore)."""

    def exists(self, path: str) -> bool:
        return os.path.exists(path)

    def mkdirs(self, path: str) -> None:
        os.makedirs(path, exist_ok=True)

    def write_bytes(self, path: str, data: bytes) -> None:
        self.mkdirs(os.path.dirname(path))
        with open(path, "wb") as f:
            f.write(data)

    def read_bytes(self, path: str) -> bytes:
        with open(path, "rb") as f:
            return f.read()


class FilesystemStore(Store):
    """Remote prefixes via fsspec (ref: store.py HDFSStore/S3 over
    pyarrow fs).  Gated: constructing one without an importable fsspec
    raises immediately with the reason, rather than failing deep inside
    a worker."""

    def __init__(self, prefix: str):
        super().__init__(prefix)
        try:
            import fsspec

            self._fs = fsspec.open(prefix).fs
        except ImportError as e:
            raise ImportError(
                f"store prefix {prefix!r} needs the fsspec package (with "
                "the scheme's backend, e.g. gcsfs for gs://) — not "
                "available in this environment") from e

    def exists(self, path: str) -> bool:
        return self._fs.exists(path)

    def mkdirs(self, path: str) -> None:
        self._fs.makedirs(path, exist_ok=True)

    def write_bytes(self, path: str, data: bytes) -> None:
        with self._fs.open(path, "wb") as f:
            f.write(data)

    def read_bytes(self, path: str) -> bytes:
        with self._fs.open(path, "rb") as f:
            return f.read()
