"""LightningEstimator — distributed fit for LightningModule-style models.

Re-conception of ref: spark/lightning/estimator.py (693 LoC: a Spark ML
estimator that trains a ``pytorch_lightning.LightningModule`` over
Horovod workers via remote.py's Trainer harness).  The TPU-native
re-build drives the *LightningModule protocol* directly — the three
methods every LightningModule defines::

    training_step(batch, batch_idx) -> loss tensor (or {"loss": ...})
    configure_optimizers()          -> optimizer (or [opts], or dict)
    validation_step(batch, batch_idx) -> loss tensor (optional)

under this framework's own distributed loop (broadcast initial state,
DistributedOptimizer gradient allreduce, epoch metric averaging) instead
of embedding the Lightning Trainer — the Trainer's accelerator/strategy
machinery is exactly the part a TPU framework replaces.  Because only
the protocol is used, ``pytorch_lightning`` itself is OPTIONAL: a real
``LightningModule`` works unchanged when the package is installed, and
any plain ``torch.nn.Module`` implementing the three methods works
without it (how the stub tests run — the same discipline as the
reference's ``to_lightning_module`` legacy adapter, lightning/legacy.py).

fit() accepts numpy arrays or a Spark DataFrame (barrier tasks, shared
split/pad lockstep discipline) exactly like TorchEstimator.
"""

from __future__ import annotations

import io
from typing import Any, Dict, List, Optional

import numpy as np

from .ml_params import MLParams
from .estimator import (check_one_world, collective_worker_env,
                        df_transform, split_and_shard)
from .executor import Executor

__all__ = ["LightningEstimator", "LightningModel"]


class LightningModel(MLParams):
    """Trained model handle (ref: spark/lightning LightningModel —
    transform() runs the module's forward; the module is exposed).
    ``save(path)`` keeps its torch.save meaning; the full-handle
    Spark-ML persistence is ``write().save(dir)`` /
    ``LightningModel.load(dir)`` (orchestrate/ml_params.py)."""

    def __init__(self, model, history: Optional[List[Dict]] = None,
                 df_meta: Optional[Dict] = None):
        self.model = model
        self.history_ = history or []
        self._df_meta = df_meta or {}

    def predict(self, x: np.ndarray) -> np.ndarray:
        import torch

        self.model.eval()
        dtype = next(self.model.parameters()).dtype
        with torch.no_grad():
            out = self.model(torch.as_tensor(np.asarray(x), dtype=dtype))
        return out.numpy()

    def transform(self, x):
        """numpy in -> predictions out; Spark DataFrame in -> DataFrame
        out with a prediction column (ref: lightning/estimator.py
        _transform)."""
        from .estimator import _is_spark_dataframe
        from .torch_estimator import torch_df_predictor

        if _is_spark_dataframe(x):
            return df_transform(x, torch_df_predictor(self.model),
                                self._df_meta)
        return self.predict(x)

    def save(self, path: str) -> None:
        import torch

        torch.save(self.model, path)


def _resolve_optimizer(configured):
    """configure_optimizers() may return an optimizer, a list/tuple of
    optimizers (+ optional schedulers list), a dict with an 'optimizer'
    key, or a list of such dicts (all documented LightningModule
    contract shapes).  One optimizer is supported — the reference's
    remote harness trains opt[0] too."""
    if isinstance(configured, dict):
        return _resolve_optimizer(configured["optimizer"])
    if isinstance(configured, (list, tuple)):
        # covers [opt], ([opts], [scheds]), and [{"optimizer": ...}]
        return _resolve_optimizer(configured[0])
    return configured


def _step_loss(module, batch, batch_idx, step_name):
    """Run training_step/validation_step; unwrap the loss from a tensor
    or a Lightning-style {'loss': ...} dict."""
    out = getattr(module, step_name)(batch, batch_idx)
    if isinstance(out, dict):
        out = out["loss"]
    return out


def _lightning_worker(spec: Dict[str, Any], model_bytes: bytes,
                      x, y, xv, yv):
    """Executor/barrier-task body: rebuild the module, wire the
    distributed optimizer, drive the LightningModule protocol.

    Returns size + state-checksum on every rank (one-world proof), plus
    the trained state and history on rank 0 — the same result contract
    as _torch_worker."""
    import torch

    import horovod_tpu as hvd
    from ..interop import torch as htorch
    from ..interop.torch_optimizer import DistributedOptimizer

    if not hvd.is_initialized():
        hvd.init()

    module = torch.load(io.BytesIO(model_bytes), weights_only=False)
    # Rank 0's init wins (ref: broadcast at fit start, remote.py).
    htorch.broadcast_parameters(module.state_dict(), root_rank=0)
    opt = _resolve_optimizer(module.configure_optimizers())
    opt = DistributedOptimizer(opt,
                               named_parameters=module.named_parameters())

    dtype = next(module.parameters()).dtype
    xt = torch.as_tensor(np.asarray(x), dtype=dtype)
    yt = torch.as_tensor(np.asarray(y))
    has_val = xv is not None and hasattr(module, "validation_step")
    if has_val:
        xvt = torch.as_tensor(np.asarray(xv), dtype=dtype)
        yvt = torch.as_tensor(np.asarray(yv))

    n, bs = len(xt), spec["batch_size"]
    torch.manual_seed(spec["seed"] + 101 * hvd.rank())
    history: List[Dict[str, float]] = []
    for epoch in range(spec["epochs"]):
        module.train()
        perm = torch.randperm(n) if spec["shuffle"] else torch.arange(n)
        losses = []
        for i, start in enumerate(range(0, n, bs)):
            idx = perm[start:start + bs]
            opt.zero_grad()
            loss = _step_loss(module, (xt[idx], yt[idx]), i,
                              "training_step")
            loss.backward()       # grads stream into named allreduces
            opt.step()
            losses.append(float(loss.detach()))
        row = {"epoch": epoch, "train_loss": float(np.asarray(
            hvd.allreduce(np.float32(np.mean(losses)),
                          name=f"le_loss.{epoch}")))}
        if has_val:
            module.eval()
            with torch.no_grad():
                vls = [float(_step_loss(module, (xvt[s:s + bs],
                                                 yvt[s:s + bs]), j,
                                        "validation_step"))
                       for j, s in enumerate(range(0, len(xvt), bs))]
            row["val_loss"] = float(np.asarray(hvd.allreduce(
                np.float32(np.mean(vls)), name=f"le_vloss.{epoch}")))
        history.append(row)

    out = {"size": hvd.size(),
           "checksum": float(sum(float(v.double().sum())
                                 for v in module.state_dict().values()))}
    if hvd.rank() == 0:
        buf = io.BytesIO()
        torch.save(module.state_dict(), buf)
        out["state"] = buf.getvalue()
        out["history"] = history
    return out


class LightningEstimator(MLParams):
    """Fit a LightningModule-protocol model data-parallel over worker
    processes (ref: spark/lightning/estimator.py LightningEstimator —
    ``num_workers`` is the reference's ``num_proc``; model/loss/optimizer
    all live on the module itself, which is the Lightning contract).

    Args:
      model: a picklable ``torch.nn.Module`` implementing
        ``training_step`` + ``configure_optimizers`` (and optionally
        ``validation_step``) — every ``pl.LightningModule`` qualifies.
      epochs / batch_size / shuffle / seed: loop knobs.
      validation_split: GLOBAL tail split before sharding (same
        discipline as the other estimators); used only when the module
        defines ``validation_step``.
    """

    def __init__(self, model=None, num_workers: int = 1, epochs: int = 1,
                 batch_size: int = 32, shuffle: bool = True,
                 validation_split: float = 0.0, seed: int = 0,
                 label_col: str = "label", feature_cols=None,
                 output_col: str = "prediction",
                 env: Optional[Dict[str, str]] = None):
        if model is None:
            raise ValueError("LightningEstimator requires a model")
        for method in ("training_step", "configure_optimizers"):
            if not callable(getattr(model, method, None)):
                raise ValueError(
                    f"model must implement {method}() — the "
                    "LightningModule protocol (any pl.LightningModule, "
                    "or a plain torch module defining it)")
        if not 0.0 <= validation_split < 1.0:
            raise ValueError("validation_split must be in [0, 1)")
        self.model = model
        self.num_workers = num_workers
        self._env = env
        self._label_col = label_col
        self._feature_cols = feature_cols
        self._output_col = output_col
        self._spec = {"epochs": int(epochs), "batch_size": int(batch_size),
                      "shuffle": bool(shuffle),
                      "validation_split": float(validation_split),
                      "seed": int(seed)}
        self.history_: List[Dict[str, float]] = []

    def _df_meta(self):
        from .estimator import estimator_df_meta

        return estimator_df_meta(self)

    def fit(self, x, y: Optional[np.ndarray] = None) -> LightningModel:
        import torch

        from .estimator import _is_spark_dataframe

        if _is_spark_dataframe(x):
            return self._fit_spark_df(x, y)
        if y is None:
            raise ValueError("array-mode fit needs y")
        x, y = np.asarray(x), np.asarray(y)
        buf = io.BytesIO()
        torch.save(self.model, buf)
        split = (self._spec["validation_split"]
                 if hasattr(self.model, "validation_step") else 0.0)
        xs, ys, xv, yv = split_and_shard(x, y, split, self.num_workers)
        with Executor(self.num_workers,
                      env=collective_worker_env(self._env)) as ex:
            results = ex.run(
                _lightning_worker, args=(self._spec, buf.getvalue()),
                per_rank_args=[(xs[r], ys[r], xv[r], yv[r])
                               for r in range(self.num_workers)])
        return self._finish(results, buf.getvalue())

    def _fit_spark_df(self, df, y) -> LightningModel:
        """fit(df): training inside Spark barrier tasks, rank r on
        partition r (ref: spark/lightning/estimator.py fit over
        DataFrames; same worker-side split/pad discipline as the other
        estimators)."""
        import torch

        from . import spark as spark_mod

        if y is not None:
            raise ValueError(
                "DataFrame fit carries labels in label_col "
                f"({self._label_col!r}); pass y=None")
        buf = io.BytesIO()
        torch.save(self.model, buf)
        model_bytes = buf.getvalue()
        spec = dict(self._spec)
        if not hasattr(self.model, "validation_step"):
            spec["validation_split"] = 0.0
        meta = self._df_meta()

        def task(rows):
            return _lightning_df_worker(spec, meta, model_bytes, rows)

        results = spark_mod.run_on_dataframe(
            task, df, num_proc=self.num_workers,
            env=collective_worker_env(self._env, local_coordinator=False))
        return self._finish(results, model_bytes)

    def _finish(self, results, model_bytes) -> LightningModel:
        import torch

        out = results[0]
        if out is None or "state" not in out:
            raise RuntimeError("rank 0 returned no model state")
        check_one_world(results, self.num_workers)
        trained = torch.load(io.BytesIO(model_bytes), weights_only=False)
        trained.load_state_dict(
            torch.load(io.BytesIO(out["state"]), weights_only=False))
        self.history_ = out["history"]
        return LightningModel(trained, out["history"],
                              df_meta=self._df_meta())


def _lightning_df_worker(spec, meta, model_bytes, rows):
    """Barrier-task body for fit(df): rows -> padded shard -> the
    standard lightning worker."""
    from .estimator import df_rows_to_shards

    x, y, xv, yv = df_rows_to_shards(rows, meta["label_col"],
                                     meta["feature_cols"],
                                     spec["validation_split"])
    return _lightning_worker(spec, model_bytes, x, y, xv, yv)
