"""RayExecutor — the reference's Ray API surface on this framework.

Re-conception of ref: ray/runner.py:168 RayExecutor (+ create_settings,
strategy.py placement).  When Ray is importable, workers become Ray
actors placed by a colocation strategy; otherwise the same API degrades
to the local Executor pool so code written against it still runs (and is
testable in this image, which has no Ray).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

from .executor import Executor

__all__ = ["RayExecutor", "create_settings", "Settings"]


@dataclasses.dataclass
class Settings:
    """Launch settings (ref: RayExecutor.create_settings — ssh/timeouts
    collapse away; the KV secret and timeouts remain meaningful)."""

    start_timeout: float = 60.0
    nics: Optional[Sequence[str]] = None
    verbose: int = 0
    placement_group_timeout_s: int = 100


def create_settings(**kwargs) -> Settings:
    return Settings(**kwargs)


class RayExecutor:
    """Actor-pool executor with the reference's constructor surface
    (ref: ray/runner.py:168-208; unsupported knobs are accepted and
    ignored with a record in ``ignored_options`` rather than erroring, so
    reference scripts port unchanged)."""

    def __init__(self, settings: Optional[Settings] = None,
                 num_workers: Optional[int] = None,
                 cpus_per_worker: int = 1,
                 use_gpu: bool = False,
                 gpus_per_worker: Optional[int] = None,
                 num_hosts: Optional[int] = None,
                 num_workers_per_host: Optional[int] = None,
                 use_current_placement_group: bool = True,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 elastic_timeout: int = 600,
                 override_discovery: bool = True,
                 env: Optional[Dict[str, str]] = None):
        if num_workers is None:
            if num_hosts and num_workers_per_host:
                num_workers = num_hosts * num_workers_per_host
            else:
                raise ValueError(
                    "provide num_workers or num_hosts*num_workers_per_host")
        self.settings = settings or Settings()
        self.num_workers = num_workers
        # Record only options the caller actually changed from their
        # defaults (placement/elastic knobs have no local-pool meaning).
        defaults = dict(cpus_per_worker=1, use_gpu=False,
                        gpus_per_worker=None,
                        use_current_placement_group=True, min_workers=None,
                        max_workers=None, reset_limit=None,
                        elastic_timeout=600, override_discovery=True)
        passed = dict(cpus_per_worker=cpus_per_worker, use_gpu=use_gpu,
                      gpus_per_worker=gpus_per_worker,
                      use_current_placement_group=use_current_placement_group,
                      min_workers=min_workers, max_workers=max_workers,
                      reset_limit=reset_limit,
                      elastic_timeout=elastic_timeout,
                      override_discovery=override_discovery)
        self.ignored_options = {k: v for k, v in passed.items()
                                if v != defaults[k]}
        self._env = env
        self._local: Optional[Executor] = None
        self._ray_workers: List[Any] = []
        self._use_ray = False  # decided at start() — ray.init may be late

    @staticmethod
    def _ray_available() -> bool:
        try:
            import ray

            return ray.is_initialized()
        except ImportError:
            return False

    # -- lifecycle ---------------------------------------------------------

    def start(self, executable_cls: Optional[type] = None,
              executable_args: Sequence = (),
              executable_kwargs: Optional[Dict] = None) -> None:
        # Ray availability is evaluated HERE, not in __init__ — reference
        # scripts construct the executor before ray.init().
        self._use_ray = self._ray_available()
        if self._use_ray:
            self._start_ray(executable_cls, executable_args,
                            executable_kwargs or {})
        else:
            self._local = Executor(self.num_workers, env=self._env,
                                   start_timeout=self.settings.start_timeout)
            self._local.start()

    def _start_ray(self, cls, args, kwargs) -> None:  # pragma: no cover
        # Ray path: one actor per worker running the same worker loop
        # contract; exercised only where Ray is installed.
        import ray

        @ray.remote
        class _Worker:
            def __init__(self, rank, size):
                import os

                os.environ.update({"HVDT_RANK": str(rank),
                                   "HVDT_SIZE": str(size)})
                self.payload = cls(*args, **kwargs) if cls else None

            def execute(self, fn, *a, **kw):
                if self.payload is not None:
                    return fn(self.payload, *a, **kw)
                return fn(*a, **kw)

        self._ray_workers = [_Worker.remote(r, self.num_workers)
                             for r in range(self.num_workers)]

    def run(self, fn: Callable, args: Sequence = (),
            kwargs: Optional[Dict] = None) -> List[Any]:
        if self._use_ray:  # pragma: no cover
            import ray

            return ray.get([w.execute.remote(fn, *(args or ()),
                                             **(kwargs or {}))
                            for w in self._ray_workers])
        return self._local.run(fn, args=args, kwargs=kwargs)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return self.run(fn, args=args, kwargs=kwargs)

    def run_remote(self, fn: Callable, args: Sequence = (),
                   kwargs: Optional[Dict] = None):
        """Async dispatch returning a waitable (ref returns ObjectRefs);
        locally a thunk that materializes on call."""
        if self._use_ray:  # pragma: no cover
            return [w.execute.remote(fn, *(args or ()), **(kwargs or {}))
                    for w in self._ray_workers]
        import functools

        return functools.partial(self._local.run, fn, args=args,
                                 kwargs=kwargs)

    def shutdown(self) -> None:
        if self._use_ray:  # pragma: no cover
            self._ray_workers = []
            return
        if self._local is not None:
            self._local.shutdown()
            self._local = None
