"""RayExecutor — the reference's Ray API surface on this framework.

Re-conception of ref: ray/runner.py:168 RayExecutor (+ create_settings,
strategy.py placement).  When Ray is initialized, workers become Ray
actors: created with the caller's cpu/gpu resource options, located by
node IP, then handed the full HVDT_* env contract (local/cross ranks
from co-location + the driver's rendezvous KV) so ``hvd.init()`` inside
actors works like CLI-launched workers.  Without Ray the same API runs
on the local Executor pool.  The Ray branch is exercised against a stub
runtime in tests/test_ray.py (Ray itself is not in this image).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, List, Optional, Sequence

from .executor import Executor

__all__ = ["RayExecutor", "create_settings", "Settings"]


@dataclasses.dataclass
class Settings:
    """Launch settings (ref: RayExecutor.create_settings — ssh knobs
    collapse away).  ``placement_group_timeout_s`` bounds actor
    scheduling; ``start_timeout`` bounds worker env setup / payload
    construction (both backends) ."""

    start_timeout: float = 60.0
    nics: Optional[Sequence[str]] = None
    verbose: int = 0
    placement_group_timeout_s: int = 100


def create_settings(**kwargs) -> Settings:
    return Settings(**kwargs)


class RayExecutor:
    """Actor-pool executor with the reference's constructor surface
    (ref: ray/runner.py:168-208; unsupported knobs are accepted and
    ignored with a record in ``ignored_options`` rather than erroring, so
    reference scripts port unchanged)."""

    def __init__(self, settings: Optional[Settings] = None,
                 num_workers: Optional[int] = None,
                 cpus_per_worker: int = 1,
                 use_gpu: bool = False,
                 gpus_per_worker: Optional[int] = None,
                 num_hosts: Optional[int] = None,
                 num_workers_per_host: Optional[int] = None,
                 use_current_placement_group: bool = True,
                 min_workers: Optional[int] = None,
                 max_workers: Optional[int] = None,
                 reset_limit: Optional[int] = None,
                 elastic_timeout: int = 600,
                 override_discovery: bool = True,
                 env: Optional[Dict[str, str]] = None,
                 coordinator_port: Optional[int] = None):
        if num_workers is None:
            if num_hosts and num_workers_per_host:
                num_workers = num_hosts * num_workers_per_host
            else:
                raise ValueError(
                    "provide num_workers or num_hosts*num_workers_per_host")
        self.settings = settings or Settings()
        self.num_workers = num_workers
        self._cpus_per_worker = cpus_per_worker
        self._use_gpu = use_gpu
        self._gpus_per_worker = gpus_per_worker
        # Record only options the caller actually changed from their
        # defaults (placement/elastic knobs have no meaning on either
        # backend here; the resource knobs above feed Ray actor options).
        defaults = dict(use_current_placement_group=True, min_workers=None,
                        max_workers=None, reset_limit=None,
                        elastic_timeout=600, override_discovery=True)
        passed = dict(use_current_placement_group=use_current_placement_group,
                      min_workers=min_workers, max_workers=max_workers,
                      reset_limit=reset_limit,
                      elastic_timeout=elastic_timeout,
                      override_discovery=override_discovery)
        self.ignored_options = {k: v for k, v in passed.items()
                                if v != defaults[k]}
        self._env = env
        self._coordinator_port = coordinator_port
        self._local: Optional[Executor] = None
        self._ray_workers: List[Any] = []
        self._ray_kv = None
        self._use_ray = False  # decided at start() — ray.init may be late

    @staticmethod
    def _ray_available() -> bool:
        try:
            import ray

            return ray.is_initialized()
        except ImportError:
            return False

    # -- lifecycle ---------------------------------------------------------

    def start(self, executable_cls: Optional[type] = None,
              executable_args: Sequence = (),
              executable_kwargs: Optional[Dict] = None) -> None:
        # Ray availability is evaluated HERE, not in __init__ — reference
        # scripts construct the executor before ray.init().
        self._use_ray = self._ray_available()
        if self._use_ray:
            self._start_ray(executable_cls, executable_args,
                            executable_kwargs or {})
        else:
            # Resource knobs feed Ray actor options; on the local pool
            # they do nothing — record any non-default ask so the caller
            # can see their request was dropped.
            for k, v, d in (("cpus_per_worker", self._cpus_per_worker, 1),
                            ("use_gpu", self._use_gpu, False),
                            ("gpus_per_worker", self._gpus_per_worker,
                             None)):
                if v != d:
                    self.ignored_options[k] = v
            self._local = Executor(self.num_workers, env=self._env,
                                   start_timeout=self.settings.start_timeout)
            self._local.start()

    def _start_ray(self, cls, args, kwargs) -> None:
        """Ray path (ref: ray/runner.py RayExecutor.start): one actor
        per worker.  Two-phase like the reference — create actors, learn
        where Ray placed them (node IPs), then push the full HVDT_* env
        contract (local/cross ranks from co-location + the driver's
        rendezvous KV) before constructing the user payload, so
        ``hvd.init()`` inside actors rendezvouses exactly like
        CLI-launched workers."""
        import socket

        import ray

        from ..runner.hosts import rank_env_from_hosts
        from ..runner.http_kv import RendezvousServer, new_secret

        @ray.remote
        class _Worker:
            def __init__(self):
                self.payload = None

            def node_ip(self):
                import ray as _ray

                return _ray.util.get_node_ip_address()

            def reserve_coordinator_port(self):
                # Ephemeral port on THIS actor's node for the JAX
                # coordination service — a process-wide fixed default
                # (29500) collides when two jobs share a node or a stale
                # coordinator lingers.  The socket is HELD OPEN (with
                # SO_REUSEADDR so the coordinator can bind it later)
                # until setup(), shrinking the window in which the OS
                # could hand the port to another process.
                import socket as _socket

                s = _socket.socket()
                s.setsockopt(_socket.SOL_SOCKET, _socket.SO_REUSEADDR, 1)
                s.bind(("", 0))
                self._reserved_port_sock = s
                return s.getsockname()[1]

            def setup(self, env, has_payload):
                import os

                os.environ.update(env)
                # Release the reserved coordinator port just before
                # anything (hvd.init in the payload ctor or in run'd
                # fns) binds it.
                sock = getattr(self, "_reserved_port_sock", None)
                if sock is not None:
                    sock.close()
                    self._reserved_port_sock = None
                if has_payload:
                    self.payload = cls(*args, **kwargs)
                return True

            def execute(self, fn, *a, **kw):
                if self.payload is not None:
                    return fn(self.payload, *a, **kw)
                return fn(*a, **kw)

        opts: Dict[str, Any] = {"num_cpus": self._cpus_per_worker}
        if self._use_gpu:
            opts["num_gpus"] = self._gpus_per_worker or 1
        worker_cls = _Worker.options(**opts)
        self._ray_workers = [worker_cls.remote()
                             for _ in range(self.num_workers)]
        # Bounded wait: an unschedulable actor set (cluster too small)
        # must fail loudly, not hang — the reference bounds this with its
        # placement-group timeout.
        try:
            timeout_error = ray.exceptions.GetTimeoutError
        except AttributeError:  # pragma: no cover - very old ray
            timeout_error = TimeoutError
        try:
            ips = ray.get([w.node_ip.remote() for w in self._ray_workers],
                          timeout=self.settings.placement_group_timeout_s)
        except timeout_error as e:
            self._ray_workers = []
            raise RuntimeError(
                f"Ray could not schedule {self.num_workers} actors within "
                f"{self.settings.placement_group_timeout_s}s — does the "
                "cluster have the requested resources?") from e
        except Exception:
            # Non-scheduling failure (actor died during creation, import
            # error in the worker env, ...) — let the real error through.
            self._ray_workers = []
            raise

        self._ray_kv = RendezvousServer(secret=new_secret())
        try:
            port = self._ray_kv.start()
            self._ray_kv.put_local("/cluster/size",
                                   str(self.num_workers).encode())
            # The driver's externally-routable IP, from Ray itself —
            # gethostbyname(gethostname()) commonly yields 127.0.1.1 on
            # Debian-style /etc/hosts, unreachable from other nodes.
            try:
                addr = ray.util.get_node_ip_address()
            except Exception:
                try:
                    addr = socket.gethostbyname(socket.gethostname())
                except OSError:
                    addr = "127.0.0.1"
            base = {
                "HVDT_RENDEZVOUS_ADDR": addr,
                "HVDT_RENDEZVOUS_PORT": str(port),
                "HVDT_SECRET": self._ray_kv.secret.hex(),
                # JAX coordination service: rank 0's node, at an ephemeral
                # port reserved by the rank-0 actor unless the caller
                # pinned one (ref contract: runner/launch.py:216).
                "HVDT_COORDINATOR_ADDR":
                    f"{ips[0]}:{self._resolve_coordinator_port(ray)}",
            }
            ray.get([
                w.setup.remote(
                    rank_env_from_hosts(r, ips, base, self._env),
                    cls is not None)
                for r, w in enumerate(self._ray_workers)],
                timeout=self.settings.start_timeout)
        except BaseException:
            # Failed start must not leak the KV server thread/port.
            self._ray_kv.stop()
            self._ray_kv = None
            self._ray_workers = []
            raise

    def _resolve_coordinator_port(self, ray) -> int:
        if self._coordinator_port is not None:
            return self._coordinator_port
        return ray.get(
            self._ray_workers[0].reserve_coordinator_port.remote(),
            timeout=self.settings.start_timeout)

    def run(self, fn: Callable, args: Sequence = (),
            kwargs: Optional[Dict] = None) -> List[Any]:
        if self._use_ray:
            import ray

            return ray.get([w.execute.remote(fn, *(args or ()),
                                             **(kwargs or {}))
                            for w in self._ray_workers])
        return self._local.run(fn, args=args, kwargs=kwargs)

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return self.run(fn, args=args, kwargs=kwargs)

    def run_remote(self, fn: Callable, args: Sequence = (),
                   kwargs: Optional[Dict] = None):
        """Async dispatch returning a waitable (ref returns ObjectRefs);
        locally a thunk that materializes on call."""
        if self._use_ray:
            return [w.execute.remote(fn, *(args or ()), **(kwargs or {}))
                    for w in self._ray_workers]
        import functools

        return functools.partial(self._local.run, fn, args=args,
                                 kwargs=kwargs)

    def shutdown(self) -> None:
        if self._use_ray:
            self._ray_workers = []
            if self._ray_kv is not None:
                self._ray_kv.stop()
                self._ray_kv = None
            return
        if self._local is not None:
            self._local.shutdown()
            self._local = None
