"""Spark-ML Params surface, persistence, and Pipeline compatibility.

Re-conception of ref: spark/common/params.py (EstimatorParams — a
pyspark ``Params`` subclass declaring one ``Param`` plus a set/get pair
per knob) and the per-framework ParamsWriter/Reader persistence
(spark/lightning/estimator.py:67-99, spark/torch/estimator.py
TorchEstimatorParamsWritable/Readable).  Three capabilities:

* **Params surface** — every estimator/model exposes
  ``getOrDefault``/``setParams``/``copy``/``explainParams`` plus the
  camelCase ``setEpochs()``/``getEpochs()`` pairs of the reference.
  TPU-native difference: the constructor signature IS the param
  registry.  Params, defaults, and the set/get surface are derived from
  ``__init__`` by introspection, so there is exactly one source of
  truth and the Params layer cannot drift from the constructor (the
  reference maintains the dummy-parent ``Param`` table and the
  constructor defaults as two parallel lists).  ``_set`` re-runs
  ``__init__`` with the merged kwargs, so constructor validation and
  derived state always apply.

* **Persistence** — ``est.save(dir)`` / ``Est.load(dir)`` (and the
  pyspark-style ``write().save`` / ``read().load`` spellings) round-trip
  estimators AND trained model handles: a human-readable
  ``metadata.json`` (class + JSON-able params) next to a ``state.pkl``
  cloudpickle of the full param map.  One blob, not per-param blobs,
  so object identity inside the map survives (a torch optimizer's
  references INTO ``model.parameters()`` stay intact — per-param
  serialization, the reference's scheme, silently severs them).
  Framework-specific payloads hook ``_ml_get_state``/``_ml_from_state``
  (keras models travel as ``.keras`` archive bytes).  Like the
  reference's codec layer this is pickle-based: only load artifacts you
  trust.

* **Pipeline compatibility** — pyspark's ``Pipeline`` hard-gates stages
  on ``isinstance(stage, Estimator/Transformer)``; the reference
  satisfies it by inheriting pyspark bases.  Here
  :func:`register_pyspark_stages` registers the framework classes as
  ABC *virtual subclasses* of ``pyspark.ml.base`` — a real
  ``pyspark.ml.Pipeline([...]).fit(df)`` accepts them with pyspark
  fully absent from this package's import graph.  A native
  :class:`Pipeline`/:class:`PipelineModel` pair provides the same
  chaining without any pyspark at all.
"""

from __future__ import annotations

import functools
import inspect
import json
import os
import re
from typing import Any, Dict, List, Optional

__all__ = ["Param", "MLParams", "Pipeline", "PipelineModel", "load",
           "load_ml", "register_pyspark_stages"]

_CAMEL_RE = re.compile(r"(?<!^)(?=[A-Z])")
_METADATA = "metadata.json"
_STATE = "state.pkl"


def _snake(name: str) -> str:
    return _CAMEL_RE.sub("_", name).lower()


class Param:
    """A named parameter handle (ref: pyspark.ml.param.Param — here a
    lightweight view over a constructor argument)."""

    __slots__ = ("parent", "name", "doc")

    def __init__(self, name: str, doc: str = "", parent: str = ""):
        self.name = name
        self.doc = doc
        self.parent = parent

    def __repr__(self) -> str:
        return f"Param({self.parent}.{self.name})"

    def __eq__(self, other) -> bool:
        return (isinstance(other, Param) and other.name == self.name
                and other.parent == self.parent)

    def __hash__(self) -> int:
        return hash((self.parent, self.name))


def _capturing(init):
    """Wrap ``__init__`` to record the fully-bound constructor kwargs in
    ``self._ml_param_values`` — the single source of truth the whole
    Params surface reads."""
    if getattr(init, "_ml_capturing", False):
        return init
    sig = inspect.signature(init)

    @functools.wraps(init)
    def wrapper(self, *args, **kwargs):
        bound = sig.bind(self, *args, **kwargs)
        bound.apply_defaults()
        values = dict(bound.arguments)
        values.pop("self", None)
        for p in sig.parameters.values():
            if p.kind is inspect.Parameter.VAR_KEYWORD:
                values.update(values.pop(p.name, {}) or {})
            elif p.kind is inspect.Parameter.VAR_POSITIONAL:
                values.pop(p.name, None)
        # Run the real constructor FIRST: if its validation rejects the
        # arguments (e.g. a bad _set), the recorded param map must keep
        # the last-good values, not the rejected ones.
        result = init(self, *args, **kwargs)
        self._ml_param_values = values
        return result

    wrapper._ml_capturing = True
    return wrapper


class MLParams:
    """Mixin: pyspark-ml ``Params`` + ``MLWritable``/``MLReadable``
    surface for a plain-constructor class (see module docstring)."""

    _ml_param_values: Dict[str, Any]

    def __init_subclass__(cls, **kwargs):
        super().__init_subclass__(**kwargs)
        if "__init__" in cls.__dict__:
            cls.__init__ = _capturing(cls.__dict__["__init__"])

    # ---- Params surface -------------------------------------------------
    # NOTE: pyspark's ``.params`` listing is served from __getattr__ (not
    # a property) so a class whose own state legitimately uses the name —
    # JaxModel.params IS the trained weights — keeps it; the Params
    # listing then lives only on classes that don't claim the attribute.

    def hasParam(self, name: str) -> bool:
        return name in self._ml_param_values

    def getParam(self, name: str) -> Param:
        if not self.hasParam(name):
            raise AttributeError(
                f"{type(self).__name__} has no param {name!r}")
        return Param(name, parent=type(self).__name__)

    def getOrDefault(self, param) -> Any:
        name = getattr(param, "name", param)
        if name not in self._ml_param_values:
            raise AttributeError(
                f"{type(self).__name__} has no param {name!r}")
        return self._ml_param_values[name]

    def isDefined(self, param) -> bool:
        return self.hasParam(getattr(param, "name", param))

    def _set(self, **kwargs) -> "MLParams":
        unknown = sorted(set(kwargs) - set(self._ml_param_values))
        if unknown:
            raise AttributeError(
                f"{type(self).__name__} has no params {unknown} "
                f"(valid: {sorted(self._ml_param_values)})")
        merged = dict(self._ml_param_values)
        merged.update(kwargs)
        # Re-run the constructor: validation and derived state (specs,
        # serialized optimizer groups, ...) are rebuilt, never patched.
        self.__init__(**merged)
        return self

    def setParams(self, **kwargs) -> "MLParams":
        return self._set(**kwargs)

    def copy(self, extra: Optional[Dict] = None) -> "MLParams":
        merged = dict(self._ml_param_values)
        for key, value in (extra or {}).items():
            merged[getattr(key, "name", key)] = value
        return type(self)(**merged)

    def explainParams(self) -> str:
        return "\n".join(f"{n}: {v!r}"
                         for n, v in sorted(self._ml_param_values.items()))

    def __getattr__(self, name: str):
        # Generated camelCase accessors: setEpochs/getEpochs <->
        # the 'epochs' constructor kwarg.  Reads self.__dict__ directly
        # so unpickling (which probes attributes before __dict__ is
        # restored) cannot recurse.
        if name[:3] in ("set", "get") and len(name) > 3:
            values = self.__dict__.get("_ml_param_values")
            pname = _snake(name[3:])
            if values is not None and pname in values:
                if name.startswith("set"):
                    return lambda value: self._set(**{pname: value})
                return lambda: self._ml_param_values[pname]
        if name == "params":
            values = self.__dict__.get("_ml_param_values")
            if values is not None:
                return [Param(n, parent=type(self).__name__)
                        for n in values]
        raise AttributeError(
            f"{type(self).__name__!r} object has no attribute {name!r}")

    # ---- persistence ----------------------------------------------------
    def _ml_get_state(self) -> Dict[str, Any]:
        """Picklable param map; override to swap framework payloads for
        portable encodings (keras -> archive bytes)."""
        return dict(self._ml_param_values)

    @classmethod
    def _ml_from_state(cls, state: Dict[str, Any]) -> "MLParams":
        return cls(**state)

    def save(self, path: str, overwrite: bool = False) -> None:
        import cloudpickle

        if os.path.exists(os.path.join(path, _METADATA)) and not overwrite:
            raise FileExistsError(
                f"{path} already holds a saved instance; pass "
                "overwrite=True (the pyspark write().overwrite() analog)")
        os.makedirs(path, exist_ok=True)
        state = self._ml_get_state()
        preview = {}
        for name, value in state.items():
            try:
                json.dumps(value)
            except (TypeError, ValueError):
                preview[name] = f"<pickled {type(value).__name__}>"
            else:
                # Namedtuples (e.g. optax transforms) JSON-flatten to
                # plain lists — preview only; the pickle keeps the type.
                preview[name] = (f"<pickled {type(value).__name__}>"
                                 if hasattr(value, "_fields") else value)
        meta = {"class": f"{type(self).__module__}.{type(self).__qualname__}",
                "params": preview}
        with open(os.path.join(path, _METADATA), "w") as f:
            json.dump(meta, f, indent=1, sort_keys=True)
        with open(os.path.join(path, _STATE), "wb") as f:
            cloudpickle.dump(state, f)

    @classmethod
    def load(cls, path: str) -> "MLParams":
        obj = load(path)
        if not isinstance(obj, cls):
            raise TypeError(
                f"{path} holds a {type(obj).__name__}, not a {cls.__name__}")
        return obj

    # pyspark MLWritable/MLReadable spellings.
    def write(self) -> "_MLWriter":
        return _MLWriter(self)

    @classmethod
    def read(cls) -> "_MLReader":
        return _MLReader(cls)


class _MLWriter:
    def __init__(self, instance: MLParams):
        self._instance = instance
        self._overwrite = False

    def overwrite(self) -> "_MLWriter":
        self._overwrite = True
        return self

    def save(self, path: str) -> None:
        # Invoke the mixin's persistence explicitly: model handles like
        # KerasModel/TorchModel define their own save(path) with a
        # framework-export meaning, which must not shadow the
        # full-handle write().save() path.
        MLParams.save(self._instance, path, overwrite=self._overwrite)


class _MLReader:
    def __init__(self, cls):
        self._cls = cls

    def load(self, path: str) -> MLParams:
        return self._cls.load(path)


def _allowed_class_prefixes() -> List[str]:
    """Module prefixes load() may import classes from (the
    HVDT_MLPARAMS_ALLOW_PREFIXES knob; default: this framework only)."""
    from ..common import config

    raw = config.get_str("HVDT_MLPARAMS_ALLOW_PREFIXES")
    return [p.strip() for p in raw.split(",") if p.strip()]


def _check_class_allowed(class_path: str) -> None:
    """Reject a metadata.json class outside the allowlist BEFORE any
    import or unpickling happens.  ``horovod_tpu.`` (trailing dot) also
    admits the bare ``horovod_tpu`` module — a prefix names a package
    subtree, not a string accident."""
    prefixes = _allowed_class_prefixes()
    for p in prefixes:
        if class_path.startswith(p) or class_path == p.rstrip("."):
            return
    raise ValueError(
        f"refusing to load class {class_path!r}: its module is not under "
        f"the allowlisted prefixes {prefixes} (loading runs that class's "
        "code and unpickles attacker-controlled state — extend "
        "HVDT_MLPARAMS_ALLOW_PREFIXES only for artifacts you trust)")


def load(path: str) -> MLParams:
    """Load any saved estimator/model/pipeline by its recorded class.

    Pickle-based (cloudpickle of the param map, like the reference's
    base64-codec params): only load artifacts you trust.  As a guardrail
    the recorded class must live under an allowlisted module prefix
    (default ``horovod_tpu.``; extend via HVDT_MLPARAMS_ALLOW_PREFIXES)
    — checked before the class import and before ``state.pkl`` is
    unpickled, so a foreign artifact is rejected with zero of its code
    executed."""
    import cloudpickle

    with open(os.path.join(path, _METADATA)) as f:
        meta = json.load(f)
    _check_class_allowed(meta["class"])
    module, _, qualname = meta["class"].rpartition(".")
    import importlib

    cls = importlib.import_module(module)
    for part in qualname.split("."):
        cls = getattr(cls, part)
    with open(os.path.join(path, _STATE), "rb") as f:
        state = cloudpickle.load(f)
    return cls._ml_from_state(state)


#: Package-level alias (``orchestrate.load_ml``): ``load`` is too generic
#: a name to re-export next to checkpoint loaders.
load_ml = load


class Pipeline(MLParams):
    """Native ``pyspark.ml.Pipeline`` analog: chain transformers and
    estimators; ``fit`` trains each estimator stage on the running
    DataFrame and returns a :class:`PipelineModel` of the fitted stages
    (ref: the Pipeline the reference's estimators drop into —
    spark/common/params.py builds on pyspark Params for exactly this).
    Works with zero pyspark; with pyspark present the framework
    estimators also drop into the real ``pyspark.ml.Pipeline`` via
    :func:`register_pyspark_stages`."""

    def __init__(self, stages: Optional[List] = None):
        self.stages = list(stages or [])

    def fit(self, df) -> "PipelineModel":
        fitted: List[Any] = []
        data = df
        # Data only needs to flow as far as the LAST estimator: stages
        # past it are appended untrained/unrun (pyspark's
        # indexOfLastEstimator rule) — running a trailing transformer's
        # full-dataset pass here would just be discarded work.
        last_fit = max((i for i, s in enumerate(self.stages)
                        if hasattr(s, "fit")), default=-1)
        for i, stage in enumerate(self.stages):
            if hasattr(stage, "fit"):
                model = stage.fit(data)
                fitted.append(model)
                if i < last_fit:
                    data = model.transform(data)
            elif hasattr(stage, "transform"):
                fitted.append(stage)
                if i < last_fit:
                    data = stage.transform(data)
            else:
                raise TypeError(
                    f"pipeline stage {i} ({type(stage).__name__}) has "
                    "neither fit nor transform")
        return PipelineModel(fitted)

    def getStages(self) -> List:
        return self.stages

    def setStages(self, stages: List) -> "Pipeline":
        return self._set(stages=stages)


class PipelineModel(MLParams):
    """Fitted pipeline: ``transform`` chains every stage's transform."""

    def __init__(self, stages: Optional[List] = None):
        self.stages = list(stages or [])

    def transform(self, df):
        for stage in self.stages:
            df = stage.transform(df)
        return df


def _framework_stage_classes():
    """(estimator_classes, model_classes) importable in this image —
    heavyweight frameworks resolve lazily and are skipped if absent."""
    from .estimator import JaxEstimator, JaxModel

    estimators: List[type] = [JaxEstimator, Pipeline]
    models: List[type] = [JaxModel, PipelineModel]
    for mod_name, est_name, mdl_name in (
            (".keras_estimator", "KerasEstimator", "KerasModel"),
            (".torch_estimator", "TorchEstimator", "TorchModel"),
            (".lightning_estimator", "LightningEstimator",
             "LightningModel")):
        try:
            import importlib

            mod = importlib.import_module(mod_name, __package__)
        except ImportError:
            continue
        estimators.append(getattr(mod, est_name))
        models.append(getattr(mod, mdl_name))
    return estimators, models


def register_pyspark_stages() -> bool:
    """Register the framework estimators/models as pyspark.ml stages.

    pyspark's ``Pipeline._fit`` gates every stage on
    ``isinstance(stage, (Estimator, Transformer))``; those bases are
    ABCs, so virtual-subclass registration satisfies the gate without
    this package inheriting (or even importing, when absent) pyspark.
    Idempotent; returns False when pyspark has no ml bases to register
    against.  Call after installing pyspark into an existing session."""
    try:
        from pyspark.ml.base import Estimator, Model, Transformer
    except ImportError:
        return False
    estimators, models = _framework_stage_classes()
    for cls in estimators:
        Estimator.register(cls)
    for cls in models:
        Transformer.register(cls)
        if Model is not Transformer:
            Model.register(cls)
    return True
