"""Elastic Ray execution: actor re-provisioning over the elastic driver.

Re-conception of ref: ray/elastic_v2.py (RayHostDiscovery :40-72,
ElasticAdapter worker_loop :331-383) — Ray's cluster state is the host
discovery source and Ray actors are the workers, but the
membership/blacklist/re-rendezvous machinery is the SAME
``runner.elastic.ElasticDriver`` the CLI elastic launcher uses: an
actor death records a FAILURE, the dead actor's node is blacklisted,
and the surviving generation re-rendezvouses (smaller world) while
discovery keeps watching ``ray.nodes()`` for replacements.

Worker contract: ``fn`` runs inside each actor with the full HVDT_*
env, exactly like CLI-launched elastic workers.  The TPU elastic model
is generation restart (a compiled XLA world cannot resize in place):
when the driver announces a membership change, in-actor training raises
``HostsUpdatedInterrupt`` at its next commit point (state committed to
the shared store first), the actor's generation ends READY, and the
next generation's actors resume from the commit —
ref: elastic_v2.py's worker_loop kill/respawn plays the same role.

ray is imported lazily; everything is stub-testable
(tests/test_ray_elastic.py) with the same actor-surface stub as
tests/test_ray.py plus scripted node lists / actor deaths.
"""

from __future__ import annotations

import queue
import socket
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

from ..common.exceptions import HostsUpdatedInterrupt
from ..common.logging_util import get_logger
from ..runner.elastic.discovery import HostManager
from ..runner.elastic.driver import ElasticDriver, RESTART_EXIT_CODE
from ..runner.hosts import HostInfo, SlotInfo
from ..runner.http_kv import RendezvousServer, new_secret

log = get_logger(__name__)

__all__ = ["RayHostDiscovery", "ElasticRayExecutor"]


class RayHostDiscovery:
    """Host discovery from Ray global state (ref: elastic_v2.py:40-72).

    A callable returning ``List[HostInfo]`` — pluggable directly into
    ``runner.elastic.discovery.HostManager`` in place of a discovery
    script."""

    def __init__(self, use_gpu: bool = False, cpus_per_worker: int = 1,
                 gpus_per_worker: Optional[int] = None):
        self.use_gpu = use_gpu
        self.cpus_per_worker = cpus_per_worker
        self.gpus_per_worker = gpus_per_worker

    def __call__(self) -> List[HostInfo]:
        import ray

        hosts: List[HostInfo] = []
        for node in ray.nodes():
            if not node.get("alive"):
                continue
            addr = node["NodeManagerAddress"]
            res = node.get("Resources", {}) or {}
            slots = int(res.get("CPU", 0) // self.cpus_per_worker)
            if self.use_gpu:
                per = self.gpus_per_worker or 1
                slots = min(slots, int(res.get("GPU", 0) // per))
            if slots > 0:
                hosts.append(HostInfo(addr, slots))
        return hosts


class ElasticRayExecutor:
    """Elastic analog of :class:`RayExecutor`
    (ref: elastic_v2.py ElasticAdapter).

    Usage::

        ex = ElasticRayExecutor(min_workers=2, max_workers=4)
        ex.start()
        results = ex.run(train_fn)     # survives actor/node loss
        ex.shutdown()
    """

    def __init__(self, min_workers: int = 1,
                 max_workers: Optional[int] = None,
                 use_gpu: bool = False, cpus_per_worker: int = 1,
                 gpus_per_worker: Optional[int] = None,
                 env: Optional[Dict[str, str]] = None,
                 reset_limit: Optional[int] = None,
                 discovery_interval: float = 1.0,
                 ping_timeout_s: float = 10.0,
                 override_discovery: Optional[Callable[[], List[HostInfo]]]
                 = None):
        self.min_workers = min_workers
        self.max_workers = max_workers or min_workers
        self._cpus = cpus_per_worker
        self._use_gpu = use_gpu
        self._gpus = gpus_per_worker
        self._env = dict(env or {})
        self._reset_limit = reset_limit
        self._interval = discovery_interval
        self._ping_timeout = ping_timeout_s
        self._discover = (override_discovery
                          or RayHostDiscovery(use_gpu, cpus_per_worker,
                                              gpus_per_worker))
        self._hm: Optional[HostManager] = None
        self._started = False

    def start(self) -> None:
        import ray

        if not ray.is_initialized():
            raise RuntimeError(
                "ElasticRayExecutor.start() requires ray.init() first")
        self._hm = HostManager(self._discover)
        self._started = True

    # -- internals ---------------------------------------------------------

    def _make_worker(self, ray, slot: SlotInfo):
        """One actor, pinned to the slot's node when Ray exposes the
        node resource (stub clusters may not)."""

        @ray.remote
        class _ElasticWorker:
            def __init__(self):
                self._payload = None

            def ping(self):
                return 1

            def setup(self, env):
                import os

                os.environ.update(env)
                return True

            def execute(self, fn, *a, **kw):
                return fn(*a, **kw)

        opts: Dict[str, Any] = {"num_cpus": self._cpus}
        if self._use_gpu:
            opts["num_gpus"] = self._gpus or 1
        try:
            nodes = {n["NodeManagerAddress"]: n.get("Resources", {}) or {}
                     for n in ray.nodes() if n.get("alive")}
            if f"node:{slot.hostname}" in nodes.get(slot.hostname, {}):
                opts["resources"] = {f"node:{slot.hostname}": 1e-3}
        except Exception:   # stub clusters without node resources
            pass
        return _ElasticWorker.options(**opts).remote()

    def run(self, fn: Callable, args: Sequence = (),
            kwargs: Optional[Dict] = None) -> List[Any]:
        """Run ``fn`` elastically; returns the final generation's results
        in rank order."""
        import ray

        if not self._started:
            self.start()
        kwargs = kwargs or {}

        server = RendezvousServer(secret=new_secret())
        port = server.start()
        try:
            addr = ray.util.get_node_ip_address()
        except Exception:
            try:
                addr = socket.gethostbyname(socket.gethostname())
            except OSError:
                addr = "127.0.0.1"

        results: Dict[int, Dict[int, Any]] = {}
        results_lock = threading.Lock()

        pending_state = {"n": 0}

        def rendezvous_cb(slots: List[SlotInfo], gen: int) -> None:
            spec = "\n".join(
                f"{s.rank},{s.hostname},{s.local_rank},{s.cross_rank},"
                f"{s.size},{s.local_size},{s.cross_size}" for s in slots)
            server.put_local(f"/rendezvous/{gen}/spec", spec.encode())
            # Same pending-base contract as runner/elastic/driver.py:
            # workers of generation gen baseline against the counter as
            # of their rendezvous, not whatever it reads at first commit.
            server.put_local(f"/rendezvous/{gen}/pending_base",
                             str(pending_state["n"]).encode())
            server.put_local("/rendezvous/version", str(gen).encode())
            server.put_local("/cluster/size", str(len(slots)).encode())

        def hosts_updated_cb(n: int) -> None:
            pending_state["n"] = n
            server.put_local("/rendezvous/pending", str(n).encode())

        def spawn_fn(slot: SlotInfo, gen: int) -> int:
            worker = self._make_worker(ray, slot)
            try:
                ray.get(worker.ping.remote(), timeout=self._ping_timeout)
            except Exception as e:
                # Node vanished between discovery and actor start
                # (ref: elastic_v2.py ping_worker edge case).
                log.warning("elastic ray: ping failed on %s: %s",
                            slot.hostname, e)
                return 1
            env = {
                "HVDT_RENDEZVOUS_ADDR": addr,
                "HVDT_RENDEZVOUS_PORT": str(port),
                "HVDT_SECRET": server.secret.hex(),
                "HVDT_ELASTIC": "1",
                "HVDT_GENERATION": str(gen),
                **slot.to_env(),
                **self._env,
            }
            try:
                ray.get(worker.setup.remote(env),
                        timeout=self._ping_timeout)
                out = ray.get(worker.execute.remote(fn, *args, **kwargs))
            except Exception as e:
                if _is_hosts_updated(e):
                    # Worker saw the membership change and committed:
                    # READY for the next generation, not a failure.
                    return RESTART_EXIT_CODE
                log.warning("elastic ray: worker %d (gen %d) died: %s",
                            slot.rank, gen, e)
                return 1
            with results_lock:
                results.setdefault(gen, {})[slot.rank] = out
            return 0

        driver = ElasticDriver(
            self._hm, self.min_workers, self.max_workers, spawn_fn,
            reset_limit=self._reset_limit,
            discovery_interval=self._interval,
            kv_server=server, hosts_updated_cb=hosts_updated_cb)
        try:
            driver.start(rendezvous_cb)
            code = driver.wait()
        finally:
            driver.stop()
            server.stop()
        if code != 0:
            raise RuntimeError(
                f"elastic ray job failed (exit {code}); "
                f"{len(results)} generations ran")
        final_gen = max(results) if results else None
        if final_gen is None:
            return []
        by_rank = results[final_gen]
        return [by_rank[r] for r in sorted(by_rank)]

    def execute(self, fn: Callable, *args, **kwargs) -> List[Any]:
        return self.run(fn, args=args, kwargs=kwargs)

    def shutdown(self) -> None:
        self._started = False


def _is_hosts_updated(e: BaseException) -> bool:
    """Detect HostsUpdatedInterrupt raised inside an actor via the typed
    cause chain ONLY: Ray wraps worker exceptions (RayTaskError carries
    the cause; stubs re-raise directly).  The class-NAME check covers
    Ray's cloudpickle round trip re-instantiating the exception in a
    fresh module; there is deliberately no str(e) substring fallback —
    a crashed worker whose log happens to contain the word must be a
    failure, not a graceful regrow."""
    seen = set()
    cur: Optional[BaseException] = e
    while cur is not None and id(cur) not in seen:
        seen.add(id(cur))
        if isinstance(cur, HostsUpdatedInterrupt):
            return True
        if type(cur).__name__ == "HostsUpdatedInterrupt":
            return True
        cur = getattr(cur, "cause", None) or cur.__cause__
    return False
