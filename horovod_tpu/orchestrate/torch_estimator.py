"""TorchEstimator — the reference's Spark Torch estimator contract.

Re-conception of ref: spark/torch/estimator.py (TorchEstimator ->
TorchModel with fit/transform) on this framework's process model, the
torch twin of ``keras_estimator.py``: the driver pickles the model, an
Executor pool of workers rebuilds it, wraps the optimizer with the
grad-hook ``interop.torch.DistributedOptimizer``, broadcasts initial
model+optimizer state from rank 0, trains data-parallel over equalized
shards, and rank 0's ``state_dict`` comes back as a local ``TorchModel``
handle.  DataFrame/Petastorm plumbing collapses to numpy arrays, same
sharding/equalization discipline as the other estimators.
"""

from __future__ import annotations

import io
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .estimator import (check_one_world, collective_worker_env,
                        split_and_shard)
from .executor import Executor
from .ml_params import MLParams

__all__ = ["TorchEstimator", "TorchModel"]


class TorchModel(MLParams):
    """Trained model handle (ref: spark/torch TorchModel — transform()
    runs the predict path; the underlying torch module is exposed).
    ``save(path)`` keeps its torch.save meaning; the full-handle
    Spark-ML persistence is ``write().save(dir)`` /
    ``TorchModel.load(dir)`` (orchestrate/ml_params.py)."""

    def __init__(self, model, history: Optional[List[Dict]] = None,
                 df_meta: Optional[Dict] = None):
        self.model = model
        self.history_ = history or []
        self._df_meta = df_meta or {}

    def predict(self, x: np.ndarray) -> np.ndarray:
        import torch

        self.model.eval()
        dtype = next(self.model.parameters()).dtype
        with torch.no_grad():
            out = self.model(torch.as_tensor(np.asarray(x), dtype=dtype))
        return out.numpy()

    def transform(self, x):
        """numpy in -> predictions out; Spark DataFrame in -> DataFrame
        out with a prediction column (ref: spark/torch/estimator.py:413
        _transform)."""
        from .estimator import _is_spark_dataframe, df_transform

        if _is_spark_dataframe(x):
            return df_transform(x, torch_df_predictor(self.model),
                                self._df_meta)
        return self.predict(x)

    def save(self, path: str) -> None:
        import torch

        torch.save(self.model, path)


def torch_df_predictor(model):
    """Picklable ``x -> preds`` closure over torch.save bytes for
    DataFrame-out inference (shared by TorchModel and LightningModel):
    ships the serialized module to executors and deserializes it lazily,
    once per worker process (the per-chunk calls reuse the cached
    module — like the reference's UDF deserializing per partition,
    spark/torch/estimator.py:430)."""
    import torch

    buf = io.BytesIO()
    torch.save(model, buf)
    model_bytes = buf.getvalue()
    cache: Dict[str, Any] = {}

    def predict(xa):
        import torch as _t

        if "m" not in cache:
            m = _t.load(io.BytesIO(model_bytes), weights_only=False)
            m.eval()
            cache["m"] = m
        m = cache["m"]
        dtype = next(m.parameters()).dtype
        with _t.no_grad():
            out = m(_t.as_tensor(np.asarray(xa), dtype=dtype))
        return out.numpy()

    return predict


def _torch_train(spec: Dict[str, Any], model_bytes: bytes, epoch_batches):
    """Shared torch training core: rebuild model, wrap optimizer, train
    over ``epoch_batches(epoch) -> iterable[(x_np, y_np)]``.

    Lockstep invariant: the DistributedOptimizer's grad-hook allreduces
    fire once per backward, so every rank MUST see the same batch count
    per epoch — array mode guarantees it via equalized shards, stream
    mode via the exchanged ceil(target/bs) wrap discipline.

    Every rank returns its final-weights checksum and world size (proof
    the ranks formed one world and ended in sync); rank 0 additionally
    returns the trained state_dict."""
    import numpy as np
    import torch

    import horovod_tpu as hvd
    from ..interop import torch as ht

    if not hvd.is_initialized():
        hvd.init()
    torch.manual_seed(spec["seed"])
    model = torch.load(io.BytesIO(model_bytes), weights_only=False)
    # Rebuild the optimizer with the ORIGINAL param-group structure:
    # each serialized group carries its per-group options plus the
    # positional indices of its params in model.parameters() order
    # (collapsing to a single default group would silently train
    # multi-group models at the wrong hyperparameters).
    params = list(model.parameters())
    groups = [{**g["options"], "params": [params[i] for i in g["idx"]]}
              for g in spec["param_groups"]]
    opt = spec["optimizer_cls"](groups)
    loss_fn = spec["loss"]
    opt = ht.DistributedOptimizer(
        opt, named_parameters=model.named_parameters())
    ht.broadcast_parameters(model.state_dict(), root_rank=0)
    ht.broadcast_optimizer_state(opt, root_rank=0)

    dtype = next(model.parameters()).dtype

    def to_tensors(xb, yb):
        xt = torch.as_tensor(np.asarray(xb), dtype=dtype)
        yt = torch.as_tensor(np.asarray(yb))
        if yt.is_floating_point():
            # match the model's compute dtype (float64 numpy targets vs
            # float32 models crash regression losses otherwise)
            yt = yt.to(dtype)
        return xt, yt

    history = []
    for epoch in range(spec["epochs"]):
        model.train()
        losses = []
        for xb, yb in epoch_batches(epoch):
            xt, yt = to_tensors(xb, yb)
            opt.zero_grad()
            loss = loss_fn(model(xt), yt)
            loss.backward()
            opt.step()
            losses.append(float(loss.detach()))
        # epoch metric averaged across ranks (ref: MetricAverage)
        mean = float(np.asarray(hvd.allreduce(
            np.float32(np.mean(losses)), name=f"te_loss.{epoch}")))
        history.append({"loss": mean})

    out = {"size": hvd.size(),
           "checksum": float(sum(float(v.double().sum())
                                 for v in model.state_dict().values()))}
    if hvd.rank() == 0:
        buf = io.BytesIO()
        torch.save(model.state_dict(), buf)
        out["state"] = buf.getvalue()
        out["history"] = history
    return out


def _torch_worker(spec: Dict[str, Any], model_bytes: bytes, x, y):
    """Executor worker (in-memory): train over permuted index batches."""
    x = np.asarray(x)
    y = np.asarray(y)
    n, bs = len(x), spec["batch_size"]

    def epoch_batches(epoch):
        import torch

        perm = (torch.randperm(n).numpy() if spec["shuffle"]
                else np.arange(n))
        for i in range(0, n, bs):
            idx = perm[i:i + bs]
            yield x[idx], y[idx]

    return _torch_train(spec, model_bytes, epoch_batches)


def _torch_stream_worker(spec: Dict[str, Any], meta: Dict[str, Any],
                         model_bytes: bytes, row_iter):
    """Barrier-task body for fit(df, cache='disk'): spill the partition
    stream to Parquet row groups, exchange lengths over the rendezvous
    KV, then train by streaming batches (same out-of-core discipline as
    JaxEstimator's disk cache — orchestrate/spill.py)."""
    import os

    from .estimator import kv_exchange_shard_lengths
    from .spill import (ZERO_TRAIN_ROWS_MSG, spill_partition_to_parquet,
                        spill_scratch, stream_batches)

    rank = int(os.environ.get("HVDT_RANK", "0"))
    spill_dir, prefix, cleanup = spill_scratch(meta.get("spill_dir"), rank)
    try:
        train_path, _val, n_train, _nv, cols = spill_partition_to_parquet(
            row_iter, meta["label_col"], meta["feature_cols"], 0.0,
            spill_dir, meta.get("rows_per_group", 4096), prefix=prefix)
        target, min_len = kv_exchange_shard_lengths(n_train)
        if min_len == 0:
            raise ValueError(ZERO_TRAIN_ROWS_MSG)
        bs = spec["batch_size"]

        def epoch_batches(epoch):
            return stream_batches(
                train_path, meta["label_col"], cols, bs, target,
                seed=spec["seed"] + 7919 * epoch + 101 * rank,
                shuffle=spec["shuffle"])

        return _torch_train(spec, model_bytes, epoch_batches)
    finally:
        cleanup()


class TorchEstimator(MLParams):
    """Fit a torch module data-parallel over worker processes (ref:
    spark/torch/estimator.py:TorchEstimator — model/optimizer/loss
    params; ``num_workers`` is the reference's ``num_proc``).

    Args:
      model: a picklable ``torch.nn.Module``.
      optimizer: a configured torch optimizer ON ``model``'s parameters
        (recreated per worker from its class + defaults, the reference's
        own rebuild trick).
      loss: callable ``loss(y_pred, y_true) -> scalar tensor`` (a torch
        loss module or function).
      epochs / batch_size / shuffle / seed: training loop knobs.
    """

    def __init__(self, model=None, optimizer=None, loss=None,
                 num_workers: int = 1, epochs: int = 1,
                 batch_size: int = 32, shuffle: bool = True, seed: int = 0,
                 label_col: str = "label", feature_cols=None,
                 output_col: str = "prediction",
                 cache: str = "memory",
                 rows_per_group: int = 4096,
                 spill_dir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None):
        if model is None or optimizer is None or loss is None:
            raise ValueError("TorchEstimator requires model, optimizer "
                             "and loss")
        self.model = model
        self.num_workers = num_workers
        self._env = env
        self._label_col = label_col
        self._feature_cols = feature_cols
        self._output_col = output_col
        if cache not in ("memory", "disk"):
            raise ValueError(
                f"cache must be 'memory' or 'disk', got {cache!r}")
        self._cache = cache
        self._rows_per_group = int(rows_per_group)
        self._spill_dir = spill_dir
        # Serialize the optimizer's full param-group structure by param
        # POSITION in model.parameters() order (ids differ per process).
        pos = {id(p): i for i, p in enumerate(model.parameters())}
        try:
            param_groups = [
                {"options": {k: v for k, v in g.items() if k != "params"},
                 "idx": [pos[id(p)] for p in g["params"]]}
                for g in optimizer.param_groups]
        except KeyError:
            raise ValueError(
                "optimizer must be constructed over model.parameters() "
                "(a param group references a tensor not in the model)")
        self._spec = {"optimizer_cls": type(optimizer),
                      "param_groups": param_groups,
                      "loss": loss, "epochs": int(epochs),
                      "batch_size": int(batch_size),
                      "shuffle": bool(shuffle), "seed": int(seed)}
        self.history_: List[Dict[str, float]] = []

    def fit(self, x, y: Optional[np.ndarray] = None) -> TorchModel:
        import torch

        from .estimator import _is_spark_dataframe

        if _is_spark_dataframe(x):
            return self._fit_spark_df(x, y)
        if y is None:
            raise ValueError("array-mode fit needs y")
        x, y = np.asarray(x), np.asarray(y)
        buf = io.BytesIO()
        torch.save(self.model, buf)
        xs, ys, _, _ = split_and_shard(x, y, 0.0, self.num_workers)
        with Executor(self.num_workers,
                      env=collective_worker_env(self._env)) as ex:
            results = ex.run(
                _torch_worker, args=(self._spec, buf.getvalue()),
                per_rank_args=[(xs[r], ys[r])
                               for r in range(self.num_workers)])
        out = results[0]
        if out is None or "state" not in out:
            raise RuntimeError("rank 0 returned no model state")
        check_one_world(results, self.num_workers)
        trained = torch.load(io.BytesIO(buf.getvalue()),
                             weights_only=False)
        trained.load_state_dict(
            torch.load(io.BytesIO(out["state"]), weights_only=False))
        self.history_ = out["history"]
        return TorchModel(trained, out["history"], df_meta=self._df_meta())

    def _df_meta(self):
        from .estimator import estimator_df_meta

        return estimator_df_meta(self)

    def _fit_spark_df(self, df, y) -> TorchModel:
        """fit(df): training inside Spark barrier tasks, rank r on
        partition r (ref: spark/torch/estimator.py fit over DataFrames;
        same worker-side split/pad discipline as the other estimators)."""
        import torch

        from . import spark as spark_mod

        if y is not None:
            raise ValueError(
                "DataFrame fit carries labels in label_col "
                f"({self._label_col!r}); pass y=None")
        buf = io.BytesIO()
        torch.save(self.model, buf)
        model_bytes = buf.getvalue()
        spec = dict(self._spec)
        meta = {"label_col": self._label_col,
                "feature_cols": (list(self._feature_cols)
                                 if self._feature_cols else None)}
        stream = self._cache == "disk"
        if stream:
            # Out-of-core feed: spill the partition stream to Parquet row
            # groups and train by streaming them back (orchestrate/spill).
            meta["rows_per_group"] = self._rows_per_group
            meta["spill_dir"] = self._spill_dir

            def task(rows):
                return _torch_stream_worker(spec, meta, model_bytes, rows)
        else:
            def task(rows):
                return _torch_df_worker(spec, meta, model_bytes, rows)

        results = spark_mod.run_on_dataframe(
            task, df, num_proc=self.num_workers,
            env=collective_worker_env(self._env, local_coordinator=False),
            stream=stream)
        out = results[0]
        if out is None or "state" not in out:
            raise RuntimeError("rank 0 returned no model state")
        check_one_world(results, self.num_workers)
        trained = torch.load(io.BytesIO(model_bytes), weights_only=False)
        trained.load_state_dict(
            torch.load(io.BytesIO(out["state"]), weights_only=False))
        self.history_ = out["history"]
        return TorchModel(trained, out["history"], df_meta=self._df_meta())


def _torch_df_worker(spec, meta, model_bytes, rows):
    """Barrier-task body for fit(df): rows -> padded shard -> the
    standard torch worker (validation handled by the torch loop's own
    knobs; torch fit has no val split today, matching array mode)."""
    from .estimator import df_rows_to_shards

    x, y, _, _ = df_rows_to_shards(rows, meta["label_col"],
                                   meta["feature_cols"], 0.0)
    return _torch_worker(spec, model_bytes, x, y)
