"""KerasEstimator — the reference's Spark Keras estimator contract.

Re-conception of ref: spark/keras/estimator.py (KerasEstimator ->
KerasModel with fit/transform) on this framework's process model: the
driver serializes the COMPILED keras model, an Executor pool of worker
processes each loads it with the optimizer re-wrapped as the
distributed one (interop.tf.load_model), trains data-parallel with the
Broadcast/MetricAverage callbacks over equalized shards, and rank 0's
trained weights come back as a local ``KerasModel`` handle.  The
DataFrame/Petastorm plumbing collapses to numpy arrays, exactly like
``JaxEstimator`` (same sharding/equalization discipline, same store
layout for rank-0 checkpoints).
"""

from __future__ import annotations

import os
import tempfile
from typing import Any, Callable, Dict, List, Optional

import numpy as np

from .executor import Executor
from .ml_params import MLParams

__all__ = ["KerasEstimator", "KerasModel"]


class _KerasMLStateMixin(MLParams):
    """Shared persistence hook: a keras model param travels as ``.keras``
    archive bytes (keras objects are not reliably picklable; the archive
    also preserves compile state, which ``KerasEstimator.__init__``
    re-validates on load)."""

    def _ml_get_state(self):
        state = super()._ml_get_state()
        if state.get("model") is not None:
            state["model"] = ("__keras_bytes__",
                              _model_to_bytes(state["model"]))
        return state

    @classmethod
    def _ml_from_state(cls, state):
        m = state.get("model")
        if isinstance(m, tuple) and len(m) == 2 and m[0] == "__keras_bytes__":
            state = dict(state)
            state["model"] = _model_from_bytes(
                m[1], distributed=False,
                custom_objects=state.get("custom_objects"))
        return cls(**state)


def _model_to_bytes(model) -> bytes:
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.keras")
        model.save(path)
        with open(path, "rb") as f:
            return f.read()


def _model_from_bytes(data: bytes, distributed: bool,
                      custom_objects: Optional[Dict] = None):
    import keras

    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "model.keras")
        with open(path, "wb") as f:
            f.write(data)
        if distributed:
            from ..interop.tf import load_model

            return load_model(path, custom_objects=custom_objects)
        return keras.models.load_model(path,
                                       custom_objects=custom_objects)


class KerasModel(_KerasMLStateMixin):
    """Trained model handle (ref: spark/keras KerasModel — transform()
    runs the predict path; the underlying keras model is exposed).
    ``save(path)`` keeps its keras-archive meaning; the full-handle
    Spark-ML persistence (history/df_meta included) is
    ``write().save(dir)`` / ``KerasModel.load(dir)``."""

    def __init__(self, model, history: Optional[List[Dict]] = None,
                 df_meta: Optional[Dict] = None,
                 custom_objects: Optional[Dict] = None):
        self.model = model
        self.history_ = history or []
        self._df_meta = df_meta or {}
        self._custom_objects = custom_objects

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self.model.predict(np.asarray(x), verbose=0))

    def transform(self, x):
        """numpy in -> predictions out; Spark DataFrame in -> DataFrame
        out with a prediction column (ref: spark/keras KerasModel
        _transform).  The model ships to executors as serialized bytes
        and deserializes lazily, once per worker process (per-chunk
        calls reuse the cached model), like the reference's UDF."""
        from .estimator import _is_spark_dataframe, df_transform

        if _is_spark_dataframe(x):
            model_bytes = _model_to_bytes(self.model)
            custom = self._custom_objects
            cache: Dict[str, Any] = {}

            def predict(xa):
                if "m" not in cache:
                    cache["m"] = _model_from_bytes(
                        model_bytes, distributed=False,
                        custom_objects=custom)
                return np.asarray(cache["m"].predict(np.asarray(xa),
                                                     verbose=0))

            return df_transform(x, predict, self._df_meta)
        return self.predict(x)

    def save(self, path: str) -> None:
        self.model.save(path)


def _keras_worker(spec: Dict[str, Any], model_bytes: bytes, x, y, xv, yv):
    """Executor worker: load + wrap the model, train data-parallel.

    Every rank returns its final-weights checksum and world size so the
    driver (and tests) can PROVE the ranks formed one world and ended in
    sync; rank 0 additionally returns the trained model."""
    import numpy as np

    import horovod_tpu as hvd
    from ..interop import tf as htf

    if not hvd.is_initialized():
        hvd.init()
    model = _model_from_bytes(model_bytes, distributed=True,
                              custom_objects=spec["custom_objects"])
    callbacks = [htf.BroadcastGlobalVariablesCallback(0),
                 htf.MetricAverageCallback()]
    if spec["store"] and hvd.rank() == 0:
        import keras

        os.makedirs(spec["store"], exist_ok=True)
        callbacks.append(keras.callbacks.ModelCheckpoint(
            os.path.join(spec["store"], "checkpoint.keras")))
    hist = model.fit(np.asarray(x), np.asarray(y),
                     epochs=spec["epochs"],
                     batch_size=spec["batch_size"],
                     shuffle=spec["shuffle"],
                     validation_data=(None if xv is None
                                      else (np.asarray(xv),
                                            np.asarray(yv))),
                     verbose=0, callbacks=callbacks)
    out = {"size": hvd.size(),
           "checksum": float(sum(float(np.sum(np.asarray(v, np.float64)))
                                 for v in model.weights))}
    if hvd.rank() == 0:
        out["model"] = _model_to_bytes(model)
        out["history"] = [
            dict(zip(hist.history, [float(v[i]) for v in
                                    hist.history.values()]))
            for i in range(len(next(iter(hist.history.values()), [])))]
    return out


class KerasEstimator(_KerasMLStateMixin):
    """Fit a compiled keras model data-parallel over worker processes
    (ref: spark/keras/estimator.py:KerasEstimator — the model/optimizer/
    loss travel via keras serialization; ``num_workers`` is the
    reference's ``num_proc``).

    Args:
      model: a COMPILED ``keras.Model`` (loss/metrics/optimizer baked
        in; the optimizer is re-wrapped as the distributed one inside
        each worker, ref: keras/estimator._load_model_from_checkpoint).
      num_workers: worker-process pool size.
      epochs / batch_size / shuffle: forwarded to ``model.fit``.
      validation_split: GLOBAL tail split before sharding (the
        reference's ``validation`` param; same discipline as
        JaxEstimator — equalization padding can never leak train rows
        into validation); workers evaluate round-robin val shards and
        MetricAverageCallback averages the metrics.
      custom_objects: forwarded to model deserialization.
      store: directory for rank-0 epoch checkpoints (ref: store param).
    """

    def __init__(self, model=None, num_workers: int = 1, epochs: int = 1,
                 batch_size: int = 32, shuffle: bool = True,
                 validation_split: float = 0.0,
                 custom_objects: Optional[Dict] = None,
                 store: Optional[str] = None,
                 label_col: str = "label",
                 feature_cols=None,
                 output_col: str = "prediction",
                 cache: str = "memory",
                 rows_per_group: int = 4096,
                 spill_dir: Optional[str] = None,
                 env: Optional[Dict[str, str]] = None):
        if model is None:
            raise ValueError("KerasEstimator requires a compiled model")
        if getattr(model, "optimizer", None) is None:
            raise ValueError("model must be compiled (model.compile(...)) "
                             "before constructing the estimator")
        if not 0.0 <= validation_split < 1.0:
            raise ValueError("validation_split must be in [0, 1)")
        self.model = model
        self.num_workers = num_workers
        self._env = env
        self._label_col = label_col
        self._feature_cols = feature_cols
        self._output_col = output_col
        if cache not in ("memory", "disk"):
            raise ValueError(
                f"cache must be 'memory' or 'disk', got {cache!r}")
        self._cache = cache
        self._rows_per_group = int(rows_per_group)
        self._spill_dir = spill_dir
        self._spec = {"epochs": int(epochs), "batch_size": int(batch_size),
                      "shuffle": bool(shuffle),
                      "validation_split": float(validation_split),
                      "custom_objects": custom_objects, "store": store}
        self.history_: List[Dict[str, float]] = []

    def fit(self, x, y: Optional[np.ndarray] = None) -> KerasModel:
        from .estimator import (_is_spark_dataframe, check_one_world,
                                collective_worker_env, split_and_shard)

        if _is_spark_dataframe(x):
            return self._fit_spark_df(x, y)
        if y is None:
            raise ValueError("array-mode fit needs y")
        x, y = np.asarray(x), np.asarray(y)
        model_bytes = _model_to_bytes(self.model)
        xs, ys, xv, yv = split_and_shard(
            x, y, self._spec["validation_split"], self.num_workers)
        with Executor(self.num_workers,
                      env=collective_worker_env(self._env)) as ex:
            results = ex.run(
                _keras_worker, args=(self._spec, model_bytes),
                per_rank_args=[(xs[r], ys[r], xv[r], yv[r])
                               for r in range(self.num_workers)])
        out = results[0]
        if out is None or "model" not in out:
            raise RuntimeError("rank 0 returned no model")
        check_one_world(results, self.num_workers)
        trained = _model_from_bytes(out["model"], distributed=False,
                                    custom_objects=self._spec[
                                        "custom_objects"])
        self.history_ = out["history"]
        return KerasModel(trained, out["history"], df_meta=self._df_meta(),
                          custom_objects=self._spec["custom_objects"])

    def _df_meta(self):
        from .estimator import estimator_df_meta

        return estimator_df_meta(self)

    def _fit_spark_df(self, df, y) -> KerasModel:
        """fit(df): training runs inside Spark barrier tasks on each
        task's own partition (ref: spark/keras/estimator.py fit over
        DataFrames; same worker-side split/pad discipline as
        JaxEstimator's DataFrame path)."""
        from . import spark as spark_mod
        from .estimator import check_one_world, collective_worker_env

        if y is not None:
            raise ValueError(
                "DataFrame fit carries labels in label_col "
                f"({self._label_col!r}); pass y=None")
        model_bytes = _model_to_bytes(self.model)
        spec = dict(self._spec)
        meta = {"label_col": self._label_col,
                "feature_cols": (list(self._feature_cols)
                                 if self._feature_cols else None)}

        stream = self._cache == "disk"
        if stream:
            # Out-of-core feed: spill the partition stream to Parquet row
            # groups and train model.fit over a streamed generator
            # (orchestrate/spill.py).
            meta["rows_per_group"] = self._rows_per_group
            meta["spill_dir"] = self._spill_dir

            def task(rows):
                return _keras_stream_worker(spec, meta, model_bytes, rows)
        else:
            def task(rows):
                return _keras_df_worker(spec, meta, model_bytes, rows)

        results = spark_mod.run_on_dataframe(
            task, df, num_proc=self.num_workers,
            env=collective_worker_env(self._env, local_coordinator=False),
            stream=stream)
        out = results[0]
        if out is None or "model" not in out:
            raise RuntimeError("rank 0 returned no model")
        check_one_world(results, self.num_workers)
        trained = _model_from_bytes(out["model"], distributed=False,
                                    custom_objects=spec["custom_objects"])
        self.history_ = out["history"]
        return KerasModel(trained, out["history"], df_meta=self._df_meta(),
                          custom_objects=spec["custom_objects"])


def _keras_df_worker(spec, meta, model_bytes, rows):
    """Barrier-task body for fit(df): materialize this partition's rows,
    apply the shared split/pad discipline (KV length exchange), then run
    the standard keras worker."""
    from .estimator import df_rows_to_shards

    x, y, xv, yv = df_rows_to_shards(rows, meta["label_col"],
                                     meta["feature_cols"],
                                     spec["validation_split"])
    return _keras_worker(spec, model_bytes, x, y, xv, yv)


def _keras_stream_worker(spec, meta, model_bytes, row_iter):
    """Barrier-task body for fit(df, cache='disk'): spill the partition
    stream to Parquet row groups (honoring validation_split per chunk),
    exchange lengths over the rendezvous KV, then drive ``model.fit``
    with streamed batch generators (``steps_per_epoch`` fixed by the
    exchanged cross-rank max so every rank runs the same lockstep batch
    count — the keras twin of the Jax/Torch disk caches).  Validation is
    all-or-none across ranks (a rank with zero val rows would desync
    MetricAverageCallback's val-metric allreduce); its last streamed
    batch wrap-pads, biasing val metrics by at most (bs-1)/n_val."""
    import os

    import numpy as np

    import horovod_tpu as hvd
    from ..interop import tf as htf
    from .estimator import kv_exchange_shard_lengths
    from .spill import (ZERO_TRAIN_ROWS_MSG, spill_partition_to_parquet,
                        spill_scratch, stream_batches)

    rank = int(os.environ.get("HVDT_RANK", "0"))
    spill_dir, prefix, cleanup = spill_scratch(meta.get("spill_dir"), rank)
    try:
        train_path, val_path, n_train, n_val, cols = \
            spill_partition_to_parquet(
                row_iter, meta["label_col"], meta["feature_cols"],
                spec["validation_split"], spill_dir,
                meta.get("rows_per_group", 4096), prefix=prefix)
        target, min_len = kv_exchange_shard_lengths(n_train)
        if min_len == 0:
            raise ValueError(ZERO_TRAIN_ROWS_MSG)
        _, min_val = kv_exchange_shard_lengths(n_val, key="/dfshard/val")

        if not hvd.is_initialized():
            hvd.init()
        model = _model_from_bytes(model_bytes, distributed=True,
                                  custom_objects=spec["custom_objects"])
        callbacks = [htf.BroadcastGlobalVariablesCallback(0),
                     htf.MetricAverageCallback()]
        if spec["store"] and hvd.rank() == 0:
            import keras

            os.makedirs(spec["store"], exist_ok=True)
            callbacks.append(keras.callbacks.ModelCheckpoint(
                os.path.join(spec["store"], "checkpoint.keras")))
        bs = spec["batch_size"]
        steps = -(-target // bs)

        def endless(path, tgt, shuffle):
            epoch = 0
            while True:            # keras draws a fixed count per epoch
                for xb, yb in stream_batches(
                        path, meta["label_col"], cols, bs, tgt,
                        seed=7919 * epoch + 101 * rank, shuffle=shuffle):
                    yield np.asarray(xb), np.asarray(yb)
                epoch += 1

        val_kwargs = {}
        if val_path is not None and min_val > 0:
            val_kwargs = {
                "validation_data": endless(val_path, n_val, False),
                "validation_steps": -(-n_val // bs)}
        hist = model.fit(endless(train_path, target, spec["shuffle"]),
                         epochs=spec["epochs"], steps_per_epoch=steps,
                         verbose=0, callbacks=callbacks, **val_kwargs)
        out = {"size": hvd.size(),
               "checksum": float(sum(
                   float(np.sum(np.asarray(v, np.float64)))
                   for v in model.weights))}
        if hvd.rank() == 0:
            out["model"] = _model_to_bytes(model)
            out["history"] = [
                dict(zip(hist.history, [float(v[i]) for v in
                                        hist.history.values()]))
                for i in range(len(next(iter(hist.history.values()), [])))]
        return out
    finally:
        cleanup()
