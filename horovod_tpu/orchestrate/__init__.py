"""Orchestrator adapters: actor-pool executor, Ray adapter, estimator.

Re-conception of ref: horovod/ray/runner.py (RayExecutor actor pool) and
horovod/spark (Estimator fit/transform) — SURVEY.md §2.6.  The core is a
cluster-agnostic ``Executor`` over persistent worker processes wired with
the launcher's env contract; ``RayExecutor`` preserves the reference's
API surface on top (Ray actors when Ray is importable, local processes
otherwise), and ``JaxEstimator`` gives the sklearn-ish fit/transform
wrapper the Spark estimators provided.
"""

from .executor import Executor
from .ray_adapter import RayExecutor
from .ray_elastic import ElasticRayExecutor, RayHostDiscovery
from .estimator import JaxEstimator, JaxModel, ParquetSource
from .ml_params import (MLParams, Pipeline, PipelineModel, load_ml,
                        register_pyspark_stages)
from . import spark  # noqa: F401  (pyspark itself is imported lazily)

__all__ = ["Executor", "RayExecutor", "ElasticRayExecutor",
           "RayHostDiscovery", "JaxEstimator", "JaxModel", "ParquetSource",
           "KerasEstimator", "KerasModel", "TorchEstimator", "TorchModel",
           "LightningEstimator", "LightningModel", "spark",
           "MLParams", "Pipeline", "PipelineModel", "load_ml",
           "register_pyspark_stages"]


def __getattr__(name):
    # framework estimators pull in TF/torch machinery — resolve lazily.
    if name in ("KerasEstimator", "KerasModel"):
        from . import keras_estimator

        return getattr(keras_estimator, name)
    if name in ("TorchEstimator", "TorchModel"):
        from . import torch_estimator

        return getattr(torch_estimator, name)
    if name in ("LightningEstimator", "LightningModel"):
        from . import lightning_estimator

        return getattr(lightning_estimator, name)
    raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
