"""Out-of-core DataFrame feed: partition -> Parquet row groups -> batches.

Re-conception of ref: spark/common/util.py ``prepare_data`` — the
reference materializes DataFrames to the store as Parquet and streams row
groups per worker via Petastorm so a partition larger than task memory
can still train.  Here the barrier task itself spills its partition's
row stream to a Parquet file in bounded chunks (never holding the whole
partition as Python objects), then the training loop streams row groups
back batch-wise each epoch.

Memory contract: at any moment a worker holds at most ``rows_per_group``
rows being spilled, or one row group plus one partial batch being
streamed — never the whole partition.
"""

from __future__ import annotations

import os
import tempfile
from typing import Iterator, Optional, Sequence, Tuple

import numpy as np

__all__ = ["spill_partition_to_parquet", "spill_paths", "spill_scratch",
           "stream_batches", "read_xy", "ZERO_TRAIN_ROWS_MSG"]

# Shared by every disk-cache worker's min-length check (the exchange
# mechanism differs — KV pre-init vs hvd allreduce — the contract not).
ZERO_TRAIN_ROWS_MSG = (
    "a worker contributed ZERO training rows (empty partition, or only "
    "validation rows after the split) — use more rows per partition, "
    "fewer workers, or a smaller validation_split")


def spill_paths(spill_dir: str, prefix: str) -> Tuple[str, str]:
    """The (train, val) Parquet paths a spill writes for ``prefix`` —
    the ONE place the naming contract lives; cleanup code in the workers
    computes paths through here, never by hand."""
    return (os.path.join(spill_dir, f"{prefix}_train.parquet"),
            os.path.join(spill_dir, f"{prefix}_val.parquet"))


def spill_scratch(spill_dir: Optional[str], rank: int):
    """Scratch-dir scaffold shared by every disk-cache worker: resolve
    the directory (mkdtemp when the caller gave none), the per-rank file
    prefix, and a cleanup callable that removes exactly what this rank's
    spill created (whole tempdir when we made it; just this rank's files
    in a user-provided dir).  Returns (spill_dir, prefix, cleanup)."""
    import shutil

    created = spill_dir is None
    if created:
        spill_dir = tempfile.mkdtemp(prefix="hvdt_spill_")
    prefix = f"rank{rank}"

    def cleanup():
        if created:
            shutil.rmtree(spill_dir, ignore_errors=True)
        else:
            for p in spill_paths(spill_dir, prefix):
                if os.path.exists(p):
                    os.remove(p)

    return spill_dir, prefix, cleanup


def _rows_chunk_to_table(rows, label_col: str, feature_cols):
    """A chunk of Rows (pyspark Row or mappings) -> pyarrow Table with
    one column per feature + the label column (vector cells flattened,
    like estimator._rows_to_x)."""
    import pyarrow as pa

    from .estimator import _row_get, infer_feature_cols

    cols = infer_feature_cols(rows[0], feature_cols, exclude=(label_col,))
    data = {}
    for c in cols:
        vals = [np.ravel(np.asarray(_row_get(r, c), np.float32))
                for r in rows]
        if vals[0].size == 1:
            data[c] = pa.array([float(v[0]) for v in vals], pa.float32())
        else:
            data[c] = pa.array([[float(x) for x in v] for v in vals],
                               pa.list_(pa.float32()))
    labels = [np.asarray(_row_get(r, label_col)) for r in rows]
    if labels[0].ndim == 0:
        # scalar labels keep their native dtype via pyarrow inference
        data[label_col] = pa.array([lb.item() for lb in labels])
    else:
        # vector labels — INCLUDING length-1 vectors, whose (n, 1) shape
        # must survive the round trip or losses silently broadcast —
        # become float32 lists (the in-memory path keeps native dtype;
        # Parquet needs a concrete column type)
        data[label_col] = pa.array(
            [[float(x) for x in np.ravel(lb)] for lb in labels],
            pa.list_(pa.float32()))
    return pa.table(data), cols


def spill_partition_to_parquet(
        row_iter: Iterator, label_col: str, feature_cols,
        validation_split: float, spill_dir: Optional[str] = None,
        rows_per_group: int = 4096,
        prefix: str = "part") -> Tuple[str, Optional[str], int, int, list]:
    """Stream a partition's rows into ``<spill_dir>/<prefix>_train.parquet``
    (one row group per ``rows_per_group`` chunk) without ever
    materializing the partition.

    The validation split happens PER CHUNK (each chunk's tail fraction
    goes to ``<prefix>_val.parquet``) — split-clean like the global tail
    split (no row lands in both files), statistically equivalent for
    shuffled data, and streamable because the total length isn't known
    until the iterator is exhausted.

    Returns (train_path, val_path_or_None, n_train, n_val, feature_cols).
    """
    import pyarrow.parquet as pq

    if spill_dir is None:
        spill_dir = tempfile.mkdtemp(prefix="hvdt_spill_")
    os.makedirs(spill_dir, exist_ok=True)
    train_path, val_path = spill_paths(spill_dir, prefix)

    writers = {"train": None, "val": None}
    counts = {"train": 0, "val": 0}
    cols: list = []

    def _write(kind, path, rows):
        nonlocal cols
        if not rows:
            return
        table, cols = _rows_chunk_to_table(rows, label_col, feature_cols)
        if writers[kind] is None:
            writers[kind] = pq.ParquetWriter(path, table.schema)
        writers[kind].write_table(table)
        counts[kind] += len(rows)

    chunk: list = []
    try:
        for row in row_iter:
            chunk.append(row)
            if len(chunk) >= rows_per_group:
                n_val = (int(round(len(chunk) * validation_split))
                         if validation_split > 0 else 0)
                _write("train", train_path, chunk[:len(chunk) - n_val])
                _write("val", val_path, chunk[len(chunk) - n_val:])
                chunk = []
        if chunk:
            n_val = (int(round(len(chunk) * validation_split))
                     if validation_split > 0 else 0)
            if validation_split > 0 and counts["val"] == 0 and n_val == 0:
                n_val = 1    # validation on => never an empty val set
            _write("train", train_path, chunk[:len(chunk) - n_val])
            _write("val", val_path, chunk[len(chunk) - n_val:])
    finally:
        for w in writers.values():
            if w is not None:
                w.close()
    return (train_path, val_path if counts["val"] else None,
            counts["train"], counts["val"], cols)


def _table_to_xy(table, label_col: str, feature_cols: Sequence[str]):
    """One column per feature; list-typed cells become multiple feature
    dims (the inverse of _rows_chunk_to_table)."""
    parts = []
    for c in feature_cols:
        a = np.asarray(table[c].to_pylist(), np.float32)
        parts.append(a if a.ndim > 1 else a[:, None])
    x = np.concatenate(parts, axis=1)
    y = np.asarray(table[label_col].to_pylist())
    return x, y


def read_xy(path: str, label_col: str, feature_cols: Sequence[str]):
    """Load an entire spilled Parquet file (used for the — bounded —
    validation set)."""
    import pyarrow.parquet as pq

    table = pq.ParquetFile(path).read()
    return _table_to_xy(table, label_col, feature_cols)


def stream_val_loss(eval_loss, params, path: str, label_col: str,
                    feature_cols: Sequence[str]) -> float:
    """Weighted-mean validation loss streamed one row group at a time —
    the val set is partition-proportional, so materializing it whole
    would defeat the bounded-memory contract the disk cache exists
    for.  (At most two distinct row-group shapes reach ``eval_loss``:
    full groups and the final partial one, so jit recompiles at most
    twice.)"""
    import pyarrow.parquet as pq

    pf = pq.ParquetFile(path)
    tot = 0.0
    n = 0
    for rg in range(pf.metadata.num_row_groups):
        x, y = _table_to_xy(pf.read_row_group(rg), label_col, feature_cols)
        tot += float(eval_loss(params, x, y)) * len(x)
        n += len(x)
    return tot / max(n, 1)


def stream_batches(path: str, label_col: str, feature_cols: Sequence[str],
                   batch_size: int, target_rows: int, seed: int,
                   shuffle: bool = True):
    """Yield exactly ``ceil(target_rows / batch_size)`` full batches from
    the spilled Parquet file, one row group in memory at a time.

    ``target_rows`` is the cross-rank MAX train length: ranks with fewer
    rows wrap around (re-reading row groups from the start) so every
    rank issues the same number of lockstep collective steps — the same
    wrap-padding discipline as the in-memory path, applied lazily.
    Shuffle is two-level (row-group order + rows within a group), the
    standard out-of-core approximation of a global permutation (the
    reference's Petastorm reader shuffles the same way).
    """
    import pyarrow.parquet as pq

    pf = pq.ParquetFile(path)
    n_rg = pf.metadata.num_row_groups
    rng = np.random.RandomState(seed)
    n_batches = -(-target_rows // batch_size)
    emitted = 0
    bx = by = None
    while emitted < n_batches:
        order = rng.permutation(n_rg) if shuffle else np.arange(n_rg)
        for rg in order:
            tbl = pf.read_row_group(int(rg))
            x, y = _table_to_xy(tbl, label_col, feature_cols)
            if shuffle:
                p = rng.permutation(len(x))
                x, y = x[p], y[p]
            bx = x if bx is None else np.concatenate([bx, x])
            by = y if by is None else np.concatenate([by, y])
            while len(bx) >= batch_size and emitted < n_batches:
                yield bx[:batch_size], by[:batch_size]
                bx = bx[batch_size:]
                by = by[batch_size:]
                emitted += 1
            if emitted >= n_batches:
                return
        # wrapped past the file's end with batches still owed: keep the
        # partial-batch remainder and continue from a fresh group order
        # (the lazy analog of wrap-padding).
