"""Worker-side loop of the Executor actor pool.

Polls the KV for successive call epochs, executes pickled functions,
posts results/exceptions (ref: ray/worker.py BaseHorovodWorker.execute —
same contract over the KV instead of Ray actor RPC).  Deliberately
imports nothing heavy: dispatched functions own their runtime.
"""

from __future__ import annotations

import os
import pickle
import sys
import traceback


def main() -> int:
    from ..runner.http_kv import KVClient

    client = KVClient(os.environ["HVDT_EXEC_ADDR"],
                      int(os.environ["HVDT_EXEC_PORT"]),
                      bytes.fromhex(os.environ["HVDT_EXEC_SECRET"]))
    rank = int(os.environ.get("HVDT_RANK", 0))
    client.put(f"/exec/ready/{rank}", b"1")
    from ..resilience.retry import Backoff

    epoch = 0
    while True:
        # Either the next call or the stop sentinel arrives for this
        # epoch.  Jittered backoff (5ms -> 50ms cap) keeps dispatch
        # latency low while idle workers decorrelate instead of
        # hammering the KV in lockstep.
        poll = Backoff(first=0.005, cap=0.05)
        while True:
            if client.get(f"/exec/{epoch}/stop") is not None:
                return 0
            raw = client.get(f"/exec/{epoch}/fn")
            if raw is not None:
                break
            poll.sleep()
        try:
            fn, args, kwargs, has_per_rank = pickle.loads(raw)
            if has_per_rank:
                extra = pickle.loads(
                    client.wait(f"/exec/{epoch}/arg/{rank}", timeout=30.0))
                args = tuple(args) + tuple(extra)
            result = ("ok", fn(*args, **kwargs))
        except BaseException:  # noqa: BLE001 - reported to the driver
            result = ("err", traceback.format_exc())
        try:
            payload = pickle.dumps(result)
        except Exception:
            payload = pickle.dumps(("err",
                                    f"unpicklable result: {result[1]!r}"))
        client.put(f"/exec/{epoch}/result/{rank}", payload)
        epoch += 1


if __name__ == "__main__":
    sys.exit(main())
