"""JaxEstimator — the Spark-estimator fit/transform shape without Spark.

Re-conception of ref: spark/keras & spark/torch estimators
(spark/common/params.py, runner.py — Spark ML fit/transform over
distributed workers).  Petastorm/DataFrame plumbing collapses to numpy
arrays sharded across the Executor pool; what survives is the contract:
``est.fit(X, y) -> model`` trains data-parallel across workers, and the
returned model is a plain local object with ``transform``/``predict``.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

from .executor import Executor

__all__ = ["JaxEstimator", "JaxModel"]


class JaxModel:
    """Trained model handle (ref: spark estimators return a Model whose
    transform() runs the predict path)."""

    def __init__(self, params: Any, predict_fn: Callable[[Any, np.ndarray],
                                                         np.ndarray]):
        self.params = params
        self._predict_fn = predict_fn

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._predict_fn(self.params, x))

    def transform(self, x: np.ndarray) -> np.ndarray:
        return self.predict(x)


def _worker_fit(train_fn, fit_kwargs, x_shard, y_shard):
    return train_fn(x_shard, y_shard, **fit_kwargs)


class JaxEstimator:
    """Data-parallel fit over an Executor pool.

    Args:
      train_fn: ``train_fn(x_shard, y_shard, **fit_kwargs) -> params`` —
        runs inside each worker process (it may hvd.init() and allreduce
        itself, or train purely locally; rank/size come from the env
        contract).  Rank 0's returned params become the model.
      predict_fn: ``predict_fn(params, x) -> y_hat`` for the model handle.
      num_workers: pool size (ref: num_proc on the spark estimators).
    """

    def __init__(self, train_fn: Callable, predict_fn: Callable,
                 num_workers: int = 1,
                 env: Optional[Dict[str, str]] = None):
        self.train_fn = train_fn
        self.predict_fn = predict_fn
        self.num_workers = num_workers
        self._env = env

    def _shards(self, x: np.ndarray, y: Optional[np.ndarray]
                ) -> Tuple[list, list]:
        xs = np.array_split(np.asarray(x), self.num_workers)
        ys = (np.array_split(np.asarray(y), self.num_workers)
              if y is not None else [None] * self.num_workers)
        return xs, ys

    def fit(self, x: np.ndarray, y: Optional[np.ndarray] = None,
            **fit_kwargs) -> JaxModel:
        xs, ys = self._shards(x, y)
        with Executor(self.num_workers, env=self._env) as ex:
            # One concurrent dispatch — workers may collectively train
            # (allreduce etc.), so they must all enter together.  Shards
            # ride per-rank KV keys: each worker downloads only its own.
            results = ex.run(_worker_fit,
                             args=(self.train_fn, fit_kwargs),
                             per_rank_args=[(xs[r], ys[r])
                                            for r in range(self.num_workers)])
        return JaxModel(results[0], self.predict_fn)
