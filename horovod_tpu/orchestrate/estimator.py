"""JaxEstimator — the Spark-estimator fit/transform shape without Spark.

Re-conception of ref: spark/keras & spark/torch estimators
(spark/common/params.py, runner.py — Spark ML fit/transform over
distributed workers).  Petastorm/DataFrame plumbing collapses to numpy
arrays sharded across the Executor pool; what survives is the contract:
``est.fit(X, y) -> model`` trains data-parallel across workers, and the
returned model is a plain local object with ``transform``/``predict``.

Two fit paths:

* **declarative** (ref: KerasEstimator's model/optimizer/loss params,
  spark/common/params.py:64-210) — pass ``model_init``/``loss_fn``/
  ``optimizer`` plus ``epochs``/``batch_size``/``validation_split``/
  ``store`` and the estimator runs the full distributed loop itself:
  broadcast initial params, per-batch eager gradient allreduce across
  worker processes, epoch metric averaging, rank-0 checkpointing into
  the store directory (ref: store.py checkpoint dir + BestModelCheckpoint
  rank-0 discipline).
* **custom** (``train_fn``) — bring-your-own worker loop, as before.
"""

from __future__ import annotations

import dataclasses
import os
import socket
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from .executor import Executor
from .ml_params import MLParams

__all__ = ["JaxEstimator", "JaxModel", "ParquetSource"]


@dataclasses.dataclass(frozen=True)
class ParquetSource:
    """Train directly from a Parquet file (ref: the Spark estimators'
    defining input path — Petastorm over Parquet row groups,
    spark/common/util.py).  Workers read only their assigned row groups;
    the driver never materializes the dataset.

    feature_cols: columns forming the feature matrix (None = all columns
    except ``label_col``).
    """

    path: str
    label_col: str
    feature_cols: Optional[Tuple[str, ...]] = None


class JaxModel(MLParams):
    """Trained model handle (ref: spark estimators return a Model whose
    transform() runs the predict path).  MLParams gives it the Spark-ML
    Model persistence surface (``save``/``load``, ``write``/``read``)
    and makes it a registered pyspark Transformer stage
    (orchestrate/ml_params.py)."""

    def __init__(self, params: Any, predict_fn: Callable[[Any, np.ndarray],
                                                         np.ndarray],
                 df_meta: Optional[Dict[str, Any]] = None):
        self.params = params
        self._predict_fn = predict_fn
        self._df_meta = df_meta or {}

    def predict(self, x: np.ndarray) -> np.ndarray:
        return np.asarray(self._predict_fn(self.params, x))

    def transform(self, x):
        """numpy in -> predictions out; Spark DataFrame in -> DataFrame
        out with a prediction column appended (ref: the Spark-ML Model
        _transform contract, spark/torch/estimator.py:413)."""
        if _is_spark_dataframe(x):
            params, predict_fn = self.params, self._predict_fn
            return df_transform(
                x, lambda xa: predict_fn(params, xa), self._df_meta)
        return self.predict(x)


def _worker_fit(train_fn, fit_kwargs, x_shard, y_shard):
    return train_fn(x_shard, y_shard, **fit_kwargs)


def _load_parquet_shard(hvd, spec: Dict[str, Any], row_groups):
    """Worker-side Parquet ingestion: read this rank's row groups, split
    validation locally (before padding, so no train row can leak in), and
    wrap-pad train rows to the cross-rank MAX length so every rank runs
    the same number of lockstep collective steps."""
    import pyarrow.parquet as pq

    meta = spec["parquet"]
    pf = pq.ParquetFile(meta["path"])
    table = pf.read_row_groups(list(row_groups))
    label = meta["label_col"]
    feats = meta["feature_cols"] or [c for c in table.column_names
                                     if c != label]
    x = np.column_stack(
        [np.asarray(table[c], dtype=np.float32) for c in feats])
    # Labels keep their native dtype (int labels index logits in
    # classification losses; array-mode fit preserves the caller's dtype
    # too).
    y = np.asarray(table[label].to_numpy(zero_copy_only=False))

    return _split_and_pad_local(hvd, spec, x, y)


def _hvd_exchange_lengths(hvd, n_train: int,
                          name: str = "est_parquet/target"):
    """Cross-rank (max, min) of per-rank train lengths over one MAX
    allreduce carrying (len, -len) — every rank also learns the MIN, so
    a rank with zero train rows fails on ALL ranks at once instead of
    stranding peers in the next collective until timeout."""
    agg = np.asarray(hvd.allreduce(
        np.asarray([n_train, -n_train], np.int64), op=hvd.Max, name=name))
    return int(agg[0]), int(-agg[1])


def _split_and_pad_local(hvd, spec: Dict[str, Any], x, y):
    """Worker-side lockstep discipline over the established hvd world
    (Parquet + declarative DataFrame paths)."""
    return _split_pad_discipline(
        x, y, spec["validation_split"],
        lambda n: _hvd_exchange_lengths(hvd, n))


def _split_pad_discipline(x, y, validation_split: float, exchange):
    """Shared worker-side lockstep discipline: local validation split
    (before padding, so no train row can leak in), then wrap-padding of
    the train rows to the cross-rank MAX length so every rank runs the
    same number of lockstep collective steps.  ``exchange(n_train)``
    returns the cross-rank (max, min) lengths — hvd MAX-allreduce or
    rendezvous-KV, depending on what the calling path has available."""
    split = validation_split
    n_val = max(1, int(round(len(x) * split))) if split > 0 else 0
    x_train, y_train = x[:len(x) - n_val], y[:len(y) - n_val]
    x_val = x[len(x) - n_val:] if n_val else None
    y_val = y[len(y) - n_val:] if n_val else None

    target, min_len = exchange(len(x_train))
    if min_len == 0:
        raise ValueError(
            "a worker contributed ZERO training rows (empty partition, or "
            "only validation rows after the split) — use more rows per "
            "partition, fewer workers, or a smaller validation_split")
    if len(x_train) < target:
        reps = [i % len(x_train) for i in range(target - len(x_train))]
        x_train = np.concatenate([x_train, x_train[reps]])
        y_train = np.concatenate([y_train, y_train[reps]])
    return x_train, y_train, x_val, y_val


def kv_exchange_shard_lengths(n_rows: int, timeout: Optional[float] = None,
                              key: str = "/dfshard/len"):
    """Cross-rank (max, min) of per-rank row counts over the rendezvous
    KV — the lockstep-padding handshake for barrier-task training paths
    that have not (yet) formed an hvd world.  Requires the launcher env
    contract (HVDT_RANK/SIZE + rendezvous address) in os.environ.
    Callers exchanging MORE than one quantity per run must use distinct
    ``key`` namespaces (per-rank keys are overwritten, not versioned)."""
    import os

    from ..runner.http_kv import KVClient

    if timeout is None:
        from ..common import config

        timeout = config.get_float("HVDT_DFSHARD_TIMEOUT")
    rank = int(os.environ["HVDT_RANK"])
    size = int(os.environ["HVDT_SIZE"])
    kv = KVClient.from_env(os.environ)
    kv.put(f"{key}/{rank}", str(int(n_rows)).encode())
    # KVClient.wait raises TimeoutError itself when a peer never posts.
    lens = [int(kv.wait(f"{key}/{r}", timeout=timeout))
            for r in range(size)]
    return max(lens), min(lens)


def df_rows_to_shards(rows, label_col: str, feature_cols,
                      validation_split: float):
    """Barrier-task DataFrame ingestion shared by the framework
    estimators: rows -> (x_train, y_train, x_val, y_val) with the shared
    split/pad discipline, lengths exchanged over the rendezvous KV (no
    hvd world needed yet).

    An EMPTY partition must fail on ALL ranks at once: this rank posts
    its length (0) to the KV *before* raising, so peers' exchange
    completes immediately and min==0 raises everywhere — instead of
    stranding them in kv.wait until the full timeout."""
    if not rows:
        kv_exchange_shard_lengths(0)
        raise ValueError(
            "a barrier task received an EMPTY DataFrame partition — "
            "repartition produced skew; use more rows or fewer workers")
    x, y = _rows_to_xy(rows, label_col, feature_cols)
    return _split_pad_discipline(x, y, validation_split,
                                 kv_exchange_shard_lengths)


def _row_get(r, c):
    try:
        return r[c]
    except (TypeError, IndexError):
        return getattr(r, c)


def infer_feature_cols(first, feature_cols, exclude=()):
    """Column discovery shared by every row-materialization path
    (in-memory fit, spill, transform): explicit ``feature_cols`` wins;
    otherwise every column of the first Row (pyspark Row or mapping)
    except ``exclude``."""
    if feature_cols:
        return list(feature_cols)
    try:
        names = list(first.__fields__)           # pyspark Row
    except AttributeError:
        names = list(first.keys())               # mapping (stub/tests)
    return [c for c in names if c not in exclude]


def _rows_to_x(rows, feature_cols, exclude=()):
    """Row materialization shared by fit(df) and transform(df): a
    partition's Rows (pyspark Row or plain mappings) -> x float32 [n, d].
    Vector-typed columns are flattened via ``np.asarray`` per cell."""
    cols = infer_feature_cols(rows[0], feature_cols, exclude)
    return np.asarray(
        [np.concatenate([np.ravel(np.asarray(_row_get(r, c), np.float32))
                         for c in cols]) for r in rows], np.float32)


def _rows_to_xy(rows, label_col: str, feature_cols):
    """Barrier-task row materialization: (x float32 [n, d],
    y native-dtype [n])."""
    if not rows:
        raise ValueError(
            "a barrier task received an EMPTY DataFrame partition — "
            "repartition produced skew; use more rows or fewer workers")
    x = _rows_to_x(rows, feature_cols, exclude=(label_col,))
    y = np.asarray([_row_get(r, label_col) for r in rows])
    return x, y


def rows_predictor(predict: Callable, label_col: str, feature_cols,
                   output_col: str):
    """Build the per-partition ``rows -> [value, ...]`` callable for
    :func:`spark.transform_dataframe` from an ``x -> preds`` model
    predict.  Per-row values: scalar predictions become Python floats,
    vector predictions become float lists (the reference flattens to
    DenseVector — torch/estimator.py:452-466)."""

    def rows_predict(rows):
        x = _rows_to_x(rows, feature_cols,
                       exclude=(label_col, output_col))
        preds = np.asarray(predict(x))
        if preds.shape[0] != len(rows):
            raise ValueError(
                f"predict returned {preds.shape[0]} predictions for "
                f"{len(rows)} rows")
        out = []
        for p in preds:
            p = np.ravel(np.asarray(p))
            out.append(float(p[0]) if p.size == 1
                       else [float(v) for v in p])
        return out

    return rows_predict


def df_transform(df, predict: Callable, meta: Dict[str, Any]):
    """DataFrame-out inference dispatch shared by the estimator model
    handles: append ``meta['output_col']`` predictions to ``df``."""
    from . import spark as spark_mod

    output_col = meta.get("output_col") or "prediction"
    return spark_mod.transform_dataframe(
        rows_predictor(predict, meta.get("label_col") or "label",
                       meta.get("feature_cols"), output_col),
        df, output_col)


def _declarative_fit(spec: Dict[str, Any], x_train, y_train, x_val, y_val):
    """Runs inside each Executor worker: the estimator-owned training loop.

    The worker env carries JAX_PLATFORMS=cpu + HVDT_COORDINATOR_ADDR (set
    by ``JaxEstimator.fit``), so ``hvd.init()`` connects the JAX
    distributed runtime across the pool and eager collectives negotiate
    through it — the same per-step gradient-allreduce shape as the
    reference's estimator workers (ref: spark/keras/remote.py train loop).

    Lockstep invariant (the val-metric collective below must be entered
    by every rank or none, and batch counts must match): in ARRAY mode
    the driver established it before dispatch — global tail split, then
    equal-length train shards (padding never touches validation rows).
    In PARQUET mode ``_load_parquet_shard`` establishes the same
    invariant worker-side: local pre-padding split with ``n_val >= 1``
    whenever validation is on, then MAX-allreduce wrap-padding of the
    train rows.  Any change to either path must preserve both halves.
    """
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp  # noqa: F401
    import optax

    import horovod_tpu as hvd

    hvd.init()
    rank = hvd.rank()

    spill_cleanup = None     # set by the out-of-core branch
    try:
        stream = None        # (train_path, feature_cols, target_rows) or None
        stream_val = None    # val parquet path (streamed eval) or None
        if spec.get("spark_df_stream"):
            # Out-of-core DataFrame mode (ref: spark/common/util.py
            # prepare_data + Petastorm row-group streaming): x_train carries
            # this barrier task's ROW ITERATOR.  Spill it to Parquet in
            # bounded chunks, exchange lengths, then stream row groups
            # batch-wise each epoch — the partition is never materialized.
            from .spill import (ZERO_TRAIN_ROWS_MSG,
                                spill_partition_to_parquet, spill_scratch)

            meta = spec["spark_df_stream"]
            # Cleanup callable is armed BEFORE the spill runs, so a
            # mid-spill failure still removes whatever row groups were
            # already written.
            spill_dir, sp_prefix, spill_cleanup = spill_scratch(
                meta.get("spill_dir"), rank)
            train_path, val_path, n_train, n_val, feat_cols = \
                spill_partition_to_parquet(
                    x_train, meta["label_col"], meta["feature_cols"],
                    spec["validation_split"], spill_dir,
                    meta.get("rows_per_group", 4096), prefix=sp_prefix)
            target, min_len = _hvd_exchange_lengths(hvd, n_train)
            if min_len == 0:
                raise ValueError(ZERO_TRAIN_ROWS_MSG)
            # Validation must be all-or-none across ranks (the est_metric/val
            # allreduce below is collective).  The per-chunk split can give a
            # rank zero val rows (partition an exact multiple of
            # rows_per_group with a tiny split): if ANY rank got none, all
            # ranks skip validation rather than mismatch the collective.
            # Evaluation STREAMS the val file (stream_val_loss) — the val
            # set is partition-proportional, so materializing it would
            # defeat the bounded-memory contract.
            _, min_val = _hvd_exchange_lengths(hvd, n_val,
                                               name="est_stream/val")
            if val_path is not None and min_val > 0:
                stream_val = val_path
            stream = (train_path, meta["label_col"], feat_cols, target)
            x_train = np.zeros((0, 1), np.float32)   # loop streams instead
            y_train = np.zeros((0,), np.float32)
        elif spec.get("parquet"):
            # Parquet mode: x_train carries this rank's ROW-GROUP indices; the
            # worker reads only those groups (the Petastorm-shape contract —
            # ref: spark/common/util.py Parquet row-group partitioning).
            x_train, y_train, x_val, y_val = _load_parquet_shard(
                hvd, spec, x_train)
        elif spec.get("spark_df"):
            # DataFrame mode: x_train carries this barrier task's partition
            # rows; materialize + apply the shared local split/pad
            # discipline (ref: dataframe->Petastorm prep, spark/common/util.py).
            meta = spec["spark_df"]
            if x_train:
                x, y = _rows_to_xy(x_train, meta["label_col"],
                                   meta["feature_cols"])
            else:
                # Empty partition: enter the length exchange with 0 rows so
                # ALL ranks fail the min==0 check together instead of peers
                # hanging in the allreduce this rank never reached.
                x = np.zeros((0, 1), np.float32)
                y = np.zeros((0,), np.float32)
            x_train, y_train, x_val, y_val = _split_and_pad_local(
                hvd, spec, x, y)
        x_train = np.asarray(x_train)
        y_train = np.asarray(y_train)

        params = spec["model_init"](jax.random.PRNGKey(spec["seed"]))
        # Broadcast rank 0's init so all replicas start identical even if
        # model_init is nondeterministic (ref: broadcast_parameters at start
        # of training, torch/functions.py:30).
        params = hvd.broadcast_parameters(params, root_rank=0)
        opt = spec["optimizer"] or optax.adam(1e-3)
        opt_state = opt.init(params)
        loss_fn = spec["loss_fn"]

        grad_step = jax.jit(jax.value_and_grad(loss_fn))
        eval_loss = jax.jit(loss_fn)

        bs = spec["batch_size"]
        rng = np.random.RandomState(spec["seed"] + 101 * rank)
        manager = None
        if spec["store"]:
            # All ranks construct the manager and enter save(): the write is
            # rank-0-only inside save_checkpoint, but its completion barrier
            # is collective.
            from ..checkpoint import CheckpointManager

            manager = CheckpointManager(spec["store"])

        def _epoch_batches(epoch):
            """Equal-count lockstep batches: stream mode yields full batches
            from Parquet row groups (wrap-around to the cross-rank max);
            array mode permutes in memory with tail-batch wrap-padding —
            both give every rank ceil(target / bs) identical-shape steps."""
            if stream is not None:
                from .spill import stream_batches

                train_path, label_c, feat_cols, target = stream
                yield from stream_batches(
                    train_path, label_c, feat_cols, bs, target,
                    seed=spec["seed"] + 7919 * epoch + 101 * rank,
                    shuffle=spec["shuffle"])
                return
            order = (rng.permutation(len(x_train)) if spec["shuffle"]
                     else np.arange(len(x_train)))
            for start in range(0, max(len(order), 1), max(bs, 1)):
                idx = order[start:start + bs]
                if idx.size == 0:
                    continue
                # Pad the tail batch to full size (static shapes: one jit
                # trace) — wrap-around rows re-weight a few samples slightly,
                # matching the reference's repartition-to-equal-shards
                # behavior rather than dropping data.
                if idx.size < bs:
                    idx = np.concatenate([idx, order[:bs - idx.size]])
                yield x_train[idx], y_train[idx]

        history: List[Dict[str, float]] = []
        for epoch in range(spec["epochs"]):
            losses = []
            for xb, yb in _epoch_batches(epoch):
                loss, grads = grad_step(params, xb, yb)
                # One grouped (all-or-nothing fused) eager allreduce per step
                # (ref: grouped allreduce + GroupTable, common/group_table.cc).
                leaves, treedef = jax.tree.flatten(grads)
                reduced = hvd.grouped_allreduce(
                    [np.asarray(g) for g in leaves], name="est_grad")
                grads = jax.tree.unflatten(
                    treedef, [jnp.asarray(r) for r in reduced])
                updates, opt_state = opt.update(grads, opt_state, params)
                params = optax.apply_updates(params, updates)
                losses.append(float(loss))
            row = {"epoch": epoch,
                   "train_loss": float(np.mean(losses)) if losses else float("nan")}
            # Cross-worker metric averaging (ref: MetricAverageCallback,
            # _keras/callbacks.py:49).
            row["train_loss"] = float(np.asarray(hvd.allreduce(
                np.asarray([row["train_loss"]], np.float32),
                name="est_metric/train"))[0])
            vl = None
            if x_val is not None:
                vl = float(eval_loss(params, np.asarray(x_val),
                                     np.asarray(y_val)))
            elif stream_val is not None:
                from .spill import stream_val_loss

                vl = stream_val_loss(eval_loss, params, stream_val,
                                     stream[1], stream[2])
            if vl is not None:
                row["val_loss"] = float(np.asarray(hvd.allreduce(
                    np.asarray([vl], np.float32), name="est_metric/val"))[0])
            history.append(row)
            if manager is not None:
                manager.save(epoch, params, force=True)
            hvd.barrier()

        return {"params": jax.tree.map(np.asarray, params), "history": history,
                "size": hvd.size()}
    finally:
        # Spilled Parquet is per-fit scratch: reused executor
        # processes must not accumulate dataset-sized files.
        if spill_cleanup is not None:
            spill_cleanup()


class JaxEstimator(MLParams):
    """Data-parallel fit over an Executor pool.

    MLParams (orchestrate/ml_params.py) adds the Spark-ML estimator
    surface: camelCase param get/set (``setEpochs(3)``), ``copy``,
    ``save``/``load`` persistence, and pyspark ``Pipeline`` stage
    compatibility (ref: spark/common/params.py EstimatorParams).

    Args:
      train_fn: ``train_fn(x_shard, y_shard, **fit_kwargs) -> params`` —
        runs inside each worker process (it may hvd.init() and allreduce
        itself, or train purely locally; rank/size come from the env
        contract).  Rank 0's returned params become the model.
      predict_fn: ``predict_fn(params, x) -> y_hat`` for the model handle.
      num_workers: pool size (ref: num_proc on the spark estimators).
    """

    def __init__(self, train_fn: Optional[Callable] = None,
                 predict_fn: Optional[Callable] = None,
                 num_workers: int = 1,
                 env: Optional[Dict[str, str]] = None,
                 *,
                 model_init: Optional[Callable] = None,
                 loss_fn: Optional[Callable] = None,
                 optimizer: Any = None,
                 epochs: int = 1,
                 batch_size: int = 32,
                 validation_split: float = 0.0,
                 shuffle: bool = True,
                 store: Optional[Any] = None,
                 label_col: str = "label",
                 feature_cols: Optional[Tuple[str, ...]] = None,
                 output_col: str = "prediction",
                 cache: str = "memory",
                 rows_per_group: int = 4096,
                 spill_dir: Optional[str] = None,
                 seed: int = 0):
        if (train_fn is None) == (model_init is None):
            raise ValueError(
                "pass exactly one of train_fn (custom loop) or "
                "model_init+loss_fn (declarative loop)")
        if model_init is not None and loss_fn is None:
            raise ValueError("declarative fit needs loss_fn")
        if predict_fn is None:
            raise ValueError(
                "predict_fn is required — the returned JaxModel's "
                "transform/predict contract depends on it")
        if not 0.0 <= validation_split < 1.0:
            raise ValueError(
                f"validation_split must be in [0, 1), got {validation_split}")
        self.train_fn = train_fn
        self.predict_fn = predict_fn
        self.num_workers = num_workers
        self._env = env
        self._label_col = label_col
        self._feature_cols = feature_cols
        self._output_col = output_col
        if cache not in ("memory", "disk"):
            raise ValueError(
                f"cache must be 'memory' or 'disk', got {cache!r}")
        self._cache = cache
        self._rows_per_group = int(rows_per_group)
        self._spill_dir = spill_dir
        if store is not None:
            from .store import _REMOTE_SCHEMES, Store

            if isinstance(store, str):
                # A str store is a LOCAL checkpoint directory, used
                # verbatim.  Remote prefixes must come in as Store
                # objects once CheckpointManager writes through the
                # Store IO backend; today it writes the local
                # filesystem only, so a raw "gs://..." string would
                # silently become a literal ./gs: directory.
                if store.startswith(_REMOTE_SCHEMES):
                    raise ValueError(
                        f"store={store!r}: remote store prefixes are not "
                        "supported as plain strings — CheckpointManager "
                        "writes the local filesystem; pass a local "
                        "directory path (or mount the bucket)")
            else:
                # Store abstraction (orchestrate/store.py): checkpoints
                # go under the prefix's run-path discipline.
                store = Store.create(store).get_checkpoint_path()
                if store.startswith(_REMOTE_SCHEMES):
                    raise ValueError(
                        f"store checkpoint path {store!r}: "
                        "CheckpointManager writes the local filesystem "
                        "only; use a LocalStore (or mount the bucket)")
        self._spec = None if model_init is None else {
            "model_init": model_init, "loss_fn": loss_fn,
            "optimizer": optimizer, "epochs": int(epochs),
            "batch_size": int(batch_size),
            "validation_split": float(validation_split),
            "shuffle": bool(shuffle), "store": store, "seed": int(seed)}
        self.history_: List[Dict[str, float]] = []

    def _shards(self, x: np.ndarray, y: Optional[np.ndarray]
                ) -> Tuple[list, list]:
        xs = np.array_split(np.asarray(x), self.num_workers)
        ys = (np.array_split(np.asarray(y), self.num_workers)
              if y is not None else [None] * self.num_workers)
        return xs, ys

    @staticmethod
    def _equalize(shards: list) -> list:
        """Wrap-pad every shard to the longest shard's length.

        Declarative workers issue name-matched collectives in lockstep, so
        every rank MUST see the same shard length (same batch count) —
        the repartition-to-equal-shards discipline of the reference's
        estimators (spark/common/util.py prep for equal row groups).
        Padding duplicates a shard's OWN rows only; validation rows are
        split off globally before this runs, so they can never leak in.
        """
        target = max(len(s) for s in shards)

        def pad(s):
            if s is None or len(s) == target:
                return s
            reps = [s[i % len(s)] for i in range(target - len(s))]
            return np.concatenate([s, np.stack(reps)]) if reps else s

        return [pad(s) for s in shards]

    def fit(self, x: np.ndarray, y: Optional[np.ndarray] = None,
            **fit_kwargs) -> JaxModel:
        env = dict(self._env or {})
        if isinstance(x, ParquetSource) and self._spec is None:
            raise ValueError(
                "ParquetSource requires the declarative estimator "
                "(model_init/loss_fn); a custom train_fn receives numpy "
                "shards")
        if _is_spark_dataframe(x):
            if self._spec is None:
                raise ValueError(
                    "DataFrame fit requires the declarative estimator "
                    "(model_init/loss_fn) — a custom train_fn receives "
                    "numpy shards")
            return self._fit_spark_df(x, y, env)
        if self._spec is not None:
            if fit_kwargs:
                raise TypeError(
                    "declarative fit() takes no per-call kwargs — pass "
                    f"them to the constructor (got {sorted(fit_kwargs)})")
            if isinstance(x, ParquetSource):
                return self._fit_parquet(x, y, env)
            if y is None:
                raise ValueError("declarative fit needs y (loss_fn is "
                                 "called as loss_fn(params, xb, yb))")
            x, y = np.asarray(x), np.asarray(y)
            xs, ys, xv, yv = split_and_shard(
                x, y, self._spec["validation_split"], self.num_workers)
            return self._run_declarative(
                self._spec, [(xs[r], ys[r], xv[r], yv[r])
                             for r in range(self.num_workers)], env)

        xs, ys = self._shards(x, y)
        with Executor(self.num_workers, env=env) as ex:
            # One concurrent dispatch — workers may collectively train
            # (allreduce etc.), so they must all enter together.  Shards
            # ride per-rank KV keys: each worker downloads only its own.
            results = ex.run(_worker_fit,
                             args=(self.train_fn, fit_kwargs),
                             per_rank_args=[(xs[r], ys[r])
                                            for r in range(self.num_workers)])
        return JaxModel(results[0], self.predict_fn,
                        df_meta=self._df_meta())


    def _fit_parquet(self, source: ParquetSource, y, env) -> JaxModel:
        """Assign Parquet row groups round-robin and let each worker read
        its own (driver touches only metadata)."""
        import pyarrow.parquet as pq

        if y is not None:
            raise ValueError(
                "ParquetSource carries labels via label_col; pass y=None")
        n_rg = pq.ParquetFile(source.path).metadata.num_row_groups
        if n_rg < self.num_workers:
            raise ValueError(
                f"{source.path} has {n_rg} row groups < num_workers="
                f"{self.num_workers}; rewrite with smaller row groups "
                "or fewer workers")
        assign = [list(range(r, n_rg, self.num_workers))
                  for r in range(self.num_workers)]
        spec = dict(self._spec)
        spec["parquet"] = {"path": source.path,
                           "label_col": source.label_col,
                           "feature_cols": (list(source.feature_cols)
                                            if source.feature_cols
                                            else None)}
        return self._run_declarative(
            spec, [(assign[r], None, None, None)
                   for r in range(self.num_workers)], env)

    def _fit_spark_df(self, df, y, env) -> JaxModel:
        """fit(df): training runs INSIDE Spark barrier tasks, each on its
        own partition's rows — the driver never collects the dataset
        (ref: spark estimators' fit(df) over dataframe->Petastorm,
        spark/common/util.py; barrier training, spark/keras/remote.py).
        Rank r's shard is partition r of ``df.repartition(num_workers)``;
        the worker-side split/pad discipline matches the Parquet path."""
        if y is not None:
            raise ValueError(
                "DataFrame fit carries labels in label_col "
                f"({self._label_col!r}); pass y=None")
        from . import spark as spark_mod

        spec = dict(self._spec)
        meta = {"label_col": self._label_col,
                "feature_cols": (list(self._feature_cols)
                                 if self._feature_cols else None)}
        stream = self._cache == "disk"
        if stream:
            # Out-of-core feed (ref: spark/common/util.py prepare_data):
            # the barrier task spills its partition stream to Parquet
            # row groups and trains by streaming them back — a partition
            # larger than task memory never materializes.
            meta["rows_per_group"] = self._rows_per_group
            meta["spill_dir"] = self._spill_dir
            spec["spark_df_stream"] = meta
        else:
            spec["spark_df"] = meta
        env = collective_worker_env(env, local_coordinator=False)

        def task(rows):
            return _declarative_fit(spec, rows, None, None, None)

        results = spark_mod.run_on_dataframe(
            task, df, num_proc=self.num_workers, env=env, stream=stream)
        return self._finish_declarative(results)

    def _run_declarative(self, spec, per_rank_args, env) -> JaxModel:
        """Shared dispatch tail for both declarative input modes."""
        env = collective_worker_env(env)
        with Executor(self.num_workers, env=env) as ex:
            results = ex.run(_declarative_fit, args=(spec,),
                             per_rank_args=per_rank_args)
        return self._finish_declarative(results)

    def _finish_declarative(self, results) -> JaxModel:
        check_one_world(results, self.num_workers)
        self.history_ = results[0]["history"]
        return JaxModel(results[0]["params"], self.predict_fn,
                        df_meta=self._df_meta())

    def _df_meta(self) -> Dict[str, Any]:
        return estimator_df_meta(self)


def estimator_df_meta(est) -> Dict[str, Any]:
    """The df_meta dict shared by every estimator's model handle
    (label/feature/output columns for transform(df) and fit(df))."""
    return {"label_col": est._label_col,
            "feature_cols": (list(est._feature_cols)
                             if est._feature_cols else None),
            "output_col": est._output_col}


def check_one_world(results, num_workers: int) -> None:
    """One-world guard shared by every estimator dispatch tail: workers
    that fail to rendezvous (coordinator unreachable, stale world in a
    reused process) would each train as a size-1 island on its own shard
    — that must be an error, not a silently under-trained model.  Each
    worker reports its ``hvd.size()`` in the result dict's ``size``."""
    sizes = {r["size"] for r in results if r}
    if sizes != {num_workers}:
        raise RuntimeError(
            f"workers did not form one world of {num_workers} "
            f"(saw sizes {sizes}) — collective training did not run")


def _is_spark_dataframe(x) -> bool:
    """Duck-typed Spark DataFrame detection (pyspark may not be
    importable here; barrier tasks see the real class)."""
    return (hasattr(x, "rdd") and hasattr(x, "columns")
            and hasattr(x, "repartition"))


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def split_and_shard(x: np.ndarray, y: np.ndarray, validation_split: float,
                    num_workers: int):
    """Shared estimator data discipline: GLOBAL validation tail split
    BEFORE sharding/equalization (padding can never leak train rows into
    validation), equalized train shards (same lockstep collective count
    per worker), round-robin val shards with a whole-set fallback so
    every rank enters the val-metric collectives.

    Returns (xs, ys, xv, yv) — per-rank lists; xv/yv entries are None
    when validation_split == 0."""
    n_val = int(round(len(x) * validation_split))
    x_tr, y_tr = x[:len(x) - n_val], y[:len(y) - n_val]
    if len(x_tr) < num_workers:
        raise ValueError(
            f"need at least num_workers={num_workers} TRAINING samples "
            f"after the validation split, got {len(x_tr)} "
            f"(n={len(x)}, validation_split={validation_split})")
    xs = JaxEstimator._equalize(np.array_split(x_tr, num_workers))
    ys = JaxEstimator._equalize(np.array_split(y_tr, num_workers))
    if n_val:
        xv = [x[len(x) - n_val:][r::num_workers] for r in range(num_workers)]
        yv = [y[len(y) - n_val:][r::num_workers] for r in range(num_workers)]
        xv = [s if len(s) else x[len(x) - n_val:] for s in xv]
        yv = [s if len(s) else y[len(y) - n_val:] for s in yv]
    else:
        xv = yv = [None] * num_workers
    return xs, ys, xv, yv


def collective_worker_env(env: Optional[Dict[str, str]],
                          local_coordinator: bool = True) -> Dict[str, str]:
    """Env for Executor workers that run COLLECTIVE training: pin them to
    the CPU platform (an accelerator-steering outer env would make every
    worker claim the real TPU; the sitecustomize pin rides
    PALLAS_AXON_POOL_IPS) and give them a JAX coordination-service
    address so ``hvd.init()`` forms one distributed world — without it
    every worker is a silent size-1 island and collectives no-op.

    ``local_coordinator=False`` (the Spark barrier-task paths) skips the
    ``127.0.0.1:<free_port>`` default: a driver-chosen localhost address
    is only reachable when every worker is colocated with the driver, so
    barrier tasks instead derive the coordinator from rank 0's task
    address over the rendezvous KV (``spark._enter_barrier``)."""
    env = dict(env or {})
    env.setdefault("JAX_PLATFORMS", "cpu")
    env.setdefault("PALLAS_AXON_POOL_IPS", "")
    if local_coordinator:
        env.setdefault("HVDT_COORDINATOR_ADDR", f"127.0.0.1:{_free_port()}")
    return env
