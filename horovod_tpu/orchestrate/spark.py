"""Run training functions inside Spark executors.

Re-conception of ref: horovod/spark/runner.py:197 ``run`` — the same
contract (run ``fn`` on ``num_proc`` Spark tasks, results returned in
rank order) on the TPU process model: instead of a Spark-side driver
service + MPI/Gloo launch chain, the driver starts this framework's
HMAC-authed rendezvous KV and the tasks run ``fn`` under **barrier
execution** (``RDD.barrier().mapPartitions``) with the launcher's
``HVDT_*`` env contract set from the barrier task context, so
``hvd.init()`` inside ``fn`` rendezvouses exactly as CLI-launched
workers do.  Barrier mode gives the reference's all-or-nothing
scheduling guarantee (every rank scheduled before any runs — ref's
start_timeout exists for the same reason).

pyspark is imported lazily; the adapter logic (rank layout from task
addresses, env contract, rank-ordered results, job-group cancellation on
timeout) is testable with a stub SparkContext (tests/test_spark.py).

``run_elastic`` is intentionally not provided: elastic membership comes
from the ``hvdtrun --elastic`` driver's discovery loop (docs/elastic.md);
re-implementing it inside a fixed-size Spark barrier stage would fake
the semantics (barrier stages cannot change width mid-run).
"""

from __future__ import annotations

import os
import queue
import socket
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Tuple

from ..common import config

__all__ = ["run", "run_on_dataframe", "transform_dataframe"]


def transform_dataframe(rows_predict: Callable, df, output_col: str
                        = "prediction", chunk_rows: int = 4096):
    """DataFrame-out inference (ref: spark/torch/estimator.py:413-470
    ``_transform`` — the other half of the Spark-ML contract): map each
    partition's rows through ``rows_predict(rows) -> [value, ...]`` and
    return a DataFrame with ``output_col`` appended to the schema.

    Plain (non-barrier) ``mapPartitions`` — inference has no collectives,
    so partitions are independent and Spark's normal scheduling/retry
    semantics apply.  The iterator is consumed in ``chunk_rows`` chunks,
    so a partition that needed ``cache='disk'`` to train also predicts
    in bounded memory (rows_predict runs once per chunk — the model's
    closure should deserialize lazily or tolerate repeated calls)."""
    import itertools

    def _part(it):
        try:
            from pyspark.sql import Row
        except ImportError:           # stub path (tests)
            Row = None
        while True:
            rows = list(itertools.islice(it, chunk_rows))
            if not rows:
                return
            preds = rows_predict(rows)
            for r, p in zip(rows, preds):
                d = dict(r.asDict()) if hasattr(r, "asDict") else dict(r)
                d[output_col] = p
                yield Row(**d) if Row is not None else d

    return df.rdd.mapPartitions(_part).toDF()


def _task_env(rank: int, addresses: List[str], base: Dict[str, str],
              extra: Optional[Dict[str, str]]) -> Dict[str, str]:
    """Per-rank HVDT_* contract from barrier task ``host:port``
    addresses (shared layout rule: runner/hosts.py
    rank_env_from_hosts)."""
    from ..runner.hosts import rank_env_from_hosts

    return rank_env_from_hosts(rank, [a.rsplit(":", 1)[0]
                                      for a in addresses], base, extra)


def run(fn: Callable, args: Tuple = (), kwargs: Optional[Dict] = None,
        num_proc: Optional[int] = None, start_timeout: Optional[int] = None,
        use_mpi: Optional[bool] = None, use_gloo: Optional[bool] = None,
        extra_mpi_args: Optional[str] = None,
        env: Optional[Dict[str, str]] = None, stdout=None, stderr=None,
        verbose: int = 1, nics=None,
        prefix_output_with_timestamp: bool = False,
        executable: Optional[str] = None) -> List[Any]:
    """Run ``fn(*args, **kwargs)`` on ``num_proc`` Spark tasks; return
    the per-rank results in rank order (ref: spark/runner.py:197 run —
    same signature; the MPI/Gloo/nics/executable knobs are accepted for
    drop-in compatibility and ignored, since workers run in-task over
    the XLA/TCP data plane rather than under a re-exec'd launcher)."""
    import pyspark

    kwargs = kwargs or {}
    if start_timeout is None:
        legacy = os.getenv("HOROVOD_SPARK_START_TIMEOUT")
        start_timeout = int(legacy) if legacy else int(
            config.get_float("HVDT_SPARK_START_TIMEOUT"))

    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError(
            "Could not find an active SparkContext, are you running in a "
            "PySpark session?")
    if num_proc is None:
        num_proc = sc.defaultParallelism

    from ..runner.http_kv import RendezvousServer, new_secret

    server = RendezvousServer(secret=new_secret())
    port = server.start()
    try:
        addr = socket.gethostbyname(socket.gethostname())
    except OSError:
        addr = "127.0.0.1"
    server.put_local("/cluster/size", str(num_proc).encode())
    base_env = {
        "HVDT_RENDEZVOUS_ADDR": addr,
        "HVDT_RENDEZVOUS_PORT": str(port),
        "HVDT_SECRET": server.secret.hex(),
    }
    extra_env = dict(env) if env else None

    def _task(iterator):
        rank = _enter_barrier(base_env, extra_env)
        result = fn(*args, **kwargs)
        yield (rank, result)

    def _make_rdd():
        return sc.parallelize(range(num_proc), num_proc)

    return _barrier_collect(sc, server, _make_rdd, _task, num_proc,
                            start_timeout, port)


def run_on_dataframe(fn: Callable, df, num_proc: Optional[int] = None,
                     start_timeout: Optional[int] = None,
                     env: Optional[Dict[str, str]] = None,
                     stream: bool = False) -> List[Any]:
    """Run ``fn(rows)`` on ``num_proc`` barrier tasks, each fed ITS
    partition of ``df`` (rows materialized as a list) — the
    DataFrame-in training path of the reference's estimators
    (ref: spark/common/util.py dataframe->Petastorm prep + barrier-task
    training in spark/keras/remote.py), without the driver ever
    collecting the dataset.

    ``stream=True`` passes ``fn`` the raw row ITERATOR instead of a
    list — the out-of-core path (estimator ``cache='disk'``) spills it
    to Parquet in bounded chunks so a partition larger than task memory
    never materializes.

    The DataFrame is repartitioned to ``num_proc`` so the barrier stage
    width equals the worker count; rank r trains on partition r.
    Returns per-rank results in rank order."""
    import pyspark

    if start_timeout is None:
        legacy = os.getenv("HOROVOD_SPARK_START_TIMEOUT")
        start_timeout = int(legacy) if legacy else int(
            config.get_float("HVDT_SPARK_START_TIMEOUT"))
    sc = pyspark.SparkContext._active_spark_context
    if sc is None:
        raise RuntimeError(
            "Could not find an active SparkContext, are you running in a "
            "PySpark session?")
    if num_proc is None:
        num_proc = sc.defaultParallelism

    from ..runner.http_kv import RendezvousServer, new_secret

    server = RendezvousServer(secret=new_secret())
    port = server.start()
    try:
        addr = socket.gethostbyname(socket.gethostname())
    except OSError:
        addr = "127.0.0.1"
    server.put_local("/cluster/size", str(num_proc).encode())
    base_env = {
        "HVDT_RENDEZVOUS_ADDR": addr,
        "HVDT_RENDEZVOUS_PORT": str(port),
        "HVDT_SECRET": server.secret.hex(),
    }
    extra_env = dict(env) if env else None

    def _task(iterator):
        rank = _enter_barrier(base_env, extra_env)
        result = fn(iterator if stream else list(iterator))
        yield (rank, result)

    def _make_rdd():
        return df.repartition(num_proc).rdd

    return _barrier_collect(sc, server, _make_rdd, _task, num_proc,
                            start_timeout, port)


def _enter_barrier(base_env, extra_env) -> int:
    """Inside a barrier task: set the HVDT_* contract, report startup,
    enter the registration barrier; returns this task's rank."""
    from pyspark import BarrierTaskContext

    ctx = BarrierTaskContext.get()
    rank = ctx.partitionId()
    addresses = [i.address for i in ctx.getTaskInfos()]
    task_env = _task_env(rank, addresses, base_env, extra_env)
    os.environ.update(task_env)
    from ..runner.http_kv import KVClient

    kv = KVClient.from_env(os.environ)
    # Key the decision off THIS run's env contract, not os.environ: with
    # spark.python.worker.reuse a stale HVDT_COORDINATOR_ADDR from a
    # previous fit() survives in the process and points at a dead
    # coordinator — always re-derive unless the caller set one.
    if not task_env.get("HVDT_COORDINATOR_ADDR"):
        # Derive the JAX coordination-service address from rank 0's OWN
        # task address: a driver-chosen 127.0.0.1 default only works when
        # every task is colocated with the driver.  Rank 0 binds a port
        # free on ITS host and publishes host:port over the KV.  The key
        # is scoped by the barrier-stage attempt: on a stage RETRY the
        # previous attempt's coordinator is dead, and an unscoped key
        # would hand its address straight back to the waiting ranks.
        attempt = getattr(ctx, "stageAttemptNumber",
                          getattr(ctx, "attemptNumber", lambda: 0))()
        key = f"/spark/coord/{attempt}"
        if rank == 0:
            host0 = addresses[0].rsplit(":", 1)[0]
            with socket.socket() as s:
                s.bind(("", 0))
                coord = f"{host0}:{s.getsockname()[1]}"
            kv.put(key, coord.encode())
        else:
            coord = kv.wait(key, timeout=config.get_float(
                "HVDT_SPARK_COORD_TIMEOUT")).decode()
        os.environ["HVDT_COORDINATOR_ADDR"] = coord
    # Tell the driver this rank was actually scheduled: startup is
    # bounded by start_timeout on the driver side, and a barrier stage
    # the cluster cannot schedule must fail fast there, not after the
    # (long) run timeout (ref: spark/runner.py start_timeout rationale).
    kv.put(f"/spark/started/{rank}", b"1")
    # All ranks enter together (mirrors the reference's registration
    # barrier before launching the job).
    ctx.barrier()
    return rank


def _barrier_collect(sc, server, make_rdd, task, num_proc, start_timeout,
                     port) -> List[Any]:
    """Shared driver tail: launch the barrier stage on a collector
    thread, bound startup by start_timeout (started-flags on the KV),
    bound the run by HVDT_SPARK_RUN_TIMEOUT, return rank-ordered
    results."""
    job_group = f"horovod_tpu.spark.run.{port}"
    result_q: "queue.Queue" = queue.Queue(1)

    def _collect():
        try:
            sc.setJobGroup(job_group, "horovod_tpu.orchestrate.spark.run",
                           interruptOnCancel=True)
            rdd = make_rdd()
            result_q.put(("ok", rdd.barrier().mapPartitions(task).collect()))
        except BaseException as e:  # noqa: BLE001 — re-raised on the caller
            result_q.put(("err", e))

    t = threading.Thread(target=_collect, daemon=True)
    t.start()
    try:
        # Phase 1 — startup, bounded by start_timeout on its own: poll for
        # either an (early) result or every rank's /spark/started/<r> flag.
        # A barrier stage the cluster cannot schedule (busy slots, dynamic
        # allocation) fails HERE with a scheduling message instead of
        # hanging until the run timeout.
        deadline = time.monotonic() + start_timeout
        status = payload = None
        while True:
            try:
                status, payload = result_q.get(timeout=1.0)
                break
            except queue.Empty:
                pass
            if all(server.get_local(f"/spark/started/{r}") is not None
                   for r in range(num_proc)):
                break
            if time.monotonic() > deadline:
                sc.cancelJobGroup(job_group)
                started = [r for r in range(num_proc)
                           if server.get_local(f"/spark/started/{r}")
                           is not None]
                raise TimeoutError(
                    f"Only {len(started)}/{num_proc} Spark barrier tasks "
                    f"started within start_timeout={start_timeout}s; "
                    f"cancelled job group {job_group}. Check that the "
                    f"cluster has {num_proc} simultaneously schedulable "
                    "tasks (barrier mode needs all of them at once).")
        # Phase 2 — the run itself, bounded by the (long) run timeout.
        if status is None:
            try:
                status, payload = result_q.get(
                    timeout=config.get_float("HVDT_SPARK_RUN_TIMEOUT"))
            except queue.Empty:
                sc.cancelJobGroup(job_group)
                raise TimeoutError(
                    f"Spark job started but produced no result within "
                    f"HVDT_SPARK_RUN_TIMEOUT; cancelled job group "
                    f"{job_group}.")
    finally:
        server.stop()
    if status == "err":
        raise payload
    by_rank = dict(payload)
    missing = [r for r in range(num_proc) if r not in by_rank]
    if missing:
        raise RuntimeError(f"Spark run returned no result for ranks "
                           f"{missing}")
    return [by_rank[r] for r in range(num_proc)]
