"""Step-pipeline layer: buffer donation + persistent compilation cache.

Two cheap, always-correct levers that BENCH_r05 showed the framework was
leaving on the table:

* **Donation** — a train step is a pipeline ``(params, opt_state) ->
  (params, opt_state)``; without ``donate_argnums`` XLA double-buffers
  every parameter and optimizer-state array (2x the largest HBM
  residents) and inserts defensive copies between steps.
  :func:`donated_step` is ``jax.jit`` with the params/opt-state
  positions donated by default — the call-shape every train step in
  bench.py and examples/ uses.

* **Persistent compilation cache** — the measured bench run pays
  ~15.8 s compile + ~14.7 s warmup on EVERY invocation for a program
  that hasn't changed.  :func:`enable_compilation_cache` points JAX's
  persistent cache (``jax.config jax_compilation_cache_dir``) at a
  directory so the second run of the same program skips XLA entirely.
  Engagement is env-transparent via the ``HVDT_COMPILATION_CACHE`` knob
  (set by ``bench.py``, forwardable by ``hvdtrun
  --compilation-cache-dir``, engaged for workers inside ``hvd.init()``).

Both are library-level conveniences: hand-rolled ``jax.jit(...,
donate_argnums=...)`` remains first-class everywhere.
"""

from __future__ import annotations

import os
from typing import Optional

from .common import config
from .common.logging_util import get_logger

__all__ = ["enable_compilation_cache", "donated_step"]

log = get_logger(__name__)

_DISABLED = ("", "0", "off", "none", "false")
_engaged: Optional[str] = None


def enable_compilation_cache(path: Optional[str] = None, *,
                             min_compile_secs: Optional[float] = None
                             ) -> Optional[str]:
    """Engage JAX's persistent XLA compilation cache.

    ``path`` defaults to the ``HVDT_COMPILATION_CACHE`` knob; empty /
    "off" means disabled and the call is a no-op returning None.
    ``min_compile_secs`` (default: the
    ``HVDT_COMPILATION_CACHE_MIN_COMPILE_SECS`` knob) filters out
    trivially cheap compilations so the cache holds the ~15 s train
    steps, not every 10 ms helper jit.  Idempotent; returns the engaged
    directory.  Never raises — an unwritable cache dir degrades to a
    warning, not a failed run.
    """
    global _engaged

    if path is None:
        path = config.get_str("HVDT_COMPILATION_CACHE")
    if path is None or str(path).strip().lower() in _DISABLED:
        return _engaged
    path = os.path.abspath(os.path.expanduser(str(path)))
    if _engaged == path:
        return _engaged
    if min_compile_secs is None:
        min_compile_secs = config.get_float(
            "HVDT_COMPILATION_CACHE_MIN_COMPILE_SECS")
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        # Cache small entries too: the knob above is the only filter a
        # user asked for.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _engaged = path
        log.info("persistent compilation cache at %s (min compile %.2fs)",
                 path, float(min_compile_secs))
    except Exception as e:     # cache must never sink a training run
        log.warning("compilation cache not engaged at %s: %r", path, e)
    return _engaged


def donated_step(fn, *, donate_argnums=(0, 1), compile_cache=None,
                 **jit_kwargs):
    """``jax.jit`` for train steps: donates the carried state buffers
    (``(params, opt_state)`` by default — pass ``donate_argnums`` for
    other call shapes, e.g. ``(0, 1, 2)`` with batch stats) and engages
    the persistent compilation cache (env-transparent: no-op unless the
    knob or ``compile_cache`` names a directory).

    Returns the jitted callable unchanged otherwise — ``.lower()``,
    static args, shard_map bodies all work as with plain ``jax.jit``.
    With telemetry on (``HVDT_TELEMETRY=1``) the callable is wrapped so
    each call's dispatch duration feeds ``hvdt_step_dispatch_seconds``;
    with distributed tracing on (``HVDT_TRACE_DIR``) the same wrapper
    records a ``train.step`` span and advances the deterministic
    per-step trace id (telemetry/trace.py).  Attribute access still
    forwards to the jitted fn; with both off the jitted fn itself is
    returned — zero wrapper objects.
    """
    import jax

    from .telemetry.instrument import wrap_step

    enable_compilation_cache(compile_cache)
    return wrap_step(jax.jit(fn, donate_argnums=donate_argnums,
                             **jit_kwargs))
