"""Step-pipeline layer: buffer donation + persistent compilation cache.

Two cheap, always-correct levers that BENCH_r05 showed the framework was
leaving on the table:

* **Donation** — a train step is a pipeline ``(params, opt_state) ->
  (params, opt_state)``; without ``donate_argnums`` XLA double-buffers
  every parameter and optimizer-state array (2x the largest HBM
  residents) and inserts defensive copies between steps.
  :func:`donated_step` is ``jax.jit`` with the params/opt-state
  positions donated by default — the call-shape every train step in
  bench.py and examples/ uses.

* **Persistent compilation cache** — the measured bench run pays
  ~15.8 s compile + ~14.7 s warmup on EVERY invocation for a program
  that hasn't changed.  :func:`enable_compilation_cache` points JAX's
  persistent cache (``jax.config jax_compilation_cache_dir``) at a
  directory so the second run of the same program skips XLA entirely.
  Engagement is env-transparent via the ``HVDT_COMPILATION_CACHE`` knob
  (set by ``bench.py``, forwardable by ``hvdtrun
  --compilation-cache-dir``, engaged for workers inside ``hvd.init()``).

Both are library-level conveniences: hand-rolled ``jax.jit(...,
donate_argnums=...)`` remains first-class everywhere.
"""

from __future__ import annotations

import os
from typing import Optional

from .common import config
from .common.logging_util import get_logger

__all__ = ["enable_compilation_cache", "donated_step", "overlap_step"]

log = get_logger(__name__)

_DISABLED = ("", "0", "off", "none", "false")
_engaged: Optional[str] = None


def enable_compilation_cache(path: Optional[str] = None, *,
                             min_compile_secs: Optional[float] = None
                             ) -> Optional[str]:
    """Engage JAX's persistent XLA compilation cache.

    ``path`` defaults to the ``HVDT_COMPILATION_CACHE`` knob; empty /
    "off" means disabled and the call is a no-op returning None.
    ``min_compile_secs`` (default: the
    ``HVDT_COMPILATION_CACHE_MIN_COMPILE_SECS`` knob) filters out
    trivially cheap compilations so the cache holds the ~15 s train
    steps, not every 10 ms helper jit.  Idempotent; returns the engaged
    directory.  Never raises — an unwritable cache dir degrades to a
    warning, not a failed run.
    """
    global _engaged

    if path is None:
        path = config.get_str("HVDT_COMPILATION_CACHE")
    if path is None or str(path).strip().lower() in _DISABLED:
        return _engaged
    path = os.path.abspath(os.path.expanduser(str(path)))
    if _engaged == path:
        return _engaged
    if min_compile_secs is None:
        min_compile_secs = config.get_float(
            "HVDT_COMPILATION_CACHE_MIN_COMPILE_SECS")
    try:
        import jax

        os.makedirs(path, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", path)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          float(min_compile_secs))
        # Cache small entries too: the knob above is the only filter a
        # user asked for.
        jax.config.update("jax_persistent_cache_min_entry_size_bytes", -1)
        _engaged = path
        log.info("persistent compilation cache at %s (min compile %.2fs)",
                 path, float(min_compile_secs))
    except Exception as e:     # cache must never sink a training run
        log.warning("compilation cache not engaged at %s: %r", path, e)
    return _engaged


def donated_step(fn, *, donate_argnums=(0, 1), compile_cache=None,
                 **jit_kwargs):
    """``jax.jit`` for train steps: donates the carried state buffers
    (``(params, opt_state)`` by default — pass ``donate_argnums`` for
    other call shapes, e.g. ``(0, 1, 2)`` with batch stats) and engages
    the persistent compilation cache (env-transparent: no-op unless the
    knob or ``compile_cache`` names a directory).

    Returns the jitted callable unchanged otherwise — ``.lower()``,
    static args, shard_map bodies all work as with plain ``jax.jit``.
    With telemetry on (``HVDT_TELEMETRY=1``) the callable is wrapped so
    each call's dispatch duration feeds ``hvdt_step_dispatch_seconds``;
    with distributed tracing on (``HVDT_TRACE_DIR``) the same wrapper
    records a ``train.step`` span and advances the deterministic
    per-step trace id (telemetry/trace.py).  Attribute access still
    forwards to the jitted fn; with both off the jitted fn itself is
    returned — zero wrapper objects.
    """
    import jax

    from .telemetry.instrument import wrap_step

    enable_compilation_cache(compile_cache)
    return wrap_step(jax.jit(fn, donate_argnums=donate_argnums,
                             **jit_kwargs))


class _OverlapStep:
    """The :func:`overlap_step` handle: calls forward to the (donated,
    cache-engaged) jitted step; :meth:`run` drives a whole batch stream
    with double-buffered host→device input."""

    __slots__ = ("_fn", "_prefetch", "_sharding", "_put")

    def __init__(self, fn, prefetch: int, sharding, put):
        self._fn = fn
        self._prefetch = prefetch
        self._sharding = sharding
        self._put = put

    def __call__(self, *args, **kwargs):
        return self._fn(*args, **kwargs)

    def __getattr__(self, name):
        return getattr(self._fn, name)

    def run(self, state, batches):
        """Drive the step over ``batches`` with ``prefetch_size`` device
        batches in flight: batch N+1's h2d transfer (sharding-aware,
        data/loader.prefetch_to_device) rides under step N's compute.

        ``state`` is the tuple of donated leading arguments (e.g.
        ``(params, opt_state)``); each batch is appended as trailing
        argument(s) — a tuple/list batch is splatted.  The step must
        return the next state tuple.  Returns the final state; the
        prefetch generator is closed (queued device buffers dropped)
        even when the loop exits early via an exception.
        """
        from .data.loader import prefetch_to_device

        state = tuple(state)
        it = prefetch_to_device(batches, size=self._prefetch,
                                sharding=self._sharding, put=self._put)
        try:
            for batch in it:
                args = (tuple(batch) if isinstance(batch, (tuple, list))
                        else (batch,))
                out = self._fn(*state, *args)
                state = out if isinstance(out, tuple) else (out,)
        finally:
            it.close()
        return state


def overlap_step(fn, *, donate_argnums=(0, 1), prefetch_size: int = 2,
                 sharding=None, put=None, compile_cache=None,
                 **jit_kwargs) -> _OverlapStep:
    """:func:`donated_step` plus double-buffered host→device input — the
    input half of the overlap scheduling layer (ops/overlap.py is the
    collective half).

    Returns an :class:`_OverlapStep`: call it exactly like the jitted
    step (``.lower()``, attributes, static args all forward), or use
    ``.run(state, batches)`` to drive a whole stream with batch N+1's
    transfer riding under step N.  ``sharding`` may be a single Sharding
    or a pytree of shardings matching each batch (per-leaf placement);
    ``put`` overrides the transfer fn entirely.
    """
    if prefetch_size < 1:
        raise ValueError(
            f"overlap_step needs prefetch_size >= 1 (got {prefetch_size})")
    step = donated_step(fn, donate_argnums=donate_argnums,
                        compile_cache=compile_cache, **jit_kwargs)
    return _OverlapStep(step, prefetch_size, sharding, put)
