"""Device-mesh construction for multi-axis parallelism.

The TPU-native analog of the reference's rank layout machinery
(ref: runner/common/util/hosts.py:get_host_assignments SlotInfo{rank,
local_rank, cross_rank} — SURVEY.md §2.5): where the reference assigns one
process per GPU and splits communicators by node, we lay devices out on an
N-dimensional ``jax.sharding.Mesh`` whose axes name the parallelism kinds.

Axis order convention follows the scaling playbook: outermost axes change
slowest across the physical topology, so put the bandwidth-hungry axes
(``tp``, ``sp``) innermost where neighboring devices share the fastest ICI
links, and the latency-tolerant axes (``dp``, ``pp``) outermost where hops
may cross DCN.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_PP = "pp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"

# Outer-to-inner canonical ordering (latency-tolerant → bandwidth-hungry).
CANONICAL_AXES: Tuple[str, ...] = (
    AXIS_DP, AXIS_PP, AXIS_FSDP, AXIS_EP, AXIS_SP, AXIS_TP)

# Transport classes: which physical interconnect tier a mesh axis rides.
# Innermost axes step between ICI neighbours (within a slice); every axis
# outside the innermost tier is presumed to hop DCN (across slices/pods).
# The transport-policy layer (horovod_tpu/transport) keys per-axis
# algorithm/wire/threshold choices on these classes.
TRANSPORT_ICI = "ici"
TRANSPORT_DCN = "dcn"
TRANSPORT_CLASSES: Tuple[str, ...] = (TRANSPORT_ICI, TRANSPORT_DCN)

__all__ = [
    "AXIS_DP", "AXIS_FSDP", "AXIS_PP", "AXIS_TP", "AXIS_SP", "AXIS_EP",
    "CANONICAL_AXES", "TRANSPORT_ICI", "TRANSPORT_DCN",
    "TRANSPORT_CLASSES", "axis_transport_class", "split_transport_axes",
    "MeshSpec", "make_mesh", "mesh_shape_for", "pod_mesh_spec",
    "pod_axis_tiers",
]


def axis_transport_class(axis: str, axes: Sequence[str]) -> str:
    """Transport tier of ``axis`` within the ordered reduce group ``axes``.

    Axes follow the mesh convention (outermost first, innermost last —
    see the module docstring): the innermost axis of a multi-axis group
    rides ICI (neighbouring devices share the fastest links), every
    outer axis is presumed to cross DCN.  A single-axis group is one ICI
    domain.  This is the default classification the transport-policy
    layer's ``ici``/``dcn`` entries key on; exact mesh-axis names
    override it.
    """
    axes = tuple(axes)
    if axis not in axes:
        raise ValueError(f"axis {axis!r} not in reduce group {axes}")
    if len(axes) == 1 or axis == axes[-1]:
        return TRANSPORT_ICI
    return TRANSPORT_DCN


def split_transport_axes(axes: Sequence[str], fast_width: int = 1
                         ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """Split an ordered reduce group into ``(slow_axes, fast_axes)``.

    ``fast_axes`` are the ``fast_width`` innermost (ICI) axes — the tier
    the hierarchical allreduce reduce-scatters over; ``slow_axes`` is
    everything outside it (the DCN tier the shard exchange crosses).  At
    least one axis always stays slow when the group has more than one
    axis, so a two-level schedule exists whenever one is possible.
    """
    axes = tuple(axes)
    if not axes:
        raise ValueError("empty reduce group")
    width = max(1, min(int(fast_width), len(axes) - 1 or 1))
    return axes[:-width], axes[-width:]


def pod_mesh_spec(num_pods: Optional[int] = None,
                  pod_size: Optional[int] = None,
                  *,
                  pp: Optional[int] = None,
                  ep: Optional[int] = None) -> "MeshSpec":
    """The data-parallel mesh of the elastic pod contract — axes
    ``("dcn", "ici")`` sized ``(num_pods, pod_size)`` — optionally
    extended to the 4D layout with pipeline/expert degrees.

    Defaults come from the pod-aware launcher's worker env
    (``HVDT_NUM_PODS`` / ``HVDT_POD_SIZE``, runner/hosts.SlotInfo.to_env
    — republished per generation at ``/rendezvous/<gen>/pods``), so a
    worker rebuilds the right hierarchy after every pod-granular resize.
    The axis NAMES are the transport classes: ``split_transport_axes``
    puts ``ici`` in the fast tier and ``dcn`` in the slow one, and the
    PR-8 policy grammar matches them directly — cross-pod gradient
    exchange rides the ``dcn`` policy (int8 + error feedback under
    ``HVDT_TRANSPORT=...,dcn:tree:8M``) with no extra wiring.

    4D extension (``pp``/``ep``, default the ``HVDT_PP``/``HVDT_EP``
    env): pipeline stages are latency-tolerant point-to-point hops, so
    ``pp`` carves pod GROUPS out of the DCN tier (``pp`` must divide
    ``num_pods``); expert alltoall is bandwidth-hungry, so ``ep``
    carves chips out of the ICI tier inside each pod (``ep`` must
    divide ``pod_size``).  The resulting axis order
    ``(pp, dcn, ici, ep)`` keeps the data-parallel reduce group at
    ``("dcn", "ici")`` — ZeRO shards and gradient hierarchies are
    unchanged — and :func:`pod_axis_tiers` names each axis's physical
    tier for pricing and policy defaults.
    """
    import os

    if num_pods is None:
        num_pods = int(os.environ.get("HVDT_NUM_PODS", "1") or 1)
    if pod_size is None:
        pod_size = int(os.environ.get("HVDT_POD_SIZE", "0") or 0)
        if pod_size <= 0:
            pod_size = int(os.environ.get("HVDT_SIZE", "1") or 1) \
                // max(1, num_pods)
    if pp is None:
        pp = int(os.environ.get("HVDT_PP", "1") or 1)
    if ep is None:
        ep = int(os.environ.get("HVDT_EP", "1") or 1)
    if num_pods < 1 or pod_size < 1:
        raise ValueError(
            f"pod mesh needs num_pods >= 1 and pod_size >= 1, got "
            f"({num_pods}, {pod_size})")
    if pp < 1 or ep < 1:
        raise ValueError(f"pp and ep must be >= 1, got ({pp}, {ep})")
    if pp == 1 and ep == 1:
        return MeshSpec(axes=((TRANSPORT_DCN, int(num_pods)),
                              (TRANSPORT_ICI, int(pod_size))))
    if num_pods % pp:
        raise ValueError(
            f"pipeline degree pp={pp} must divide num_pods={num_pods} "
            "(stages are pod groups on the DCN tier)")
    if pod_size % ep:
        raise ValueError(
            f"expert degree ep={ep} must divide pod_size={pod_size} "
            "(experts share a pod's ICI tier)")
    axes: List[Tuple[str, int]] = []
    if pp > 1:
        axes.append((AXIS_PP, int(pp)))
    axes.append((TRANSPORT_DCN, int(num_pods // pp)))
    axes.append((TRANSPORT_ICI, int(pod_size // ep)))
    if ep > 1:
        axes.append((AXIS_EP, int(ep)))
    return MeshSpec(axes=tuple(axes))


def pod_axis_tiers(spec: "MeshSpec") -> Dict[str, str]:
    """Physical tier of each axis in a pod-contract mesh spec.

    Axes at or outside the ``dcn`` axis cross pod boundaries (``pp``
    hops ride DCN); axes at or inside the ``ici`` axis stay within a
    pod (``ep`` alltoall rides ICI).  Cost pricing and transport-policy
    class defaults consult this instead of guessing from reduce-group
    position — a single-axis ``pp`` group would otherwise classify as
    ICI under :func:`axis_transport_class`'s innermost-is-fast rule.
    """
    names = spec.names
    boundary = names.index(TRANSPORT_ICI) if TRANSPORT_ICI in names \
        else len(names) - 1
    return {name: (TRANSPORT_ICI if i >= boundary else TRANSPORT_DCN)
            for i, name in enumerate(names)}


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A validated mesh layout: ordered (axis, size) pairs.

    ``MeshSpec.create(dp=2, tp=4)`` fills unspecified axes with size 1 and
    orders axes canonically; total size must divide the device count.
    """

    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def create(cls, *, devices_total: Optional[int] = None,
               **sizes: int) -> "MeshSpec":
        for name, n in sizes.items():
            if n < 1:
                raise ValueError(f"axis {name!r} must have size >= 1, got {n}")
        ordered: List[Tuple[str, int]] = []
        for name in CANONICAL_AXES:
            if name in sizes:
                ordered.append((name, sizes.pop(name)))
        # Unknown (user-defined) axes go last, in given order.
        for name, n in sizes.items():
            ordered.append((name, n))
        spec = cls(tuple(ordered))
        if devices_total is not None:
            want = spec.total
            if want > devices_total or devices_total % want:
                raise ValueError(
                    f"mesh spec {spec.shape} (total {want}) does not divide "
                    f"{devices_total} devices")
        return spec

    @property
    def shape(self) -> Dict[str, int]:
        return dict(self.axes)

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.axes)

    @property
    def total(self) -> int:
        return math.prod(n for _, n in self.axes)


def mesh_shape_for(n_devices: int,
                   *,
                   tp: int = 1,
                   pp: int = 1,
                   sp: int = 1,
                   ep: int = 1,
                   fsdp: int = 1) -> MeshSpec:
    """Fill the ``dp`` axis with whatever devices remain after the model axes.

    The default-layout helper: give it the model-parallel degrees and it
    derives data parallelism, mirroring how ``horovodrun -np N`` derives the
    world size from host slots (ref: runner/launch.py, hosts.py).
    """
    model = tp * pp * sp * ep * fsdp
    if n_devices % model:
        raise ValueError(
            f"model-parallel degree {model} (tp={tp} pp={pp} sp={sp} ep={ep} "
            f"fsdp={fsdp}) does not divide {n_devices} devices")
    return MeshSpec.create(dp=n_devices // model, pp=pp, fsdp=fsdp,
                           ep=ep, sp=sp, tp=tp)


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None,
              *,
              explicit_sharding: bool = False,
              **sizes: int):
    """Build a ``jax.sharding.Mesh`` from a spec or axis sizes.

    ``make_mesh(dp=2, tp=4)`` → Mesh over the first 8 devices with axes
    ("dp", "tp") in canonical order.  Uses ``jax.make_mesh`` when laying out
    over all real devices so XLA can pick a topology-aware device order;
    falls back to reshaping an explicit device list otherwise.
    """
    import jax
    from jax.sharding import Mesh

    if spec is None:
        spec = MeshSpec.create(**sizes)
    elif sizes:
        raise TypeError("pass either spec= or axis sizes, not both")
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if spec.total > len(devices):
        raise ValueError(
            f"mesh {spec.shape} needs {spec.total} devices, "
            f"have {len(devices)}")
    shape = tuple(n for _, n in spec.axes)
    # Auto axes = classic GSPMD propagation: plain model code works and the
    # partitioner inserts collectives.  Explicit (sharding-in-types) mode is
    # opt-in for users who want shardings checked in the type system.
    # jax <= 0.4.x has no AxisType (every mesh axis is Auto-equivalent):
    # degrade to a plain Mesh there — explicit_sharding needs the type
    # system and cannot be honoured, so it raises rather than silently
    # weakening the user's contract.
    try:
        from jax.sharding import AxisType
    except ImportError:
        AxisType = None
    if AxisType is None:
        if explicit_sharding:
            raise NotImplementedError(
                "explicit_sharding=True needs jax.sharding.AxisType "
                "(sharding-in-types); this JAX build predates it")
        if len(devices) == spec.total and devices == list(jax.devices()):
            return jax.make_mesh(shape, spec.names)
        used = np.asarray(devices[: spec.total], dtype=object).reshape(shape)
        return Mesh(used, spec.names)
    kind = AxisType.Explicit if explicit_sharding else AxisType.Auto
    axis_types = (kind,) * len(shape)
    if len(devices) == spec.total and devices == list(jax.devices()):
        # Topology-aware layout for the full device set.
        return jax.make_mesh(shape, spec.names, axis_types=axis_types)
    used = np.asarray(devices[: spec.total], dtype=object).reshape(shape)
    return Mesh(used, spec.names, axis_types=axis_types)
