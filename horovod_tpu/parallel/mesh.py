"""Device-mesh construction for multi-axis parallelism.

The TPU-native analog of the reference's rank layout machinery
(ref: runner/common/util/hosts.py:get_host_assignments SlotInfo{rank,
local_rank, cross_rank} — SURVEY.md §2.5): where the reference assigns one
process per GPU and splits communicators by node, we lay devices out on an
N-dimensional ``jax.sharding.Mesh`` whose axes name the parallelism kinds.

Axis order convention follows the scaling playbook: outermost axes change
slowest across the physical topology, so put the bandwidth-hungry axes
(``tp``, ``sp``) innermost where neighboring devices share the fastest ICI
links, and the latency-tolerant axes (``dp``, ``pp``) outermost where hops
may cross DCN.
"""

from __future__ import annotations

import dataclasses
import math
from typing import Dict, List, Mapping, Optional, Sequence, Tuple

import numpy as np

AXIS_DP = "dp"
AXIS_FSDP = "fsdp"
AXIS_PP = "pp"
AXIS_TP = "tp"
AXIS_SP = "sp"
AXIS_EP = "ep"

# Outer-to-inner canonical ordering (latency-tolerant → bandwidth-hungry).
CANONICAL_AXES: Tuple[str, ...] = (
    AXIS_DP, AXIS_PP, AXIS_FSDP, AXIS_EP, AXIS_SP, AXIS_TP)

# Transport classes: which physical interconnect tier a mesh axis rides.
# Innermost axes step between ICI neighbours (within a slice); every axis
# outside the innermost tier is presumed to hop DCN (across slices/pods).
# The transport-policy layer (horovod_tpu/transport) keys per-axis
# algorithm/wire/threshold choices on these classes.
TRANSPORT_ICI = "ici"
TRANSPORT_DCN = "dcn"
TRANSPORT_CLASSES: Tuple[str, ...] = (TRANSPORT_ICI, TRANSPORT_DCN)

__all__ = [
    "AXIS_DP", "AXIS_FSDP", "AXIS_PP", "AXIS_TP", "AXIS_SP", "AXIS_EP",
    "CANONICAL_AXES", "TRANSPORT_ICI", "TRANSPORT_DCN",
    "TRANSPORT_CLASSES", "axis_transport_class", "split_transport_axes",
    "MeshSpec", "make_mesh", "mesh_shape_for", "pod_mesh_spec",
]


def axis_transport_class(axis: str, axes: Sequence[str]) -> str:
    """Transport tier of ``axis`` within the ordered reduce group ``axes``.

    Axes follow the mesh convention (outermost first, innermost last —
    see the module docstring): the innermost axis of a multi-axis group
    rides ICI (neighbouring devices share the fastest links), every
    outer axis is presumed to cross DCN.  A single-axis group is one ICI
    domain.  This is the default classification the transport-policy
    layer's ``ici``/``dcn`` entries key on; exact mesh-axis names
    override it.
    """
    axes = tuple(axes)
    if axis not in axes:
        raise ValueError(f"axis {axis!r} not in reduce group {axes}")
    if len(axes) == 1 or axis == axes[-1]:
        return TRANSPORT_ICI
    return TRANSPORT_DCN


def split_transport_axes(axes: Sequence[str], fast_width: int = 1
                         ) -> Tuple[Tuple[str, ...], Tuple[str, ...]]:
    """Split an ordered reduce group into ``(slow_axes, fast_axes)``.

    ``fast_axes`` are the ``fast_width`` innermost (ICI) axes — the tier
    the hierarchical allreduce reduce-scatters over; ``slow_axes`` is
    everything outside it (the DCN tier the shard exchange crosses).  At
    least one axis always stays slow when the group has more than one
    axis, so a two-level schedule exists whenever one is possible.
    """
    axes = tuple(axes)
    if not axes:
        raise ValueError("empty reduce group")
    width = max(1, min(int(fast_width), len(axes) - 1 or 1))
    return axes[:-width], axes[-width:]


def pod_mesh_spec(num_pods: Optional[int] = None,
                  pod_size: Optional[int] = None) -> "MeshSpec":
    """The two-level data-parallel mesh of the elastic pod contract:
    axes ``("dcn", "ici")`` sized ``(num_pods, pod_size)``.

    Defaults come from the pod-aware launcher's worker env
    (``HVDT_NUM_PODS`` / ``HVDT_POD_SIZE``, runner/hosts.SlotInfo.to_env
    — republished per generation at ``/rendezvous/<gen>/pods``), so a
    worker rebuilds the right hierarchy after every pod-granular resize.
    The axis NAMES are the transport classes: ``split_transport_axes``
    puts ``ici`` in the fast tier and ``dcn`` in the slow one, and the
    PR-8 policy grammar matches them directly — cross-pod gradient
    exchange rides the ``dcn`` policy (int8 + error feedback under
    ``HVDT_TRANSPORT=...,dcn:tree:int8:8M``) with no extra wiring.
    """
    import os

    if num_pods is None:
        num_pods = int(os.environ.get("HVDT_NUM_PODS", "1") or 1)
    if pod_size is None:
        pod_size = int(os.environ.get("HVDT_POD_SIZE", "0") or 0)
        if pod_size <= 0:
            pod_size = int(os.environ.get("HVDT_SIZE", "1") or 1) \
                // max(1, num_pods)
    if num_pods < 1 or pod_size < 1:
        raise ValueError(
            f"pod mesh needs num_pods >= 1 and pod_size >= 1, got "
            f"({num_pods}, {pod_size})")
    return MeshSpec(axes=((TRANSPORT_DCN, int(num_pods)),
                          (TRANSPORT_ICI, int(pod_size))))


@dataclasses.dataclass(frozen=True)
class MeshSpec:
    """A validated mesh layout: ordered (axis, size) pairs.

    ``MeshSpec.create(dp=2, tp=4)`` fills unspecified axes with size 1 and
    orders axes canonically; total size must divide the device count.
    """

    axes: Tuple[Tuple[str, int], ...]

    @classmethod
    def create(cls, *, devices_total: Optional[int] = None,
               **sizes: int) -> "MeshSpec":
        for name, n in sizes.items():
            if n < 1:
                raise ValueError(f"axis {name!r} must have size >= 1, got {n}")
        ordered: List[Tuple[str, int]] = []
        for name in CANONICAL_AXES:
            if name in sizes:
                ordered.append((name, sizes.pop(name)))
        # Unknown (user-defined) axes go last, in given order.
        for name, n in sizes.items():
            ordered.append((name, n))
        spec = cls(tuple(ordered))
        if devices_total is not None:
            want = spec.total
            if want > devices_total or devices_total % want:
                raise ValueError(
                    f"mesh spec {spec.shape} (total {want}) does not divide "
                    f"{devices_total} devices")
        return spec

    @property
    def shape(self) -> Dict[str, int]:
        return dict(self.axes)

    @property
    def names(self) -> Tuple[str, ...]:
        return tuple(n for n, _ in self.axes)

    @property
    def total(self) -> int:
        return math.prod(n for _, n in self.axes)


def mesh_shape_for(n_devices: int,
                   *,
                   tp: int = 1,
                   pp: int = 1,
                   sp: int = 1,
                   ep: int = 1,
                   fsdp: int = 1) -> MeshSpec:
    """Fill the ``dp`` axis with whatever devices remain after the model axes.

    The default-layout helper: give it the model-parallel degrees and it
    derives data parallelism, mirroring how ``horovodrun -np N`` derives the
    world size from host slots (ref: runner/launch.py, hosts.py).
    """
    model = tp * pp * sp * ep * fsdp
    if n_devices % model:
        raise ValueError(
            f"model-parallel degree {model} (tp={tp} pp={pp} sp={sp} ep={ep} "
            f"fsdp={fsdp}) does not divide {n_devices} devices")
    return MeshSpec.create(dp=n_devices // model, pp=pp, fsdp=fsdp,
                           ep=ep, sp=sp, tp=tp)


def make_mesh(spec: Optional[MeshSpec] = None,
              devices: Optional[Sequence] = None,
              *,
              explicit_sharding: bool = False,
              **sizes: int):
    """Build a ``jax.sharding.Mesh`` from a spec or axis sizes.

    ``make_mesh(dp=2, tp=4)`` → Mesh over the first 8 devices with axes
    ("dp", "tp") in canonical order.  Uses ``jax.make_mesh`` when laying out
    over all real devices so XLA can pick a topology-aware device order;
    falls back to reshaping an explicit device list otherwise.
    """
    import jax
    from jax.sharding import Mesh

    if spec is None:
        spec = MeshSpec.create(**sizes)
    elif sizes:
        raise TypeError("pass either spec= or axis sizes, not both")
    if devices is None:
        devices = jax.devices()
    devices = list(devices)
    if spec.total > len(devices):
        raise ValueError(
            f"mesh {spec.shape} needs {spec.total} devices, "
            f"have {len(devices)}")
    shape = tuple(n for _, n in spec.axes)
    # Auto axes = classic GSPMD propagation: plain model code works and the
    # partitioner inserts collectives.  Explicit (sharding-in-types) mode is
    # opt-in for users who want shardings checked in the type system.
    from jax.sharding import AxisType

    kind = AxisType.Explicit if explicit_sharding else AxisType.Auto
    axis_types = (kind,) * len(shape)
    if len(devices) == spec.total and devices == list(jax.devices()):
        # Topology-aware layout for the full device set.
        return jax.make_mesh(shape, spec.names, axis_types=axis_types)
    used = np.asarray(devices[: spec.total], dtype=object).reshape(shape)
    return Mesh(used, spec.names, axis_types=axis_types)
