"""Parallelism substrate: mesh axes, sharding rules, SP/PP/EP building blocks.

This subpackage is the capability the reference framework lacks but whose
substrate SURVEY.md §2.7/§5.7 requires the TPU build to provide: tensor,
pipeline, sequence/context (ring attention), and expert parallelism expressed
natively over a ``jax.sharding.Mesh`` with XLA collectives — instead of the
reference's answer of "more data-parallel replicas + better allreduce"
(ref: common/process_set.{h,cc} process sets and the raw alltoall primitive,
operations.cc:1642, are the closest the reference gets).

Canonical axis names (any subset may be present in a mesh, size-1 axes are
free):

* ``dp`` — data parallel (gradient allreduce; the reference's whole world)
* ``fsdp`` — fully-sharded data parallel (param/grad reduce-scatter +
  all-gather; the ZeRO-style axis SURVEY.md §2.7 lists as absent upstream)
* ``pp`` — pipeline stages (microbatch circulation over ``ppermute``)
* ``tp`` — tensor (Megatron-style) parallel within a layer
* ``sp`` — sequence/context parallel (ring attention)
* ``ep`` — expert parallel (MoE alltoall token routing)
"""

from .mesh import (  # noqa: F401
    AXIS_DP,
    AXIS_FSDP,
    AXIS_PP,
    AXIS_TP,
    AXIS_SP,
    AXIS_EP,
    CANONICAL_AXES,
    MeshSpec,
    make_mesh,
    mesh_shape_for,
    pod_axis_tiers,
    pod_mesh_spec,
)
from .sharding import (  # noqa: F401
    batch_spec,
    logical_to_mesh,
    named_sharding,
    pcast_to_union,
    transformer_rules,
)
from .ring_attention import ring_attention  # noqa: F401
from .pipeline import (  # noqa: F401
    bubble_fraction,
    pipeline_1f1b,
    pipeline_spmd,
    report_pipeline_mfu,
)
from .moe import (  # noqa: F401
    MoEAux,
    moe_capacity,
    moe_dispatch_combine,
    report_moe_aux,
)
