"""Expert parallelism: switch-style top-1 MoE with alltoall token routing.

The reference exposes the raw alltoall primitive that makes user-level MoE
possible (ref: operations.cc:1642-1725, ops/collective_operations.h:195
AlltoallOp) but ships no EP layer (SURVEY.md §2.7).  Here the full dispatch
→ expert → combine path is provided, TPU-style: static capacity (no dynamic
shapes for XLA), ``lax.all_to_all`` over the ``ep`` mesh axis riding ICI.
"""

from __future__ import annotations

from typing import Any, Callable, NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["moe_dispatch_combine", "MoEAux"]


class MoEAux(NamedTuple):
    load_balance_loss: jax.Array   # switch-transformer aux loss (scalar)
    dropped_fraction: jax.Array    # fraction of tokens over capacity (scalar)


def moe_dispatch_combine(tokens: jax.Array,
                         router_logits: jax.Array,
                         expert_fn: Callable[[jax.Array], jax.Array],
                         *,
                         axis: str = "ep",
                         experts_per_rank: int = 1,
                         capacity_factor: float = 1.25) -> Tuple[jax.Array, MoEAux]:
    """Route each token to its top-1 expert across the ``ep`` axis.

    Must run inside shard_map with ``axis`` bound.  Tokens over a full
    expert's capacity are dropped (residual passthrough — standard switch
    behavior).

    Args:
      tokens: local tokens ``[T, D]``.
      router_logits: ``[T, E]`` where ``E = ep_size * experts_per_rank``.
      expert_fn: vmapped-over-experts body ``[E_local, N, D] -> [E_local, N, D]``.
      capacity_factor: per-expert slots = ceil(T/E * factor).

    Returns (combined ``[T, D]``, MoEAux).
    """
    t, d = tokens.shape
    ep = _axis_size_static(axis)
    e_total = ep * experts_per_rank
    if router_logits.shape[-1] != e_total:
        raise ValueError(
            f"router logits last dim {router_logits.shape[-1]} != "
            f"ep*experts_per_rank = {e_total}")
    cap = max(1, int(-(-t * capacity_factor // e_total)))  # ceil

    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    expert = jnp.argmax(probs, axis=-1)                     # [T]
    gate = jnp.take_along_axis(probs, expert[:, None], axis=-1)[:, 0]

    one_hot = jax.nn.one_hot(expert, e_total, dtype=jnp.float32)   # [T, E]
    pos = (jnp.cumsum(one_hot, axis=0) - one_hot) * one_hot        # [T, E]
    pos_in_expert = pos.sum(-1).astype(jnp.int32)                  # [T]
    kept = pos_in_expert < cap

    # Scatter local tokens into [E, cap, D] dispatch slots.
    dispatch = jnp.zeros((e_total, cap, d), tokens.dtype)
    idx_e = jnp.where(kept, expert, 0)
    idx_c = jnp.where(kept, pos_in_expert, 0)
    weight = jnp.where(kept, 1.0, 0.0)
    dispatch = dispatch.at[idx_e, idx_c].add(
        tokens * weight[:, None].astype(tokens.dtype))

    # [E, cap, D] -> [ep, E_local, cap, D] -> alltoall over ep.
    dispatch = dispatch.reshape(ep, experts_per_rank, cap, d)
    recv = lax.all_to_all(dispatch, axis, split_axis=0, concat_axis=0,
                          tiled=False)                  # [ep(src), E_l, cap, D]
    # Fold source-rank dim into the capacity dim for the expert body.
    recv = recv.transpose(1, 0, 2, 3).reshape(experts_per_rank, ep * cap, d)
    processed = expert_fn(recv)
    processed = processed.reshape(experts_per_rank, ep, cap, d).transpose(
        1, 0, 2, 3)
    back = lax.all_to_all(processed, axis, split_axis=0, concat_axis=0,
                          tiled=False)                  # [ep, E_l, cap, D]
    back = back.reshape(e_total, cap, d)

    # Combine: gather each kept token's slot, weight by its gate.
    out = back[idx_e, idx_c] * (gate * weight).astype(tokens.dtype)[:, None]

    # Switch-transformer load-balancing loss: E * Σ_e f_e · P_e, where f is
    # the routed fraction and P the mean router prob — averaged globally.
    f = one_hot.mean(axis=0)
    p_mean = probs.mean(axis=0)
    f = lax.pmean(f, axis)
    p_mean = lax.pmean(p_mean, axis)
    aux = MoEAux(
        load_balance_loss=e_total * jnp.sum(f * p_mean),
        dropped_fraction=lax.pmean(1.0 - kept.mean(), axis))
    return out, aux
