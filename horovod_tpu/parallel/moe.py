"""Expert parallelism: capacity-factor top-k MoE with alltoall token routing.

The reference exposes the raw alltoall primitive that makes user-level MoE
possible (ref: operations.cc:1642-1725, ops/collective_operations.h:195
AlltoallOp) but ships no EP layer (SURVEY.md §2.7).  Here the full dispatch
→ expert → combine path is provided, TPU-style: static capacity (no dynamic
shapes for XLA), ``lax.all_to_all`` over the ``ep`` mesh axis.

The token exchange rides the transport-policy layer
(horovod_tpu/transport): an ``HVDT_TRANSPORT=ep:ring:int8:8M`` entry puts
the dispatch/combine payloads on the block-scaled int8 wire (quant/kernels
— real int8 bytes plus f32 block scales on the wire, f32 math on both
ends), exactly like the gradient allreduce's per-axis wire override.
Both alltoalls are booked against the trace-time telemetry and flight
recorder (ops/device.fused_allreduce idiom), so ``hvdt_collective_*``
series and desync forensics cover expert routing with no extra wiring.
"""

from __future__ import annotations

import os
from typing import Any, Callable, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.device import _axis_size_static

__all__ = ["moe_dispatch_combine", "MoEAux", "moe_capacity",
           "report_moe_aux"]


class MoEAux(NamedTuple):
    load_balance_loss: jax.Array   # switch-transformer aux loss (scalar)
    dropped_fraction: jax.Array    # fraction of tokens over capacity (scalar)


def _env_float(name: str, default: float) -> float:
    raw = os.environ.get(name, "")
    try:
        return float(raw) if raw else default
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    raw = os.environ.get(name, "")
    try:
        return int(raw) if raw else default
    except ValueError:
        return default


def moe_capacity(tokens_per_rank: int, num_experts: int, *,
                 top_k: int = 1, capacity_factor: float = 1.25) -> int:
    """Per-expert dispatch slots: ``ceil(T·k/E · factor)``, floor 1.

    The static-shape contract every tensor in the dispatch path is sized
    by (GShard's expert capacity) — XLA never sees a data-dependent
    shape; tokens beyond it are dropped (residual passthrough)."""
    want = tokens_per_rank * top_k * capacity_factor
    return max(1, int(-(-want // num_experts)))


def _a2a_transport(block: jax.Array, axis: str, name: str):
    """``lax.all_to_all`` over ``axis`` with the transport policy's wire.

    ``block`` is ``[ep, ...]`` (leading dim = axis size; slice i goes to
    rank i).  Resolves ``axis`` against ``HVDT_TRANSPORT`` exactly like
    the fused allreduce: an int8 wire sends block-scaled int8 payloads +
    f32 scales (two alltoalls, f32 restore on arrival); bf16/fp16 cast
    down for the flight; unset keeps the exact-dtype exchange.  Books
    the trace-time collective counters and one flight-recorder event
    per traced program."""
    from ..telemetry import flight_recorder as _frm
    from ..telemetry import instrument as _ti
    from ..transport import policy as _tpolicy

    _res = _tpolicy.resolve_axis(axis)
    wire = _res.fast.wire if _res is not None else None

    orig_dtype = block.dtype
    ep = block.shape[0]
    rest = int(block.size) // ep
    payload_bytes = int(block.size) * jnp.dtype(orig_dtype).itemsize
    wire_label = jnp.dtype(orig_dtype).name

    def _a2a(x):
        return lax.all_to_all(x, axis, split_axis=0, concat_axis=0,
                              tiled=False)

    int8_wire = (wire == "int8"
                 and jnp.issubdtype(orig_dtype, jnp.floating))
    cast_wire = (wire in ("bf16", "fp16")
                 and jnp.issubdtype(orig_dtype, jnp.floating))

    if int8_wire:
        from ..quant.kernels import (dequantize_flat, quant_block_size,
                                     quantize_flat)

        shape = block.shape
        bs = quant_block_size()
        pad = (-rest) % bs
        rows = block.reshape(ep, rest).astype(jnp.float32)
        if pad:
            rows = jnp.concatenate(
                [rows, jnp.zeros((ep, pad), jnp.float32)], axis=1)
        padded = rest + pad
        # Row boundaries align with block boundaries after padding, so
        # one flat quantize covers all rows.
        q, scales = quantize_flat(rows.reshape(-1), bs)
        wire_label = "int8_blockwise"
        payload_bytes = int(q.size) + int(scales.size) * 4
        with jax.named_scope(f"hvdt.moe_a2a.{name}"):
            recv_q = _a2a(q.reshape(ep, padded))
            recv_s = _a2a(scales.reshape(ep, padded // bs))
        out = dequantize_flat(recv_q.reshape(-1),
                              recv_s.reshape(-1), bs)
        out = out.reshape(ep, padded)
        if pad:
            out = out[:, :rest]
        result = out.reshape(shape).astype(orig_dtype)
    else:
        x = block
        if cast_wire:
            wdt = jnp.bfloat16 if wire == "bf16" else jnp.float16
            x = x.astype(wdt)
            wire_label = jnp.dtype(wdt).name
            payload_bytes = int(x.size) * jnp.dtype(wdt).itemsize
        with jax.named_scope(f"hvdt.moe_a2a.{name}"):
            result = _a2a(x)
        if result.dtype != orig_dtype:
            result = result.astype(orig_dtype)

    _rec = _ti.get_recorder()
    _flight = _frm.get_flight_recorder()
    if _rec is not None:
        _rec.record_collective(
            "alltoall", jnp.dtype(orig_dtype).name, wire_label,
            payload_bytes, count=1, path="jit", axis=axis)
    if _flight is not None:
        _flight.record(
            op="alltoall", name=name, dtype=jnp.dtype(orig_dtype).name,
            shape=tuple(int(s) for s in block.shape),
            nbytes=payload_bytes, wire=wire_label, path="jit",
            count=1, axis=axis)
    return result


def moe_dispatch_combine(tokens: jax.Array,
                         router_logits: jax.Array,
                         expert_fn: Callable[[jax.Array], jax.Array],
                         *,
                         axis: str = "ep",
                         experts_per_rank: int = 1,
                         capacity_factor: Optional[float] = None,
                         top_k: Optional[int] = None
                         ) -> Tuple[jax.Array, MoEAux]:
    """Route each token to its top-k experts across the ``ep`` axis.

    Must run inside shard_map with ``axis`` bound.  Tokens over a full
    expert's capacity are dropped (residual passthrough — standard switch
    behavior); primary (k=0) choices claim capacity before secondary
    ones, so overflow sheds the lowest-gate assignments first.

    Args:
      tokens: local tokens ``[T, D]``.
      router_logits: ``[T, E]`` where ``E = ep_size * experts_per_rank``.
      expert_fn: vmapped-over-experts body ``[E_local, N, D] -> [E_local, N, D]``.
      capacity_factor: per-expert slots = ceil(T·k/E · factor); defaults
        to ``HVDT_MOE_CAPACITY_FACTOR`` (1.25).
      top_k: experts per token, gates renormalized over the chosen k;
        defaults to ``HVDT_MOE_TOPK`` (1, switch routing).

    Returns (combined ``[T, D]``, MoEAux).
    """
    if capacity_factor is None:
        capacity_factor = _env_float("HVDT_MOE_CAPACITY_FACTOR", 1.25)
    if top_k is None:
        top_k = _env_int("HVDT_MOE_TOPK", 1)
    k = max(1, int(top_k))
    t, d = tokens.shape
    ep = _axis_size_static(axis)
    e_total = ep * experts_per_rank
    if router_logits.shape[-1] != e_total:
        raise ValueError(
            f"router logits last dim {router_logits.shape[-1]} != "
            f"ep*experts_per_rank = {e_total}")
    if k > e_total:
        raise ValueError(f"top_k={k} exceeds {e_total} experts")
    cap = moe_capacity(t, e_total, top_k=k,
                       capacity_factor=capacity_factor)

    probs = jax.nn.softmax(router_logits.astype(jnp.float32), axis=-1)
    top_vals, top_idx = lax.top_k(probs, k)                  # [T, K]
    gates = top_vals / jnp.maximum(
        top_vals.sum(-1, keepdims=True), 1e-9)               # [T, K]

    # Flatten choices k-major ([K*T]): row k*T + t is token t's k-th
    # choice, so the cumsum hands capacity to every primary assignment
    # before any secondary one.
    expert_f = top_idx.T.reshape(-1)                         # [K*T]
    gate_f = gates.T.reshape(-1)                             # [K*T]
    tokens_f = jnp.tile(tokens, (k, 1))                      # [K*T, D]

    one_hot = jax.nn.one_hot(expert_f, e_total, dtype=jnp.float32)
    pos = (jnp.cumsum(one_hot, axis=0) - one_hot) * one_hot  # [K*T, E]
    pos_in_expert = pos.sum(-1).astype(jnp.int32)            # [K*T]
    kept = pos_in_expert < cap

    # Scatter local tokens into [E, cap, D] dispatch slots.
    dispatch = jnp.zeros((e_total, cap, d), tokens.dtype)
    idx_e = jnp.where(kept, expert_f, 0)
    idx_c = jnp.where(kept, pos_in_expert, 0)
    weight = jnp.where(kept, 1.0, 0.0)
    dispatch = dispatch.at[idx_e, idx_c].add(
        tokens_f * weight[:, None].astype(tokens.dtype))

    # [E, cap, D] -> [ep, E_local, cap, D] -> alltoall over ep.
    dispatch = dispatch.reshape(ep, experts_per_rank, cap, d)
    recv = _a2a_transport(dispatch, axis, "moe.dispatch")
    # Fold source-rank dim into the capacity dim for the expert body.
    recv = recv.transpose(1, 0, 2, 3).reshape(experts_per_rank, ep * cap, d)
    processed = expert_fn(recv)
    processed = processed.reshape(experts_per_rank, ep, cap, d).transpose(
        1, 0, 2, 3)
    back = _a2a_transport(processed, axis, "moe.combine")
    back = back.reshape(e_total, cap, d)

    # Combine: gather each kept slot, weight by its renormalized gate.
    slots = back[idx_e, idx_c] * (gate_f * weight).astype(
        tokens.dtype)[:, None]                               # [K*T, D]
    out = slots.reshape(k, t, d).sum(axis=0)

    # Switch-transformer load-balancing loss over the PRIMARY routing:
    # E * Σ_e f_e · P_e, where f is the top-1 routed fraction and P the
    # mean router prob — averaged globally (reduces to the classic
    # switch loss at k=1).
    primary = jax.nn.one_hot(top_idx[:, 0], e_total, dtype=jnp.float32)
    f = lax.pmean(primary.mean(axis=0), axis)
    p_mean = lax.pmean(probs.mean(axis=0), axis)
    aux = MoEAux(
        load_balance_loss=e_total * jnp.sum(f * p_mean),
        dropped_fraction=lax.pmean(1.0 - kept.mean(), axis))

    from ..telemetry import instrument as _ti

    _rec = _ti.get_recorder()
    if _rec is not None:
        # Static routing geometry, booked at trace time (path=jit
        # convention): slot count and the slot/token expansion the
        # capacity factor buys.
        _rec.registry.gauge(
            "hvdt_moe_capacity_slots",
            "Per-expert dispatch slots of the last traced MoE layer "
            "(ceil(T*k/E * capacity_factor))").set(float(cap))
        _rec.registry.gauge(
            "hvdt_moe_expansion_ratio",
            "Dispatch slots / routed assignments of the last traced "
            "MoE layer (capacity head-room; <1 guarantees drops)"
        ).set(float(cap * e_total) / float(t * k))
    return out, aux


def report_moe_aux(aux: MoEAux, *, step: Optional[int] = None) -> None:
    """Host-side per-step reporter for the routing aux outputs.

    The traced program returns ``MoEAux`` as arrays; the train loop
    calls this after the step to surface them as ``hvdt_moe_*`` gauges
    (attribution-plane idiom — the time-series/anomaly layer picks the
    gauges up from the registry).  No-op when telemetry is off."""
    from ..telemetry import instrument as _ti

    _rec = _ti.get_recorder()
    if _rec is None:
        return
    del step
    _rec.registry.gauge(
        "hvdt_moe_load_balance_loss",
        "Switch-transformer load-balance aux loss of the last "
        "reported step (E * sum_e f_e * P_e)").set(
        float(jax.device_get(aux.load_balance_loss)))
    _rec.registry.gauge(
        "hvdt_moe_dropped_fraction",
        "Fraction of routed token assignments dropped over expert "
        "capacity in the last reported step").set(
        float(jax.device_get(aux.dropped_fraction)))
