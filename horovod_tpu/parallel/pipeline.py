"""Pipeline parallelism: GPipe-style microbatch circulation over a mesh axis.

Substrate beyond reference parity (SURVEY.md §2.7 — the reference has no
pipeline layer).  TPU-native design: all ``pp`` ranks run the same SPMD
program; activations hop stage→stage with ``lax.ppermute`` inside a
``lax.scan`` over clock ticks, so XLA sees one static program and can
overlap the permute with the next tick's compute.  Differentiable end to
end — ``jax.grad`` through the scan yields the 1F1B-equivalent backward
schedule automatically (ppermute transposes to the reverse permute).
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["pipeline_spmd"]


def pipeline_spmd(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stage_params: Any,
                  microbatches: jax.Array,
                  *,
                  axis: str = "pp",
                  broadcast_out: bool = True) -> jax.Array:
    """Run ``stage_fn`` as one pipeline stage per ``axis`` rank.

    Must be called inside shard_map with ``axis`` bound.  Stage activations
    must be shape-uniform across stages (do embedding before and the head
    after the pipeline — replicated over ``pp``).

    Args:
      stage_fn: ``(params, x) -> y`` mapping one microbatch activation
        through this rank's stage; same output shape as input.
      stage_params: this rank's stage parameters (slice the stacked
        [stages, ...] params over ``pp`` in your in_specs).
      microbatches: ``[M, mb, ...]`` activations, replicated over ``pp``.
      broadcast_out: if True, psum-broadcast the last stage's outputs to all
        ``pp`` ranks so the loss can be computed replicated (simplest
        composition with dp/tp). If False, non-final ranks return zeros.

    Returns ``[M, mb, ...]`` outputs of the final stage.
    """
    p = _axis_size_static(axis)
    me = lax.axis_index(axis)
    m = microbatches.shape[0]
    ticks = m + p - 1
    fwd = [(i, (i + 1) % p) for i in range(p)]

    def tick(carry, t):
        recv, out_buf = carry
        mb_idx = t - me                      # microbatch this rank works on
        active = (mb_idx >= 0) & (mb_idx < m)
        x0 = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), keepdims=False)
        x_in = jnp.where(me == 0, x0, recv)
        y = stage_fn(stage_params, x_in)
        # Zero the bubble so garbage never contaminates grads/outputs.
        y = jnp.where(active, y, jnp.zeros_like(y))
        is_last = me == p - 1
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf,
            jnp.where(active & is_last,
                      y,
                      lax.dynamic_index_in_dim(
                          out_buf, jnp.clip(mb_idx, 0, m - 1),
                          keepdims=False)),
            jnp.clip(mb_idx, 0, m - 1), axis=0)
        recv_next = lax.ppermute(y, axis, fwd)
        return (recv_next, out_buf), None

    # Initial carries must match the body's varying-manual-axes type
    # (inputs' vma plus the pipeline axis) for vma stability under scan.
    from .sharding import pcast_to_union

    def _varying(x):
        return pcast_to_union(x, microbatches,
                              *jax.tree.leaves(stage_params),
                              extra=(axis,))

    recv0 = _varying(jnp.zeros_like(microbatches[0]))
    out0 = _varying(jnp.zeros_like(microbatches))
    (_, out), _ = lax.scan(tick, (recv0, out0), jnp.arange(ticks))
    if broadcast_out:
        # Only the last stage wrote non-zeros; psum = broadcast from it.
        out = lax.psum(jnp.where(me == p - 1, out, jnp.zeros_like(out)), axis)
    return out
