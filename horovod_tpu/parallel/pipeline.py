"""Pipeline parallelism: 1F1B microbatch schedule over a mesh axis.

Substrate beyond reference parity (SURVEY.md §2.7 — the reference has no
pipeline layer).  TPU-native design: all ``pp`` ranks run the same SPMD
program; activations hop stage→stage with ``lax.ppermute`` inside
``lax.scan`` clocks, so XLA sees one static program and can overlap the
permute with the next tick's compute.

The clock is the 1F1B shape: a **warmup** segment (the first ``p-1``
ticks — the pipeline fills, trailing stages idle), a **steady** segment
(every stage busy, one microbatch in / one out per tick), and a
**cooldown** segment (the last ``p-1`` ticks — the pipeline drains).
Differentiable end to end: ``jax.grad`` through the scans yields the
reverse clock automatically (ppermute transposes to the reverse
permute), i.e. the backward drains in mirrored cooldown/steady/warmup
order — the 1F1B-equivalent schedule with the same
``(p-1)/(m+p-1)`` bubble fraction the cost model prices
(analysis/costmodel.pipeline_bubble_fraction).

Telemetry (trace time, path=jit convention): each traced schedule books
per-stage phase histograms ``hvdt_phase_PIPELINE_STAGE<i>_{WARMUP,
ACTIVE,COOLDOWN}_seconds`` in tick units — idle ÷ total ticks across
stages IS the observed bubble fraction the CI perf gate checks against
the priced one — plus one flight-recorder send/recv event per clock
segment.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.device import _axis_size_static

__all__ = ["pipeline_1f1b", "pipeline_spmd", "bubble_fraction",
           "report_pipeline_mfu"]


def bubble_fraction(num_stages: int, num_microbatches: int) -> float:
    """Idle ÷ total stage-ticks of the 1F1B clock: ``(p-1)/(m+p-1)``.

    Every stage is idle for exactly ``p-1`` of the ``m+p-1`` ticks
    (stage ``s``: ``s`` warmup ticks + ``p-1-s`` cooldown ticks), so the
    per-stage and schedule-wide fractions coincide."""
    p, m = int(num_stages), int(num_microbatches)
    if p < 1 or m < 1:
        raise ValueError(f"need p >= 1 and m >= 1, got ({p}, {m})")
    return (p - 1) / (m + p - 1)


def _record_schedule(axis: str, p: int, m: int, tick_bytes: int,
                     dtype: str = "float32") -> None:
    """Trace-time booking of one pipeline schedule (ops/device idiom):
    per-stage phase histograms in tick units + one flight-recorder
    send/recv event per clock segment."""
    from ..telemetry import flight_recorder as _frm
    from ..telemetry import instrument as _ti

    _rec = _ti.get_recorder()
    _flight = _frm.get_flight_recorder()
    if _rec is None and _flight is None:
        return
    ticks = m + p - 1
    warmup = p - 1
    steady = max(0, m - (p - 1))
    cooldown = ticks - warmup - steady
    if _rec is not None:
        for s in range(p):
            # Tick units: the static clock is known at trace time; the
            # idle/total ratio (the observed bubble fraction) is
            # unit-free, so histogram sums compare directly against
            # the cost model's priced fraction.
            _rec.observe_phase(f"PIPELINE_STAGE{s}_WARMUP", float(s))
            _rec.observe_phase(f"PIPELINE_STAGE{s}_ACTIVE", float(m))
            _rec.observe_phase(f"PIPELINE_STAGE{s}_COOLDOWN",
                               float(p - 1 - s))
        _rec.record_collective(
            "ppermute", dtype, "exact", tick_bytes * ticks,
            count=ticks, path="jit", axis=axis)
    if _flight is not None:
        for seg, n in (("warmup", warmup), ("steady", steady),
                       ("cooldown", cooldown)):
            if n <= 0:
                continue
            _flight.record(
                op="ppermute", name=f"pipeline.{seg}",
                dtype=dtype, shape=(int(tick_bytes),),
                nbytes=tick_bytes * n, wire="exact", path="jit",
                count=n, axis=axis)


def pipeline_1f1b(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stage_params: Any,
                  microbatches: jax.Array,
                  *,
                  axis: str = "pp",
                  broadcast_out: bool = True) -> jax.Array:
    """Run ``stage_fn`` as one pipeline stage per ``axis`` rank, on the
    1F1B warmup/steady/cooldown clock.

    Must be called inside shard_map with ``axis`` bound.  Stage activations
    must be shape-uniform across stages (do embedding before and the head
    after the pipeline — replicated over ``pp``).

    Args:
      stage_fn: ``(params, x) -> y`` mapping one microbatch activation
        through this rank's stage; same output shape as input.
      stage_params: this rank's stage parameters (slice the stacked
        [stages, ...] params over ``pp`` in your in_specs).
      microbatches: ``[M, mb, ...]`` activations, replicated over ``pp``.
      broadcast_out: if True, psum-broadcast the last stage's outputs to all
        ``pp`` ranks so the loss can be computed replicated (simplest
        composition with dp/tp). If False, non-final ranks return zeros.

    Returns ``[M, mb, ...]`` outputs of the final stage.
    """
    p = _axis_size_static(axis)
    me = lax.axis_index(axis)
    m = microbatches.shape[0]
    ticks = m + p - 1
    fwd = [(i, (i + 1) % p) for i in range(p)]

    mb_bytes = int(microbatches[0].size) * microbatches.dtype.itemsize
    _record_schedule(axis, p, m, mb_bytes,
                     dtype=jnp.dtype(microbatches.dtype).name)

    def tick(carry, t):
        recv, out_buf = carry
        mb_idx = t - me                      # microbatch this rank works on
        active = (mb_idx >= 0) & (mb_idx < m)
        x0 = lax.dynamic_index_in_dim(
            microbatches, jnp.clip(t, 0, m - 1), keepdims=False)
        x_in = jnp.where(me == 0, x0, recv)
        y = stage_fn(stage_params, x_in)
        # Zero the bubble so garbage never contaminates grads/outputs.
        y = jnp.where(active, y, jnp.zeros_like(y))
        is_last = me == p - 1
        out_buf = lax.dynamic_update_index_in_dim(
            out_buf,
            jnp.where(active & is_last,
                      y,
                      lax.dynamic_index_in_dim(
                          out_buf, jnp.clip(mb_idx, 0, m - 1),
                          keepdims=False)),
            jnp.clip(mb_idx, 0, m - 1), axis=0)
        recv_next = lax.ppermute(y, axis, fwd)
        return (recv_next, out_buf), None

    # Initial carries must match the body's varying-manual-axes type
    # (inputs' vma plus the pipeline axis) for vma stability under scan.
    from .sharding import pcast_to_union

    def _varying(x):
        return pcast_to_union(x, microbatches,
                              *jax.tree.leaves(stage_params),
                              extra=(axis,))

    recv0 = _varying(jnp.zeros_like(microbatches[0]))
    out0 = _varying(jnp.zeros_like(microbatches))

    # The clock runs as one scan per 1F1B segment (fill / steady /
    # drain).  The tick body is identical — segment boundaries are a
    # property of the CLOCK, not the per-tick program — but separate
    # scans keep the segments distinct in the jaxpr (three ppermute
    # sites, named scopes hvdt.pipeline.<segment>), which is what the
    # schedule fingerprint and flight-recorder events key on.
    warmup = min(p - 1, ticks)
    steady = max(0, m - (p - 1))
    cooldown = ticks - warmup - steady
    carry = (recv0, out0)
    t0 = 0
    for seg, n in (("warmup", warmup), ("steady", steady),
                   ("cooldown", cooldown)):
        if n <= 0:
            continue
        with jax.named_scope(f"hvdt.pipeline.{seg}"):
            carry, _ = lax.scan(tick, carry, jnp.arange(t0, t0 + n))
        t0 += n
    _, out = carry
    if broadcast_out:
        # Only the last stage wrote non-zeros; psum = broadcast from it.
        out = lax.psum(jnp.where(me == p - 1, out, jnp.zeros_like(out)), axis)
    return out


def pipeline_spmd(stage_fn: Callable[[Any, jax.Array], jax.Array],
                  stage_params: Any,
                  microbatches: jax.Array,
                  *,
                  axis: str = "pp",
                  broadcast_out: bool = True) -> jax.Array:
    """Compatibility alias for :func:`pipeline_1f1b` (the GPipe-ish
    single-scan schedule this name used to carry was replaced by the
    segmented 1F1B clock; same contract, same outputs)."""
    return pipeline_1f1b(stage_fn, stage_params, microbatches,
                         axis=axis, broadcast_out=broadcast_out)


def report_pipeline_mfu(flops_per_step: float, step_seconds: float,
                        peak_flops_per_sec: Optional[float] = None
                        ) -> float:
    """Host-side MFU reporter: achieved model FLOP/s ÷ peak, as the
    ``hvdt_pipeline_mfu`` gauge.

    ``peak_flops_per_sec`` defaults to ``HVDT_PEAK_FLOPS`` (per-chip
    peak × chips; on the CPU sim any consistent nominal peak works —
    MFU is a ratio).  Returns the computed MFU; no-op gauge write when
    telemetry is off."""
    import os

    if peak_flops_per_sec is None:
        from ..analysis.topology import NOMINAL_SIM_PEAK_FLOPS

        raw = os.environ.get("HVDT_PEAK_FLOPS", "")
        peak_flops_per_sec = float(raw) if raw else NOMINAL_SIM_PEAK_FLOPS
    mfu = float(flops_per_step) / (float(step_seconds)
                                   * float(peak_flops_per_sec))
    from ..telemetry import instrument as _ti

    _rec = _ti.get_recorder()
    if _rec is not None:
        _rec.registry.gauge(
            "hvdt_pipeline_mfu",
            "Model FLOPs utilization of the last reported pipeline "
            "step (achieved model FLOP/s / peak FLOP/s)").set(mfu)
    return mfu
