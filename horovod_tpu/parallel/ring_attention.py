"""Ring attention: exact attention over sequence shards via an ICI ring.

Long-context substrate (SURVEY.md §5.7 — absent upstream; the reference's
only sequence-adjacent primitive is alltoall, operations.cc:1642).  Design
follows the ring-attention pattern: Q stays put, K/V blocks rotate around
the ``sp`` mesh axis with ``lax.ppermute`` while each device accumulates
its block's contribution with flash-style (log-sum-exp) running statistics,
so per-step memory is O(block) and comm overlaps compute under XLA async
dispatch.

Must be called inside ``shard_map``/pjit where the ``sp`` axis is bound and
the sequence dimension of q/k/v is the *local* shard.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..ops.device import _axis_size_static

__all__ = ["ring_attention"]

_NEG_INF = -1e30


def _block_update(q, k, v, acc, row_max, row_sum, mask, scale):
    """One flash-attention block accumulation step.

    q: [B, Lq, H, D]; k/v: [B, Lk, Hkv, D] (Hkv divides H — expanded here,
    after the ring transfer, so the ppermute only ever moves the small
    unexpanded K/V); acc: [B, Lq, H, D]; row_max/row_sum: [B, H, Lq];
    mask: broadcastable to [B, H, Lq, Lk].
    """
    h, kv_heads = q.shape[2], k.shape[2]
    if h != kv_heads:
        k = jnp.repeat(k, h // kv_heads, axis=2)
        v = jnp.repeat(v, h // kv_heads, axis=2)
    scores = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                        preferred_element_type=jnp.float32) * scale
    scores = jnp.where(mask, scores, _NEG_INF)
    new_max = jnp.maximum(row_max, scores.max(axis=-1))
    # exp() of masked rows would be exp(0)=1 when the whole row is masked
    # (scores == new_max == -inf); re-mask explicitly.
    p = jnp.where(mask, jnp.exp(scores - new_max[..., None]), 0.0)
    correction = jnp.exp(row_max - new_max)
    acc = acc * correction.transpose(0, 2, 1)[..., None] + jnp.einsum(
        "bhqk,bkhd->bqhd", p.astype(v.dtype), v,
        preferred_element_type=jnp.float32)
    row_sum = row_sum * correction + p.sum(axis=-1)
    return acc, new_max, row_sum


def _bwd_block_grads(qf, dof, k_blk, v_blk, lse, delta_bhq, mask, scale,
                     group):
    """One visiting K/V block's (dq, dk, dv) contributions in the jnp
    ring backward — scores recomputed from the saved logsumexp.

    qf/dof: f32 ``[B, Lq, H, D]``; k_blk/v_blk: raw ``[B, Lk, Hkv, D]``;
    lse: ``[B, H, Lq]``; delta_bhq: ``[B, H, Lq]``; mask: broadcastable
    to ``[B, H, Lq, Lk]`` or None (fully visible); group = H // Hkv.

    Factored out of :func:`_ring_diff_bwd`'s scan body so A/B harnesses
    (tools/ring_ab.py) time the PRODUCTION step math by import instead
    of an inline copy that could silently drift.
    """
    f32 = jnp.float32
    ks = k_blk.astype(f32)
    vs = v_blk.astype(f32)
    if group > 1:
        ks = jnp.repeat(ks, group, axis=2)
        vs = jnp.repeat(vs, group, axis=2)
    s_ = jnp.einsum("bqhd,bkhd->bhqk", qf, ks) * scale
    p = jnp.exp(s_ - lse[..., None])
    if mask is not None:
        p = jnp.where(mask, p, 0.0)
    dv_c = jnp.einsum("bhqk,bqhd->bkhd", p, dof)
    dp = jnp.einsum("bqhd,bkhd->bhqk", dof, vs)
    ds = p * (dp - delta_bhq[..., None]) * scale
    dq_c = jnp.einsum("bhqk,bkhd->bqhd", ds, ks)
    dk_c = jnp.einsum("bhqk,bqhd->bkhd", ds, qf)
    if group > 1:
        b, lk = k_blk.shape[0], k_blk.shape[1]
        hkv, d = k_blk.shape[2], k_blk.shape[3]
        dk_c = dk_c.reshape(b, lk, hkv, group, d).sum(3)
        dv_c = dv_c.reshape(b, lk, hkv, group, d).sum(3)
    return dq_c, dk_c, dv_c


def ring_attention(q: jax.Array, k: jax.Array, v: jax.Array,
                   *,
                   axis: str = "sp",
                   causal: bool = True,
                   scale: Optional[float] = None,
                   segment_ids: Optional[jax.Array] = None,
                   use_pallas: Optional[bool] = None) -> jax.Array:
    """Exact (optionally causal) attention over a sequence-sharded ring.

    Differentiation: the common path (``segment_ids=None``) carries a
    ``custom_vjp`` whose backward is a SECOND ring pass that recomputes
    scores blockwise from the saved logsumexp — O(local_seq x block)
    memory, like the forward.  Plain autodiff through the forward scan
    would instead save every visiting block's score matrix
    (O(local_seq x global_seq) per device), which defeats the point of
    sequence parallelism at long context.  The ``segment_ids`` path
    still differentiates that way (exact, memory-heavy).  With
    ``use_pallas=True`` BOTH ring passes run Pallas kernels
    (ops/pallas_kernels.flash_block_update forward,
    flash_grad_block backward) — fully trainable.

    Args:
      q, k, v: local shards ``[batch, local_seq, heads, head_dim]``.  MQA/GQA
        is supported: k/v may have fewer heads as long as q heads divide;
        the ring only ever transfers the unexpanded K/V.
      axis: mesh axis name carrying the sequence shards.
      causal: apply a causal mask using *global* positions.
      scale: score scale; default ``1/sqrt(head_dim)``.
      segment_ids: optional ``[batch, local_seq]`` int segment labels for
        packed sequences; attention is masked to equal segments.  The key
        side's labels rotate around the ring with K/V.
      use_pallas: run each ring step through the Pallas flash kernels —
        ops/pallas_kernels.flash_block_update forward,
        flash_grad_block backward (dK/dV accumulated blockwise in VMEM
        scratch and rotated with their block) — instead of the jnp
        block update.  Trainable: grads match the jnp path and the
        dense reference (tests/test_parallel.py).  Default **False**
        (requires segment_ids=None and 128-tiling shapes; the jnp path
        is the portable default).

    Returns ``[batch, local_seq, heads, head_dim]`` in q's dtype.
    """
    b, lq, h, d = q.shape
    if h % k.shape[2]:
        raise ValueError(
            f"q heads {h} not divisible by kv heads {k.shape[2]}")
    if scale is None:
        scale = d ** -0.5
    lk = k.shape[1]

    kernel_legal = (segment_ids is None
                    and not (lq % min(128, lq) or lk % min(128, lk)))
    if use_pallas is None:
        # Env-driven default (HVDT_RING_PALLAS=1): engage the kernels
        # where they are legal, silently keep the jnp path elsewhere.
        from ..common import config

        use_pallas = config.get_bool("HVDT_RING_PALLAS") and kernel_legal
    elif use_pallas and not kernel_legal:
        import warnings

        warnings.warn(
            "ring_attention(use_pallas=True) ignored: the kernel needs "
            "segment_ids=None and 128-tiling shapes "
            f"(lq={lq}, lk={lk}); running the jnp block update",
            stacklevel=2)
        use_pallas = False

    # The custom_vjp path needs scale as a static Python float
    # (nondiff arg); a traced scale (e.g. a learned temperature) keeps
    # the plain-autodiff path, which handles it fine.
    try:
        static_scale = float(scale)
    except Exception:
        static_scale = None
    if segment_ids is None and static_scale is not None:
        return _ring_diff(q, k, v, axis, causal, static_scale, use_pallas)
    out, _ = _ring_forward(q, k, v, axis, causal, scale,
                           segment_ids, use_pallas)
    return out


def _ring_forward(q, k, v, axis, causal, scale, segment_ids, use_pallas):
    """Forward ring pass; returns (out, lse [B,H,Lq])."""
    b, lq, h, d = q.shape
    sp = _axis_size_static(axis)
    my = lax.axis_index(axis)
    lk = k.shape[1]

    q_pos = my * lq + jnp.arange(lq)                      # global q positions

    # Initial accumulators must carry the same varying-manual-axes type the
    # scan body produces (q/k/v's vma plus the ring axis) so the carry is
    # type-stable — q may additionally vary over dp/tp axes of the mesh.
    from .sharding import pcast_to_union

    def _varying(x):
        return pcast_to_union(x, q, k, v, extra=(axis,))

    acc = _varying(jnp.zeros((b, lq, h, d), jnp.float32))
    row_max = _varying(jnp.full((b, h, lq), _NEG_INF, jnp.float32))
    row_sum = _varying(jnp.zeros((b, h, lq), jnp.float32))
    fwd = [(i, (i + 1) % sp) for i in range(sp)]
    k_seg0 = segment_ids if segment_ids is not None else None

    def step(carry, s):
        k_blk, v_blk, k_seg, acc, row_max, row_sum = carry
        # After s rotations the resident block originated at rank (my - s).
        src = (my - s) % sp
        if use_pallas:
            # Fused VMEM-resident block update (ops/pallas_kernels.py).
            # Ring blocks need only three mask cases — source block fully
            # visible (src < my), the causal diagonal (src == my), or
            # fully in the future (identity) — so the kernel's position
            # offsets stay static and lax.switch picks the case.
            from ..ops.pallas_kernels import flash_block_update

            def _full(ops):
                qq, kb, vb, a, m_, s_ = ops
                return flash_block_update(qq, kb, vb, a, m_, s_,
                                          q_offset=0, k_offset=0,
                                          causal=False, scale=scale)

            def _diag(ops):
                qq, kb, vb, a, m_, s_ = ops
                return flash_block_update(qq, kb, vb, a, m_, s_,
                                          q_offset=0, k_offset=0,
                                          causal=True, scale=scale)

            def _skip(ops):
                _, _, _, a, m_, s_ = ops
                return a, m_, s_

            ops_in = (q, k_blk, v_blk, acc, row_max, row_sum)
            if causal:
                case = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
                acc, row_max, row_sum = lax.switch(
                    case, [_full, _diag, _skip], ops_in)
            else:
                acc, row_max, row_sum = _full(ops_in)
        else:
            k_pos = src * lk + jnp.arange(lk)
            if causal:
                mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
            else:
                mask = jnp.ones((1, 1, 1, 1), bool)
            if k_seg is not None:
                same = segment_ids[:, :, None] == k_seg[:, None, :]
                mask = jnp.logical_and(mask, same[:, None, :, :])
            acc, row_max, row_sum = _block_update(
                q, k_blk, v_blk, acc, row_max, row_sum, mask, scale)
        # Rotate K/V (and its segment labels) forward for the next step.
        k_nxt = lax.ppermute(k_blk, axis, fwd)
        v_nxt = lax.ppermute(v_blk, axis, fwd)
        seg_nxt = (lax.ppermute(k_seg, axis, fwd)
                   if k_seg is not None else None)
        return (k_nxt, v_nxt, seg_nxt, acc, row_max, row_sum), None

    (_, _, _, acc, row_max, row_sum), _ = lax.scan(
        step, (k, v, k_seg0, acc, row_max, row_sum), jnp.arange(sp))
    row_sum = jnp.maximum(row_sum, 1e-30)
    out = acc / row_sum.transpose(0, 2, 1)[..., None]
    lse = row_max + jnp.log(row_sum)                       # [B, H, Lq]
    return out.astype(q.dtype), lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _ring_diff(q, k, v, axis, causal, scale, use_pallas):
    out, _ = _ring_forward(q, k, v, axis, causal, scale, None, use_pallas)
    return out


def _ring_diff_fwd(q, k, v, axis, causal, scale, use_pallas):
    out, lse = _ring_forward(q, k, v, axis, causal, scale, None, use_pallas)
    return out, (q, k, v, out, lse)


def _ring_diff_bwd(axis, causal, scale, use_pallas, res, do):
    """Second ring pass: dk/dv accumulators travel WITH their K/V block
    (ppermute) and arrive home after sp rotations carrying every rank's
    contribution; dq accumulates locally.  Scores are recomputed per
    visiting block from the saved logsumexp — O(local_seq x block)
    memory, mirroring the forward."""
    q, k, v, out, lse = res
    b, lq, h, d = q.shape
    lk, hkv = k.shape[1], k.shape[2]
    group = h // hkv
    sp = _axis_size_static(axis)
    my = lax.axis_index(axis)
    fwd = [(i, (i + 1) % sp) for i in range(sp)]
    f32 = jnp.float32

    qf = q.astype(f32)
    dof = do.astype(f32)
    # delta_i = sum_d do_i * o_i (rowsum term of dS)       [B, Lq, H]
    delta = jnp.einsum("bqhd,bqhd->bqh", do, out,
                       preferred_element_type=f32)
    q_pos = my * lq + jnp.arange(lq)

    from .sharding import pcast_to_union

    def _varying(x):
        return pcast_to_union(x, q, k, v, do, extra=(axis,))

    delta, lse_v = _varying(delta), _varying(lse)
    qf, dof = _varying(qf), _varying(dof)

    if use_pallas:
        # Per-step grads through the Pallas backward kernels
        # (ops/pallas_kernels.flash_grad_block): the VMEM-tiled
        # recompute of this block pair's (dq, dk, dv) — no [B,H,Lq,Lk]
        # f32 score tensor in HBM.  Ring blocks need only the three
        # static mask cases of the forward (full/diagonal/future), so
        # the kernels see static causal flags and zero offsets.
        from ..ops.pallas_kernels import flash_grad_block

        qv, dov, outv = _varying(q), _varying(do), _varying(out)
        delta_bhq = _varying(delta.transpose(0, 2, 1))        # [B,H,Lq]

        def _grads(kb, vb, causal_flag):
            return flash_grad_block(qv, kb, vb, dov, outv, lse_v,
                                    causal=causal_flag, scale=scale,
                                    delta=delta_bhq)

        def pstep(carry, s):
            k_blk, v_blk, dk_blk, dv_blk, dq_acc = carry
            src = (my - s) % sp

            def _full(ops):
                return _grads(ops[0], ops[1], False)

            def _diag(ops):
                return _grads(ops[0], ops[1], True)

            def _skip(ops):
                return (_varying(jnp.zeros((b, lq, h, d), f32)),
                        _varying(jnp.zeros((b, lk, hkv, d), f32)),
                        _varying(jnp.zeros((b, lk, hkv, d), f32)))

            if causal:
                case = jnp.where(src < my, 0, jnp.where(src == my, 1, 2))
                dq_c, dk_c, dv_c = lax.switch(
                    case, [_full, _diag, _skip], (k_blk, v_blk))
            else:
                dq_c, dk_c, dv_c = _full((k_blk, v_blk))
            return (lax.ppermute(k_blk, axis, fwd),
                    lax.ppermute(v_blk, axis, fwd),
                    lax.ppermute(dk_blk + dk_c, axis, fwd),
                    lax.ppermute(dv_blk + dv_c, axis, fwd),
                    dq_acc + dq_c), None

        zeros_kv = _varying(jnp.zeros((b, lk, hkv, d), f32))
        dq0 = _varying(jnp.zeros((b, lq, h, d), f32))
        (_, _, dk, dv, dq), _ = lax.scan(
            pstep, (k, v, zeros_kv, zeros_kv, dq0), jnp.arange(sp))
        return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))

    delta_bhq = delta.transpose(0, 2, 1)                      # [B,H,Lq]

    def step(carry, s):
        k_blk, v_blk, dk_blk, dv_blk, dq_acc = carry
        src = (my - s) % sp
        if causal:
            k_pos = src * lk + jnp.arange(lk)
            mask = (q_pos[:, None] >= k_pos[None, :])[None, None]
        else:
            mask = None
        dq_c, dk_c, dv_c = _bwd_block_grads(
            qf, dof, k_blk, v_blk, lse_v, delta_bhq, mask, scale, group)
        dq_acc = dq_acc + dq_c
        dk_blk = dk_blk + dk_c
        dv_blk = dv_blk + dv_c
        return (lax.ppermute(k_blk, axis, fwd),
                lax.ppermute(v_blk, axis, fwd),
                lax.ppermute(dk_blk, axis, fwd),
                lax.ppermute(dv_blk, axis, fwd),
                dq_acc), None

    zeros_kv = _varying(jnp.zeros((b, lk, hkv, d), f32))
    dq0 = _varying(jnp.zeros((b, lq, h, d), f32))
    (_, _, dk, dv, dq), _ = lax.scan(
        step, (k, v, zeros_kv, zeros_kv, dq0), jnp.arange(sp))
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_ring_diff.defvjp(_ring_diff_fwd, _ring_diff_bwd)
