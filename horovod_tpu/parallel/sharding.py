"""Logical-axis sharding rules → ``PartitionSpec``s.

The reference has no analog (its only layout concept is one-process-per-GPU
data parallelism); this is the TPU-native substrate SURVEY.md §2.7 calls for.
Models name their parameter dimensions with *logical* axes ("embed", "mlp",
"heads", "batch", "seq", ...) and a rule table maps those to mesh axes —
the pattern used across public JAX LLM codebases (t5x/flax partitioning).
"""

from __future__ import annotations

from typing import Dict, Mapping, Optional, Sequence, Tuple, Union

from jax.sharding import Mesh, NamedSharding, PartitionSpec

from .mesh import AXIS_DP, AXIS_EP, AXIS_FSDP, AXIS_PP, AXIS_SP, AXIS_TP

__all__ = [
    "pcast_to_union",
    "transformer_rules", "logical_to_mesh", "named_sharding", "batch_spec",
    "fsdp_shardings",
]

MeshAxes = Union[None, str, Tuple[str, ...]]


def transformer_rules(*, fsdp: bool = False) -> Dict[str, MeshAxes]:
    """Default logical→mesh rules for a Megatron-style transformer.

    * ``embed`` (the model/hidden dim) is replicated across ``tp`` —
      or sharded over ``fsdp`` when ZeRO-style sharding is on;
    * ``mlp``/``heads``/``kv`` (the per-layer wide dims) shard over ``tp``;
    * ``batch`` shards over (dp, fsdp), ``seq`` over ``sp``;
    * ``experts`` shard over ``ep``; ``stages`` over ``pp``;
    * ``vocab`` shards over ``tp`` (parallel embedding / logits).
    """
    return {
        "batch": (AXIS_DP, AXIS_FSDP) if fsdp else AXIS_DP,
        "seq": AXIS_SP,
        "embed": AXIS_FSDP if fsdp else None,
        "mlp": AXIS_TP,
        "heads": AXIS_TP,
        "kv": None,
        # Vocab stays replicated: a tp-sharded embedding makes the token
        # gather's output sharding ambiguous under sharding-in-types, and
        # the per-layer dims already carry the tp FLOPs.  (Megatron-style
        # vocab-parallel embedding = future refinement via one-hot matmul.)
        "vocab": None,
        "experts": AXIS_EP,
        "stages": AXIS_PP,
        "unmodeled": None,
    }


def logical_to_mesh(logical: Sequence[Optional[str]],
                    rules: Mapping[str, MeshAxes],
                    mesh: Optional[Mesh] = None) -> PartitionSpec:
    """Map a tuple of logical axis names to a PartitionSpec.

    Mesh axes not present in ``mesh`` (or of size 1) are dropped so one rule
    table works across mesh shapes — e.g. the same model runs pure-DP or
    DP×TP without edits.  A mesh axis may be consumed at most once.
    """
    present = dict(mesh.shape) if mesh is not None else None
    used = set()
    out = []
    for name in logical:
        axes = rules.get(name) if name is not None else None
        if axes is None:
            out.append(None)
            continue
        if isinstance(axes, str):
            axes = (axes,)
        kept = []
        for ax in axes:
            if present is not None and present.get(ax, 1) <= 1:
                continue
            if ax in used:
                raise ValueError(
                    f"mesh axis {ax!r} consumed twice in logical spec "
                    f"{tuple(logical)}")
            used.add(ax)
            kept.append(ax)
        if not kept:
            out.append(None)
        elif len(kept) == 1:
            out.append(kept[0])
        else:
            out.append(tuple(kept))
    while out and out[-1] is None:
        out.pop()
    return PartitionSpec(*out)


def named_sharding(mesh: Mesh, logical: Sequence[Optional[str]],
                   rules: Optional[Mapping[str, MeshAxes]] = None
                   ) -> NamedSharding:
    """Convenience: ``NamedSharding`` for a logical spec under ``rules``."""
    if rules is None:
        rules = transformer_rules()
    return NamedSharding(mesh, logical_to_mesh(logical, rules, mesh))


def batch_spec(mesh: Optional[Mesh] = None, *, seq_sharded: bool = False,
               rules: Optional[Mapping[str, MeshAxes]] = None
               ) -> PartitionSpec:
    """PartitionSpec for an input batch [batch, seq, ...]."""
    if rules is None:
        rules = transformer_rules()
    logical = ("batch", "seq" if seq_sharded else None)
    return logical_to_mesh(logical, rules, mesh)


def fsdp_shardings(mesh: Mesh, logical_tree,
                   rules: Optional[Mapping[str, MeshAxes]] = None):
    """Per-leaf ``NamedSharding``s that shard parameters over the
    ``fsdp`` mesh axis — the ZeRO-3 "params" layout for the GSPMD-auto
    path (``HVDT_ZERO=params``, ops/zero.py).

    ``logical_tree`` is a same-structure pytree of logical axis tuples
    (e.g. ``models.transformer_logical_axes``); rules default to
    ``transformer_rules(fsdp=True)``, so ``embed`` dims land on
    ``AXIS_FSDP``.  ``jax.device_put`` params with these shardings and
    a jitted forward allgathers each layer's weights **on demand, per
    layer** — XLA inserts the gather right before the first use and
    frees the full tensor after the last, which is exactly the
    deferred-materialization half of ZeRO-3 (the manual-shard_map half
    lives in ``ops.zero.ZeroTransformation.gather_params``).
    """
    import jax

    if rules is None:
        rules = transformer_rules(fsdp=True)
    return jax.tree.map(
        lambda logical: NamedSharding(
            mesh, logical_to_mesh(logical, rules, mesh)),
        logical_tree, is_leaf=lambda x: isinstance(x, tuple))


def pcast_to_union(x, *operands, extra=()):
    """Promote ``x``'s varying-manual-axes (vma) type to the union of the
    operands' vma sets (plus any ``extra`` axis names).

    Inside a ``shard_map`` island, scan carries / accumulators must hold
    the same vma type as the values the body produces; this is THE
    idiom for initializing them (used by ring attention, the pipeline
    schedule, the transformer layer scan, and the flash-attention
    backward)."""
    import jax
    from jax import lax

    typeof = getattr(jax, "typeof", None)
    if typeof is None:      # JAX without vma tracking: nothing to align
        return x
    want = set(extra)
    for op in operands:
        want |= set(getattr(typeof(op), "vma", frozenset()))
    missing = tuple(want - set(getattr(typeof(x), "vma", frozenset())))
    return lax.pcast(x, missing, to="varying") if missing else x
