"""Two-stage quantized allreduce — int8 or packed-int4 wire end to end.

The EQuARX schedule (arxiv 2506.17615), expressed with XLA named-axis
collectives so GSPMD/Mosaic can overlap it like any other program:

1. each rank quantizes its full local vector (block-scaled int8,
   quant/kernels);
2. **reduce-scatter in wire format**: an ``all_to_all`` moves every
   rank's copy of shard *j* (int8 payload + f32 block scales) to rank
   *j* — the bandwidth-heavy hop crosses the wire at ~1 B/element;
3. each rank dequantize-accumulates its shard in f32 (the reduction
   itself is never done in int8 — accumulating in wire precision would
   overflow and compound rounding);
4. the reduced shard is **requantized** and reassembled in wire format
   (zero-embed + int8 psum, disjoint regions — the psum-family terminal
   op keeps the result type *replicated*, which P() out_specs and
   optax.MultiSteps require) — the second hop also rides int8;
5. final dequantize to the requested dtype.

Wire bytes per rank ≈ ``3 (n-1)/n · size · (1 + 4/block)`` vs
``8 (n-1)/n · size`` for the f32 ring — a ~2.7x reduction at the
default block 256 (:func:`quant.kernels.wire_bytes` is the per-message
payload accounting).

Error model: stage-1 error is bounded by each rank's block scale / 2
and is what :mod:`..quant.error_feedback` carries into the next step;
stage-4 requantization error is bounded by the *reduced* shard's block
scale / 2.  Values already on the grid survive both stages exactly.

Old-JAX guard (container jax 0.4.37): axis size is resolved through
``lax.psum(1, axis)`` — static under shard_map on every JAX — instead
of ``lax.axis_size`` (absent there); no ``jax.typeof``/``lax.pcast``
needed anywhere on this path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional

import jax
import jax.numpy as jnp
from jax import lax

from ..common.types import ReduceOp
from . import kernels as qk

__all__ = ["quantized_allreduce_flat", "quantized_allreduce",
           "quantized_allreduce_start", "quantized_allreduce_finish",
           "quantized_reduce_scatter_start",
           "quantized_reduce_scatter_finish",
           "InflightQuantized", "eager_quantized_allreduce",
           "INT8_WIRE", "INT4_WIRE", "quant_wire_leg", "wire_sentinel"]

# Sentinels a Compressor exposes as ``wire_dtype`` to select this path in
# fused_allreduce (strings on purpose: never mistakable for a dtype).
INT8_WIRE = "int8_blockwise"
INT4_WIRE = "int4_blockwise"

# Every quantized-wire spelling a wire_dtype slot may carry, mapped to
# the quantized leg it selects.  The ONE place consumers (overlap, zero,
# device, hierarchy) classify a wire_dtype as quantized — adding a leg
# here adds it everywhere.
_WIRE_LEGS = {"int8": "int8", INT8_WIRE: "int8",
              "int4": "int4", INT4_WIRE: "int4"}


def quant_wire_leg(wire_dtype) -> Optional[str]:
    """``"int8"`` / ``"int4"`` when ``wire_dtype`` names a quantized
    wire (policy name or blockwise sentinel), else ``None``."""
    if not isinstance(wire_dtype, str):
        return None
    return _WIRE_LEGS.get(wire_dtype)


def wire_sentinel(wire: str) -> str:
    """The telemetry/compressor sentinel for a quantized leg name."""
    return INT4_WIRE if wire == "int4" else INT8_WIRE


def _leg_wire_bytes(wire: str, size: int, block: int) -> int:
    return (qk.wire_bytes_int4(size, block) if wire == "int4"
            else qk.wire_bytes(size, block))


def _check_wire(wire: str) -> str:
    if wire not in ("int8", "int4"):
        raise ValueError(
            f"quantized allreduce wire must be 'int8' or 'int4', "
            f"got {wire!r}")
    return wire


def _single_axis(axis) -> str:
    if isinstance(axis, str):
        return axis
    axes = tuple(axis)
    if len(axes) == 1:
        return axes[0]
    raise ValueError(
        f"quantized (int8-wire) allreduce reduces over ONE mesh axis, "
        f"got {axes}; reduce hierarchically or pick a single axis")


def _axis_size_static(axis: str) -> int:
    size_fn = getattr(lax, "axis_size", None)
    return int(size_fn(axis)) if size_fn is not None else int(
        lax.psum(1, axis))


@dataclasses.dataclass
class InflightQuantized:
    """A quantized allreduce whose bandwidth-heavy wire hop has been
    issued but whose dequant-accumulate half has not run yet.

    Produced by :func:`quantized_allreduce_start`, consumed by
    :func:`quantized_allreduce_finish` — the seam the overlap scheduler
    (ops/overlap.py) pipelines across buckets: while bucket N sits in
    this state, bucket N+1's wire hop is already in flight, so N's
    dequant-accumulate overlaps N+1's wire phase.  ``q_recv``/``s_recv``
    are traced arrays (the received wire shards); everything else is
    static trace-time metadata.
    """
    q_recv: Any
    s_recv: Any
    axis: str
    op: ReduceOp
    block: int
    n: int
    shard: int
    total: int
    size: int
    dtype: Any
    # Which quantized leg the payload rides: "int8" (1 B/elem) or
    # "int4" (packed two lanes per byte; q_recv holds shard/2 bytes).
    wire: str = "int8"


def quantized_allreduce_start(flat, axis="dp",
                              op: ReduceOp = ReduceOp.AVERAGE,
                              block_size: Optional[int] = None,
                              prescale_factor: float = 1.0,
                              wire: str = "int8"
                              ) -> InflightQuantized:
    """Stages 1-2 of the quantized allreduce: quantize locally and issue
    the wire-format reduce-scatter (the bandwidth-heavy ``all_to_all``
    hop).  ``wire`` selects the int8 or packed-int4 payload; both legs
    trace the same schedule shape, so autotune flips between them (and
    f32) without recompiling structure.  Returns an
    :class:`InflightQuantized` handle for
    :func:`quantized_allreduce_finish`; ``finish(start(x))`` is the
    exact program :func:`quantized_allreduce_flat` traces."""
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"quantized allreduce supports SUM/AVERAGE, got {op}")
    wire = _check_wire(wire)
    ax = _single_axis(axis)
    block = block_size or qk.quant_block_size()
    n = _axis_size_static(ax)
    dtype = flat.dtype
    size = flat.shape[0]

    # Telemetry (trace time, path=jit — the compiled program executes the
    # wire hops): record the wire-format payload this bucket's program
    # moves per hop (1 B/elem int8 or 0.5 B/elem int4, + f32 block
    # scales).
    from ..telemetry import instrument as _ti
    from ..telemetry import flight_recorder as _frm

    sentinel = wire_sentinel(wire)
    payload = _leg_wire_bytes(wire, size, block)
    _rec = _ti.get_recorder()
    if _rec is not None:
        _rec.record_collective("allreduce", jnp.dtype(dtype).name,
                               sentinel, payload, path="jit", axis=ax)
    _flight = _frm.get_flight_recorder()
    if _flight is not None:
        _flight.record(op="allreduce", name="quantized.flat",
                       dtype=jnp.dtype(dtype).name, shape=(int(size),),
                       nbytes=int(payload),
                       wire=sentinel, path="jit", axis=ax)

    x = flat.astype(jnp.float32)
    if prescale_factor != 1.0:
        x = x * prescale_factor
    # Pad so the vector splits into n equal, block-aligned rank shards.
    shard = -(-size // (n * block)) * block
    total = shard * n
    if total != size:
        x = jnp.concatenate([x, jnp.zeros((total - size,), jnp.float32)])

    # Stage 1-2: quantize locally, reduce-scatter the wire format.  The
    # int4 payload rows are shard/2 packed bytes (block is even by
    # _check_wire + kernels' block % 2 check, so shard is too).
    if wire == "int4":
        q, scales = qk.quantize_flat_int4(x, block)
        q_rows = q.reshape(n, shard // 2)
    else:
        q, scales = qk.quantize_flat(x, block)
        q_rows = q.reshape(n, shard)
    s_rows = scales.reshape(n, shard // block)
    q_recv = lax.all_to_all(q_rows, ax, split_axis=0, concat_axis=0,
                            tiled=True)
    s_recv = lax.all_to_all(s_rows, ax, split_axis=0, concat_axis=0,
                            tiled=True)
    return InflightQuantized(q_recv=q_recv, s_recv=s_recv, axis=ax, op=op,
                             block=block, n=n, shard=shard, total=total,
                             size=size, dtype=dtype, wire=wire)


def _dequant_accumulate(inflight: InflightQuantized):
    """Stage 3, shared by finish and reduce-scatter finish: dequantize
    the n received wire shards and accumulate in f32 (never in wire
    precision — that would overflow and compound rounding)."""
    block, n, shard = inflight.block, inflight.n, inflight.shard
    q_recv, s_recv = inflight.q_recv, inflight.s_recv
    if inflight.wire == "int4":
        deq = qk.dequantize_flat_int4(q_recv.reshape(-1),
                                      s_recv.reshape(-1), block)
        acc = jnp.sum(deq.reshape(n, shard), axis=0)
    else:
        contrib = (q_recv.reshape(n, shard // block, block)
                   .astype(jnp.float32) * s_recv[:, :, None])
        acc = jnp.sum(contrib, axis=0).reshape(-1)
    if inflight.op == ReduceOp.AVERAGE:
        acc = acc * (1.0 / n)
    return acc


def quantized_allreduce_finish(inflight: InflightQuantized,
                               postscale_factor: float = 1.0):
    """Stages 3-5 of the quantized allreduce: dequantize-accumulate this
    rank's shard, requantize, reassemble in wire format, final
    dequantize.  Inverse bookend of :func:`quantized_allreduce_start`."""
    ax = inflight.axis
    block = inflight.block
    shard, total, size = inflight.shard, inflight.total, inflight.size
    dtype = inflight.dtype

    # Stage 3: dequantize-accumulate this rank's shard in f32.
    acc = _dequant_accumulate(inflight)

    # Stage 4-5: requantize, reassemble in wire format, final dequantize.
    # Reassembly is zero-embed + psum rather than all_gather: the
    # psum-family terminal op restores the *replicated* result type every
    # consumer of an allreduce expects (P() out_specs, optax.MultiSteps
    # cond-type stability — see device.invariant_allgather_shards for
    # the idiom), and the embedded regions are disjoint so the int8 sum
    # cannot overflow.  Costs 2(n-1)/n wire bytes on this hop vs the
    # allgather's (n-1)/n — total wire still well under the f32 ring.
    idx = lax.axis_index(ax)
    if inflight.wire == "int4":
        q_out, s_out = qk.quantize_flat_int4(acc, block)
        q_full = lax.psum(
            lax.dynamic_update_slice_in_dim(
                jnp.zeros((total // 2,), jnp.int8), q_out,
                idx * (shard // 2), axis=0),
            ax)
    else:
        q_out, s_out = qk.quantize_flat(acc, block)
        q_full = lax.psum(
            lax.dynamic_update_slice_in_dim(
                jnp.zeros((total,), jnp.int8), q_out, idx * shard, axis=0),
            ax)
    s_full = lax.psum(
        lax.dynamic_update_slice_in_dim(
            jnp.zeros((total // block,), jnp.float32), s_out,
            idx * (shard // block), axis=0),
        ax)
    if inflight.wire == "int4":
        out = qk.dequantize_flat_int4(q_full, s_full, block)
    else:
        out = qk.dequantize_flat(q_full, s_full, block)
    if postscale_factor != 1.0:
        out = out * postscale_factor
    if total != size:
        out = out[:size]
    return out.astype(dtype)


def quantized_reduce_scatter_start(flat, axis="dp",
                                   op: ReduceOp = ReduceOp.SUM,
                                   block_size: Optional[int] = None,
                                   prescale_factor: float = 1.0,
                                   wire: str = "int8"
                                   ) -> InflightQuantized:
    """The quantized-wire **reduce-scatter** half of the two-stage
    collective — stage 1-2 only (quantize + wire-format all_to_all).
    Identical to :func:`quantized_allreduce_start`; named separately
    because the ZeRO exchange (ops/zero.py) consumes the *shard*, never
    the reassembled vector: the established quant seam splits exactly at
    the reduce-scatter / dequant-accumulate boundary."""
    return quantized_allreduce_start(flat, axis, op, block_size,
                                     prescale_factor, wire=wire)


def quantized_reduce_scatter_finish(inflight: InflightQuantized):
    """Stage 3 only: dequantize-accumulate this rank's shard in f32 and
    return it (``[inflight.shard]`` elements, this rank's contiguous
    chunk of the padded vector) — no requantize, no reassembly.  The
    shard carries only stage-1 quantization error (each rank's block
    scale / 2); the ZeRO update consumes it directly and allgathers
    exact parameter deltas instead of a requantized gradient."""
    return _dequant_accumulate(inflight)


def quantized_allreduce_flat(flat, axis="dp",
                             op: ReduceOp = ReduceOp.AVERAGE,
                             block_size: Optional[int] = None,
                             prescale_factor: float = 1.0,
                             postscale_factor: float = 1.0,
                             wire: str = "int8"):
    """Allreduce one flat float vector over ``axis`` with the quantized
    wire (the bucket-level primitive ``fused_allreduce`` routes to).
    Valid inside shard_map where ``axis`` is bound; SUM/AVERAGE only
    (MIN/MAX etc. have no meaningful block-rescaled accumulation).
    Returns the reduced vector in the input dtype, replicated across
    ``axis``.

    Composition of :func:`quantized_allreduce_start` (quantize + wire
    reduce-scatter) and :func:`quantized_allreduce_finish`
    (dequant-accumulate + requantize + reassembly) — split so the
    overlap scheduler can pipeline bucket N's finish under bucket N+1's
    wire phase; calling this traces the identical monolithic program."""
    return quantized_allreduce_finish(
        quantized_allreduce_start(flat, axis, op, block_size,
                                  prescale_factor, wire=wire),
        postscale_factor)


def quantized_allreduce(tree, axis="dp", op: ReduceOp = ReduceOp.AVERAGE,
                        block_size: Optional[int] = None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0,
                        wire: str = "int8"):
    """Pytree convenience wrapper: every float leaf rides
    :func:`quantized_allreduce_flat` (flattened per leaf — for the
    bucketed hot path use ``ops.device.fused_allreduce`` with
    ``Compression.int8``, which concatenates leaves first); non-float
    leaves take the exact ``ops.device.allreduce``."""
    from ..ops import device as dev

    def _one(leaf):
        if jnp.issubdtype(jnp.result_type(leaf), jnp.floating):
            flat = jnp.ravel(leaf)
            red = quantized_allreduce_flat(
                flat, axis, op, block_size, prescale_factor,
                postscale_factor, wire=wire)
            return red.reshape(leaf.shape)
        return dev.allreduce(leaf, axis, op, prescale_factor,
                             postscale_factor)

    return jax.tree.map(_one, tree)


def eager_quantized_allreduce(tensor, name: Optional[str] = None,
                              op: ReduceOp = ReduceOp.AVERAGE,
                              block_size: Optional[int] = None,
                              process_set=None):
    """Host/eager-path quantized allreduce for the negotiated route (the
    torch grad-hook optimizer's data plane).

    The negotiated eager collective reduces ONE homogeneous buffer, so
    true mixed int8+f32 payloads cannot ride a single ``allreduce``;
    instead the wire carries an ``allgather`` of the packed per-rank
    wire bytes (int8 payload ‖ f32 scales) and each rank
    dequantize-accumulates locally — per-rank traffic
    ``(n-1)·size·(1+4/block)`` bytes, which beats the f32 ring's
    ``2(n-1)/n·4·size`` whenever n ≤ ~7 (past that, prefer
    ``Compression.int8``'s on-grid f32 simulation on the host path; the
    jit path always wins).  Returns a float ndarray like
    ``hvd.allreduce``."""
    import numpy as np

    from ..ops import eager

    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"quantized allreduce supports SUM/AVERAGE, got {op}")
    block = block_size or qk.quant_block_size()
    arr = np.asarray(tensor)
    shape, dtype = arr.shape, arr.dtype
    flat = arr.astype(np.float32).ravel()
    pad = (-flat.size) % block
    if pad:
        flat = np.concatenate([flat, np.zeros(pad, np.float32)])
    x2 = flat.reshape(-1, block)
    absmax = np.max(np.abs(x2), axis=1, keepdims=True)
    scale = absmax * (1.0 / 127.0)
    with np.errstate(divide="ignore", invalid="ignore"):
        inv = np.where(scale > 0, 1.0 / np.where(scale > 0, scale, 1.0), 0.0)
    q = np.clip(np.rint(x2 * inv), -127, 127).astype(np.int8)
    # Pack payload ‖ scale bytes into one uint8 wire buffer per rank.
    packed = np.concatenate(
        [q.reshape(-1).view(np.uint8),
         scale[:, 0].astype(np.float32).view(np.uint8)])
    from ..telemetry import instrument as _ti

    _rec = _ti.get_recorder()
    if _rec is not None:
        # Wire-format accounting under the quantized label; the generic
        # eager counter also books the allgather under its own
        # op=allgather/dtype=uint8 label (different label set, not a
        # double count of the same series).
        _rec.record_collective("allreduce", str(dtype), INT8_WIRE,
                               packed.size, path="eager")
    from ..telemetry import flight_recorder as _frm

    _flight = _frm.get_flight_recorder()
    _fr_seq = None
    if _flight is not None:
        _fr_seq = _flight.record_begin(
            op="allreduce", name=name or "quantized.eager",
            dtype=str(dtype), shape=shape, nbytes=int(packed.size),
            wire=INT8_WIRE, path="eager")
    try:
        gathered = eager.allgather(packed, name=name and f"{name}.q8",
                                   process_set=process_set)
    except Exception:
        if _flight is not None:
            _flight.record_end(_fr_seq, status="error")
        raise
    if _flight is not None:
        _flight.record_end(_fr_seq)
    per_rank = np.asarray(gathered).reshape(-1, packed.size)
    n = per_rank.shape[0]
    nblocks = x2.shape[0]
    acc = np.zeros(nblocks * block, np.float32)
    for r in range(n):
        payload = per_rank[r, :nblocks * block].view(np.int8)
        scales_r = per_rank[r, nblocks * block:].view(np.float32)
        acc += (payload.reshape(nblocks, block).astype(np.float32)
                * scales_r[:, None]).reshape(-1)
    if op == ReduceOp.AVERAGE:
        acc /= n
    if pad:
        acc = acc[:-pad]
    return acc.reshape(shape).astype(dtype)
