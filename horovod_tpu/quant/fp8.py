"""Per-tensor-scaled fp8 (e4m3) matmul — the low-precision COMPUTE leg.

Where the int8/int4 wire (quant/kernels, quant/collectives) shrinks
communication, this module shrinks the matmul itself: weights and
activations are scaled into ``float8_e4m3fn`` per tensor and the MXU/
dot runs on the 8-bit operands with f32 accumulation
(``preferred_element_type``), the pattern XLA fuses into a native fp8
convert-dot on hardware with fp8 support.

Scaling is symmetric per-tensor ``amax / E4M3_MAX``: e4m3 has no inf
and a max finite value of 448, so anything scaled into [-448, 448]
survives the cast.  Two ways to supply ``amax``:

* **current-max** (default): ``stop_gradient(max|x|)`` of this very
  operand — one extra reduction per matmul, always correct.
* **delayed-max** (:class:`Fp8AmaxState`, :func:`fp8_matmul_delayed`):
  the rolling max of the last N steps' amaxes, the Transformer-Engine
  recipe — the scale is known BEFORE the operand is produced, so the
  cast fuses with the producer.  Out-of-history spikes clip for one
  step; the history catches up the next.

Gate: ``HVDT_FP8=off|matmul`` (:func:`matmul_enabled`), consumed by the
transformer's MLP and attention projections.  Capability is probed at
first use (:func:`fp8_available`): the dtype must exist AND a tiny
jitted fp8 ``dot_general`` must actually execute on the default
backend.  Probe failure ⇒ :func:`fp8_matmul` IS ``x @ w`` — the gate is
a provable no-op (identity-tested) on builds without fp8, e.g. older
jax or backends that reject f8 convert-dots.  The container's jax
0.4.37 CPU build passes the probe, so tests exercise the real
convert-dot lowering (``f8e4m3`` in the HLO) everywhere.
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from ..common import config

__all__ = [
    "E4M3_MAX",
    "fp8_available",
    "fp8_mode",
    "matmul_enabled",
    "fp8_matmul",
    "Fp8AmaxState",
    "init_amax_state",
    "fp8_matmul_delayed",
]

# Max finite |value| of float8_e4m3fn (no inf encoding; 0x7E = 448).
E4M3_MAX = 448.0

_FP8_MODES = ("off", "matmul")

_probe_result: Optional[bool] = None


def _fp8_dtype():
    return getattr(jnp, "float8_e4m3fn", None)


def fp8_available() -> bool:
    """True when ``float8_e4m3fn`` exists and an fp8 ``dot_general``
    actually executes on the default backend (probed once per process:
    dtype presence alone doesn't guarantee the backend accepts f8
    convert-dots)."""
    global _probe_result
    if _probe_result is None:
        dt = _fp8_dtype()
        if dt is None:
            _probe_result = False
        else:
            try:
                a = jnp.ones((8, 8), dt)
                f = jax.jit(lambda x, y: jax.lax.dot_general(
                    x, y, (((1,), (0,)), ((), ())),
                    preferred_element_type=jnp.float32))
                jax.block_until_ready(f(a, a))
                _probe_result = True
            except Exception:
                _probe_result = False
    return _probe_result


def fp8_mode() -> str:
    """The validated ``HVDT_FP8`` value."""
    mode = (config.get_str("HVDT_FP8") or "off").lower()
    if mode not in _FP8_MODES:
        raise ValueError(
            f"unknown HVDT_FP8 mode {mode!r}; valid: "
            f"{', '.join(_FP8_MODES)}")
    return mode


def matmul_enabled() -> bool:
    """True when matmuls should ride the fp8 path: ``HVDT_FP8=matmul``
    AND the capability probe passes."""
    return fp8_mode() == "matmul" and fp8_available()


def _scale_for(amax):
    """Per-tensor scale mapping ``[-amax, amax]`` onto the e4m3 range;
    all-zero tensors get scale 1 (q = 0 exactly, no 0/0)."""
    amax = jnp.maximum(amax.astype(jnp.float32), 0.0)
    return jnp.where(amax > 0, amax * (1.0 / E4M3_MAX), 1.0)


def _cast_e4m3(x, scale):
    # Clip before the convert: values past ±448 would otherwise land on
    # e4m3 NaN (no inf encoding).
    dt = _fp8_dtype()
    y = jnp.clip(x.astype(jnp.float32) / scale, -E4M3_MAX, E4M3_MAX)
    return y.astype(dt)


def fp8_matmul(x, w, amax_x=None, amax_w=None):
    """``x @ w`` with both operands per-tensor-scaled into e4m3 and f32
    accumulation; result in ``x``'s dtype.  ``x`` is ``[..., k]``, ``w``
    is ``[k, n]`` (the transformer projection shape).

    ``amax_x`` / ``amax_w`` override the current-max statistics (the
    delayed-scaling hook); by default each operand's own
    ``stop_gradient(max|·|)`` is used.  When fp8 is unavailable this IS
    the plain matmul — same dtype, same math."""
    if not fp8_available():
        return x @ w.astype(x.dtype)
    if amax_x is None:
        amax_x = jax.lax.stop_gradient(jnp.max(jnp.abs(x)))
    if amax_w is None:
        amax_w = jax.lax.stop_gradient(jnp.max(jnp.abs(w)))
    sx = _scale_for(jnp.asarray(amax_x))
    sw = _scale_for(jnp.asarray(amax_w))
    qx = _cast_e4m3(x, sx)
    qw = _cast_e4m3(w, sw)
    nd = qx.ndim
    out = jax.lax.dot_general(
        qx, qw, (((nd - 1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32)
    return (out * (sx * sw)).astype(x.dtype)


class Fp8AmaxState(NamedTuple):
    """Delayed-max scaling state for ONE matmul site: rolling amax
    history per operand (f32 ``[history]``, newest last)."""
    x: Any
    w: Any


def init_amax_state(history: int = 16) -> Fp8AmaxState:
    """Fresh all-zero history (zero amax ⇒ scale 1 on step 0; real
    statistics take over as the history fills)."""
    return Fp8AmaxState(x=jnp.zeros((history,), jnp.float32),
                        w=jnp.zeros((history,), jnp.float32))


def fp8_matmul_delayed(x, w, state: Fp8AmaxState
                       ) -> Tuple[jax.Array, Fp8AmaxState]:
    """``x @ w`` scaled by the HISTORY's max (Transformer-Engine delayed
    scaling) and the rolled-forward state carrying this step's observed
    amaxes.  Functional: thread the state like any optimizer state."""
    if not fp8_available():
        return x @ w.astype(x.dtype), state
    ax = jax.lax.stop_gradient(jnp.max(jnp.abs(x)).astype(jnp.float32))
    aw = jax.lax.stop_gradient(jnp.max(jnp.abs(w)).astype(jnp.float32))
    # Scale from history ∪ current: never a stale zero on the first
    # step, never more than one step behind after that.
    out = fp8_matmul(x, w,
                     amax_x=jnp.maximum(jnp.max(state.x), ax),
                     amax_w=jnp.maximum(jnp.max(state.w), aw))
    new = Fp8AmaxState(
        x=jnp.concatenate([state.x[1:], ax[None]]),
        w=jnp.concatenate([state.w[1:], aw[None]]))
    return out, new
