"""Block-scaled symmetric int8/int4 quantize/dequantize kernels.

The wire-format primitives of the quantized-collective subsystem
(EQuARX, arxiv 2506.17615: block-scaled quantization inside the
allreduce roughly halves wire bytes vs bf16 at negligible quality
loss, and a 4-bit grid roughly halves that again when error feedback
absorbs the coarser rounding).  int8 format: a flat float vector is
cut into fixed-size blocks (``HVDT_QUANT_BLOCK`` elements); each block
carries one f32 scale ``absmax / 127`` and its elements as symmetric
int8 ``round(x / scale)`` clipped to [-127, 127].  Wire bytes per
element: 1 + 4/block (vs 4 for f32) — ~3.9x smaller at the default
block 256.  int4 format: same block grid, scale ``absmax / 7``,
elements clipped to [-7, 7] and packed two lanes per int8 byte —
0.5 + 4/block B/elem, ~0.51x of the int8 wire at block 256.

int4 packing is half-split, not adjacent-pair: byte ``j`` of a block
carries element ``j`` in its low nibble and element ``j + block/2`` in
its high nibble, so pack/unpack are contiguous half-block slices plus
lane-local shifts — Mosaic-friendly (no strided sublane gathers).

Two lowerings with identical math (the optim_kernels pattern):

* Pallas kernels (:func:`_quantize_pallas` / :func:`_dequantize_pallas`)
  — one VMEM-resident pass computes per-block absmax, scale and the
  int8 payload together, no separate HBM pass for the statistics.
  Tiling: blocks are ``[nblocks, block]`` 2D with ``block`` a multiple
  of 128 lanes; the int8 payload needs the (32, 128) int8 sublane tile,
  so block-rows-per-program is clamped to a power-of-2 divisor of
  ``nblocks`` >= 32 (:func:`quant_kernel_eligible` gates exactly this,
  platform-independently, so CPU exercises the same eligible/fallback
  split as TPU).  Off-TPU the kernels run under ``interpret=True``.
* Pure-XLA fallback (:func:`_quantize_xla` / :func:`_dequantize_xla`)
  — same formulas; the default on CPU (``HVDT_QUANT_KERNELS=auto``)
  where interpret-mode would be needlessly slow on the hot path.

``HVDT_QUANT_KERNELS``: ``auto`` (Pallas on TPU, XLA elsewhere), ``on``
(force Pallas — interpret mode off-TPU; what the kernel-equivalence
tests use), ``off`` (XLA everywhere).

API-guarded for older JAX (container runs jax 0.4.37): no
``jax.typeof`` / vma kwargs are required here — quantize runs on
already-flat bucket values inside the collective, and the pallas_call
carries no out-shape vma (``pallas_kernels._vma_kw`` degrades to ``{}``
on such builds).
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp

from ..common import config
from ..ops.pallas_kernels import _use_interpret, _vma_kw

__all__ = [
    "quant_block_size",
    "quant_kernel_eligible",
    "quant_kernel_eligible_int4",
    "quantize_flat",
    "dequantize_flat",
    "quantize_dequantize",
    "quantize_flat_int4",
    "dequantize_flat_int4",
    "quantize_dequantize_int4",
    "wire_bytes",
    "wire_bytes_int4",
]

_LANES = 128
# int8 payload tile is (32, 128); f32 operands need only (8, 128) — the
# int8 floor dominates.
_INT8_SUBLANE = 32
# Block-rows per grid program cap: 32 rows x 4096-elem blocks x 4 B (f32
# view) = 512 KiB/operand — comfortable VMEM with double buffering.
_BLOCK_ROWS = 32


def quant_block_size() -> int:
    """The block-scaling granularity (``HVDT_QUANT_BLOCK``, default 256
    elements: 1.6% scale overhead, fine-grained enough that one outlier
    only coarsens its own 256 neighbours)."""
    block = config.get_int("HVDT_QUANT_BLOCK")
    return block if block > 0 else 256


def _kernels_on() -> bool:
    mode = config.get_str("HVDT_QUANT_KERNELS").lower()
    if mode == "on":
        return True
    if mode == "off":
        return False
    return not _use_interpret()  # auto: real Mosaic lowering only


def quant_kernel_eligible(size: int, block: int) -> bool:
    """True when a ``size``-element flat vector in ``block``-element
    blocks can take the Pallas lowering: whole blocks only, lane-aligned
    block, and a power-of-2 block-row divisor clearing the int8 sublane
    tile.  Platform-independent on purpose (see module docstring)."""
    if block <= 0 or block % _LANES or size <= 0 or size % block:
        return False
    nblocks = size // block
    return (nblocks & -nblocks) >= _INT8_SUBLANE


def _block_rows(nblocks: int) -> int:
    return min(_BLOCK_ROWS, nblocks & -nblocks)


# ---- shared math ---------------------------------------------------------


def _scale_and_q(x2):
    """Per-block-row scale + int8 payload; identical text in both
    lowerings so they can only differ by reduction association."""
    absmax = jnp.max(jnp.abs(x2), axis=-1, keepdims=True)
    scale = absmax * (1.0 / 127.0)
    # All-zero block: scale 0 — force q = 0 instead of 0/0.
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    q = jnp.clip(jnp.round(x2 * inv), -127.0, 127.0).astype(jnp.int8)
    return scale, q


# ---- pure-XLA lowering ---------------------------------------------------


def _quantize_xla(x2):
    scale, q = _scale_and_q(x2)
    return q, scale[:, 0]


def _dequantize_xla(q2, scales):
    return q2.astype(jnp.float32) * scales[:, None]


# ---- Pallas lowering -----------------------------------------------------


def _quant_kernel(x_ref, q_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)
    scale, q = _scale_and_q(x)
    q_ref[...] = q
    # Scale output is lane-broadcast to [rows, 128] so the f32 output
    # keeps a legal Mosaic tile; the caller reads lane 0.
    s_ref[...] = jnp.broadcast_to(scale, s_ref.shape)


def _dequant_kernel(q_ref, s_ref, o_ref):
    o_ref[...] = q_ref[...].astype(jnp.float32) * s_ref[..., :1]


def _quantize_pallas(x2):
    import jax.experimental.pallas as pl

    nblocks, block = x2.shape
    br = _block_rows(nblocks)
    kw = _vma_kw(x2)
    spec = pl.BlockSpec((br, block), lambda i: (i, 0))
    sspec = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    q, s = pl.pallas_call(
        _quant_kernel,
        grid=(nblocks // br,),
        in_specs=[spec],
        out_specs=[spec, sspec],
        out_shape=(jax.ShapeDtypeStruct((nblocks, block), jnp.int8, **kw),
                   jax.ShapeDtypeStruct((nblocks, _LANES), jnp.float32,
                                        **kw)),
        interpret=_use_interpret(),
    )(x2)
    return q, s[:, 0]


def _dequantize_pallas(q2, scales):
    import jax.experimental.pallas as pl

    nblocks, block = q2.shape
    br = _block_rows(nblocks)
    s2 = jnp.broadcast_to(scales[:, None], (nblocks, _LANES))
    kw = _vma_kw(q2, scales)
    spec = pl.BlockSpec((br, block), lambda i: (i, 0))
    sspec = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    return pl.pallas_call(
        _dequant_kernel,
        grid=(nblocks // br,),
        in_specs=[spec, sspec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((nblocks, block), jnp.float32, **kw),
        interpret=_use_interpret(),
    )(q2, s2)


# ---- public API ----------------------------------------------------------


def quantize_flat(flat, block_size: Optional[int] = None,
                  use_kernels: Optional[bool] = None
                  ) -> Tuple[jax.Array, jax.Array]:
    """Quantize a flat float vector whose size divides into whole
    blocks.  Returns ``(q, scales)``: int8 ``[size]`` and f32
    ``[size // block]``.  Callers own padding (the collective pads to
    rank-shard boundaries anyway; :func:`quantize_dequantize` pads for
    arbitrary shapes)."""
    block = block_size or quant_block_size()
    if flat.ndim != 1:
        raise ValueError(f"quantize_flat takes a 1-D vector, got "
                         f"shape {flat.shape}")
    if flat.size % block:
        raise ValueError(
            f"size {flat.size} is not a whole number of {block}-element "
            "blocks — pad first (quantize_dequantize does)")
    x2 = flat.astype(jnp.float32).reshape(-1, block)
    if use_kernels is None:
        use_kernels = _kernels_on()
    if use_kernels and quant_kernel_eligible(flat.size, block):
        q2, scales = _quantize_pallas(x2)
    else:
        q2, scales = _quantize_xla(x2)
    return q2.reshape(-1), scales


def dequantize_flat(q, scales, block_size: Optional[int] = None,
                    use_kernels: Optional[bool] = None) -> jax.Array:
    """Inverse of :func:`quantize_flat`; returns f32 ``[size]``."""
    block = block_size or quant_block_size()
    q2 = q.reshape(-1, block)
    if use_kernels is None:
        use_kernels = _kernels_on()
    if use_kernels and quant_kernel_eligible(q.size, block):
        out = _dequantize_pallas(q2, scales)
    else:
        out = _dequantize_xla(q2, scales)
    return out.reshape(-1)


def quantize_dequantize(x, block_size: Optional[int] = None,
                        use_kernels: Optional[bool] = None):
    """Round-trip an arbitrary-shape float array through the wire
    format (pad → quantize → dequantize → unpad), returning it in the
    input dtype.  This IS the value the wire would carry — error
    feedback subtracts it from the true gradient, and the host
    (eager/torch) path sends it in place of real int8 payloads."""
    block = block_size or quant_block_size()
    shape, dtype = x.shape, x.dtype
    flat = jnp.ravel(x).astype(jnp.float32)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    q, scales = quantize_flat(flat, block, use_kernels)
    out = dequantize_flat(q, scales, block, use_kernels)
    if pad:
        out = out[:-pad]
    return out.reshape(shape).astype(dtype)


def wire_bytes(size: int, block_size: Optional[int] = None) -> int:
    """Bytes the wire format occupies for ``size`` elements: 1 B/elem
    payload + one f32 scale per (padded) block.  The accounting the
    bench and BENCH trajectory use."""
    block = block_size or quant_block_size()
    nblocks = -(-size // block)
    return nblocks * block + nblocks * 4


# ---- int4 wire -----------------------------------------------------------


def quant_kernel_eligible_int4(size: int, block: int) -> bool:
    """int4 Pallas eligibility: the int8 conditions plus a lane-aligned
    *packed* half-block (``block % 256 == 0``) so the [rows, block/2]
    int8 payload keeps a legal tile.  The default block 256 qualifies;
    smaller blocks take the identical-math XLA fallback."""
    return (quant_kernel_eligible(size, block)
            and (block // 2) % _LANES == 0)


def _scale_and_q4(x2):
    """Per-block-row scale + unpacked 4-bit codes (int32 lanes, one
    element per lane — packing is a separate step so both lowerings
    share this text)."""
    absmax = jnp.max(jnp.abs(x2), axis=-1, keepdims=True)
    scale = absmax * (1.0 / 7.0)
    inv = jnp.where(scale > 0, 1.0 / jnp.where(scale > 0, scale, 1.0), 0.0)
    q = jnp.clip(jnp.round(x2 * inv), -7.0, 7.0).astype(jnp.int32)
    return scale, q


def _pack4(q):
    """[..., block] int32 4-bit codes -> [..., block/2] int8 bytes:
    element j in the low nibble, element j + block/2 in the high one
    (half-split layout; see module docstring).  Two's-complement
    masking keeps negative codes exact: (-7 & 0xF) = 9."""
    half = q.shape[-1] // 2
    lo = q[..., :half] & 0xF
    hi = q[..., half:] & 0xF
    v = lo | (hi << 4)
    return jnp.where(v >= 128, v - 256, v).astype(jnp.int8)


def _unpack4(p):
    """Inverse of :func:`_pack4`; returns [..., block] int32 codes in
    [-7, 7] (well, [-8, 7] for arbitrary bytes)."""
    b = p.astype(jnp.int32)
    b = jnp.where(b < 0, b + 256, b)
    lo = b & 0xF
    hi = (b >> 4) & 0xF
    sext = lambda x: jnp.where(x >= 8, x - 16, x)  # noqa: E731
    return jnp.concatenate([sext(lo), sext(hi)], axis=-1)


def _quantize4_xla(x2):
    scale, q = _scale_and_q4(x2)
    return _pack4(q), scale[:, 0]


def _dequantize4_xla(p2, scales):
    return _unpack4(p2).astype(jnp.float32) * scales[:, None]


def _quant4_kernel(x_ref, p_ref, s_ref):
    x = x_ref[...].astype(jnp.float32)
    scale, q = _scale_and_q4(x)
    p_ref[...] = _pack4(q)
    s_ref[...] = jnp.broadcast_to(scale, s_ref.shape)


def _dequant4_kernel(p_ref, s_ref, o_ref):
    o_ref[...] = _unpack4(p_ref[...]).astype(jnp.float32) * s_ref[..., :1]


def _quantize4_pallas(x2):
    import jax.experimental.pallas as pl

    nblocks, block = x2.shape
    br = _block_rows(nblocks)
    kw = _vma_kw(x2)
    spec = pl.BlockSpec((br, block), lambda i: (i, 0))
    pspec = pl.BlockSpec((br, block // 2), lambda i: (i, 0))
    sspec = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    p, s = pl.pallas_call(
        _quant4_kernel,
        grid=(nblocks // br,),
        in_specs=[spec],
        out_specs=[pspec, sspec],
        out_shape=(jax.ShapeDtypeStruct((nblocks, block // 2), jnp.int8,
                                        **kw),
                   jax.ShapeDtypeStruct((nblocks, _LANES), jnp.float32,
                                        **kw)),
        interpret=_use_interpret(),
    )(x2)
    return p, s[:, 0]


def _dequantize4_pallas(p2, scales):
    import jax.experimental.pallas as pl

    nblocks, half = p2.shape
    br = _block_rows(nblocks)
    s2 = jnp.broadcast_to(scales[:, None], (nblocks, _LANES))
    kw = _vma_kw(p2, scales)
    pspec = pl.BlockSpec((br, half), lambda i: (i, 0))
    spec = pl.BlockSpec((br, 2 * half), lambda i: (i, 0))
    sspec = pl.BlockSpec((br, _LANES), lambda i: (i, 0))
    return pl.pallas_call(
        _dequant4_kernel,
        grid=(nblocks // br,),
        in_specs=[pspec, sspec],
        out_specs=spec,
        out_shape=jax.ShapeDtypeStruct((nblocks, 2 * half), jnp.float32,
                                       **kw),
        interpret=_use_interpret(),
    )(p2, s2)


def quantize_flat_int4(flat, block_size: Optional[int] = None,
                       use_kernels: Optional[bool] = None
                       ) -> Tuple[jax.Array, jax.Array]:
    """int4 sibling of :func:`quantize_flat`.  Returns ``(packed,
    scales)``: int8 ``[size // 2]`` (two 4-bit lanes per byte,
    half-split layout) and f32 ``[size // block]``."""
    block = block_size or quant_block_size()
    if flat.ndim != 1:
        raise ValueError(f"quantize_flat_int4 takes a 1-D vector, got "
                         f"shape {flat.shape}")
    if block % 2:
        raise ValueError(f"int4 wire needs an even block size, got {block}")
    if flat.size % block:
        raise ValueError(
            f"size {flat.size} is not a whole number of {block}-element "
            "blocks — pad first (quantize_dequantize_int4 does)")
    x2 = flat.astype(jnp.float32).reshape(-1, block)
    if use_kernels is None:
        use_kernels = _kernels_on()
    if use_kernels and quant_kernel_eligible_int4(flat.size, block):
        p2, scales = _quantize4_pallas(x2)
    else:
        p2, scales = _quantize4_xla(x2)
    return p2.reshape(-1), scales


def dequantize_flat_int4(packed, scales, block_size: Optional[int] = None,
                         use_kernels: Optional[bool] = None) -> jax.Array:
    """Inverse of :func:`quantize_flat_int4`; ``packed`` holds
    ``size // 2`` bytes, returns f32 ``[size]``."""
    block = block_size or quant_block_size()
    p2 = packed.reshape(-1, block // 2)
    if use_kernels is None:
        use_kernels = _kernels_on()
    if use_kernels and quant_kernel_eligible_int4(2 * packed.size, block):
        out = _dequantize4_pallas(p2, scales)
    else:
        out = _dequantize4_xla(p2, scales)
    return out.reshape(-1)


def quantize_dequantize_int4(x, block_size: Optional[int] = None,
                             use_kernels: Optional[bool] = None):
    """int4 sibling of :func:`quantize_dequantize`: the value the 4-bit
    wire would carry, in the input shape/dtype — what error feedback
    subtracts on the int4 leg."""
    block = block_size or quant_block_size()
    shape, dtype = x.shape, x.dtype
    flat = jnp.ravel(x).astype(jnp.float32)
    pad = (-flat.size) % block
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), jnp.float32)])
    p, scales = quantize_flat_int4(flat, block, use_kernels)
    out = dequantize_flat_int4(p, scales, block, use_kernels)
    if pad:
        out = out[:-pad]
    return out.reshape(shape).astype(dtype)


def wire_bytes_int4(size: int, block_size: Optional[int] = None) -> int:
    """int4 wire accounting: 0.5 B/elem payload + one f32 scale per
    (padded) block — ~0.51x of :func:`wire_bytes` at block 256."""
    block = block_size or quant_block_size()
    nblocks = -(-size // block)
    return nblocks * (block // 2) + nblocks * 4
