"""Quantized collectives and low-precision compute — int8/int4 wire,
fp8 matmul.

The gradient wire path's third and fourth compression tiers (after
bf16/fp16 casts, ops/compression.py): EQuARX-style (arxiv 2506.17615)
block-scaled symmetric int8 — and packed sub-byte int4 — with
per-block f32 absmax scales, reduced in two quantized hops
(reduce-scatter in wire format → f32 dequant-accumulate → requantize →
allgather), with optax-compatible error feedback so convergence
matches the f32 wire.  :mod:`.fp8` adds the compute-side leg: e4m3
per-tensor-scaled matmuls (``HVDT_FP8=matmul``) with f32 accumulation.

Layout:

* :mod:`.kernels` — quantize/dequantize as Pallas kernels (one
  VMEM-resident pass, interpret-mode off-TPU) with an identical-math
  pure-XLA fallback; ``HVDT_QUANT_BLOCK`` / ``HVDT_QUANT_KERNELS``.
* :mod:`.collectives` — the two-stage quantized allreduce for the jit
  path (wired into ``fused_allreduce`` as the ``Compression.int8``
  wire mode) plus an eager/host variant for the torch grad-hook route.
* :mod:`.error_feedback` — ``with_error_feedback(tx)`` residual
  accumulator carrying quantization error into the next step.

Selection: ``DistributedOptimizer(compression=hvd.Compression.int8)``
(or ``.int4``), env-wide via ``HVDT_COMPRESSION=int8|int4`` /
``HVDT_QUANT=1``; the autotuner can A/B the f32/int8/int4 legs online
with ``HVDT_AUTOTUNE_QUANT=1`` (state-compatible hot-swap legs).
"""

from __future__ import annotations

from .kernels import (  # noqa: F401
    quant_block_size,
    quant_kernel_eligible,
    quant_kernel_eligible_int4,
    quantize_flat,
    dequantize_flat,
    quantize_dequantize,
    quantize_flat_int4,
    dequantize_flat_int4,
    quantize_dequantize_int4,
    wire_bytes,
    wire_bytes_int4,
)
from .collectives import (  # noqa: F401
    INT8_WIRE,
    INT4_WIRE,
    quant_wire_leg,
    wire_sentinel,
    quantized_allreduce,
    quantized_allreduce_flat,
    eager_quantized_allreduce,
)
from .error_feedback import (  # noqa: F401
    ErrorFeedbackState,
    with_error_feedback,
    tile_residual,
    stack_residual,
    unstack_residual,
)
from .fp8 import (  # noqa: F401
    E4M3_MAX,
    Fp8AmaxState,
    fp8_available,
    fp8_matmul,
    fp8_matmul_delayed,
    init_amax_state,
)

__all__ = [
    "quant_block_size",
    "quant_kernel_eligible",
    "quant_kernel_eligible_int4",
    "quantize_flat",
    "dequantize_flat",
    "quantize_dequantize",
    "quantize_flat_int4",
    "dequantize_flat_int4",
    "quantize_dequantize_int4",
    "wire_bytes",
    "wire_bytes_int4",
    "INT8_WIRE",
    "INT4_WIRE",
    "quant_wire_leg",
    "wire_sentinel",
    "quantized_allreduce",
    "quantized_allreduce_flat",
    "eager_quantized_allreduce",
    "ErrorFeedbackState",
    "with_error_feedback",
    "tile_residual",
    "stack_residual",
    "unstack_residual",
    "E4M3_MAX",
    "Fp8AmaxState",
    "fp8_available",
    "fp8_matmul",
    "fp8_matmul_delayed",
    "init_amax_state",
]
