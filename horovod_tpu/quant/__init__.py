"""Quantized collective communication — block-scaled int8 wire format.

The gradient wire path's third compression tier (after bf16/fp16
casts, ops/compression.py): EQuARX-style (arxiv 2506.17615)
block-scaled symmetric int8 with per-block f32 absmax scales, reduced
in two quantized hops (reduce-scatter in wire format → f32
dequant-accumulate → requantize → allgather), with optax-compatible
error feedback so convergence matches the f32 wire.

Layout:

* :mod:`.kernels` — quantize/dequantize as Pallas kernels (one
  VMEM-resident pass, interpret-mode off-TPU) with an identical-math
  pure-XLA fallback; ``HVDT_QUANT_BLOCK`` / ``HVDT_QUANT_KERNELS``.
* :mod:`.collectives` — the two-stage quantized allreduce for the jit
  path (wired into ``fused_allreduce`` as the ``Compression.int8``
  wire mode) plus an eager/host variant for the torch grad-hook route.
* :mod:`.error_feedback` — ``with_error_feedback(tx)`` residual
  accumulator carrying quantization error into the next step.

Selection: ``DistributedOptimizer(compression=hvd.Compression.int8)``,
or env-wide via ``HVDT_COMPRESSION=int8`` / ``HVDT_QUANT=1``; the
autotuner can A/B the wire online with ``HVDT_AUTOTUNE_QUANT=1``
(state-compatible hot-swap legs).
"""

from __future__ import annotations

from .kernels import (  # noqa: F401
    quant_block_size,
    quant_kernel_eligible,
    quantize_flat,
    dequantize_flat,
    quantize_dequantize,
    wire_bytes,
)
from .collectives import (  # noqa: F401
    INT8_WIRE,
    quantized_allreduce,
    quantized_allreduce_flat,
    eager_quantized_allreduce,
)
from .error_feedback import (  # noqa: F401
    ErrorFeedbackState,
    with_error_feedback,
    tile_residual,
    stack_residual,
    unstack_residual,
)

__all__ = [
    "quant_block_size",
    "quant_kernel_eligible",
    "quantize_flat",
    "dequantize_flat",
    "quantize_dequantize",
    "wire_bytes",
    "INT8_WIRE",
    "quantized_allreduce",
    "quantized_allreduce_flat",
    "eager_quantized_allreduce",
    "ErrorFeedbackState",
    "with_error_feedback",
    "tile_residual",
    "stack_residual",
    "unstack_residual",
]
