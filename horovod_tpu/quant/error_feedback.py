"""Error feedback for quantized gradient communication.

1-bit SGD / EF-SGD lineage (Seide et al.; Karimireddy et al.): when the
wire carries a lossy gradient, add the quantization error back into the
NEXT step's gradient instead of dropping it.  The compressed sequence
then converges like the exact one — the error is carried, not
compounded — which is what lets the int8 wire match the f32-wire loss
trajectory (tests/test_quant.py proves the 200-step MLP parity).

Mechanics per step, per leaf (f32 residual state):

    e        = grad + residual          # error-compensated gradient
    sent     = Q(e)                     # on-grid value the wire carries
    residual = e - sent                 # local quantization error
    inner.update(sent, ...)             # comm chain + optimizer see `sent`

``sent`` is computed with :func:`..quant.kernels.quantize_dequantize` —
exactly the stage-1 wire value, so the first collective hop
(reduce-scatter of the already-on-grid payload) is lossless; only the
post-reduction requantize in stage 4 contributes fresh error, bounded
by the *reduced* gradient's block scale.

``enabled=False`` keeps the identical state tree (residual stays all
zeros and ``sent = e``) — that is what makes the autotuner's int8/f32
wire legs hot-swappable mid-run with one optimizer state
(``AutotunedStep``'s ``quant=`` dimension relies on it, the same
state-compatibility contract as ops/optim_kernels' ``use_kernels``).
"""

from __future__ import annotations

from typing import Any, NamedTuple, Optional

import jax
import jax.numpy as jnp

from . import kernels as qk

__all__ = ["ErrorFeedbackState", "with_error_feedback",
           "tile_residual", "stack_residual", "unstack_residual"]


class ErrorFeedbackState(NamedTuple):
    residual: Any   # f32 pytree of carried quantization error
    inner: Any      # wrapped transformation's state


# The residual is PER-RANK state (each worker carries its own
# quantization error), while ``inner`` stays replicated (it only sees
# post-collective values).  Under shard_map that means the residual
# crosses the boundary stacked over the dp axis — in_specs/out_specs
# P(axis) on the residual, P() on everything else.  These helpers
# implement the pattern (docs/performance.md shows the full loop):


def tile_residual(state: ErrorFeedbackState, n: int) -> ErrorFeedbackState:
    """Prepare a freshly init'd state for an ``n``-rank shard_map carry:
    residual leaves gain a leading [n] axis (identical zero copies)."""
    return state._replace(residual=jax.tree.map(
        lambda t: jnp.tile(t[None], (n,) + (1,) * t.ndim),
        state.residual))


def unstack_residual(state: ErrorFeedbackState) -> ErrorFeedbackState:
    """Inside the shard_map body: drop this rank's leading [1] axis."""
    return state._replace(
        residual=jax.tree.map(lambda t: t[0], state.residual))


def stack_residual(state: ErrorFeedbackState) -> ErrorFeedbackState:
    """Inside the shard_map body: re-add the leading [1] axis so the
    residual exits through a P(axis) out_spec."""
    return state._replace(
        residual=jax.tree.map(lambda t: jnp.asarray(t)[None],
                              state.residual))


def with_error_feedback(inner, block_size: Optional[int] = None,
                        enabled: bool = True, wire: str = "int8"):
    """Wrap an optax ``GradientTransformation`` (typically the whole
    ``DistributedOptimizer(..., compression=Compression.int8)`` chain)
    with a quantization-error residual accumulator::

        tx = hvd.quant.with_error_feedback(
            hvd.DistributedOptimizer(optax.adam(1e-3),
                                     compression=hvd.Compression.int8))
        state = tx.init(params)
        updates, state = tx.update(grads, state, params)

    Args:
      inner: the transformation receiving the on-grid gradients.
      block_size: wire block size (default ``HVDT_QUANT_BLOCK``).
      enabled: with False, gradients pass through untouched and the
        residual stays zero — same state STRUCTURE, exact math; the
        f32-wire leg of a quant A/B.
      wire: which quantization grid ``sent`` rides — ``"int8"`` or
        ``"int4"``.  The residual tree is plain f32 ``zeros_like``
        leaves on EVERY leg, so int8↔int4↔f32 hot-swaps carry the
        accumulated error across without restructuring state.
    """
    import optax

    if wire not in ("int8", "int4"):
        raise ValueError(
            f"with_error_feedback wire must be 'int8' or 'int4', "
            f"got {wire!r}")
    qdq = (qk.quantize_dequantize_int4 if wire == "int4"
           else qk.quantize_dequantize)

    def init_fn(params):
        residual = jax.tree.map(
            lambda t: jnp.zeros(t.shape, jnp.float32), params)
        return ErrorFeedbackState(residual=residual,
                                  inner=inner.init(params))

    def update_fn(updates, state, params=None):
        def compensated(g, r):
            return g.astype(jnp.float32) + r

        e = jax.tree.map(compensated, updates, state.residual)
        if enabled:
            sent = jax.tree.map(
                lambda t: qdq(t, block_size), e)
            residual = jax.tree.map(jnp.subtract, e, sent)
        else:
            sent = e
            residual = state.residual  # already zeros; keep the leaves
        # Inner chain sees the wire values in the gradients' own dtype.
        sent = jax.tree.map(
            lambda s, g: s.astype(jnp.result_type(g)), sent, updates)
        new_updates, inner_state = inner.update(sent, state.inner, params)
        return new_updates, ErrorFeedbackState(residual=residual,
                                               inner=inner_state)

    return optax.GradientTransformation(init_fn, update_fn)
