"""Cross-process data plane for the eager path, over host arrays.

TPU-native replacement for the reference's CPU/network data plane
(ref: ops/mpi_operations.cc, ops/gloo_operations.cc): eager tensors live on
the host (or a single local device) per process; collectives across
processes are executed as jitted XLA programs over the process-set's device
mesh, so the bytes ride ICI/DCN exactly like the jit path — there is no
second transport stack to maintain.

Mechanics: each process contributes its value on its first local mesh
device (identity elements elsewhere), a cached jitted reduction with
replicated output sharding forces the collective, and every process reads
the replicated result locally.  Single-process short-circuits at the layer
above (ops/eager.py), so these functions assume size > 1.
"""

from __future__ import annotations

import functools
import warnings
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common.types import ReduceOp

__all__ = ["host_allreduce", "host_allgather", "host_broadcast",
           "host_alltoall", "host_reducescatter"]


def check_device_representable(value: np.ndarray) -> None:
    """Raise (synchronously, rank-locally) when the XLA host data plane
    cannot carry ``value`` losslessly — called at ENQUEUE time (ops/
    eager.py _prep) so the offending rank errors at its own call site.
    Raising later, inside the multi-process jitted collective, would
    strand the in-range ranks in a distributed hang with no message."""
    import jax

    if (value.dtype.kind in "iu" and value.dtype.itemsize == 8
            and not jax.config.jax_enable_x64):
        tgt = np.int32 if value.dtype.kind == "i" else np.uint32
        info = np.iinfo(tgt)
        if value.size and (value.min() < info.min
                           or value.max() > info.max):
            raise ValueError(
                f"{value.dtype} collective value exceeds 32-bit range and "
                "JAX x64 is disabled — enable jax_enable_x64 or use the "
                "TCP data plane (HVDT_CPU_OPERATIONS=tcp)")


def _canonical_for_device(value: np.ndarray) -> np.ndarray:
    """Make a 64-bit array safe for the XLA host data plane.

    Without ``jax_enable_x64``, ``device_put`` silently downcasts 64-bit
    inputs while the global-array assembly still declares the 64-bit
    aval — the resulting buffer/aval mismatch CORRUPTS values (measured:
    int64 [120, -120] MAX-allreduced to [120, 0]).  Canonicalize on the
    host instead: ints downcast losslessly with a range check, floats
    with a warning; callers cast the result back to the request dtype.
    """
    import jax

    if value.dtype.itemsize != 8 or jax.config.jax_enable_x64:
        return value
    kind = value.dtype.kind
    if kind in "iu":
        # Backstop only — the user-facing check runs at enqueue time
        # (check_device_representable); by dispatch the name is already
        # negotiated, so a raise here strands the peers.
        check_device_representable(value)
        return value.astype(np.int32 if kind == "i" else np.uint32)
    if kind == "f":
        warnings.warn("float64 collective downcast to float32 on the XLA "
                      "host data plane (jax_enable_x64 is off)",
                      stacklevel=3)
        return value.astype(np.float32)
    if kind == "c":
        return value.astype(np.complex64)
    return value


def _identity_value(op: ReduceOp, dtype: np.dtype):
    """Reduction identity element, dtype-aware (int MIN/MAX must not use
    float infinities)."""
    if op in (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM):
        return 0
    if op == ReduceOp.PRODUCT:
        return 1
    if op == ReduceOp.MIN:
        return np.iinfo(dtype).max if dtype.kind in "iu" else np.inf
    if op == ReduceOp.MAX:
        return np.iinfo(dtype).min if dtype.kind in "iu" else -np.inf
    raise ValueError(f"No identity for {op}")


@functools.lru_cache(maxsize=32)
def _flat_mesh(mesh):
    """1-D view of any mesh for host collectives (the eager data plane is
    rank-level, so axis structure is irrelevant here)."""
    from jax.sharding import Mesh

    if mesh.axis_names == ("dp",) and mesh.devices.ndim == 1:
        return mesh
    return Mesh(np.asarray(list(mesh.devices.flat), dtype=object), ("dp",))


def _mesh_local_devices(mesh) -> List[Any]:
    import jax

    local = [d for d in mesh.devices.flat if d.process_index ==
             jax.process_index()]
    if not local:
        raise RuntimeError("This process owns no devices in the mesh")
    return local


@functools.lru_cache(maxsize=256)
def _reduce_fn(mesh, op: ReduceOp, n_participants: int):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    def fn(g):
        if op in (ReduceOp.SUM, ReduceOp.AVERAGE, ReduceOp.ADASUM):
            out = g.sum(0)
            if op == ReduceOp.AVERAGE:
                out = out / n_participants
        elif op == ReduceOp.MIN:
            out = g.min(0)
        elif op == ReduceOp.MAX:
            out = g.max(0)
        elif op == ReduceOp.PRODUCT:
            out = g.prod(0)
        else:
            raise ValueError(f"Unsupported host reduce op {op}")
        return out

    return jax.jit(fn, out_shardings=NamedSharding(mesh, P()))


@functools.lru_cache(maxsize=16)
def _identity_fn(mesh):
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    return jax.jit(lambda g: g, out_shardings=NamedSharding(mesh, P()))


def _make_global(mesh, rows_per_device: Dict[Any, np.ndarray],
                 row_shape: Tuple[int, ...]) -> Any:
    """Build a global (D, *row_shape) array where device d holds
    rows_per_device[d] (dtype comes from the buffers themselves — which
    is exactly why 64-bit inputs must be canonicalized BEFORE device_put,
    see _canonical_for_device)."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P

    devs = list(mesh.devices.flat)
    sharding = NamedSharding(mesh, P("dp", *([None] * len(row_shape))))
    local = [jax.device_put(rows_per_device[d][None], d)
             for d in devs if d.process_index == jax.process_index()]
    return jax.make_array_from_single_device_arrays(
        (len(devs),) + row_shape, sharding, local)


def _contribution_rows(mesh, value: np.ndarray, identity_val: float):
    """value on the first local device, identity elsewhere."""
    local = _mesh_local_devices(mesh)
    rows = {}
    for i, d in enumerate(local):
        if i == 0:
            rows[d] = value
        else:
            rows[d] = np.full_like(value, identity_val)
    return rows


def host_allreduce(value: np.ndarray, process_set, op: ReduceOp) -> np.ndarray:
    """Allreduce ``value`` across the processes of ``process_set``."""
    from . import tcp_backend

    if tcp_backend.enabled():
        return tcp_backend.tcp_allreduce(np.ascontiguousarray(value),
                                         process_set, op)
    mesh = _flat_mesh(process_set.mesh)
    orig_dtype = value.dtype
    value = _canonical_for_device(np.ascontiguousarray(value))
    calc_dtype = value.dtype
    if op == ReduceOp.PRODUCT and value.dtype.kind in "iu":
        import jax

        # f64 avoids int overflow in products — but only when the device
        # path can actually carry f64; with x64 off, keep the integer
        # type (C/MPI wraparound semantics) rather than silently rounding
        # through float32.
        if jax.config.jax_enable_x64:
            calc_dtype = np.float64
    rows = _contribution_rows(mesh, value.astype(calc_dtype),
                              _identity_value(op, np.dtype(calc_dtype)))
    g = _make_global(mesh, rows, value.shape)
    out = _reduce_fn(mesh, op, process_set.size())(g)
    return np.asarray(out.addressable_data(0)).astype(orig_dtype)


def host_broadcast(value: Optional[np.ndarray], root_rank: int, process_set,
                   shape: Tuple[int, ...], dtype) -> np.ndarray:
    """Broadcast from set-relative ``root_rank``.  Non-root processes pass
    value=None and receive the root's tensor.

    Scalars: ``np.ascontiguousarray`` promotes 0-d arrays to shape
    ``(1,)``, so the global array is laid out from the CONTRIBUTION's
    shape (identical promotion on every rank) and the negotiated
    ``shape`` is restored on return — building it from ``shape`` directly
    desynchronizes the per-device buffers from the declared aval for 0-d
    tensors (e.g. a Keras optimizer's iteration counter)."""
    from . import tcp_backend

    is_root = process_set.rank() == root_rank
    contrib = np.ascontiguousarray(value if is_root
                                   else np.zeros(shape, dtype))
    if tcp_backend.enabled():
        out = tcp_backend.tcp_broadcast(contrib, process_set, root_rank)
        return np.asarray(out).astype(dtype, copy=False).reshape(shape)
    mesh = _flat_mesh(process_set.mesh)
    contrib = _canonical_for_device(contrib)
    rows = _contribution_rows(mesh, contrib, 0.0)
    g = _make_global(mesh, rows, contrib.shape)
    out = _reduce_fn(mesh, ReduceOp.SUM, process_set.size())(g)
    return np.asarray(
        out.addressable_data(0)).astype(dtype).reshape(shape)


def host_allgather(value: np.ndarray, process_set,
                   all_dim0: Sequence[int]) -> np.ndarray:
    """Ragged allgather: concat along dim 0 with per-rank sizes
    ``all_dim0`` (negotiated by the controller — the analog of the
    allgather displacement math in ops/collective_operations.h:129)."""
    from . import tcp_backend

    if tcp_backend.enabled():
        return tcp_backend.tcp_allgather(np.ascontiguousarray(value),
                                         process_set)
    mesh = _flat_mesh(process_set.mesh)
    orig_dtype = value.dtype
    value = _canonical_for_device(np.ascontiguousarray(value))
    max0 = max(all_dim0) if all_dim0 else 0
    rest = value.shape[1:]
    padded = np.zeros((max0,) + rest, value.dtype)
    padded[: value.shape[0]] = value
    # Row for first local device = my padded block; zeros elsewhere.  The
    # replicated identity jit forces an all-gather of every row.
    rows = _contribution_rows(mesh, padded, 0.0)
    g = _make_global(mesh, rows, (max0,) + rest)
    full = np.asarray(_identity_fn(mesh)(g).addressable_data(0))
    # row index of each process's first local device in mesh order
    devs = list(mesh.devices.flat)
    first_row_of_proc: Dict[int, int] = {}
    for i, d in enumerate(devs):
        first_row_of_proc.setdefault(d.process_index, i)
    import jax

    proc_ids = sorted(first_row_of_proc)
    pieces = []
    for set_rank, proc in enumerate(proc_ids):
        n = all_dim0[set_rank]
        pieces.append(full[first_row_of_proc[proc], :n])
    out = np.concatenate(pieces, axis=0) if pieces else value
    return out.astype(orig_dtype)


def host_alltoall(value: np.ndarray, splits: Sequence[int], process_set,
                  all_splits: Sequence[Sequence[int]]) -> Tuple[np.ndarray, List[int]]:
    """Uneven alltoall (ref: AlltoallOp PrepareOutputAndParams
    collective_operations.h:209-273).  ``all_splits[r]`` is rank r's send
    splits, negotiated by the controller.  Returns (output, recv_splits).

    Implemented as ragged allgather + local slicing: correctness-first (the
    jit path's lax.all_to_all is the performance path)."""
    from . import tcp_backend

    my_rank = process_set.rank()
    if tcp_backend.enabled():
        out = tcp_backend.tcp_alltoall(np.ascontiguousarray(value),
                                       process_set, list(splits))
        return out, [int(s[my_rank]) for s in all_splits]
    dim0s = [int(sum(s)) for s in all_splits]
    gathered = host_allgather(value, process_set, dim0s)
    out_pieces = []
    recv_splits = []
    offset = 0
    for r, s in enumerate(all_splits):
        start = offset + int(sum(s[:my_rank]))
        n = int(s[my_rank])
        out_pieces.append(gathered[start:start + n])
        recv_splits.append(n)
        offset += dim0s[r]
    return np.concatenate(out_pieces, axis=0), recv_splits


def host_reducescatter(value: np.ndarray, process_set,
                       op: ReduceOp) -> np.ndarray:
    """Reduce + scatter rows (TPU-native extension; equal-ish split with
    remainder to low ranks)."""
    reduced = host_allreduce(value, process_set, op)
    p = process_set.size()
    r = process_set.rank()
    n = reduced.shape[0]
    base, rem = divmod(n, p)
    start = r * base + min(r, rem)
    stop = start + base + (1 if r < rem else 0)
    return reduced[start:stop]
