"""Fused Pallas optimizer kernels — the *update* side of the hot path.

The comm side of the gradient path is already fused
(ops/device.fused_allreduce buckets the pytree into few collectives);
this module fuses the other half.  A stock optax Adam step lowers to
~10 separate elementwise XLA ops — moment decay, moment update, two
bias corrections, rsqrt, divide, scale, apply — and on an HBM-bound
chip every one of them is a full read/write pass over every parameter.
ZeRO (Rajbhandari et al.) and LAMB (You et al.) both treat the
optimizer update as a first-class bandwidth target; these kernels do
the TPU-native version: one grid program reads a ``(grad, m, v)``
(+``param`` for weight decay) tile into VMEM, runs the ENTIRE Adam (or
SGD-momentum) recurrence on the VPU in f32, and writes ``(update, m,
v)`` back — one HBM pass per parameter, with the moment buffers
aliased in-place (``input_output_aliases``) so donated optimizer state
never double-buffers.

Exposed as optax-compatible ``GradientTransformation``s:

* :func:`fused_adam` — optax.adam/adamw semantics (bias-corrected
  moments, optional additive weight decay, schedule or float lr);
* :func:`fused_sgd` — optax.sgd semantics (momentum/nesterov trace).

Both compose with ``DistributedOptimizer``'s comm chain unchanged::

    opt = hvd.DistributedOptimizer(hvd.fused_adam(1e-3))
    updates, opt_state = opt.update(grads, opt_state, params)
    params = optax.apply_updates(params, updates)

Contract note: the optax ``update`` contract returns *updates* (the
delta), so ``apply_updates`` still costs one fused XLA add over the
params — the kernels collapse the ~10-op moment/correction chain into
one pass, and the delta-add is the single pass the optax interface
keeps.  The moment state round-trips HBM exactly once either way.

Eligibility + fallback: Mosaic tiles the trailing dim at 128 lanes
with a per-dtype sublane floor, so a leaf is kernel-eligible when its
flat size folds to ``[rows, 128]`` with a power-of-2 row tile >= the
floor (:func:`fused_update_eligible`).  Ineligible leaves (odd biases,
non-128 channel counts, sub-2-byte dtypes) take an XLA fallback with
the *same* f32-accumulated formulas, so the pytree never changes
semantics, only lowering.  The gate is platform-independent —
interpret mode has no alignment floor, but gating identically on CPU
means the CPU suite exercises the exact eligible/fallback split that
runs on hardware.  Kernels run under ``interpret=True`` off-TPU, so
tests compare the very same kernel code against optax
(tests/test_optim_kernels.py).
"""

from __future__ import annotations

import functools
from typing import Any, Optional

import jax
import jax.numpy as jnp

from .pallas_kernels import _use_interpret, _vma_kw

__all__ = ["fused_adam", "fused_sgd", "fused_update_eligible",
           "sgd_leaf_update", "adam_leaf_update"]

_LANES = 128
# Per-dtype minimum sublane tile (see pallas_kernels._fit_block): Mosaic
# refuses smaller second-to-last dims on real TPU.
_SUBLANE = {4: 8, 2: 16, 1: 32}
# Row-tile upper bound: 512x128 f32 is 256 KiB per operand — 7 operands
# stay well under VMEM with double-buffering headroom.
_BLOCK_ROWS = 512


def _sublane_floor(*dtypes) -> int:
    return max(_SUBLANE.get(jnp.dtype(d).itemsize, 8) for d in dtypes)


def fused_update_eligible(leaf, *extra_dtypes) -> bool:
    """True when ``leaf`` can take the fused kernel: floating, >=2-byte
    dtype, flat size folding to ``[rows, 128]`` whose largest power-of-2
    row divisor clears the strictest sublane floor among the leaf's and
    ``extra_dtypes``' tiles.  Deliberately platform-independent (see
    module docstring) — CPU and TPU route identically."""
    dtype = jnp.dtype(leaf.dtype)
    if not jnp.issubdtype(dtype, jnp.floating) or dtype.itemsize < 2:
        return False
    for d in extra_dtypes:
        d = jnp.dtype(d)
        if not jnp.issubdtype(d, jnp.floating) or d.itemsize < 2:
            return False
    n = 1
    for s in leaf.shape:
        n *= int(s)
    if n == 0 or n % _LANES:
        return False
    rows = n // _LANES
    return (rows & -rows) >= _sublane_floor(leaf.dtype, *extra_dtypes)


def _row_block(rows: int) -> int:
    br = min(_BLOCK_ROWS, rows & -rows)
    return max(br, 1)


def _as2d(x):
    return x.reshape(x.size // _LANES, _LANES)


def _vma_align(*ops):
    """Promote operands to the union of their varying manual axes —
    replicated params meeting still-varying grads inside shard_map need
    matching vma before they share a kernel (same idiom as
    ops/conv_fused)."""
    from ..parallel.sharding import pcast_to_union

    return tuple(pcast_to_union(op, *ops) for op in ops)


# ---- Adam ----------------------------------------------------------------


def _adam_kernel(sc_ref, *refs, b1: float, b2: float, eps: float,
                 eps_root: float, wd: float):
    """One VMEM-resident tile: full Adam recurrence in f32 on the VPU.

    ``sc_ref`` (SMEM scalar prefetch): [lr, 1/(1-b1^t), 1/(1-b2^t)].
    With weight decay the param tile rides along (AdamW's additive
    term); without it the params are never even read.
    """
    if wd:
        p_ref, g_ref, m_ref, v_ref, d_ref, mo_ref, vo_ref = refs
    else:
        g_ref, m_ref, v_ref, d_ref, mo_ref, vo_ref = refs
    f32 = jnp.float32
    g = g_ref[...].astype(f32)
    m = b1 * m_ref[...].astype(f32) + (1.0 - b1) * g
    v = b2 * v_ref[...].astype(f32) + (1.0 - b2) * (g * g)
    u = (m * sc_ref[1]) / (jnp.sqrt(v * sc_ref[2] + eps_root) + eps)
    if wd:
        u = u + wd * p_ref[...].astype(f32)
    d_ref[...] = (-sc_ref[0] * u).astype(d_ref.dtype)
    mo_ref[...] = m.astype(mo_ref.dtype)
    vo_ref[...] = v.astype(vo_ref.dtype)


def _adam_leaf_fused(p, g, m, v, scalars, *, b1, b2, eps, eps_root, wd):
    """Single-HBM-pass Adam for one eligible leaf; returns (delta,
    m_new, v_new) in the leaf dtypes.  m/v alias their outputs."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape = g.shape
    ops = ((p, g, m, v) if wd else (g, m, v))
    ops = _vma_align(*ops)
    kw = _vma_kw(*ops)
    ops2d = tuple(_as2d(x) for x in ops)
    rows = ops2d[0].shape[0]
    br = _row_block(rows)
    spec = pl.BlockSpec((br, _LANES), lambda i, *_: (i, 0))
    n_in = len(ops2d)
    # Operand indices count the scalar-prefetch arg: scalars=0, then the
    # tensor operands; m and v are the last two inputs → alias onto the
    # m_new/v_new outputs (in-place moments under donation).
    aliases = {n_in - 1: 1, n_in: 2}
    d, mo, vo = pl.pallas_call(
        functools.partial(_adam_kernel, b1=b1, b2=b2, eps=eps,
                          eps_root=eps_root, wd=wd),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(rows // br,),
            in_specs=[spec] * n_in, out_specs=[spec, spec, spec]),
        out_shape=(jax.ShapeDtypeStruct(ops2d[0].shape, p.dtype, **kw),
                   jax.ShapeDtypeStruct(ops2d[0].shape, m.dtype, **kw),
                   jax.ShapeDtypeStruct(ops2d[0].shape, v.dtype, **kw)),
        input_output_aliases=aliases,
        interpret=_use_interpret(),
    )(scalars, *ops2d)
    return d.reshape(shape), mo.reshape(shape), vo.reshape(shape)


def _adam_leaf_xla(p, g, m, v, scalars, *, b1, b2, eps, eps_root, wd):
    """Fallback for ineligible leaves — identical f32 math, XLA-fused."""
    f32 = jnp.float32
    g32 = g.astype(f32)
    m_new = b1 * m.astype(f32) + (1.0 - b1) * g32
    v_new = b2 * v.astype(f32) + (1.0 - b2) * (g32 * g32)
    u = (m_new * scalars[1]) / (jnp.sqrt(v_new * scalars[2] + eps_root)
                                + eps)
    if wd:
        u = u + wd * p.astype(f32)
    return ((-scalars[0] * u).astype(p.dtype), m_new.astype(m.dtype),
            v_new.astype(v.dtype))


def fused_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
               eps: float = 1e-8, eps_root: float = 0.0, *,
               weight_decay: float = 0.0,
               mu_dtype: Optional[Any] = None,
               use_kernels: bool = True):
    """optax.adam/adamw drop-in whose per-leaf update is one Pallas HBM
    pass (see module docstring).  ``learning_rate`` may be a float or an
    optax schedule (evaluated at the pre-increment step count, matching
    optax.scale_by_schedule).
    ``weight_decay`` > 0 gives adamw's additive decoupled decay.
    State is ``optax.ScaleByAdamState`` — checkpoints and
    ``DistributedOptimizer``/``MultiSteps`` wrappers see a stock shape.

    ``use_kernels=False`` forces the XLA fallback lowering for every
    leaf — same state tree, same f32 math, different lowering — which is
    what makes a fused-vs-unfused A/B (autotune's fused dimension)
    hot-swappable mid-run without re-initializing optimizer state.
    """
    import optax

    def init_fn(params):
        mu = jax.tree.map(
            lambda t: jnp.zeros_like(t, dtype=mu_dtype or t.dtype), params)
        nu = jax.tree.map(jnp.zeros_like, params)
        return optax.ScaleByAdamState(count=jnp.zeros([], jnp.int32),
                                      mu=mu, nu=nu)

    def update_fn(updates, state, params=None):
        if weight_decay and params is None:
            raise ValueError(
                "fused_adam(weight_decay=...) requires params: call "
                "update(grads, state, params)")
        count_inc = optax.safe_int32_increment(state.count)
        f32 = jnp.float32
        t = count_inc.astype(f32)
        # Schedules see the PRE-increment count (optax.scale_by_schedule
        # evaluates step_size_fn(state.count)); bias correction uses the
        # incremented count (optax.scale_by_adam) — match both exactly.
        lr = (learning_rate(state.count) if callable(learning_rate)
              else learning_rate)
        scalars = jnp.stack([
            jnp.asarray(lr, f32),
            1.0 / (1.0 - jnp.power(b1, t)),
            1.0 / (1.0 - jnp.power(b2, t))]).astype(f32)

        g_leaves, treedef = jax.tree.flatten(updates)
        m_leaves = treedef.flatten_up_to(state.mu)
        v_leaves = treedef.flatten_up_to(state.nu)
        p_leaves = (treedef.flatten_up_to(params) if params is not None
                    else g_leaves)

        out_d, out_m, out_v = [], [], []
        for p, g, m, v in zip(p_leaves, g_leaves, m_leaves, v_leaves):
            fn = (_adam_leaf_fused if use_kernels and
                  fused_update_eligible(g, p.dtype, m.dtype, v.dtype)
                  else _adam_leaf_xla)
            d, mn, vn = fn(p, g, m, v, scalars, b1=b1, b2=b2, eps=eps,
                           eps_root=eps_root, wd=weight_decay)
            out_d.append(d)
            out_m.append(mn)
            out_v.append(vn)
        return (jax.tree.unflatten(treedef, out_d),
                optax.ScaleByAdamState(
                    count=count_inc,
                    mu=jax.tree.unflatten(treedef, out_m),
                    nu=jax.tree.unflatten(treedef, out_v)))

    # Hyperparameter tag for the ZeRO router (ops/zero.py):
    # DistributedOptimizer(..., zero="states"/"params") shards this
    # update's math, so it must know the family + coefficients.
    update_fn._hvdt_optim_spec = {
        "kind": "adam", "learning_rate": learning_rate, "b1": b1,
        "b2": b2, "eps": eps, "eps_root": eps_root,
        "weight_decay": weight_decay, "use_kernels": use_kernels}
    return optax.GradientTransformation(init_fn, update_fn)


def adam_leaf_update(p, g, m, v, scalars, *, b1: float = 0.9,
                     b2: float = 0.999, eps: float = 1e-8,
                     eps_root: float = 0.0, weight_decay: float = 0.0,
                     use_kernels: bool = True):
    """Public per-leaf Adam update ``(delta, m_new, v_new)`` — the unit
    the overlap scheduler pipelines between bucket collectives
    (ops/overlap.exchange_and_update).  ``scalars`` is the
    ``[lr, 1/(1-b1^t), 1/(1-b2^t)]`` f32 stack (what ``fused_adam``
    builds per step); picks the single-HBM-pass Pallas kernel when the
    leaf is tile-eligible, the identical-math XLA fallback otherwise."""
    fn = (_adam_leaf_fused if use_kernels
          and fused_update_eligible(g, p.dtype, m.dtype, v.dtype)
          else _adam_leaf_xla)
    return fn(p, g, m, v, scalars, b1=b1, b2=b2, eps=eps,
              eps_root=eps_root, wd=weight_decay)


# ---- SGD (momentum) ------------------------------------------------------


def _sgd_kernel(sc_ref, g_ref, m_ref, d_ref, mo_ref, *, momentum: float,
                nesterov: bool):
    """optax.trace recurrence in one tile pass: m = g + momentum*m;
    update = g + momentum*m (nesterov) or m."""
    f32 = jnp.float32
    g = g_ref[...].astype(f32)
    m = g + momentum * m_ref[...].astype(f32)
    u = g + momentum * m if nesterov else m
    d_ref[...] = (-sc_ref[0] * u).astype(d_ref.dtype)
    mo_ref[...] = m.astype(mo_ref.dtype)


def _sgd_leaf_fused(g, m, scalars, *, momentum, nesterov):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    shape = g.shape
    g, m = _vma_align(g, m)
    kw = _vma_kw(g, m)
    g2, m2 = _as2d(g), _as2d(m)
    rows = g2.shape[0]
    br = _row_block(rows)
    spec = pl.BlockSpec((br, _LANES), lambda i, *_: (i, 0))
    d, mo = pl.pallas_call(
        functools.partial(_sgd_kernel, momentum=momentum,
                          nesterov=nesterov),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1, grid=(rows // br,),
            in_specs=[spec, spec], out_specs=[spec, spec]),
        out_shape=(jax.ShapeDtypeStruct(g2.shape, g.dtype, **kw),
                   jax.ShapeDtypeStruct(g2.shape, m.dtype, **kw)),
        input_output_aliases={2: 1},     # m (after scalars, g) → m_new
        interpret=_use_interpret(),
    )(scalars, g2, m2)
    return d.reshape(shape), mo.reshape(shape)


def _sgd_leaf_xla(g, m, scalars, *, momentum, nesterov):
    f32 = jnp.float32
    g32 = g.astype(f32)
    m_new = g32 + momentum * m.astype(f32)
    u = g32 + momentum * m_new if nesterov else m_new
    return (-scalars[0] * u).astype(g.dtype), m_new.astype(m.dtype)


def sgd_leaf_update(g, m, scalars, *, momentum: float,
                    nesterov: bool = False, use_kernels: bool = True):
    """Public per-leaf SGD-momentum update ``(delta, new_trace)`` — the
    unit the overlap scheduler pipelines between bucket collectives
    (ops/overlap.exchange_and_update / pipelined_sgd).  ``scalars`` is
    the 1-element f32 ``[lr]`` stack; picks the single-HBM-pass Pallas
    kernel when the leaf is tile-eligible, the identical-math XLA
    fallback otherwise."""
    fn = (_sgd_leaf_fused
          if use_kernels and fused_update_eligible(g, m.dtype)
          else _sgd_leaf_xla)
    return fn(g, m, scalars, momentum=momentum, nesterov=nesterov)


def fused_sgd(learning_rate, momentum: float = 0.0,
              nesterov: bool = False, *, use_kernels: bool = True):
    """optax.sgd drop-in; with ``momentum`` the trace update runs as one
    Pallas HBM pass per eligible leaf.  Without momentum there is no
    state and the update is the single XLA scale it always was (nothing
    to fuse).  State is ``optax.TraceState``.  Schedules need a step
    count the stock TraceState doesn't carry — pass a float (or use
    :func:`fused_adam`, which supports schedules).
    ``use_kernels=False``: XLA fallback lowering for every leaf, same
    state tree — the hot-swappable unfused A/B leg (see fused_adam)."""
    import optax

    if callable(learning_rate):
        raise ValueError(
            "fused_sgd takes a float learning_rate (TraceState carries "
            "no step count for a schedule); use fused_adam for "
            "schedule support")
    if not momentum:
        def init_plain(params):
            del params
            return optax.EmptyState()

        def update_plain(updates, state, params=None):
            del params
            return (jax.tree.map(
                lambda g: (-learning_rate
                           * g.astype(jnp.float32)).astype(g.dtype),
                updates), state)

        update_plain._hvdt_optim_spec = {
            "kind": "sgd", "learning_rate": learning_rate,
            "momentum": 0.0, "nesterov": False,
            "use_kernels": use_kernels}
        return optax.GradientTransformation(init_plain, update_plain)

    def init_fn(params):
        return optax.TraceState(trace=jax.tree.map(jnp.zeros_like, params))

    def update_fn(updates, state, params=None):
        del params
        scalars = jnp.stack([jnp.asarray(learning_rate, jnp.float32)])

        g_leaves, treedef = jax.tree.flatten(updates)
        m_leaves = treedef.flatten_up_to(state.trace)
        out_d, out_m = [], []
        for g, m in zip(g_leaves, m_leaves):
            fn = (_sgd_leaf_fused
                  if use_kernels and fused_update_eligible(g, m.dtype)
                  else _sgd_leaf_xla)
            d, mn = fn(g, m, scalars, momentum=momentum, nesterov=nesterov)
            out_d.append(d)
            out_m.append(mn)
        return (jax.tree.unflatten(treedef, out_d),
                optax.TraceState(trace=jax.tree.unflatten(treedef, out_m)))

    # ZeRO router tag (see fused_adam).
    update_fn._hvdt_optim_spec = {
        "kind": "sgd", "learning_rate": learning_rate,
        "momentum": momentum, "nesterov": nesterov,
        "use_kernels": use_kernels}
    return optax.GradientTransformation(init_fn, update_fn)
