"""Sparse (indexed-slices) allreduce — the embedding-gradient path.

Re-conception of ref: torch/mpi_ops.py:556-578 sparse_allreduce_async
(double allgather of indices and values; average applied to values) and
the TF IndexedSlices path (tensorflow/__init__.py allreduce with
sparse_as_dense=False).  A sparse gradient is (indices [nnz],
values [nnz, ...rest], dense_shape); ranks hold different nnz — the
eager allgather negotiates the ragged first dim.

Two paths:

* ``sparse_allreduce`` — eager: allgather indices and values across the
  process set; result keeps duplicate indices (exactly like the
  reference's concatenated IndexedSlices) plus ``to_dense`` scatter-add.
* ``sparse_allreduce_jit`` — inside shard_map: fixed-nnz all_gather along
  a mesh axis, returning concatenated (indices, values) — nnz must be
  equal per rank under jit (pad with a sentinel row if needed).
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Tuple

import numpy as np

from ..common.types import ReduceOp

__all__ = ["SparseGradient", "sparse_allreduce", "sparse_allreduce_async",
           "sparse_allreduce_jit"]


@dataclasses.dataclass
class SparseGradient:
    """Indexed-slices gradient: ``dense[indices[i]] += values[i]``."""

    indices: np.ndarray          # [nnz] int
    values: np.ndarray           # [nnz, ...rest]
    dense_shape: Tuple[int, ...]

    def to_dense(self) -> np.ndarray:
        out = np.zeros(self.dense_shape, self.values.dtype)
        np.add.at(out, np.asarray(self.indices), np.asarray(self.values))
        return out


def sparse_allreduce_async(indices, values, dense_shape,
                           name: Optional[str] = None,
                           op: ReduceOp = ReduceOp.AVERAGE,
                           process_set=None):
    """Async start; returns a zero-arg resolver (ref: returns ``handle``
    closure, torch/mpi_ops.py:565-576)."""
    from . import eager

    # When unnamed, let the controller auto-name each collective with its
    # deterministic per-process counter — the name must be identical on
    # every rank for negotiation to match (a process-local id() would
    # deadlock multi-rank runs).
    h_idx = eager.allgather_async(np.asarray(indices),
                                  name=f"{name}.indices" if name else None,
                                  process_set=process_set)
    h_val = eager.allgather_async(np.asarray(values),
                                  name=f"{name}.values" if name else None,
                                  process_set=process_set)

    def resolve() -> SparseGradient:
        vals = np.asarray(eager.synchronize(h_val))
        idx = np.asarray(eager.synchronize(h_idx))
        if op == ReduceOp.AVERAGE:
            from ..common import basics

            size = (process_set.size() if process_set is not None
                    else basics.size())
            vals = (vals / size).astype(vals.dtype)
        return SparseGradient(idx, vals, tuple(dense_shape))

    return resolve


def sparse_allreduce(indices, values, dense_shape,
                     name: Optional[str] = None,
                     op: ReduceOp = ReduceOp.AVERAGE,
                     process_set=None) -> SparseGradient:
    return sparse_allreduce_async(indices, values, dense_shape, name=name,
                                  op=op, process_set=process_set)()


def sparse_allreduce_jit(indices, values, axis: str = "dp",
                         op: ReduceOp = ReduceOp.AVERAGE):
    """Sparse allreduce under jit/shard_map: equal-nnz all_gather along
    ``axis``; returns concatenated (indices, values) with values averaged
    for AVERAGE.  Use a sentinel index (e.g. 0 with zero values) to pad
    ranks to a common nnz."""
    import jax.numpy as jnp
    from jax import lax

    gi = lax.all_gather(indices, axis, tiled=True)
    gv = lax.all_gather(values, axis, tiled=True)
    if op == ReduceOp.AVERAGE:
        from .device import _axis_size_static

        gv = gv / _axis_size_static(axis)
    elif op != ReduceOp.SUM:
        raise ValueError("sparse allreduce supports SUM/AVERAGE")
    return gi, gv.astype(jnp.result_type(values))
