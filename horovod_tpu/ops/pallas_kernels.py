"""Pallas TPU kernels for the attention hot path.

The framework's compute plane is XLA; Pallas is reserved for the ops
where profiling shows XLA's fusion isn't enough (SURVEY.md §7: "Pallas
only if profiling demands").  Attention is that op: the naive einsum
materializes the [B,H,Lq,Lk] score matrix in HBM, while the flash kernel
streams K/V blocks through VMEM with an online softmax — HBM traffic
drops from O(L²) to O(L·D), which is the difference between
bandwidth-bound and MXU-bound at long sequence.

Two entry points:

* ``flash_attention(q, k, v)`` — fused causal/full attention for the
  non-ring path (one device holds the whole sequence).
* ``flash_block_update(...)`` — one ring-attention step: takes the
  running (acc, row_max, row_sum) online-softmax carry and a K/V block
  (with its global position offset), returns the updated carry.
  ``parallel/ring_attention.py`` composes it around ``lax.ppermute``.

Both run in Pallas interpret mode off-TPU, so the CPU test suite
exercises the very same kernel code (tests/test_pallas.py compares
against the jnp reference).

Layout: kernels work in [B, H, L, D]; wrappers accept the framework's
[B, L, H, D] and transpose.  GQA/MQA is handled in the BlockSpec index
maps (kv head = q head // group) — K/V are never materially expanded.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp

__all__ = ["flash_attention", "flash_attention_smallseq",
           "flash_block_update", "flash_grad_block",
           "attention_reference"]

_NEG_INF = -1e30


def _use_interpret() -> bool:
    return jax.devices()[0].platform != "tpu"


def _vma_kw(*ops) -> dict:
    """``{"vma": ...}`` kwargs for pallas_call out_shapes: inside
    shard_map (check_vma) out types must carry the varying-axes set, and
    outputs vary over every axis any operand varies over.  Empty when no
    operand varies (plain jit) — and on JAX builds without ``jax.typeof``
    (no vma tracking at all), where empty is the only correct answer."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:
        return {}
    vma = frozenset()
    for op in ops:
        vma |= frozenset(getattr(typeof(op), "vma", frozenset()))
    return {"vma": vma} if vma else {}


def _fit_block(n: int, block: int, *dtypes) -> int:
    """Largest power-of-2 reduction of ``block`` that divides ``n`` (the
    defaults are tuned upper bounds, not divisibility requirements —
    callers gate on 128-divisible sequence lengths, so this lands on
    >=128 for them and degrades gracefully for anything else).

    On real TPU the block's sublane dimension must stay tile-aligned
    (the per-dtype minimum sublane tile: 8 rows for f32, 16 for bf16,
    32 for 1-byte types); Mosaic fails to lower smaller blocks with an
    obscure error, so refuse explicitly instead.  Interpret mode (the
    CPU test path) has no alignment floor."""
    fitted = min(block, n)
    while n % fitted:
        fitted //= 2
    fitted = max(fitted, 1)
    floor = max({4: 8, 2: 16, 1: 32}.get(jnp.dtype(d).itemsize, 8)
                for d in dtypes)
    if fitted < floor and not _use_interpret():
        names = "/".join(jnp.dtype(d).name for d in dtypes)
        raise ValueError(
            f"sequence length {n} only tiles at block={fitted}, below the "
            f"TPU sublane tile ({floor} rows for {names}) "
            f"— pad the sequence to a multiple of 128")
    return fitted


def _kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, acc_ref, m_ref, l_ref,
            oacc_ref, om_ref, ol_ref, acc_s, m_s, l_s, *, causal: bool,
            scale: float):
    """Grid program (b, h, iq, ik): one K/V block per step, online softmax.

    The canonical TPU flash layout: ik is the innermost (sequential) grid
    dim, so K/V stream through VMEM with pipelined double-buffering while
    the (acc, m, l) state lives in persistent VMEM scratch — initialized
    from the carry inputs at ik==0, flushed to the outputs at the last ik.
    qo/ko: scalar-prefetch global position offsets (SMEM) for the causal
    mask; q_ref: [1,1,bq,d]; k_ref/v_ref: [1,1,bk,d].
    """
    import jax.experimental.pallas as pl

    iq = pl.program_id(2)
    ik = pl.program_id(3)
    nk = pl.num_programs(3)
    bq = q_ref.shape[2]
    bk = k_ref.shape[2]

    @pl.when(ik == 0)
    def _init():
        acc_s[...] = acc_ref[0, 0, :, :].astype(jnp.float32)
        m_s[...] = m_ref[0, 0, :, :].astype(jnp.float32)
        l_s[...] = l_ref[0, 0, :, :].astype(jnp.float32)

    def _compute():
        q = q_ref[0, 0, :, :]                   # [bq, d]
        k_blk = k_ref[0, 0, :, :]               # [bk, d]
        v_blk = v_ref[0, 0, :, :]
        s = jax.lax.dot_general(
            q, k_blk, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32) * scale   # [bq, bk]
        if causal:
            q_pos = (qo_ref[0] + iq * bq
                     + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0))
            k_pos = (ko_ref[0] + ik * bk
                     + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1))
            mask = q_pos >= k_pos               # [bq, bk]
            s = jnp.where(mask, s, _NEG_INF)
        m = m_s[...]
        l = l_s[...]
        acc = acc_s[...]
        m_new = jnp.maximum(m, s.max(axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)
        if causal:
            p = jnp.where(mask, p, 0.0)
        corr = jnp.exp(m - m_new)
        acc_s[...] = acc * corr + jax.lax.dot_general(
            p.astype(v_blk.dtype), v_blk, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        l_s[...] = l * corr + p.sum(axis=-1, keepdims=True)
        m_s[...] = m_new

    if causal:
        # Causal block pruning: when even this q-block's LAST row precedes
        # the k-block's first position the whole tile is masked — skip both
        # matmuls (the flops halving that makes causal flash ~2x full).
        last_q = qo_ref[0] + iq * bq + (bq - 1)
        first_k = ko_ref[0] + ik * bk
        pl.when(last_q >= first_k)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _flush():
        oacc_ref[0, 0, :, :] = acc_s[...]
        om_ref[0, 0, :, :] = m_s[...]
        ol_ref[0, 0, :, :] = l_s[...]


def _flash_call(q, k, v, acc, m, l, q_offset, k_offset, *, causal, scale,
                block_q, block_k):
    """pallas_call plumbing shared by both entry points.  All operands in
    [B, H(q or kv), L, D] / [B, H, L, 1] layout; returns (acc, m, l)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, h, lq, d = q.shape
    _, hkv, lk, _ = k.shape
    group = h // hkv
    if lq % block_q or lk % block_k:
        raise ValueError(
            f"seq lens (q={lq}, k={lk}) must divide block sizes "
            f"({block_q}, {block_k})")
    grid = (b, h, lq // block_q, lk // block_k)

    qspec = pl.BlockSpec((1, 1, block_q, d),
                         lambda bb, hh, qq, kk, *_: (bb, hh, qq, 0))
    kvspec = pl.BlockSpec((1, 1, block_k, d),
                          lambda bb, hh, qq, kk, *_: (bb, hh // group, kk, 0))
    carry_d = pl.BlockSpec((1, 1, block_q, d),
                           lambda bb, hh, qq, kk, *_: (bb, hh, qq, 0))
    carry_1 = pl.BlockSpec((1, 1, block_q, 1),
                           lambda bb, hh, qq, kk, *_: (bb, hh, qq, 0))

    kernel = functools.partial(_kernel, causal=causal, scale=scale)
    kw = _vma_kw(q, k, v, acc, m, l)
    out_shapes = (
        jax.ShapeDtypeStruct((b, h, lq, d), jnp.float32, **kw),
        jax.ShapeDtypeStruct((b, h, lq, 1), jnp.float32, **kw),
        jax.ShapeDtypeStruct((b, h, lq, 1), jnp.float32, **kw),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=grid,
        in_specs=[qspec, kvspec, kvspec, carry_d, carry_1, carry_1],
        out_specs=[carry_d, carry_1, carry_1],
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32),
                        pltpu.VMEM((block_q, 1), jnp.float32)])
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shapes,
        interpret=_use_interpret(),
    )(jnp.atleast_1d(q_offset).astype(jnp.int32),
      jnp.atleast_1d(k_offset).astype(jnp.int32),
      q, k, v, acc, m, l)


def flash_attention(q: jax.Array, k: jax.Array, v: jax.Array, *,
                    causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 512, block_k: int = 1024) -> jax.Array:
    """Fused flash attention; layouts/API match
    parallel.ring_attention (q,k,v: [B, L, H, D]; GQA via fewer kv heads).

    Differentiable: the forward runs the Pallas kernel (pallas_call has
    no autodiff rule of its own); the backward is the standard flash
    gradient recomputed BLOCKWISE over K in plain XLA — the saved
    logsumexp makes the recomputation exact, and the [B,H,Lq,block_k]
    working set keeps backward memory O(L·block) instead of O(L²)
    (the property that makes long-context training fit in HBM at all).
    """
    b, lq, h, d = q.shape
    if scale is None:
        scale = d ** -0.5
    block_q = _fit_block(lq, block_q, q.dtype)
    block_k = _fit_block(k.shape[1], block_k, k.dtype, v.dtype)
    return _flash_attn_diff(q, k, v, causal, float(scale), block_q,
                            block_k)


def _flash_fwd_core(q, k, v, causal, scale, block_q, block_k):
    """Kernel forward returning (out [B,L,H,D], lse [B,H,Lq])."""
    b, lq, h, d = q.shape
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    acc = jnp.zeros((b, h, lq, d), jnp.float32)
    m = jnp.full((b, h, lq, 1), _NEG_INF, jnp.float32)
    l = jnp.zeros((b, h, lq, 1), jnp.float32)
    acc, m, l = _flash_call(qt, kt, vt, acc, m, l, 0, 0, causal=causal,
                            scale=scale, block_q=block_q, block_k=block_k)
    l = jnp.maximum(l, 1e-30)
    out = (acc / l).transpose(0, 2, 1, 3).astype(q.dtype)
    lse = (m + jnp.log(l))[..., 0]                       # [B, H, Lq]
    return out, lse


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6))
def _flash_attn_diff(q, k, v, causal, scale, block_q, block_k):
    out, _ = _flash_fwd_core(q, k, v, causal, scale, block_q, block_k)
    return out


def _flash_attn_fwd(q, k, v, causal, scale, block_q, block_k):
    out, lse = _flash_fwd_core(q, k, v, causal, scale, block_q, block_k)
    return out, (q, k, v, out, lse)


def _flash_attn_bwd(causal, scale, block_q, block_k, res, do):
    q, k, v, out, lse = res
    from ..common import config

    if config.get_str("HVDT_FLASH_BWD").lower() in ("kernel", "pallas"):
        # Pallas backward passes (flash_grad_block) instead of the
        # blockwise XLA recompute — A/B with HVDT_FLASH_BWD=kernel.
        # NOTE: this env read happens at TRACE time (custom_vjp bwd is
        # traced under jit); flipping the env after a grad function is
        # compiled does not change its backward until re-trace.  The
        # caller's forward block sizes are forwarded so the A/B against
        # the XLA path above is like-for-like (both re-fit internally).
        dq, dk, dv = flash_grad_block(q, k, v, do, out, lse,
                                      causal=causal, scale=scale,
                                      block_q=block_q, block_k=block_k)
        return (dq.astype(q.dtype), dk.astype(k.dtype),
                dv.astype(v.dtype))
    b, lq, h, d = q.shape
    lk, hkv = k.shape[1], k.shape[2]
    group = h // hkv
    # Backward tiles bounded independently of the forward kernel's
    # VMEM-tuned blocks: the [B,H,tq,blk] f32 score tile is the
    # backward's working set, so cap it ADAPTIVELY by B*H — at large
    # batch x heads a fixed 512x512 tile is a quarter-GB per
    # intermediate and XLA starts spilling (measured: BERT-Large
    # seq 4096 collapsed from 12.3k to 6.5k tok/s when batch doubled
    # the tile to 256 MB).  The budget also halves the 134 MB batch-8
    # config's tiles; measured harmless there (12.9k capped vs 12.3k
    # uncapped — smaller tiles cost nothing on this workload).
    blk = _fit_block(lk, min(block_k, 512), jnp.float32)
    tq = _fit_block(lq, min(block_q, 512), jnp.float32)
    tile_budget = 96 * 1024 * 1024                       # bytes, f32 tile
    while b * h * tq * blk * 4 > tile_budget and max(tq, blk) > 128:
        if blk >= tq and blk > 128:
            blk = _fit_block(lk, blk // 2, jnp.float32)
        else:
            tq = _fit_block(lq, tq // 2, jnp.float32)
    nblk, ntq = lk // blk, lq // tq

    f32 = jnp.float32
    # delta_i = sum_d do_i * o_i (rowsum term of dS), f32-accumulated
    # without materializing whole-sequence f32 copies of do/out — tiles
    # are upcast inside tile() instead (the [B,Lq,*,D] f32 copies would
    # cost ~3x 128 MB at the documented bf16 seq-8192 config).
    delta = jnp.einsum("bqhd,bqhd->bqh", do, out,
                       preferred_element_type=f32)

    from ..parallel.sharding import pcast_to_union

    def _v(x):
        return pcast_to_union(x, q, k, v, do)

    delta, lse = _v(delta), _v(lse)

    def tile(i, j, ks, vs):
        """Grad contributions of (q tile j) x (k block i)."""
        q_t = jax.lax.dynamic_slice_in_dim(q, j * tq, tq, 1).astype(f32)
        do_t = jax.lax.dynamic_slice_in_dim(do, j * tq, tq, 1).astype(f32)
        dl_t = jax.lax.dynamic_slice_in_dim(delta, j * tq, tq, 1)
        lse_t = jax.lax.dynamic_slice_in_dim(lse, j * tq, tq, 2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q_t, ks) * scale
        if causal:
            q_pos = j * tq + jnp.arange(tq)
            k_pos = i * blk + jnp.arange(blk)
            mask = q_pos[:, None] >= k_pos[None, :]
            s = jnp.where(mask[None, None], s, _NEG_INF)
        p = jnp.exp(s - lse_t[..., None])                # [B,H,tq,blk]
        dv_b = jnp.einsum("bhqk,bqhd->bkhd", p, do_t)
        dp = jnp.einsum("bqhd,bkhd->bhqk", do_t, vs)
        ds = p * (dp - dl_t.transpose(0, 2, 1)[..., None]) * scale
        dq_t = jnp.einsum("bhqk,bkhd->bqhd", ds, ks)
        dk_b = jnp.einsum("bhqk,bqhd->bkhd", ds, q_t)
        return dq_t, dk_b, dv_b

    def k_block(dq_acc, i):
        ks = jax.lax.dynamic_slice_in_dim(k, i * blk, blk, 1).astype(f32)
        vs = jax.lax.dynamic_slice_in_dim(v, i * blk, blk, 1).astype(f32)
        if group > 1:
            ks = jnp.repeat(ks, group, axis=2)
            vs = jnp.repeat(vs, group, axis=2)

        def q_tile(carry, j):
            dq_acc, dk_b, dv_b = carry

            def compute(args):
                dq_acc, dk_b, dv_b = args
                dq_t, dk_t, dv_t = tile(i, j, ks, vs)
                dq_acc = jax.lax.dynamic_update_slice_in_dim(
                    dq_acc,
                    jax.lax.dynamic_slice_in_dim(dq_acc, j * tq, tq, 1)
                    + dq_t, j * tq, 1)
                return dq_acc, dk_b + dk_t, dv_b + dv_t

            if causal:
                # Causal pruning (the forward kernel's flops halving,
                # mirrored): a q tile strictly above this K block's
                # first row is fully masked — skip its four einsums.
                visible = (j + 1) * tq - 1 >= i * blk
                dq_acc, dk_b, dv_b = jax.lax.cond(
                    visible, compute, lambda args: args,
                    (dq_acc, dk_b, dv_b))
            else:
                dq_acc, dk_b, dv_b = compute((dq_acc, dk_b, dv_b))
            return (dq_acc, dk_b, dv_b), None

        zeros_kv = _v(jnp.zeros((b, blk, h, d), f32))
        (dq_acc, dk_b, dv_b), _ = jax.lax.scan(
            q_tile, (dq_acc, zeros_kv, zeros_kv), jnp.arange(ntq))
        if group > 1:
            dk_b = dk_b.reshape(b, blk, hkv, group, d).sum(3)
            dv_b = dv_b.reshape(b, blk, hkv, group, d).sum(3)
        return dq_acc, (dk_b, dv_b)

    dq0 = _v(jnp.zeros((b, lq, h, d), f32))
    dq, (dk_blocks, dv_blocks) = jax.lax.scan(k_block, dq0,
                                              jnp.arange(nblk))
    dk = dk_blocks.transpose(1, 0, 2, 3, 4).reshape(b, lk, hkv, d)
    dv = dv_blocks.transpose(1, 0, 2, 3, 4).reshape(b, lk, hkv, d)
    return (dq.astype(q.dtype), dk.astype(k.dtype), dv.astype(v.dtype))


_flash_attn_diff.defvjp(_flash_attn_fwd, _flash_attn_bwd)


def flash_block_update(q: jax.Array, k_blk: jax.Array, v_blk: jax.Array,
                       acc: jax.Array, row_max: jax.Array,
                       row_sum: jax.Array, *, q_offset, k_offset,
                       causal: bool, scale: float,
                       block_q: int = 512, block_k: int = 1024
                       ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """One ring step in ring-attention layout.

    q/acc: [B, Lq, H, D]; k_blk/v_blk: [B, Lk, Hkv, D];
    row_max/row_sum: [B, H, Lq].  ``q_offset``/``k_offset`` are the global
    positions of the local shards (traced values are fine — they ride the
    scalar-prefetch arguments).
    """
    b, lq, h, d = q.shape
    block_q = _fit_block(lq, block_q, q.dtype)
    block_k = _fit_block(k_blk.shape[1], block_k, k_blk.dtype, v_blk.dtype)
    qt = q.transpose(0, 2, 1, 3)
    kt = k_blk.transpose(0, 2, 1, 3)
    vt = v_blk.transpose(0, 2, 1, 3)
    acc_t = acc.transpose(0, 2, 1, 3).astype(jnp.float32)
    m_t = row_max[..., None].astype(jnp.float32)
    l_t = row_sum[..., None].astype(jnp.float32)
    acc_t, m_t, l_t = _flash_call(
        qt, kt, vt, acc_t, m_t, l_t, q_offset, k_offset, causal=causal,
        scale=scale, block_q=block_q, block_k=block_k)
    return (acc_t.transpose(0, 2, 1, 3), m_t[..., 0], l_t[..., 0])


def _dq_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, dl_ref,
               lse_ref, dq_ref, dq_s, *, causal: bool, scale: float):
    """Grid (b, h, iq, ik), ik innermost: dq tile accumulated in VMEM
    scratch while K/V/dO stream; flushed at the last ik.  Standard flash
    backward dq pass with the saved logsumexp making the score recompute
    exact."""
    import jax.experimental.pallas as pl

    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(ik == 0)
    def _init():
        dq_s[...] = jnp.zeros_like(dq_s)

    def _compute():
        q = q_ref[0, 0, :, :]
        kb = k_ref[0, 0, :, :]
        vb = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse_ref[0, 0, :, :])
        if causal:
            q_pos = (qo_ref[0] + iq * bq
                     + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0))
            k_pos = (ko_ref[0] + ik * bk
                     + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1))
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, 0, :, :]) * scale
        dq_s[...] += jax.lax.dot_general(
            ds.astype(kb.dtype), kb, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        last_q = qo_ref[0] + iq * bq + (bq - 1)
        first_k = ko_ref[0] + ik * bk
        pl.when(last_q >= first_k)(_compute)
    else:
        _compute()

    @pl.when(ik == nk - 1)
    def _flush():
        dq_ref[0, 0, :, :] = dq_s[...]


def _dkv_kernel(qo_ref, ko_ref, q_ref, k_ref, v_ref, do_ref, dl_ref,
                lse_ref, dk_ref, dv_ref, dk_s, dv_s, *, causal: bool,
                scale: float):
    """Grid (b, h, ik, iq), iq innermost: dk/dv tiles accumulated in VMEM
    scratch while Q/dO stream past the resident K/V block."""
    import jax.experimental.pallas as pl

    ik, iq = pl.program_id(2), pl.program_id(3)
    nq = pl.num_programs(3)
    bq, bk = q_ref.shape[2], k_ref.shape[2]

    @pl.when(iq == 0)
    def _init():
        dk_s[...] = jnp.zeros_like(dk_s)
        dv_s[...] = jnp.zeros_like(dv_s)

    def _compute():
        q = q_ref[0, 0, :, :]
        kb = k_ref[0, 0, :, :]
        vb = v_ref[0, 0, :, :]
        do = do_ref[0, 0, :, :]
        s = jax.lax.dot_general(q, kb, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        p = jnp.exp(s - lse_ref[0, 0, :, :])
        if causal:
            q_pos = (qo_ref[0] + iq * bq
                     + jax.lax.broadcasted_iota(jnp.int32, (bq, 1), 0))
            k_pos = (ko_ref[0] + ik * bk
                     + jax.lax.broadcasted_iota(jnp.int32, (1, bk), 1))
            p = jnp.where(q_pos >= k_pos, p, 0.0)
        pb = p.astype(do.dtype)
        dv_s[...] += jax.lax.dot_general(
            pb, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, vb, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = p * (dp - dl_ref[0, 0, :, :]) * scale
        dk_s[...] += jax.lax.dot_general(
            ds.astype(q.dtype), q, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32)

    if causal:
        last_q = qo_ref[0] + iq * bq + (bq - 1)
        first_k = ko_ref[0] + ik * bk
        pl.when(last_q >= first_k)(_compute)
    else:
        _compute()

    @pl.when(iq == nq - 1)
    def _flush():
        dk_ref[0, 0, :, :] = dk_s[...]
        dv_ref[0, 0, :, :] = dv_s[...]


def flash_grad_block(q, k, v, do, out, lse, *, q_offset=0, k_offset=0,
                     causal: bool = True, scale: Optional[float] = None,
                     block_q: int = 512, block_k: int = 512,
                     delta: Optional[jax.Array] = None
                     ) -> Tuple[jax.Array, jax.Array, jax.Array]:
    """Pallas flash backward for one (Q block x K/V block) pair.

    The gradient counterpart of :func:`flash_block_update` — the piece
    that makes the Pallas ring-attention path trainable (VERDICT r2 #4):
    ``parallel/ring_attention.py`` calls it once per ring step with the
    visiting K/V block and its global offset, accumulating dK/dV that
    travel with the block.  Also usable as a whole-sequence flash
    backward (q_offset=k_offset=0).

    Layout matches the framework: q/do/out [B, Lq, H, D]; k/v
    [B, Lk, Hkv, D] (GQA: dk/dv are group-summed here); lse [B, H, Lq].
    Returns (dq, dk, dv) in f32.
    """
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, lq, h, d = q.shape
    lk, hkv = k.shape[1], k.shape[2]
    group = h // hkv
    if scale is None:
        scale = d ** -0.5
    block_q = _fit_block(lq, block_q, q.dtype)
    block_k = _fit_block(lk, block_k, k.dtype, v.dtype)

    if delta is None:
        delta = jnp.einsum("bqhd,bqhd->bqh", do, out,
                           preferred_element_type=jnp.float32)  # [B,Lq,H]
        delta = delta.transpose(0, 2, 1)                        # [B,H,Lq]
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    dot = do.transpose(0, 2, 1, 3)
    dl = delta[..., None]                                       # [B,H,Lq,1]
    lse_c = lse[..., None]                                      # [B,H,Lq,1]

    kw = _vma_kw(q, k, v, do, lse)

    qspec = pl.BlockSpec((1, 1, block_q, d),
                         lambda bb, hh, qq, kk, *_: (bb, hh, qq, 0))
    kvspec = pl.BlockSpec((1, 1, block_k, d),
                          lambda bb, hh, qq, kk, *_: (bb, hh // group, kk, 0))
    col_q = pl.BlockSpec((1, 1, block_q, 1),
                         lambda bb, hh, qq, kk, *_: (bb, hh, qq, 0))

    dq, = pl.pallas_call(
        functools.partial(_dq_kernel, causal=causal, scale=float(scale)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, lq // block_q, lk // block_k),
            in_specs=[qspec, kvspec, kvspec, qspec, col_q, col_q],
            out_specs=[qspec],
            scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)]),
        out_shape=(jax.ShapeDtypeStruct((b, h, lq, d), jnp.float32, **kw),),
        interpret=_use_interpret(),
    )(jnp.atleast_1d(q_offset).astype(jnp.int32),
      jnp.atleast_1d(k_offset).astype(jnp.int32),
      qt, kt, vt, dot, dl, lse_c)

    # dkv pass: grid loops K blocks outer, Q blocks inner.  BlockSpec
    # index maps receive (bb, hh, kk, qq).
    qspec2 = pl.BlockSpec((1, 1, block_q, d),
                          lambda bb, hh, kk, qq, *_: (bb, hh, qq, 0))
    kvspec2 = pl.BlockSpec((1, 1, block_k, d),
                           lambda bb, hh, kk, qq, *_:
                           (bb, hh // group, kk, 0))
    kvout2 = pl.BlockSpec((1, 1, block_k, d),
                          lambda bb, hh, kk, qq, *_: (bb, hh, kk, 0))
    col_q2 = pl.BlockSpec((1, 1, block_q, 1),
                          lambda bb, hh, kk, qq, *_: (bb, hh, qq, 0))
    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, causal=causal, scale=float(scale)),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=2,
            grid=(b, h, lk // block_k, lq // block_q),
            in_specs=[qspec2, kvspec2, kvspec2, qspec2, col_q2, col_q2],
            out_specs=[kvout2, kvout2],
            scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                            pltpu.VMEM((block_k, d), jnp.float32)]),
        out_shape=(jax.ShapeDtypeStruct((b, h, lk, d), jnp.float32, **kw),
                   jax.ShapeDtypeStruct((b, h, lk, d), jnp.float32, **kw)),
        interpret=_use_interpret(),
    )(jnp.atleast_1d(q_offset).astype(jnp.int32),
      jnp.atleast_1d(k_offset).astype(jnp.int32),
      qt, kt, vt, dot, dl, lse_c)

    dq = dq.transpose(0, 2, 1, 3)                               # [B,Lq,H,D]
    dk = dk.transpose(0, 2, 1, 3)                               # [B,Lk,H,D]
    dv = dv.transpose(0, 2, 1, 3)
    if group > 1:
        dk = dk.reshape(b, lk, hkv, group, d).sum(3)
        dv = dv.reshape(b, lk, hkv, group, d).sum(3)
    return dq, dk, dv


def _smallseq_fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref, *,
                         causal: bool, scale: float, group: int):
    """Grid (b, h//hb): the WHOLE sequence of ``hb`` heads per program.

    The streaming flash kernel (grid b x h x q-blocks x k-blocks) pays a
    fixed per-grid-step cost that dominates at short sequence / large
    batch*heads — measured 3x WORSE than XLA attention end-to-end at
    BERT-Large bs128 seq512 (tools/ab_results.json
    lm_flash_kernelbwd_bs128).  When the sequence fits one block there
    is nothing to stream: each (batch, head) is a self-contained
    softmax(qk')v in VMEM, so batch hb heads per program and skip the
    online-softmax carry entirely.  HBM traffic is O(L*D) like flash;
    grid steps drop hb*n_q_blocks*n_k_blocks-fold."""
    import jax.experimental.pallas as pl

    hb, l = q_ref.shape[1], q_ref.shape[2]

    # Always-true cond: under shard_map + interpret mode (the CPU test
    # path) TOP-LEVEL ref reads discharge to dynamic_slice whose vma
    # rule rejects varying-operand/unvarying-index mixes; inside a cond
    # the branch vma rule reconciles them (measured; jax 0.9 asks for an
    # upstream issue).  Free on TPU — one trivially-true predicate.
    @pl.when(pl.program_id(0) >= 0)
    def _body():
        if causal:
            mask = (jax.lax.broadcasted_iota(jnp.int32, (l, l), 0)
                    >= jax.lax.broadcasted_iota(jnp.int32, (l, l), 1))
        for i in range(hb):
            qh = q_ref[0, i, :, :]
            kh = k_ref[0, i // group, :, :]
            vh = v_ref[0, i // group, :, :]
            s = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=jnp.float32) * scale    # [L, L]
            if causal:
                s = jnp.where(mask, s, _NEG_INF)
            m = s.max(axis=-1, keepdims=True)
            p = jnp.exp(s - m)
            if causal:
                p = jnp.where(mask, p, 0.0)
            lsum = p.sum(axis=-1, keepdims=True)
            acc = jax.lax.dot_general(
                p.astype(vh.dtype), vh, (((1,), (0,)), ((), ())),
                preferred_element_type=jnp.float32)
            o_ref[0, i, :, :] = (acc / lsum).astype(o_ref.dtype)
            lse_ref[0, i, :, :] = m + jnp.log(lsum)


def _smallseq_bwd_kernel(q_ref, k_ref, v_ref, do_ref, out_ref, lse_ref,
                         dq_ref, dk_ref, dv_ref, *, causal: bool,
                         scale: float, group: int):
    """Grad counterpart of :func:`_smallseq_fwd_kernel`: one program
    computes dq/dk/dv for ``hb`` heads' full sequence, recomputing the
    probabilities from the saved logsumexp.  GQA: dk/dv accumulate over
    the ``group`` q-heads sharing each kv head (heads of a group are
    adjacent in the loop)."""
    import jax.experimental.pallas as pl

    hb, l = q_ref.shape[1], q_ref.shape[2]
    f32 = jnp.float32

    # Always-true cond: see _smallseq_fwd_kernel.
    @pl.when(pl.program_id(0) >= 0)
    def _body():
        if causal:
            mask = (jax.lax.broadcasted_iota(jnp.int32, (l, l), 0)
                    >= jax.lax.broadcasted_iota(jnp.int32, (l, l), 1))
        for i in range(hb):
            qh = q_ref[0, i, :, :]
            kh = k_ref[0, i // group, :, :]
            vh = v_ref[0, i // group, :, :]
            doh = do_ref[0, i, :, :]
            oh = out_ref[0, i, :, :]
            s = jax.lax.dot_general(
                qh, kh, (((1,), (1,)), ((), ())),
                preferred_element_type=f32) * scale
            p = jnp.exp(s - lse_ref[0, i, :, :])
            if causal:
                p = jnp.where(mask, p, 0.0)
            delta = (doh.astype(f32) * oh.astype(f32)).sum(-1,
                                                           keepdims=True)
            pb = p.astype(doh.dtype)
            dv_c = jax.lax.dot_general(
                pb, doh, (((0,), (0,)), ((), ())),
                preferred_element_type=f32)                   # [Lk, D]
            dp = jax.lax.dot_general(
                doh, vh, (((1,), (1,)), ((), ())),
                preferred_element_type=f32)                   # [Lq, Lk]
            ds = p * (dp - delta) * scale
            dsb = ds.astype(qh.dtype)
            dq_ref[0, i, :, :] = jax.lax.dot_general(
                dsb, kh, (((1,), (0,)), ((), ())),
                preferred_element_type=f32)
            dk_c = jax.lax.dot_general(
                dsb, qh, (((0,), (0,)), ((), ())),
                preferred_element_type=f32)                   # [Lk, D]
            if group > 1:
                first = (i % group == 0)
                dk_ref[0, i // group, :, :] = (
                    dk_c if first
                    else dk_ref[0, i // group, :, :] + dk_c)
                dv_ref[0, i // group, :, :] = (
                    dv_c if first
                    else dv_ref[0, i // group, :, :] + dv_c)
            else:
                dk_ref[0, i, :, :] = dk_c
                dv_ref[0, i, :, :] = dv_c


def _fit_heads_per_block(h: int, group: int, heads_per_block: int) -> int:
    """Largest hb <= requested that divides h and is a multiple of the
    GQA group (so a program's kv heads are whole blocks).  ``group`` is
    the floor: a request below it (or a nonsense knob value <= 0) clamps
    up to one whole kv group per program — never 0 (ZeroDivisionError)."""
    hb = max(min(heads_per_block, h), group)
    while h % hb or hb % group:
        hb -= 1
    return max(hb, group)


def _smallseq_call(q, k, v, causal, scale, hb):
    """Forward pallas_call in [B, H, L, D]; returns (out, lse)."""
    import jax.experimental.pallas as pl

    b, h, l, d = q.shape
    hkv = k.shape[1]
    group = h // hkv
    hb_kv = hb // group
    kw = _vma_kw(q, k, v)
    qspec = pl.BlockSpec((1, hb, l, d), lambda bb, hh: (bb, hh, 0, 0))
    kvspec = pl.BlockSpec((1, hb_kv, l, d), lambda bb, hh: (bb, hh, 0, 0))
    col = pl.BlockSpec((1, hb, l, 1), lambda bb, hh: (bb, hh, 0, 0))
    return pl.pallas_call(
        functools.partial(_smallseq_fwd_kernel, causal=causal,
                          scale=scale, group=group),
        grid=(b, h // hb),
        in_specs=[qspec, kvspec, kvspec],
        out_specs=[qspec, col],
        out_shape=(jax.ShapeDtypeStruct((b, h, l, d), q.dtype, **kw),
                   jax.ShapeDtypeStruct((b, h, l, 1), jnp.float32, **kw)),
        interpret=_use_interpret(),
    )(q, k, v)


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5))
def _smallseq_diff(q, k, v, causal, scale, hb):
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out, _ = _smallseq_call(qt, kt, vt, causal, scale, hb)
    return out.transpose(0, 2, 1, 3)


def _smallseq_diff_fwd(q, k, v, causal, scale, hb):
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    out, lse = _smallseq_call(qt, kt, vt, causal, scale, hb)
    return out.transpose(0, 2, 1, 3), (q, k, v, out, lse)


def _smallseq_diff_bwd(causal, scale, hb, res, do):
    import jax.experimental.pallas as pl

    q, k, v, out_t, lse = res                  # out_t/lse in [B,H,L,D/1]
    b, lq, h, d = q.shape
    hkv = k.shape[2]
    group = h // hkv
    hb_kv = hb // group
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    dot = do.transpose(0, 2, 1, 3)
    kw = _vma_kw(q, k, v, do)
    qspec = pl.BlockSpec((1, hb, lq, d), lambda bb, hh: (bb, hh, 0, 0))
    kvspec = pl.BlockSpec((1, hb_kv, lq, d), lambda bb, hh: (bb, hh, 0, 0))
    col = pl.BlockSpec((1, hb, lq, 1), lambda bb, hh: (bb, hh, 0, 0))
    dq, dk, dv = pl.pallas_call(
        functools.partial(_smallseq_bwd_kernel, causal=causal,
                          scale=scale, group=group),
        grid=(b, h // hb),
        in_specs=[qspec, kvspec, kvspec, qspec, qspec, col],
        out_specs=[qspec, kvspec, kvspec],
        out_shape=(
            jax.ShapeDtypeStruct((b, h, lq, d), jnp.float32, **kw),
            jax.ShapeDtypeStruct((b, hkv, lq, d), jnp.float32, **kw),
            jax.ShapeDtypeStruct((b, hkv, lq, d), jnp.float32, **kw)),
        interpret=_use_interpret(),
    )(qt, kt, vt, dot, out_t, lse)
    return (dq.transpose(0, 2, 1, 3).astype(q.dtype),
            dk.transpose(0, 2, 1, 3).astype(k.dtype),
            dv.transpose(0, 2, 1, 3).astype(v.dtype))


_smallseq_diff.defvjp(_smallseq_diff_fwd, _smallseq_diff_bwd)


def flash_attention_smallseq(q: jax.Array, k: jax.Array, v: jax.Array, *,
                             causal: bool = True,
                             scale: Optional[float] = None,
                             heads_per_block: int = 8) -> jax.Array:
    """Head-batched single-block fused attention for the short-sequence
    regime (the BERT-Large-shape complement of :func:`flash_attention`).

    Same API/layout as flash_attention (q/k/v: [B, L, H, D], GQA via
    fewer kv heads, differentiable — the backward is a single Pallas
    program per (batch, head-block) recomputing probabilities from the
    saved logsumexp).  Use when the sequence fits one VMEM block
    (L <= ~1024): HBM never sees a score matrix AND the grid is
    b*h/hb programs instead of flash's b*h*n_q*n_k.
    """
    b, l, h, d = q.shape
    hkv = k.shape[2]
    if h % hkv:
        raise ValueError(f"q heads {h} not divisible by kv heads {hkv}")
    if k.shape[1] != l:
        raise ValueError("flash_attention_smallseq needs lq == lk "
                         f"(got {l} vs {k.shape[1]})")
    if scale is None:
        scale = d ** -0.5
    # Same sublane-tile floor as _fit_block: the [L, D] per-head tile.
    _fit_block(l, l, q.dtype, k.dtype, v.dtype)
    hb = _fit_heads_per_block(h, h // hkv, heads_per_block)
    return _smallseq_diff(q, k, v, causal, float(scale), hb)


def attention_reference(q, k, v, *, causal=True, scale=None):
    """Naive jnp attention (materializes scores) — the correctness oracle."""
    b, lq, h, d = q.shape
    hkv = k.shape[2]
    if scale is None:
        scale = d ** -0.5
    if h != hkv:
        k = jnp.repeat(k, h // hkv, axis=2)
        v = jnp.repeat(v, h // hkv, axis=2)
    s = jnp.einsum("bqhd,bkhd->bhqk", q.astype(jnp.float32),
                   k.astype(jnp.float32)) * scale
    if causal:
        lk = k.shape[1]
        mask = jnp.arange(lq)[:, None] >= jnp.arange(lk)[None, :]
        s = jnp.where(mask[None, None], s, _NEG_INF)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p,
                      v.astype(jnp.float32)).astype(q.dtype)
