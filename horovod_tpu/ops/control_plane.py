"""Control-plane transports for eager negotiation.

TPU-native analog of the reference's Controller transport layer
(ref: common/controller.h:47-64 — six pure-virtual comm primitives
implemented per backend: mpi/mpi_controller.cc, gloo/gloo_controller.cc).

On TPU the idiomatic control plane is the JAX coordination service (the
same service `jax.distributed.initialize` stands up for rendezvous), used
as a key-value store + barrier — replacing MPI_Gatherv/MPI_Bcast.  The
primitives here are deliberately coarser than the reference's six
(gather-to-root + broadcast-from-root + barrier) because a KV round trip
dominates either way.

A Local transport serves single-process runs (the negotiation degenerates
but queue/fusion/cache/timeline still run, preserving eager semantics).
"""

from __future__ import annotations

import abc
import threading
import time
from typing import List, Optional

__all__ = ["ControlPlane", "LocalControlPlane", "CoordServiceControlPlane",
           "default_control_plane"]


class ControlPlane(abc.ABC):
    """Blocking, cycle-synchronous control collectives over process ranks."""

    @abc.abstractmethod
    def rank(self) -> int: ...

    @abc.abstractmethod
    def size(self) -> int: ...

    @abc.abstractmethod
    def gather(self, payload: str, cycle: int) -> Optional[List[str]]:
        """All ranks submit a payload; returns the rank-ordered list on rank
        0, None elsewhere (ref: RecvReadyTensors/SendReadyTensors,
        mpi_controller.cc:135,191)."""

    @abc.abstractmethod
    def broadcast(self, payload: Optional[str], cycle: int) -> str:
        """Rank 0 provides payload; everyone returns it
        (ref: SendFinalTensors = MPI_Bcast, mpi_controller.cc:180)."""

    @abc.abstractmethod
    def barrier(self, tag: str = "") -> None: ...

    def shutdown(self) -> None:
        pass


class LocalControlPlane(ControlPlane):
    """Single-process control plane — trivial negotiation."""

    def rank(self) -> int:
        return 0

    def size(self) -> int:
        return 1

    def gather(self, payload: str, cycle: int) -> Optional[List[str]]:
        return [payload]

    def broadcast(self, payload: Optional[str], cycle: int) -> str:
        assert payload is not None
        return payload

    def barrier(self, tag: str = "") -> None:
        return None


class CoordServiceControlPlane(ControlPlane):
    """Negotiation over the JAX coordination service KV store.

    Key scheme: ``hvdt/<namespace>/<cycle>/g<rank>`` for gather payloads and
    ``hvdt/<namespace>/<cycle>/resp`` for the response broadcast.  Cycle
    counters advance in lockstep on every rank (each rank participates in
    every negotiation cycle, exactly like the reference's RunLoopOnce),
    which keeps keys unique without deletion races; old keys are deleted
    opportunistically a few cycles later.
    """

    def __init__(self, namespace: str = "ctl",
                 timeout_s: Optional[float] = None):
        import jax

        from jax._src import distributed as _dist

        if timeout_s is None:
            # Failure-detection latency bound: a dead peer surfaces as
            # this timeout expiring in gather/broadcast, which the
            # controller converts into HorovodInternalError → elastic
            # recovery.  Chaos tests shrink it to recover in seconds.
            from ..common import config

            timeout_s = config.get_float("HVDT_CONTROL_PLANE_TIMEOUT_S")

        client = getattr(_dist.global_state, "client", None)
        if client is None:
            raise RuntimeError(
                "JAX distributed runtime is not initialized; "
                "CoordServiceControlPlane requires jax.distributed.initialize")
        self._client = client
        self._ns = namespace
        self._rank = jax.process_index()
        self._size = jax.process_count()
        self._timeout_ms = int(timeout_s * 1000)

    def rank(self) -> int:
        return self._rank

    def size(self) -> int:
        return self._size

    def _key(self, cycle: int, suffix: str) -> str:
        return f"hvdt/{self._ns}/{cycle}/{suffix}"

    def gather(self, payload: str, cycle: int) -> Optional[List[str]]:
        self._client.key_value_set(self._key(cycle, f"g{self._rank}"), payload)
        if self._rank != 0:
            return None
        out = []
        for r in range(self._size):
            out.append(self._client.blocking_key_value_get(
                self._key(cycle, f"g{r}"), self._timeout_ms))
        return out

    def broadcast(self, payload: Optional[str], cycle: int) -> str:
        key = self._key(cycle, "resp")
        if self._rank == 0:
            assert payload is not None
            self._client.key_value_set(key, payload)
            self._gc(cycle)
            return payload
        val = self._client.blocking_key_value_get(key, self._timeout_ms)
        return val

    def _gc(self, cycle: int, keep: int = 8) -> None:
        # Opportunistic deletion of stale cycle keys (rank 0 only).
        old = cycle - keep
        if old < 0:
            return
        try:
            self._client.key_value_delete(f"hvdt/{self._ns}/{old}/")
        except Exception:
            pass

    def barrier(self, tag: str = "") -> None:
        self._client.wait_at_barrier(
            f"hvdt/{self._ns}/barrier/{tag}", self._timeout_ms)


def default_control_plane() -> ControlPlane:
    """Pick the control plane for the current topology."""
    import jax

    from ..common import basics

    if basics.size() > 1:
        return CoordServiceControlPlane()
    return LocalControlPlane()
