from . import device  # noqa: F401
