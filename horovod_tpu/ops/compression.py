"""Gradient compression for the wire.

TPU-native analog of the reference's compression algorithms
(ref: horovod/torch/compression.py:1-74, tensorflow/compression.py:1-141 —
NoneCompressor / FP16Compressor selected via ``Compression.fp16``).

On TPU the natural wire dtype is bfloat16 (same exponent range as f32 — no
loss-scaling gymnastics needed, and the MXU-native type), so ``fp16`` maps
to bf16 by default; IEEE float16 remains available for parity.  In the jit
path the framework consumes only ``wire_dtype`` — the cast target of the
fused collective (optimizer.py → fused_allreduce).  ``compress``/
``decompress`` mirror the reference's optimizer-level API for user code
that wants explicit round-trip casts around eager ops.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

__all__ = ["Compressor", "NoneCompressor", "FP16Compressor",
           "BF16Compressor", "Compression"]


class Compressor:
    """Interface (ref: compression.py Compressor.compress/decompress)."""

    wire_dtype: Optional[Any] = None  # jit-path fused-collective cast target

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    wire_dtype = None

    @staticmethod
    def compress(tensor) -> Tuple[Any, Any]:
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    _cast_to: Any = None

    @classmethod
    def compress(cls, tensor) -> Tuple[Any, Any]:
        dtype = getattr(tensor, "dtype", None)
        if dtype is not None and np.dtype(dtype).kind == "f":
            return tensor.astype(cls._cast_to), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    _cast_to = np.float16
    wire_dtype = np.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = "bfloat16"

    @classmethod
    def compress(cls, tensor):
        dtype = getattr(tensor, "dtype", None)
        if dtype is None or np.dtype(dtype).kind != "f":
            return tensor, None
        if type(tensor).__module__.startswith("jax"):
            import jax.numpy as jnp

            return tensor.astype(jnp.bfloat16), dtype
        # numpy path via ml_dtypes — deliberately jax-free so host-side
        # users (the torch grad-hook optimizer) never trigger an
        # accelerator backend init just to cast a gradient.
        import ml_dtypes

        return np.asarray(tensor).astype(ml_dtypes.bfloat16), dtype

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class Compression:
    """Option enum-style holder (ref: compression.py Compression)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
