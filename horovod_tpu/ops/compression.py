"""Gradient compression for the wire.

TPU-native analog of the reference's compression algorithms
(ref: horovod/torch/compression.py:1-74, tensorflow/compression.py:1-141 —
NoneCompressor / FP16Compressor selected via ``Compression.fp16``).

On TPU the natural wire dtype is bfloat16 (same exponent range as f32 — no
loss-scaling gymnastics needed, and the MXU-native type), so ``fp16`` maps
to bf16 by default; IEEE float16 remains available for parity.  In the jit
path the framework consumes only ``wire_dtype`` — the cast target of the
fused collective (optimizer.py → fused_allreduce).  ``compress``/
``decompress`` mirror the reference's optimizer-level API for user code
that wants explicit round-trip casts around eager ops.

Beyond the reference: ``Compression.int8`` / ``Compression.int4``
select the block-scaled quantized wire (horovod_tpu/quant/ —
EQuARX-style int8 or packed sub-byte int4 payload + f32 block scales,
with the two-stage quantized collective on the jit path).
Compressors are also selectable by NAME from the environment
(``HVDT_COMPRESSION=none|bf16|fp16|int8|int4``, or ``HVDT_QUANT=1`` as
the int8 shorthand) via :meth:`Compression.from_env`, consumed by
``hvd.init()`` and the optimizer wrappers when no explicit
``compression=`` is passed; the launcher forwards ``--compression``.
"""

from __future__ import annotations

from typing import Any, Optional, Tuple

import numpy as np

__all__ = ["Compressor", "NoneCompressor", "FP16Compressor",
           "BF16Compressor", "Int8Compressor", "Int4Compressor",
           "Compression"]


class Compressor:
    """Interface (ref: compression.py Compressor.compress/decompress)."""

    wire_dtype: Optional[Any] = None  # jit-path fused-collective cast target

    @staticmethod
    def compress(tensor):
        raise NotImplementedError

    @staticmethod
    def decompress(tensor, ctx):
        raise NotImplementedError


class NoneCompressor(Compressor):
    wire_dtype = None

    @staticmethod
    def compress(tensor) -> Tuple[Any, Any]:
        return tensor, None

    @staticmethod
    def decompress(tensor, ctx):
        return tensor


class _CastCompressor(Compressor):
    _cast_to: Any = None

    @classmethod
    def compress(cls, tensor) -> Tuple[Any, Any]:
        dtype = getattr(tensor, "dtype", None)
        if dtype is not None and np.dtype(dtype).kind == "f":
            return tensor.astype(cls._cast_to), dtype
        return tensor, None

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class FP16Compressor(_CastCompressor):
    _cast_to = np.float16
    wire_dtype = np.float16


class BF16Compressor(_CastCompressor):
    wire_dtype = "bfloat16"

    @classmethod
    def compress(cls, tensor):
        dtype = getattr(tensor, "dtype", None)
        if dtype is None or np.dtype(dtype).kind != "f":
            return tensor, None
        if type(tensor).__module__.startswith("jax"):
            import jax.numpy as jnp

            return tensor.astype(jnp.bfloat16), dtype
        # numpy path via ml_dtypes — deliberately jax-free so host-side
        # users (the torch grad-hook optimizer) never trigger an
        # accelerator backend init just to cast a gradient.
        import ml_dtypes

        return np.asarray(tensor).astype(ml_dtypes.bfloat16), dtype

    @classmethod
    def decompress(cls, tensor, ctx):
        if ctx is not None:
            return tensor.astype(ctx)
        return tensor


class Int8Compressor(Compressor):
    """Block-scaled symmetric int8 wire (horovod_tpu/quant/).

    jit path: ``wire_dtype`` is the :data:`~..quant.collectives.INT8_WIRE`
    sentinel — ``fused_allreduce`` routes each float bucket through the
    two-stage quantized collective (real int8 payloads + f32 block
    scales on the wire, f32 accumulation in the middle).

    Host/eager path (``compress``/``decompress`` — the torch grad-hook
    and tf/mxnet binding route): the negotiated collective reduces one
    homogeneous buffer, so ``compress`` returns the gradient *snapped to
    the int8 grid* (quantize→dequantize) in its original dtype — the
    exact value the real wire would deliver, so convergence behaviour
    (and error-feedback residuals) match the jit path, while the bytes
    ride the negotiated transport uncompressed.  For true host wire
    compression use ``quant.eager_quantized_allreduce`` (packed
    allgather; wins for small world sizes)."""

    wire_dtype = "int8_blockwise"   # == quant.collectives.INT8_WIRE

    @classmethod
    def compress(cls, tensor) -> Tuple[Any, Any]:
        dtype = getattr(tensor, "dtype", None)
        if dtype is None or np.dtype(dtype).kind != "f":
            return tensor, None
        if type(tensor).__module__.startswith("jax"):
            from ..quant import kernels as _qk

            return _qk.quantize_dequantize(tensor), None
        # numpy path — jax-free on purpose (same rationale as
        # BF16Compressor: host-side users must not trigger an
        # accelerator backend init to compress a gradient).
        return cls._np_quantize_dequantize(np.asarray(tensor)), None

    @classmethod
    def decompress(cls, tensor, ctx):
        del ctx  # on-grid values ARE the decompressed representation
        return tensor

    # Quantization grid: (divisor, clip) — int8's absmax/127 grid.
    _GRID = (127.0, 127)

    @classmethod
    def _np_quantize_dequantize(cls, arr: np.ndarray) -> np.ndarray:
        """Numpy mirror of quant.kernels.quantize_dequantize (identical
        block math; np.rint and jnp.round are both round-half-even)."""
        from ..common import config

        div, clip = cls._GRID
        block = config.get_int("HVDT_QUANT_BLOCK")
        block = block if block > 0 else 256
        shape, dtype = arr.shape, arr.dtype
        flat = arr.astype(np.float32).ravel()
        pad = (-flat.size) % block
        if pad:
            flat = np.concatenate([flat, np.zeros(pad, np.float32)])
        x2 = flat.reshape(-1, block)
        scale = np.max(np.abs(x2), axis=1, keepdims=True) * (1.0 / div)
        inv = np.where(scale > 0,
                       1.0 / np.where(scale > 0, scale, 1.0), 0.0)
        q = np.clip(np.rint(x2 * inv), -clip, clip)
        out = (q * scale).reshape(-1)
        if pad:
            out = out[:-pad]
        return out.reshape(shape).astype(dtype)


class Int4Compressor(Int8Compressor):
    """Packed sub-byte int4 wire (two 4-bit lanes per byte,
    absmax/7 block scales) — same contract as :class:`Int8Compressor`
    with the coarser grid; pair with
    ``quant.with_error_feedback(wire='int4')`` to carry the larger
    rounding error forward.  jit path: ``wire_dtype`` is the
    :data:`~..quant.collectives.INT4_WIRE` sentinel; host path snaps to
    the int4 grid."""

    wire_dtype = "int4_blockwise"   # == quant.collectives.INT4_WIRE
    _GRID = (7.0, 7)

    @classmethod
    def compress(cls, tensor) -> Tuple[Any, Any]:
        dtype = getattr(tensor, "dtype", None)
        if dtype is None or np.dtype(dtype).kind != "f":
            return tensor, None
        if type(tensor).__module__.startswith("jax"):
            from ..quant import kernels as _qk

            return _qk.quantize_dequantize_int4(tensor), None
        return cls._np_quantize_dequantize(np.asarray(tensor)), None


class Compression:
    """Option enum-style holder (ref: compression.py Compression)."""

    none = NoneCompressor
    fp16 = FP16Compressor
    bf16 = BF16Compressor
    int8 = Int8Compressor
    int4 = Int4Compressor

    _BY_NAME = {"none": NoneCompressor, "fp16": FP16Compressor,
                "bf16": BF16Compressor, "int8": Int8Compressor,
                "int4": Int4Compressor}

    @classmethod
    def by_name(cls, name: str) -> type:
        """Resolve a compressor by name; unknown names raise with the
        valid list (the env-selection contract)."""
        key = (name or "none").strip().lower()
        try:
            return cls._BY_NAME[key]
        except KeyError:
            raise ValueError(
                f"unknown compression {name!r}; valid: "
                f"{sorted(cls._BY_NAME)}") from None

    @classmethod
    def from_env(cls) -> type:
        """The environment-selected compressor: ``HVDT_QUANT=1`` forces
        int8, else ``HVDT_COMPRESSION`` by name (empty = none).
        Consumed by ``hvd.init()`` (early validation) and by every
        optimizer wrapper whose ``compression=`` is left unset."""
        from ..common import config

        if config.get_bool("HVDT_QUANT"):
            return Int8Compressor
        return cls.by_name(config.get_str("HVDT_COMPRESSION") or "none")
