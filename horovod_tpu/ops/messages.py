"""Wire messages for the eager negotiation protocol.

TPU-native analog of the reference's Request/Response wire layer
(ref: common/message.{h,cc} — Request message.h:50, Response message.h:153;
flatbuffers schema common/wire/message.fbs).

The reference serializes with FlatBuffers because the C++ hot loop parses
thousands of these per second; our control plane exchanges them over the JAX
coordination-service KV a handful of times per cycle, so compact JSON is the
idiomatic choice (schema kept field-compatible so a native C++ fast path can
swap in — see native/).
"""

from __future__ import annotations

import dataclasses
import enum
import json
from typing import List, Optional, Sequence, Tuple

from ..common.types import DataType, ReduceOp

__all__ = ["RequestType", "Request", "Response", "encode_request_list",
           "decode_request_list", "encode_response_list",
           "decode_response_list"]


class RequestType(enum.IntEnum):
    """(ref: message.h:52-60 Request::RequestType)"""

    ALLREDUCE = 0
    ALLGATHER = 1
    BROADCAST = 2
    JOIN = 3
    ADASUM = 4
    ALLTOALL = 5
    BARRIER = 6
    REDUCESCATTER = 7  # TPU-native extension (first-class on TPU)


@dataclasses.dataclass
class Request:
    """One rank's announcement that a named tensor is ready
    (ref: message.h:50-150)."""

    request_rank: int
    request_type: RequestType
    tensor_name: str
    tensor_type: int               # DataType value
    tensor_shape: Tuple[int, ...]
    reduce_op: int = int(ReduceOp.AVERAGE)
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    root_rank: int = -1            # broadcast only
    splits: Tuple[int, ...] = ()   # alltoall only
    process_set_id: int = 0
    group_id: int = -1             # grouped-allreduce membership

    def descriptor(self) -> Tuple:
        """The fields that must agree across ranks (ref: ConstructResponse
        shape/dtype cross-validation, controller.cc:495).  Allgather and
        alltoall legitimately differ in dim 0 across ranks (ragged/uneven),
        so only trailing dims participate for those ops."""
        if self.request_type in (RequestType.ALLGATHER, RequestType.ALLTOALL):
            shape_part = self.tensor_shape[1:]
        else:
            shape_part = self.tensor_shape
        return (self.request_type, self.tensor_type, shape_part,
                self.reduce_op, self.root_rank, self.process_set_id)

    def to_obj(self) -> list:
        return [self.request_rank, int(self.request_type), self.tensor_name,
                self.tensor_type, list(self.tensor_shape), self.reduce_op,
                self.prescale_factor, self.postscale_factor, self.root_rank,
                list(self.splits), self.process_set_id, self.group_id]

    @staticmethod
    def from_obj(o: list) -> "Request":
        return Request(o[0], RequestType(o[1]), o[2], o[3], tuple(o[4]), o[5],
                       o[6], o[7], o[8], tuple(o[9]), o[10], o[11])


@dataclasses.dataclass
class Response:
    """Coordinator's instruction to execute a (possibly fused) collective
    (ref: message.h:153-262 — fused tensor_names + tensor_sizes + error)."""

    response_type: RequestType
    tensor_names: List[str]
    error_message: str = ""
    # per-tensor shapes so joined/late ranks can materialize zero inputs
    # (ref: Response::tensor_sizes)
    tensor_shapes: List[Tuple[int, ...]] = dataclasses.field(default_factory=list)
    tensor_type: int = 0
    reduce_op: int = int(ReduceOp.AVERAGE)
    prescale_factor: float = 1.0
    postscale_factor: float = 1.0
    root_rank: int = -1
    recv_splits: List[Tuple[int, ...]] = dataclasses.field(default_factory=list)
    process_set_id: int = 0
    last_joined_rank: int = -1

    def to_obj(self) -> list:
        return [int(self.response_type), self.tensor_names, self.error_message,
                [list(s) for s in self.tensor_shapes], self.tensor_type,
                self.reduce_op, self.prescale_factor, self.postscale_factor,
                self.root_rank, [list(s) for s in self.recv_splits],
                self.process_set_id, self.last_joined_rank]

    @staticmethod
    def from_obj(o: list) -> "Response":
        return Response(RequestType(o[0]), list(o[1]), o[2],
                        [tuple(s) for s in o[3]], o[4], o[5], o[6], o[7],
                        o[8], [tuple(s) for s in o[9]], o[10], o[11])


def encode_request_list(reqs: Sequence[Request]) -> str:
    return json.dumps({"r": [r.to_obj() for r in reqs]})


def decode_request_list(data: str) -> List[Request]:
    obj = json.loads(data)
    return [Request.from_obj(o) for o in obj["r"]]


def encode_response_list(resps: Sequence[Response]) -> str:
    return json.dumps([r.to_obj() for r in resps])


def decode_response_list(data: str) -> List[Response]:
    return [Response.from_obj(o) for o in json.loads(data)]
