"""Fused 1x1-conv (matmul) + BatchNorm-affine epilogue Pallas kernel.

The below-XLA ResNet roofline probe (VERDICT r4 weak #3): the bs128
ResNet-50 step is pinned at the HBM roofline (``hbm_util`` 1.0,
docs/performance.md), and the two residual traffic levers round 2 named
— conv layout copies and unfused BN passes — were never probed beneath
XLA.  A 1x1 convolution IS a matmul over the flattened spatial grid
(``[B*H*W, Cin] @ [Cin, Cout]``), and the bottleneck blocks'
1x1 convs carry most of ResNet-50's conv FLOPs
(models/resnet.py:_bottleneck — conv1/conv3 of every block; ref: the
same blocks in the reference's synthetic ResNet benchmark,
examples/pytorch/pytorch_synthetic_benchmark.py).  This kernel computes

    y = relu((x @ w) * scale + bias)

in one pass: tiled MXU matmul with f32 VMEM accumulation and the BN
affine (normalized/inference form — scale and bias folded from
gamma/beta/mean/var) applied in the epilogue before the single bf16
HBM write.  If XLA already fuses the affine into its conv output, the
A/B (tools/resnet_probe.py) shows parity and closes the lever with a
number; if not, the delta is the banked win.

Runs in Pallas interpret mode off-TPU so the CPU suite exercises the
same kernel code (tests/test_pallas.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

# Shared with the attention kernels: the interpret-mode switch and the
# dtype-aware block fitter (per-dtype sublane floors — bf16 needs 16
# rows on real TPU; a hand-rolled 8-row check would pass interpret-mode
# tests and then fail Mosaic lowering on hardware).
from .pallas_kernels import _fit_block, _use_interpret

__all__ = ["matmul_bn_relu", "conv1x1_bn_relu", "conv1x1_bn_relu_reference"]


def _mm_kernel(a_ref, w_ref, s_ref, b_ref, o_ref, acc_ref, *, relu: bool):
    """Grid program (i, j, k): accumulate one K-block into the f32 VMEM
    accumulator; on the last K step apply the BN affine (+ReLU) and make
    the ONLY HBM write of this output tile."""
    import jax.experimental.pallas as pl

    @pl.when(pl.program_id(2) == 0)
    def _zero():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(a_ref[...], w_ref[...],
                            preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _epilogue():
        y = acc_ref[...] * s_ref[...] + b_ref[...]
        if relu:
            y = jnp.maximum(y, 0.0)
        o_ref[...] = y.astype(o_ref.dtype)


def matmul_bn_relu(a: jax.Array, w: jax.Array, scale: jax.Array,
                   bias: jax.Array, *, relu: bool = True,
                   block_m: int = 512, block_n: int = 256,
                   block_k: int = 512) -> jax.Array:
    """``relu((a @ w) * scale + bias)`` with the affine fused into the
    matmul epilogue.  a: [M, K]; w: [K, N]; scale/bias: [N] (f32);
    returns [M, N] in ``a``'s dtype with f32 accumulation throughout.

    Differentiable (``custom_vjp``): the backward recomputes the
    pre-activation ``z = a @ w`` instead of saving it — rematerialized
    FLOPs on the MXU, zero extra residual HBM traffic (recovering z
    from the saved output would be cheaper still, but is undefined at
    ``scale == 0``, which zero-init-gamma ResNets hit on every residual
    block's last BN).  The backward matmuls run in XLA (MXU-shaped
    dots; fusing them into Pallas is a further step only if the forward
    probe banks a win)."""
    return _mm_diff(a, w, scale, bias, relu, block_m, block_n, block_k)


def _mm_forward(a, w, scale, bias, relu, block_m, block_n, block_k):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = a.shape
    k2, n = w.shape
    if k != k2:
        raise ValueError(f"a has K={k} but w has K={k2}")
    if scale.shape != (n,) or bias.shape != (n,):
        raise ValueError(
            f"scale/bias must be [{n}], got {scale.shape}/{bias.shape}")
    # _fit_block enforces the per-dtype sublane floor on real TPU (and
    # raises loudly); the lane (N) dimension needs full 128-lane tiles,
    # checked here.
    bm = _fit_block(m, block_m, a.dtype)
    bk = _fit_block(k, block_k, a.dtype, w.dtype)
    bn = min(block_n, n)
    while n % bn:
        bn //= 2
    if bn < 128:
        raise ValueError(
            f"N={n} only tiles at {bn} lanes — below the 128-lane TPU "
            "tile floor; pad the channel dim to a multiple of 128")
    grid = (m // bm, n // bn, k // bk)

    kwargs = {}
    if not _use_interpret():
        # M/N tiles are independent; only K carries the accumulator.
        params_cls = getattr(pltpu, "CompilerParams",
                             getattr(pltpu, "TPUCompilerParams", None))
        if params_cls is not None:
            kwargs["compiler_params"] = params_cls(
                dimension_semantics=("parallel", "parallel", "arbitrary"))

    return pl.pallas_call(
        functools.partial(_mm_kernel, relu=relu),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=_use_interpret(),
        **kwargs,
    )(a, w, scale.astype(jnp.float32).reshape(1, n),
      bias.astype(jnp.float32).reshape(1, n))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _mm_diff(a, w, scale, bias, relu, block_m, block_n, block_k):
    return _mm_forward(a, w, scale, bias, relu, block_m, block_n, block_k)


def _mm_diff_fwd(a, w, scale, bias, relu, block_m, block_n, block_k):
    y = _mm_forward(a, w, scale, bias, relu, block_m, block_n, block_k)
    # Residuals: y feeds only the relu mask — with relu=False (the
    # zero-init-gamma residual placement) it is dead in the backward
    # and must not pin an [M, N] activation.  bias ([N], negligible)
    # rides along for its dtype.
    return y, (a, w, scale, bias, y if relu else None)


def _mm_diff_bwd(relu, block_m, block_n, block_k, res, dy):
    """g = dy * 1[y>0]; dz = g * scale; da = dz w^T; dw = a^T dz;
    dbias = sum_M g; dscale = sum_M g*z with z = a @ w RECOMPUTED
    (bf16 operands, f32 accumulation — the forward kernel's own
    precision) — exact for every scale (including the zero-init-gamma
    case where z cannot be recovered from the saved output).

    ReLU subgradient convention: relu'(0) = 0 (the flash-kernel norm;
    jnp.maximum's autodiff instead splits ties 0.5).  Units at EXACTLY
    zero pre-activation get zero gradient — note zero-init gamma
    belongs on a residual block's LAST BN, where the add precedes the
    relu, i.e. this kernel runs with relu=False and gamma trains."""
    a, w, scale, bias, y = res
    f32 = jnp.float32
    g = dy.astype(f32)
    if relu:
        g = jnp.where(y.astype(f32) > 0, g, 0.0)
    # Native-dtype operands + f32 accumulation: no materialized f32
    # copies of a/w, full bf16 MXU rate on the backward dots.
    dz = g * scale.astype(f32)
    da = jnp.dot(dz.astype(a.dtype), w.T,
                 preferred_element_type=f32).astype(a.dtype)
    dw = jnp.dot(a.T, dz.astype(a.dtype),
                 preferred_element_type=f32).astype(w.dtype)
    dbias = g.sum(axis=0).astype(bias.dtype)
    z = jnp.dot(a, w, preferred_element_type=f32)
    dscale = (g * z).sum(axis=0).astype(scale.dtype)
    return da, dw, dscale, dbias


_mm_diff.defvjp(_mm_diff_fwd, _mm_diff_bwd)


def conv1x1_bn_relu(x: jax.Array, w: jax.Array, scale: jax.Array,
                    bias: jax.Array, *, relu: bool = True) -> jax.Array:
    """Fused NHWC 1x1 conv + BN affine (+ReLU).  x: [B, H, W, Cin];
    w: [Cin, Cout]; scale/bias: [Cout]."""
    b, h, wd, cin = x.shape
    out = matmul_bn_relu(x.reshape(b * h * wd, cin), w, scale, bias,
                         relu=relu)
    return out.reshape(b, h, wd, w.shape[1])


def conv1x1_bn_relu_reference(x, w, scale, bias, *, relu=True):
    """jnp oracle (f32 accumulation, same math, XLA-scheduled)."""
    y = jnp.einsum("bhwc,cd->bhwd", x.astype(jnp.float32),
                   w.astype(jnp.float32))
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)
