"""Fused 1x1-conv (matmul) + BatchNorm-affine epilogue Pallas kernel.

The below-XLA ResNet roofline probe (VERDICT r4 weak #3): the bs128
ResNet-50 step is pinned at the HBM roofline (``hbm_util`` 1.0,
docs/performance.md), and the two residual traffic levers round 2 named
— conv layout copies and unfused BN passes — were never probed beneath
XLA.  A 1x1 convolution IS a matmul over the flattened spatial grid
(``[B*H*W, Cin] @ [Cin, Cout]``), and the bottleneck blocks'
1x1 convs carry most of ResNet-50's conv FLOPs
(models/resnet.py:_bottleneck — conv1/conv3 of every block; ref: the
same blocks in the reference's synthetic ResNet benchmark,
examples/pytorch/pytorch_synthetic_benchmark.py).  This kernel computes

    y = relu((x @ w) * scale + bias)

in one pass: tiled MXU matmul with f32 VMEM accumulation and the BN
affine (normalized/inference form — scale and bias folded from
gamma/beta/mean/var) applied in the epilogue before the single bf16
HBM write.  If XLA already fuses the affine into its conv output, the
A/B (tools/resnet_probe.py) shows parity and closes the lever with a
number; if not, the delta is the banked win.

Runs in Pallas interpret mode off-TPU so the CPU suite exercises the
same kernel code (tests/test_pallas.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

# Shared with the attention kernels: the interpret-mode switch, the
# dtype-aware block fitter (per-dtype sublane floors — bf16 needs 16
# rows on real TPU; a hand-rolled 8-row check would pass interpret-mode
# tests and then fail Mosaic lowering on hardware), and the
# shard_map/check_vma out-shape helper.
from .pallas_kernels import _fit_block, _use_interpret, _vma_kw

__all__ = ["matmul_bn_relu", "conv1x1_bn_relu", "conv1x1_bn_relu_reference",
           "matmul_batch_stats", "conv1x1_bn_train",
           "conv1x1_bn_train_reference"]


def _ct_to_primal_vma(ct, primal):
    """psum a cotangent over the mesh axes its PRIMAL does not vary on
    (a replicated weight meeting sharded activations): custom_vjp must
    return cotangents with the primal's vma — the same psum XLA's
    autodiff inserts when transposing the implicit broadcast."""
    typeof = getattr(jax, "typeof", None)
    if typeof is None:      # JAX without vma tracking: nothing to reduce
        return ct
    extra = tuple(set(getattr(typeof(ct), "vma", frozenset()))
                  - set(getattr(typeof(primal), "vma", frozenset())))
    return jax.lax.psum(ct, extra) if extra else ct


def _vma_align(*ops):
    """Promote every operand to the union of the group's varying
    manual axes — dot_general (and the interpret-mode kernel body)
    require matching vma, and replicated params meeting dp-sharded
    activations inside shard_map don't match without this."""
    from ..parallel.sharding import pcast_to_union

    return tuple(pcast_to_union(op, *ops) for op in ops)


def _fit_lanes(n: int, block_n: int) -> int:
    """Lane (last-dim) tile: largest power-of-2 reduction of ``block_n``
    that divides ``n``; refuses below the 128-lane TPU tile floor."""
    bn = min(block_n, n)
    while n % bn:
        bn //= 2
    if bn < 128:
        raise ValueError(
            f"N={n} only tiles at {bn} lanes — below the 128-lane TPU "
            "tile floor; pad the channel dim to a multiple of 128")
    return bn


def _tpu_params() -> dict:
    """compiler_params kwargs for the matmul grids: M/N tiles are
    independent, only K carries the accumulator.  Empty in interpret
    mode (and under a JAX without the params class)."""
    if _use_interpret():
        return {}
    from jax.experimental.pallas import tpu as pltpu

    params_cls = getattr(pltpu, "CompilerParams",
                         getattr(pltpu, "TPUCompilerParams", None))
    if params_cls is None:
        return {}
    return {"compiler_params": params_cls(
        dimension_semantics=("parallel", "parallel", "arbitrary"))}


def _mm_kernel(a_ref, w_ref, s_ref, b_ref, o_ref, acc_ref, *, relu: bool):
    """Grid program (i, j, k): accumulate one K-block into the f32 VMEM
    accumulator; on the last K step apply the BN affine (+ReLU) and make
    the ONLY HBM write of this output tile.

    First-k WRITES the accumulator (no zero-init: an unvarying zeros
    tile added to a shard_map-varying dot fails check_vma in interpret
    mode)."""
    import jax.experimental.pallas as pl

    part = jnp.dot(a_ref[...], w_ref[...],
                   preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == 0)
    def _first():
        acc_ref[...] = part

    @pl.when(pl.program_id(2) > 0)
    def _accumulate():
        acc_ref[...] += part

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _epilogue():
        y = acc_ref[...] * s_ref[...] + b_ref[...]
        if relu:
            y = jnp.maximum(y, 0.0)
        o_ref[...] = y.astype(o_ref.dtype)


def matmul_bn_relu(a: jax.Array, w: jax.Array, scale: jax.Array,
                   bias: jax.Array, *, relu: bool = True,
                   block_m: int = 512, block_n: int = 256,
                   block_k: int = 512) -> jax.Array:
    """``relu((a @ w) * scale + bias)`` with the affine fused into the
    matmul epilogue.  a: [M, K]; w: [K, N]; scale/bias: [N] (f32);
    returns [M, N] in ``a``'s dtype with f32 accumulation throughout.

    Differentiable (``custom_vjp``): the backward recomputes the
    pre-activation ``z = a @ w`` instead of saving it — rematerialized
    FLOPs on the MXU, zero extra residual HBM traffic (recovering z
    from the saved output would be cheaper still, but is undefined at
    ``scale == 0``, which zero-init-gamma ResNets hit on every residual
    block's last BN).  The backward matmuls run in XLA (MXU-shaped
    dots; fusing them into Pallas is a further step only if the forward
    probe banks a win)."""
    return _mm_diff(a, w, scale, bias, relu, block_m, block_n, block_k)


def _mm_forward(a, w, scale, bias, relu, block_m, block_n, block_k):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = a.shape
    k2, n = w.shape
    if k != k2:
        raise ValueError(f"a has K={k} but w has K={k2}")
    if scale.shape != (n,) or bias.shape != (n,):
        raise ValueError(
            f"scale/bias must be [{n}], got {scale.shape}/{bias.shape}")
    # _fit_block enforces the per-dtype sublane floor on real TPU (and
    # raises loudly); _fit_lanes the 128-lane floor on N.
    bm = _fit_block(m, block_m, a.dtype)
    bk = _fit_block(k, block_k, a.dtype, w.dtype)
    bn = _fit_lanes(n, block_n)
    grid = (m // bm, n // bn, k // bk)
    a, w, scale, bias = _vma_align(a, w, scale, bias)

    return pl.pallas_call(
        functools.partial(_mm_kernel, relu=relu),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
            pl.BlockSpec((1, bn), lambda i, j, kk: (0, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), a.dtype,
                                       **_vma_kw(a, w, scale, bias)),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=_use_interpret(),
        **_tpu_params(),
    )(a, w, scale.astype(jnp.float32).reshape(1, n),
      bias.astype(jnp.float32).reshape(1, n))


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6, 7))
def _mm_diff(a, w, scale, bias, relu, block_m, block_n, block_k):
    return _mm_forward(a, w, scale, bias, relu, block_m, block_n, block_k)


def _mm_diff_fwd(a, w, scale, bias, relu, block_m, block_n, block_k):
    y = _mm_forward(a, w, scale, bias, relu, block_m, block_n, block_k)
    # Residuals: y feeds only the relu mask — with relu=False (the
    # zero-init-gamma residual placement) it is dead in the backward
    # and must not pin an [M, N] activation.  bias ([N], negligible)
    # rides along for its dtype.
    return y, (a, w, scale, bias, y if relu else None)


def _mm_diff_bwd(relu, block_m, block_n, block_k, res, dy):
    """g = dy * 1[y>0]; dz = g * scale; da = dz w^T; dw = a^T dz;
    dbias = sum_M g; dscale = sum_M g*z with z = a @ w RECOMPUTED
    (bf16 operands, f32 accumulation — the forward kernel's own
    precision) — exact for every scale (including the zero-init-gamma
    case where z cannot be recovered from the saved output).

    ReLU subgradient convention: relu'(0) = 0 (the flash-kernel norm;
    jnp.maximum's autodiff instead splits ties 0.5).  Units at EXACTLY
    zero pre-activation get zero gradient — note zero-init gamma
    belongs on a residual block's LAST BN, where the add precedes the
    relu, i.e. this kernel runs with relu=False and gamma trains."""
    a, w, scale, bias, y = res
    f32 = jnp.float32
    g = dy.astype(f32)
    if relu:
        g = jnp.where(y.astype(f32) > 0, g, 0.0)
    # Native-dtype operands + f32 accumulation: no materialized f32
    # copies of a/w, full bf16 MXU rate on the backward dots.
    dz = g * scale.astype(f32)
    da = jnp.dot(dz.astype(a.dtype), w.T,
                 preferred_element_type=f32).astype(a.dtype)
    dw = jnp.dot(a.T, dz.astype(a.dtype),
                 preferred_element_type=f32).astype(w.dtype)
    dbias = g.sum(axis=0).astype(bias.dtype)
    z = jnp.dot(a, w, preferred_element_type=f32)
    dscale = (g * z).sum(axis=0).astype(scale.dtype)
    return (_ct_to_primal_vma(da, a), _ct_to_primal_vma(dw, w),
            _ct_to_primal_vma(dscale, scale),
            _ct_to_primal_vma(dbias, bias))


_mm_diff.defvjp(_mm_diff_fwd, _mm_diff_bwd)


def conv1x1_bn_relu(x: jax.Array, w: jax.Array, scale: jax.Array,
                    bias: jax.Array, *, relu: bool = True) -> jax.Array:
    """Fused NHWC 1x1 conv + BN affine (+ReLU).  x: [B, H, W, Cin];
    w: [Cin, Cout]; scale/bias: [Cout]."""
    b, h, wd, cin = x.shape
    out = matmul_bn_relu(x.reshape(b * h * wd, cin), w, scale, bias,
                         relu=relu)
    return out.reshape(b, h, wd, w.shape[1])


def conv1x1_bn_relu_reference(x, w, scale, bias, *, relu=True):
    """jnp oracle (f32 accumulation, same math, XLA-scheduled)."""
    y = jnp.einsum("bhwc,cd->bhwd", x.astype(jnp.float32),
                   w.astype(jnp.float32))
    y = y * scale.astype(jnp.float32) + bias.astype(jnp.float32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype)


# ---- train-form BN: matmul + batch-stat partial sums in one pass --------
#
# Training BatchNorm normalizes with the CURRENT batch's statistics of
# the conv output z, so the affine epilogue above cannot apply — the
# stats are a reduction OVER z.  XLA's schedule reads z (at least)
# twice: once for the mean/var reduction, once to normalize.  This
# kernel emits z AND per-(M-block) partial sums (sum z, sum z^2) from
# the same VMEM-resident accumulator tile, so z takes ONE write and
# ONE read (the normalize, which XLA fuses with scale/shift/relu):
# per-op BN traffic drops by a full read of z.  The partial sums are
# [M/bm, N] f32 — thousands of times smaller than z.


def _mm_stats_kernel(a_ref, w_ref, o_ref, s1_ref, s2_ref, acc_ref):
    import jax.experimental.pallas as pl

    part = jnp.dot(a_ref[...], w_ref[...],
                   preferred_element_type=jnp.float32)

    @pl.when(pl.program_id(2) == 0)
    def _first():
        acc_ref[...] = part

    @pl.when(pl.program_id(2) > 0)
    def _accumulate():
        acc_ref[...] += part

    @pl.when(pl.program_id(2) == pl.num_programs(2) - 1)
    def _emit():
        z = acc_ref[...]
        o_ref[...] = z.astype(o_ref.dtype)
        s1_ref[...] = z.sum(axis=0, keepdims=True)
        s2_ref[...] = (z * z).sum(axis=0, keepdims=True)


def matmul_batch_stats(a: jax.Array, w: jax.Array, *, block_m: int = 512,
                       block_n: int = 256, block_k: int = 512):
    """One fused pass: ``z = a @ w`` (written once, in ``a``'s dtype)
    plus per-M-block partial sums of z and z^2 (f32 ``[M/bm, N]``).
    Finalize stats as ``mean = s1.sum(0)/M``,
    ``var = s2.sum(0)/M - mean^2`` (f32 accumulation throughout)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    m, k = a.shape
    k2, n = w.shape
    if k != k2:
        raise ValueError(f"a has K={k} but w has K={k2}")
    bm = _fit_block(m, block_m, a.dtype)
    bk = _fit_block(k, block_k, a.dtype, w.dtype)
    bn = _fit_lanes(n, block_n)
    grid = (m // bm, n // bn, k // bk)
    a, w = _vma_align(a, w)

    stat_spec = pl.BlockSpec((1, bn), lambda i, j, kk: (i, j))
    return pl.pallas_call(
        _mm_stats_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
                   stat_spec, stat_spec],
        out_shape=(jax.ShapeDtypeStruct((m, n), a.dtype,
                                        **_vma_kw(a, w)),
                   jax.ShapeDtypeStruct((m // bm, n), jnp.float32,
                                        **_vma_kw(a, w)),
                   jax.ShapeDtypeStruct((m // bm, n), jnp.float32,
                                        **_vma_kw(a, w))),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        interpret=_use_interpret(),
        **_tpu_params(),
    )(a, w)


def conv1x1_bn_train(x: jax.Array, w: jax.Array, gamma: jax.Array,
                     beta: jax.Array, *, eps: float = 1e-5,
                     relu: bool = True, axis: Optional[str] = None):
    """Fused NHWC 1x1 conv + TRAIN-mode BN (+ReLU): batch statistics
    come from the kernel's partial sums; the normalize (+scale/shift/
    relu) is the only re-read of z and XLA fuses it into one pass.
    Returns ``(y, batch_mean, batch_var)`` — mean/var feed the caller's
    running-stat update exactly like models/resnet.py _batch_norm.

    ``axis``: SyncBatchNorm — statistics are computed over the GLOBAL
    batch by ``lax.psum`` of the per-device partial sums (the ragged
    reduction is [devices, N] numbers, not activations).  Must be
    called under shard_map with that mesh axis bound; the backward's
    batch-mean terms use the same cross-device means, so gradients
    match autodiff through the synced unfused path.

    Differentiable (``custom_vjp``): the standard batch-stat BN
    backward with z recomputed (bf16 operands, f32 accumulation) —
    same remat philosophy as :func:`matmul_bn_relu`'s backward.
    Cotangents arriving on the mean/var outputs are honored (callers
    that treat running stats as non-differentiated aux simply
    contribute zeros)."""
    b, h, wd, cin = x.shape
    cout = w.shape[1]
    if gamma.shape != (cout,) or beta.shape != (cout,):
        raise ValueError(
            f"gamma/beta must be [{cout}], got {gamma.shape}/{beta.shape}")
    y2d, mean, var = _train_diff(x.reshape(b * h * wd, cin), w, gamma,
                                 beta, float(eps), relu, axis)
    return y2d.reshape(b, h, wd, cout), mean, var


def _global_m(m: int, axis: Optional[str]):
    from .device import _axis_size_static

    return m * _axis_size_static(axis) if axis else m


def _axis_mean(v, axis: Optional[str]):
    """Mean over the local M rows, then over the sync axis if set."""
    out = v.mean(axis=0)
    return jax.lax.pmean(out, axis) if axis else out


@functools.partial(jax.custom_vjp, nondiff_argnums=(4, 5, 6))
def _train_diff(a, w, gamma, beta, eps, relu, axis):
    y, mean, var, _ = _train_forward(a, w, gamma, beta, eps, relu, axis)
    return y, mean, var


def _train_forward(a, w, gamma, beta, eps, relu, axis):
    mg = _global_m(a.shape[0], axis)
    z, s1, s2 = matmul_batch_stats(a, w)
    f32 = jnp.float32
    s1t, s2t = s1.sum(axis=0), s2.sum(axis=0)
    if axis:
        s1t = jax.lax.psum(s1t, axis)
        s2t = jax.lax.psum(s2t, axis)
    mean = s1t / mg
    var = jnp.maximum(s2t / mg - mean * mean, 0.0)
    scale = gamma.astype(f32) * jax.lax.rsqrt(var + eps)
    bias = beta.astype(f32) - mean * scale
    y = z.astype(f32) * scale + bias
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(a.dtype), mean, var, z


def _train_diff_fwd(a, w, gamma, beta, eps, relu, axis):
    y, mean, var, _ = _train_forward(a, w, gamma, beta, eps, relu, axis)
    # z is recomputed in the backward (remat); y feeds only the relu
    # mask; mean/var are [N] — negligible residuals.
    return (y, mean, var), (a, w, gamma, beta, mean, var,
                            y if relu else None)


def _train_diff_bwd(eps, relu, axis, res, cts):
    """Batch-stat BN backward.  With inv = rsqrt(var+eps) and
    zhat = (z-mean)*inv:  g = dy*1[y>0]; dbeta = sum g;
    dgamma = sum g*zhat; dzhat = g*gamma;
    dz = inv*(dzhat - mean_B(dzhat) - zhat*mean_B(dzhat*zhat))
    where mean_B is the (optionally cross-device) batch mean;
    da = dz w^T; dw = a^T dz.  Cotangents on the mean/var outputs add
    their direct paths (d mean/d z = 1/M_global;
    d var/d z = 2(z-mean)/M_global)."""
    a, w, gamma, beta, mean, var, y = res
    dy, dmean_ct, dvar_ct = cts
    f32 = jnp.float32
    mg = _global_m(a.shape[0], axis)
    g = dy.astype(f32)
    if relu:
        g = jnp.where(y.astype(f32) > 0, g, 0.0)
    z = jnp.dot(a, w, preferred_element_type=f32)
    inv = jax.lax.rsqrt(var + eps)
    zhat = (z - mean) * inv
    dbeta = g.sum(axis=0).astype(beta.dtype)
    dgamma = (g * zhat).sum(axis=0).astype(gamma.dtype)
    dzhat = g * gamma.astype(f32)
    dz = inv * (dzhat - _axis_mean(dzhat, axis)
                - zhat * _axis_mean(dzhat * zhat, axis))
    dz = dz + dmean_ct.astype(f32) / mg
    dz = dz + dvar_ct.astype(f32) * 2.0 * (z - mean) / mg
    da = jnp.dot(dz.astype(a.dtype), w.T,
                 preferred_element_type=f32).astype(a.dtype)
    dw = jnp.dot(a.T, dz.astype(a.dtype),
                 preferred_element_type=f32).astype(w.dtype)
    # Param cotangents reduce to their primals' vma (the psum XLA's
    # autodiff inserts for the replicated-param broadcast) — identical
    # totals to the synced unfused path.
    return (_ct_to_primal_vma(da, a), _ct_to_primal_vma(dw, w),
            _ct_to_primal_vma(dgamma, gamma),
            _ct_to_primal_vma(dbeta, beta))


_train_diff.defvjp(_train_diff_fwd, _train_diff_bwd)


def conv1x1_bn_train_reference(x, w, gamma, beta, *, eps=1e-5, relu=True):
    """jnp train-form oracle (f32 throughout)."""
    f32 = jnp.float32
    z = jnp.einsum("bhwc,cd->bhwd", x.astype(f32), w.astype(f32))
    mean = z.mean(axis=(0, 1, 2))
    var = z.var(axis=(0, 1, 2))
    y = (z - mean) * jax.lax.rsqrt(var + eps) * gamma.astype(f32) \
        + beta.astype(f32)
    if relu:
        y = jnp.maximum(y, 0.0)
    return y.astype(x.dtype), mean, var
