"""Overlap scheduling layer — dependency-ordered, latency-hidden gradient
exchange.

The core insight of the source paper (Horovod: tensor fusion + overlapping
allreduce with the backward pass) and of "Exploring the limits of
Concurrency in ML Training on Google TPUs" (latency-hiding collectives
behind compute is what separates 0.3-MFU from 0.5-MFU runs) applied to the
jit data plane.  ``fused_allreduce`` packs buckets well, but a
compute-then-communicate step only starts collectives after the whole
backward has materialized.  This module turns the train step into a
pipelined exchange:

* **Reverse-topological bucket schedule** — gradient leaves arrive in
  forward (parameter) order and the backward materializes them in
  *reverse*, so buckets are planned over the reversed leaf order
  (:func:`overlap_schedule`, reusing ``fused_allreduce_buckets``) and each
  bucket's fused allreduce is issued as soon as that segment's grads
  exist.  Issue order is pinned with ``jax.lax.optimization_barrier`` — a
  token chain threads every bucket's *payload* (never its result, which
  would serialize done→issue and kill the overlap) so XLA cannot
  re-serialize the collectives into one trailing block.

* **Segmented VJP** (:func:`overlap_value_and_grad`) — for models
  expressed as a chain of stages, the backward is walked stage by stage
  and each stage's exchange is issued *between* VJP segments: the
  upstream cotangent is barriered with the stage's payload token, so the
  traced program literally interleaves collectives with backward compute
  (the lowered-HLO contract tests/test_overlap.py pins).

* **Pipelined int8 wire** — the quantized collective
  (quant/collectives.py) is split into ``start`` (quantize + wire-format
  reduce-scatter) and ``finish`` (dequant-accumulate + requantize +
  reassembly); the scheduler issues bucket N+1's wire hop before
  finishing bucket N, so N's dequant-accumulate overlaps N+1's wire
  phase.

* **Pallas latency-hiding leg** (:func:`exchange_and_update`,
  :func:`pipelined_sgd`) — the single-HBM-pass optimizer update
  (ops/optim_kernels.py) of bucket N runs while bucket N+1's collective
  is in flight, so the optimizer is no longer a serial epilogue.

* **Async collective flags** (:func:`enable_latency_hiding`) — engages
  XLA:TPU's latency-hiding scheduler / async collective fusion through
  the ``LIBTPU_INIT_ARGS`` env contract (``HVDT_XLA_LATENCY_HIDING``),
  which is what actually turns the dependency freedom above into
  overlapped execution on hardware.

Zero-overhead contract (same pattern as telemetry/instrument.py and
resilience/faults.py): with ``HVDT_OVERLAP`` unset/off,
:func:`get_scheduler` returns ``None`` and :func:`exchange_fn` returns
``ops.device.fused_allreduce`` ITSELF — the exact pre-existing code
object, identity-tested — so the monolithic path stays byte-for-byte the
``HVDT_OVERLAP=off`` fallback.

Numerics: bucketing and barriers never change f32 math — a psum is
elementwise across ranks, so any bucketing slices out bitwise-identical
leaves (tests pin grads AND updated params bitwise against the
monolithic path on a mesh-8 CPU run).  The int8 wire keeps the
established block-scale/2 error bound per stage; bucket *composition*
differs from the forward plan, so int8 results are bounded, not bitwise.

jax-0.4.37 guard: everything here uses ``lax.optimization_barrier``
(present since 0.4.x) and the env-contract flags — no ``jax.typeof`` /
``lax.pcast`` / ``shard_map``-API dependence anywhere on this path.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Callable, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common import config
from ..common.logging_util import get_logger
from ..common.types import ReduceOp
from . import device as dev

__all__ = [
    "enabled", "get_scheduler", "exchange_fn", "reset", "OverlapScheduler",
    "overlap_schedule", "overlap_value_and_grad", "exchange_and_update",
    "pipelined_sgd", "enable_latency_hiding", "overlap_fraction",
    "last_schedule", "reset_accounting",
]

log = get_logger(__name__)

_TRUTHY = ("1", "true", "yes", "on")


def enabled() -> bool:
    """Whether the overlap scheduling layer is on (``HVDT_OVERLAP``)."""
    return os.environ.get("HVDT_OVERLAP", "").strip().lower() in _TRUTHY


# ---------------------------------------------------------------------------
# Process-wide scheduler (env-gated, cached on the raw env string so per-test
# monkeypatching rebuilds it — same idiom as telemetry.instrument.get_recorder)
# ---------------------------------------------------------------------------

_lock = threading.Lock()
_cached_env: Optional[str] = "\0unset"   # sentinel != any real env value
_cached_scheduler: Optional["OverlapScheduler"] = None


def get_scheduler() -> Optional["OverlapScheduler"]:
    """The process-wide overlap scheduler, or ``None`` when off.

    The disabled steady state costs one environ read and a string
    compare; call sites branch on ``is None`` and touch nothing else."""
    global _cached_env, _cached_scheduler
    raw = os.environ.get("HVDT_OVERLAP")
    if raw != _cached_env:
        with _lock:
            if raw != _cached_env:
                _cached_scheduler = OverlapScheduler() if enabled() else None
                _cached_env = raw
    return _cached_scheduler


def exchange_fn() -> Callable:
    """The bucketed gradient-exchange callable the optimizer layer uses.

    ``HVDT_OVERLAP`` on → the scheduler's dependency-ordered
    :meth:`OverlapScheduler.exchange`; off/unset → the monolithic
    ``ops.device.fused_allreduce`` — the EXACT pre-existing code object
    (``exchange_fn() is fused_allreduce``, identity-tested), so the off
    path carries zero wrapper objects."""
    sched = get_scheduler()
    return dev.fused_allreduce if sched is None else sched.exchange


def reset() -> None:
    """Drop the cached scheduler (test isolation)."""
    global _cached_env, _cached_scheduler
    with _lock:
        _cached_env = "\0unset"
        _cached_scheduler = None


# ---------------------------------------------------------------------------
# Overlap accounting: collective bytes issued with compute left to hide
# under vs. total — the trace-time feed for the hvdt_overlap_fraction
# gauge and bench.py --overlap's JSON.  Recorded at TRACE time (under jit
# the compiled program, not this host code, runs the schedule), same
# path=jit convention as the per-collective instrumentation.
# ---------------------------------------------------------------------------

_acct_lock = threading.Lock()
_acct_hidden = 0.0
_acct_total = 0.0
_last_schedule: Optional[dict] = None


def _account(bucket_bytes: List[int], wire: str) -> None:
    global _acct_hidden, _acct_total, _last_schedule
    total = float(sum(bucket_bytes))
    # Every bucket except the LAST issued still has backward compute (or
    # pipelined updates) scheduled under its flight window; the final
    # collective has nothing left to hide under.
    hidden = float(sum(bucket_bytes[:-1])) if len(bucket_bytes) > 1 else 0.0
    with _acct_lock:
        _acct_hidden += hidden
        _acct_total += total
        _last_schedule = {
            "buckets": len(bucket_bytes),
            "bucket_bytes": list(bucket_bytes),
            "hidden_buckets": max(0, len(bucket_bytes) - 1),
            "wire": wire,
        }
    from ..telemetry import instrument as _ti

    rec = _ti.get_recorder()
    if rec is not None:
        rec.observe_overlap(hidden, total)


def overlap_fraction() -> Optional[float]:
    """Collective bytes issued with compute left to hide under ÷ total
    collective bytes, cumulative over every schedule traced in this
    process (the byte-weighted proxy for collective-seconds hidden ÷
    total collective seconds until a TPU profile refines it).  ``None``
    before any overlapped exchange has been traced."""
    with _acct_lock:
        if _acct_total <= 0:
            return None
        return _acct_hidden / _acct_total


def last_schedule() -> Optional[dict]:
    """Bucket plan of the most recently traced overlapped exchange."""
    with _acct_lock:
        return dict(_last_schedule) if _last_schedule else None


def reset_accounting() -> None:
    global _acct_hidden, _acct_total, _last_schedule
    with _acct_lock:
        _acct_hidden = _acct_total = 0.0
        _last_schedule = None


# ---------------------------------------------------------------------------
# Schedule planning
# ---------------------------------------------------------------------------


def overlap_schedule(leaves: Sequence[Any],
                     threshold_bytes: Optional[int] = None
                     ) -> List[List[int]]:
    """Reverse-topological bucket plan over a gradient pytree's leaves.

    Gradient leaves arrive in forward (parameter) order; the backward
    materializes them in reverse, so the plan is
    ``fused_allreduce_buckets`` over the REVERSED leaf order mapped back
    to original indices — bucket 0 holds the output-side leaves whose
    grads exist first, and is issued first.  Pure planning function;
    host-side, shape-only."""
    threshold_bytes = dev._validated_threshold(threshold_bytes)
    n = len(leaves)
    rev = list(reversed(list(leaves)))
    return [[n - 1 - i for i in b]
            for b in dev.fused_allreduce_buckets(rev, threshold_bytes)]


def _payload_token(flat):
    """A tiny (1-element) slice of a bucket payload — the dependency
    handle the barrier chain threads.  Depends only on the payload, so
    pinning on it never waits for the collective's *result*."""
    return lax.slice_in_dim(flat, 0, 1)


def _exchange_leaves(leaves, axis, op, threshold_bytes, prescale_factor,
                     postscale_factor, wire_dtype, quant_wire, token,
                     leaf_finish=None):
    """Core dependency-ordered exchange over a flat leaf list.

    ``quant_wire`` names the quantized leg ("int8" / "int4") or is
    falsy for exact/cast wires (a bare ``True`` means int8, the legacy
    bool spelling).

    Returns ``(cells, token)`` where ``cells[i]`` is the reduced leaf
    (or whatever ``leaf_finish(i, reduced_leaf, pin)`` returned) and
    ``token`` is the last bucket's payload token — thread it into the
    next call (the segmented backward) to keep one global issue order.

    Two-phase walk:

    1. **issue** — every bucket's payload is concatenated, barriered
       with the previous payload's token (issue-order pin) and its
       collective started (for the int8 wire: the quantize + wire-format
       reduce-scatter ``quantized_allreduce_start``; under a transport
       policy: the hierarchical fast-axis reduce-scatter + slow-axis
       wire hop ``hierarchical_allreduce_start``);
    2. **finish** — bucket k's epilogue (dequant-accumulate for the
       quantized wire, slow finish + allgather for the hierarchical
       path, the optimizer update when ``leaf_finish`` runs one) is
       barriered with bucket k+1's payload, so it is scheduled while
       k+1's collective is in flight.
    """
    schedule = overlap_schedule(leaves, threshold_bytes)

    from ..telemetry import instrument as _ti
    from ..transport import policy as _tpolicy

    quant_leg = "int8" if quant_wire is True else (quant_wire or None)

    rec = _ti.get_recorder()
    _res = _tpolicy.resolve_axis(axis)
    hier = (_res is not None and _res.kind == "hierarchical"
            and op in (ReduceOp.SUM, ReduceOp.AVERAGE))
    _axis_label = "+".join((axis,) if isinstance(axis, str)
                           else tuple(axis))

    issued = []   # (bucket, shapes, sizes, orig_dtype, kind, state, payload)
    bucket_bytes: List[int] = []
    for bi, bucket in enumerate(schedule):
        parts = [leaves[i] for i in bucket]
        shapes = [p.shape for p in parts]
        sizes = [p.size for p in parts]
        flat = jnp.concatenate([jnp.ravel(p) for p in parts]) \
            if len(parts) > 1 else jnp.ravel(parts[0])
        orig_dtype = flat.dtype
        float_bucket = jnp.issubdtype(orig_dtype, jnp.floating)
        hier_bucket = hier and float_bucket
        if wire_dtype is not None and flat.dtype != wire_dtype \
                and not hier_bucket:
            flat = flat.astype(wire_dtype)
        # Issue-order pin: this payload cannot be scheduled before the
        # previous bucket's payload, so collectives keep the
        # reverse-topological order instead of being re-serialized.
        if token is not None:
            flat, _ = lax.optimization_barrier((flat, token))
        token = _payload_token(flat)
        nbytes = int(flat.size) * jnp.dtype(flat.dtype).itemsize
        quant_bucket = (quant_leg is not None and float_bucket
                        and not hier_bucket)
        if hier_bucket:
            from ..transport import hierarchy as _th

            bucket_bytes.append(_th.wire_bytes_estimate(
                _res, int(flat.size),
                jnp.dtype(flat.dtype).itemsize) or nbytes)
        elif quant_bucket:
            from ..quant import kernels as _qk

            _wb = (_qk.wire_bytes_int4 if quant_leg == "int4"
                   else _qk.wire_bytes)
            bucket_bytes.append(int(_wb(
                int(flat.size), _qk.quant_block_size())))
        else:
            bucket_bytes.append(nbytes)
        if rec is not None:
            rec.observe_fusion_fill(nbytes / float(threshold_bytes))
            if not quant_bucket and not hier_bucket:
                rec.record_collective(
                    "allreduce", jnp.dtype(orig_dtype).name,
                    jnp.dtype(flat.dtype).name, nbytes,
                    count=len(parts), path="jit", axis=_axis_label)
        with jax.named_scope(f"hvdt.overlap.b{bi}"):
            if hier_bucket:
                from ..transport import hierarchy as _th

                state = _th.hierarchical_allreduce_start(
                    flat, _res, op=op, prescale_factor=prescale_factor)
                kind = "hier"
            elif quant_bucket:
                from ..quant import collectives as qc

                state = qc.quantized_allreduce_start(
                    flat, axis, op=op, prescale_factor=prescale_factor,
                    wire=quant_leg)
                kind = "quant"
            else:
                state = dev.allreduce(flat, axis, op, prescale_factor,
                                      postscale_factor)
                kind = "plain"
        issued.append((bucket, shapes, sizes, orig_dtype, kind, state, flat))

    from ..quant.collectives import wire_sentinel as _sentinel

    _account(bucket_bytes,
             wire=("hierarchical" if hier
                   else _sentinel(quant_leg) if quant_leg is not None
                   else "exact"))

    cells: List[Any] = [None] * len(leaves)
    for k, (bucket, shapes, sizes, orig_dtype, kind, state, _payload) \
            in enumerate(issued):
        pin = (_payload_token(issued[k + 1][6])
               if k + 1 < len(issued) else None)
        if kind == "hier":
            from ..transport import hierarchy as _th

            # Slow finish + allgather of bucket k overlaps bucket k+1's
            # flight window: the inflight arrays are barriered with
            # k+1's payload, never with k+1's result.
            state = _th.pin_inflight(state, pin)
            with jax.named_scope(f"hvdt.overlap.b{k}.finish"):
                red = _th.hierarchical_allreduce_finish(
                    state, postscale_factor)
        elif kind == "quant":
            import dataclasses as _dc

            from ..quant import collectives as qc

            if pin is not None:
                # Dequant-accumulate of bucket k overlaps the wire phase
                # of bucket k+1: the received wire shards are barriered
                # with k+1's payload, never with k+1's result.
                q2, s2, _ = lax.optimization_barrier(
                    (state.q_recv, state.s_recv, pin))
                state = _dc.replace(state, q_recv=q2, s_recv=s2)
            with jax.named_scope(f"hvdt.overlap.b{k}.finish"):
                red = qc.quantized_allreduce_finish(state, postscale_factor)
        else:
            red = state
        if red.dtype != orig_dtype:
            red = red.astype(orig_dtype)
        offset = 0
        for i, shape, sz in zip(bucket, shapes, sizes):
            g = lax.dynamic_slice_in_dim(red, offset, sz).reshape(shape)
            offset += sz
            cells[i] = g if leaf_finish is None else leaf_finish(i, g, pin)
    return cells, token


class OverlapScheduler:
    """Dependency-ordered bucketed exchange — the ``HVDT_OVERLAP=on``
    replacement for the monolithic ``fused_allreduce`` (same signature,
    same semantics, overlapped schedule).  Stateless: safe to share
    across threads and jit traces."""

    def exchange(self, tree, axis="dp", op: ReduceOp = ReduceOp.AVERAGE,
                 threshold_bytes: Optional[int] = None,
                 prescale_factor: float = 1.0,
                 postscale_factor: float = 1.0,
                 wire_dtype: Optional[Any] = None):
        """Drop-in for ``ops.device.fused_allreduce`` with the
        reverse-topological, barrier-pinned bucket schedule.  Bitwise
        identical results for exact wires (psum is elementwise — any
        bucketing slices out the same values); the int8 wire keeps the
        established block-scale/2 bound per stage."""
        from ..transport import policy as _tpolicy

        from ..quant.collectives import quant_wire_leg as _qleg

        threshold_bytes = dev._validated_threshold(
            _tpolicy.bucket_threshold(axis, threshold_bytes))
        quant_wire = _qleg(wire_dtype)
        if quant_wire is not None:
            wire_dtype = None  # the quantized path owns the wire format
        leaves, treedef = jax.tree.flatten(tree)
        if not leaves:
            return tree
        cells, _token = _exchange_leaves(
            leaves, axis, op, threshold_bytes, prescale_factor,
            postscale_factor, wire_dtype, quant_wire, token=None)
        return jax.tree.unflatten(treedef, cells)


# ---------------------------------------------------------------------------
# Segmented VJP: per-bucket backward segments with the exchange issued
# between them — the traced program itself interleaves collectives with
# VJP compute (the lowered-HLO contract).
# ---------------------------------------------------------------------------


def overlap_value_and_grad(stage_fns: Sequence[Callable],
                           axis="dp", op: ReduceOp = ReduceOp.AVERAGE, *,
                           threshold_bytes: Optional[int] = None,
                           prescale_factor: float = 1.0,
                           postscale_factor: float = 1.0,
                           wire_dtype: Optional[Any] = None,
                           reduce_grads: bool = True) -> Callable:
    """Value-and-grad over a chain of stages with each stage's gradient
    exchange issued as soon as that VJP segment's grads exist.

    ``stage_fns``: sequence of ``f_i(params_i, x) -> x``; the LAST stage
    must return a scalar loss.  Returns ``fn(params_seq, x) -> (loss,
    grads_seq)`` where ``grads_seq[i]`` is stage i's gradient pytree,
    already allreduced over ``axis`` (dependency-ordered: stage i's
    collective is issued between VJP segment i and segment i-1, and the
    upstream cotangent is barriered with the stage's payload token so
    XLA cannot hoist the remaining backward above the issue point).
    ``reduce_grads=False`` skips the exchange (raw per-shard grads) —
    the A/B leg for measuring the exchange itself.

    Valid inside shard_map where ``axis`` is bound, like every
    collective in ops/device.py.
    """
    stage_fns = tuple(stage_fns)
    if not stage_fns:
        raise ValueError("overlap_value_and_grad needs at least one stage")

    def fn(params_seq, x):
        params_seq = list(params_seq)
        if len(params_seq) != len(stage_fns):
            raise ValueError(
                f"{len(params_seq)} param trees for {len(stage_fns)} stages")
        vjps = []
        act = x
        for f, p in zip(stage_fns, params_seq):
            act, vjp = jax.vjp(f, p, act)
            vjps.append(vjp)
        loss = act
        if getattr(loss, "shape", ()) != ():
            raise ValueError("the last stage must return a scalar loss")

        from ..transport import policy as _tpolicy

        threshold = dev._validated_threshold(
            _tpolicy.bucket_threshold(axis, threshold_bytes))
        from ..quant.collectives import quant_wire_leg as _qleg
        from ..quant.collectives import wire_sentinel as _sentinel

        quant_wire = _qleg(wire_dtype)
        wd = wire_dtype if quant_wire is None else None

        # ZeRO composition (ops/zero.py): with HVDT_ZERO live, each VJP
        # segment's exchange rides the reduce-scatter wire (rs_exchange:
        # per-bucket reduce-scatter + invariant allgather, itself
        # payload-chain pinned when this scheduler is on) — the traced
        # program interleaves reduce-scatters with backward compute,
        # the lowered-HLO contract tests/test_zero.py pins.
        from . import zero as _zero

        zero_stage = _zero.stage()

        grads: List[Any] = [None] * len(stage_fns)
        token = None
        ct = jnp.ones_like(loss)
        for i in reversed(range(len(stage_fns))):
            with jax.named_scope(f"hvdt.overlap.vjp_seg{i}"):
                g_p, ct = vjps[i](ct)
            if reduce_grads:
                leaves, treedef = jax.tree.flatten(g_p)
                if leaves and zero_stage is not None:
                    g_p = _zero.rs_exchange(
                        g_p, axis, op, threshold_bytes=threshold,
                        prescale_factor=prescale_factor,
                        postscale_factor=postscale_factor,
                        wire_dtype=wd if quant_wire is None
                        else _sentinel(quant_wire))
                    token = _payload_token(jnp.ravel(leaves[0]))
                    if i > 0:
                        ct, _ = lax.optimization_barrier((ct, token))
                elif leaves:
                    cells, token = _exchange_leaves(
                        leaves, axis, op, threshold, prescale_factor,
                        postscale_factor, wd, quant_wire, token)
                    g_p = jax.tree.unflatten(treedef, cells)
                    if i > 0 and token is not None:
                        # Pin the issue point BETWEEN VJP segments: the
                        # upstream cotangent is barriered with this
                        # stage's payload token, so segment i-1's compute
                        # is scheduled after stage i's exchange is issued
                        # (and the exchange cannot sink below it).
                        ct, _ = lax.optimization_barrier((ct, token))
            grads[i] = g_p
        return loss, grads

    return fn


# ---------------------------------------------------------------------------
# Pallas latency-hiding leg: pipelined exchange + fused optimizer update
# ---------------------------------------------------------------------------


def exchange_and_update(grads, leaf_update: Callable, aux_trees=(),
                        axis="dp", op: ReduceOp = ReduceOp.AVERAGE, *,
                        threshold_bytes: Optional[int] = None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0,
                        wire_dtype: Optional[Any] = None):
    """Pipelined gradient exchange fused with the per-leaf optimizer
    update: bucket N's update runs while bucket N+1's collective is in
    flight, so the optimizer is no longer a serial epilogue after the
    last collective (the Pallas latency-hiding leg — pair with the
    single-HBM-pass units in ops/optim_kernels:
    ``sgd_leaf_update`` / ``adam_leaf_update``).

    ``leaf_update(reduced_grad, *aux_leaves) -> out`` (array or tuple of
    arrays); ``aux_trees`` are pytrees congruent with ``grads`` whose
    leaves ride along (momentum/moment buffers, params).  Returns a
    pytree matching ``grads`` — or a tuple of such pytrees when
    ``leaf_update`` returns tuples (e.g. ``(updates, new_trace)``).
    """
    from ..quant.collectives import quant_wire_leg as _qleg

    threshold_bytes = dev._validated_threshold(threshold_bytes)
    quant_wire = _qleg(wire_dtype)
    if quant_wire is not None:
        wire_dtype = None
    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return grads
    aux_leaves = [treedef.flatten_up_to(t) for t in aux_trees]

    def finish(i, g, pin):
        aux = [a[i] for a in aux_leaves]
        if pin is not None:
            # The update of this bucket is scheduled under the NEXT
            # collective's flight window: its inputs are barriered with
            # the next bucket's payload (never its result).
            pinned = lax.optimization_barrier(tuple([g] + aux) + (pin,))
            g, aux = pinned[0], list(pinned[1:-1])
        return leaf_update(g, *aux)

    cells, _token = _exchange_leaves(
        leaves, axis, op, threshold_bytes, prescale_factor,
        postscale_factor, wire_dtype, quant_wire, token=None,
        leaf_finish=finish)
    if cells and isinstance(cells[0], (tuple, list)):
        width = len(cells[0])
        return tuple(jax.tree.unflatten(treedef, [c[j] for c in cells])
                     for j in range(width))
    return jax.tree.unflatten(treedef, cells)


def pipelined_sgd(learning_rate, momentum: float = 0.0,
                  nesterov: bool = False, *, axis="dp",
                  op: ReduceOp = ReduceOp.AVERAGE,
                  threshold_bytes: Optional[int] = None,
                  wire_dtype: Optional[Any] = None,
                  use_kernels: bool = True):
    """Drop-in for ``optax.chain(DistributedGradientTransformation(...),
    fused_sgd(...))`` with the exchange and the single-HBM-pass momentum
    update pipelined per bucket (:func:`exchange_and_update`).  Same
    state tree (``optax.TraceState`` — or ``EmptyState`` without
    momentum), same f32-accumulated math, hot-swappable against the
    unpipelined chain mid-run.

    Gradient-aware semantics mirror ``optimizer.allreduce_gradients``:
    leaves unvarying over ``axis`` (already cross-shard summed by modern
    AD) and runs with no bound axis skip the collective and only scale.
    """
    import optax

    if callable(learning_rate):
        raise ValueError(
            "pipelined_sgd takes a float learning_rate (TraceState "
            "carries no step count for a schedule); see fused_adam for "
            "schedule support")

    def init_fn(params):
        if not momentum:
            del params
            return optax.EmptyState()
        return optax.TraceState(trace=jax.tree.map(jnp.zeros_like, params))

    def update_fn(updates, state, params=None):
        del params
        from .optim_kernels import sgd_leaf_update

        scalars = jnp.stack([jnp.asarray(learning_rate, jnp.float32)])

        def upd(g, *aux):
            if not momentum:
                return (-scalars[0] * g.astype(jnp.float32)).astype(g.dtype)
            return sgd_leaf_update(g, aux[0], scalars, momentum=momentum,
                                   nesterov=nesterov,
                                   use_kernels=use_kernels)

        from ..optimizer import _axis_bound

        leaves, treedef = jax.tree.flatten(updates)
        aux = (state.trace,) if momentum else ()
        if not _axis_bound(axis) or not leaves:
            # No bound mesh axis (plain auto-sharded jit): gradients are
            # already global — plain (unpipelined) update.
            aux_leaves = [treedef.flatten_up_to(t) for t in aux]
            cells = [upd(g, *[a[i] for a in aux_leaves])
                     for i, g in enumerate(leaves)]
        else:
            n = 1
            for a in ((axis,) if isinstance(axis, str) else tuple(axis)):
                n *= dev._axis_size_static(a)
            varying = [dev.is_varying(l, axis) for l in leaves]
            scale = (1.0 / n) if op == ReduceOp.AVERAGE else 1.0
            if all(varying):
                out = exchange_and_update(
                    updates, upd, aux_trees=aux, axis=axis, op=op,
                    threshold_bytes=threshold_bytes, wire_dtype=wire_dtype)
                if momentum:
                    deltas, new_m = out
                    return deltas, optax.TraceState(trace=new_m)
                return out, state
            # Mixed/unvarying regime (modern AD pre-summed the cotangent
            # of replicated params): scale instead of reducing.
            aux_leaves = [treedef.flatten_up_to(t) for t in aux]
            cells = []
            for i, g in enumerate(leaves):
                if varying[i]:
                    g = dev.allreduce(g, axis, op)
                elif scale != 1.0:
                    g = g * scale
                cells.append(upd(g, *[a[i] for a in aux_leaves]))
        if momentum:
            deltas = jax.tree.unflatten(treedef, [c[0] for c in cells])
            new_m = jax.tree.unflatten(treedef, [c[1] for c in cells])
            return deltas, optax.TraceState(trace=new_m)
        return jax.tree.unflatten(treedef, cells), state

    return optax.GradientTransformation(init_fn, update_fn)


# ---------------------------------------------------------------------------
# XLA latency-hiding scheduler / async collective fusion engagement
# ---------------------------------------------------------------------------

# XLA:TPU flags that turn dependency freedom into overlapped execution:
# async collective fusion wraps independent compute between a
# collective's (start, done) pair; the continuation/overlap flag lets
# the TensorCore run compute while a collective is in flight.  Ridden
# through the LIBTPU_INIT_ARGS env contract — read once at TPU backend
# init, inert on CPU/GPU backends (the jax-0.4.37-safe engagement: no
# jax API involved at all).
_ASYNC_COLLECTIVE_FLAGS = (
    "--xla_tpu_enable_async_collective_fusion=true",
    "--xla_tpu_enable_async_collective_fusion_fuse_all_gather=true",
    "--xla_tpu_enable_async_collective_fusion_multiple_steps=true",
    "--xla_tpu_overlap_compute_collective_tc=true",
)


def _jax_backend_initialized() -> bool:
    try:
        from jax._src import xla_bridge

        return bool(getattr(xla_bridge, "_backends", None))
    except Exception:
        return False


def enable_latency_hiding(mode: Optional[str] = None) -> Optional[str]:
    """Engage XLA's latency-hiding scheduler / async-collective-fusion
    flags (``HVDT_XLA_LATENCY_HIDING``: auto|on|off).

    ``auto`` (default) appends the flags to ``LIBTPU_INIT_ARGS`` unless
    ``JAX_PLATFORMS`` pins a non-TPU backend (the CPU test mesh keeps
    its environment untouched); ``on`` always appends (the flags are
    inert off-TPU anyway); ``off`` is a no-op.  Idempotent — flags
    already present are never duplicated.  Returns the resulting
    ``LIBTPU_INIT_ARGS`` string, or ``None`` when nothing was engaged.

    Called by ``hvd.init()`` and ``bench.py --overlap``; call it before
    the first jax computation — libtpu reads the env once at backend
    init, so flags added later apply to the NEXT process (warned).
    """
    if mode is None:
        mode = config.get_str("HVDT_XLA_LATENCY_HIDING")
    mode = (mode or "auto").strip().lower()
    if mode in ("off", "0", "false", "none", "no"):
        return None
    platforms = os.environ.get("JAX_PLATFORMS", "")
    if mode == "auto" and platforms and "tpu" not in platforms.lower():
        return None
    cur = os.environ.get("LIBTPU_INIT_ARGS", "")
    missing = [f for f in _ASYNC_COLLECTIVE_FLAGS
               if f.split("=", 1)[0] not in cur]
    if not missing:
        return cur or None
    if _jax_backend_initialized():
        log.warning(
            "latency-hiding flags engaged AFTER jax backend init; "
            "LIBTPU_INIT_ARGS is read once at TPU init, so they apply "
            "to the next process")
    os.environ["LIBTPU_INIT_ARGS"] = (cur + " " + " ".join(missing)).strip()
    log.info("XLA latency-hiding flags engaged: %s", " ".join(missing))
    return os.environ["LIBTPU_INIT_ARGS"]
