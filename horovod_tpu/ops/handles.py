"""Async operation handles.

TPU-native analog of the reference's handle manager
(ref: torch/handle_manager.{h,cc} — int handle → future Status;
torch/mpi_ops.py:914-952 poll/synchronize).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from ..common.exceptions import HorovodInternalError
from ..common.types import Status

__all__ = ["HandleManager"]


class _Entry:
    __slots__ = ("event", "status", "result")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.status: Optional[Status] = None
        self.result: Any = None


class HandleManager:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._entries: Dict[int, _Entry] = {}

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._entries[h] = _Entry()
            return h

    def mark_done(self, handle: int, status: Status, result: Any = None) -> None:
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            return
        e.status = status
        e.result = result
        e.event.set()

    def known(self, handle: int) -> bool:
        """True while the handle has an unresolved entry (resolved or
        never-allocated handles return False) — lets framework-side
        registries sweep entries for handles resolved elsewhere."""
        with self._lock:
            return handle in self._entries

    def poll(self, handle: int) -> bool:
        """True if the operation completed (ref: mpi_ops.py:914 poll)."""
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            raise ValueError(f"Unknown handle {handle}")
        return e.event.is_set()

    def synchronize(self, handle: int, timeout: Optional[float] = None) -> Any:
        """Block until done, return the result or raise
        (ref: mpi_ops.py:930 synchronize)."""
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            raise ValueError(f"Unknown handle {handle}")
        if not e.event.wait(timeout):
            # Keep the entry: the collective may still complete and the
            # caller may retry synchronize()/poll() on the same handle.
            raise TimeoutError(f"Collective op (handle {handle}) timed out")
        with self._lock:
            self._entries.pop(handle, None)
        assert e.status is not None
        if not e.status.ok_p():
            raise HorovodInternalError(e.status.reason)
        return e.result

    def abort_all(self, reason: str) -> None:
        """Fail every outstanding handle (elastic teardown path)."""
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if not e.event.is_set():
                e.status = Status.aborted(reason)
                e.event.set()
