"""Async operation handles.

TPU-native analog of the reference's handle manager
(ref: torch/handle_manager.{h,cc} — int handle → future Status;
torch/mpi_ops.py:914-952 poll/synchronize).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, Optional, Tuple

from ..common.exceptions import HorovodInternalError
from ..common.types import Status

__all__ = ["HandleManager"]


class _Entry:
    __slots__ = ("event", "status", "result", "meta")

    def __init__(self) -> None:
        self.event = threading.Event()
        self.status: Optional[Status] = None
        self.result: Any = None
        self.meta: Any = None


class HandleManager:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._next = 0
        self._entries: Dict[int, _Entry] = {}

    def allocate(self) -> int:
        with self._lock:
            h = self._next
            self._next += 1
            self._entries[h] = _Entry()
            return h

    def mark_done(self, handle: int, status: Status, result: Any = None) -> None:
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            return
        e.status = status
        e.result = result
        e.event.set()

    def set_meta(self, handle: int, meta: Any) -> None:
        """Attach framework-side metadata (e.g. the torch binding's
        result dtype / in-place target) to a live handle.  Metadata
        shares the entry's lifetime — dropped with the entry at
        ``synchronize`` — so framework registries cannot outlive or leak
        past the handles they describe."""
        with self._lock:
            e = self._entries.get(handle)
            if e is not None:
                e.meta = meta

    def take_meta(self, handle: int) -> Any:
        """Return and clear the handle's metadata (None if the handle is
        unknown, already resolved, or carries none)."""
        with self._lock:
            e = self._entries.get(handle)
            if e is None or e.meta is None:
                return None
            meta, e.meta = e.meta, None
            return meta

    def poll(self, handle: int) -> bool:
        """True if the operation completed (ref: mpi_ops.py:914 poll)."""
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            raise ValueError(f"Unknown handle {handle}")
        return e.event.is_set()

    def synchronize(self, handle: int, timeout: Optional[float] = None) -> Any:
        """Block until done, return the result or raise
        (ref: mpi_ops.py:930 synchronize)."""
        with self._lock:
            e = self._entries.get(handle)
        if e is None:
            raise ValueError(f"Unknown handle {handle}")
        if not e.event.wait(timeout):
            # Keep the entry: the collective may still complete and the
            # caller may retry synchronize()/poll() on the same handle.
            raise TimeoutError(f"Collective op (handle {handle}) timed out")
        with self._lock:
            self._entries.pop(handle, None)
        assert e.status is not None
        if not e.status.ok_p():
            raise HorovodInternalError(e.status.reason)
        return e.result

    def abort_all(self, reason: str) -> None:
        """Fail every outstanding handle (elastic teardown path)."""
        with self._lock:
            entries = list(self._entries.values())
        for e in entries:
            if not e.event.is_set():
                e.status = Status.aborted(reason)
                e.event.set()
