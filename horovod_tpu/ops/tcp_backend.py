"""Selectable native-TCP data plane for eager host collectives.

Analog of the reference's CPU-operations backend selection
(ref: HOROVOD_CPU_OPERATIONS, common.h:127-128, parsed in
utils/env_parser.cc → LibType MPI/GLOO/CCL; dispatch priority
operations.cc:144-253).  Here there are two host data planes:

* ``xla`` (default) — host tensors ride the XLA device mesh
  (ops/host_collectives.py), so eager bytes use ICI/DCN like the jit path.
* ``tcp`` — the native C++ backend (native/src/tcp_group.cc): a full TCP
  socket mesh between processes, no accelerator involvement.  This is the
  Gloo-analog fallback for CPU-only fleets, host-side control traffic, or
  debugging without touching devices.

Selection: ``HVDT_CPU_OPERATIONS=tcp`` + ``HVDT_TCP_ADDRS`` (rank-ordered
``host:port`` list; the launcher exports it automatically when
``HVDT_CPU_OPERATIONS=tcp`` — runner/launch.py — or the operator sets it
by hand).  Each process set gets its own socket mesh; its members listen
on ``base_port + process_set_id * HVDT_TCP_SET_PORT_STRIDE``.  The stride
(default 128) keeps per-set ports clear of *other ranks'* base ports on
the same host: with ranks at consecutive ports (e.g. 9000, 9001, ...), a
naive +set_id offset would land set 1's rank-0 listener on rank 1's base
port.  Contract: all base ports on one host must sit in a contiguous
block smaller than the stride.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

import numpy as np

from ..common import config
from ..common.types import ReduceOp

__all__ = ["enabled", "group_for", "shutdown_groups"]

_lock = threading.Lock()
_groups: Dict[int, "object"] = {}


def enabled() -> bool:
    if config.get_str("HVDT_CPU_OPERATIONS").lower() != "tcp":
        return False
    if not config.get_str("HVDT_TCP_ADDRS"):
        return False
    from .. import native

    return native.available()


def group_for(process_set):
    """TcpProcessGroup for this process set (cached; lazily connected).

    The socket-mesh bootstrap happens OUTSIDE the cache lock — every
    member must be connecting concurrently for the mesh to form (in
    production one process is one rank; in tests several rank threads
    share the process, hence also the (set, rank) cache key).

    Bootstrap is retried with the shared exponential backoff
    (``HVDT_TCP_CONNECT_RETRIES`` attempts): peers of a restarted or
    freshly scheduled rank come up at different times, and a one-shot
    connect turns that skew into a job failure."""
    from ..native import NativeError, TcpProcessGroup
    from ..resilience import faults
    from ..resilience.retry import Backoff, retry

    key = (process_set.id, process_set.rank())
    with _lock:
        g = _groups.get(key)
    if g is not None:
        return g
    addrs_all = [a.strip() for a in
                 config.get_str("HVDT_TCP_ADDRS").split(",") if a.strip()]
    offset = process_set.id * config.get_int("HVDT_TCP_SET_PORT_STRIDE")
    member_addrs = []
    for r in process_set.ranks:
        host, port = addrs_all[r].rsplit(":", 1)
        member_addrs.append(f"{host}:{int(port) + offset}")

    def _connect():
        inj = faults.get_injector()
        if inj is not None:
            inj.fire("tcp.connect", rank=process_set.rank())
        return TcpProcessGroup(process_set.rank(), process_set.size(),
                               member_addrs,
                               timeout_ms=config.get_int("HVDT_TCP_TIMEOUT_MS"))

    g = retry(_connect,
              attempts=max(1, config.get_int("HVDT_TCP_CONNECT_RETRIES")),
              retry_on=(NativeError, ConnectionError, OSError),
              backoff=Backoff(first=0.2, cap=5.0),
              describe=f"tcp mesh bootstrap (set {process_set.id})")
    with _lock:
        existing = _groups.setdefault(key, g)
    if existing is not g:
        g.close()
        return existing
    return g


def shutdown_groups() -> None:
    with _lock:
        for g in _groups.values():
            try:
                g.close()
            except Exception:
                pass
        _groups.clear()


# -- collective entry points mirroring ops/host_collectives signatures --


def tcp_allreduce(value: np.ndarray, process_set, op: ReduceOp) -> np.ndarray:
    return group_for(process_set).allreduce(value, op=op)


def tcp_allgather(value: np.ndarray, process_set) -> np.ndarray:
    return group_for(process_set).allgather(value)


def tcp_broadcast(value: np.ndarray, process_set, root: int) -> np.ndarray:
    return group_for(process_set).broadcast(value, root=root)


def tcp_alltoall(value: np.ndarray, process_set,
                 splits: Optional[list] = None) -> np.ndarray:
    return group_for(process_set).alltoall(value, splits=splits)


def tcp_adasum(flat: np.ndarray, process_set) -> np.ndarray:
    return group_for(process_set).adasum_allreduce(flat)
