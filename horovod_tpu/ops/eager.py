"""Eager named-collective path: negotiation controller + public async API.

TPU-native re-conception of the reference's coordination core
(ref: common/operations.cc — background thread loop RunLoopOnce
operations.cc:706-806, enqueue API :1357-1795; common/controller.cc —
ComputeResponseList :73, ConstructResponse :495, FuseResponses :808,
IncrementTensorCount :977; common/tensor_queue.{h,cc};
common/response_cache.{h,cc}; common/group_table.{h,cc}).

Why this layer exists on TPU at all (SURVEY.md §5.8): under jit, op order
is globally consistent and XLA fuses collectives — that path lives in
ops/device.py.  The eager path serves Horovod-parity semantics: framework
threads enqueue *named* tensors in nondeterministic order; a controller
matches names across ranks, validates shapes/dtypes, fuses small tensors,
and executes — with joined-rank zero-contribution, stall detection, a
response cache, and per-tensor timeline instrumentation.

Threading model mirrors the reference design comment (operations.cc:363-383):
a single background thread owns all cross-rank communication; framework
threads only touch the tensor queue and handle table.
"""

from __future__ import annotations

import collections
import itertools
import threading
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..common import basics, config
from ..common.exceptions import HorovodInternalError
from ..common.logging_util import get_logger
from ..common.process_sets import ProcessSet, global_process_set
from ..common.types import DUPLICATE_NAME_ERROR, ReduceOp, Status, data_type_of, numpy_dtype_of
from . import host_collectives as hostc
from .control_plane import ControlPlane, default_control_plane
from .handles import HandleManager
from .messages import (Request, RequestType, Response, decode_request_list,
                       decode_response_list, encode_request_list,
                       encode_response_list)

__all__ = [
    "allreduce", "allreduce_async", "grouped_allreduce",
    "grouped_allreduce_async", "allgather", "allgather_async", "broadcast",
    "broadcast_async", "alltoall", "alltoall_async", "reducescatter",
    "reducescatter_async", "barrier", "join", "poll", "synchronize",
    "shutdown_controller",
]

log = get_logger(__name__)


# ---------------------------------------------------------------------------
# Local bookkeeping structures
# ---------------------------------------------------------------------------

class _Entry:
    """Local in-flight tensor (ref: TensorTableEntry common.h:348-382)."""

    __slots__ = ("request", "tensor", "handle", "enqueue_ts", "was_jax",
                 "announce_ts", "fr_seq")

    def __init__(self, request: Request, tensor: Optional[np.ndarray],
                 handle: int, was_jax: bool,
                 fr_seq: Optional[int] = None):
        self.request = request
        self.tensor = tensor
        self.handle = handle
        self.enqueue_ts = time.monotonic()
        self.was_jax = was_jax
        # Stamped by the background cycle when the request is announced —
        # telemetry splits enqueue->announce (queue) from
        # announce->response (negotiate).  None when telemetry is off.
        self.announce_ts: Optional[float] = None
        # Flight-recorder sequence opened at enqueue (None when the
        # recorder is off) — closed when the handle completes, so a hung
        # peer's collectives stay visibly "inflight" in the ring.
        self.fr_seq = fr_seq


class ResponseCache:
    """LRU cache of negotiated request descriptors, coherent across ranks
    (ref: common/response_cache.{h,cc}): every rank applies identical
    updates in response-execution order, so cache bit positions agree
    without extra synchronization — the analog of the reference's
    bitvector-AND steady-state path (controller.cc:780-806)."""

    def __init__(self, capacity: int):
        self.capacity = capacity
        # name -> Request (insertion-ordered for LRU)
        self._entries: "collections.OrderedDict[str, Request]" = \
            collections.OrderedDict()

    def lookup_bit(self, req: Request) -> Optional[int]:
        if req.group_id >= 0:
            # grouped requests always fully negotiate: group membership is
            # not carried by cached descriptors, and the all-or-nothing
            # gate (GroupTable) must see the live group id
            return None
        cached = self._entries.get(req.tensor_name)
        if cached is None:
            return None
        if cached.descriptor() != req.descriptor() or \
                cached.splits != req.splits or \
                cached.prescale_factor != req.prescale_factor or \
                cached.postscale_factor != req.postscale_factor or \
                cached.tensor_shape != req.tensor_shape:
            # descriptor changed → treat as uncached; will be re-inserted
            return None
        return list(self._entries).index(req.tensor_name)

    def request_for_bit(self, bit: int) -> Optional[Request]:
        names = list(self._entries)
        if 0 <= bit < len(names):
            return self._entries[names[bit]]
        return None

    def insert(self, req: Request) -> None:
        if self.capacity <= 0:
            return
        name = req.tensor_name
        if name in self._entries:
            self._entries.pop(name)
        self._entries[name] = req
        while len(self._entries) > self.capacity:
            self._entries.popitem(last=False)


class _MessageTable:
    """Coordinator-side readiness table (ref: IncrementTensorCount
    controller.cc:977; arrival-ordered like the reference's ready queue)."""

    def __init__(self) -> None:
        # key -> {rank: Request}; insertion order = first-arrival order
        self.pending: "collections.OrderedDict[Tuple[int, str], Dict[int, Request]]" = \
            collections.OrderedDict()

    def add(self, req: Request) -> None:
        key = (req.process_set_id, req.tensor_name)
        self.pending.setdefault(key, {})[req.request_rank] = req


# ---------------------------------------------------------------------------
# Controller
# ---------------------------------------------------------------------------

class EagerController:
    def __init__(self, control_plane: Optional[ControlPlane] = None):
        self.cp = control_plane or default_control_plane()
        self.handles = HandleManager()
        self._lock = threading.Lock()
        # (ps_id, name) -> _Entry   (ref: TensorQueue duplicate-name check)
        self._entries: Dict[Tuple[int, str], _Entry] = {}
        self._to_announce: List[Request] = []
        self._cache = ResponseCache(config.get_int("HVDT_CACHE_CAPACITY"))
        self._message_table = _MessageTable()
        self._group_members: Dict[int, set] = {}   # group_id -> names
        self._next_group_id = itertools.count()
        self._joined: Dict[int, Dict[int, int]] = {}  # ps_id -> {rank: join order}
        self._local_join_handles: Dict[int, int] = {}  # ps_id -> handle
        self._cycle = 0
        self._running = True
        from ..resilience.escalation import EscalationPolicy, Escalator
        from ..stall import StallInspector

        # Stall policy ladder (warn → abort collective → request elastic
        # reset).  Only built when an escalation rung is configured, so
        # the default path keeps the plain warn-only inspector.
        policy = EscalationPolicy.from_env()
        self._escalator = (Escalator(policy)
                           if (policy.abort_s or policy.reset_s) else None)
        self._stall = StallInspector(self.cp.size(),
                                     escalator=self._escalator)
        from ..timeline import get_timeline

        get_timeline()  # trigger env auto-start once
        self._cycle_time_s = config.get_float("HVDT_CYCLE_TIME") / 1000.0
        self._thread = threading.Thread(target=self._loop,
                                        name="hvdt-controller", daemon=True)
        self._thread.start()

    @property
    def _timeline(self):
        # read the live singleton each time so dynamic start_timeline()/
        # stop_timeline() take effect on a running controller
        from ..timeline import current

        return current()

    # -- framework-thread API ----------------------------------------------
    def enqueue(self, request: Request, tensor: Optional[np.ndarray],
                was_jax: bool) -> int:
        key = (request.process_set_id, request.tensor_name)
        from ..telemetry import flight_recorder as _frm

        flight = _frm.get_flight_recorder()
        fr_seq = None
        if flight is not None:
            dtype = numpy_dtype_of_safe(request.tensor_type)
            shape = tuple(request.tensor_shape or ())
            nbytes = int(np.prod(shape)) * dtype.itemsize if shape \
                else dtype.itemsize
            fr_seq = flight.record_begin(
                op=RequestType(request.request_type).name.lower(),
                name=request.tensor_name, dtype=dtype.name, shape=shape,
                nbytes=nbytes, path="eager")
        with self._lock:
            if not self._running:
                if flight is not None:
                    flight.record_end(fr_seq, status="error")
                raise HorovodInternalError("controller is shut down")
            if key in self._entries:
                if flight is not None:
                    flight.record_end(fr_seq, status="error")
                raise ValueError(DUPLICATE_NAME_ERROR +
                                 f" (tensor: {request.tensor_name})")
            handle = self.handles.allocate()
            self._entries[key] = _Entry(request, tensor, handle, was_jax,
                                        fr_seq=fr_seq)
            self._to_announce.append(request)
        if self._timeline:
            self._timeline.start_activity(
                request.tensor_name,
                f"NEGOTIATE_{RequestType(request.request_type).name}")
        return handle

    def enqueue_join(self, ps: ProcessSet) -> int:
        req = Request(self.cp.rank(), RequestType.JOIN, f"join.{ps.id}",
                      0, (), process_set_id=ps.id)
        with self._lock:
            if ps.id in self._local_join_handles:
                raise ValueError(f"join already pending for process set {ps.id}")
            handle = self.handles.allocate()
            self._local_join_handles[ps.id] = handle
            self._to_announce.append(req)
        return handle

    def next_group_id(self) -> int:
        return next(self._next_group_id)

    # -- background loop (ref: RunLoopOnce operations.cc:706) --------------
    def _loop(self) -> None:
        idle_sleep = 0.0001
        while self._running:
            if self._cycle_time_s > 0:
                time.sleep(self._cycle_time_s)
            try:
                did_work = self._run_cycle()
            except Exception as e:  # pragma: no cover - defensive
                with self._lock:
                    # Idle = nothing in flight anywhere this rank knows
                    # about: no local entries/announcements/joins AND (on
                    # the coordinator) no other rank's requests mid-
                    # negotiation.
                    idle = (not self._entries and not self._to_announce
                            and not self._local_join_handles
                            and not self._message_table.pending
                            and not any(self._joined.values()))
                if not self._running or idle:
                    # Teardown raced a blocking control-plane call — our
                    # own shutdown(), or a peer's coordination service
                    # going away while this rank idles in the long-poll.
                    # Nothing was in flight so nothing was lost, but the
                    # controller is DEAD: _fail_all (race-free under the
                    # lock) marks it so later enqueues raise instead of
                    # queueing forever.  Only the log level differs from
                    # a real mid-work failure.
                    log.debug("controller loop exiting on teardown: %s", e)
                    self._fail_all(
                        f"controller shut down (control plane gone: {e})")
                    return
                log.exception("controller cycle failed: %s", e)
                self._fail_all(f"controller cycle failed: {e}")
                return
            if self._timeline:
                self._timeline.mark_cycle()
            if not did_work and self._cycle_time_s == 0:
                time.sleep(idle_sleep)
                idle_sleep = min(idle_sleep * 2, 0.002)
            else:
                idle_sleep = 0.0001

    def _run_cycle(self) -> bool:
        from ..telemetry import instrument as _ti

        with self._lock:
            to_send = self._to_announce
            self._to_announce = []
            if to_send and _ti.get_recorder() is not None:
                now = time.monotonic()
                for req in to_send:
                    e = self._entries.get(
                        (req.process_set_id, req.tensor_name))
                    if e is not None:
                        e.announce_ts = now
        multi = self.cp.size() > 1
        if not multi and not to_send:
            return False

        # -- announce: cache bits for hits, full requests for misses
        bits: List[int] = []
        misses: List[Request] = []
        for req in to_send:
            if req.request_type == RequestType.JOIN:
                misses.append(req)
                continue
            bit = self._cache.lookup_bit(req)
            if bit is not None:
                bits.append(bit)
            else:
                misses.append(req)
        payload = encode_request_list(misses)
        payload = f"{','.join(map(str, bits))}|{payload}"

        gathered = self.cp.gather(payload, self._cycle)

        # -- coordinator: build response list
        resp_payload: Optional[str] = None
        if gathered is not None:
            responses = self._construct_response_list(gathered)
            resp_payload = encode_response_list(responses)
        resp_payload = self.cp.broadcast(resp_payload, self._cycle)
        self._cycle += 1
        responses = decode_response_list(resp_payload)
        if responses:
            self._execute_response_list(responses)
        return bool(to_send) or bool(responses)

    # -- coordinator logic (ref: ComputeResponseList controller.cc:73) -----
    def _construct_response_list(self, gathered: List[str]) -> List[Response]:
        for rank, raw in enumerate(gathered):
            bits_part, _, req_part = raw.partition("|")
            reqs = decode_request_list(req_part)
            if bits_part:
                import dataclasses as _dc

                for bit in map(int, bits_part.split(",")):
                    cached = self._cache.request_for_bit(bit)
                    if cached is not None:
                        reqs.append(_dc.replace(cached, request_rank=rank))
            for req in reqs:
                req.request_rank = rank
                if req.request_type == RequestType.JOIN:
                    joined = self._joined.setdefault(req.process_set_id, {})
                    if rank not in joined:
                        joined[rank] = len(joined)
                    continue
                self._message_table.add(req)
                self._stall.record(req.tensor_name, rank)

        responses: List[Response] = []
        ready_keys: List[Tuple[int, str]] = []
        for key, by_rank in self._message_table.pending.items():
            ps_id = key[0]
            try:
                ps = basics._global_state().process_set_table.get(ps_id)
                ps_size = ps.size()
            except Exception:
                ps_size = self.cp.size()
            joined = self._joined.get(ps_id, {})
            if len(by_rank) + len([r for r in joined if r not in by_rank]) \
                    >= ps_size:
                ready_keys.append(key)

        # group all-or-nothing gate (ref: group_table.{h,cc})
        ready_group_names: Dict[int, set] = {}
        for key in ready_keys:
            req = next(iter(self._message_table.pending[key].values()))
            if req.group_id >= 0:
                ready_group_names.setdefault(req.group_id, set()).add(key[1])
        gated: List[Tuple[int, str]] = []
        for key in ready_keys:
            req = next(iter(self._message_table.pending[key].values()))
            if req.group_id >= 0:
                members = self._group_members.get(req.group_id)
                if members is not None and \
                        ready_group_names.get(req.group_id, set()) != members:
                    continue
            gated.append(key)

        for key in gated:
            by_rank = self._message_table.pending.pop(key)
            self._stall.resolve(key[1])
            responses.append(self._construct_response(key, by_rank))

        # JOIN responses: all ranks of a set joined and nothing pending
        for ps_id, joined in list(self._joined.items()):
            try:
                ps = basics._global_state().process_set_table.get(ps_id)
                ps_size = ps.size()
            except Exception:
                ps_size = self.cp.size()
            has_pending = any(k[0] == ps_id
                              for k in self._message_table.pending)
            if len(joined) >= ps_size and not has_pending:
                last = max(joined, key=lambda r: joined[r])
                responses.append(Response(RequestType.JOIN, [f"join.{ps_id}"],
                                          process_set_id=ps_id,
                                          last_joined_rank=last))
                del self._joined[ps_id]

        self._stall.check()
        responses.extend(self._abort_escalated_stalls())
        return self._fuse_responses(responses)

    def _abort_escalated_stalls(self) -> List[Response]:
        """Consume the escalation ladder (coordinator side): tensors past
        the abort threshold get an error response — every waiting rank's
        synchronize() then raises HorovodInternalError and the elastic
        retry loop takes over, instead of the job hanging on one wedged
        rank.  A reset-rung crossing additionally asks the elastic driver
        for a re-rendezvous (best-effort, elastic launches only)."""
        if self._escalator is None:
            return []
        out: List[Response] = []
        names = self._escalator.drain_aborts()
        if names:
            for key in [k for k in list(self._message_table.pending)
                        if k[1] in names]:
                req = next(iter(self._message_table.pending.pop(key).values()))
                self._stall.resolve(key[1])
                out.append(Response(
                    req.request_type, [key[1]], process_set_id=key[0],
                    error_message=(
                        f"collective {key[1]} aborted: stalled past "
                        f"HVDT_STALL_ABORT_TIME_SECONDS (missing ranks "
                        f"never submitted)")))
        if self._escalator.reset_requested():
            from ..resilience.escalation import request_elastic_reset

            request_elastic_reset("stalled collective escalation")
        return out

    def _construct_response(self, key: Tuple[int, str],
                            by_rank: Dict[int, Request]) -> Response:
        """Validate cross-rank agreement and emit a Response
        (ref: ConstructResponse controller.cc:495)."""
        ps_id, name = key
        reqs = list(by_rank.values())
        first = reqs[0]
        for other in reqs[1:]:
            if other.request_type != first.request_type:
                return Response(first.request_type, [name],
                                error_message=f"Mismatched collective type for "
                                f"tensor {name}.")
            if other.tensor_type != first.tensor_type:
                return Response(first.request_type, [name],
                                error_message=f"Mismatched data type for tensor "
                                f"{name}.")
            if other.descriptor() != first.descriptor():
                return Response(first.request_type, [name],
                                error_message=f"Mismatched shape/params for "
                                f"tensor {name}: {first.tensor_shape} vs "
                                f"{other.tensor_shape}.")
        rt = first.request_type
        resp = Response(rt, [name], tensor_type=first.tensor_type,
                        reduce_op=first.reduce_op,
                        prescale_factor=first.prescale_factor,
                        postscale_factor=first.postscale_factor,
                        root_rank=first.root_rank, process_set_id=ps_id)
        if rt == RequestType.ALLGATHER:
            # per-set-rank dim0 sizes, joined ranks contribute 0 rows
            try:
                ps = basics._global_state().process_set_table.get(ps_id)
                set_ranks = ps.ranks
            except Exception:
                set_ranks = list(range(self.cp.size()))
            shapes = []
            for r in set_ranks:
                if r in by_rank:
                    shapes.append(tuple(by_rank[r].tensor_shape))
                else:
                    shapes.append((0,) + tuple(first.tensor_shape[1:]))
            resp.tensor_shapes = shapes
        elif rt == RequestType.ALLTOALL:
            try:
                ps = basics._global_state().process_set_table.get(ps_id)
                set_ranks = ps.ranks
            except Exception:
                set_ranks = list(range(self.cp.size()))
            resp.recv_splits = [tuple(by_rank[r].splits) if r in by_rank
                                else (0,) * len(set_ranks)
                                for r in set_ranks]
            resp.tensor_shapes = [tuple(first.tensor_shape)]
        else:
            resp.tensor_shapes = [tuple(first.tensor_shape)]
        return resp

    def _fuse_responses(self, responses: List[Response]) -> List[Response]:
        """Pack compatible allreduce responses into fused responses up to the
        fusion threshold (ref: FuseResponses controller.cc:808)."""
        threshold = config.get_int("HVDT_FUSION_THRESHOLD")
        if not config.get_bool("HVDT_BATCH_COLLECTIVES"):
            return responses
        fused: List[Response] = []
        pending: Optional[Response] = None
        pending_bytes = 0

        def flush():
            nonlocal pending, pending_bytes
            if pending is not None:
                fused.append(pending)
            pending, pending_bytes = None, 0

        for resp in responses:
            fusible = (resp.response_type in (RequestType.ALLREDUCE,
                                              RequestType.ADASUM)
                       and not resp.error_message)
            if not fusible:
                flush()
                fused.append(resp)
                continue
            nbytes = int(np.prod(resp.tensor_shapes[0]) *
                         numpy_dtype_of_safe(resp.tensor_type).itemsize) \
                if resp.tensor_shapes[0] else 0
            compatible = (
                pending is not None
                and pending.response_type == resp.response_type
                and pending.tensor_type == resp.tensor_type
                and pending.reduce_op == resp.reduce_op
                and pending.prescale_factor == resp.prescale_factor
                and pending.postscale_factor == resp.postscale_factor
                and pending.process_set_id == resp.process_set_id
                and pending_bytes + nbytes <= threshold)
            if compatible:
                pending.tensor_names.extend(resp.tensor_names)
                pending.tensor_shapes.extend(resp.tensor_shapes)
                pending_bytes += nbytes
            else:
                flush()
                pending = resp
                pending_bytes = nbytes
        flush()
        return fused

    # -- execution (ref: PerformOperation operations.cc:257) ---------------
    def _execute_response_list(self, responses: List[Response]) -> None:
        for resp in responses:
            try:
                self._execute_response(resp)
            except Exception as e:
                log.exception("execution failed for %s", resp.tensor_names)
                self._fail_response(resp, f"{type(e).__name__}: {e}")

    def _pop_entries(self, resp: Response) -> List[Optional[_Entry]]:
        entries = []
        with self._lock:
            for name in resp.tensor_names:
                entries.append(self._entries.pop((resp.process_set_id, name),
                                                 None))
        return entries

    def _execute_response(self, resp: Response) -> None:
        # Profiler range per fused response (NVTX analog — ref:
        # common/nvtx_op_range.h, ranges named by op and batch size;
        # disable via HVDT_DISABLE_PROFILER_RANGES).  Shows up in
        # jax.profiler / XPlane traces alongside device activity.
        from ..common import config

        if not config.get_bool("HVDT_DISABLE_PROFILER_RANGES"):
            import jax

            label = (f"hvdt.{RequestType(resp.response_type).name}"
                     f".x{len(resp.tensor_names)}")
            with jax.profiler.TraceAnnotation(label):
                self._execute_response_inner(resp)
            return
        self._execute_response_inner(resp)

    def _execute_response_inner(self, resp: Response) -> None:
        rt = resp.response_type
        if rt == RequestType.JOIN:
            with self._lock:
                handle = self._local_join_handles.pop(resp.process_set_id, None)
            if handle is not None:
                self.handles.mark_done(handle, Status.ok(),
                                       resp.last_joined_rank)
            return
        if rt == RequestType.BARRIER:
            for name, entry in zip(resp.tensor_names, self._pop_entries(resp)):
                if entry is not None:
                    self._fr_close([entry])
                    self.handles.mark_done(entry.handle, Status.ok(), None)
            return
        if resp.error_message:
            self._fail_response(resp, resp.error_message)
            return

        entries = self._pop_entries(resp)
        # record timeline: negotiation over, execution begins
        if self._timeline:
            for name in resp.tensor_names:
                self._timeline.end_activity(name)
                self._timeline.start_activity(name, f"EXEC_{rt.name}",
                                              {"fused": len(resp.tensor_names)})
        from ..telemetry import instrument as _ti
        from ..telemetry import trace as _trace

        rec = _ti.get_recorder()
        tracer = _trace.get_tracer()
        t_exec0 = time.monotonic() if (rec is not None or
                                       tracer is not None) else 0.0
        if rec is not None:
            dtype = numpy_dtype_of_safe(resp.tensor_type)
            nbytes = sum(
                int(np.prod(shape)) * dtype.itemsize if shape else
                dtype.itemsize
                for shape in (resp.tensor_shapes or []))
            rec.record_collective(rt.name, dtype.name, dtype.name, nbytes,
                                  count=len(resp.tensor_names),
                                  path="eager")
            for entry in entries:
                if entry is None:
                    continue
                if entry.announce_ts is not None:
                    rec.observe_queue(entry.announce_ts - entry.enqueue_ts)
                    rec.observe_negotiate(t_exec0 - entry.announce_ts)
                else:
                    rec.observe_negotiate(t_exec0 - entry.enqueue_ts)
        try:
            import jax

            with jax.profiler.TraceAnnotation(
                    f"hvdt.{rt.name}.{resp.tensor_names[0]}"
                    + (f"+{len(resp.tensor_names)-1}" if
                       len(resp.tensor_names) > 1 else "")):
                self._dispatch(resp, entries)
        except Exception as e:
            # Entries are already popped here, so the outer
            # _fail_response cannot find them — fail their handles
            # directly or the callers' synchronize() would hang forever.
            # Skip handles _dispatch already completed (a fused response
            # can fail partway through its finish loop); mark_done has no
            # already-done guard and would overwrite a good result.
            self._fr_close(entries, status="error")
            for entry in entries:
                if entry is not None and not self.handles.poll(entry.handle):
                    self.handles.mark_done(
                        entry.handle,
                        Status.unknown(f"{type(e).__name__}: {e}"))
            raise
        else:
            self._fr_close(entries)
        finally:
            if rec is not None:
                rec.observe_execute(time.monotonic() - t_exec0)
            if tracer is not None:
                tracer.complete(
                    f"EXEC_{rt.name}:{resp.tensor_names[0]}",
                    time.monotonic() - t_exec0, cat="collective",
                    args={"fused": len(resp.tensor_names),
                          "tensors": list(resp.tensor_names[:4])})
            if self._timeline:
                for name, shape in zip(resp.tensor_names,
                                       resp.tensor_shapes or
                                       [()] * len(resp.tensor_names)):
                    self._timeline.end_activity(name, {"shape": list(shape)})
        # coherent cache update on every rank, in execution order
        ps = basics._global_state().process_set_table.get(resp.process_set_id)
        my_splits: Tuple[int, ...] = ()
        if rt == RequestType.ALLTOALL and resp.recv_splits and ps.included():
            my_splits = tuple(resp.recv_splits[ps.rank()])
        for name, shape in zip(resp.tensor_names, resp.tensor_shapes):
            req = Request(0, rt, name, resp.tensor_type, tuple(shape),
                          resp.reduce_op, resp.prescale_factor,
                          resp.postscale_factor, resp.root_rank,
                          my_splits, resp.process_set_id, -1)
            self._cache.insert(req)

    def _dispatch(self, resp: Response, entries: List[Optional[_Entry]]) -> None:
        ps = basics._global_state().process_set_table.get(resp.process_set_id)
        if not ps.included():
            # responses broadcast to all ranks; non-members just skip
            # (they hold no entries and own no devices in the sub-mesh)
            return
        rt = resp.response_type
        dtype = numpy_dtype_of_safe(resp.tensor_type)
        single = ps.size() == 1

        def finish(entry: Optional[_Entry], value: np.ndarray) -> None:
            if entry is None:
                return
            result: Any = value
            if entry.was_jax:
                import jax.numpy as jnp

                result = jnp.asarray(value)
            self.handles.mark_done(entry.handle, Status.ok(), result)

        if rt in (RequestType.ALLREDUCE, RequestType.ADASUM):
            op = ReduceOp(resp.reduce_op)
            values = []
            for name, shape, entry in zip(resp.tensor_names,
                                          resp.tensor_shapes, entries):
                if entry is None or entry.tensor is None:
                    # joined rank: contribute zeros (ref: JoinOp semantics)
                    values.append(np.zeros(shape, dtype))
                else:
                    values.append(np.asarray(entry.tensor))
            pre, post = resp.prescale_factor, resp.postscale_factor
            if pre != 1.0:
                values = [v * np.asarray(pre, v.dtype) for v in values]
            if single:
                outs = values
            else:
                flat = np.concatenate([v.reshape(-1) for v in values]) \
                    if len(values) > 1 else values[0].reshape(-1)
                if op == ReduceOp.ADASUM:
                    from .adasum import host_adasum

                    red = host_adasum(flat, ps)
                else:
                    red = hostc.host_allreduce(flat, ps, op)
                outs = []
                off = 0
                for shape in resp.tensor_shapes:
                    n = int(np.prod(shape)) if shape else 1
                    outs.append(red[off:off + n].reshape(shape))
                    off += n
            if post != 1.0:
                outs = [o * np.asarray(post, o.dtype) for o in outs]
            for entry, out in zip(entries, outs):
                finish(entry, out)
        elif rt == RequestType.ALLGATHER:
            entry = entries[0]
            dim0s = [s[0] for s in resp.tensor_shapes]
            if single:
                out = np.asarray(entry.tensor) if entry else np.zeros((0,), dtype)
            else:
                my = np.asarray(entry.tensor) if entry is not None and \
                    entry.tensor is not None else \
                    np.zeros((0,) + tuple(resp.tensor_shapes[0][1:]), dtype)
                out = hostc.host_allgather(my, ps, dim0s)
            finish(entry, out)
        elif rt == RequestType.BROADCAST:
            entry = entries[0]
            shape = resp.tensor_shapes[0]
            if single:
                out = np.asarray(entry.tensor) if entry else np.zeros(shape, dtype)
            else:
                val = np.asarray(entry.tensor) if entry is not None and \
                    entry.tensor is not None else None
                out = hostc.host_broadcast(val, resp.root_rank, ps, shape,
                                           dtype)
            finish(entry, out)
        elif rt == RequestType.ALLTOALL:
            entry = entries[0]
            all_splits = [list(s) for s in resp.recv_splits]
            if single:
                out = np.asarray(entry.tensor) if entry else np.zeros((0,), dtype)
                recv = [out.shape[0]] if out.ndim else [0]
            else:
                # joined rank: zero-row contribution with zero splits
                my = (np.asarray(entry.tensor) if entry is not None and
                      entry.tensor is not None else
                      np.zeros((0,) + tuple(resp.tensor_shapes[0][1:]), dtype))
                my_splits = all_splits[ps.rank()]
                out, recv = hostc.host_alltoall(my, my_splits, ps, all_splits)
            if entry is not None:
                result = (out, recv)
                if entry.was_jax:
                    import jax.numpy as jnp

                    result = (jnp.asarray(out), recv)
                self.handles.mark_done(entry.handle, Status.ok(), result)
        elif rt == RequestType.REDUCESCATTER:
            entry = entries[0]
            op = ReduceOp(resp.reduce_op)
            if single:
                out = np.asarray(entry.tensor) if entry else np.zeros((0,), dtype)
            else:
                # joined rank contributes zeros of the negotiated shape
                my = (np.asarray(entry.tensor) if entry is not None and
                      entry.tensor is not None else
                      np.zeros(tuple(resp.tensor_shapes[0]), dtype))
                out = hostc.host_reducescatter(my, ps, op)
            finish(entry, out)
        else:
            raise HorovodInternalError(f"Unknown response type {rt}")

    def _fr_close(self, entries, status: str = "done") -> None:
        """Close the flight-recorder events opened at enqueue for these
        entries (no-op when the recorder is off)."""
        from ..telemetry import flight_recorder as _frm

        flight = _frm.get_flight_recorder()
        if flight is None:
            return
        for e in entries:
            if e is not None and e.fr_seq is not None:
                flight.record_end(e.fr_seq, status=status)
                e.fr_seq = None

    def _fail_response(self, resp: Response, message: str) -> None:
        for entry in self._pop_entries(resp):
            if entry is not None:
                self._fr_close([entry], status="error")
                self.handles.mark_done(entry.handle,
                                       Status.unknown(message))
        if self._timeline:
            for name in resp.tensor_names:
                self._timeline.instant(name, "ERROR", {"message": message})

    def _fail_all(self, message: str) -> None:
        with self._lock:
            self._running = False
            entries = list(self._entries.values())
            self._entries.clear()
        self._fr_close(entries, status="error")
        for e in entries:
            self.handles.mark_done(e.handle, Status.unknown(message))
        self.handles.abort_all(message)

    # -- group registration -------------------------------------------------
    def register_group(self, group_id: int, names: Sequence[str]) -> None:
        self._group_members[group_id] = set(names)

    def shutdown(self) -> None:
        self._running = False
        self._thread.join(timeout=5)
        self.handles.abort_all("controller shut down")
        self.cp.shutdown()


def numpy_dtype_of_safe(tensor_type: int) -> np.dtype:
    from ..common.types import DataType, numpy_dtype_of

    try:
        return numpy_dtype_of(DataType(tensor_type))
    except Exception:
        return np.dtype(np.float32)


# ---------------------------------------------------------------------------
# Module-level controller lifecycle
# ---------------------------------------------------------------------------

def _controller() -> EagerController:
    state = basics._global_state()
    if not state.initialized:
        from ..common.exceptions import NotInitializedError

        raise NotInitializedError()
    with state.lock:
        if state.eager_controller is None:
            state.eager_controller = EagerController()
        return state.eager_controller


def shutdown_controller() -> None:
    state = basics._global_state()
    with state.lock:
        if state.eager_controller is not None:
            state.eager_controller.shutdown()
            state.eager_controller = None


# ---------------------------------------------------------------------------
# Public API (ref: torch/mpi_ops.py:107-994 API surface)
# ---------------------------------------------------------------------------

_name_counters: Dict[str, Any] = collections.defaultdict(itertools.count)


def _auto_name(kind: str, name: Optional[str]) -> str:
    """Deterministic auto-naming — identical across ranks as long as ops are
    issued in the same order (ref: allreduce.noname.N convention)."""
    if name is not None:
        return name
    return f"{kind}.noname.{next(_name_counters[kind])}"


def _prep(tensor) -> Tuple[np.ndarray, bool]:
    was_jax = type(tensor).__module__.startswith("jax")
    value = np.asarray(tensor)
    if (value.dtype.kind in "iu" and value.dtype.itemsize == 8
            and _controller().cp.size() > 1):
        from . import tcp_backend

        if not tcp_backend.enabled():
            # Fail at the call site (rank-local, synchronous) rather than
            # mid-collective where peers would hang — see
            # host_collectives.check_device_representable.
            from .host_collectives import check_device_representable

            check_device_representable(value)
    return value, was_jax


def _resolve_op(op, average):
    if op is not None and average is not None:
        raise ValueError("Specify either op or average, not both")
    if op is None:
        if average is None or average:
            return ReduceOp.AVERAGE
        return ReduceOp.SUM
    return ReduceOp(op)


def allreduce_async(tensor, average=None, name: Optional[str] = None,
                    op=None, prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    process_set: Optional[ProcessSet] = None) -> int:
    """Asynchronously allreduce a named tensor across ranks
    (ref: torch/mpi_ops.py allreduce_async_)."""
    ps = process_set or global_process_set()
    value, was_jax = _prep(tensor)
    rop = _resolve_op(op, average)
    req = Request(_controller().cp.rank(),
                  RequestType.ADASUM if rop == ReduceOp.ADASUM
                  else RequestType.ALLREDUCE,
                  _auto_name("allreduce", name), int(data_type_of(value)),
                  tuple(value.shape), int(rop), prescale_factor,
                  postscale_factor, process_set_id=ps.id)
    return _controller().enqueue(req, value, was_jax)


def allreduce(tensor, average=None, name: Optional[str] = None, op=None,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0,
              process_set: Optional[ProcessSet] = None):
    return synchronize(allreduce_async(tensor, average, name, op,
                                       prescale_factor, postscale_factor,
                                       process_set))


def grouped_allreduce_async(tensors: Sequence, average=None,
                            name: Optional[str] = None, op=None,
                            prescale_factor: float = 1.0,
                            postscale_factor: float = 1.0,
                            process_set: Optional[ProcessSet] = None,
                            group_id: Optional[int] = None) -> List[int]:
    """Grouped allreduce: all-or-nothing fusion
    (ref: EnqueueTensorAllreduces operations.cc:1384, GroupTable).

    ``group_id`` lets callers with a fixed group structure reuse a stable
    id: the coordinator's all-or-nothing gate keys member-name sets by
    group id, so a caller whose groups may be ISSUED in different orders
    on different ranks (e.g. autograd-hook order) must pre-allocate ids
    deterministically instead of taking a fresh one per call."""
    ps = process_set or global_process_set()
    ctl = _controller()
    rop = _resolve_op(op, average)
    gid = ctl.next_group_id() if group_id is None else int(group_id)
    base = _auto_name("grouped_allreduce", name)
    names = [f"{base}.{i}" for i in range(len(tensors))]
    ctl.register_group(gid, names)
    handles = []
    for nm, t in zip(names, tensors):
        value, was_jax = _prep(t)
        req = Request(ctl.cp.rank(), RequestType.ALLREDUCE, nm,
                      int(data_type_of(value)), tuple(value.shape), int(rop),
                      prescale_factor, postscale_factor,
                      process_set_id=ps.id, group_id=gid)
        handles.append(ctl.enqueue(req, value, was_jax))
    return handles


def grouped_allreduce(tensors: Sequence, **kwargs) -> List:
    return [synchronize(h) for h in grouped_allreduce_async(tensors, **kwargs)]


def allgather_async(tensor, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    ps = process_set or global_process_set()
    value, was_jax = _prep(tensor)
    req = Request(_controller().cp.rank(), RequestType.ALLGATHER,
                  _auto_name("allgather", name), int(data_type_of(value)),
                  tuple(value.shape), process_set_id=ps.id)
    return _controller().enqueue(req, value, was_jax)


def allgather(tensor, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    return synchronize(allgather_async(tensor, name, process_set))


def broadcast_async(tensor, root_rank: int, name: Optional[str] = None,
                    process_set: Optional[ProcessSet] = None) -> int:
    ps = process_set or global_process_set()
    value, was_jax = _prep(tensor)
    req = Request(_controller().cp.rank(), RequestType.BROADCAST,
                  _auto_name("broadcast", name), int(data_type_of(value)),
                  tuple(value.shape), root_rank=root_rank,
                  process_set_id=ps.id)
    return _controller().enqueue(req, value, was_jax)


def broadcast(tensor, root_rank: int, name: Optional[str] = None,
              process_set: Optional[ProcessSet] = None):
    return synchronize(broadcast_async(tensor, root_rank, name, process_set))


def alltoall_async(tensor, splits: Optional[Sequence[int]] = None,
                   name: Optional[str] = None,
                   process_set: Optional[ProcessSet] = None) -> int:
    ps = process_set or global_process_set()
    value, was_jax = _prep(tensor)
    if splits is None:
        n = value.shape[0]
        p = ps.size()
        base, rem = divmod(n, p)
        splits = [base + (1 if i < rem else 0) for i in range(p)]
    if int(sum(splits)) != value.shape[0]:
        raise ValueError(
            f"splits sum ({sum(splits)}) != tensor dim0 ({value.shape[0]})")
    req = Request(_controller().cp.rank(), RequestType.ALLTOALL,
                  _auto_name("alltoall", name), int(data_type_of(value)),
                  tuple(value.shape), splits=tuple(int(s) for s in splits),
                  process_set_id=ps.id)
    return _controller().enqueue(req, value, was_jax)


def alltoall(tensor, splits: Optional[Sequence[int]] = None,
             name: Optional[str] = None,
             process_set: Optional[ProcessSet] = None):
    """Returns (output, recv_splits) (ref: torch/mpi_ops.py alltoall)."""
    return synchronize(alltoall_async(tensor, splits, name, process_set))


def reducescatter_async(tensor, op=ReduceOp.SUM, name: Optional[str] = None,
                        process_set: Optional[ProcessSet] = None) -> int:
    ps = process_set or global_process_set()
    value, was_jax = _prep(tensor)
    req = Request(_controller().cp.rank(), RequestType.REDUCESCATTER,
                  _auto_name("reducescatter", name),
                  int(data_type_of(value)), tuple(value.shape),
                  int(ReduceOp(op)), process_set_id=ps.id)
    return _controller().enqueue(req, value, was_jax)


def reducescatter(tensor, op=ReduceOp.SUM, name: Optional[str] = None,
                  process_set: Optional[ProcessSet] = None):
    return synchronize(reducescatter_async(tensor, op, name, process_set))


def barrier(process_set: Optional[ProcessSet] = None) -> None:
    """Block until all ranks reach the barrier (ref: operations.cc barrier
    enqueue :1767)."""
    ps = process_set or global_process_set()
    ctl = _controller()
    req = Request(ctl.cp.rank(), RequestType.BARRIER,
                  _auto_name("barrier", None), 0, (), process_set_id=ps.id)
    synchronize(ctl.enqueue(req, None, False))


def join(process_set: Optional[ProcessSet] = None) -> int:
    """Signal this rank has no more work; block until all ranks join.
    Returns the last rank to join (ref: torch/mpi_ops.py:954 join;
    JoinOp ops/collective_operations.h:275)."""
    ps = process_set or global_process_set()
    return synchronize(_controller().enqueue_join(ps))


def poll(handle: int) -> bool:
    return _controller().handles.poll(handle)


def synchronize(handle: int, timeout: Optional[float] = None):
    return _controller().handles.synchronize(handle, timeout)
