"""Device-level collective primitives — the TPU data plane.

This module is the TPU-native replacement for the reference's entire
collective-op backend stack (ref: ops/mpi_operations.cc, ops/nccl_operations.cc,
ops/gloo_operations.cc, ops/ccl_operations.cc — SURVEY.md §2.2): instead of
hand-written transports, collectives are XLA programs over ICI/DCN expressed
with ``jax.lax`` named-axis primitives.  They are valid inside ``shard_map``
/ ``pjit`` bodies where the named mesh axes are bound.

Design notes (SURVEY.md §5.8): under jit, op order is globally consistent, so
the reference's name-negotiation machinery is unnecessary here — XLA plays the
role of the OperationManager, and fusion is explicit bucketing (see
``fused_allreduce``) mirroring the FusionBufferManager
(ref: common/fusion_buffer_manager.{h,cc}, controller.cc:808 FuseResponses).
"""

from __future__ import annotations

import functools
from typing import Any, Dict, List, Optional, Sequence, Tuple, Union

import jax
import jax.numpy as jnp
from jax import lax

from ..common.logging_util import get_logger
from ..common.types import ReduceOp

log = get_logger(__name__)

__all__ = [
    "allreduce",
    "allgather",
    "allgather_ragged",
    "reduce_scatter",
    "broadcast",
    "alltoall",
    "alltoall_uneven",
    "axis_rank",
    "axis_size",
    "fused_allreduce",
    "fused_allreduce_buckets",
    "hierarchical_allreduce",
    "invariant_allgather_shards",
    "reduce_scatter_flat",
    "allgather_flat_shards",
    "shard_owner_index",
]

AxisName = Union[str, Tuple[str, ...]]


def axis_rank(axis: AxisName) -> jax.Array:
    """Rank of this shard along ``axis`` (ref: horovod_rank per communicator)."""
    return lax.axis_index(axis)


def axis_size(axis: AxisName) -> int:
    """Number of shards along ``axis`` (ref: horovod_size)."""
    return _axis_size_static(axis)


def _axes_tuple(axis: AxisName) -> Tuple[str, ...]:
    return (axis,) if isinstance(axis, str) else tuple(axis)


def _axis_size_static(axis: AxisName) -> int:
    """Static size of the bound mesh axis/axes.  Guarded for JAX builds
    without ``lax.axis_size`` (<= 0.4.x): ``lax.psum`` of the literal 1
    is constant-folded to a python int under shard_map on every JAX.
    Raises (NameError) when ``axis`` is not bound, like axis_size."""
    size_fn = getattr(lax, "axis_size", None)
    n = 1
    for a in _axes_tuple(axis):
        n *= int(size_fn(a)) if size_fn is not None else int(lax.psum(1, a))
    return n


def _vma_tracking_active(axis: AxisName) -> bool:
    """True when varying-manual-axes tracking is live for ``axis`` in the
    current trace.  Under ``shard_map(..., check_vma=False)`` every aval
    reports an empty vma, which would be indistinguishable from "genuinely
    replicated" — probe with a pcast: if even an explicitly-varied zero
    reports an empty vma, tracking is off and callers must assume varying.
    """
    import jax.numpy as jnp

    for a in _axes_tuple(axis):
        try:
            probe = lax.pcast(jnp.zeros((), jnp.float32), a, to="varying")
            if a not in jax.typeof(probe).vma:
                return False
        except Exception:
            return False
    return True


def is_varying(x, axis: AxisName) -> bool:
    """Whether ``x`` is varying (per-shard distinct) over ``axis`` under
    JAX's varying-manual-axes tracking (jax>=0.8 shard_map).

    Load-bearing semantics note: in modern JAX, ``jax.grad`` taken inside
    ``shard_map`` w.r.t. a *replicated* (unvarying) parameter already
    returns the cross-shard SUM of per-shard gradients — the AD system
    inserts the psum to keep the cotangent unvarying.  An allreduce on such
    a value must therefore not psum again; the varying-aware fast paths
    below keep Horovod allreduce semantics exact in both regimes.

    Conservatively returns True (collective WILL be issued) whenever
    tracking cannot be positively confirmed: older jax, eager, or
    ``check_vma=False`` shard_maps.
    """
    if not _vma_tracking_active(axis):
        return True
    try:
        vma = jax.typeof(x).vma
    except Exception:
        return True
    return any(a in vma for a in _axes_tuple(axis))


def allreduce(x, axis: AxisName = "dp", op: ReduceOp = ReduceOp.AVERAGE,
              prescale_factor: float = 1.0, postscale_factor: float = 1.0):
    """Allreduce over a mesh axis (ref: EnqueueTensorAllreduce
    operations.cc:1357; NCCLAllreduce::Execute nccl_operations.cc:175).

    Average is implemented as sum + postscale by 1/size, matching the
    reference's prescale/postscale split (torch/optimizer.py:197-204) —
    XLA folds the scales into neighbouring ops.
    """
    if prescale_factor != 1.0:
        x = jax.tree.map(lambda t: t * prescale_factor, x)

    # Varying-aware fast path: an unvarying input is identical on every
    # shard, so the reduction is a scalar identity and no collective is
    # needed.  SEMANTICS: this treats x as "the per-rank value" — average
    # of n identical copies is x, sum is n*x (exactly what a psum would
    # return, minus the collective).  For GRADIENTS of replicated params,
    # which modern AD delivers pre-summed, use
    # optimizer.allreduce_gradients — it applies the gradient-aware
    # interpretation (average = x/n) instead.
    leaves = jax.tree.leaves(x)
    if leaves and all(not is_varying(t, axis) for t in leaves):
        n = 1
        for a in _axes_tuple(axis):
            n *= _axis_size_static(a)
        if op == ReduceOp.SUM:
            out = jax.tree.map(lambda t: t * n, x)
        elif op in (ReduceOp.AVERAGE, ReduceOp.MIN, ReduceOp.MAX,
                    ReduceOp.ADASUM):
            out = x
        elif op == ReduceOp.PRODUCT:
            out = jax.tree.map(lambda t: t ** n, x)
        else:
            raise ValueError(f"Unsupported reduce op: {op}")
        if postscale_factor != 1.0:
            out = jax.tree.map(lambda t: t * postscale_factor, out)
        return out

    if op in (ReduceOp.AVERAGE, ReduceOp.SUM):
        out = lax.psum(x, axis)
        if op == ReduceOp.AVERAGE:
            n = _axis_size_static(axis)
            out = jax.tree.map(lambda t: t / n, out)
    elif op == ReduceOp.MIN:
        out = lax.pmin(x, axis)
    elif op == ReduceOp.MAX:
        out = lax.pmax(x, axis)
    elif op == ReduceOp.PRODUCT:
        # exp(psum(log|x|)) with explicit sign/zero tracking so arbitrary
        # reals reduce correctly (log of a negative would poison the psum).
        def _prod(t):
            mag = jnp.exp(lax.psum(jnp.log(jnp.where(t == 0, 1.0, jnp.abs(t))), axis))
            n_neg = lax.psum((t < 0).astype(jnp.int32), axis)
            any_zero = lax.psum((t == 0).astype(jnp.int32), axis) > 0
            signed = jnp.where(n_neg % 2 == 1, -mag, mag)
            return jnp.where(any_zero, 0.0, signed).astype(t.dtype)

        out = jax.tree.map(_prod, x)
    elif op == ReduceOp.ADASUM:
        from . import adasum as _adasum

        out = _adasum.adasum_allreduce(x, axis)
    else:
        raise ValueError(f"Unsupported reduce op: {op}")
    if postscale_factor != 1.0:
        out = jax.tree.map(lambda t: t * postscale_factor, out)
    return out


def allgather(x, axis: AxisName = "dp", concat_axis: int = 0, *, tiled: bool = True):
    """Allgather over a mesh axis, concatenating along ``concat_axis``
    (ref: EnqueueTensorAllgather; AllgatherOp displacement math
    ops/collective_operations.h:129).  Unlike the reference, first-dimension
    ragged gathers are not supported under jit (static shapes); use the eager
    path for ragged inputs."""
    return jax.tree.map(
        lambda t: lax.all_gather(t, axis, axis=concat_axis, tiled=tiled), x)


def reduce_scatter(x, axis: AxisName = "dp", scatter_axis: int = 0,
                   op: ReduceOp = ReduceOp.SUM):
    """Reduce-scatter over a mesh axis — first-class on TPU (building block
    for ZeRO/FSDP-style sharding and Adasum; the reference only has it
    embedded inside NCCLHierarchicalAllreduce, nccl_operations.cc:378).

    SUM/AVERAGE lower to ``psum_scatter`` (the native ICI reduction).
    MIN/MAX/PRODUCT have no scatter-reduce XLA primitive, so they lower to
    the bandwidth-equivalent all-to-all + local reduce: each element
    crosses the wire exactly once, then n shard-copies reduce locally —
    the same wire cost as a ring reduce-scatter (the reference's dispatch
    handles these ops generically, ops/collective_operations.h:209-273)."""
    def _rs(t):
        if op in (ReduceOp.SUM, ReduceOp.AVERAGE):
            out = lax.psum_scatter(t, axis, scatter_dimension=scatter_axis,
                                   tiled=True)
            if op == ReduceOp.AVERAGE:
                out = out / _axis_size_static(axis)
            return out
        n = _axis_size_static(axis)
        if t.shape[scatter_axis] % n:
            raise ValueError(
                f"reduce_scatter dim {scatter_axis} ({t.shape[scatter_axis]}) "
                f"not divisible by axis size {n}")
        # rank r receives every rank's r'th slice, stacked along
        # scatter_axis: [..., n*chunk, ...] -> [..., n, chunk, ...]
        gathered = lax.all_to_all(t, axis, split_axis=scatter_axis,
                                  concat_axis=scatter_axis, tiled=True)
        chunk = t.shape[scatter_axis] // n
        shape = (gathered.shape[:scatter_axis] + (n, chunk)
                 + gathered.shape[scatter_axis + 1:])
        stacked = gathered.reshape(shape)
        if op == ReduceOp.MIN:
            return jnp.min(stacked, axis=scatter_axis)
        if op == ReduceOp.MAX:
            return jnp.max(stacked, axis=scatter_axis)
        if op == ReduceOp.PRODUCT:
            return jnp.prod(stacked, axis=scatter_axis)
        raise ValueError(f"Unsupported reduce op: {op}")

    return jax.tree.map(_rs, x)


def allgather_ragged(x, sizes: Sequence[int], axis: AxisName = "dp"):
    """Allgather where rank r contributes its first ``sizes[r]`` rows —
    the jit-path answer to the reference's first-dimension-ragged allgather
    (AllgatherOp displacement math, ops/collective_operations.h:129).

    ``sizes`` must be static (known at trace time): XLA needs static
    shapes, so the dynamic-shape negotiation the reference does at runtime
    moves to trace time here.  Every rank passes a uniformly padded array
    with ``max(sizes)`` rows (SPMD requires identical per-rank shapes);
    rows past ``sizes[rank]`` are ignored.  Returns the exact
    ``sum(sizes)``-row concatenation, replicated (axis-invariant).

    Lowering: each rank zero-embeds its valid rows at its static
    displacement and the result is one psum — gather and invariance
    restoration fused into a single all-reduce (see
    ``invariant_allgather_shards`` for the equal-shard case).
    """
    sizes = [int(s) for s in sizes]
    n = _axis_size_static(axis)
    if len(sizes) != n:
        raise ValueError(f"len(sizes)={len(sizes)} != axis size {n}")
    maxpad = max(sizes)
    total = sum(sizes)
    offsets = jnp.asarray(
        [sum(sizes[:r]) for r in range(n)], jnp.int32)
    sizes_arr = jnp.asarray(sizes, jnp.int32)
    idx = lax.axis_index(axis)

    def _one(t):
        if t.shape[0] != maxpad:
            raise ValueError(
                f"ragged allgather input must be padded to max(sizes)="
                f"{maxpad} rows, got {t.shape[0]}")
        mask_shape = (maxpad,) + (1,) * (t.ndim - 1)
        mask = (jnp.arange(maxpad) < sizes_arr[idx]).reshape(mask_shape)
        contrib = jnp.where(mask, t, jnp.zeros((), t.dtype))
        # Embed into total+maxpad rows so the padded block never clamps;
        # masked-zero overhang rows land in the next rank's region and
        # add nothing under psum.
        buf = jnp.zeros((total + maxpad,) + t.shape[1:], t.dtype)
        buf = lax.dynamic_update_slice_in_dim(buf, contrib, offsets[idx],
                                              axis=0)
        return lax.psum(buf, axis)[:total]

    return jax.tree.map(_one, x)


def alltoall_uneven(x, send_splits: Sequence[Sequence[int]],
                    axis: AxisName = "dp"):
    """All-to-all with per-(src, dst) row counts — the jit-path analog of
    the reference's alltoallv (AlltoallOp::PrepareOutputAndParams recv-
    split exchange, ops/collective_operations.h:209-273).

    ``send_splits[r][j]`` = rows rank r sends to rank j, static at trace
    time (the runtime recv-split MPI exchange moves to trace time under
    XLA's static-shape model).  Each rank's row counts must sum to the
    (uniform) input first dimension.  Because received totals differ per
    rank while SPMD output shapes cannot, the result is padded to the
    largest receive total; returns ``(out, recv_count)`` where ``out`` has
    ``max_j(sum_r send_splits[r][j])`` rows (rows past ``recv_count`` are
    zero) and ``recv_count`` is this rank's valid-row scalar.

    Wire cost: segments are padded to the largest single split for the
    device all_to_all — bounded overhead for near-even splits (the MoE
    capacity-padding regime this substrate targets, SURVEY.md §2.7);
    grossly skewed splits pay padding bandwidth.
    """
    M = [[int(v) for v in row] for row in send_splits]
    n = _axis_size_static(axis)
    if len(M) != n or any(len(row) != n for row in M):
        raise ValueError(f"send_splits must be {n}x{n}")
    row_tot = {sum(row) for row in M}
    if len(row_tot) != 1:
        raise ValueError(
            "each rank's send_splits row must sum to the same (uniform) "
            f"input length, got sums {sorted(row_tot)}")
    in_rows = row_tot.pop()
    maxseg = max(max(row) for row in M)
    recv_totals = [sum(M[r][j] for r in range(n)) for j in range(n)]
    max_out = max(recv_totals)

    send_off = jnp.asarray(
        [[sum(row[:j]) for j in range(n)] for row in M], jnp.int32)
    seg_len = jnp.asarray(M, jnp.int32)
    recv_off = jnp.asarray(
        [[sum(M[k][j] for k in range(r)) for j in range(n)]
         for r in range(n)], jnp.int32)
    recv_tot = jnp.asarray(recv_totals, jnp.int32)
    idx = lax.axis_index(axis)

    def _one(t):
        if t.shape[0] != in_rows:
            raise ValueError(
                f"input rows {t.shape[0]} != send_splits row sum {in_rows}")
        pad = jnp.zeros((maxseg,) + t.shape[1:], t.dtype)
        tp = jnp.concatenate([t, pad], axis=0)
        segs = []
        for j in range(n):
            seg = lax.dynamic_slice_in_dim(tp, send_off[idx, j], maxseg,
                                           axis=0)
            mask = (jnp.arange(maxseg) < seg_len[idx, j]).reshape(
                (maxseg,) + (1,) * (t.ndim - 1))
            segs.append(jnp.where(mask, seg, jnp.zeros((), t.dtype)))
        sendbuf = jnp.concatenate(segs, axis=0)        # [n*maxseg, ...]
        recvbuf = lax.all_to_all(sendbuf, axis, split_axis=0,
                                 concat_axis=0, tiled=True)
        out = jnp.zeros((max_out + maxseg,) + t.shape[1:], t.dtype)
        for r in range(n):
            block = lax.dynamic_slice_in_dim(recvbuf, r * maxseg, maxseg,
                                             axis=0)
            # blocks are already masked by the sender; valid regions are
            # disjoint, so additive embedding assembles the compaction.
            embed = jnp.zeros_like(out)
            embed = lax.dynamic_update_slice_in_dim(
                embed, block, recv_off[r, idx], axis=0)
            out = out + embed
        return out[:max_out]

    return jax.tree.map(_one, x), recv_tot[idx]


def broadcast(x, root_rank: int = 0, axis: AxisName = "dp"):
    """Broadcast from ``root_rank``'s shard to all shards along ``axis``
    (ref: EnqueueTensorBroadcast; NCCLBroadcast nccl_operations.cc:535).

    Implemented as a masked psum — the idiomatic XLA lowering (a one-hot
    select then all-reduce rides the same ICI reduction tree as a native
    broadcast)."""
    idx = lax.axis_index(axis)

    def _bcast(t):
        # where (not multiply) so NaN/Inf in non-root shards — e.g.
        # uninitialized buffers being overwritten by the broadcast — cannot
        # poison the psum.
        zero = jnp.zeros((), dtype=jnp.int32 if t.dtype == jnp.bool_ else t.dtype)
        contrib = jnp.where(idx == root_rank,
                            t.astype(zero.dtype) if t.dtype == jnp.bool_ else t,
                            zero)
        out = lax.psum(contrib, axis)
        return (out != 0) if t.dtype == jnp.bool_ else out

    return jax.tree.map(_bcast, x)


def alltoall(x, axis: AxisName = "dp", split_axis: int = 0, concat_axis: int = 0):
    """All-to-all over a mesh axis (ref: EnqueueTensorAlltoall
    operations.cc:1642; AlltoallOp ops/collective_operations.h:195).

    Equal splits only under jit (static shapes); the eager path handles
    uneven splits.  This is the substrate for expert parallelism (MoE token
    routing) — SURVEY.md §2.7."""
    return jax.tree.map(
        lambda t: lax.all_to_all(t, axis, split_axis=split_axis,
                                 concat_axis=concat_axis, tiled=True), x)


# ---------------------------------------------------------------------------
# Tensor fusion: bucketed fused allreduce over a pytree of gradients.
# (ref: FusionBufferManager common/fusion_buffer_manager.{h,cc};
#  FuseResponses controller.cc:808; fused memcpy collective_operations.cc.)
# On TPU the "fusion buffer" is a flat concatenated array per (dtype, bucket)
# — XLA emits a single all-reduce per bucket, the concat/split melt into
# copies that fuse with neighbours.
# ---------------------------------------------------------------------------

_threshold_warned = False


def _validated_threshold(threshold_bytes: Optional[Any] = None) -> int:
    """Resolve and validate the fusion threshold.

    ``None`` reads ``HVDT_FUSION_THRESHOLD``.  Non-positive or
    unparseable values (env garbage, a caller passing 0/-1) must not
    flow into bucket planning — a threshold of 0 would put every leaf
    in its own bucket and a negative one is meaningless — so they clamp
    to the registry default with a one-time warning."""
    global _threshold_warned
    from ..common import config

    if threshold_bytes is None:
        threshold_bytes = config.get_int("HVDT_FUSION_THRESHOLD")
    try:
        t = int(threshold_bytes)
    except (TypeError, ValueError):
        t = -1
    if t <= 0:
        default = int(config.KNOBS["HVDT_FUSION_THRESHOLD"].default)
        if not _threshold_warned:
            log.warning(
                "invalid fusion threshold %r (HVDT_FUSION_THRESHOLD or "
                "caller override); clamping to the default %d bytes",
                threshold_bytes, default)
            _threshold_warned = True
        return default
    return t


def fused_allreduce_buckets(leaves: Sequence[jax.Array],
                            threshold_bytes: int) -> List[List[int]]:
    """Plan fusion buckets: group leaf indices by dtype, pack up to
    ``threshold_bytes`` per bucket (64-byte alignment unit like the
    reference, common.h:147 — moot on TPU but kept for parity of the plan).

    Pure planning function; host-side, shape-only.  Deterministic:
    dtype groups are emitted in canonical (dtype-name) order, not dict
    insertion order, so the plan does not depend on which dtype happens
    to appear first in ``leaves`` — same leaves, any interleaving of
    dtypes → same bucket plan (within a dtype, input order is preserved:
    it is the reverse-topological adjacency the overlap schedule needs).
    """
    threshold_bytes = _validated_threshold(threshold_bytes)
    by_dtype: Dict[Any, List[int]] = {}
    for i, leaf in enumerate(leaves):
        by_dtype.setdefault(jnp.result_type(leaf), []).append(i)
    buckets: List[List[int]] = []
    for dtype, idxs in sorted(by_dtype.items(),
                              key=lambda kv: jnp.dtype(kv[0]).name):
        cur: List[int] = []
        cur_bytes = 0
        itemsize = jnp.dtype(dtype).itemsize
        for i in idxs:
            nbytes = -(-leaves[i].size * itemsize // 64) * 64
            if cur and cur_bytes + nbytes > threshold_bytes:
                buckets.append(cur)
                cur, cur_bytes = [], 0
            cur.append(i)
            cur_bytes += nbytes
        if cur:
            buckets.append(cur)
    return buckets


def fused_allreduce(tree, axis: AxisName = "dp", op: ReduceOp = ReduceOp.AVERAGE,
                    threshold_bytes: Optional[int] = None,
                    prescale_factor: float = 1.0,
                    postscale_factor: float = 1.0,
                    wire_dtype: Optional[Any] = None):
    """Allreduce a pytree as few fused flat collectives (the hot path of
    DistributedOptimizer — ref call stack SURVEY.md §3.2).

    ``wire_dtype`` optionally casts buckets for the reduction (bf16 wire
    compression — ref: tensorflow/compression.py:141) and casts back.
    The sentinels ``"int8_blockwise"`` / ``"int4_blockwise"``
    (``Compression.int8`` / ``.int4`` ``wire_dtype``, ==
    quant.collectives INT8_WIRE/INT4_WIRE) instead route each float
    bucket through the two-stage block-scaled quantized allreduce —
    real int8 (or packed int4) payloads on the wire, f32 accumulation
    in the middle; non-float buckets keep the exact path.

    Transport policies (``HVDT_TRANSPORT``, horovod_tpu/transport): when
    the active policy resolves ``axis``, float SUM/AVERAGE buckets route
    through the two-level hierarchical allreduce (fast-axis
    reduce-scatter → slow-axis shard exchange → allgather) with the
    per-axis algorithm/wire/threshold the policy names; a single-axis
    flat resolution only overrides the wire/threshold.  Unset (the
    default) leaves this function's program byte-identical — the policy
    lookup is one env read at trace time.
    """
    from ..transport import policy as _tpolicy

    _res = _tpolicy.resolve_axis(axis)
    if threshold_bytes is None and _res is not None:
        threshold_bytes = _res.threshold_bytes
    threshold_bytes = _validated_threshold(threshold_bytes)

    if _res is not None and _res.kind == "flat" and wire_dtype is None:
        # Per-axis wire override for the single-axis flat path (the
        # policy's exact-name / ici-class entry); an explicit caller
        # wire (Compression) keeps precedence.
        wire_dtype = {"bf16": jnp.bfloat16, "fp16": jnp.float16,
                      "int8": "int8_blockwise",
                      "int4": "int4_blockwise"}.get(_res.fast.wire)

    from ..quant.collectives import quant_wire_leg as _qleg

    quant_leg = _qleg(wire_dtype)
    quant_wire = quant_leg is not None
    if quant_wire:
        wire_dtype = None  # the quantized path owns the wire format
    hier = (_res is not None and _res.kind == "hierarchical"
            and op in (ReduceOp.SUM, ReduceOp.AVERAGE))

    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    buckets = fused_allreduce_buckets(leaves, threshold_bytes)

    # Telemetry (trace time): under jit the compiled program, not this
    # host code, executes the collectives — so jit-path counters are
    # labelled path=jit and count traced bucket programs (the quantized
    # branch records its own wire accounting inside
    # quantized_allreduce_flat).
    from ..telemetry import instrument as _ti
    from ..telemetry import flight_recorder as _frm

    _rec = _ti.get_recorder()
    _flight = _frm.get_flight_recorder()

    _axis_label = "+".join(_axes_tuple(axis))
    out_leaves: List[Optional[jax.Array]] = [None] * len(leaves)
    for bi, bucket in enumerate(buckets):
        parts = [leaves[i] for i in bucket]
        shapes = [p.shape for p in parts]
        sizes = [p.size for p in parts]
        flat = jnp.concatenate([jnp.ravel(p) for p in parts]) if len(parts) > 1 \
            else jnp.ravel(parts[0])
        orig_dtype = flat.dtype
        float_bucket = jnp.issubdtype(orig_dtype, jnp.floating)
        hier_bucket = hier and float_bucket
        if wire_dtype is not None and flat.dtype != wire_dtype \
                and not hier_bucket:
            flat = flat.astype(wire_dtype)
        if _rec is not None or _flight is not None:
            bucket_bytes = int(flat.size) * jnp.dtype(flat.dtype).itemsize
            quant_bucket = quant_wire and float_bucket
            if _rec is not None:
                _rec.observe_fusion_fill(
                    bucket_bytes / float(threshold_bytes))
                if not quant_bucket and not hier_bucket:
                    _rec.record_collective(
                        "allreduce", jnp.dtype(orig_dtype).name,
                        jnp.dtype(flat.dtype).name, bucket_bytes,
                        count=len(parts), path="jit", axis=_axis_label)
            if _flight is not None and not quant_bucket:
                # One traced event per compiled bucket program (under jit
                # the program, not this host code, runs the collective).
                _flight.record(
                    op="allreduce",
                    name=f"hier.b{bi}" if hier_bucket else f"fused.b{bi}",
                    dtype=jnp.dtype(orig_dtype).name,
                    shape=(int(flat.size),), nbytes=bucket_bytes,
                    wire=(f"{_res.fast.wire}/{_res.slow.wire}"
                          if hier_bucket
                          else jnp.dtype(flat.dtype).name),
                    path="jit", count=len(parts), axis=_axis_label)
        # Named scope per fused bucket — the jit-trace analog of the
        # reference's NVTX op ranges; buckets appear as
        # hvdt.fused_allreduce.bN in XPlane/profiler output.
        with jax.named_scope(f"hvdt.fused_allreduce.b{bi}"):
            if hier_bucket:
                from ..transport.hierarchy import hierarchical_allreduce_flat

                red = hierarchical_allreduce_flat(
                    flat, _res, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor)
            elif quant_wire and float_bucket:
                from ..quant.collectives import quantized_allreduce_flat

                red = quantized_allreduce_flat(
                    flat, axis, op=op,
                    prescale_factor=prescale_factor,
                    postscale_factor=postscale_factor,
                    wire=quant_leg)
            else:
                red = allreduce(flat, axis, op, prescale_factor,
                                postscale_factor)
        if red.dtype != orig_dtype:
            red = red.astype(orig_dtype)
        offset = 0
        for i, shape, sz in zip(bucket, shapes, sizes):
            out_leaves[i] = lax.dynamic_slice_in_dim(red, offset, sz).reshape(shape)
            offset += sz
    return jax.tree.unflatten(treedef, out_leaves)


def invariant_allgather_shards(shard, axis: AxisName):
    """Reassemble equal shards into the full vector with an *invariant*
    result type: each rank zero-embeds its shard at its offset and the
    full vector is the psum.

    Rationale: every data-moving collective (all_gather/all_to_all/
    psum_scatter) keeps the varying-manual-axes type, so a pipeline that
    must end replicated (out_specs=P()) needs a psum-family terminal op;
    this fuses the gather and the invariance restoration into one
    allreduce instead of all_gather + identity pmean.
    shard: [chunk, ...]; returns [axis_size*chunk, ...]."""
    n = _axis_size_static(axis)
    idx = lax.axis_index(axis)
    chunk = shard.shape[0]
    full = jnp.zeros((n * chunk,) + shard.shape[1:], shard.dtype)
    full = lax.dynamic_update_slice_in_dim(full, shard, idx * chunk, axis=0)
    return lax.psum(full, axis)


def _rs_hop_order(axis: AxisName) -> Tuple[str, ...]:
    """Sequential reduce-scatter hop order over a reduce group:
    innermost (ICI) axis first, so the full payload rides the fast
    links and only the 1/n_fast shard crosses the slow outer tier (the
    mesh convention: outer axes are the slow ones)."""
    return tuple(reversed(_axes_tuple(axis)))


def reduce_scatter_flat(flat, axis: AxisName):
    """Tiled reduce-scatter of a flat vector over a (possibly
    multi-axis) reduce group: one ``psum_scatter`` hop per axis in
    :func:`_rs_hop_order`.  ``flat``'s length must divide by the group
    size.  Rank ``shard_owner_index(axis)`` receives its contiguous
    1/n chunk of the fully reduced vector — the ZeRO wire primitive
    (ops/zero.py) and the ``bench_allreduce --reduce-scatter`` leg."""
    shard = flat
    for a in _rs_hop_order(axis):
        shard = lax.psum_scatter(shard, a, tiled=True)
    return shard


def allgather_flat_shards(shard, axis: AxisName):
    """Inverse of :func:`reduce_scatter_flat`: invariant zero-embed +
    psum reassembly per axis in reverse hop order, so the result is
    *replicated* over the whole group (P() out_specs / optax.MultiSteps
    type stability — see :func:`invariant_allgather_shards`)."""
    full = shard
    for a in reversed(_rs_hop_order(axis)):
        full = invariant_allgather_shards(full, a)
    return full


def shard_owner_index(axis: AxisName):
    """Linearized chunk index this rank owns after
    :func:`reduce_scatter_flat` (most-significant digit = first RS
    hop).  Trace-time value; ``axis`` must be bound."""
    idx = None
    for a in _rs_hop_order(axis):
        k = _axis_size_static(a)
        i = lax.axis_index(a)
        idx = i if idx is None else idx * k + i
    return idx


def hierarchical_allreduce(x, inner_axis: AxisName = "ici",
                           outer_axis: AxisName = "dcn",
                           op: ReduceOp = ReduceOp.AVERAGE,
                           prescale_factor: float = 1.0,
                           postscale_factor: float = 1.0):
    """Two-level allreduce: reduce-scatter over the fast inner axis,
    allreduce the 1/n_inner shard over the slow outer axis, reassemble
    over inner (ref: NCCLHierarchicalAllreduce — local ncclReduceScatter
    → cross-node MPI_Allreduce → local ncclAllGather,
    nccl_operations.cc:249-517).

    On TPU the natural mapping is inner=ICI (within a slice), outer=DCN
    (between slices): outer-axis wire bytes drop to G/n_inner per chip.
    XLA's GSPMD often derives this itself for plain psum over both axes;
    this op makes the schedule explicit and controllable
    (ref knob: HOROVOD_HIERARCHICAL_ALLREDUCE, common.h:122)."""
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(f"hierarchical_allreduce supports SUM/AVERAGE, got {op}")

    def _one(t):
        ni = _axis_size_static(inner_axis)
        shape, dtype = t.shape, t.dtype
        flat = jnp.ravel(t)
        if prescale_factor != 1.0:
            flat = flat * jnp.asarray(prescale_factor, dtype)
        pad = (-flat.size) % ni
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros(pad, dtype)])
        shard = lax.psum_scatter(flat, inner_axis, tiled=True)
        shard = lax.psum(shard, outer_axis)
        full = invariant_allgather_shards(shard, inner_axis)
        if pad:
            full = full[:-pad]
        if op == ReduceOp.AVERAGE:
            full = full / (ni * _axis_size_static(outer_axis))
        if postscale_factor != 1.0:
            full = full * jnp.asarray(postscale_factor, full.dtype)
        return full.reshape(shape).astype(dtype)

    return jax.tree.map(_one, x)
