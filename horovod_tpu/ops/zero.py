"""ZeRO-sharded gradient exchange and optimizer state — the reduce-scatter
data plane (``HVDT_ZERO``).

Every training step of the replicated path ends n-fold redundant:
``fused_allreduce`` materializes the complete reduced gradient on every
rank and the optimizer touches full-size moment buffers everywhere, so
optimizer HBM and update FLOPs scale with the *replica count* instead of
the model (Rajbhandari et al., ZeRO; the MLPerf-on-TPU-pods runs train at
pod scale only with sharded state).  This module removes that redundancy
in three stages, selected by ``HVDT_ZERO=off|grads|states|params``
(ZeRO-1/2/3-style):

* ``grads`` — the *wire* changes: the bucket-level allreduce becomes an
  explicit **reduce-scatter + invariant allgather** split (same total
  wire bytes; the split is what lets the allgather be deferred and
  overlapped), everything else untouched.  Any optax optimizer works.
* ``states`` — gradients are reduce-scattered and **never fully
  materialized**: each rank runs the single-HBM-pass
  ``adam_leaf_update``/``sgd_leaf_update`` (ops/optim_kernels) on its
  **1/n shard** of the flat gradient with its 1/n shard of the moment
  buffers, then only the updated parameter *deltas* are allgathered —
  params stay replicated between steps, optimizer HBM shrinks ~n×.
* ``params`` — additionally the parameters themselves live **sharded
  between steps** (the caller carries the flat shards;
  :meth:`ZeroTransformation.gather_params` materializes them on demand
  — per step inside a shard_map, or per layer via GSPMD with the
  ``AXIS_FSDP`` rules in ``parallel/sharding``), so updates come back in
  shard layout and the per-step delta allgather disappears entirely.

Math contract: Adam/SGD are **elementwise**, so updating a flat
concatenated bucket shard computes bit-for-bit the values the replicated
per-leaf update computes — ``HVDT_ZERO=states`` is bitwise-equal (f32)
to the replicated path (params AND moments), the contract
tests/test_zero.py pins over 10 mesh-8 training steps.

Composition:

* **overlap** (ops/overlap.py): with ``HVDT_OVERLAP=on`` the per-bucket
  reduce-scatters are issued in the same reverse-topological order with
  the same ``optimization_barrier`` payload-token chain — bucket N's
  shard-update + allgather is pinned under bucket N+1's flight window.
* **transport** (horovod_tpu/transport): a hierarchical resolution
  routes the legs per mesh axis — fast-axis ``psum_scatter`` first, the
  1/n_fast shard exchanged over the slow axis (the block-scaled **int8
  start/finish wire** when the slow policy says so: the quant seam
  already splits exactly at reduce-scatter / dequant-accumulate).
* **quant** (Compression.int8 on a flat axis): the bucket rides
  :func:`quant.collectives.quantized_reduce_scatter_start` — the first
  hop of the established two-stage collective IS a wire-format
  reduce-scatter, so ZeRO gets the int8 wire for free.

State layout: per reverse-topological bucket, moments are flat
``[num_shards, shard_len]`` stacks (shard_len 256-element aligned so
every shard is kernel-tileable and int8-block-aligned).  Three crossing
modes are supported and auto-detected at trace time:

* **manual** — state enters a ``shard_map`` through ``in_specs
  P(axis)`` as ``[1, shard_len]`` rows: each device stores only its
  shard (the true n× memory saving);
* **replicated** — state enters through ``P()``: rank r dynamic-slices
  row r, and the updated row is re-assembled with the zero-embed+psum
  idiom so the output stays replicated (convenient, but every device
  materializes the stack — use NamedSharding/P(axis) for real savings);
* **unbound** — no mesh axis (plain auto-jit / host): gradients are
  already global, every shard row updates locally, no collective.

Zero-wrapper contract (the telemetry/faults/overlap idiom): with
``HVDT_ZERO`` unset, :func:`get_zero` returns ``None``,
:func:`exchange_fn` returns the pre-existing exchange code object
(``overlap.exchange_fn()`` — ``fused_allreduce`` itself when overlap is
also off), and ``DistributedOptimizer`` builds the exact replicated
chain it always built (identity-tested).

jax-0.4.37 guard: only ``psum``/``psum_scatter``/``optimization_barrier``
and the guarded ``dev._axis_size_static`` — no ``jax.typeof``/``pcast``/
``shard_map``-API dependence anywhere on this path.
"""

from __future__ import annotations

import dataclasses
import os
import threading
from typing import Any, Callable, Dict, List, NamedTuple, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common.logging_util import get_logger
from ..common.types import ReduceOp
from . import device as dev
from . import overlap as ovl

log = get_logger(__name__)

__all__ = [
    "STAGES", "ZeroSpec", "ZeroTransformation", "ZeroAdamState",
    "ZeroSgdState", "stage", "enabled", "get_zero", "reset",
    "validate_env", "resolve_stage", "exchange_fn", "rs_exchange",
    "zero_transform", "zero_sgd", "zero_adam", "zero_from_optimizer",
    "state_metadata", "reshard_state", "shard_align",
    "extract_shard_rows", "implant_shard_rows",
    "flatten_state_buffers", "rebucket_state", "concat_states",
]

STAGES: Tuple[str, ...] = ("off", "grads", "states", "params")

# Shard alignment (elements): multiples of 256 keep every flat shard
# 128-lane tileable for the fused optimizer kernels AND divisible by the
# default int8 quantization block, so the quantized reduce-scatter seam
# needs no re-padding.  A larger HVDT_QUANT_BLOCK raises it.


def shard_align() -> int:
    from ..quant import kernels as qk

    return max(256, int(qk.quant_block_size()))


# ---------------------------------------------------------------------------
# Env engagement (the get_recorder/get_scheduler idiom)
# ---------------------------------------------------------------------------

_OFF = ("", "0", "off", "none", "false", "no")

_lock = threading.Lock()
_cached_env: Optional[str] = "\0unset"   # sentinel != any real env value
_cached_stage: Optional[str] = None


def stage() -> Optional[str]:
    """The active ZeRO stage from ``HVDT_ZERO``, or ``None`` when off.
    Unknown values raise with the valid list (the HVDT_COMPRESSION
    early-validation idiom — ``hvd.init()`` calls :func:`validate_env`
    so a typo fails every worker at init)."""
    global _cached_env, _cached_stage
    raw = os.environ.get("HVDT_ZERO")
    if raw != _cached_env:
        with _lock:
            if raw != _cached_env:
                val = (raw or "").strip().lower()
                if val in _OFF:
                    _cached_stage = None
                elif val in STAGES:
                    _cached_stage = val
                else:
                    raise ValueError(
                        f"unknown HVDT_ZERO stage {raw!r}; valid: "
                        f"{', '.join(STAGES)}")
                _cached_env = raw
    return _cached_stage


def enabled() -> bool:
    return stage() is not None


def get_zero() -> Optional["ZeroSpec"]:
    """The env-selected ZeRO spec, or ``None`` when off — the
    zero-wrapper identity handle call sites branch on (``is None`` ⇒
    the pre-existing replicated path, untouched)."""
    st = stage()
    return None if st is None else ZeroSpec(stage=st)


def reset() -> None:
    """Drop the cached stage (test isolation)."""
    global _cached_env, _cached_stage
    with _lock:
        _cached_env = "\0unset"
        _cached_stage = None


def validate_env() -> Optional[str]:
    """Early validation for ``hvd.init()``: parse ``HVDT_ZERO`` NOW so
    an unknown stage fails at init with the valid list."""
    return stage()


def resolve_stage(value=None) -> Optional[str]:
    """Normalize a ``zero=`` keyword: None reads the env; a ZeroSpec
    passes through its stage; strings are validated."""
    if value is None:
        return stage()
    if isinstance(value, ZeroSpec):
        return value.stage
    if value is True:
        st = stage()
        return st if st is not None else "states"
    val = str(value).strip().lower()
    if val in _OFF:
        return None
    if val not in STAGES:
        raise ValueError(
            f"unknown ZeRO stage {value!r}; valid: {', '.join(STAGES)}")
    return val


def exchange_fn() -> Callable:
    """The bucketed gradient-exchange callable with ZeRO routing on top
    of the overlap routing: ``HVDT_ZERO`` at ``grads`` or beyond →
    :func:`rs_exchange` (reduce-scatter + invariant allgather split);
    off/unset → ``overlap.exchange_fn()``'s result — ``fused_allreduce``
    ITSELF when overlap is also off (identity-tested)."""
    return ovl.exchange_fn() if stage() is None else rs_exchange


# ---------------------------------------------------------------------------
# Spec / plan
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class ZeroSpec:
    """Construction-time ZeRO configuration.

    ``num_shards``: the shard count the state layout is built for; None
    resolves at ``init`` time (bound mesh axis → its size, else the
    initialized framework mesh, else ``jax.device_count()``).  Restoring
    a checkpoint onto a different mesh goes through
    :func:`reshard_state`."""

    stage: str = "states"
    axis: Any = "dp"
    num_shards: Optional[int] = None
    threshold_bytes: Optional[int] = None

    def __post_init__(self):
        if self.stage not in STAGES or self.stage == "off":
            raise ValueError(
                f"ZeroSpec stage must be one of {STAGES[1:]}, "
                f"got {self.stage!r}")


@dataclasses.dataclass(frozen=True)
class _Plan:
    """Deterministic bucket plan + shard geometry, fixed at init so the
    state layout never moves under autotune threshold changes."""

    buckets: Tuple[Tuple[int, ...], ...]   # leaf indices, reverse-topo
    sizes: Tuple[int, ...]                 # logical flat elems per bucket
    shard_lens: Tuple[int, ...]            # aligned elems per shard
    dtypes: Tuple[Any, ...]                # bucket dtype
    leaf_shapes: Tuple[Tuple[int, ...], ...]
    leaf_sizes: Tuple[int, ...]
    num_shards: int
    threshold_bytes: int

    @property
    def padded_sizes(self) -> Tuple[int, ...]:
        return tuple(sl * self.num_shards for sl in self.shard_lens)

    def state_bytes_total(self, n_buffers: int = 1) -> int:
        """Bytes of ``n_buffers`` moment stacks over the whole plan."""
        return n_buffers * sum(
            ps * jnp.dtype(dt).itemsize
            for ps, dt in zip(self.padded_sizes, self.dtypes))

    def state_bytes_per_rank(self, n_buffers: int = 1) -> int:
        return self.state_bytes_total(n_buffers) // self.num_shards


def _make_plan(leaves: Sequence[Any], threshold_bytes: Optional[int],
               num_shards: int) -> _Plan:
    threshold_bytes = dev._validated_threshold(threshold_bytes)
    buckets = ovl.overlap_schedule(leaves, threshold_bytes)
    align = shard_align()
    sizes, shard_lens, dtypes = [], [], []
    for bucket in buckets:
        size = sum(int(leaves[i].size) for i in bucket)
        sizes.append(size)
        shard_lens.append(-(-size // (num_shards * align)) * align)
        dtypes.append(jnp.result_type(leaves[bucket[0]]))
    return _Plan(
        buckets=tuple(tuple(b) for b in buckets),
        sizes=tuple(sizes), shard_lens=tuple(shard_lens),
        dtypes=tuple(dtypes),
        leaf_shapes=tuple(tuple(int(s) for s in l.shape) for l in leaves),
        leaf_sizes=tuple(int(l.size) for l in leaves),
        num_shards=int(num_shards),
        threshold_bytes=int(threshold_bytes))


def _resolve_num_shards(spec: ZeroSpec) -> int:
    if spec.num_shards is not None:
        return int(spec.num_shards)
    axes = ((spec.axis,) if isinstance(spec.axis, str)
            else tuple(spec.axis))
    try:
        n = 1
        for a in axes:
            n *= dev._axis_size_static(a)
        return n                       # init ran inside the shard_map
    except Exception:
        pass
    from ..common import basics

    if basics.is_initialized():
        try:
            shape = dict(basics.mesh().shape)
            n = 1
            for a in axes:
                n *= int(shape.get(a, 1))
            if n > 1:
                return n
        except Exception:
            pass
    return max(1, jax.device_count())


# ---------------------------------------------------------------------------
# Axis helpers: reduce-scatter order, owner index, allgather order
# ---------------------------------------------------------------------------


def _axes_tuple(axis) -> Tuple[str, ...]:
    return (axis,) if isinstance(axis, str) else tuple(axis)


# The flat RS/AG primitives live in the data-plane module
# (ops/device.py): reduce_scatter_flat, allgather_flat_shards,
# shard_owner_index — aliased here for the update machinery below.
_rs_order = dev._rs_hop_order
_reduce_scatter_flat = dev.reduce_scatter_flat
_allgather_flat = dev.allgather_flat_shards
_owner_index = dev.shard_owner_index


def _group_size(axis) -> int:
    n = 1
    for a in _axes_tuple(axis):
        n *= dev._axis_size_static(a)
    return n


# ---------------------------------------------------------------------------
# Telemetry (trace-time, path=jit convention)
# ---------------------------------------------------------------------------


def _record_bucket(op: str, axis_label: str, dtype, wire: str,
                   nbytes: int, name: str, count: int = 1) -> None:
    from ..telemetry import flight_recorder as _frm
    from ..telemetry import instrument as _ti

    rec = _ti.get_recorder()
    if rec is not None:
        rec.record_collective(op, jnp.dtype(dtype).name, wire, int(nbytes),
                              count=count, path="jit", axis=axis_label)
    flight = _frm.get_flight_recorder()
    if flight is not None:
        flight.record(op=op, name=name, dtype=jnp.dtype(dtype).name,
                      shape=(int(nbytes),), nbytes=int(nbytes), wire=wire,
                      path="jit", count=count, axis=axis_label)


def record_state_gauges(spec_bytes_per_rank: int,
                        zero_stage: str) -> None:
    """Feed the per-rank post-sharding optimizer-state accounting into
    the telemetry memory gauges (no-op with telemetry off)."""
    from ..telemetry.step_stats import record_memory_accounting

    record_memory_accounting(optimizer_state_bytes=spec_bytes_per_rank,
                             zero_stage=zero_stage)


# ---------------------------------------------------------------------------
# The exchange: per-bucket reduce-scatter (+ deferred allgather), with
# the overlap payload-token chain and the transport/quant wire seams
# ---------------------------------------------------------------------------


def _quant_slow_axis(axis, wire_dtype):
    """``(axis, leg)`` for the single axis whose shard exchange rides a
    block-scaled quantized wire ("int8" / "int4"): an explicit
    ``Compression.int8``/``.int4`` on a flat group, or the transport
    policy's quantized slow tier on a hierarchical group; ``None``
    otherwise."""
    from ..quant.collectives import quant_wire_leg

    axes = _axes_tuple(axis)
    leg = quant_wire_leg(wire_dtype)
    if leg is not None and len(axes) == 1:
        return axes[0], leg
    from ..transport import policy as _tpolicy

    res = _tpolicy.resolve_axis(axis)
    if (res is not None and res.kind == "hierarchical"
            and res.slow is not None
            and quant_wire_leg(res.slow.wire) is not None
            and len(res.slow_axes) == 1):
        return res.slow_axes[0], quant_wire_leg(res.slow.wire)
    return None


def _int8_slow_axis(axis, wire_dtype) -> Optional[str]:
    """Back-compat shim: the axis half of :func:`_quant_slow_axis`."""
    hit = _quant_slow_axis(axis, wire_dtype)
    return None if hit is None else hit[0]


def _cast_wire(axis, wire_dtype):
    """Exact wire cast for the reduce-scatter hops (bf16/fp16 — the
    established cast-around-the-collective compression; the transport
    policy's fast wire applies when the caller passed none)."""
    if isinstance(wire_dtype, str):
        wire_dtype = {"bfloat16": jnp.bfloat16,
                      "float16": jnp.float16}.get(wire_dtype)
    if wire_dtype is not None:
        return wire_dtype
    from ..transport import policy as _tpolicy

    res = _tpolicy.resolve_axis(axis)
    if res is not None:
        return {"bf16": jnp.bfloat16, "fp16": jnp.float16}.get(
            res.fast.wire)
    return None


@dataclasses.dataclass
class _InflightShard:
    """One bucket's reduce-scatter in flight: the fast-tier shard (and,
    on an int8 slow wire, the quantized slow hop) issued, the
    dequant-accumulate / final division not yet run — the seam the
    overlap chain pins under the next bucket's flight window."""

    shard: Optional[Any]
    quant_state: Optional[Any]
    slow_axis: Optional[str]
    dtype: Any


def _rs_start(flat, axis, wire_dtype, float_bucket) -> _InflightShard:
    dtype = flat.dtype
    hit = _quant_slow_axis(axis, wire_dtype) if float_bucket else None
    cast_to = _cast_wire(axis, wire_dtype) if float_bucket else None
    x = flat
    if cast_to is not None and x.dtype != cast_to:
        x = x.astype(cast_to)
    if hit is None:
        return _InflightShard(shard=_reduce_scatter_flat(x, axis),
                              quant_state=None, slow_axis=None,
                              dtype=dtype)
    slow, leg = hit
    from ..quant.collectives import quantized_reduce_scatter_start

    axes = _axes_tuple(axis)
    fast_axes = tuple(a for a in _rs_order(axes) if a != slow)
    shard = x
    for a in fast_axes:
        shard = lax.psum_scatter(shard, a, tiled=True)
    qs = quantized_reduce_scatter_start(shard.astype(jnp.float32), slow,
                                        wire=leg)
    return _InflightShard(shard=None, quant_state=qs, slow_axis=slow,
                          dtype=dtype)


def _rs_finish(inflight: _InflightShard):
    if inflight.quant_state is None:
        shard = inflight.shard
    else:
        from ..quant.collectives import quantized_reduce_scatter_finish

        shard = quantized_reduce_scatter_finish(inflight.quant_state)
    if shard.dtype != inflight.dtype:
        shard = shard.astype(inflight.dtype)
    return shard


def _pin_inflight_shard(inflight: _InflightShard, pin) -> _InflightShard:
    if pin is None:
        return inflight
    out = dataclasses.replace(inflight)
    if inflight.quant_state is not None:
        qs = inflight.quant_state
        q2, s2, _ = lax.optimization_barrier((qs.q_recv, qs.s_recv, pin))
        out.quant_state = dataclasses.replace(qs, q_recv=q2, s_recv=s2)
    else:
        shard2, _ = lax.optimization_barrier((inflight.shard, pin))
        out.shard = shard2
    return out


def _exchange_buckets(leaves, plan: _Plan, axis, op: ReduceOp,
                      prescale_factor, postscale_factor, wire_dtype,
                      shard_finish: Callable, varying=None,
                      rs_wire: bool = True):
    """Drive the per-bucket reduce-scatter schedule.

    ``shard_finish(bi, g_shard, pin)`` receives bucket ``bi``'s reduced,
    already averaged/postscaled flat shard (padded to ``shard_lens[bi]``)
    and returns whatever the caller assembles (updated deltas, the
    allgathered gradient, ...).  With the overlap scheduler live
    (``HVDT_OVERLAP=on``) buckets are issued in reverse-topological
    order under the payload-token chain and each finish is pinned under
    the next bucket's flight window; otherwise the same program traces
    sequentially with no barriers.  Returns ``[shard_finish results]``
    in bucket order.

    ``rs_wire=False`` is the autotuner's *replicated-exchange* leg: the
    bucket rides a full allreduce and each rank slices its own shard —
    identical reduced values and the SAME sharded state layout (that is
    the one-state-tree hot-swap contract of HVDT_AUTOTUNE_ZERO), just a
    different wire pattern.  The int8/hierarchical wire seams only
    apply on the reduce-scatter leg.
    """
    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(f"ZeRO exchange supports SUM/AVERAGE, got {op}")
    n = _group_size(axis)
    if n != plan.num_shards:
        raise ValueError(
            f"ZeRO state was built for {plan.num_shards} shards but the "
            f"bound reduce group {_axes_tuple(axis)} has size {n}; "
            f"reshard the state (checkpoint.restore_zero_state) or "
            f"rebuild the transform with num_shards={n}")
    pipelined = ovl.get_scheduler() is not None
    _axis_label = "+".join(_axes_tuple(axis))

    issued: List[Tuple[int, _InflightShard, Any]] = []
    bucket_bytes: List[int] = []
    token = None
    for bi, bucket in enumerate(plan.buckets):
        parts = []
        for i in bucket:
            g = leaves[i]
            if varying is not None and not varying[i]:
                # Unvarying leaf (modern AD pre-summed the cotangent of a
                # replicated param): pre-scale by 1/n so the redundant
                # cross-rank sum of n identical copies lands back on the
                # gradient-aware value (exact for power-of-2 n).
                g = g * (1.0 / n)
            parts.append(jnp.ravel(g))
        flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
        if prescale_factor != 1.0:
            flat = flat * jnp.asarray(prescale_factor, flat.dtype)
        pad = plan.padded_sizes[bi] - plan.sizes[bi]
        if pad:
            flat = jnp.concatenate(
                [flat, jnp.zeros((pad,), flat.dtype)])
        float_bucket = jnp.issubdtype(flat.dtype, jnp.floating)
        if pipelined and token is not None:
            flat, _ = lax.optimization_barrier((flat, token))
        if pipelined:
            token = ovl._payload_token(flat)
        nbytes = int(flat.size) * jnp.dtype(flat.dtype).itemsize
        # Ring accounting: a reduce-scatter moves (n-1)/n of the payload.
        bucket_bytes.append(nbytes * (n - 1) // max(1, n))
        from ..quant.collectives import wire_sentinel as _sentinel

        _qhit = _quant_slow_axis(axis, wire_dtype) if float_bucket else None
        _record_bucket("reduce_scatter", _axis_label, flat.dtype,
                       (_sentinel(_qhit[1]) if _qhit is not None
                        else jnp.dtype(flat.dtype).name),
                       bucket_bytes[-1], name=f"zero.b{bi}",
                       count=len(bucket))
        with jax.named_scope(f"hvdt.zero.b{bi}.rs"):
            if not rs_wire:
                # Replicated-exchange A/B leg: full allreduce, slice
                # own shard — same values, same state layout.
                full = lax.psum(flat, _axes_tuple(axis))
                own = _owner_index(axis)
                shard = lax.dynamic_slice_in_dim(
                    full, own * plan.shard_lens[bi],
                    plan.shard_lens[bi])
                inflight = _InflightShard(shard=shard, quant_state=None,
                                          slow_axis=None,
                                          dtype=flat.dtype)
            elif float_bucket:
                inflight = _rs_start(flat, axis, wire_dtype, True)
            else:
                inflight = _InflightShard(
                    shard=_reduce_scatter_flat(flat, axis),
                    quant_state=None, slow_axis=None, dtype=flat.dtype)
        issued.append((bi, inflight, flat))

    if pipelined:
        ovl._account(bucket_bytes, wire="zero_reduce_scatter")

    out: List[Any] = [None] * len(plan.buckets)
    for k, (bi, inflight, _payload) in enumerate(issued):
        pin = (ovl._payload_token(issued[k + 1][2])
               if pipelined and k + 1 < len(issued) else None)
        inflight = _pin_inflight_shard(inflight, pin)
        with jax.named_scope(f"hvdt.zero.b{bi}.finish"):
            g_shard = _rs_finish(inflight)
            if op == ReduceOp.AVERAGE:
                g_shard = g_shard / n
            if postscale_factor != 1.0:
                g_shard = g_shard * jnp.asarray(postscale_factor,
                                                g_shard.dtype)
            # AVERAGE promotes integer buckets to float — cast back to
            # the bucket dtype like fused_allreduce does.
            if g_shard.dtype != plan.dtypes[bi]:
                g_shard = g_shard.astype(plan.dtypes[bi])
            out[bi] = shard_finish(bi, g_shard, pin)
    return out


def _split_bucket(flat, plan: _Plan, bi: int):
    """Slice one bucket's reassembled flat vector back into its leaves;
    returns {leaf_index: array}."""
    cells: Dict[int, Any] = {}
    offset = 0
    for i in plan.buckets[bi]:
        sz = plan.leaf_sizes[i]
        cells[i] = lax.dynamic_slice_in_dim(flat, offset, sz).reshape(
            plan.leaf_shapes[i])
        offset += sz
    return cells


def rs_exchange(tree, axis="dp", op: ReduceOp = ReduceOp.AVERAGE,
                threshold_bytes: Optional[int] = None,
                prescale_factor: float = 1.0,
                postscale_factor: float = 1.0,
                wire_dtype: Optional[Any] = None):
    """Drop-in for ``fused_allreduce`` over the reduce-scatter wire: per
    reverse-topological bucket, reduce-scatter then invariant allgather
    (``HVDT_ZERO=grads`` — the explicit RS/AG split whose allgather the
    deeper stages defer or drop).  Bitwise-identical to the fused psum
    for exact wires; the int8 wire keeps the established block-scale
    bound.  Valid inside shard_map where ``axis`` is bound."""
    from ..transport import policy as _tpolicy

    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return tree
    threshold_bytes = dev._validated_threshold(
        _tpolicy.bucket_threshold(axis, threshold_bytes))
    n = _group_size(axis)
    plan = _make_plan(leaves, threshold_bytes, n)
    _axis_label = "+".join(_axes_tuple(axis))

    def finish(bi, g_shard, pin):
        nbytes = (int(g_shard.size) * n
                  * jnp.dtype(g_shard.dtype).itemsize)
        _record_bucket("allgather", _axis_label, g_shard.dtype,
                       jnp.dtype(g_shard.dtype).name,
                       nbytes * (n - 1) // max(1, n),
                       name=f"zero.b{bi}.ag")
        with jax.named_scope(f"hvdt.zero.b{bi}.ag"):
            full = _allgather_flat(g_shard, axis)
        return _split_bucket(full, plan, bi)

    results = _exchange_buckets(leaves, plan, axis, op, prescale_factor,
                                postscale_factor, wire_dtype, finish)
    cells: List[Any] = [None] * len(leaves)
    for d in results:
        for i, v in d.items():
            cells[i] = v
    return jax.tree.unflatten(treedef, cells)


# ---------------------------------------------------------------------------
# State containers
# ---------------------------------------------------------------------------


class ZeroAdamState(NamedTuple):
    """Sharded Adam state: per-bucket ``[num_shards, shard_len]`` moment
    stacks (``[1, shard_len]`` rows inside a ``P(axis)`` shard_map
    crossing)."""

    count: jax.Array
    mu: Tuple[jax.Array, ...]
    nu: Tuple[jax.Array, ...]


class ZeroSgdState(NamedTuple):
    """Sharded SGD-momentum state (empty ``trace`` without momentum)."""

    trace: Tuple[jax.Array, ...]


class ZeroTransformation(NamedTuple):
    """optax-duck-typed transformation (``init``/``update``) plus the
    ZeRO-specific handles: param shard/gather for the ``params`` stage,
    ``full_state`` to materialize the equivalent replicated optax state
    (checkpoint interop / parity tests), and the resolved spec/plan
    accessors."""

    init: Callable
    update: Callable
    shard_params: Callable
    gather_params: Callable
    full_state: Callable
    spec: ZeroSpec
    plan_for: Callable            # params -> _Plan (deterministic)
    state_bytes_per_rank: Callable


# ---------------------------------------------------------------------------
# Mode detection + shard plumbing
# ---------------------------------------------------------------------------


def _mode(spec_axis, n: int, stacked_leading: Optional[int]) -> str:
    from ..optimizer import _axis_bound

    if not _axis_bound(spec_axis):
        return "unbound"
    if stacked_leading == 1 and n > 1:
        return "manual"
    return "replicated"


def _own_row(stacked, mode: str, owner, n: int):
    """This rank's ``[shard_len]`` row of a ``[n|1, shard_len]`` stack
    (or the full flattened stack in unbound mode)."""
    if mode == "unbound":
        return stacked.reshape(-1)
    if mode == "manual":
        return stacked[0]
    row = lax.dynamic_slice_in_dim(stacked, owner, 1, axis=0)
    return row.reshape(-1)


def _emit_row(row, mode: str, owner, n: int, axis):
    """Re-emit an updated row in the input stack's crossing mode:
    manual → ``[1, L]`` (exits through ``P(axis)``); replicated →
    zero-embed + psum back to the replicated ``[n, L]`` stack (disjoint
    embeds, the invariant-reassembly idiom); unbound → ``[n, L]``
    reshape."""
    if mode == "unbound":
        return row.reshape(n, -1)
    if mode == "manual":
        return row[None]
    stack = jnp.zeros((n, row.shape[0]), row.dtype)
    stack = lax.dynamic_update_slice_in_dim(stack, row[None], owner,
                                            axis=0)
    return lax.psum(stack, _axes_tuple(axis))


def _bucket_flat(leaves, plan: _Plan, bi: int, dtype=None):
    """Concatenate + pad one bucket's leaves to the padded size."""
    parts = [jnp.ravel(leaves[i]) for i in plan.buckets[bi]]
    flat = jnp.concatenate(parts) if len(parts) > 1 else parts[0]
    pad = plan.padded_sizes[bi] - plan.sizes[bi]
    if pad:
        flat = jnp.concatenate([flat, jnp.zeros((pad,), flat.dtype)])
    return flat if dtype is None else flat.astype(dtype)


# ---------------------------------------------------------------------------
# The fused-update transform (stages "states" and "params")
# ---------------------------------------------------------------------------


def zero_transform(optim_spec: Dict[str, Any], *, stage: str = "states",
                   axis="dp", op: ReduceOp = ReduceOp.AVERAGE,
                   num_shards: Optional[int] = None,
                   threshold_bytes: Optional[int] = None,
                   wire_dtype: Optional[Any] = None,
                   prescale_factor: float = 1.0,
                   postscale_factor: float = 1.0,
                   use_kernels: Optional[bool] = None,
                   rs_wire: bool = True) -> ZeroTransformation:
    """Build the ZeRO-sharded comm+update transformation for a known
    optimizer family.

    ``optim_spec``: ``{"kind": "sgd", "learning_rate", "momentum",
    "nesterov"}`` or ``{"kind": "adam", "learning_rate", "b1", "b2",
    "eps", "eps_root", "weight_decay"}`` — what ``fused_sgd`` /
    ``fused_adam`` tag onto their update fns (``_hvdt_optim_spec``), so
    ``DistributedOptimizer(hvd.fused_adam(...), zero="states")`` routes
    here without the caller restating hyperparameters.  The update math
    is the single-HBM-pass ``adam_leaf_update``/``sgd_leaf_update`` on
    flat bucket shards — elementwise, hence bitwise-equal to the
    replicated per-leaf update.
    """
    import optax

    kind = optim_spec.get("kind")
    if kind not in ("sgd", "adam"):
        raise ValueError(
            f"ZeRO sharded update supports the fused sgd/adam family, "
            f"got optimizer kind {kind!r}; build the optimizer with "
            f"hvd.fused_sgd(...) / hvd.fused_adam(...) (or use "
            f"HVDT_ZERO=grads, which composes with any optax chain)")
    if stage not in ("states", "params"):
        raise ValueError(
            f"zero_transform implements stages 'states'/'params', got "
            f"{stage!r} (use rs_exchange / DistributedOptimizer for "
            f"'grads')")
    if use_kernels is None:
        use_kernels = bool(optim_spec.get("use_kernels", True))
    momentum = float(optim_spec.get("momentum", 0.0) or 0.0)
    nesterov = bool(optim_spec.get("nesterov", False))
    lr = optim_spec.get("learning_rate")
    if kind == "sgd" and callable(lr):
        raise ValueError("zero sgd takes a float learning_rate "
                         "(TraceState carries no step count); use the "
                         "adam family for schedule support")

    spec = ZeroSpec(stage=stage, axis=axis, num_shards=num_shards,
                    threshold_bytes=threshold_bytes)
    plan_cache: Dict[Any, _Plan] = {}

    def plan_for(params) -> _Plan:
        leaves, treedef = jax.tree.flatten(params)
        key = (treedef,
               tuple((tuple(int(s) for s in l.shape),
                      str(jnp.result_type(l))) for l in leaves))
        plan = plan_cache.get(key)
        if plan is None:
            n = (spec.num_shards if spec.num_shards is not None
                 else _resolve_num_shards(spec))
            plan = _make_plan(leaves, spec.threshold_bytes, n)
            plan_cache[key] = plan
        return plan

    n_buffers = (2 if kind == "adam" else (1 if momentum else 0))

    def init_fn(params):
        plan = plan_for(params)
        n = plan.num_shards

        def stacks(dtype_sel=None):
            return tuple(
                jnp.zeros((n, sl),
                          dtype_sel(dt) if dtype_sel else dt)
                for sl, dt in zip(plan.shard_lens, plan.dtypes))

        record_state_gauges(plan.state_bytes_per_rank(n_buffers), stage)
        if kind == "adam":
            return ZeroAdamState(count=jnp.zeros([], jnp.int32),
                                 mu=stacks(), nu=stacks())
        if momentum:
            return ZeroSgdState(trace=stacks())
        return ZeroSgdState(trace=())

    def shard_params(params):
        """Full replicated tree → per-bucket ``[n, shard_len]`` flat
        shard stacks (the between-steps layout of the ``params``
        stage).  Host/trace-agnostic: pure reshape, no collective."""
        plan = plan_for(params)
        leaves = jax.tree.flatten(params)[0]
        return tuple(
            _bucket_flat(leaves, plan, bi).reshape(plan.num_shards, -1)
            for bi in range(len(plan.buckets)))

    def gather_params(pshards, template):
        """Materialize the full parameter tree from shard stacks — the
        on-demand allgather.  Inside a shard_map with ``[1, L]`` rows
        this is the invariant allgather over ``axis``; with the full
        stack present (replicated / unbound / GSPMD-auto) it is a free
        reshape, and under GSPMD with the stacks NamedSharding'd over
        ``AXIS_FSDP`` XLA inserts the per-layer allgathers on demand
        (parallel/sharding.fsdp_shardings)."""
        plan = plan_for(template)
        leaves, treedef = jax.tree.flatten(template)
        cells: List[Any] = [None] * len(leaves)
        for bi, stack in enumerate(pshards):
            if stack.ndim != 2:
                raise ValueError("param shards must be [n|1, shard_len]")
            if stack.shape[0] == 1 and plan.num_shards > 1:
                full = _allgather_flat(stack[0], axis)
            else:
                full = stack.reshape(-1)
            for i, v in _split_bucket(full, plan, bi).items():
                cells[i] = v.astype(jnp.result_type(leaves[i]))
        return jax.tree.unflatten(treedef, cells)

    def full_state(state, template):
        """The equivalent replicated optax state
        (``ScaleByAdamState``/``TraceState``) — parity tests and
        checkpoint interop.  Requires the full stacks (unbound /
        replicated layout)."""
        plan = plan_for(template)
        leaves, treedef = jax.tree.flatten(template)

        def unstack(stacks):
            cells: List[Any] = [None] * len(leaves)
            for bi, stack in enumerate(stacks):
                full = stack.reshape(-1)
                for i, v in _split_bucket(full, plan, bi).items():
                    cells[i] = v
            return jax.tree.unflatten(treedef, cells)

        if kind == "adam":
            return optax.ScaleByAdamState(count=state.count,
                                          mu=unstack(state.mu),
                                          nu=unstack(state.nu))
        if momentum:
            return optax.TraceState(trace=unstack(state.trace))
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        from .optim_kernels import adam_leaf_update, sgd_leaf_update

        leaves, treedef = jax.tree.flatten(updates)
        plan = plan_for(updates)
        n = plan.num_shards
        from ..optimizer import _axis_bound

        bound = _axis_bound(axis)
        if bound:
            live = _group_size(axis)
            if live != n:
                raise ValueError(
                    f"ZeRO state built for {n} shards; bound reduce "
                    f"group {_axes_tuple(axis)} has size {live}")
        if stage == "params":
            if params is None:
                raise ValueError(
                    "stage='params' updates need the param shard stacks: "
                    "update(grads, state, params=pshards)")
            pshards = tuple(params)
        else:
            pshards = None
            p_leaves = (jax.tree.flatten(params)[0]
                        if params is not None else None)
        moments = (state.mu if kind == "adam"
                   else (state.trace if momentum else ()))
        stacked = moments[0] if moments else (
            pshards[0] if pshards else None)
        leading = int(stacked.shape[0]) if stacked is not None else None
        mode = _mode(axis, n, leading) if bound else "unbound"
        owner = (_owner_index(axis)
                 if (bound and mode == "replicated") else None)

        if kind == "adam":
            count_inc = optax.safe_int32_increment(state.count)
            t = count_inc.astype(jnp.float32)
            lr_t = lr(state.count) if callable(lr) else lr
            b1 = float(optim_spec.get("b1", 0.9))
            b2 = float(optim_spec.get("b2", 0.999))
            scalars = jnp.stack([
                jnp.asarray(lr_t, jnp.float32),
                1.0 / (1.0 - jnp.power(b1, t)),
                1.0 / (1.0 - jnp.power(b2, t))]).astype(jnp.float32)
            wd = float(optim_spec.get("weight_decay", 0.0) or 0.0)
            if wd and params is None:
                raise ValueError(
                    "zero adam with weight_decay requires params: call "
                    "update(grads, state, params)")
        else:
            scalars = jnp.stack([jnp.asarray(lr, jnp.float32)])
            wd = 0.0

        def p_shard_for(bi):
            if stage == "params":
                return _own_row(pshards[bi], mode, owner, n)
            if p_leaves is None:
                return None
            flat = _bucket_flat(p_leaves, plan, bi,
                                dtype=plan.dtypes[bi])
            if mode == "unbound":
                return flat
            off = (owner if owner is not None else _owner_index(axis))
            return lax.dynamic_slice_in_dim(
                flat, off * plan.shard_lens[bi], plan.shard_lens[bi])

        new_m: List[Any] = [None] * len(plan.buckets)
        new_v: List[Any] = [None] * len(plan.buckets)
        deltas: List[Any] = [None] * len(plan.buckets)
        varying = ([dev.is_varying(l, axis) for l in leaves]
                   if bound else None)

        def shard_finish(bi, g_shard, pin):
            aux = []
            if kind == "adam":
                aux = [_own_row(state.mu[bi], mode, owner, n),
                       _own_row(state.nu[bi], mode, owner, n)]
            elif momentum:
                aux = [_own_row(state.trace[bi], mode, owner, n)]
            p_sh = p_shard_for(bi) if (wd or stage == "params") \
                else None
            if pin is not None and aux:
                # Pallas latency-hiding leg: this bucket's shard update
                # is scheduled under the NEXT bucket's flight window —
                # inputs barriered with its payload, never its result.
                pinned = lax.optimization_barrier(
                    tuple([g_shard] + aux) + (pin,))
                g_shard, aux = pinned[0], list(pinned[1:-1])
            if kind == "adam":
                # Without weight decay the param operand is dtype-only
                # (never read) — pass the grad shard instead of
                # allocating a placeholder.
                p_in = p_sh if p_sh is not None else g_shard
                d, m2, v2 = adam_leaf_update(
                    p_in, g_shard, aux[0], aux[1], scalars,
                    b1=float(optim_spec.get("b1", 0.9)),
                    b2=float(optim_spec.get("b2", 0.999)),
                    eps=float(optim_spec.get("eps", 1e-8)),
                    eps_root=float(optim_spec.get("eps_root", 0.0)),
                    weight_decay=wd, use_kernels=use_kernels)
                new_m[bi] = _emit_row(m2, mode, owner, n, axis)
                new_v[bi] = _emit_row(v2, mode, owner, n, axis)
            elif momentum:
                d, m2 = sgd_leaf_update(
                    g_shard, aux[0], scalars, momentum=momentum,
                    nesterov=nesterov, use_kernels=use_kernels)
                new_m[bi] = _emit_row(m2, mode, owner, n, axis)
            else:
                d = (-scalars[0]
                     * g_shard.astype(jnp.float32)).astype(
                         g_shard.dtype)
            if stage == "params":
                # Deltas stay in shard layout; no per-step allgather
                # (forward materializes on demand).
                return _emit_row(d, mode, owner, n, axis)
            if mode == "unbound":
                return _split_bucket(d, plan, bi)
            nbytes = int(d.size) * n * jnp.dtype(d.dtype).itemsize
            _record_bucket("allgather", "+".join(_axes_tuple(axis)),
                           d.dtype, jnp.dtype(d.dtype).name,
                           nbytes * (n - 1) // max(1, n),
                           name=f"zero.b{bi}.ag")
            with jax.named_scope(f"hvdt.zero.b{bi}.ag"):
                full = _allgather_flat(d, axis)
            return _split_bucket(full, plan, bi)

        if mode == "unbound":
            # No bound mesh axis: gradients are already global; run the
            # identical elementwise update over the whole stack.
            for bi in range(len(plan.buckets)):
                flat = _bucket_flat(leaves, plan, bi)
                deltas[bi] = shard_finish(bi, flat, None)
        else:
            results = _exchange_buckets(
                leaves, plan, axis, op, prescale_factor,
                postscale_factor, wire_dtype, shard_finish,
                varying=varying, rs_wire=rs_wire)
            for bi, r in enumerate(results):
                deltas[bi] = r

        if kind == "adam":
            new_state = ZeroAdamState(count=count_inc, mu=tuple(new_m),
                                      nu=tuple(new_v))
        elif momentum:
            new_state = ZeroSgdState(trace=tuple(new_m))
        else:
            new_state = state
        if stage == "params":
            return tuple(deltas), new_state
        cells: List[Any] = [None] * len(leaves)
        for d in deltas:
            for i, v in d.items():
                cells[i] = v.astype(jnp.result_type(leaves[i]))
        return jax.tree.unflatten(treedef, cells), new_state

    def state_bytes_per_rank(params) -> int:
        return plan_for(params).state_bytes_per_rank(n_buffers)

    return ZeroTransformation(
        init=init_fn, update=update_fn, shard_params=shard_params,
        gather_params=gather_params, full_state=full_state, spec=spec,
        plan_for=plan_for, state_bytes_per_rank=state_bytes_per_rank)


def zero_sgd(learning_rate, momentum: float = 0.0,
             nesterov: bool = False, **kw) -> ZeroTransformation:
    """Sugar: :func:`zero_transform` for the SGD-momentum family."""
    return zero_transform(
        {"kind": "sgd", "learning_rate": learning_rate,
         "momentum": momentum, "nesterov": nesterov}, **kw)


def zero_adam(learning_rate, b1: float = 0.9, b2: float = 0.999,
              eps: float = 1e-8, eps_root: float = 0.0,
              weight_decay: float = 0.0, **kw) -> ZeroTransformation:
    """Sugar: :func:`zero_transform` for the Adam/AdamW family."""
    return zero_transform(
        {"kind": "adam", "learning_rate": learning_rate, "b1": b1,
         "b2": b2, "eps": eps, "eps_root": eps_root,
         "weight_decay": weight_decay}, **kw)


def zero_from_optimizer(optimizer, *, stage: str, axis="dp",
                        op: ReduceOp = ReduceOp.AVERAGE,
                        num_shards: Optional[int] = None,
                        threshold_bytes: Optional[int] = None,
                        wire_dtype: Optional[Any] = None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0,
                        rs_wire: bool = True) -> ZeroTransformation:
    """Route a tagged optimizer (``hvd.fused_adam``/``hvd.fused_sgd``)
    through :func:`zero_transform` — the ``DistributedOptimizer(...,
    zero=...)`` dispatch."""
    spec = getattr(getattr(optimizer, "update", None),
                   "_hvdt_optim_spec", None)
    if spec is None:
        raise ValueError(
            "HVDT_ZERO stages 'states'/'params' shard the optimizer "
            "update itself, so the optimizer's math must be known: "
            "build it with hvd.fused_adam(...) / hvd.fused_sgd(...) "
            "(stage 'grads' composes with any optax chain)")
    return zero_transform(
        dict(spec), stage=stage, axis=axis, op=op, num_shards=num_shards,
        threshold_bytes=threshold_bytes, wire_dtype=wire_dtype,
        prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, rs_wire=rs_wire)


# ---------------------------------------------------------------------------
# Checkpoint metadata + mesh-size resharding (the shard/gather-fn pattern)
# ---------------------------------------------------------------------------


def state_metadata(tx: ZeroTransformation, params) -> Dict[str, Any]:
    """JSON-serializable layout descriptor saved next to a sharded
    checkpoint so restore can rebuild (and re-shard) the state without
    the original transform."""
    plan = tx.plan_for(params)
    return {
        "zero_stage": tx.spec.stage,
        "num_shards": plan.num_shards,
        "threshold_bytes": plan.threshold_bytes,
        "align": shard_align(),
        "buckets": [
            {"size": int(s), "shard_len": int(sl), "dtype": str(dt)}
            for s, sl, dt in zip(plan.sizes, plan.shard_lens,
                                 plan.dtypes)],
    }


def extract_shard_rows(state, shard_index: int) -> Dict[str, Any]:
    """One rank's rows of every ``[n, shard_len]`` bucket stack, as
    host numpy — the peer-replication payload (resilience/peer_store.py):
    in the flat layout a peer copy of rank ``s`` is exactly row ``s`` of
    each stack, one allgather slice, not a full-state clone.  Keys
    follow the ``save_zero_state`` naming (``mu_0``, ``nu_0``,
    ``trace_0``, ... plus ``count`` for Adam)."""
    import numpy as np

    s = int(shard_index)
    rows: Dict[str, Any] = {}
    if hasattr(state, "mu"):
        rows["count"] = np.asarray(state.count)
        stacks = [("mu", state.mu), ("nu", state.nu)]
    else:
        stacks = [("trace", state.trace)]
    for name, bufs in stacks:
        for bi, stack in enumerate(bufs):
            rows[f"{name}_{bi}"] = np.asarray(stack[s])
    return rows


def implant_shard_rows(state, shard_index: int, rows: Dict[str, Any]):
    """Inverse of :func:`extract_shard_rows`: a new state with row
    ``shard_index`` of every bucket stack replaced by the replicated
    rows (host-side; the caller re-places on device as usual)."""
    import numpy as np

    s = int(shard_index)

    def patch(stacks, name):
        out = []
        for bi, stack in enumerate(stacks):
            arr = np.asarray(stack).copy()
            arr[s] = np.asarray(rows[f"{name}_{bi}"])
            out.append(jnp.asarray(arr))
        return tuple(out)

    if hasattr(state, "mu"):
        count = state.count
        if "count" in rows:
            count = jnp.asarray(np.asarray(rows["count"]))
        return ZeroAdamState(count=count,
                             mu=patch(state.mu, "mu"),
                             nu=patch(state.nu, "nu"))
    return ZeroSgdState(trace=patch(state.trace, "trace"))


def _reshard_stack(stack, logical_size: int, new_n: int, align: int):
    """[n_old, L_old] → [n_new, L_new]: concatenate, truncate the
    alignment padding, re-pad for the new shard count."""
    import numpy as np

    flat = np.asarray(stack).reshape(-1)[:logical_size]
    new_len = -(-logical_size // (new_n * align)) * align
    out = np.zeros((new_n * new_len,), flat.dtype)
    out[:logical_size] = flat
    return out.reshape(new_n, new_len)


def reshard_state(state, meta: Dict[str, Any], new_num_shards: int):
    """Re-shard a saved ZeRO state onto a different mesh size (host-side
    numpy; the restore half of roadmap item 5's acceptance bar).
    Returns ``(new_state, new_meta)``."""
    align = int(meta.get("align", 256))
    sizes = [int(b["size"]) for b in meta["buckets"]]

    def reshard_all(stacks):
        return tuple(
            jnp.asarray(_reshard_stack(s, sz, new_num_shards, align))
            for s, sz in zip(stacks, sizes))

    if isinstance(state, ZeroAdamState) or hasattr(state, "mu"):
        new_state = ZeroAdamState(count=jnp.asarray(state.count),
                                  mu=reshard_all(state.mu),
                                  nu=reshard_all(state.nu))
    else:
        new_state = ZeroSgdState(trace=reshard_all(state.trace))
    new_meta = dict(meta)
    new_meta["num_shards"] = int(new_num_shards)
    new_meta["buckets"] = [
        {"size": sz,
         "shard_len": -(-sz // (new_num_shards * align)) * align,
         "dtype": b["dtype"]}
        for sz, b in zip(sizes, meta["buckets"])]
    return new_state, new_meta


# ---------------------------------------------------------------------------
# Layout-change restore (4D mesh): the shard/gather-fn generalization of
# reshard_state.  reshard_state only changes the shard COUNT of an
# unchanged bucketization; a parallelism-layout change — merging
# pipeline-stage checkpoints into one data-parallel state, or splitting
# a flat state back onto pipeline stages — also changes the BUCKET
# boundaries (each stage buckets only its own parameters).  Both
# directions factor through the same invariant: strip every bucket's
# alignment padding, concatenate in bucket order, and the result is the
# *global logical vector* in deterministic parameter order.  Any target
# layout whose parameter order matches (stage-major — stage 0's
# parameters before stage 1's, the order ``plan_for`` walks a stacked
# param tree in) is then a pure re-split of that vector.
# ---------------------------------------------------------------------------


def _state_buffers(state):
    """``[(name, stacks)]`` for either state flavour (mu/nu or trace)."""
    if hasattr(state, "mu"):
        return [("mu", state.mu), ("nu", state.nu)]
    return [("trace", state.trace)]


def flatten_state_buffers(state, meta: Dict[str, Any]):
    """``{buffer_name: global logical vector}`` (host numpy): every
    ``[n, shard_len]`` bucket stack stripped of alignment padding and
    concatenated in bucket order."""
    import numpy as np

    sizes = [int(b["size"]) for b in meta["buckets"]]
    out = {}
    for name, stacks in _state_buffers(state):
        out[name] = np.concatenate(
            [np.asarray(s).reshape(-1)[:sz]
             for s, sz in zip(stacks, sizes)]) if stacks else \
            np.zeros((0,), np.float32)
    return out


def _split_logical(flat, buckets, num_shards: int):
    """Re-split one global logical vector into ``[num_shards,
    shard_len]`` stacks per the target bucket list."""
    import numpy as np

    stacks = []
    off = 0
    for b in buckets:
        sz, sl = int(b["size"]), int(b["shard_len"])
        chunk = flat[off:off + sz]
        off += sz
        padded = np.zeros((num_shards * sl,), chunk.dtype)
        padded[:sz] = chunk
        stacks.append(jnp.asarray(padded.reshape(num_shards, sl)))
    if off != flat.size:
        raise ValueError(
            f"target buckets cover {off} elements but the saved state "
            f"holds {flat.size} — the layouts describe different "
            "parameter sets")
    return tuple(stacks)


def rebucket_state(state, meta: Dict[str, Any],
                   new_meta: Dict[str, Any]):
    """Re-lay a saved ZeRO state onto a DIFFERENT bucketization and/or
    shard count (same total logical size) via the global flat vector.
    Returns the new state; ``new_meta`` (``state_metadata`` of the
    target transform) is authoritative for the result layout."""
    flats = flatten_state_buffers(state, meta)
    n = int(new_meta["num_shards"])
    buckets = new_meta["buckets"]
    if hasattr(state, "mu"):
        return ZeroAdamState(
            count=jnp.asarray(state.count),
            mu=_split_logical(flats["mu"], buckets, n),
            nu=_split_logical(flats["nu"], buckets, n))
    return ZeroSgdState(trace=_split_logical(flats["trace"], buckets, n))


def concat_states(states, metas):
    """Concatenate per-pipeline-stage ZeRO states (stage-major order)
    into one combined ``(state, meta)`` whose bucket list is the stage
    bucket lists in order.  All stages must agree on shard count,
    alignment and state flavour; the combined meta carries
    ``layout={"pp": n_stages, "dp": num_shards}``."""
    if not states:
        raise ValueError("concat_states needs at least one state")
    first = metas[0]
    for m in metas[1:]:
        if int(m["num_shards"]) != int(first["num_shards"]):
            raise ValueError("stage checkpoints disagree on num_shards")
        if int(m.get("align", 256)) != int(first.get("align", 256)):
            raise ValueError("stage checkpoints disagree on alignment")
    kinds = {hasattr(s, "mu") for s in states}
    if len(kinds) != 1:
        raise ValueError("stage checkpoints mix Adam and SGD states")
    buffers = {}
    for name, _ in _state_buffers(states[0]):
        buffers[name] = tuple(
            stack for st in states
            for stack in dict(_state_buffers(st))[name])
    if hasattr(states[0], "mu"):
        state = ZeroAdamState(count=jnp.asarray(states[0].count),
                              mu=buffers["mu"], nu=buffers["nu"])
    else:
        state = ZeroSgdState(trace=buffers["trace"])
    meta = dict(first)
    meta["buckets"] = [dict(b) for m in metas for b in m["buckets"]]
    meta["layout"] = {"pp": len(states), "dp": int(first["num_shards"])}
    return state, meta
