"""Adasum: scale-invariant gradient combination.

TPU-native re-conception of the reference's Adasum
(ref: ops/adasum/adasum.h — recursive vector-halving distance-doubling
with dot-product-based scale mixing; ops/adasum_mpi_operations.cc,
ops/adasum_gpu_operations.cc; docs/adasum_user_guide.rst).

The Adasum combination of two gradients a, b is::

    adasum(a, b) = (1 - (a·b)/(2·a·a)) · a  +  (1 - (a·b)/(2·b·b)) · b

which reduces to the sum for orthogonal gradients and to (a+b)/2 for
parallel ones, making the result robust to learning-rate scaling across
ranks.  Across N = 2^k ranks it is applied recursively in a binary tree
(ref: adasum.h:33 requires power-of-2 ranks).

Two implementations:

* ``adasum_allreduce`` — jit/shard_map path: one ``all_gather`` of the
  flattened per-rank vectors, then every rank evaluates the identical
  binary combination tree locally (the tree is unrolled at trace time —
  rank count is static under jit).  Correctness-first: memory/bandwidth is
  O(N·G) per device versus the reference's recursive-halving O(G); a
  reduce-scattered formulation (combination tree on 1/N shards with
  psum'd scalar dots per level, mirroring the bandwidth shape of
  nccl_operations.cc:249-517) is the planned optimization once profiled.
* ``host_adasum`` — eager-path version over host arrays.
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

__all__ = ["adasum_allreduce", "host_adasum", "adasum_pair"]


def adasum_pair(a, b, dot_ab, dot_aa, dot_bb):
    """One Adasum combination given precomputed dots (works for np/jnp)."""
    eps = np.finfo(np.float32).tiny
    scale_a = 1.0 - dot_ab / (2.0 * (dot_aa + eps))
    scale_b = 1.0 - dot_ab / (2.0 * (dot_bb + eps))
    return scale_a * a + scale_b * b


def _np_adasum_tree(vectors: List[np.ndarray]) -> np.ndarray:
    """Reference-semantics binary-tree Adasum over a list of rank vectors."""
    vecs = [v.astype(np.float64) for v in vectors]
    n = len(vecs)
    if n & (n - 1):
        raise ValueError(f"Adasum requires a power-of-2 rank count, got {n}")
    while len(vecs) > 1:
        nxt = []
        for i in range(0, len(vecs), 2):
            a, b = vecs[i], vecs[i + 1]
            nxt.append(adasum_pair(a, b, float(a @ b), float(a @ a),
                                   float(b @ b)))
        vecs = nxt
    return vecs[0]


def host_adasum(flat: np.ndarray, process_set) -> np.ndarray:
    """Eager-path Adasum across the processes of ``process_set``.

    Correctness-first: allgather the flattened gradients, then every rank
    computes the identical tree reduction locally (deterministic).  The
    bandwidth-optimal path is the jit-side ``adasum_allreduce``."""
    from . import host_collectives as hostc
    from . import tcp_backend

    p = process_set.size()
    if p == 1:
        return flat
    if tcp_backend.enabled() and not (p & (p - 1)):
        # Native VHDD (native/src/adasum.cc) — bandwidth shape of the
        # reference's recursive halving, O(G) wire bytes per rank.
        return tcp_backend.tcp_adasum(np.ascontiguousarray(flat),
                                      process_set)
    orig_dtype = flat.dtype
    stacked = hostc.host_allgather(flat[None, :], process_set,
                                   [1] * p)  # (p, n)
    out = _np_adasum_tree([stacked[i] for i in range(p)])
    return out.astype(orig_dtype)


def adasum_allreduce(x, axis: str = "dp"):
    """Adasum allreduce inside shard_map/jit over a mesh axis.

    Gathers per-rank vectors along the axis (bf16-safe: combination math in
    f32), then runs the same binary tree as the host path, unrolled (axis
    size is static under jit).  See the module docstring for the
    memory/bandwidth caveat vs. the reference's recursive halving.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    def _one(t):
        n = lax.axis_size(axis)
        if n & (n - 1):
            raise ValueError(f"Adasum requires power-of-2 ranks, got {n}")
        orig_shape = t.shape
        orig_dtype = t.dtype
        flat = t.reshape(-1).astype(jnp.float32)
        # (n, len) on every rank
        gathered = lax.all_gather(flat, axis)
        vecs = [gathered[i] for i in range(n)]
        while len(vecs) > 1:
            nxt = []
            for i in range(0, len(vecs), 2):
                a, b = vecs[i], vecs[i + 1]
                nxt.append(adasum_pair(a, b, jnp.vdot(a, b), jnp.vdot(a, a),
                                       jnp.vdot(b, b)))
            vecs = nxt
        # Every rank computed the identical tree from the same gathered
        # data, but VMA typing still marks it varying; pmean is a numeric
        # identity here and restores the invariant type so downstream
        # out_specs=P() replication checks pass.
        out = lax.pmean(vecs[0], axis)
        return out.reshape(orig_shape).astype(orig_dtype)

    return jax.tree.map(_one, x)
