"""Adasum: scale-invariant gradient combination.

TPU-native re-conception of the reference's Adasum
(ref: ops/adasum/adasum.h — recursive vector-halving distance-doubling
with dot-product-based scale mixing; ops/adasum_mpi_operations.cc,
ops/adasum_gpu_operations.cc; docs/adasum_user_guide.rst).

The Adasum combination of two gradients a, b is::

    adasum(a, b) = (1 - (a·b)/(2·a·a)) · a  +  (1 - (a·b)/(2·b·b)) · b

which reduces to the sum for orthogonal gradients and to (a+b)/2 for
parallel ones, making the result robust to learning-rate scaling across
ranks.  Across N = 2^k ranks it is applied recursively in a binary tree
(ref: adasum.h:33 requires power-of-2 ranks).

Two implementations:

* ``adasum_allreduce`` — jit/shard_map path, sharded formulation:
  all_to_all distributes shard s of every rank's vector to rank s, the
  binary combination tree runs on 1/N shards with exact full-vector dots
  via one batched psum per level, and a psum-embed reassembles — O(G)
  wire and memory per rank, the bandwidth shape of the reference's
  recursive halving (nccl_operations.cc:249-517).
* ``host_adasum`` — eager-path version over host arrays (native C++ VHDD
  when the TCP backend is active).
"""

from __future__ import annotations

from typing import Any, List

import numpy as np

__all__ = ["adasum_allreduce", "host_adasum", "adasum_pair"]


def adasum_pair(a, b, dot_ab, dot_aa, dot_bb):
    """One Adasum combination given precomputed dots (works for np/jnp)."""
    eps = np.finfo(np.float32).tiny
    scale_a = 1.0 - dot_ab / (2.0 * (dot_aa + eps))
    scale_b = 1.0 - dot_ab / (2.0 * (dot_bb + eps))
    return scale_a * a + scale_b * b


def _np_adasum_tree(vectors: List[np.ndarray]) -> np.ndarray:
    """Reference-semantics binary-tree Adasum over a list of rank vectors."""
    vecs = [v.astype(np.float64) for v in vectors]
    n = len(vecs)
    if n & (n - 1):
        raise ValueError(f"Adasum requires a power-of-2 rank count, got {n}")
    while len(vecs) > 1:
        nxt = []
        for i in range(0, len(vecs), 2):
            a, b = vecs[i], vecs[i + 1]
            nxt.append(adasum_pair(a, b, float(a @ b), float(a @ a),
                                   float(b @ b)))
        vecs = nxt
    return vecs[0]


def host_adasum(flat: np.ndarray, process_set) -> np.ndarray:
    """Eager-path Adasum across the processes of ``process_set``.

    Correctness-first: allgather the flattened gradients, then every rank
    computes the identical tree reduction locally (deterministic).  The
    bandwidth-optimal path is the jit-side ``adasum_allreduce``."""
    from . import host_collectives as hostc
    from . import tcp_backend

    p = process_set.size()
    if p == 1:
        return flat
    if tcp_backend.enabled() and not (p & (p - 1)):
        # Native VHDD (native/src/adasum.cc) — bandwidth shape of the
        # reference's recursive halving, O(G) wire bytes per rank.
        return tcp_backend.tcp_adasum(np.ascontiguousarray(flat),
                                      process_set)
    orig_dtype = flat.dtype
    stacked = hostc.host_allgather(flat[None, :], process_set,
                                   [1] * p)  # (p, n)
    out = _np_adasum_tree([stacked[i] for i in range(p)])
    return out.astype(orig_dtype)


def adasum_allreduce(x, axis: str = "dp"):
    """Adasum allreduce inside shard_map/jit over a mesh axis — the
    sharded (reduce-scatter-shaped) formulation.

    Mirrors the bandwidth shape of the reference's recursive halving
    (ref: adasum.h FusedAllreduce; AdasumGpuAllreduceOp = local
    reduce-scatter → cross Adasum → local all-gather):

    1. all_to_all the flattened vector so rank s holds shard s of EVERY
       rank's gradient — O(G) wire, O(G) memory per rank (the previous
       all-gather formulation was O(p·G) both).
    2. run the binary combination tree on the local shards; the
       dot-products per pair are computed exactly as psums of per-shard
       partials (one batched psum per tree level, 3 scalars per pair).
    3. reassemble by zero-embedding each combined shard and psum-ing —
       one collective that both gathers and restores the VMA-invariant
       type (device.invariant_allgather_shards).

    bf16-safe: combination math in f32.
    """
    import jax
    import jax.numpy as jnp
    from jax import lax

    from .device import invariant_allgather_shards

    def _one(t):
        n = _axis_size_static(axis)
        if n & (n - 1):
            raise ValueError(f"Adasum requires power-of-2 ranks, got {n}")
        orig_shape = t.shape
        orig_dtype = t.dtype
        flat = t.reshape(-1).astype(jnp.float32)
        if n == 1:
            return flat.reshape(orig_shape).astype(orig_dtype)
        pad = (-flat.size) % n
        if pad:
            flat = jnp.concatenate([flat, jnp.zeros(pad, jnp.float32)])
        chunk = flat.size // n
        # rows after a2a: row j = rank j's values on MY shard's index range
        rows = lax.all_to_all(flat.reshape(n, chunk), axis, split_axis=0,
                              concat_axis=0, tiled=False)
        vecs = [rows[j] for j in range(n)]
        while len(vecs) > 1:
            pairs = [(vecs[i], vecs[i + 1]) for i in range(0, len(vecs), 2)]
            # exact full-vector dots: psum of per-shard partials, batched
            # into one collective per tree level
            partial = jnp.stack([
                jnp.stack([jnp.vdot(a, b), jnp.vdot(a, a), jnp.vdot(b, b)])
                for a, b in pairs])                       # [pairs, 3]
            dots = lax.psum(partial, axis)
            vecs = [adasum_pair(a, b, dots[k, 0], dots[k, 1], dots[k, 2])
                    for k, (a, b) in enumerate(pairs)]
        full = invariant_allgather_shards(vecs[0], axis)
        if pad:
            full = full[:-pad]
        return full.reshape(orig_shape).astype(orig_dtype)

    return jax.tree.map(_one, x)
