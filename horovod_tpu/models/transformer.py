"""Flagship decoder-only Transformer LM, built for the 5-axis mesh.

TPU-first design decisions:

* **bf16 compute, f32 params/accumulation** — MXU-native (SURVEY.md §6's
  per-chip throughput target is set by MXU utilization).  With
  ``HVDT_FP8=matmul`` the MLP and attention projections drop to
  per-tensor-scaled e4m3 operands (quant/fp8.py) where the backend
  supports the fp8 convert-dot; accumulation stays f32.
* **RoPE** instead of learned positions — no position table to shard.
* **Scan over layers** — one compiled block body regardless of depth
  (compile time O(1) in layers), standard XLA practice.
* **Hybrid parallelism**: dp/fsdp/tp are expressed with logical-axis
  sharding rules (GSPMD auto-partitioning inserts the collectives); sp
  (ring attention) and ep (MoE alltoall) are manual ``shard_map`` islands;
  pp wraps the block stack in ``pipeline_spmd``.

The reference has no model layer — its examples lean on torchvision/Keras
(ref: examples/pytorch/pytorch_synthetic_benchmark.py:17-26).  This module
is the equivalent benchmark substrate plus the TP/SP/PP/EP showcase the
reference lacks (SURVEY.md §2.7).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
import numpy as np
from jax import lax

from ..parallel.moe import moe_dispatch_combine
from ..parallel.pipeline import pipeline_spmd
from ..parallel.ring_attention import ring_attention
from ..quant import fp8 as _fp8

__all__ = [
    "TransformerConfig", "transformer_init", "transformer_apply",
    "transformer_loss", "transformer_logical_axes",
    "transformer_flops_per_token", "remat_from_env", "checkpoint_policy",
    "transformer_decode_paged", "transformer_prefill_paged",
    "transformer_prefill_collect",
]


@dataclasses.dataclass(frozen=True)
class TransformerConfig:
    vocab: int = 32000
    layers: int = 4
    d_model: int = 512
    heads: int = 8
    kv_heads: int = 8            # < heads ⇒ GQA
    d_ff: int = 2048
    max_seq: int = 2048
    rope_theta: float = 10000.0
    dtype: Any = jnp.bfloat16    # activation/compute dtype
    param_dtype: Any = jnp.float32
    # MoE: num_experts == 0 ⇒ dense MLP.  Every block is MoE when on
    # (simplest uniform scan body; interleaving is a config refinement).
    num_experts: int = 0
    capacity_factor: float = 1.25
    # Parallel degrees the *model code* must know about (mesh axes the
    # forward pass opens manual islands for); dp/fsdp/tp stay automatic.
    sp: int = 1                  # sequence-parallel degree (ring attention)
    ep: int = 1                  # expert-parallel degree
    pp: int = 1                  # pipeline stages (layers % pp == 0)
    remat: bool = False          # jax.checkpoint each block
    # Rematerialization policy when remat=True:
    #   "full" — save only block inputs, recompute everything (min HBM,
    #            +1/3 FLOPs — the classic trade);
    #   "dots" — jax.checkpoint_policies.dots_with_no_batch_dims_saveable:
    #            save non-batched matmul outputs (projections, FF), so
    #            the backward recomputes only cheap elementwise work and
    #            attention scores.  ~MXU-free recompute at the cost of
    #            O(layers * 6*b*l*d + b*l*4d) extra HBM residency.
    # (An "attn" policy saving each block's attention output was measured
    # and REMOVED: saving attention's output cannot skip recomputing its
    # internals — the VJP still needs q/k/v/scores — so it bought 1.3%
    # of grad FLOPs for ~3.2 GB extra residency and OOM'd the BERT-Large
    # bs128 config.)
    remat_policy: str = "full"
    loss_chunk: int = 0          # >0: chunked-vocab cross entropy

    @property
    def head_dim(self) -> int:
        return self.d_model // self.heads

    @property
    def layers_per_stage(self) -> int:
        assert self.layers % max(self.pp, 1) == 0
        return self.layers // max(self.pp, 1)


def _init_linear(key, fan_in, shape, dtype):
    return (jax.random.normal(key, shape) * (fan_in ** -0.5)).astype(dtype)


def transformer_init(key: jax.Array, cfg: TransformerConfig) -> Dict:
    """Parameter pytree. Block params are stacked [layers, ...] for scan;
    under pp they are reshaped to [pp, layers_per_stage, ...] at apply time
    (same memory layout, stage-major)."""
    keys = jax.random.split(key, 8)
    d, h, hk, dh, f = (cfg.d_model, cfg.heads, cfg.kv_heads, cfg.head_dim,
                       cfg.d_ff)
    L = cfg.layers
    pd = cfg.param_dtype

    def stack(initfn, subkey):
        return jnp.stack([initfn(k) for k in jax.random.split(subkey, L)])

    block = {
        "ln1": jnp.ones((L, d), pd),
        "ln2": jnp.ones((L, d), pd),
        "wq": stack(lambda k: _init_linear(k, d, (d, h * dh), pd), keys[1]),
        "wk": stack(lambda k: _init_linear(k, d, (d, hk * dh), pd), keys[2]),
        "wv": stack(lambda k: _init_linear(k, d, (d, hk * dh), pd), keys[3]),
        "wo": stack(lambda k: _init_linear(k, h * dh, (h * dh, d), pd),
                    keys[4]),
    }
    if cfg.num_experts:
        e = cfg.num_experts
        block["w_router"] = stack(
            lambda k: _init_linear(k, d, (d, e), pd), keys[5])
        block["w_up"] = stack(
            lambda k: _init_linear(k, d, (e, d, f), pd), keys[6])
        block["w_down"] = stack(
            lambda k: _init_linear(k, f, (e, f, d), pd), keys[7])
    else:
        block["w_up"] = stack(lambda k: _init_linear(k, d, (d, f), pd),
                              keys[5])
        block["w_gate"] = stack(lambda k: _init_linear(k, d, (d, f), pd),
                                keys[6])
        block["w_down"] = stack(lambda k: _init_linear(k, f, (f, d), pd),
                                keys[7])
    return {
        "embed": (jax.random.normal(keys[0], (cfg.vocab, d)) * 0.02
                  ).astype(pd),
        "ln_f": jnp.ones((d,), pd),
        "block": block,
    }


def transformer_logical_axes(cfg: TransformerConfig) -> Dict:
    """Same-structure pytree of logical axis names (None = replicated dim)
    for ``parallel.sharding.logical_to_mesh``. Leading stacked-layers dim
    maps to "stages" so pp shards it when the mesh has a pp axis."""
    block = {
        "ln1": ("stages", None),
        "ln2": ("stages", None),
        "wq": ("stages", "embed", "heads"),
        "wk": ("stages", "embed", "kv"),
        "wv": ("stages", "embed", "kv"),
        "wo": ("stages", "heads", "embed"),
    }
    if cfg.num_experts:
        block["w_router"] = ("stages", "embed", None)
        block["w_up"] = ("stages", "experts", "embed", "mlp")
        block["w_down"] = ("stages", "experts", "mlp", "embed")
    else:
        block["w_up"] = ("stages", "embed", "mlp")
        block["w_gate"] = ("stages", "embed", "mlp")
        block["w_down"] = ("stages", "mlp", "embed")
    return {"embed": ("vocab", "embed"), "ln_f": (None,), "block": block}


def _rmsnorm(x, g):
    var = jnp.mean(jnp.square(x.astype(jnp.float32)), -1, keepdims=True)
    return (x.astype(jnp.float32) * jax.lax.rsqrt(var + 1e-6)).astype(
        x.dtype) * g.astype(x.dtype)


def _rope(x, positions, theta):
    """x: [B, L, H, D]; positions: [B, L] global token positions."""
    d2 = x.shape[-1] // 2
    freqs = (1.0 / theta) ** (jnp.arange(d2, dtype=jnp.float32) / d2)
    ang = positions[..., None].astype(jnp.float32) * freqs   # [B, L, d2]
    cos, sin = jnp.cos(ang)[..., None, :], jnp.sin(ang)[..., None, :]
    x1, x2 = x[..., :d2], x[..., d2:]
    return jnp.concatenate(
        [x1 * cos - x2 * sin, x1 * sin + x2 * cos], -1).astype(x.dtype)


def _proj(x, w):
    """Dense projection ``x @ w`` in the activation dtype — rides the
    per-tensor-scaled fp8 (e4m3) convert-dot when ``HVDT_FP8=matmul``
    and the backend supports it (quant/fp8.py); otherwise exactly the
    plain matmul.  The gate is resolved at trace time from env config,
    so flipping HVDT_FP8 recompiles rather than branching in-graph."""
    if _fp8.matmul_enabled():
        return _fp8.fp8_matmul(x, w)
    return x @ w.astype(x.dtype)


def _qkv(p, x, positions, cfg: TransformerConfig):
    """Rotated q/k/v projections — the one place the projection + RoPE
    recipe lives, shared by training attention (:func:`_attention`) and
    the serving paged-KV prefill/decode paths, so the cache can never
    hold keys rotated differently from the ones training computed."""
    b, l, _ = x.shape
    h, hk, dh = cfg.heads, cfg.kv_heads, cfg.head_dim
    q = _proj(x, p["wq"]).reshape(b, l, h, dh)
    k = _proj(x, p["wk"]).reshape(b, l, hk, dh)
    v = _proj(x, p["wv"]).reshape(b, l, hk, dh)
    q = _rope(q, positions, cfg.rope_theta)
    k = _rope(k, positions, cfg.rope_theta)
    return q, k, v


def _attention(p, x, positions, cfg: TransformerConfig):
    b, l, d = x.shape
    h, hk, dh = cfg.heads, cfg.kv_heads, cfg.head_dim
    q, k, v = _qkv(p, x, positions, cfg)
    flash_plan = None if cfg.sp > 1 else _flash_plan(b, l, h, hk, dh)
    if cfg.sp > 1:
        # Manual island: the sequence dim is the local sp shard here (the
        # caller's shard_map over {'sp'} has already split it).
        o = ring_attention(q, k, v, axis="sp", causal=True)
    elif flash_plan == "direct":
        # Pallas fused attention on TPU: O(L·D) HBM traffic instead of a
        # materialized [B,H,L,L] score matrix (ops/pallas_kernels.py).
        o = _flash_fn(l, dh, batch=b, heads=h)(q, k, v)
    elif flash_plan is not None:
        # GSPMD-auto mesh: Mosaic kernels can't be auto-partitioned, so
        # open a manual shard_map island over the batch (dp/fsdp) and
        # heads (tp) axes and run the kernel on the local shard — the
        # multi-chip engagement the auto gate alone would refuse (the
        # role of the reference's in-graph custom-call path, ref:
        # tensorflow/xla_mpi_ops.cc:165-235 "collectives/kernels live
        # inside the compiled program").
        from jax.sharding import PartitionSpec as P

        dp_axes, tp_ax, names = flash_plan
        dp_size, tp_size = _island_local_sizes(
            jax.sharding.get_abstract_mesh(), dp_axes, tp_ax)
        fn = _flash_fn(l, dh, batch=max(1, b // dp_size),
                       heads=max(1, h // tp_size))
        spec = P(dp_axes if dp_axes else None, None, tp_ax, None)
        # _flash_plan only emits island plans when the public
        # jax.shard_map exists (jax 0.4.x has neither it nor
        # AxisType-aware abstract meshes).
        shard_map_fn = getattr(jax, "shard_map", None)
        o = shard_map_fn(
            fn, in_specs=(spec, spec, spec), out_specs=spec,
            axis_names=names)(q, k, v)
    else:
        scale = dh ** -0.5
        if h != hk:
            k = jnp.repeat(k, h // hk, axis=2)
            v = jnp.repeat(v, h // hk, axis=2)
        s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                       preferred_element_type=jnp.float32) * scale
        mask = jnp.tril(jnp.ones((l, l), bool))
        s = jnp.where(mask[None, None], s, -1e30)
        w = jax.nn.softmax(s, axis=-1).astype(v.dtype)
        o = jnp.einsum("bhqk,bkhd->bqhd", w, v)
    return _proj(o.reshape(b, l, h * dh), p["wo"])


def _flash_enabled(seq_len: int, head_dim: int, *, batch: int = 1,
                   heads: int = 1) -> bool:
    """Flash kernel policy: HVDT_FLASH_ATTENTION=auto|on|off.

    'auto' (default) engages the kernel on TPU only when the
    materialized-score path would be memory-heavy: the f32 score tensor
    ``batch x heads x L x L`` at or past ~4 GB.  The kernel is a
    CAPACITY play — measured on v5e (BERT-Large, docs/performance.md):
    at seq 512 bs 128 (2.1 GB scores) XLA's fused attention is ~1.5x
    faster than kernel-forward + blockwise backward, while at 4+ GB the
    kernel admits 2x the batch and past ~8 GB XLA attention doesn't fit
    at all.  'on' forces it whenever shapes tile.

    ``batch``/``heads`` are the sizes the kernel will actually see —
    pass LOCAL (per-shard) sizes when the call site shards them."""
    from ..common import config

    mode = config.get_str("HVDT_FLASH_ATTENTION").lower()
    if mode == "off":
        return False
    shapes_ok = seq_len % min(128, seq_len) == 0 and seq_len >= 8
    if mode == "on":
        return shapes_ok
    score_bytes = 4 * batch * heads * seq_len * seq_len
    return (shapes_ok and score_bytes >= 4 * 1024 ** 3
            and jax.devices()[0].platform == "tpu")


def _island_local_sizes(am, dp_axes, tp_ax) -> Tuple[int, int]:
    """(dp_size, tp_size) of an island plan under abstract mesh ``am`` —
    the ONE place this arithmetic lives: _flash_plan gates on the local
    shapes it implies and _attention picks the kernel with the same
    numbers, so they cannot diverge."""
    dp_size = (int(np.prod([am.shape[a] for a in dp_axes]))
               if dp_axes else 1)
    tp_size = am.shape[tp_ax] if tp_ax else 1
    return dp_size, tp_size


# 'auto' engagement threshold for the smallseq kernel: minimum number of
# (batch x head-block) grid programs.  None = auto disengaged: the kernel
# is correctness-proven (CPU interpret suite) but its TPU A/B
# (tools/tpu_ab.py lm_smallseq_* legs) hasn't run — an unmeasured kernel
# must not be a default (round-3 verdict discipline).  Set to the
# measured break-even once the legs land.
_SMALLSEQ_AUTO_MIN_PROGRAMS: Optional[int] = None


def _smallseq_vmem_ok(seq_len: int, head_dim: int, hb: int) -> bool:
    """Whether one (batch, head-block) program's working set fits VMEM.

    Models the BACKWARD kernel (the larger of the two): bf16 q/do/out +
    k/v blocks, f32 dq/dk/dv outputs, plus one head's f32 probability
    and d-score [L, L] scratch pair.  Budget 12 MiB of the ~16 MiB/core
    so Mosaic keeps headroom for pipelining.  Assumes hb_kv == hb (no
    GQA shrink) — conservative: GQA only makes the k/v blocks smaller."""
    bf16_in = 5 * hb * seq_len * head_dim * 2
    f32_out = 3 * hb * seq_len * head_dim * 4
    scratch = 2 * seq_len * seq_len * 4
    return bf16_in + f32_out + scratch <= 12 * 1024 ** 2


def _smallseq_enabled(seq_len: int, head_dim: int, *, batch: int,
                      heads: int) -> bool:
    """Head-batched single-block kernel policy: HVDT_FLASH_SMALLSEQ.

    The complement of :func:`_flash_enabled`'s capacity play — the
    streaming kernel's per-grid-step overhead is ruinous at short
    sequence / large batch*heads (measured 3x WORSE than XLA end-to-end
    at BERT-Large bs128 seq512, tools/ab_results.json
    lm_flash_kernelbwd_bs128), while the profiled XLA path spends
    ~30% of the step materializing scores there.  'auto' engages
    flash_attention_smallseq on TPU when the whole sequence fits one
    VMEM block ('on' honors the same fit — a kernel that cannot lower is
    never a valid choice) and there are at least
    ``_SMALLSEQ_AUTO_MIN_PROGRAMS`` (batch x head-block) grid programs
    to amortize per-program overhead.  ``batch``/``heads`` are LOCAL
    (per-shard) sizes."""
    from ..common import config

    mode = config.get_str("HVDT_FLASH_SMALLSEQ").lower()
    if mode == "off":
        return False
    shapes_ok = seq_len % 128 == 0 and seq_len <= 1024
    if mode == "on":
        # 'on' is the A/B force switch: it must select the kernel for
        # every tiling shape, or a forced leg would silently measure the
        # baseline path.  The VMEM estimate below is a MODEL — only
        # 'auto' trusts it; a genuinely unlowerable block still fails
        # loudly in the kernel's own _fit_block.
        return shapes_ok
    if _SMALLSEQ_AUTO_MIN_PROGRAMS is None:
        return False
    hb = min(config.get_int("HVDT_FLASH_SMALLSEQ_HB"), max(heads, 1))
    programs = batch * max(heads, 1) // max(hb, 1)
    return (shapes_ok and _smallseq_vmem_ok(seq_len, head_dim, hb)
            and programs >= _SMALLSEQ_AUTO_MIN_PROGRAMS
            and jax.devices()[0].platform == "tpu")


def _flash_fn(seq_len: int, head_dim: int, *, batch: int, heads: int):
    """The attention kernel to use for these LOCAL shapes, or None for
    XLA attention.  HVDT_FLASH_ATTENTION=off is the master off switch;
    =on keeps its A/B meaning (force the STREAMING kernel)."""
    from ..common import config
    from ..ops.pallas_kernels import (flash_attention,
                                      flash_attention_smallseq)

    mode = config.get_str("HVDT_FLASH_ATTENTION").lower()
    if mode == "off":
        return None
    if mode != "on" and _smallseq_enabled(seq_len, head_dim, batch=batch,
                                          heads=heads):
        return functools.partial(
            flash_attention_smallseq, causal=True,
            heads_per_block=config.get_int("HVDT_FLASH_SMALLSEQ_HB"))
    if _flash_enabled(seq_len, head_dim, batch=batch, heads=heads):
        return functools.partial(flash_attention, causal=True)
    return None


def _flash_plan(b: int, l: int, h: int, hk: int, dh: int):
    """Decide how the flash kernel can engage under the ambient mesh.

    Returns "direct" (call the kernel as-is: no mesh, or every mesh axis
    already manual here), a ``(dp_axes, tp_axis)`` island plan (the mesh
    has GSPMD-auto axes — run the kernel inside a partial-manual
    shard_map over those axes; Mosaic kernels cannot be auto-partitioned
    by GSPMD), or None (fall back to XLA attention).  The memory policy
    (_flash_enabled) is evaluated on the per-shard shapes the kernel
    would actually see."""
    try:
        am = jax.sharding.get_abstract_mesh()
        auto = ([n for n, t in zip(am.axis_names, am.axis_types)
                 if t == jax.sharding.AxisType.Auto]
                if not am.empty else [])
        manual = ([n for n, t in zip(am.axis_names, am.axis_types)
                   if t == jax.sharding.AxisType.Manual]
                  if not am.empty else [])
    except Exception:       # pragma: no cover - very old jax
        auto, manual = [], []
    if not auto:
        return ("direct"
                if _flash_fn(l, dh, batch=b, heads=h) is not None else None)
    if manual:
        # Already inside a shard_map (e.g. the pp/sp/ep pipeline island)
        # with auto axes remaining: nesting another partial-manual island
        # here fails shardy lowering on the BACKWARD (the residuals'
        # dimension shardings mix manual-after-free axes — verified on
        # jax 0.9: "manual axes must come before free axes").  Fall back
        # to XLA attention; pure-auto meshes (dp/fsdp/tp) still engage.
        return None
    if getattr(jax, "shard_map", None) is None:
        # Island plans need the public partial-manual shard_map API
        # (absent on jax 0.4.x — where AxisType meshes don't exist
        # either, so this is belt-and-braces).
        return None
    # Shard batch over dp-like axes and heads over tp, where divisible.
    dp_axes: Tuple[str, ...] = tuple(a for a in ("dp", "fsdp")
                                     if a in auto)
    while dp_axes and b % _island_local_sizes(am, dp_axes, None)[0]:
        dp_axes = dp_axes[:-1]
    tp_ax = "tp" if "tp" in auto else None
    if tp_ax and (h % am.shape[tp_ax] or hk % am.shape[tp_ax]):
        tp_ax = None
    dp_size, tp_size = _island_local_sizes(am, dp_axes, tp_ax)
    # Any OTHER size>1 auto axis (e.g. an auto axis sharding the
    # sequence) means the island's replicated in_specs would force a
    # full-sequence all-gather per layer — don't engage the kernel there.
    # Size-1 leftovers are included in the island instead: Mosaic refuses
    # to lower while ANY auto axis is ambient, even a trivial one.
    leftover = [a for a in auto if a not in dp_axes and a != tp_ax]
    if any(am.shape[a] > 1 for a in leftover):
        return None
    if _flash_fn(l, dh, batch=max(1, b // dp_size),
                 heads=max(1, h // tp_size)) is None:
        return None
    names = frozenset(dp_axes) | ({tp_ax} if tp_ax else set()) | \
        frozenset(leftover)
    return (dp_axes, tp_ax, names)


def _mlp(p, x):
    up = _proj(x, p["w_up"])
    gate = jax.nn.silu(_proj(x, p["w_gate"]))
    return _proj(up * gate, p["w_down"])


def _moe_mlp(p, x, cfg: TransformerConfig):
    b, l, d = x.shape
    tokens = x.reshape(b * l, d)
    logits = tokens @ p["w_router"].astype(x.dtype)
    w_up, w_down = p["w_up"].astype(x.dtype), p["w_down"].astype(x.dtype)
    if cfg.ep > 1:
        # w_up/w_down enter the island sharded over ep on the expert dim.
        def expert_fn(toks):   # [E_local, N, D]
            hmid = jax.nn.silu(jnp.einsum("end,edf->enf", toks, w_up))
            return jnp.einsum("enf,efd->end", hmid, w_down)
        out, aux = moe_dispatch_combine(
            tokens, logits, expert_fn, axis="ep",
            experts_per_rank=cfg.num_experts // cfg.ep,
            capacity_factor=cfg.capacity_factor)
    else:
        # Dense (einsum-over-experts) fallback: exact, no capacity drops.
        probs = jax.nn.softmax(logits.astype(jnp.float32), -1)
        top = jnp.argmax(probs, -1)
        gate = jnp.take_along_axis(probs, top[:, None], 1)[:, 0]
        hmid = jax.nn.silu(jnp.einsum("nd,edf->enf", tokens, w_up))
        all_out = jnp.einsum("enf,efd->end", hmid, w_down)
        sel = jnp.take_along_axis(
            all_out, top[None, :, None], 0)[0]
        out = sel * gate[:, None].astype(x.dtype)
        aux = None
    return out.reshape(b, l, d), aux


def _dots_policy():
    """The ``dots_with_no_batch_dims_saveable`` checkpoint policy, or
    ``None`` on jax builds that don't ship it (the container's 0.4.37
    has it, but the guard keeps HVDT_REMAT=dots from crashing older/
    newer builds that rename it)."""
    policies = getattr(jax, "checkpoint_policies", None)
    return getattr(policies, "dots_with_no_batch_dims_saveable", None)


_REMAT_MODES = ("none", "full", "dots")


def checkpoint_policy(mode: Optional[str] = None):
    """Resolve an ``HVDT_REMAT`` mode to a ``jax.checkpoint`` wrapper
    argument: ``None`` (no remat), the string sentinel ``"full"`` (plain
    ``jax.checkpoint``), or a policy callable (``dots``).  ``mode=None``
    reads the env knob; unknown modes raise with the valid list; a
    ``dots`` request on a build without the policy degrades to ``full``
    with a warning (never a crash)."""
    from ..common import config
    from ..common.logging_util import get_logger

    if mode is None:
        mode = config.get_str("HVDT_REMAT")
    mode = (mode or "none").strip().lower() or "none"
    if mode not in _REMAT_MODES:
        raise ValueError(
            f"unknown HVDT_REMAT mode {mode!r}; valid: "
            f"{', '.join(_REMAT_MODES)}")
    if mode == "none":
        return None
    if mode == "dots":
        pol = _dots_policy()
        if pol is None:
            get_logger(__name__).warning(
                "HVDT_REMAT=dots requested but this jax build has no "
                "dots_with_no_batch_dims_saveable policy; falling back "
                "to remat='full'")
            return "full"
        return pol
    return "full"


def remat_from_env(cfg: TransformerConfig,
                   mode: Optional[str] = None) -> TransformerConfig:
    """Apply the ``HVDT_REMAT`` knob (``none|full|dots``) to a config —
    the memory-for-MFU trade surfaced as ``bench.py --remat`` /
    ``hvdtrun --remat``.  Returns ``cfg`` unchanged for ``none`` (and
    the ``dots``→``full`` fallback is resolved here so the config names
    the policy that will actually run)."""
    pol = checkpoint_policy(mode)
    if pol is None:
        return dataclasses.replace(cfg, remat=False)
    policy_name = "full" if pol == "full" else "dots"
    return dataclasses.replace(cfg, remat=True, remat_policy=policy_name)


def _block(p, x, positions, cfg: TransformerConfig):
    x = x + _attention(p, _rmsnorm(x, p["ln1"]), positions, cfg)
    if cfg.num_experts:
        y, _ = _moe_mlp(p, _rmsnorm(x, p["ln2"]), cfg)
    else:
        y = _mlp(p, _rmsnorm(x, p["ln2"]))
    return x + y


def _scan_blocks(block_params, x, positions, cfg: TransformerConfig):
    body = functools.partial(_block, positions=positions, cfg=cfg)
    if cfg.remat:
        if cfg.remat_policy == "dots":
            pol = _dots_policy()
            if pol is None:
                # Guarded for jax builds without the named policy
                # (HVDT_REMAT=dots on such a build degrades to 'full'
                # at config time; a hand-built config degrades here).
                from ..common.logging_util import get_logger

                get_logger(__name__).warning(
                    "remat_policy='dots' unavailable on this jax "
                    "build; using 'full'")
                body = jax.checkpoint(body)
            else:
                body = jax.checkpoint(body, policy=pol)
        elif cfg.remat_policy == "full":
            body = jax.checkpoint(body)
        else:
            raise ValueError(
                f"unknown remat_policy {cfg.remat_policy!r} "
                "(expected 'full' or 'dots')")

    def step(h, layer_p):
        return body(layer_p, h), None

    out, _ = lax.scan(step, x, block_params)
    return out


def transformer_hidden(params: Dict, tokens: jax.Array,
                       cfg: TransformerConfig) -> jax.Array:
    """Final-norm hidden states [batch, seq, d_model] (everything but the
    vocab projection — split out so the chunked loss can avoid ever
    materializing [batch, seq, vocab] logits).

    tokens: [batch, seq] int32 — the *local* sp shard of the sequence when
    called inside a shard_map over {'sp'} (positions are globalized with
    the sp rank), the full sequence otherwise.
    """
    b, l = tokens.shape
    if cfg.sp > 1:
        offset = lax.axis_index("sp") * l
    else:
        offset = 0
    positions = offset + jnp.broadcast_to(jnp.arange(l), (b, l))
    x = params["embed"].astype(cfg.dtype)[tokens]
    # Manual-island axes make activations varying (e.g. the MoE alltoall);
    # pre-cast so the scan-over-layers carry is type-stable under vma.
    from ..parallel.sharding import pcast_to_union

    manual_axes = [ax for ax, on in (("sp", cfg.sp > 1),
                                     ("ep", cfg.ep > 1 and cfg.num_experts))
                   if on]
    x = pcast_to_union(x, extra=tuple(manual_axes))
    if cfg.pp > 1:
        # Inside a shard_map over {'pp'} the stacked-layers dim of the
        # block params is the sharded "stages" logical axis, so the local
        # slice is already this rank's [layers_per_stage, ...] stage.
        # Microbatch over batch dim with M = pp (minimum schedule).
        m = cfg.pp
        assert b % m == 0, f"batch {b} not divisible by pp {cfg.pp}"
        mb = b // m
        acts = x.reshape(m, mb, l, cfg.d_model)
        pos_mb = positions.reshape(m, mb, l)

        def stage_fn(stage_p, a):
            # positions are identical across microbatches in this layout
            return _scan_blocks(stage_p, a, pos_mb[0], cfg)

        x = pipeline_spmd(stage_fn, params["block"], acts, axis="pp")
        x = x.reshape(b, l, cfg.d_model)
    else:
        # Block params may still be varying on manual axes the config
        # doesn't know about (e.g. a stages dim spec'd onto a size-1 pp
        # mesh axis); the scan carry must match, so pcast x up to the
        # union of the params' varying axes.
        from ..parallel.sharding import pcast_to_union

        x = pcast_to_union(x, *jax.tree.leaves(params["block"]))
        x = _scan_blocks(params["block"], x, positions, cfg)
    return _rmsnorm(x, params["ln_f"])


def transformer_apply(params: Dict, tokens: jax.Array,
                      cfg: TransformerConfig) -> jax.Array:
    """Logits for next-token prediction (see transformer_hidden)."""
    x = transformer_hidden(params, tokens, cfg)
    return (x @ params["embed"].astype(x.dtype).T).astype(jnp.float32)


def _chunked_xent(x: jax.Array, embed: jax.Array, targets: jax.Array,
                  chunk: int) -> jax.Array:
    """Cross entropy without the [tokens, vocab] logits: scan over vocab
    chunks with an online logsumexp, checkpointed so the backward pass
    recomputes each chunk's logits instead of saving them.  Peak memory
    per step drops from O(tokens x vocab) f32 to O(tokens x chunk) —
    the lever that lets BERT-Large-scale batches fit in HBM (measured:
    dense f32 logits at batch 128 x seq 512 x 30k vocab are 8 GB alone).
    Numerics match the dense path up to fp reassociation."""
    b, t, d = x.shape
    vocab = embed.shape[0]
    n_chunks = -(-vocab // chunk)
    pad = n_chunks * chunk - vocab
    w = embed.astype(x.dtype)
    if pad:
        w = jnp.concatenate([w, jnp.zeros((pad, d), x.dtype)])
    w = w.reshape(n_chunks, chunk, d)
    xf = x.reshape(b * t, d)
    tgt = targets.reshape(b * t)

    def body(carry, wc_ci):
        m, s, tl = carry
        wc, ci = wc_ci
        logits = (xf @ wc.T).astype(jnp.float32)        # [N, chunk]
        base = ci * chunk
        valid = (jnp.arange(chunk) + base) < vocab
        logits = jnp.where(valid[None, :], logits, -jnp.inf)
        m_new = jnp.maximum(m, logits.max(-1))
        s = (s * jnp.exp(m - m_new)
             + jnp.exp(logits - m_new[:, None]).sum(-1))
        in_chunk = (tgt >= base) & (tgt < base + chunk)
        idx = jnp.clip(tgt - base, 0, chunk - 1)
        tl = jnp.where(
            in_chunk,
            jnp.take_along_axis(logits, idx[:, None], 1)[:, 0], tl)
        return (m_new, s, tl), None

    init = (jnp.full((b * t,), -jnp.inf, jnp.float32),
            jnp.zeros((b * t,), jnp.float32),
            jnp.zeros((b * t,), jnp.float32))
    # Inside a shard_map island (sp/pp) the hidden states are varying, so
    # the scan body's outputs are too — the carry init must match the
    # body's output vma or the scan type check rejects it.  jax builds
    # without vma tracking (0.4.x: no jax.typeof/lax.pcast) need no
    # alignment — there is no vma type to mismatch.
    typeof = getattr(jax, "typeof", None)
    vma = (tuple(set(typeof(xf).vma) | set(typeof(tgt).vma))
           if typeof is not None else ())
    if vma:
        init = jax.tree.map(lambda a: lax.pcast(a, vma, to="varying"), init)
    (m, s, tl), _ = lax.scan(jax.checkpoint(body), init,
                             (w, jnp.arange(n_chunks)))
    return (jnp.log(s) + m - tl).mean()


def transformer_loss(params: Dict, tokens: jax.Array,
                     cfg: TransformerConfig) -> jax.Array:
    """Causal LM loss (next-token cross entropy) over the local shard.

    The model runs on the FULL sequence and the last position's
    prediction is dropped — mathematically identical to feeding
    ``tokens[:, :-1]`` (causal attention means position i never sees
    i+1), but it keeps the attention length at the caller's power-of-two
    ``seq`` instead of ``seq - 1``, which is what lets the flash kernel
    (block-divisibility gate) engage on the training path.

    ``cfg.loss_chunk > 0`` switches to the chunked-vocab logsumexp path
    (no [tokens, vocab] logits tensor)."""
    targets = tokens[:, 1:]
    if cfg.loss_chunk:
        x = transformer_hidden(params, tokens, cfg)[:, :-1]
        return _chunked_xent(x, params["embed"], targets, cfg.loss_chunk)
    logits = transformer_apply(params, tokens, cfg)[:, :-1]
    logp = jax.nn.log_softmax(logits, -1)
    ll = jnp.take_along_axis(logp, targets[..., None], -1)[..., 0]
    return -ll.mean()


# ---------------------------------------------------------------------------
# Paged-KV serving paths (serve/llm continuous-batching engine).
#
# The cache layout is ``[layers, num_blocks, block_size, kv_heads,
# head_dim]`` — fixed-size physical blocks indexed per sequence through a
# block table (``serve/llm/kv_cache.py`` owns allocation; this module
# owns the math).  All three entry points have FIXED shapes in every
# argument, so admission/eviction of sequences between iterations can
# never change a jitted program: that is the zero-steady-state-recompile
# contract the static bucket engine pioneered, carried into decode.
#
# Physical block 0 is the write SINK: inactive decode slots and padded
# prefill positions scatter their k/v there, where no block table ever
# points (the allocator never hands block 0 out), so masked lanes stay
# harmless without a single dynamic shape.
# ---------------------------------------------------------------------------


def _masked_softmax_attn(q, keys, vals, mask):
    """Attention with an explicit mask and a clamped denominator.

    q: [B, Lq, H, D]; keys/vals: [B, T, Hkv, D]; mask: [B, Lq, T] bool.
    Fully-masked rows (inactive decode slots, padded prefill lanes)
    return exactly 0 instead of NaN — ``jax.nn.softmax`` over an
    all-masked row is 0/0, and one NaN hidden row would poison every
    *valid* row at the next layer through its scattered k/v."""
    h, hkv = q.shape[2], keys.shape[2]
    if h != hkv:
        keys = jnp.repeat(keys, h // hkv, axis=2)
        vals = jnp.repeat(vals, h // hkv, axis=2)
    scale = q.shape[-1] ** -0.5
    s = jnp.einsum("bqhd,bkhd->bhqk", q, keys,
                   preferred_element_type=jnp.float32) * scale
    m = mask[:, None]                                   # [B, 1, Lq, T]
    s = jnp.where(m, s, -1e30)
    smax = jax.lax.stop_gradient(s.max(axis=-1, keepdims=True))
    p = jnp.where(m, jnp.exp(s - smax), 0.0)
    denom = jnp.maximum(p.sum(axis=-1, keepdims=True), 1e-9)
    w = (p / denom).astype(vals.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", w, vals)


def transformer_decode_paged(params, tokens, block_tables, seq_lens,
                             kc, vc, cfg: TransformerConfig,
                             block_size: int):
    """One continuous-batching decode iteration over the paged cache.

    tokens [S] int32 (each slot's current last token), block_tables
    [S, maxb] int32 physical block ids, seq_lens [S] int32 (tokens in
    the sequence INCLUDING the one decoded now; 0 = inactive slot),
    kc/vc [L, num_blocks, block_size, kv_heads, head_dim].

    Per layer: scatter this token's k/v at position ``seq_len - 1``
    (inactive slots scatter into sink block 0), gather the whole block
    table, attend over key positions ``< seq_len``.  Returns
    ``(next_tokens [S] int32, kc, vc)`` — greedy argmax stays in-graph
    so the host transfer per iteration is S ints, not S×vocab logits.
    """
    s_slots = tokens.shape[0]
    maxb = block_tables.shape[1]
    active = seq_lens > 0
    pos = jnp.maximum(seq_lens - 1, 0)                         # [S]
    x = params["embed"].astype(cfg.dtype)[tokens][:, None, :]  # [S,1,d]
    slot_idx = jnp.arange(s_slots)
    key_pos = jnp.arange(maxb * block_size)
    attn_mask = key_pos[None, :] < seq_lens[:, None]           # [S, T]

    def body(h_carry, layer):
        p, kc_l, vc_l = layer
        hx = _rmsnorm(h_carry, p["ln1"])
        q, k, v = _qkv(p, hx, pos[:, None], cfg)
        blk = jnp.where(active,
                        block_tables[slot_idx, pos // block_size], 0)
        off = pos % block_size
        kc_l = kc_l.at[blk, off].set(k[:, 0].astype(kc_l.dtype))
        vc_l = vc_l.at[blk, off].set(v[:, 0].astype(vc_l.dtype))
        keys = kc_l[block_tables].reshape(
            s_slots, maxb * block_size, *kc_l.shape[2:])
        vals = vc_l[block_tables].reshape(
            s_slots, maxb * block_size, *vc_l.shape[2:])
        o = _masked_softmax_attn(q, keys.astype(cfg.dtype),
                                 vals.astype(cfg.dtype),
                                 attn_mask[:, None, :])
        h_carry = h_carry + _proj(
            o.reshape(s_slots, 1, -1), p["wo"])
        h_carry = h_carry + _mlp(p, _rmsnorm(h_carry, p["ln2"]))
        return h_carry, (kc_l, vc_l)

    x, (kc, vc) = lax.scan(body, x, (params["block"], kc, vc))
    x = _rmsnorm(x, params["ln_f"])
    logits = (x[:, 0] @ params["embed"].astype(x.dtype).T
              ).astype(jnp.float32)
    nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    return nxt, kc, vc


def transformer_prefill_paged(params, tokens, ctx_start, n_valid,
                              block_table, kc, vc,
                              cfg: TransformerConfig, block_size: int):
    """One prefill CHUNK of one sequence into the paged cache.

    tokens [C] int32 (zero-padded past ``n_valid``), ctx_start scalar
    int32 (global position of tokens[0]), n_valid scalar int32,
    block_table [maxb] int32.  Scatters the chunk's k/v at its global
    positions (padded lanes go to sink block 0), then attends each chunk
    query over the WHOLE table — chunk i sees chunks 0..i-1 from the
    cache plus its own just-scattered keys, which is what lets a long
    prompt stream through in fixed-shape chunks without ever stalling
    decode for more than one chunk.  Returns ``(kc, vc)``; the last
    prompt token is deliberately NOT prefilled — it enters through the
    decode step, which produces the first generated token.
    """
    c = tokens.shape[0]
    maxb = block_table.shape[0]
    pos = ctx_start + jnp.arange(c)                            # [C]
    valid = jnp.arange(c) < n_valid
    x = params["embed"].astype(cfg.dtype)[tokens][None]        # [1,C,d]
    key_pos = jnp.arange(maxb * block_size)
    # Causal by global position, bounded by what exists after this
    # chunk scatters; padded queries are fully masked.
    attn_mask = ((key_pos[None, :] <= pos[:, None])
                 & (key_pos[None, :] < ctx_start + n_valid)
                 & valid[:, None])[None]                       # [1,C,T]

    def body(h_carry, layer):
        p, kc_l, vc_l = layer
        hx = _rmsnorm(h_carry, p["ln1"])
        q, k, v = _qkv(p, hx, pos[None], cfg)
        blk = jnp.where(valid, block_table[pos // block_size], 0)
        off = pos % block_size
        kc_l = kc_l.at[blk, off].set(k[0].astype(kc_l.dtype))
        vc_l = vc_l.at[blk, off].set(v[0].astype(vc_l.dtype))
        keys = kc_l[block_table].reshape(
            1, maxb * block_size, *kc_l.shape[2:])
        vals = vc_l[block_table].reshape(
            1, maxb * block_size, *vc_l.shape[2:])
        o = _masked_softmax_attn(q, keys.astype(cfg.dtype),
                                 vals.astype(cfg.dtype), attn_mask)
        h_carry = h_carry + _proj(o.reshape(1, c, -1), p["wo"])
        h_carry = h_carry + _mlp(p, _rmsnorm(h_carry, p["ln2"]))
        return h_carry, (kc_l, vc_l)

    _, (kc, vc) = lax.scan(body, x, (params["block"], kc, vc))
    return kc, vc


def transformer_prefill_collect(params, tokens, cfg: TransformerConfig):
    """Whole-prompt prefill that RETURNS every layer's rotated k/v.

    The long-context prefill path: called inside a ``shard_map`` over
    the ``sp`` axis when ``cfg.sp > 1``, so attention runs as the exact
    :func:`~horovod_tpu.parallel.ring_attention.ring_attention` ring
    while each shard emits its local k/v slab; the caller's out_specs
    reassemble ``[L, B, S, kv_heads, head_dim]`` slabs that the serving
    engine scatters into the paged cache in one shot.  tokens
    [B, S_local] int32.  Returns ``(k_all, v_all)``.
    """
    b, l = tokens.shape
    if cfg.sp > 1:
        offset = lax.axis_index("sp") * l
    else:
        offset = 0
    positions = offset + jnp.broadcast_to(jnp.arange(l), (b, l))
    x = params["embed"].astype(cfg.dtype)[tokens]
    if cfg.sp > 1:
        # Ring transfers make activations varying on sp; the scan carry
        # must be type-stable under vma (transformer_hidden idiom).
        from ..parallel.sharding import pcast_to_union

        x = pcast_to_union(x, extra=("sp",))

    def body(h_carry, p):
        hx = _rmsnorm(h_carry, p["ln1"])
        q, k, v = _qkv(p, hx, positions, cfg)
        if cfg.sp > 1:
            o = ring_attention(q, k, v, axis="sp", causal=True)
        else:
            mask = jnp.tril(jnp.ones((l, l), bool))[None]
            o = _masked_softmax_attn(q, k, v, mask)
        h_carry = h_carry + _proj(o.reshape(b, l, -1), p["wo"])
        h_carry = h_carry + _mlp(p, _rmsnorm(h_carry, p["ln2"]))
        return h_carry, (k, v)

    _, (k_all, v_all) = lax.scan(body, x, params["block"])
    return k_all, v_all


def transformer_flops_per_token(cfg: TransformerConfig) -> float:
    """Approximate forward-pass matmul FLOPs per token (for MFU metrics)."""
    d, f, l = cfg.d_model, cfg.d_ff, cfg.layers
    h, hk, dh = cfg.heads, cfg.kv_heads, cfg.head_dim
    attn_proj = 2 * d * (h * dh + 2 * hk * dh + h * dh)
    attn_scores = 2 * 2 * cfg.max_seq * h * dh          # per token, approx
    mlp = 2 * d * f * (3 if not cfg.num_experts else 2)
    return l * (attn_proj + attn_scores + mlp) + 2 * d * cfg.vocab
