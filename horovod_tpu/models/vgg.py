"""VGG-16, pure-JAX pytree implementation.

The third model of the reference's published benchmark table
(ref: docs/benchmarks.rst:8-14 — 68% scaling efficiency at 512 GPUs,
the hard case: 138M params, most of them in the FC layers, so gradient
traffic dominates).  Provided for the same role here: the
communication-heavy end of the synthetic benchmark/scaling harness
(examples/jax_synthetic_benchmark.py --model vgg16).

TPU-first choices: NHWC, bf16 compute with f32 params, classifier FCs
as big MXU matmuls.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, Tuple

import jax
import jax.numpy as jnp
from jax import lax

__all__ = ["VGGConfig", "vgg16_init", "vgg_apply", "vgg_loss"]

# Configuration D (VGG-16): conv channel per layer, "M" = 2x2 maxpool.
_VGG16 = (64, 64, "M", 128, 128, "M", 256, 256, 256, "M",
          512, 512, 512, "M", 512, 512, 512, "M")


@dataclasses.dataclass(frozen=True)
class VGGConfig:
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    image_size: int = 224


def vgg16_init(key: jax.Array, cfg: VGGConfig) -> Dict:
    pd = cfg.param_dtype
    n_conv = sum(1 for c in _VGG16 if c != "M")
    keys = iter(jax.random.split(key, n_conv + 3))
    params: Dict = {}
    cin = 3
    for i, c in enumerate(_VGG16):
        if c == "M":
            continue
        fan_in = 9 * cin
        params[f"conv{i}"] = {
            "w": (jax.random.normal(next(keys), (3, 3, cin, c))
                  * (2.0 / fan_in) ** 0.5).astype(pd),
            "b": jnp.zeros((c,), pd)}
        cin = c
    spatial = cfg.image_size // 32          # five 2x pools
    flat = spatial * spatial * 512
    for name, (fi, fo) in (("fc1", (flat, 4096)), ("fc2", (4096, 4096)),
                           ("fc3", (4096, cfg.num_classes))):
        params[name] = {
            "w": (jax.random.normal(next(keys), (fi, fo)) * fi ** -0.5
                  ).astype(pd),
            "b": jnp.zeros((fo,), pd)}
    return params


def vgg_apply(params: Dict, images: jax.Array, cfg: VGGConfig) -> jax.Array:
    """images: [N, H, W, 3] -> logits [N, classes]."""
    x = images.astype(cfg.dtype)
    for i, c in enumerate(_VGG16):
        if c == "M":
            x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 2, 2, 1),
                                  (1, 2, 2, 1), "VALID")
            continue
        p = params[f"conv{i}"]
        x = lax.conv_general_dilated(
            x, p["w"].astype(x.dtype), (1, 1), "SAME",
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
        x = jax.nn.relu(x + p["b"].astype(x.dtype))
    x = x.reshape(x.shape[0], -1)
    for name, act in (("fc1", True), ("fc2", True), ("fc3", False)):
        p = params[name]
        x = x @ p["w"].astype(x.dtype) + p["b"].astype(x.dtype)
        if act:
            x = jax.nn.relu(x)
    return x.astype(jnp.float32)


def vgg_loss(params: Dict, images: jax.Array, labels: jax.Array,
             cfg: VGGConfig) -> jax.Array:
    logits = vgg_apply(params, images, cfg)
    logp = jax.nn.log_softmax(logits, -1)
    return -jnp.take_along_axis(logp, labels[:, None], -1).mean()
