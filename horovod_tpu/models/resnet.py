"""ResNet-50 (v1.5), pure-JAX pytree implementation.

The reference benchmarks Horovod with torchvision/Keras ResNet-50
(ref: examples/pytorch/pytorch_synthetic_benchmark.py:17-26,
examples/tensorflow2/tensorflow2_synthetic_benchmark.py; docs/benchmarks.rst
headline numbers — SURVEY.md §6).  This is the equivalent model for this
framework's synthetic benchmark and scaling-efficiency harness (bench.py).

TPU-first choices: NHWC layout (XLA-TPU native), bf16 compute with f32
batch-norm statistics, ``(params, batch_stats)`` as explicit pytrees so
the train step is a pure function.  Cross-replica BN is available via
``horovod_tpu.sync_batch_norm`` semantics: pass ``bn_axis`` to average
batch statistics over the data-parallel mesh axis (the reference's
SyncBatchNorm, ref: torch/sync_batch_norm.py:1-218).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any, Dict, Optional, Tuple

import jax
import jax.ad_checkpoint
import jax.numpy as jnp
import numpy as np
from jax import lax

__all__ = ["ResNetConfig", "resnet50_init", "resnet101_init",
           "resnet_apply", "resnet_loss"]

# Stage layouts: (blocks, mid-channels) per stage.  ResNet-101 is the
# reference's published benchmark model (docs/benchmarks.rst:27-43 —
# 1656.82 img/s over 16 P100s); ResNet-50 is its synthetic-benchmark
# default (examples/pytorch/pytorch_synthetic_benchmark.py:17-26).
_STAGES = {
    # Minimal bottleneck layout (ResNet-26): one block per stage — same
    # stem/BN/downsample plumbing as 50/101 at a fraction of the compile
    # time; used by tests that probe plumbing rather than capacity.
    26: ((1, 64), (1, 128), (1, 256), (1, 512)),
    50: ((3, 64), (4, 128), (6, 256), (3, 512)),
    101: ((3, 64), (4, 128), (23, 256), (3, 512)),
}


@dataclasses.dataclass(frozen=True)
class ResNetConfig:
    num_classes: int = 1000
    dtype: Any = jnp.bfloat16
    param_dtype: Any = jnp.float32
    bn_momentum: float = 0.9
    bn_eps: float = 1e-5
    bn_axis: Optional[str] = None   # mesh axis for cross-replica SyncBN
    # Rematerialization of the per-block BN/relu epilogues: "epilogue"
    # saves ONLY conv outputs for the backward pass and recomputes the
    # (cheap, elementwise) BN+relu from them.  Cuts peak activation
    # memory ~2x for batch scaling, but measured SLOWER on v5e at bs128
    # (2324 vs 2705 img/s — the recompute pass re-reads conv outputs, a
    # net traffic add on an HBM-bound step), so the default is "none".
    remat: str = "none"
    # Stem lowering: "s2d" rewrites the 7x7/2 stem conv as an exactly
    # equivalent space-to-depth(2) + 4x4/1 conv (the MLPerf-TPU stem
    # trick): C_in goes 3 -> 12, quartering the MXU lane padding waste of
    # a 3-channel conv and shrinking the 224x224 input slicing XLA
    # otherwise does.  "conv" keeps the literal 7x7 conv.
    stem: str = "s2d"
    depth: int = 50              # 26, 50 or 101 (bottleneck stage layouts)


def _conv_init(key, kh, kw, cin, cout, dtype):
    fan_in = kh * kw * cin
    return (jax.random.normal(key, (kh, kw, cin, cout))
            * np.sqrt(2.0 / fan_in)).astype(dtype)


def _bn_init(c, dtype):
    return {"scale": jnp.ones((c,), dtype), "bias": jnp.zeros((c,), dtype)}


def _bn_stats(c):
    return {"mean": jnp.zeros((c,), jnp.float32),
            "var": jnp.ones((c,), jnp.float32)}


def resnet50_init(key: jax.Array, cfg: ResNetConfig
                  ) -> Tuple[Dict, Dict]:
    """Returns (params, batch_stats) for the cfg's depth (50 default)."""
    pd = cfg.param_dtype
    stages = _STAGES[cfg.depth]
    n_blocks = sum(b for b, _ in stages)
    keys = iter(jax.random.split(key, 4 + n_blocks * 4))
    params: Dict = {"conv_stem": _conv_init(next(keys), 7, 7, 3, 64, pd),
                    "bn_stem": _bn_init(64, pd)}
    stats: Dict = {"bn_stem": _bn_stats(64)}
    cin = 64
    for si, (blocks, mid) in enumerate(stages):
        cout = mid * 4
        for bi in range(blocks):
            name = f"s{si}b{bi}"
            p = {
                "conv1": _conv_init(next(keys), 1, 1, cin, mid, pd),
                "bn1": _bn_init(mid, pd),
                "conv2": _conv_init(next(keys), 3, 3, mid, mid, pd),
                "bn2": _bn_init(mid, pd),
                "conv3": _conv_init(next(keys), 1, 1, mid, cout, pd),
                "bn3": _bn_init(cout, pd),
            }
            s = {"bn1": _bn_stats(mid), "bn2": _bn_stats(mid),
                 "bn3": _bn_stats(cout)}
            if bi == 0:
                p["conv_proj"] = _conv_init(next(keys), 1, 1, cin, cout, pd)
                p["bn_proj"] = _bn_init(cout, pd)
                s["bn_proj"] = _bn_stats(cout)
            params[name] = p
            stats[name] = s
            cin = cout
    params["fc_w"] = (jax.random.normal(next(keys), (cin, cfg.num_classes))
                      * (cin ** -0.5)).astype(pd)
    params["fc_b"] = jnp.zeros((cfg.num_classes,), pd)
    return params, stats


def resnet101_init(key: jax.Array, cfg: ResNetConfig
                   ) -> Tuple[Dict, Dict]:
    """ResNet-101 (the reference's published benchmark model,
    ref: docs/benchmarks.rst:27-43).  Returns (params, batch_stats).

    Requires ``cfg.depth == 101``: ``resnet_apply`` walks the stage
    layout from the SAME cfg, so silently patching depth here would
    leave the caller applying a ResNet-50 subgraph over 101's params."""
    if cfg.depth != 101:
        raise ValueError(
            f"resnet101_init needs ResNetConfig(depth=101), got "
            f"depth={cfg.depth} — resnet_apply uses cfg.depth too")
    return resnet50_init(key, cfg)


def _conv(x, w, stride=1):
    y = lax.conv_general_dilated(
        x, w.astype(x.dtype), (stride, stride), "SAME",
        dimension_numbers=("NHWC", "HWIO", "NHWC"))
    # Tag conv outputs as the residency boundary for the "epilogue" remat
    # policy (see ResNetConfig.remat).
    return jax.ad_checkpoint.checkpoint_name(y, "rn_conv_out")


def _stem_conv(x, w, cfg: ResNetConfig):
    """The 7x7/2 stem, pad (3,3) — lowered per ``cfg.stem``.

    "s2d" is the exact space-to-depth rewrite: with y[i] reading input
    rows 2i-3..2i+3, pack row pairs into channels (xs[p, (dy,dx,k)] =
    x[2p+dy, 2q+dx, k], 224^2x3 -> 112^2x12) and convolve with the 4x4
    repack of the 7x7 kernel, W4[u,v,(dy,dx,k),c] = w[2u+dy-1, 2v+dx-1,
    k, c] (zero where the index underflows), stride 1, pad (2,1).  Same
    sum, identical output; the MXU sees C_in=12 instead of 3."""
    w = w.astype(x.dtype)
    # s2d needs even H/W for the 2x2 pixel packing; odd sizes (e.g.
    # --image-size 225) take the literal conv.
    if cfg.stem != "s2d" or x.shape[1] % 2 or x.shape[2] % 2:
        return lax.conv_general_dilated(
            x, w, (2, 2), [(3, 3), (3, 3)],
            dimension_numbers=("NHWC", "HWIO", "NHWC"))
    n, h, wd, c = x.shape
    xs = x.reshape(n, h // 2, 2, wd // 2, 2, c).transpose(0, 1, 3, 2, 4, 5)
    xs = xs.reshape(n, h // 2, wd // 2, 4 * c)
    wp = jnp.pad(w, ((1, 0), (1, 0), (0, 0), (0, 0)))
    w4 = wp.reshape(4, 2, 4, 2, c, w.shape[-1]).transpose(0, 2, 1, 3, 4, 5)
    w4 = w4.reshape(4, 4, 4 * c, w.shape[-1])
    return lax.conv_general_dilated(
        xs, w4, (1, 1), [(2, 1), (2, 1)],
        dimension_numbers=("NHWC", "HWIO", "NHWC"))


def _batch_norm(x, p, s, cfg: ResNetConfig, train: bool):
    """Returns (y, new_stats). In training mode uses batch statistics
    (optionally averaged over ``cfg.bn_axis`` — SyncBatchNorm) and
    EMA-updates the running stats."""
    if train:
        axes = (0, 1, 2)
        # f32 upcast + square fuse into the reduction pass (reads bf16
        # from HBM, accumulates f32 — no materialized f32 copy).
        xf = x.astype(jnp.float32)
        if cfg.bn_axis is not None:
            # Sync the MOMENTS, then form the variance (the one shared
            # implementation — sync_batch_norm.sync_batch_stats):
            # pmean'ing per-device variances would drop the
            # between-device mean-variance term, undershooting the
            # exact global var by Var_devices(mean_d).
            from ..sync_batch_norm import sync_batch_stats

            mean, var = sync_batch_stats(xf, cfg.bn_axis,
                                         reduction_axes=axes)
        else:
            mean = xf.mean(axes)
            var = (xf ** 2).mean(axes) - mean ** 2
        m = cfg.bn_momentum
        new_s = {"mean": m * s["mean"] + (1 - m) * mean,
                 "var": m * s["var"] + (1 - m) * var}
    else:
        mean, var = s["mean"], s["var"]
        new_s = s
    # Fold (mean, var, scale, bias) into one per-channel FMA applied in the
    # activation dtype: stats/coefficients stay f32 (reduction precision) but
    # the [N,H,W,C] elementwise work is y = x*a + b in bf16, which XLA fuses
    # as a conv epilogue without materializing f32 activation copies — this
    # is the HBM-traffic lever on v5e (the f32 normalize chain cost ~8 bytes
    # per element per pass vs 2 here).
    inv = lax.rsqrt(var + cfg.bn_eps)
    a = (p["scale"].astype(jnp.float32) * inv).astype(x.dtype)
    b = (p["bias"].astype(jnp.float32)
         - mean * p["scale"].astype(jnp.float32) * inv).astype(x.dtype)
    return x * a + b, new_s


def _fused_1x1_eligible(w, stride, cfg, x=None) -> bool:
    """HVDT_FUSED_CONV1X1 gate: fused Pallas conv+BN for 1x1 stride-1
    convs with 128-lane-tiling output channels.  SyncBN (cfg.bn_axis)
    is supported — the kernel's per-device stat partials are psum'd
    over the axis (ops/conv_fused.conv1x1_bn_train(axis=...)).

    When ``x`` is given, also gate on the matmul's M = B*H*W rows
    tiling: the kernel's row blocks must clear the per-dtype sublane
    floor (8 rows f32 / 16 bf16 / 32 one-byte), so an M whose largest
    power-of-2 divisor is smaller (e.g. batch 1 at 14x14 → M=196 → 4)
    falls back to the XLA conv path instead of crashing in
    ops/conv_fused._fit_block at trace time (ADVICE r5)."""
    from ..common import config

    kh, kw, cin, cout = w.shape
    if x is not None:
        m = x.shape[0] * x.shape[1] * x.shape[2]
        floor = {4: 8, 2: 16, 1: 32}.get(jnp.dtype(x.dtype).itemsize, 8)
        if (m & -m) < floor:     # largest power-of-2 divisor of M
            return False
    # cin gate too: K=64 lane tiles (stage-0 blocks, 64->256) are
    # outside every probe-validated shape — keep them on XLA until a
    # probe shape covers them.
    return (config.get_bool("HVDT_FUSED_CONV1X1") and kh == 1 and kw == 1
            and stride == 1 and cout % 128 == 0 and cin % 128 == 0)


def _conv_bn(x, w, bn_p, bn_s, cfg, train, *, stride=1, relu=False):
    """conv + BN (+ReLU) — one call site shape for both the XLA path
    and the fused Pallas path (ops/conv_fused.py), so the A/B differs
    only in lowering.  One documented exception to exact gradient
    equality: the fused kernel uses relu'(0)=0 while jnp.maximum's
    autodiff splits the tie at 0.5, so units with EXACTLY zero
    pre-activation (measure zero under random inputs) get different
    subgradients.  Returns (y, new_bn_stats)."""
    if _fused_1x1_eligible(w, stride, cfg, x):
        from ..ops.conv_fused import conv1x1_bn_relu, conv1x1_bn_train

        w2 = w.reshape(w.shape[2], w.shape[3]).astype(x.dtype)
        if train:
            y, mean, var = conv1x1_bn_train(
                x, w2, bn_p["scale"], bn_p["bias"], eps=cfg.bn_eps,
                relu=relu, axis=cfg.bn_axis)
            m = cfg.bn_momentum
            new_s = {"mean": m * bn_s["mean"] + (1 - m) * mean,
                     "var": m * bn_s["var"] + (1 - m) * var}
        else:
            inv = lax.rsqrt(bn_s["var"] + cfg.bn_eps)
            scale = bn_p["scale"].astype(jnp.float32) * inv
            bias = (bn_p["bias"].astype(jnp.float32)
                    - bn_s["mean"] * scale)
            y = conv1x1_bn_relu(x, w2, scale, bias, relu=relu)
            new_s = bn_s
        # Same residency anchor as _conv, so the "epilogue" remat
        # policy keeps a boundary here on the fused path too.
        return jax.ad_checkpoint.checkpoint_name(y, "rn_conv_out"), new_s
    y, new_s = _batch_norm(_conv(x, w, stride), bn_p, bn_s, cfg, train)
    if relu:
        y = jax.nn.relu(y)
    return y, new_s


def _bottleneck(x, p, s, cfg, train, stride):
    out_s = {}
    y, out_s["bn1"] = _conv_bn(x, p["conv1"], p["bn1"], s["bn1"], cfg,
                               train, relu=True)
    # v1.5: stride lives on the 3x3 conv.
    y, out_s["bn2"] = _conv_bn(y, p["conv2"], p["bn2"], s["bn2"], cfg,
                               train, stride=stride, relu=True)
    y, out_s["bn3"] = _conv_bn(y, p["conv3"], p["bn3"], s["bn3"], cfg,
                               train, relu=False)
    if "conv_proj" in p:
        sc, out_s["bn_proj"] = _conv_bn(x, p["conv_proj"], p["bn_proj"],
                                        s["bn_proj"], cfg, train,
                                        stride=stride, relu=False)
    else:
        sc = x
    return jax.nn.relu(y + sc), out_s


def resnet_apply(params: Dict, batch_stats: Dict, images: jax.Array,
                 cfg: ResNetConfig, train: bool = True
                 ) -> Tuple[jax.Array, Dict]:
    """images: [N, H, W, 3] → (logits [N, classes], new_batch_stats)."""
    x = images.astype(cfg.dtype)
    new_stats: Dict = {}
    x = _stem_conv(x, params["conv_stem"], cfg)
    x, new_stats["bn_stem"] = _batch_norm(
        x, params["bn_stem"], batch_stats["bn_stem"], cfg, train)
    x = jax.nn.relu(x)
    x = lax.reduce_window(x, -jnp.inf, lax.max, (1, 3, 3, 1), (1, 2, 2, 1),
                          "SAME")
    def _block(x, p, s, stride):
        return _bottleneck(x, p, s, cfg, train, stride)

    if cfg.remat == "epilogue":
        policy = jax.checkpoint_policies.save_only_these_names("rn_conv_out")
        block = jax.checkpoint(_block, policy=policy, static_argnums=(3,))
    else:
        block = _block
    for si, (blocks, _) in enumerate(_STAGES[cfg.depth]):
        for bi in range(blocks):
            name = f"s{si}b{bi}"
            stride = 2 if (bi == 0 and si > 0) else 1
            x, new_stats[name] = block(
                x, params[name], batch_stats[name], stride)
    x = x.mean(axis=(1, 2)).astype(jnp.float32)
    logits = x @ params["fc_w"].astype(jnp.float32) + params["fc_b"].astype(
        jnp.float32)
    return logits, new_stats


def resnet_loss(params: Dict, batch_stats: Dict, images: jax.Array,
                labels: jax.Array, cfg: ResNetConfig
                ) -> Tuple[jax.Array, Dict]:
    """Cross-entropy loss; returns (loss, new_batch_stats) for
    ``jax.value_and_grad(..., has_aux=True)``."""
    logits, new_stats = resnet_apply(params, batch_stats, images, cfg, True)
    logp = jax.nn.log_softmax(logits, -1)
    loss = -jnp.take_along_axis(logp, labels[:, None], -1).mean()
    return loss, new_stats
