"""Small MLP classifier — the MNIST-class example model.

Equivalent of the reference's MNIST examples used as CI smoke tests
(ref: examples/pytorch/pytorch_mnist.py, .buildkite/gen-pipeline.sh:157-189
— SURVEY.md §4 tier 4).
"""

from __future__ import annotations

from typing import Dict, Sequence

import jax
import jax.numpy as jnp

__all__ = ["mlp_init", "mlp_apply", "mlp_loss"]


def mlp_init(key: jax.Array, sizes: Sequence[int] = (784, 256, 128, 10)
             ) -> Dict:
    params = {}
    keys = jax.random.split(key, len(sizes) - 1)
    for i, (a, b) in enumerate(zip(sizes[:-1], sizes[1:])):
        params[f"w{i}"] = jax.random.normal(keys[i], (a, b)) * (a ** -0.5)
        params[f"b{i}"] = jnp.zeros((b,))
    return params


def mlp_apply(params: Dict, x: jax.Array) -> jax.Array:
    n = len(params) // 2
    for i in range(n):
        x = x @ params[f"w{i}"] + params[f"b{i}"]
        if i < n - 1:
            x = jax.nn.relu(x)
    return x


def mlp_loss(params: Dict, x: jax.Array, labels: jax.Array) -> jax.Array:
    logp = jax.nn.log_softmax(mlp_apply(params, x), -1)
    return -jnp.take_along_axis(logp, labels[:, None], -1).mean()
