"""Model zoo for benchmarks, examples, and the driver's flagship entry.

The reference ships its models as examples (ref: examples/pytorch/
pytorch_synthetic_benchmark.py — torchvision resnet50;
examples/tensorflow2/tensorflow2_synthetic_benchmark.py — Keras ResNet50;
examples/pytorch/pytorch_mnist.py).  Here the models are first-class,
pure-JAX pytree models designed to compose with the parallelism substrate
(``horovod_tpu.parallel``): logical-axis annotations per parameter, ring
attention over ``sp``, MoE over ``ep``, pipeline stacking over ``pp``.
"""

from .transformer import (  # noqa: F401
    TransformerConfig,
    transformer_init,
    transformer_apply,
    transformer_loss,
    transformer_logical_axes,
    transformer_flops_per_token,
    remat_from_env,
    checkpoint_policy,
)
from .resnet import (  # noqa: F401
    ResNetConfig,
    resnet50_init,
    resnet101_init,
    resnet_apply,
    resnet_loss,
)
from .vgg import (  # noqa: F401
    VGGConfig,
    vgg16_init,
    vgg_apply,
    vgg_loss,
)
from .mlp import mlp_init, mlp_apply, mlp_loss  # noqa: F401
