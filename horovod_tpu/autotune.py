"""Autotuning: Bayesian optimization of communication knobs.

Re-conception of ref: common/parameter_manager.{h,cc} (ParameterManager,
joint Bayesian knobs :178-220) + common/optim/bayesian_optimization.{h,cc}
and gaussian_process.{h,cc} (GP regression + expected-improvement
acquisition) — in Python/NumPy, since on TPU the tuning loop runs host-side
between steps, far off the hot path.

Tuned knobs (the TPU analogs of fusion-threshold/cycle-time):

* ``log2_bucket_bytes`` — gradient fusion bucket size for
  ``fused_allreduce`` (bigger ⇒ fewer collectives, less overlap);
* ``overlap_buckets`` — how many buckets to keep in flight (the cycle-time
  analog: scheduling granularity of comm/compute overlap).

Score = bytes/sec of gradient traffic, synchronized across ranks by
construction (every rank sees the same step timings via the same jit
program; for eager use, scores can be fed per-rank and the argmax is
deterministic given identical samples — ref: parameter_manager.cc
SynchronizeParameters broadcast is replaced by deterministic replay).
"""

from __future__ import annotations

import csv
import dataclasses
import math
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from .common import config
from .common.logging_util import get_logger

log = get_logger(__name__)

__all__ = ["GaussianProcess", "BayesianOptimizer", "ParameterManager",
           "BenchmarkAutotuner", "AutotunedStep", "autotuned_step"]


class GaussianProcess:
    """RBF-kernel GP regression (ref: optim/gaussian_process.{h,cc}).

    Hyperparameters are fixed (length_scale per-dim, signal/noise variance)
    rather than L-BFGS-optimized — adequate for the handful of samples the
    tuner sees, and dependency-free.
    """

    def __init__(self, length_scale: float = 1.0, signal_var: float = 1.0,
                 noise: float = 0.1):
        self.length_scale = length_scale
        self.signal_var = signal_var
        self.noise = noise
        self._x: Optional[np.ndarray] = None
        self._y: Optional[np.ndarray] = None
        self._alpha: Optional[np.ndarray] = None
        self._l_chol: Optional[np.ndarray] = None

    def _kernel(self, a: np.ndarray, b: np.ndarray) -> np.ndarray:
        d2 = ((a[:, None, :] - b[None, :, :]) ** 2).sum(-1)
        return self.signal_var * np.exp(-0.5 * d2 / self.length_scale ** 2)

    def fit(self, x: np.ndarray, y: np.ndarray) -> None:
        self._x = np.atleast_2d(np.asarray(x, float))
        self._y = np.asarray(y, float).reshape(-1)
        k = self._kernel(self._x, self._x)
        k[np.diag_indices_from(k)] += self.noise
        self._l_chol = np.linalg.cholesky(k)
        self._alpha = np.linalg.solve(
            self._l_chol.T, np.linalg.solve(self._l_chol, self._y))

    def predict(self, x: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
        """Posterior mean and stddev at query points."""
        x = np.atleast_2d(np.asarray(x, float))
        if self._x is None:
            return np.zeros(len(x)), np.full(len(x),
                                             math.sqrt(self.signal_var))
        ks = self._kernel(x, self._x)
        mean = ks @ self._alpha
        v = np.linalg.solve(self._l_chol, ks.T)
        var = self.signal_var - (v ** 2).sum(0)
        return mean, np.sqrt(np.maximum(var, 1e-12))


class BayesianOptimizer:
    """Expected-improvement acquisition over a candidate grid
    (ref: optim/bayesian_optimization.{h,cc})."""

    def __init__(self, candidates: np.ndarray, noise: float = 0.1,
                 xi: float = 0.01):
        self.candidates = np.atleast_2d(np.asarray(candidates, float))
        self.gp = GaussianProcess(noise=noise)
        self.xi = xi
        self._xs: List[np.ndarray] = []
        self._ys: List[float] = []

    def observe(self, x: Sequence[float], y: float) -> None:
        self._xs.append(np.asarray(x, float))
        self._ys.append(float(y))
        # Z-score-normalize scores before fitting: raw bytes/sec (~1e9)
        # against a unit-variance kernel would collapse EI to 0 everywhere
        # (the reference normalizes in ParameterManager too).
        ys = np.asarray(self._ys)
        std = float(ys.std())
        self._y_scale = std if std > 0 else 1.0
        self._y_shift = float(ys.mean())
        self._yn = (ys - self._y_shift) / self._y_scale
        self.gp.fit(np.stack(self._xs), self._yn)

    def suggest(self) -> np.ndarray:
        if not self._xs:
            return self.candidates[0]
        mean, std = self.gp.predict(self.candidates)
        best = float(self._yn.max())
        z = (mean - best - self.xi) / std
        phi = np.exp(-0.5 * z ** 2) / math.sqrt(2 * math.pi)
        cdf = 0.5 * (1 + np.vectorize(math.erf)(z / math.sqrt(2)))
        ei = (mean - best - self.xi) * cdf + std * phi
        # Avoid re-suggesting seen points (in EI and in the fallback).
        seen_mask = np.zeros(len(self.candidates), bool)
        for seen in self._xs:
            seen_mask |= np.all(np.isclose(self.candidates, seen), axis=1)
        ei[seen_mask] = -1
        if np.all(ei <= 0):
            fallback = np.where(seen_mask, -np.inf, mean)
            if np.all(np.isneginf(fallback)):  # every candidate visited
                return self.candidates[int(np.argmax(mean))]
            return self.candidates[int(np.argmax(fallback))]
        return self.candidates[int(np.argmax(ei))]

    @property
    def best(self) -> Tuple[np.ndarray, float]:
        i = int(np.argmax(self._ys))
        return self._xs[i], self._ys[i]


@dataclasses.dataclass
class _Sample:
    point: np.ndarray
    bytes_total: float = 0.0
    seconds: float = 0.0
    steps: int = 0

    @property
    def score(self) -> float:
        return self.bytes_total / self.seconds if self.seconds > 0 else 0.0


class ParameterManager:
    """Online tuner with warmup → sample → done lifecycle
    (ref: common/parameter_manager.cc Update/Tune/LogParameters).

    Usage::

        pm = ParameterManager()
        for step in range(...):
            t0 = time.perf_counter()
            ...train step using pm.bucket_bytes...
            pm.record(grad_bytes, time.perf_counter() - t0)
    """

    LOG2_BUCKET_CANDIDATES = tuple(range(20, 29))     # 1 MiB .. 256 MiB
    OVERLAP_CANDIDATES = (1, 2, 4)
    FUSED_OPTIMIZER_CANDIDATES = (0.0, 1.0)
    # 0 = f32, 1 = int8, 2 = int4 (the quant_leg encoding): one knob
    # column, three wire legs, all state-compatible hot-swaps.
    QUANT_CANDIDATES = (0.0, 1.0, 2.0)
    OVERLAP_SCHEDULE_CANDIDATES = (0.0, 1.0)
    TRANSPORT_CANDIDATES = (0.0, 1.0)
    ZERO_CANDIDATES = (0.0, 1.0)
    # Expert capacity factors (parallel/moe.py): dispatch payload and
    # dropped-token fraction trade directly against each other.
    MOE_CAPACITY_CANDIDATES = (1.0, 1.25, 1.5, 2.0)
    # log2(microbatch count) for the 1F1B clock (parallel/pipeline.py):
    # 4..32 microbatches — bubble fraction (p-1)/(m+p-1) vs per-tick
    # ppermute payload.
    PIPELINE_LOG2_MICROBATCH_CANDIDATES = (2.0, 3.0, 4.0, 5.0)

    def __init__(self,
                 warmup_samples: Optional[int] = None,
                 steps_per_sample: Optional[int] = None,
                 max_samples: Optional[int] = None,
                 log_file: Optional[str] = None,
                 noise: Optional[float] = None,
                 tune_fused_optimizer: Optional[bool] = None,
                 tune_quant: Optional[bool] = None,
                 tune_overlap: Optional[bool] = None,
                 tune_transport: Optional[bool] = None,
                 tune_zero: Optional[bool] = None,
                 tune_moe: Optional[bool] = None,
                 tune_pipeline: Optional[bool] = None):
        self.warmup = (warmup_samples if warmup_samples is not None
                       else config.get_int("HVDT_AUTOTUNE_WARMUP_SAMPLES"))
        self.steps_per_sample = (
            steps_per_sample if steps_per_sample is not None
            else config.get_int("HVDT_AUTOTUNE_STEPS_PER_SAMPLE"))
        self.max_samples = (
            max_samples if max_samples is not None
            else config.get_int("HVDT_AUTOTUNE_BAYES_OPT_MAX_SAMPLES"))
        noise = (noise if noise is not None
                 else config.get_float("HVDT_AUTOTUNE_GAUSSIAN_PROCESS_NOISE"))
        self._log_file = log_file or config.get_str("HVDT_AUTOTUNE_LOG") or None
        # Optional third knob dimension: fused-vs-unfused optimizer
        # kernels (ops/optim_kernels) — a 0/1 A/B the GP searches
        # jointly with the comm knobs, since comm/compute overlap and
        # the update's HBM footprint interact.
        self.tune_fused = (
            tune_fused_optimizer if tune_fused_optimizer is not None
            else config.get_bool("HVDT_AUTOTUNE_FUSED_OPTIMIZER"))
        # Optional fourth dimension: the quantized gradient-wire leg
        # (horovod_tpu/quant; f32/int8/int4) — comm bytes and step time
        # trade against quantize/dequantize compute, so the GP prices
        # the wire jointly with the bucketing it directly interacts
        # with.
        self.tune_quant = (tune_quant if tune_quant is not None
                           else config.get_bool("HVDT_AUTOTUNE_QUANT"))
        # Optional fifth dimension: overlap-schedule on/off
        # (ops/overlap.py) — whether the dependency-ordered, pipelined
        # exchange beats the monolithic fused path depends on the very
        # bucketing the GP already searches, so they are priced jointly.
        # Both legs keep one optimizer state tree (the schedule changes
        # lowering, never state), so the hot swap is a re-jit only.
        self.tune_overlap = (tune_overlap if tune_overlap is not None
                             else config.get_bool("HVDT_AUTOTUNE_OVERLAP"))
        # Optional sixth dimension: flat-vs-hierarchical transport
        # (horovod_tpu/transport) — whether the two-level fast-axis/
        # slow-axis schedule beats the flat collective depends on the
        # bucketing and wire already searched, so the GP prices the
        # policy jointly.  Both legs keep one optimizer state tree (the
        # policy changes lowering, never state), so the hot swap is a
        # re-jit only.  The starting leg is MEASURED when
        # HVDT_AUTOTUNE_TRANSPORT_SEED points at a bench_allreduce
        # sweep (hierarchical_speedup_vs_flat_at_peak > 1).
        self.tune_transport = (
            tune_transport if tune_transport is not None
            else config.get_bool("HVDT_AUTOTUNE_TRANSPORT"))
        # Optional seventh dimension: replicated-vs-ZeRO-sharded
        # exchange/update (ops/zero.py) — reduce-scatter wire + sharded
        # state trades an extra allgather against n-fold-smaller
        # optimizer HBM (bigger batches), so the GP prices it jointly
        # with bucketing and wire.  Both legs keep ONE sharded state
        # tree (the replicated leg exchanges via allreduce and slices
        # its shard — same layout, different wire), so the hot swap is
        # a re-jit only.  The starting leg is MEASURED when
        # HVDT_AUTOTUNE_ZERO_SEED points at a bench_allreduce
        # --reduce-scatter sweep (rs_ag_speedup_vs_allreduce_at_peak
        # > 1).
        self.tune_zero = (tune_zero if tune_zero is not None
                          else config.get_bool("HVDT_AUTOTUNE_ZERO"))
        # Optional eighth dimension: expert capacity factor
        # (parallel/moe.py) — a2a dispatch bytes scale linearly with
        # capacity while the dropped-token fraction falls, and the
        # break-even moves with the dispatch wire the GP is already
        # pricing, so they are searched jointly.  Hot-swappable: the
        # capacity changes the dispatch layout (a re-jit), never
        # optimizer state.  The starting leg is the explicit
        # HVDT_MOE_CAPACITY_FACTOR, the MEASURED
        # HVDT_AUTOTUNE_MOE_SEED verdict, or the cost model's a2a-wire
        # ordering.
        self.tune_moe = (tune_moe if tune_moe is not None
                         else config.get_bool("HVDT_AUTOTUNE_MOE"))
        # Optional ninth dimension: 1F1B microbatch count
        # (parallel/pipeline.py) — more microbatches shrink the bubble
        # (p-1)/(m+p-1) but shrink every ppermute tick's payload toward
        # the latency floor, the same alpha/beta trade the bucket-size
        # dimension walks, so the GP prices them jointly.
        # Hot-swappable: the clock changes lowering, never state.
        self.tune_pipeline = (
            tune_pipeline if tune_pipeline is not None
            else config.get_bool("HVDT_AUTOTUNE_PIPELINE"))
        # Column layout: [log2_bucket, overlap] (+fused) (+quant)
        # (+overlap_schedule) (+transport) (+zero) (+moe) (+pipeline).
        self._quant_col = (2 + int(self.tune_fused)) if self.tune_quant \
            else None
        self._overlap_col = (
            2 + int(self.tune_fused) + int(self.tune_quant)
        ) if self.tune_overlap else None
        self._transport_col = (
            2 + int(self.tune_fused) + int(self.tune_quant)
            + int(self.tune_overlap)
        ) if self.tune_transport else None
        self._zero_col = (
            2 + int(self.tune_fused) + int(self.tune_quant)
            + int(self.tune_overlap) + int(self.tune_transport)
        ) if self.tune_zero else None
        self._moe_col = (
            2 + int(self.tune_fused) + int(self.tune_quant)
            + int(self.tune_overlap) + int(self.tune_transport)
            + int(self.tune_zero)
        ) if self.tune_moe else None
        self._pipeline_col = (
            2 + int(self.tune_fused) + int(self.tune_quant)
            + int(self.tune_overlap) + int(self.tune_transport)
            + int(self.tune_zero) + int(self.tune_moe)
        ) if self.tune_pipeline else None
        import itertools

        dims = [self.LOG2_BUCKET_CANDIDATES, self.OVERLAP_CANDIDATES]
        if self.tune_fused:
            dims.append(self.FUSED_OPTIMIZER_CANDIDATES)
        if self.tune_quant:
            dims.append(self.QUANT_CANDIDATES)
        if self.tune_overlap:
            dims.append(self.OVERLAP_SCHEDULE_CANDIDATES)
        if self.tune_transport:
            dims.append(self.TRANSPORT_CANDIDATES)
        if self.tune_zero:
            dims.append(self.ZERO_CANDIDATES)
        if self.tune_moe:
            dims.append(self.MOE_CAPACITY_CANDIDATES)
        if self.tune_pipeline:
            dims.append(self.PIPELINE_LOG2_MICROBATCH_CANDIDATES)
        grid = np.array(list(itertools.product(*dims)), float)
        self._bo = BayesianOptimizer(grid, noise=noise)
        start = [math.log2(config.get_int("HVDT_FUSION_THRESHOLD")), 1.0]
        if self.tune_fused:
            start.append(float(config.get_bool("HVDT_FUSED_OPTIMIZER")))
        if self.tune_quant:
            start.append(_LEG_VALUES[_env_quant_leg()])
        if self.tune_overlap:
            start.append(float(_env_overlap()))
        if self.tune_transport:
            start.append(float(_env_transport()))
        if self.tune_zero:
            start.append(float(_env_zero()))
        if self.tune_moe:
            start.append(_env_capacity_factor())
        if self.tune_pipeline:
            start.append(math.log2(_env_microbatches()))
        self._current = np.array(start)
        self._sample = _Sample(self._current)
        self._samples_done = 0
        self._warmups_done = 0
        self._done = False

    # -- knob views --------------------------------------------------------

    @property
    def bucket_bytes(self) -> int:
        return int(2 ** self._current[0])

    @property
    def overlap_buckets(self) -> int:
        return int(self._current[1])

    @property
    def fused_optimizer(self) -> bool:
        """Current fused-optimizer A/B choice; outside the tuned
        dimension it reports the HVDT_FUSED_OPTIMIZER default."""
        if self.tune_fused:
            return bool(self._current[2] >= 0.5)
        return config.get_bool("HVDT_FUSED_OPTIMIZER")

    @property
    def quant_wire(self) -> bool:
        """Current quantized-vs-f32 wire choice (any quantized leg);
        outside the tuned dimension it reports the HVDT_QUANT /
        HVDT_COMPRESSION env default."""
        if self.tune_quant:
            return bool(self._current[self._quant_col] >= 0.5)
        return _env_quant_wire()

    @property
    def quant_leg(self) -> str:
        """Current wire leg by name — "f32", "int8" or "int4" (the
        0/1/2 knob encoding); outside the tuned dimension it reports
        the env default leg."""
        if self.tune_quant:
            v = float(self._current[self._quant_col])
            return "int4" if v >= 1.5 else ("int8" if v >= 0.5 else "f32")
        return _env_quant_leg()

    @property
    def overlap_schedule(self) -> bool:
        """Current overlap-schedule on/off choice; outside the tuned
        dimension it reports the HVDT_OVERLAP env default."""
        if self.tune_overlap:
            return bool(self._current[self._overlap_col] >= 0.5)
        return _env_overlap()

    @property
    def transport_policy(self) -> bool:
        """Current flat-vs-hierarchical transport choice; outside the
        tuned dimension it reports the HVDT_TRANSPORT / seed-file env
        default."""
        if self.tune_transport:
            return bool(self._current[self._transport_col] >= 0.5)
        return _env_transport()

    @property
    def zero_sharding(self) -> bool:
        """Current replicated-vs-ZeRO-sharded choice; outside the tuned
        dimension it reports the HVDT_ZERO / seed-file env default."""
        if self.tune_zero:
            return bool(self._current[self._zero_col] >= 0.5)
        return _env_zero()

    @property
    def capacity_factor(self) -> float:
        """Current expert capacity-factor choice; outside the tuned
        dimension it reports the HVDT_MOE_CAPACITY_FACTOR / seed-file
        default."""
        if self.tune_moe:
            return float(self._current[self._moe_col])
        return _env_capacity_factor()

    @property
    def num_microbatches(self) -> int:
        """Current 1F1B microbatch-count choice (the log2 knob decoded);
        outside the tuned dimension it reports the
        HVDT_PIPELINE_MICROBATCHES / seed-file default."""
        if self.tune_pipeline:
            return int(round(2 ** self._current[self._pipeline_col]))
        return _env_microbatches()

    @property
    def tuning_complete(self) -> bool:
        return self._done

    # -- feeding -----------------------------------------------------------

    def record(self, grad_bytes: float, seconds: float) -> bool:
        """Record one step; returns True when knob values just changed
        (caller should rebuild/re-jit its buckets)."""
        if self._done:
            return False
        s = self._sample
        s.bytes_total += grad_bytes
        s.seconds += seconds
        s.steps += 1
        if s.steps < self.steps_per_sample:
            return False
        return self._finish_sample()

    def _finish_sample(self) -> bool:
        s = self._sample
        if self._warmups_done < self.warmup:
            self._warmups_done += 1
            self._sample = _Sample(self._current)
            return False
        self._bo.observe(s.point, s.score)
        self._log(s)
        self._samples_done += 1
        if self._samples_done >= self.max_samples:
            best_x, best_y = self._bo.best
            self._current = best_x
            self._done = True
            log.info("autotune done: bucket=%d MiB overlap=%d (%.1f MB/s)",
                     self.bucket_bytes // 2 ** 20, self.overlap_buckets,
                     best_y / 1e6)
            return True
        self._current = self._bo.suggest()
        self._sample = _Sample(self._current)
        return True

    def _log(self, s: _Sample) -> None:
        if not self._log_file:
            return
        try:
            with open(self._log_file, "a", newline="") as f:
                row = [time.time(), int(2 ** s.point[0]), int(s.point[1])]
                for extra in s.point[2:]:    # fused/quant/.../moe dims
                    # Leg knobs are small ints; the capacity-factor
                    # column is fractional — keep it readable either way.
                    row.append(int(extra) if float(extra).is_integer()
                               else f"{extra:g}")
                csv.writer(f).writerow(row + [f"{s.score:.1f}"])
        except OSError as e:
            log.warning("autotune log write failed: %s", e)


def _model_seed(dim: str) -> Optional[bool]:
    """Cost-model leg ordering (analysis/costmodel.predict_leg_order)
    consulted only when ``HVDT_AUTOTUNE_MODEL_SEED`` is enabled AND the
    caller found no measured seed / explicit env policy — the
    ROADMAP-5 seam: when measurement is unavailable the tuner starts
    from the model's ordering instead of blind.  ``None`` = knob off or
    model unanswerable; callers keep their pre-existing default."""
    raw = config.get_str("HVDT_AUTOTUNE_MODEL_SEED").strip()
    if raw.lower() in ("", "0", "off", "false", "no"):
        return None
    try:
        from .analysis import costmodel

        path = (None if raw.lower() in ("1", "on", "true", "yes", "auto")
                else raw)
        cal = costmodel.load_calibration(path)
        verdict = costmodel.predict_leg_order(cal).get(dim)
        if verdict is not None:
            log.info("autotune %s starting leg model-seeded: %s "
                     "(%s)", dim, verdict, cal.describe())
        return verdict
    except Exception as e:     # a seed must never break training startup
        log.warning("autotune model seed unavailable for %s: %s", dim, e)
        return None


# quant_leg knob encoding (one GP column spanning three legs).
_LEG_VALUES = {"f32": 0.0, "int8": 1.0, "int4": 2.0}


def _env_quant_leg() -> str:
    """The environment's quantized-wire default leg (the quant
    dimension's starting point): HVDT_QUANT → int8,
    HVDT_COMPRESSION=int8|int4 by name; with neither set (and no
    explicit non-quantized compression choice), the cost model may
    order the leg (HVDT_AUTOTUNE_MODEL_SEED — a True verdict starts at
    int8, the conservative quantized leg)."""
    if config.get_bool("HVDT_QUANT"):
        return "int8"
    comp = config.get_str("HVDT_COMPRESSION").strip().lower()
    if comp in ("int8", "int4"):
        return comp
    if comp:
        return "f32"           # explicit non-quantized wire choice wins
    ms = _model_seed("quant")
    return "int8" if ms else "f32"


def _env_quant_wire() -> bool:
    """The environment's quantized-wire default as a bool (any
    quantized leg; the legacy ``quant=`` builder keyword)."""
    return _env_quant_leg() != "f32"


def _env_overlap() -> bool:
    """The environment's overlap-schedule default (the overlap
    dimension's starting leg): HVDT_OVERLAP truthy; unset (not an
    explicit 'off'), the cost model may order the leg
    (HVDT_AUTOTUNE_MODEL_SEED)."""
    from .ops.overlap import enabled

    if enabled():
        return True
    if config.get_str("HVDT_OVERLAP").strip():
        return False           # explicit off wins over the model
    ms = _model_seed("overlap")
    return bool(ms) if ms is not None else False


def _env_zero() -> bool:
    """The environment's replicated-vs-sharded default (the zero
    dimension's starting leg): HVDT_ZERO set, else the MEASURED verdict
    of a bench_allreduce --reduce-scatter sweep named by
    HVDT_AUTOTUNE_ZERO_SEED (rs_ag_speedup_vs_allreduce_at_peak > 1 ⇒
    start sharded) — the policies-are-measured loop, mirroring
    _env_transport."""
    from .ops.zero import enabled as zero_enabled

    try:
        if zero_enabled():
            return True
    except ValueError:
        return False
    seed = config.get_str("HVDT_AUTOTUNE_ZERO_SEED").strip()
    if not seed:
        return False
    import json

    try:
        with open(seed) as fh:
            doc = json.load(fh)
        return float(doc.get("rs_ag_speedup_vs_allreduce_at_peak",
                             0.0)) > 1.0
    except (OSError, ValueError, TypeError) as e:
        log.warning("zero autotune seed %s unreadable: %s", seed, e)
        return False


def _env_transport() -> bool:
    """The environment's flat-vs-hierarchical default (the transport
    dimension's starting leg): HVDT_TRANSPORT set, else the MEASURED
    verdict of a bench_allreduce sweep named by
    HVDT_AUTOTUNE_TRANSPORT_SEED (hierarchical_speedup_vs_flat_at_peak
    > 1 ⇒ start hierarchical) — the policies-are-measured loop."""
    from .transport import enabled

    if enabled():
        return True
    seed = config.get_str("HVDT_AUTOTUNE_TRANSPORT_SEED").strip()
    if not seed:
        ms = _model_seed("transport")
        return bool(ms) if ms is not None else False
    import json

    try:
        with open(seed) as fh:
            doc = json.load(fh)
        return float(doc.get("hierarchical_speedup_vs_flat_at_peak",
                             0.0)) > 1.0
    except (OSError, ValueError, TypeError) as e:
        log.warning("transport autotune seed %s unreadable: %s", seed, e)
        ms = _model_seed("transport")
        return bool(ms) if ms is not None else False


def _env_capacity_factor() -> float:
    """The environment's expert capacity-factor default (the MoE
    dimension's starting leg): an explicitly set
    HVDT_MOE_CAPACITY_FACTOR wins; else the MEASURED verdict of a
    bench.py --moe sweep named by HVDT_AUTOTUNE_MOE_SEED
    (capacity_factor_at_peak); else the cost model may order the leg
    (HVDT_AUTOTUNE_MODEL_SEED — a True 'moe' verdict means the
    quantized dispatch wire wins, so capacity headroom is cheap: start
    at the registry default 1.25; False starts tight at 1.0 to keep
    the expensive f32 dispatch payload minimal)."""
    import os

    if os.environ.get("HVDT_MOE_CAPACITY_FACTOR", "").strip():
        return config.get_float("HVDT_MOE_CAPACITY_FACTOR")
    seed = config.get_str("HVDT_AUTOTUNE_MOE_SEED").strip()
    if seed:
        import json

        try:
            with open(seed) as fh:
                doc = json.load(fh)
            v = float(doc.get("capacity_factor_at_peak", 0.0))
            if v > 0:
                return v
        except (OSError, ValueError, TypeError) as e:
            log.warning("moe autotune seed %s unreadable: %s", seed, e)
    ms = _model_seed("moe")
    if ms is not None:
        return 1.25 if ms else 1.0
    return config.get_float("HVDT_MOE_CAPACITY_FACTOR")


def _env_microbatches() -> int:
    """The environment's 1F1B microbatch-count default (the pipeline
    dimension's starting leg): an explicitly set
    HVDT_PIPELINE_MICROBATCHES wins; else the MEASURED verdict of a
    bench.py --pipeline sweep named by HVDT_AUTOTUNE_PIPELINE_SEED
    (microbatches_at_peak); else the cost model may order the leg
    (HVDT_AUTOTUNE_MODEL_SEED — a True 'pipeline' verdict means the
    tick is bandwidth-dominated, so halving per-tick payload is free
    bubble shrink: start at the high end 16; False starts at the
    registry default 8)."""
    import os

    if os.environ.get("HVDT_PIPELINE_MICROBATCHES", "").strip():
        return max(1, config.get_int("HVDT_PIPELINE_MICROBATCHES"))
    seed = config.get_str("HVDT_AUTOTUNE_PIPELINE_SEED").strip()
    if seed:
        import json

        try:
            with open(seed) as fh:
                doc = json.load(fh)
            v = int(doc.get("microbatches_at_peak", 0))
            if v > 0:
                return v
        except (OSError, ValueError, TypeError) as e:
            log.warning("pipeline autotune seed %s unreadable: %s",
                        seed, e)
    ms = _model_seed("pipeline")
    if ms is not None:
        return 16 if ms else max(1, config.get_int(
            "HVDT_PIPELINE_MICROBATCHES"))
    return max(1, config.get_int("HVDT_PIPELINE_MICROBATCHES"))


class BenchmarkAutotuner:
    """Closed-loop driver tying :class:`ParameterManager` to a train loop.

    The reference's autotuner is closed-loop: measured step throughput
    feeds the Bayesian optimizer, the winning parameters are synchronized
    across ranks, and the fusion pipeline actually uses them
    (ref: common/parameter_manager.cc Update/SynchronizeParameters,
    operations.cc:793-800).  This is that loop for the jit path::

        tuner = BenchmarkAutotuner(tree_example=params)
        step = build_step(threshold_bytes=tuner.bucket_bytes)
        for ...:
            t0 = time.perf_counter(); run_n_steps(k)
            if tuner.record(time.perf_counter() - t0, steps=k):
                step = build_step(threshold_bytes=tuner.bucket_bytes)

    ``record`` returns True when the knobs changed — the caller re-jits
    its step with the new ``bucket_bytes`` (the fusion threshold is a
    trace-time constant under XLA, so "apply" = re-jit; compile cost is
    absorbed by the next sample and the warmup discards).

    Cross-rank sync: when knobs change, rank 0's choice is broadcast
    through the eager control plane KV and adopted everywhere, so every
    rank always jits the same bucketing (the SynchronizeParameters
    analog).  Single-process runs use the Local plane (no-op).
    """

    def __init__(self, tree_example, steps_per_sample: Optional[int] = None,
                 pm: Optional[ParameterManager] = None,
                 control_plane=None):
        self.pm = pm or ParameterManager(steps_per_sample=steps_per_sample)
        self._grad_bytes = float(sum(
            np.prod(getattr(l, "shape", ())) * np.dtype(l.dtype).itemsize
            for l in _tree_leaves(tree_example)))
        self._cp = control_plane
        self._sync_cycle = 0

    @property
    def bucket_bytes(self) -> int:
        return self.pm.bucket_bytes

    @property
    def done(self) -> bool:
        return self.pm.tuning_complete

    def record(self, seconds: float, steps: int = 1) -> bool:
        """Feed ``steps`` steps that took ``seconds`` total; True when the
        knobs changed and the caller should re-jit."""
        changed = False
        per = seconds / max(1, steps)
        for _ in range(steps):
            changed = self.pm.record(self._grad_bytes, per) or changed
        if changed:
            self._sync()
        return changed

    def _sync(self) -> None:
        """Adopt rank 0's knob point everywhere (KV broadcast)."""
        cp = self._cp
        if cp is None:
            from .common import basics
            from .ops.control_plane import (LocalControlPlane,
                                            default_control_plane)

            # Un-initialized framework == single process: nothing to sync.
            self._cp = cp = (default_control_plane()
                             if basics.is_initialized()
                             else LocalControlPlane())
        if cp.size() <= 1:
            return
        self._sync_cycle += 1
        payload = None
        if cp.rank() == 0:
            payload = ",".join(f"{v:.6f}" for v in self.pm._current)
        wire = cp.broadcast(payload, cycle=10_000_000 + self._sync_cycle)
        point = np.array([float(v) for v in wire.split(",")])
        self.pm._current = point
        self.pm._sample = _Sample(point)

    def summary(self) -> str:
        state = "converged" if self.done else "tuning"
        fused = (f" fused_opt={int(self.pm.fused_optimizer)}"
                 if self.pm.tune_fused else "")
        quant = (f" wire={self.pm.quant_leg}"
                 if self.pm.tune_quant else "")
        ovl = (f" schedule={'overlap' if self.pm.overlap_schedule else 'mono'}"
               if self.pm.tune_overlap else "")
        tr = (f" transport={'hier' if self.pm.transport_policy else 'flat'}"
              if self.pm.tune_transport else "")
        zr = (f" zero={'sharded' if self.pm.zero_sharding else 'repl'}"
              if self.pm.tune_zero else "")
        moe = (f" capacity={self.pm.capacity_factor:g}"
               if self.pm.tune_moe else "")
        pipe = (f" microbatches={self.pm.num_microbatches}"
                if self.pm.tune_pipeline else "")
        return (f"{state}: bucket={self.pm.bucket_bytes // 2**20} MiB "
                f"overlap={self.pm.overlap_buckets}"
                f"{fused}{quant}{ovl}{tr}{zr}{moe}{pipe} "
                f"({self.pm._samples_done} samples)")


def _tree_leaves(tree):
    import jax

    return jax.tree.leaves(tree)


class AutotunedStep:
    """Transparent env-driven engagement of the closed tuning loop.

    The reference's autotuner engages for ANY training run when
    ``HOROVOD_AUTOTUNE=1`` is set — no script changes (ref:
    common/operations.cc:466-475 reads the env; :793-800 applies tuned
    values inside the background loop).  Under XLA the fusion threshold is
    a trace-time constant, so "apply" = re-jit: the engagement point is a
    step *wrapper* owning the (re-)build::

        step = hvd.autotune.autotuned_step(build_step)   # always
        ...
        params, opt_state, loss = step(params, opt_state, batch)

    With ``HVDT_AUTOTUNE`` unset this is a zero-overhead passthrough
    (``builder(None)`` once, direct dispatch).  With ``HVDT_AUTOTUNE=1``
    (what ``hvdtrun --autotune`` exports) the wrapper times
    steps_per_sample-step regions (closed by a host fetch of the
    smallest output leaf — block_until_ready lies on tunnelled PJRT
    backends), feeds :class:`BenchmarkAutotuner`, rebuilds the step via
    ``builder(new_threshold_bytes)`` when the knobs move, KV-syncs rank
    0's choice, and discards the first (compile-polluted) region after
    every rebuild.

    With ``HVDT_AUTOTUNE_FUSED_OPTIMIZER=1`` the search space gains a
    fused-vs-unfused optimizer dimension (ops/optim_kernels): a builder
    that accepts a ``fused`` keyword is rebuilt as
    ``builder(threshold_bytes, fused=bool)`` at each knob change, so the
    GP prices the update-side kernels jointly with the comm bucketing.
    Builders without the keyword keep the old call shape.

    With ``HVDT_AUTOTUNE_QUANT=1`` the space likewise gains a
    quantized-*wire* leg dimension (horovod_tpu/quant; f32/int8/int4):
    builders accepting a ``quant`` keyword are rebuilt as
    ``builder(threshold_bytes, quant=bool)`` (any quantized leg →
    True); builders accepting ``quant_leg`` additionally receive the
    leg by name (``quant_leg="f32"|"int8"|"int4"``) and can pick the
    matching ``Compression`` + ``with_error_feedback(wire=...)``.
    Hot-swappable mid-run because every wire leg keeps one optimizer
    state tree — the error-feedback residual is leg-independent f32
    (``quant.with_error_feedback(enabled=..., wire=...)``;
    tests/test_quant.py and tests/test_lowbit.py pin the contract).

    With ``HVDT_AUTOTUNE_OVERLAP=1`` the space gains an
    overlap-schedule on/off dimension (ops/overlap.py): builders
    accepting an ``overlap`` keyword are rebuilt as
    ``builder(threshold_bytes, overlap=bool)`` — hot-swappable mid-run
    because the schedule changes lowering, never optimizer state, so
    both legs keep one state tree (and a leg-memoizing builder flips
    back to a previously compiled program without re-jitting;
    tests/test_overlap.py pins the contract).

    With ``HVDT_AUTOTUNE_TRANSPORT=1`` the space gains a
    flat-vs-hierarchical transport dimension (horovod_tpu/transport):
    builders accepting a ``transport`` keyword are rebuilt as
    ``builder(threshold_bytes, transport=bool)`` — same
    one-state-tree hot-swap contract (the policy changes lowering,
    never state; tests/test_transport.py pins it), with the STARTING
    leg seeded from ``HVDT_TRANSPORT`` or the measured
    ``HVDT_AUTOTUNE_TRANSPORT_SEED`` bench verdict.

    With ``HVDT_AUTOTUNE_ZERO=1`` the space gains a
    replicated-vs-ZeRO-sharded dimension (ops/zero.py): builders
    accepting a ``zero`` keyword are rebuilt as
    ``builder(threshold_bytes, zero=bool)`` — hot-swappable because
    both legs keep ONE sharded state tree (the replicated leg is the
    allreduce + own-shard-slice wire, ``zero_transform(...,
    rs_wire=False)``; tests/test_zero.py pins the contract), with the
    STARTING leg seeded from ``HVDT_ZERO`` or the measured
    ``HVDT_AUTOTUNE_ZERO_SEED`` bench_allreduce --reduce-scatter
    verdict.

    With ``HVDT_AUTOTUNE_MOE=1`` the space gains an expert
    capacity-factor dimension (parallel/moe.py): builders accepting a
    ``capacity_factor`` keyword are rebuilt as
    ``builder(threshold_bytes, capacity_factor=float)`` — dispatch
    payload vs dropped-token fraction, priced jointly with the wire
    legs; hot-swappable because capacity changes the dispatch layout
    (a re-jit), never optimizer state.  Starting leg: explicit
    ``HVDT_MOE_CAPACITY_FACTOR``, the measured
    ``HVDT_AUTOTUNE_MOE_SEED`` bench verdict, or the cost model's
    a2a-wire ordering (``HVDT_AUTOTUNE_MODEL_SEED``).

    With ``HVDT_AUTOTUNE_PIPELINE=1`` the space gains a 1F1B
    microbatch-count dimension (parallel/pipeline.py): builders
    accepting a ``microbatches`` keyword are rebuilt as
    ``builder(threshold_bytes, microbatches=int)`` — bubble fraction
    vs per-tick ppermute payload; hot-swappable because the clock
    changes lowering, never state.  Starting leg: explicit
    ``HVDT_PIPELINE_MICROBATCHES``, the measured
    ``HVDT_AUTOTUNE_PIPELINE_SEED`` bench verdict, or the cost model's
    ppermute ordering.

    Args:
      builder: ``builder(threshold_bytes | None) -> step_callable``
        (optionally also accepting ``fused=bool``).
      tree_example: gradient-sized pytree for the bytes/sec score; when
        None, the first positional arg of the first call is used.
      enabled: force on/off; None (default) reads ``HVDT_AUTOTUNE``.
    """

    def __init__(self, builder, tree_example=None, *,
                 enabled: Optional[bool] = None,
                 steps_per_sample: Optional[int] = None,
                 control_plane=None):
        import inspect

        if enabled is None:
            enabled = config.get_bool("HVDT_AUTOTUNE")
        self.enabled = bool(enabled)
        self._builder = builder
        try:
            sig = inspect.signature(builder).parameters
            var_kw = any(p.kind is inspect.Parameter.VAR_KEYWORD
                         for p in sig.values())
            self._accepts_fused = "fused" in sig or var_kw
            self._accepts_quant = "quant" in sig or var_kw
            self._accepts_quant_leg = "quant_leg" in sig or var_kw
            self._accepts_overlap = "overlap" in sig or var_kw
            self._accepts_transport = "transport" in sig or var_kw
            self._accepts_zero = "zero" in sig or var_kw
            self._accepts_capacity = "capacity_factor" in sig or var_kw
            self._accepts_microbatches = "microbatches" in sig or var_kw
        except (TypeError, ValueError):
            self._accepts_fused = False
            self._accepts_quant = False
            self._accepts_quant_leg = False
            self._accepts_overlap = False
            self._accepts_transport = False
            self._accepts_zero = False
            self._accepts_capacity = False
            self._accepts_microbatches = False
        # Pin every tuned A/B dimension's starting leg at build 0 so the
        # opt-state structure established before tuning matches every
        # later rebuild (both fused legs keep one state tree —
        # ops/optim_kernels; both wire legs too —
        # quant.with_error_feedback(enabled=...)).
        build_kw = {}
        if (self.enabled and self._accepts_fused
                and config.get_bool("HVDT_AUTOTUNE_FUSED_OPTIMIZER")):
            build_kw["fused"] = config.get_bool("HVDT_FUSED_OPTIMIZER")
        if (self.enabled and self._accepts_quant
                and config.get_bool("HVDT_AUTOTUNE_QUANT")):
            build_kw["quant"] = _env_quant_wire()
        if (self.enabled and self._accepts_quant_leg
                and config.get_bool("HVDT_AUTOTUNE_QUANT")):
            build_kw["quant_leg"] = _env_quant_leg()
        if (self.enabled and self._accepts_overlap
                and config.get_bool("HVDT_AUTOTUNE_OVERLAP")):
            build_kw["overlap"] = _env_overlap()
        if (self.enabled and self._accepts_transport
                and config.get_bool("HVDT_AUTOTUNE_TRANSPORT")):
            build_kw["transport"] = _env_transport()
        if (self.enabled and self._accepts_zero
                and config.get_bool("HVDT_AUTOTUNE_ZERO")):
            build_kw["zero"] = _env_zero()
        if (self.enabled and self._accepts_capacity
                and config.get_bool("HVDT_AUTOTUNE_MOE")):
            build_kw["capacity_factor"] = _env_capacity_factor()
        if (self.enabled and self._accepts_microbatches
                and config.get_bool("HVDT_AUTOTUNE_PIPELINE")):
            build_kw["microbatches"] = _env_microbatches()
        self._step = builder(None, **build_kw)
        self._tree_example = tree_example
        self._steps_per_sample = steps_per_sample
        self._cp = control_plane
        self._tuner: Optional[BenchmarkAutotuner] = None
        self._t0: Optional[float] = None
        self._pending = 0
        self._skip_sample = False
        # Controller seam (horovod_tpu/control): leg overrides queued by
        # apply_leg, adopted at the next __call__ boundary and merged
        # LAST into every later rebuild so the tuner doesn't stomp them.
        self._pending_legs: Dict[str, Any] = {}
        self._leg_overrides: Dict[str, Any] = {}
        self._override_threshold: Optional[int] = None

    @property
    def autotuner(self) -> Optional[BenchmarkAutotuner]:
        return self._tuner

    @property
    def bucket_bytes(self) -> Optional[int]:
        return self._tuner.bucket_bytes if self._tuner else None

    def summary(self) -> str:
        if not self.enabled:
            return "autotune disabled (HVDT_AUTOTUNE not set)"
        return self._tuner.summary() if self._tuner else "no samples yet"

    def _rebuild(self):
        """Re-jit at the tuner's current knob point (fused/quant
        dimensions forwarded only when both the tuner and the builder
        carry them).  Controller leg overrides merge last — an applied
        policy decision survives the tuner's own rebuilds."""
        pm = self._tuner.pm
        kw = {}
        if pm.tune_fused and self._accepts_fused:
            kw["fused"] = pm.fused_optimizer
        if pm.tune_quant and self._accepts_quant:
            kw["quant"] = pm.quant_wire
        if pm.tune_quant and self._accepts_quant_leg:
            kw["quant_leg"] = pm.quant_leg
        if pm.tune_overlap and self._accepts_overlap:
            kw["overlap"] = pm.overlap_schedule
        if pm.tune_transport and self._accepts_transport:
            kw["transport"] = pm.transport_policy
        if pm.tune_zero and self._accepts_zero:
            kw["zero"] = pm.zero_sharding
        if pm.tune_moe and self._accepts_capacity:
            kw["capacity_factor"] = pm.capacity_factor
        if pm.tune_pipeline and self._accepts_microbatches:
            kw["microbatches"] = pm.num_microbatches
        kw.update(self._filtered_overrides())
        threshold = (self._override_threshold
                     if self._override_threshold is not None
                     else self._tuner.bucket_bytes)
        return self._builder(threshold, **kw)

    # -- controller seam (horovod_tpu/control) -----------------------------

    _LEG_ACCEPTS = {"fused": "_accepts_fused", "quant": "_accepts_quant",
                    "quant_leg": "_accepts_quant_leg",
                    "overlap": "_accepts_overlap",
                    "transport": "_accepts_transport",
                    "zero": "_accepts_zero",
                    "capacity_factor": "_accepts_capacity",
                    "microbatches": "_accepts_microbatches"}

    def apply_leg(self, **legs: Any) -> None:
        """Queue a policy-controller leg override, adopted at the NEXT
        ``__call__`` — never mid-step.  Accepts the builder leg
        keywords (``transport=bool``, ``overlap=bool``, ``zero=bool``,
        ``quant=bool``, ``quant_leg=str``, ``fused=bool``) plus
        ``threshold_bytes=int`` for a bucket retune.  Adoption is the
        same state-compatible rebuild the tuner performs: one optimizer
        state tree, re-jit only, and a leg-memoizing builder flips back
        to an already-compiled program without recompiling.  Works with
        the tuner off (``HVDT_AUTOTUNE`` unset) — the controller can
        steer an untuned run."""
        self._pending_legs.update(legs)

    def _filtered_overrides(self) -> Dict[str, Any]:
        return {k: v for k, v in self._leg_overrides.items()
                if getattr(self, self._LEG_ACCEPTS.get(k, ""), False)}

    def _adopt_legs(self) -> None:
        pending, self._pending_legs = self._pending_legs, {}
        if "threshold_bytes" in pending:
            self._override_threshold = int(pending.pop("threshold_bytes"))
        self._leg_overrides.update(pending)
        self._step = (self._rebuild() if self._tuner is not None
                      else self._builder(self._override_threshold,
                                         **self._filtered_overrides()))
        if self._tuner is not None:
            # The adopting region includes a possible re-jit: discard
            # its sample so compile time can't poison the tuner score.
            self._skip_sample = True
        log.info("controller leg adopted: %s%s", pending,
                 (f" threshold={self._override_threshold}"
                  if self._override_threshold is not None else ""))

    @staticmethod
    def _fetch(out) -> None:
        """Close the timed region with a device->host transfer that
        data-depends on the step output (the smallest leaf).  Multi-host
        arrays aren't fully addressable — np.asarray would raise — so
        fetch an addressable shard instead."""
        leaves = [l for l in _tree_leaves(out) if hasattr(l, "dtype")]
        if not leaves:
            return
        smallest = min(leaves, key=lambda l: int(np.prod(
            getattr(l, "shape", ()) or (1,))))
        shards = getattr(smallest, "addressable_shards", None)
        if shards:
            np.asarray(shards[0].data)
        else:
            np.asarray(smallest)

    def __call__(self, *args, **kwargs):
        if self._pending_legs:
            self._adopt_legs()
        if not self.enabled:
            return self._step(*args, **kwargs)
        if self._tuner is None:
            tree = (self._tree_example if self._tree_example is not None
                    else (args[0] if args else ()))
            self._tuner = BenchmarkAutotuner(
                tree_example=tree, steps_per_sample=self._steps_per_sample,
                control_plane=self._cp)
        if self._t0 is None:
            self._t0 = time.perf_counter()
        out = self._step(*args, **kwargs)
        self._pending += 1
        if self._pending >= self._tuner.pm.steps_per_sample:
            self._fetch(out)
            dt = time.perf_counter() - self._t0
            if self._skip_sample:
                # Region included a re-jit: compile time would poison the
                # new point's score — discard, measure the next region.
                self._skip_sample = False
            elif self._tuner.record(dt, steps=self._pending):
                self._step = self._rebuild()
                self._skip_sample = True
                log.info("autotune applied: bucket=%d MiB",
                         self._tuner.bucket_bytes // 2 ** 20)
            self._pending = 0
            self._t0 = None
        return out


def autotuned_step(builder, tree_example=None, *,
                   enabled: Optional[bool] = None,
                   steps_per_sample: Optional[int] = None,
                   control_plane=None) -> AutotunedStep:
    """See :class:`AutotunedStep` — the ``HVDT_AUTOTUNE`` engagement."""
    return AutotunedStep(builder, tree_example, enabled=enabled,
                         steps_per_sample=steps_per_sample,
                         control_plane=control_plane)
