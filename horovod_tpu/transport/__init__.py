"""Topology-aware transport policies for collective communication.

The per-mesh-axis policy layer (ROADMAP item 2): instead of one flat
algorithm and one global wire format over the whole mesh, every mesh
axis gets its own **transport policy** — algorithm (``ring | tree |
2d_ring``), wire dtype (``f32 | bf16 | fp16 | int8``) and fusion
threshold — selected by ``HVDT_TRANSPORT`` and applied by the
hierarchical allreduce in :mod:`.hierarchy`:

* reduce-scatter over the fast (ICI) axis,
* cross-axis exchange of the 1/n shard over the slow (DCN) axis —
  riding the block-scaled int8 wire (quant/collectives) when the slow
  policy says so,
* allgather back over the fast axis.

Zero-wrapper contract (same idiom as telemetry/instrument and
ops/overlap): with ``HVDT_TRANSPORT`` unset, :func:`get_policy` returns
``None`` and every data-plane call site takes its pre-existing flat
path untouched — ``overlap.exchange_fn()`` still resolves to
``ops.device.fused_allreduce`` as the identical code object.
"""

from .policy import (AxisPolicy, ResolvedTransport, TransportPolicy,
                     bucket_threshold, enabled, get_policy, parse_transport,
                     reset, resolve_axis, validate_env)
from .hierarchy import (InflightHierarchical, hierarchical_allreduce_finish,
                        hierarchical_allreduce_flat,
                        hierarchical_allreduce_start, pin_inflight,
                        wire_bytes_estimate)

__all__ = [
    "AxisPolicy", "ResolvedTransport", "TransportPolicy",
    "parse_transport", "get_policy", "resolve_axis", "bucket_threshold",
    "enabled", "reset", "validate_env",
    "InflightHierarchical", "hierarchical_allreduce_start",
    "hierarchical_allreduce_finish", "hierarchical_allreduce_flat",
    "pin_inflight", "wire_bytes_estimate",
]
