"""Hierarchical allreduce under a transport policy — the two-level data
plane (ref: NCCLHierarchicalAllreduce, nccl_operations.cc:249-517; the
MLPerf-on-TPU-pods schedule: ICI reduce-scatter → DCN shard exchange →
ICI allgather).

The bucket-level primitive ``ops.device.fused_allreduce`` and the overlap
scheduler (``ops/overlap.py``) route float buckets here when
``HVDT_TRANSPORT`` resolves the reduce group hierarchically:

1. optional fast-axis wire cast (``bf16``/``fp16`` — the established
   cast-around-the-collective compression);
2. **fast tier** — reduce-scatter over the innermost (ICI) axis (or the
   two innermost under ``2d_ring``); ``tree`` skips the split and fuses
   the whole fast reduction into one collective (latency-optimal for
   small buckets);
3. **slow tier** — the 1/n shard crosses the outer (DCN) axes: plain
   psum for exact wires, or the block-scaled int8 two-stage collective
   (``quant/collectives``) when the slow policy says ``int8`` — the
   bandwidth-heavy cross-pod hop at ~1 B/element;
4. allgather back over the fast tier (``invariant_allgather_shards`` —
   the psum-family terminal op keeps the result replicated, which P()
   out_specs and optax.MultiSteps require);
5. single final division for AVERAGE, postscale, cast to the original
   dtype.

Split into :func:`hierarchical_allreduce_start` /
:func:`hierarchical_allreduce_finish` (the ``quantized_allreduce_start``
/ ``finish`` seam) so the overlap scheduler can pipeline bucket N's
slow-tier finish + allgather under bucket N+1's flight window;
``finish(start(x))`` is the exact program
:func:`hierarchical_allreduce_flat` traces.

Numerics: the fast/slow split only *reassociates* the cross-rank sum —
the same values are added, grouped per tier — and AVERAGE divides the
full sum once by the total group size exactly like the flat path, so
f32 results differ from flat ``fused_allreduce`` by reassociation
rounding at most (bitwise-equal on exactly-representable inputs, the
contract tests/test_transport.py pins).  The int8 slow wire keeps the
established per-stage block-scale/2 bound on 1/n-sized shards.

jax-0.4.37 guard: only ``lax.psum``/``psum_scatter``/named-axis
primitives — no ``jax.typeof``/``lax.pcast`` anywhere on this path;
axis sizes resolve through the guarded ``dev._axis_size_static``.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from ..common.logging_util import get_logger
from ..common.types import ReduceOp
from .policy import ResolvedTransport

log = get_logger(__name__)

__all__ = ["InflightHierarchical", "hierarchical_allreduce_start",
           "hierarchical_allreduce_finish", "hierarchical_allreduce_flat",
           "pin_inflight", "wire_bytes_estimate"]

_WIRE_DTYPES = {"bf16": jnp.bfloat16, "fp16": jnp.float16}


@dataclasses.dataclass
class InflightHierarchical:
    """A hierarchical allreduce whose fast reduce-scatter (and, for the
    int8 slow wire, the bandwidth-heavy slow wire hop) has been issued
    but whose finish half has not run yet — the seam the overlap
    scheduler pipelines across buckets.

    The finish half carries the plain-wire slow psum, the int8
    dequant-accumulate, and the fast allgather — every terminal op is
    psum-family, so replication over the full reduce group is restored
    AFTER any ``optimization_barrier`` pin (barriers erase replication
    tracking; a pinned finish must re-establish it, the same design as
    the quantized start/finish split).  ``shard`` / ``quant_state``
    hold the traced arrays; everything else is static trace-time
    metadata."""

    res: ResolvedTransport
    op: ReduceOp
    n_total: int
    size: int
    pad: int
    dtype: Any
    gathered: bool                  # True when the fast tier was fused
    slow_done: bool                 # True when no slow exchange remains
    shard: Optional[Any] = None
    quant_state: Optional[Any] = None   # slow tier in flight (int8 wire)


def _record_hop(op: str, axis: str, dtype, wire: str, nbytes: int,
                count: int = 1) -> None:
    """Trace-time per-axis accounting (path=jit convention): the main
    collective counters gain the axis label and the per-axis
    ``hvdt_wire_bytes_total{axis=...}`` counter books the hop."""
    from ..telemetry import instrument as _ti

    rec = _ti.get_recorder()
    if rec is not None:
        rec.record_collective(op, jnp.dtype(dtype).name, wire,
                              int(nbytes), count=count, path="jit",
                              axis=axis)


def _ring_bytes(size_elems: int, itemsize: int, k: int) -> int:
    """Per-rank ring wire bytes for one data-moving hop (RS or AG) over
    an axis of size k: (k-1)/k of the payload crosses the wire."""
    if k <= 1:
        return 0
    return int(size_elems * itemsize * (k - 1) // k)


def hierarchical_allreduce_start(flat, res: ResolvedTransport,
                                 op: ReduceOp = ReduceOp.AVERAGE,
                                 prescale_factor: float = 1.0
                                 ) -> InflightHierarchical:
    """Fast-tier reduce-scatter + slow-tier wire hop for one flat float
    bucket.  Returns the inflight handle for
    :func:`hierarchical_allreduce_finish`."""
    from ..ops import device as dev

    if op not in (ReduceOp.SUM, ReduceOp.AVERAGE):
        raise ValueError(
            f"hierarchical allreduce supports SUM/AVERAGE, got {op}")
    from ..quant.collectives import quant_wire_leg

    if quant_wire_leg(res.fast.wire) is not None:
        raise ValueError(
            f"{res.fast.wire} rides the slow (dcn) axis; the fast-axis "
            "reduce-scatter leg has no quantized wire format")

    dtype = flat.dtype
    size = int(flat.shape[0])
    n_total = 1
    for a in res.axes:
        n_total *= dev._axis_size_static(a)

    x = flat
    if prescale_factor != 1.0:
        x = x * jnp.asarray(prescale_factor, dtype)
    cast_to = _WIRE_DTYPES.get(res.fast.wire)
    if cast_to is not None and x.dtype != cast_to:
        x = x.astype(cast_to)

    pad = 0
    if res.fast.algorithm == "tree":
        # Latency-optimal fast tier: one fused collective, no RS/AG
        # split — the slow tier then exchanges the FULL vector (right
        # when the bucket is small enough that launches dominate).
        n_fast = _fast_size(res)
        _record_hop("allreduce", "+".join(res.fast_axes), dtype,
                    res.fast.wire,
                    2 * _ring_bytes(size, jnp.dtype(x.dtype).itemsize,
                                    n_fast))
        shard = lax.psum(x, res.fast_axes)
        gathered = True
    else:
        n_fast = _fast_size(res)
        pad = (-size) % n_fast
        if pad:
            x = jnp.concatenate([x, jnp.zeros((pad,), x.dtype)])
        shard = x
        remaining = size + pad
        for a in res.fast_axes:
            k = dev._axis_size_static(a)
            _record_hop("reduce_scatter", a, dtype, res.fast.wire,
                        _ring_bytes(remaining,
                                    jnp.dtype(shard.dtype).itemsize, k))
            shard = lax.psum_scatter(shard, a, tiled=True)
            remaining //= k
        gathered = False

    inflight = InflightHierarchical(
        res=res, op=op, n_total=n_total, size=size, pad=pad, dtype=dtype,
        gathered=gathered, slow_done=not res.slow_axes, shard=shard)

    if res.slow_axes and quant_wire_leg(res.slow.wire) is not None:
        # The bandwidth-heavy slow wire hop (the all_to_all carrying
        # int8/int4 payloads) is issued at start so the overlap
        # scheduler can hide it; the dequant-accumulate half rides
        # finish.
        from ..quant.collectives import quantized_allreduce_start

        inflight.quant_state = quantized_allreduce_start(
            shard, res.slow_axes[0], op=ReduceOp.SUM,
            wire=quant_wire_leg(res.slow.wire))
        inflight.shard = None
        inflight.slow_done = True   # finish side: quant finish only
    return inflight


def _fast_size(res: ResolvedTransport) -> int:
    from ..ops import device as dev

    n = 1
    for a in res.fast_axes:
        n *= dev._axis_size_static(a)
    return n


def hierarchical_allreduce_finish(inflight: InflightHierarchical,
                                  postscale_factor: float = 1.0):
    """Slow-tier exchange/finish + fast allgather + single AVERAGE
    division + postscale + final cast — inverse bookend of
    :func:`hierarchical_allreduce_start`.

    The plain-wire slow psum lives HERE (a bare psum has no
    start/finish split; keeping every remaining collective psum-family
    and after the overlap scheduler's pin barrier restores replication
    over the full reduce group — barriers erase replication tracking).
    """
    from ..ops import device as dev

    res = inflight.res
    if inflight.quant_state is not None:
        from ..quant.collectives import quantized_allreduce_finish

        shard = quantized_allreduce_finish(inflight.quant_state)
    else:
        shard = inflight.shard
        if not inflight.slow_done:
            slow = res.slow
            cast_slow = _WIRE_DTYPES.get(slow.wire)
            hop = shard
            if cast_slow is not None and hop.dtype != cast_slow:
                hop = hop.astype(cast_slow)
            n_slow = 1
            for a in res.slow_axes:
                n_slow *= dev._axis_size_static(a)
            _record_hop("allreduce", "+".join(res.slow_axes),
                        inflight.dtype, slow.wire,
                        2 * _ring_bytes(int(shard.shape[0]),
                                        jnp.dtype(hop.dtype).itemsize,
                                        n_slow))
            hop = lax.psum(hop, res.slow_axes)
            shard = hop.astype(shard.dtype) if hop.dtype != shard.dtype \
                else hop
    if not inflight.gathered:
        for a in reversed(res.fast_axes):
            k = dev._axis_size_static(a)
            _record_hop("allgather", a, inflight.dtype, res.fast.wire,
                        _ring_bytes(int(shard.shape[0]) * k,
                                    jnp.dtype(shard.dtype).itemsize, k))
            shard = dev.invariant_allgather_shards(shard, a)
    out = shard
    if inflight.pad:
        out = out[:inflight.size]
    if inflight.op == ReduceOp.AVERAGE:
        out = out / inflight.n_total
    if postscale_factor != 1.0:
        out = out * jnp.asarray(postscale_factor, out.dtype)
    return out.astype(inflight.dtype)


def hierarchical_allreduce_flat(flat, res: ResolvedTransport,
                                op: ReduceOp = ReduceOp.AVERAGE,
                                prescale_factor: float = 1.0,
                                postscale_factor: float = 1.0):
    """Allreduce one flat float vector over a hierarchically-resolved
    reduce group (the bucket-level primitive ``fused_allreduce`` routes
    to when ``HVDT_TRANSPORT`` is live).  Composition of ``start`` and
    ``finish`` — calling this traces the identical monolithic program
    the overlap scheduler pipelines."""
    return hierarchical_allreduce_finish(
        hierarchical_allreduce_start(flat, res, op, prescale_factor),
        postscale_factor)


def pin_inflight(inflight: InflightHierarchical,
                 pin) -> InflightHierarchical:
    """Barrier the inflight's traced arrays with the NEXT bucket's
    payload token (never its result — done→issue serialization would
    kill the overlap), so this bucket's finish is scheduled under the
    next bucket's flight window.

    Only pinned when the finish half still contains psum-family
    collectives over EVERY reduce axis (barriers erase replication
    tracking; the finish must re-establish it — see
    :class:`InflightHierarchical`): i.e. only for the reduce-scatter
    fast tier, whose finish allgather covers the fast axes and whose
    slow psum / quant finish covers the slow ones.  A fused (``tree``)
    fast tier established fast-axis replication BEFORE the pin point,
    so it keeps the existing plain-bucket behavior: issue-order pinned
    via the payload chain only."""
    if pin is None or inflight.gathered:
        return inflight
    out = dataclasses.replace(inflight)
    if inflight.quant_state is not None:
        qs = inflight.quant_state
        q2, s2, _ = lax.optimization_barrier((qs.q_recv, qs.s_recv, pin))
        out.quant_state = dataclasses.replace(qs, q_recv=q2, s_recv=s2)
    else:
        shard2, _ = lax.optimization_barrier((inflight.shard, pin))
        out.shard = shard2
    return out


def wire_bytes_estimate(res: ResolvedTransport, count: int,
                        itemsize: int) -> int:
    """Per-rank wire bytes one hierarchical allreduce of ``count``
    elements moves across both tiers (ring accounting: a data-moving
    hop over an axis of size k carries (k-1)/k of its payload) — the
    accounting the overlap scheduler's hidden/total byte counters and
    the bench rows carry.  Must be called where the group's axes are
    bound (trace time); outside a trace the tier sizes degrade to 1 and
    the estimate to 0."""
    fast_n, slow_n = tier_sizes(res)
    fast_item = {"bf16": 2, "fp16": 2}.get(res.fast.wire, itemsize)
    if res.fast.algorithm == "tree":
        total = 2 * _ring_bytes(count, fast_item, fast_n)  # fused AR
        shard = count
    else:
        total = 2 * _ring_bytes(count, fast_item, fast_n)  # RS + AG
        shard = max(1, count // max(1, fast_n))
    if slow_n > 1 and res.slow is not None:
        if res.slow.wire == "int8":
            from ..quant import kernels as qk

            total += int(qk.wire_bytes(shard, qk.quant_block_size()))
        elif res.slow.wire == "int4":
            from ..quant import kernels as qk

            total += int(qk.wire_bytes_int4(shard, qk.quant_block_size()))
        else:
            slow_item = {"bf16": 2, "fp16": 2}.get(res.slow.wire, itemsize)
            total += 2 * _ring_bytes(shard, slow_item, slow_n)
    return int(total)


def tier_sizes(res: ResolvedTransport) -> Tuple[int, int]:
    """(fast, slow) tier sizes for a resolved group with bound axes;
    falls back to (1, 1) outside a trace where axes are unbound."""
    from ..ops import device as dev

    try:
        fast = 1
        for a in res.fast_axes:
            fast *= dev._axis_size_static(a)
        slow = 1
        for a in res.slow_axes:
            slow *= dev._axis_size_static(a)
        return fast, slow
    except Exception:
        return 1, 1
