"""Per-mesh-axis transport policy: grammar, resolution, env engagement.

``HVDT_TRANSPORT`` grammar (strict — unknown vocabulary raises at
``hvd.init()``, same early-validation idiom as ``HVDT_COMPRESSION``)::

    HVDT_TRANSPORT = entry ("," entry)*  |  "auto"
    entry          = axis ":" algorithm ":" wire [":" threshold]
    axis           = "ici" | "dcn"            (transport class)
                   | dp|pp|fsdp|ep|sp|tp      (exact mesh-axis name)
    algorithm      = "ring" | "tree" | "2d_ring"
    wire           = "f32" | "bf16" | "fp16" | "int8" | "int4"
    threshold      = digits [K|M|G]           (fusion bucket bytes)

e.g. ``ici:ring:f32:64M,dcn:tree:int8:8M`` — big buckets ride the
bandwidth-optimal reduce-scatter/allgather split on ICI at f32 while the
cross-pod shard exchange goes latency-optimal tree at ~1 B/element
(``int4``: the packed sub-byte wire, ~0.5 B/element — same dcn-only
placement rule as int8).
``auto`` derives the sane default from the mesh topology convention
(parallel/mesh.py: innermost axis = ICI, outer = DCN): ICI rings at f32
with the global fusion threshold, DCN trees at f32 with 8 MiB buckets.

Class entries (``ici``/``dcn``) key on :func:`parallel.mesh.
axis_transport_class`; exact mesh-axis names win over their class.
Thresholds are parsed strictly (garbage raises at init) and clamped
through ``ops.device._validated_threshold`` at use, so a ``0`` entry
degrades to the registry default with a warning instead of planning
one-leaf buckets.

Algorithm semantics on the XLA data plane (we pick the *decomposition*;
XLA/libtpu picks the wire-level schedule within each collective):

* ``ring`` — bandwidth-optimal: reduce-scatter + allgather split over
  the axis, so the slow-axis hop moves 1/n of the bytes;
* ``tree`` — latency-optimal: one fused collective over the axis (no
  RS/AG split — XLA lowers small all-reduces to trees), right for
  small tensors where the split's extra launches dominate;
* ``2d_ring`` — the reduce-scatter spreads over the TWO innermost
  axes (when the reduce group has ≥ 3 axes) so each ICI ring carries
  1/(n1·n2) of the slow-axis payload.
"""

from __future__ import annotations

import dataclasses
import os
import re
import threading
from typing import Dict, Optional, Tuple, Union

from ..common.logging_util import get_logger
from ..parallel import mesh as _mesh

log = get_logger(__name__)

__all__ = ["AxisPolicy", "ResolvedTransport", "TransportPolicy",
           "parse_transport", "get_policy", "resolve_axis",
           "bucket_threshold", "enabled", "reset", "validate_env",
           "ALGORITHMS", "WIRES", "QUANT_WIRES", "VALID_AXES"]

ALGORITHMS: Tuple[str, ...] = ("ring", "tree", "2d_ring")
WIRES: Tuple[str, ...] = ("f32", "bf16", "fp16", "int8", "int4")
# Block-scaled quantized wires: slow-axis (dcn) only, single slow axis.
QUANT_WIRES: Tuple[str, ...] = ("int8", "int4")
VALID_AXES: Tuple[str, ...] = _mesh.TRANSPORT_CLASSES + _mesh.CANONICAL_AXES

_AUTO_DCN_THRESHOLD = 8 * 1024 * 1024
_SIZE_RE = re.compile(r"^(\d+)([KkMmGg]?)$")
_SIZE_MULT = {"": 1, "k": 1 << 10, "m": 1 << 20, "g": 1 << 30}


@dataclasses.dataclass(frozen=True)
class AxisPolicy:
    """One axis entry: algorithm + wire dtype + optional fusion threshold."""

    algorithm: str = "ring"
    wire: str = "f32"
    threshold_bytes: Optional[int] = None

    def describe(self) -> str:
        t = (f":{self.threshold_bytes}"
             if self.threshold_bytes is not None else "")
        return f"{self.algorithm}:{self.wire}{t}"


@dataclasses.dataclass(frozen=True)
class ResolvedTransport:
    """A policy applied to one concrete reduce group (tuple of bound mesh
    axes, outermost first).  ``hierarchical`` when the group splits into
    a slow tier and a fast tier; ``flat`` when a single-axis group only
    carries a per-axis wire/threshold override."""

    kind: str                       # "hierarchical" | "flat"
    axes: Tuple[str, ...]
    fast_axes: Tuple[str, ...]
    slow_axes: Tuple[str, ...]
    fast: AxisPolicy
    slow: Optional[AxisPolicy]
    threshold_bytes: Optional[int]


def _parse_threshold(tok: str, entry: str) -> int:
    m = _SIZE_RE.match(tok.strip())
    if not m:
        raise ValueError(
            f"invalid HVDT_TRANSPORT threshold {tok!r} in entry "
            f"{entry!r}; expected digits with an optional K/M/G suffix "
            f"(e.g. 64M)")
    return int(m.group(1)) * _SIZE_MULT[m.group(2).lower()]


def parse_transport(spec: str) -> Dict[str, AxisPolicy]:
    """Parse an ``HVDT_TRANSPORT`` spec into {axis: AxisPolicy}.

    Strict: unknown axis/algorithm/wire names and garbage thresholds
    raise ``ValueError`` listing the valid vocabulary — consumed by
    ``hvd.init()`` so a typo fails every worker at init, not at the
    first traced step on some rank.
    """
    entries: Dict[str, AxisPolicy] = {}
    for raw in spec.split(","):
        entry = raw.strip()
        if not entry:
            continue
        fields = [f.strip().lower() for f in entry.split(":")]
        if len(fields) not in (3, 4):
            raise ValueError(
                f"invalid HVDT_TRANSPORT entry {entry!r}; expected "
                f"axis:algorithm:wire[:threshold] (e.g. ici:ring:f32:64M)")
        axis, algorithm, wire = fields[:3]
        if axis not in VALID_AXES:
            raise ValueError(
                f"unknown HVDT_TRANSPORT axis {axis!r}; valid: "
                f"{', '.join(VALID_AXES)}")
        if algorithm not in ALGORITHMS:
            raise ValueError(
                f"unknown HVDT_TRANSPORT algorithm {algorithm!r} for axis "
                f"{axis!r}; valid: {', '.join(ALGORITHMS)}")
        if wire not in WIRES:
            raise ValueError(
                f"unknown HVDT_TRANSPORT wire {wire!r} for axis {axis!r}; "
                f"valid: {', '.join(WIRES)}")
        if axis == _mesh.TRANSPORT_ICI and wire in QUANT_WIRES:
            raise ValueError(
                f"HVDT_TRANSPORT: {wire} rides the slow (dcn) axis — "
                f"the fast-axis reduce-scatter leg has no quantized "
                f"wire format; put {wire} on dcn (e.g. "
                f"dcn:tree:{wire}:8M).  Valid wires: {', '.join(WIRES)} "
                f"(quantized: {', '.join(QUANT_WIRES)}, dcn-only)")
        if axis in entries:
            raise ValueError(
                f"duplicate HVDT_TRANSPORT axis {axis!r}")
        threshold = (_parse_threshold(fields[3], entry)
                     if len(fields) == 4 else None)
        entries[axis] = AxisPolicy(algorithm, wire, threshold)
    if not entries:
        raise ValueError(
            "empty HVDT_TRANSPORT spec; expected "
            "axis:algorithm:wire[:threshold] entries or 'auto'")
    return entries


class TransportPolicy:
    """Per-axis transport choices plus the resolution logic that applies
    them to a concrete reduce group."""

    def __init__(self, entries: Dict[str, AxisPolicy], spec: str = ""):
        self.entries = dict(entries)
        self.spec = spec

    @classmethod
    def parse(cls, spec: str) -> "TransportPolicy":
        spec = spec.strip()
        if spec.lower() == "auto":
            return cls.auto()
        return cls(parse_transport(spec), spec)

    @classmethod
    def auto(cls) -> "TransportPolicy":
        """The topology-derived default (parallel/mesh.py convention:
        innermost axis = ICI, outer axes = DCN): bandwidth-optimal ring
        at f32 on ICI with the global fusion threshold; latency-lean
        tree at f32 with 8 MiB buckets on DCN.  Numerics-neutral — only
        the schedule changes, never the math."""
        return cls({
            _mesh.TRANSPORT_ICI: AxisPolicy("ring", "f32", None),
            _mesh.TRANSPORT_DCN: AxisPolicy("tree", "f32",
                                            _AUTO_DCN_THRESHOLD),
        }, "auto")

    def _lookup(self, axis: str, cls_name: str) -> Optional[AxisPolicy]:
        """Exact mesh-axis entry wins over its transport class."""
        pol = self.entries.get(axis)
        if pol is None:
            pol = self.entries.get(cls_name)
        return pol

    def resolve(self, axis: Union[str, Tuple[str, ...]]
                ) -> Optional[ResolvedTransport]:
        """Apply this policy to a reduce group.

        Multi-axis groups (outermost first, the mesh convention) go
        hierarchical: the innermost axis (two innermost under
        ``2d_ring``) is the fast reduce-scatter tier, everything outer
        is the slow shard-exchange tier.  Single-axis groups resolve to
        a flat override when an entry (exact name, else the ``ici``
        class — one axis is one ICI domain) exists; ``None`` means the
        policy has nothing to say and the call site keeps its exact
        pre-existing path.
        """
        axes = (axis,) if isinstance(axis, str) else tuple(axis)
        if len(axes) >= 2:
            fast = self._lookup(axes[-1], _mesh.TRANSPORT_ICI) \
                or AxisPolicy()
            width = 2 if (fast.algorithm == "2d_ring"
                          and len(axes) > 2) else 1
            slow_axes, fast_axes = _mesh.split_transport_axes(axes, width)
            slow = self._lookup(slow_axes[0], _mesh.TRANSPORT_DCN) \
                or AxisPolicy("tree")
            if slow.wire in QUANT_WIRES and len(slow_axes) != 1:
                raise ValueError(
                    f"{slow.wire} slow-axis wire needs exactly one slow "
                    f"axis, got {slow_axes} (quantized allreduce reduces "
                    f"over ONE mesh axis)")
            threshold = (fast.threshold_bytes
                         if fast.threshold_bytes is not None
                         else slow.threshold_bytes)
            return ResolvedTransport(
                kind="hierarchical", axes=axes, fast_axes=fast_axes,
                slow_axes=slow_axes, fast=fast, slow=slow,
                threshold_bytes=threshold)
        pol = self._lookup(axes[0], _mesh.TRANSPORT_ICI)
        if pol is None:
            return None
        return ResolvedTransport(
            kind="flat", axes=axes, fast_axes=axes, slow_axes=(),
            fast=pol, slow=None, threshold_bytes=pol.threshold_bytes)

    def describe(self) -> str:
        body = ",".join(f"{a}:{p.describe()}"
                        for a, p in sorted(self.entries.items()))
        return f"TransportPolicy({body})"


# ---------------------------------------------------------------------------
# Process-wide policy (env-gated, cached on the raw env string so per-test
# monkeypatching rebuilds it — the telemetry.instrument.get_recorder idiom)
# ---------------------------------------------------------------------------

_TRUTHY_OFF = ("", "0", "off", "none", "false", "no")

_lock = threading.Lock()
_cached_env: Optional[str] = "\0unset"   # sentinel != any real env value
_cached_policy: Optional[TransportPolicy] = None


def enabled() -> bool:
    """Whether the transport-policy layer is on (``HVDT_TRANSPORT``)."""
    return os.environ.get("HVDT_TRANSPORT",
                          "").strip().lower() not in _TRUTHY_OFF


def get_policy() -> Optional[TransportPolicy]:
    """The process-wide transport policy, or ``None`` when off.

    The disabled steady state costs one environ read and a string
    compare; data-plane call sites branch on ``is None`` and keep their
    exact pre-existing flat path.  A malformed spec raises here (and so
    at ``hvd.init()`` through :func:`validate_env`)."""
    global _cached_env, _cached_policy
    raw = os.environ.get("HVDT_TRANSPORT")
    if raw != _cached_env:
        with _lock:
            if raw != _cached_env:
                _cached_policy = (TransportPolicy.parse(raw)
                                  if enabled() else None)
                _cached_env = raw
    return _cached_policy


def resolve_axis(axis) -> Optional[ResolvedTransport]:
    """Resolve the active policy against a reduce group; ``None`` when
    the layer is off or the policy has no entry for the group."""
    pol = get_policy()
    return None if pol is None else pol.resolve(axis)


def bucket_threshold(axis, explicit: Optional[int] = None) -> Optional[int]:
    """The fusion threshold a bucketed exchange over ``axis`` should
    plan with: an explicit caller/autotuner value always wins, else the
    policy's per-axis threshold, else ``None`` (the env default —
    ``ops.device._validated_threshold`` applies its clamping either
    way)."""
    if explicit is not None:
        return explicit
    res = resolve_axis(axis)
    return None if res is None else res.threshold_bytes


def reset() -> None:
    """Drop the cached policy (test isolation)."""
    global _cached_env, _cached_policy
    with _lock:
        _cached_env = "\0unset"
        _cached_policy = None


def validate_env() -> Optional[TransportPolicy]:
    """Early validation for ``hvd.init()``: parse ``HVDT_TRANSPORT`` NOW
    so unknown vocabulary fails at init with the valid lists, not at the
    first traced step on some worker (the ``HVDT_COMPRESSION`` idiom)."""
    return get_policy()
