"""Worker-side entry for the programmatic ``hvd.run`` API.

Fetches the pickled function from the launcher's KV store, executes it,
posts the pickled result keyed by rank (ref: runner/run_task.py +
task_fn.py — same exec-pickled-fn contract, HTTP KV instead of the
pickle-RPC task service).
"""

from __future__ import annotations

import os
import pickle
import sys


def main() -> int:
    from .http_kv import KVClient

    client = KVClient(os.environ["HVDT_RUNFUNC_ADDR"],
                      int(os.environ["HVDT_RUNFUNC_PORT"]),
                      bytes.fromhex(os.environ["HVDT_RUNFUNC_SECRET"]))
    fn = pickle.loads(client.wait("/runfunc/fn", timeout=60.0))
    rank = int(os.environ.get("HVDT_RANK", 0))
    result = fn()
    client.put(f"/runfunc/result/{rank}", pickle.dumps(result))
    return 0


if __name__ == "__main__":
    sys.exit(main())
