"""Host parsing and rank/slot assignment.

Re-conception of ref: runner/common/util/hosts.py:1-155 (parse_hosts,
get_host_assignments → SlotInfo{rank, local_rank, cross_rank, sizes}) for
the TPU process model: one process per TPU VM (host), each controlling its
local chips, so "slots" default to 1 per host but remain configurable for
multi-process-per-host layouts (e.g. one process per chip on v4).
"""

from __future__ import annotations

import dataclasses
import re
from typing import Dict, List, Optional, Sequence

__all__ = ["HostInfo", "SlotInfo", "parse_hosts", "parse_host_files",
           "get_host_assignments", "rank_env_from_hosts"]


@dataclasses.dataclass(frozen=True)
class HostInfo:
    hostname: str
    slots: int
    pod: Optional[str] = None

    @classmethod
    def from_string(cls, s: str) -> "HostInfo":
        """Parse ``host[:slots][@pod]`` — the optional ``@pod`` column is
        how a discovery script declares which pod (TPU slice) a host
        belongs to; hosts sharing a pod fail, resize, and blacklist as
        one unit (runner/elastic/pods.py)."""
        m = re.match(r"^(?P<host>[^:@]+)(:(?P<slots>\d+))?"
                     r"(@(?P<pod>[A-Za-z0-9._-]+))?$", s.strip())
        if not m:
            raise ValueError(f"bad host string: {s!r}")
        return cls(m.group("host"), int(m.group("slots") or 1),
                   m.group("pod"))


@dataclasses.dataclass(frozen=True)
class SlotInfo:
    hostname: str
    rank: int
    local_rank: int
    cross_rank: int
    size: int
    local_size: int
    cross_size: int
    # Pod (two-level) topology: filled by the elastic driver's pod-aware
    # assignment (runner/elastic/pods.py).  ``pod`` empty = the flat,
    # pod-less contract (static launch) — to_env then omits HVDT_POD*.
    pod: str = ""
    pod_index: int = 0
    pod_rank: int = 0
    num_pods: int = 1
    pod_size: int = 0

    def to_env(self) -> Dict[str, str]:
        """The launcher→worker env contract (analog of the reference's
        HOROVOD_RANK/... set at runner/gloo_run.py:65-76)."""
        env = {
            "HVDT_HOSTNAME": self.hostname,
            "HVDT_RANK": str(self.rank),
            "HVDT_SIZE": str(self.size),
            "HVDT_LOCAL_RANK": str(self.local_rank),
            "HVDT_LOCAL_SIZE": str(self.local_size),
            "HVDT_CROSS_RANK": str(self.cross_rank),
            "HVDT_CROSS_SIZE": str(self.cross_size),
        }
        if self.pod:
            env.update({
                "HVDT_POD": self.pod,
                "HVDT_POD_INDEX": str(self.pod_index),
                "HVDT_POD_RANK": str(self.pod_rank),
                "HVDT_NUM_PODS": str(self.num_pods),
                "HVDT_POD_SIZE": str(self.pod_size),
            })
        return env


def parse_hosts(hosts_string: str) -> List[HostInfo]:
    """Parse "host1:2,host2:4" (ref: hosts.py parse_hosts)."""
    return [HostInfo.from_string(part)
            for part in hosts_string.split(",") if part.strip()]


def parse_host_files(filename: str) -> List[HostInfo]:
    """Parse a hostfile with "hostname slots=N" lines (mpirun-style)."""
    hosts = []
    with open(filename) as f:
        for line in f:
            line = line.split("#")[0].strip()
            if not line:
                continue
            m = re.match(r"^(\S+)(\s+slots\s*=\s*(\d+))?", line)
            if m:
                hosts.append(HostInfo(m.group(1), int(m.group(3) or 1)))
    return hosts


def get_host_assignments(hosts: Sequence[HostInfo], min_np: int,
                         max_np: int = 0) -> List[SlotInfo]:
    """Round-robin-free contiguous rank assignment: fill each host's slots
    in order (ref: hosts.py get_host_assignments — same contiguous layout,
    which keeps local ranks adjacent for hierarchical collectives).

    Raises if fewer than ``min_np`` slots are available; assigns at most
    ``max_np`` (default: min_np) slots.
    """
    total = sum(h.slots for h in hosts)
    if total < min_np:
        raise ValueError(
            f"requested {min_np} processes but only {total} slots available "
            f"on {len(hosts)} hosts")
    want = min(max_np or min_np, total)
    assignments: List[SlotInfo] = []
    rank = 0
    cross_size = 0
    for h in hosts:
        if rank >= want:
            break
        cross_size += 1
        for local_rank in range(min(h.slots, want - rank)):
            assignments.append(SlotInfo(
                hostname=h.hostname, rank=rank, local_rank=local_rank,
                cross_rank=cross_size - 1, size=want,
                local_size=0, cross_size=0))
            rank += 1
    # Fix up local/cross sizes now that the layout is known.
    local_sizes: Dict[str, int] = {}
    for a in assignments:
        local_sizes[a.hostname] = local_sizes.get(a.hostname, 0) + 1
    return [dataclasses.replace(a, local_size=local_sizes[a.hostname],
                                cross_size=cross_size)
            for a in assignments]


def rank_env_from_hosts(rank: int, hosts: Sequence[str],
                        base: "dict | None" = None,
                        extra: "dict | None" = None) -> dict:
    """Per-rank HVDT_* env contract from an already-placed host list.

    ``hosts[i]`` is rank i's hostname/IP (as reported by the
    orchestrator — Spark barrier task addresses, Ray actor node IPs).
    Ranks sharing a host get consecutive local ranks; hosts are
    cross-ranked in first-appearance order — the same layout rule as
    ``get_host_assignments`` (ref: runner/common/util/hosts.py), applied
    post hoc to an externally scheduled set."""
    my_host = hosts[rank]
    host_order: list = []
    for h in hosts:
        if h not in host_order:
            host_order.append(h)
    env = dict(base or {})
    env.update({
        "HVDT_RANK": str(rank),
        "HVDT_SIZE": str(len(hosts)),
        "HVDT_LOCAL_RANK": str(sum(1 for h in hosts[:rank]
                                   if h == my_host)),
        "HVDT_LOCAL_SIZE": str(hosts.count(my_host)),
        "HVDT_CROSS_RANK": str(host_order.index(my_host)),
        "HVDT_CROSS_SIZE": str(len(host_order)),
        "HVDT_HOSTNAME": my_host,
    })
    if extra:
        env.update(extra)
    return env
