"""Worker state registry — the rendezvous barrier for elastic resets.

Re-conception of ref: runner/elastic/registration.py:1-180
(WorkerStateRegistry): workers report READY (want a new rendezvous),
SUCCESS, or FAILURE; when every live worker has reported, the driver
fires the reset callback that re-keys the rendezvous.
"""

from __future__ import annotations

import threading
from typing import Callable, Dict, Optional, Set

__all__ = ["WorkerStateRegistry", "READY", "SUCCESS", "FAILURE"]

READY = "READY"
SUCCESS = "SUCCESS"
FAILURE = "FAILURE"


class WorkerStateRegistry:
    def __init__(self, on_barrier: Callable[[Dict[str, Set[int]]], None],
                 reset_limit: Optional[int] = None):
        self._on_barrier = on_barrier
        self._reset_limit = reset_limit
        self._lock = threading.Lock()
        self._states: Dict[str, Set[int]] = {READY: set(), SUCCESS: set(),
                                             FAILURE: set()}
        self._size = 0
        self._reset_count = 0
        self._barrier_fired = False

    def reset(self, size: int) -> None:
        """Arm the barrier for a new worker generation of ``size`` ranks."""
        with self._lock:
            self._states = {READY: set(), SUCCESS: set(), FAILURE: set()}
            self._size = size
            self._barrier_fired = False

    @property
    def reset_count(self) -> int:
        with self._lock:
            return self._reset_count

    def reset_limit_reached(self) -> bool:
        with self._lock:
            return (self._reset_limit is not None
                    and self._reset_count >= self._reset_limit)

    def record_ready(self, rank: int) -> None:
        self._record(READY, rank)

    def record_success(self, rank: int) -> None:
        self._record(SUCCESS, rank)

    def record_failure(self, rank: int) -> None:
        self._record(FAILURE, rank)

    def count(self, state: str) -> int:
        with self._lock:
            return len(self._states[state])

    def _record(self, state: str, rank: int) -> None:
        fire = False
        with self._lock:
            for s in self._states.values():
                s.discard(rank)
            self._states[state].add(rank)
            reported = set().union(*self._states.values())
            if (self._size > 0 and len(reported) >= self._size
                    and not self._barrier_fired):
                self._barrier_fired = True
                if self._states[READY]:
                    self._reset_count += 1
                fire = True
            snapshot = {k: set(v) for k, v in self._states.items()}
        if fire:
            self._on_barrier(snapshot)
