"""Elastic driver: discovery loop, slot reassignment, worker lifecycle.

Re-conception of ref: runner/elastic/driver.py:1-314 (ElasticDriver:
discovery thread :181, host-assignment update + worker notify :203-265,
worker spawn :277, exit handling :297).  Differences for TPU: worker
notification rides the rendezvous KV (workers poll a version key at
commit points) instead of a per-worker RPC service, and re-rendezvous
re-initializes the JAX coordination service rather than re-bootstrapping
Gloo.
"""

from __future__ import annotations

import dataclasses
import os
import shlex
import socket
import sys
import threading
import time
from typing import Callable, Dict, List, Optional

from .. import hosts as hosts_mod
from ..http_kv import RendezvousServer, new_secret
from ..safe_shell_exec import safe_execute
from . import pods as pods_mod
from .discovery import HostManager
from .registration import WorkerStateRegistry, READY, SUCCESS, FAILURE

__all__ = ["ElasticDriver", "run_elastic", "RESTART_EXIT_CODE"]

_DISCOVERY_INTERVAL_S = 1.0

# Worker exit code meaning "ready for the next rendezvous" — the TPU
# elastic model is process-restart (a compiled XLA world cannot resize
# in place): workers persist their committed state to disk and exit with
# this code; the driver respawns every slot under the new generation and
# the fresh processes resume from the disk commit (see
# horovod_tpu/elastic.py run()).
RESTART_EXIT_CODE = 79


@dataclasses.dataclass
class _WorkerProc:
    slot: hosts_mod.SlotInfo
    thread: threading.Thread
    generation: int


class ElasticDriver:
    """Drives elastic worker generations.

    ``spawn_fn(slot, generation)`` starts one worker and returns when it
    exits, reporting the exit code — injectable so unit tests can fake
    whole clusters (ref test strategy: test/single/test_elastic_driver.py,
    SURVEY.md §4 tier 2).
    """

    def __init__(self,
                 host_manager: HostManager,
                 min_np: int,
                 max_np: Optional[int] = None,
                 spawn_fn: Optional[Callable[..., int]] = None,
                 reset_limit: Optional[int] = None,
                 discovery_interval: float = _DISCOVERY_INTERVAL_S,
                 kv_server: Optional[RendezvousServer] = None,
                 hosts_updated_cb: Optional[Callable[[int], None]] = None,
                 elastic_timeout: float = 600.0,
                 pod_slots: int = 0,
                 pod_tracker: Optional[pods_mod.PodTracker] = None):
        self._hm = host_manager
        self._kv = kv_server
        self._hosts_updated_cb = hosts_updated_cb
        self._pending_updates = 0
        self._min_np = min_np
        self._max_np = max_np or min_np
        self._spawn_fn = spawn_fn or (lambda slot, gen: 0)
        self._interval = discovery_interval
        self._elastic_timeout = elastic_timeout
        # Pod-granular control plane (runner/elastic/pods.py): exit
        # correlation, preemption drains, straggler eviction.  With no
        # declared pods and pod_slots=0 everything degenerates to the
        # flat per-host semantics.
        self._pod_slots = pod_slots
        self._pods = pod_tracker or pods_mod.PodTracker()
        self.registry = WorkerStateRegistry(self._on_barrier,
                                            reset_limit=reset_limit)
        self._lock = threading.Lock()
        self._cond = threading.Condition(self._lock)
        self._generation = 0
        self._assignments: List[hosts_mod.SlotInfo] = []
        self._workers: Dict[int, _WorkerProc] = {}
        self._shutdown = threading.Event()
        self._result: Optional[int] = None
        self._discovery_thread: Optional[threading.Thread] = None
        self._rendezvous_cb: Optional[Callable[[List[hosts_mod.SlotInfo],
                                                int], None]] = None
        # Cluster anomaly correlation (telemetry/anomaly.py): created
        # lazily on the first discovery tick that finds HVDT_EVENT_LOG
        # configured — cluster events (a pod-wide step-time shift is
        # ONE event) land in the driver's JSONL event log.
        self._cluster_anomalies = None
        # Online policy controller (horovod_tpu/control): bound lazily
        # on the first tick that finds HVDT_CONTROLLER set — the
        # zero-overhead contract (control.get_controller() is None
        # otherwise, and nothing below exists).
        self._controller = None
        self._controller_seq = 0

    # -- lifecycle ---------------------------------------------------------

    def start(self, rendezvous_cb=None) -> None:
        """rendezvous_cb(assignments, generation) publishes the new cluster
        spec (KV) before workers of that generation spawn."""
        self._rendezvous_cb = rendezvous_cb
        self._hm.update_available_hosts()
        self._discovery_thread = threading.Thread(
            target=self._discovery_loop, daemon=True, name="hvdt-elastic")
        self._discovery_thread.start()
        self._rendezvous()

    def stop(self) -> None:
        self._shutdown.set()
        with self._cond:
            self._cond.notify_all()

    def wait(self, timeout: Optional[float] = None) -> Optional[int]:
        """Block until the job finishes; returns the exit code."""
        deadline = (time.monotonic() + timeout) if timeout else None
        with self._cond:
            while self._result is None and not self._shutdown.is_set():
                remaining = None
                if deadline is not None:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        return None
                self._cond.wait(remaining if remaining else 1.0)
            return self._result

    # -- discovery ---------------------------------------------------------

    def _discovery_loop(self) -> None:
        while not self._shutdown.wait(self._interval):
            try:
                changed = self._hm.update_available_hosts()
            except Exception as e:   # discovery scripts may flake
                print(f"elastic: discovery failed: {e}", file=sys.stderr)
                continue
            if changed:
                self._notify_hosts_updated()
            self._poll_worker_registry()
            self._check_pod_stragglers()
            events = self._check_cluster_anomalies()
            self._check_controller(events)

    def _poll_worker_registry(self) -> None:
        """Feed KV-reported worker states (workers put
        /registry/<generation>/<rank> = READY|SUCCESS|FAILURE at commit
        points — the KV replaces the reference's in-worker RPC listener,
        ref: runner/elastic/worker.py WorkerNotificationService)."""
        if self._kv is None:
            return
        gen = self.generation
        prefix = f"/registry/{gen}/"
        with self._kv.lock:
            items = {k: v for k, v in self._kv.store.items()
                     if k.startswith(prefix)}
        for key, val in items.items():
            try:
                rank = int(key.rsplit("/", 1)[1])
            except ValueError:
                continue
            state = val.decode()
            if state == READY:
                self.registry.record_ready(rank)
            elif state == SUCCESS:
                self.registry.record_success(rank)
            elif state == FAILURE:
                self.registry.record_failure(rank)

    def record_ready(self, rank: int) -> None:
        """A live worker requests re-rendezvous (HostsUpdatedInterrupt or
        collective failure recovery in its training loop)."""
        self.registry.record_ready(rank)

    def resize(self, min_np: Optional[int] = None,
               max_np: Optional[int] = None) -> None:
        """Scale hook: adjust the world-size bounds mid-run.  The next
        rendezvous plans assignments against the new bounds; live
        workers are nudged through the hosts-updated channel so one
        lands at their next commit.  This is the driver-side seam the
        serving autoscaler's policy layer and the online controller
        (ROADMAP item 5) drive — resize decisions stay outside the
        rendezvous machinery itself."""
        with self._lock:
            if min_np is not None:
                self._min_np = max(1, int(min_np))
            if max_np is not None:
                self._max_np = max(self._min_np, int(max_np))
        self._notify_hosts_updated()

    def telemetry_snapshots(self):
        """Aggregate worker telemetry snapshots from the rendezvous KV
        (workers publish /telemetry/<rank> every
        HVDT_TELEMETRY_PUBLISH_S when HVDT_TELEMETRY is on).  Returns
        {rank: snapshot_dict}; empty when no KV or nothing published —
        the driver-side half of the observability subsystem
        (telemetry/exporter.collect_driver_snapshots).  Each snapshot
        carries the worker's pod id plus its kv_retries_total /
        kv_errors_total counters, so control-plane flakiness is visible
        fleet-wide from the driver; the snapshots also feed the
        pod-straggler eviction rung (_check_pod_stragglers)."""
        if self._kv is None:
            return {}
        from ...telemetry.exporter import collect_driver_snapshots

        return collect_driver_snapshots(self._kv)

    def trace_dumps(self):
        """Per-rank Chrome-trace dumps published to the rendezvous KV
        (workers publish /trace/<rank> when HVDT_TRACE_DIR is set —
        merged into one rank-as-pid trace by telemetry.trace.merge_dumps
        / write_merged; run_elastic writes trace_merged.json under
        --trace-dir).  Returns {rank: dump}; empty without a KV."""
        if self._kv is None:
            return {}
        from ...telemetry.trace import collect_server_dumps

        return collect_server_dumps(self._kv)

    def flight_recorder_events(self):
        """Per-rank collective flight-recorder event lists from the
        rendezvous KV (/flightrecorder/<rank>) — the raw material of
        telemetry.flight_recorder.analyze_desync."""
        if self._kv is None:
            return {}
        from ...telemetry.flight_recorder import collect_server_events

        return collect_server_events(self._kv)

    def telemetry_rollup(self):
        """Step-aligned fleet roll-up over the latest KV snapshots
        (telemetry/aggregate.rollup): per-pod median/p99 step time,
        cluster wire-bytes-by-axis, goodput series, worst pod.  Ranks
        publishing the old snapshot schema (no step id / time series)
        are skipped and counted, never failed."""
        snaps = self.telemetry_snapshots()
        if not snaps:
            return {}
        from ...telemetry import aggregate as _aggregate

        return _aggregate.rollup(snaps)

    def _check_cluster_anomalies(self):
        """Run the cluster anomaly rules over the fleet snapshots each
        discovery tick (active only when HVDT_EVENT_LOG names a driver-
        side event log — the zero-overhead gate).  Returns the events
        that newly fired this tick — the controller's input."""
        if self._kv is None:
            return []
        events = []
        try:
            from ...telemetry import anomaly as _anomaly

            if self._cluster_anomalies is None:
                if _anomaly.get_event_log() is None:
                    return []
                self._cluster_anomalies = _anomaly.ClusterAnomalyMonitor()
            snaps = self.telemetry_snapshots()
            if not snaps:
                return []
            events = self._cluster_anomalies.observe(snaps)
            for ev in events:
                print(f"elastic: anomaly {ev.get('kind')} "
                      f"({ev.get('scope')}): {ev.get('message')}",
                      file=sys.stderr)
        except Exception as e:   # detection must never sink the driver
            print(f"elastic: cluster anomaly check failed: {e}",
                  file=sys.stderr)
        return events

    # -- online policy controller (horovod_tpu/control) --------------------

    def _bind_controller(self, ctl) -> None:
        """Wire the controller's action kinds to the driver seams it
        acts through.  Comm-leg actions publish a KV override the
        workers' LegListener adopts at their next step boundary;
        membership actions ride the same paths the straggler rung and
        the serving autoscaler already use."""
        from ... import control as _control

        def _evict(action) -> bool:
            pod = str(action.param("pod") or "")
            if not pod:
                return False
            self._hm.blacklist_pod(pod)
            self._hm.update_available_hosts()
            self._notify_hosts_updated()
            return True

        def _resize(action) -> bool:
            self.resize(min_np=action.param("min_np"),
                        max_np=action.param("max_np"))
            return True

        def _scale(action) -> bool:
            if self._kv is None:
                return False
            from ... import fleet as _fleet

            target = int(action.param("target"))
            sched = _fleet.get_scheduler()
            if sched is not None:
                # A fleet scheduler owns /serve/target_replicas: the
                # controller's scale becomes a HINT through its
                # guardrails instead of a second writer on the key.
                return sched.hint_scale(target, source="controller",
                                        reason=action.reason)
            # No scheduler: write the seq-guarded doc directly — the
            # audited form, refused while a raw-int operator override
            # owns the key (the two-writers race regression).
            return _fleet.write_target(
                self._kv, target, writer="controller",
                reason=action.reason) is not None

        def _leg(action) -> bool:
            if self._kv is None:
                return False
            legs = _control.apply.legs_for_action(action)
            if not legs:
                return False
            self._controller_seq += 1
            return _control.apply.publish_legs(self._kv, legs,
                                               self._controller_seq)

        ctl.bind_appliers({
            "evict_pod": _evict, "resize": _resize,
            "scale_replicas": _scale, "flip_transport": _leg,
            "retune_bucket": _leg, "toggle_overlap": _leg,
            "toggle_zero": _leg,
        })

    def _check_controller(self, events) -> None:
        """One controller tick per discovery tick: feed the fresh
        anomaly events plus the fleet's deviation/step picture, let it
        verify pending actions and decide on the new ones."""
        try:
            from ... import control as _control

            ctl = _control.get_controller()
            if ctl is None:
                return
            if ctl is not self._controller:
                self._bind_controller(ctl)
                # Seed the geometry the pricer needs from the live
                # cluster picture.
                pods = {s.pod for s in self.assignments if s.pod}
                if pods:
                    ctl.state.pods = len(pods)
                if self._pod_slots:
                    ctl.state.pod_size = self._pod_slots
                    ctl.state.chips_per_pod = self._pod_slots
                self._controller = ctl
            snaps = self.telemetry_snapshots()
            deviation = None
            step = None
            step_s = None
            if snaps:
                ratios = [float(s.get("perf_deviation_ratio") or 0.0)
                          for s in snaps.values()]
                deviation = max(ratios) if any(ratios) else None
                steps = [int(s.get("step") or 0) for s in snaps.values()]
                step = max(steps) if steps else None
                from ...telemetry import aggregate as _aggregate

                means = _aggregate.recent_step_means(snaps)
                if means:
                    vals = sorted(means.values())
                    step_s = vals[(len(vals) - 1) // 2]
            ctl.tick(events or (), deviation_ratio=deviation,
                     observed_step_s=step_s, step=step)
        except Exception as e:   # the loop must never sink the driver
            print(f"elastic: controller tick failed: {e}",
                  file=sys.stderr)

    def _check_pod_stragglers(self) -> None:
        """The pod-granular escalation rung over the PR-5 straggler
        gauges: aggregate per-rank step-time medians from the telemetry
        snapshots into per-pod medians; a pod slower than threshold x
        the cross-pod median for HVDT_POD_STRAGGLER_EVICT consecutive
        windows is EVICTED — blacklisted (cooldown applies, so a
        recovered pod can rejoin) and the run resizes down to the
        remaining pod multiple instead of limping at the slow pod's
        pace."""
        if self._pods.evict_windows <= 0 or self._kv is None:
            return
        snaps = self.telemetry_snapshots()
        if not snaps or not self._pods.snapshots_fingerprint(snaps):
            return
        rank_pod = {s.rank: s.pod for s in self.assignments}
        by_pod: Dict[str, List[float]] = {}
        for rank, snap in snaps.items():
            ms = snap.get("step_time_p50_ms")
            pod = snap.get("pod") or rank_pod.get(rank)
            if ms and pod:
                by_pod.setdefault(pod, []).append(float(ms))
        medians = {p: sorted(v)[(len(v) - 1) // 2]
                   for p, v in by_pod.items()}
        for pod in self._pods.observe_step_medians(medians):
            print(f"elastic: pod {pod} evicted as straggler "
                  f"(median step {medians[pod]:.1f} ms over "
                  f"{self._pods.evict_windows} windows)", file=sys.stderr)
            self._hm.blacklist_pod(pod)
            self._hm.update_available_hosts()
            self._notify_hosts_updated()

    def _notify_hosts_updated(self) -> None:
        with self._cond:
            self._cond.notify_all()
            self._pending_updates += 1
            n = self._pending_updates
        # Publish so live workers see the membership change at their next
        # commit and exit for respawn (the KV replaces the reference's
        # in-worker notification RPC, runner/elastic/worker.py).
        if self._hosts_updated_cb is not None:
            self._hosts_updated_cb(n)

    def _usable_slots(self) -> int:
        """Slots assignable at pod granularity: whole same-size pods
        only, minus drained (preempted) pods — so the rendezvous wait
        doesn't end on a half-discovered pod it can't place."""
        return pods_mod.usable_slots(self._hm.current.hosts,
                                     self._pod_slots,
                                     self._pods.drained_pods())

    def wait_for_available_slots(self, min_np: int,
                                 timeout: float = 600.0) -> None:
        """(ref: driver.py:145) block until discovery reports >= min_np
        pod-assignable slots."""
        deadline = time.monotonic() + timeout
        with self._cond:
            while self._usable_slots() < min_np:
                if self._shutdown.is_set():
                    raise RuntimeError("driver shut down while waiting")
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError(
                        f"timed out waiting for {min_np} slots; discovered "
                        f"{self._usable_slots()}")
                self._cond.wait(min(remaining, self._interval))

    # -- rendezvous / spawn ------------------------------------------------

    def _rendezvous(self) -> None:
        # Recovery-budget attribution, driver side: the rendezvous phase
        # starts the moment a new generation is needed and ends when
        # every slot of the new world has been handed to a spawner.
        # Workers attribute their own boot restore/replay; the driver
        # owns the slot-wait + assignment + publish window.
        t0 = time.monotonic()
        self.wait_for_available_slots(self._min_np,
                                      timeout=self._elastic_timeout)
        with self._lock:
            self._generation += 1
            gen = self._generation
            self._assignments = pods_mod.plan_assignments(
                self._hm.current.hosts, self._min_np, self._max_np,
                pod_slots=self._pod_slots,
                exclude=self._pods.drained_pods())
            self.registry.reset(len(self._assignments))
        layout = pods_mod.pod_layout(self._assignments)
        print(f"elastic: rendezvous generation {gen}: "
              f"{len(self._assignments)} slots in {layout['num_pods']} "
              f"pod(s) x {layout['pod_size']} "
              f"(dcn={layout['mesh']['dcn']}, ici={layout['mesh']['ici']})",
              file=sys.stderr)
        if self._rendezvous_cb:
            self._rendezvous_cb(self._assignments, gen)
        for slot in self._assignments:
            self._start_worker(slot, gen)
        self.last_rendezvous_seconds = time.monotonic() - t0
        self.rendezvous_seconds_total = getattr(
            self, "rendezvous_seconds_total", 0.0) \
            + self.last_rendezvous_seconds
        if gen > 1:
            # Generation 1 is job boot, not recovery; later generations
            # are the rendezvous leg of a recovery and are printed so
            # scenario harnesses (and operators reading driver logs) can
            # audit the budget without scraping worker metrics.
            print(f"elastic: generation {gen} rendezvous took "
                  f"{self.last_rendezvous_seconds:.2f}s", file=sys.stderr)

    def _start_worker(self, slot: hosts_mod.SlotInfo, gen: int) -> None:
        def _run():
            try:
                code = self._spawn_fn(slot, gen)
            except Exception as e:
                print(f"elastic: worker {slot.rank} spawn error: {e}",
                      file=sys.stderr)
                code = 1
            self.record_exit(slot, gen, code)

        t = threading.Thread(target=_run, daemon=True,
                             name=f"hvdt-worker-{slot.rank}")
        with self._lock:
            self._workers[slot.rank] = _WorkerProc(slot, t, gen)
        t.start()

    def record_exit(self, slot: hosts_mod.SlotInfo, gen: int,
                    code: int) -> None:
        from ...resilience.preempt import PREEMPT_EXIT_CODE

        with self._lock:
            if gen != self._generation:
                return   # stale worker from a previous generation
        pod = slot.pod or self._hm.pod_of(slot.hostname)
        if code == RESTART_EXIT_CODE:
            # Worker observed a membership change and exited for respawn:
            # it is READY for the next rendezvous, not failed.
            self.registry.record_ready(slot.rank)
            return
        if code == PREEMPT_EXIT_CODE:
            # Clean preemption exit (resilience/preempt.py): the worker
            # checkpointed and its host is going away.  Preemption
            # reclaims whole slices, so ONE rank's grace-window exit
            # drains its entire pod: the next rendezvous won't place
            # workers on the pod's other hosts even while discovery
            # still lists them.  No blacklist, no failure count.
            if self._pods.drain(pod):
                print(f"elastic: pod {pod} draining (rank {slot.rank} "
                      f"preempted on {slot.hostname}, clean removal)",
                      file=sys.stderr)
            self.registry.record_ready(slot.rank)
            return
        if code == 0:
            self.registry.record_success(slot.rank)
        else:
            # Failed worker ⇒ suspect POD (ref: driver.py:297 exit
            # handling + discovery blacklist).  Exits of one pod's ranks
            # within HVDT_POD_EXIT_WINDOW_S are one correlated loss:
            # the first opens the pod-removal event and blacklists the
            # pod ONCE; the rest fold into it (no cooldown doubling, no
            # N independent recovery decisions).
            if self._pods.record_failure(pod):
                print(f"elastic: pod-removal event for pod {pod} "
                      f"(rank {slot.rank} on {slot.hostname} exited "
                      f"{code}); correlated exits within the window "
                      f"fold into this event", file=sys.stderr)
                self._hm.blacklist_pod(pod)
                self._hm.update_available_hosts()
            self.registry.record_failure(slot.rank)

    # -- barrier -----------------------------------------------------------

    def _on_barrier(self, states: Dict[str, set]) -> None:
        if states[READY]:
            if self.registry.reset_limit_reached():
                self._finish(1)
                return
            threading.Thread(target=self._rendezvous, daemon=True).start()
        elif states[FAILURE] and not states[READY]:
            if len(states[FAILURE]) >= len(self._assignments):
                self._finish(1)
            else:
                # Partial failure: survivors need a new, smaller rendezvous.
                threading.Thread(target=self._safe_rerendezvous,
                                 daemon=True).start()
        else:
            self._finish(0)

    def _safe_rerendezvous(self) -> None:
        try:
            self._rendezvous()
        except (TimeoutError, RuntimeError) as e:
            print(f"elastic: cannot re-rendezvous: {e}", file=sys.stderr)
            self._finish(1)

    def _finish(self, code: int) -> None:
        with self._cond:
            if self._result is None:
                self._result = code
            self._cond.notify_all()

    # -- introspection (tests) --------------------------------------------

    @property
    def generation(self) -> int:
        with self._lock:
            return self._generation

    @property
    def assignments(self) -> List[hosts_mod.SlotInfo]:
        with self._lock:
            return list(self._assignments)


def run_elastic(args) -> int:
    """CLI entry for ``hvdtrun --host-discovery-script ...``
    (ref: launch.py:621 _run_elastic → gloo_run.py:340)."""
    from ..launch import knob_env_for

    knob_env = knob_env_for(args)
    # The policy controller lives in THIS process (discovery loop), not
    # in the workers, so its knobs must reach the driver's own env —
    # knob_env is only forwarded into worker processes.
    for _k, _v in knob_env.items():
        if _k.startswith("HVDT_CONTROLLER") or _k == "HVDT_EVENT_LOG":
            os.environ[_k] = _v
    if knob_env.get("HVDT_CPU_OPERATIONS", "").lower() == "tcp":
        # The static rank->addr contract HVDT_TCP_ADDRS encodes cannot
        # survive elastic membership changes; reject up front instead of
        # letting workers crash on an empty address list mid-bootstrap.
        raise RuntimeError(
            "--cpu-operations tcp is not supported with elastic launch: "
            "the TCP socket mesh needs a static rank->host:port mapping. "
            "Use the default 'xla' host data plane for elastic jobs.")

    hm = HostManager.from_script(args.host_discovery_script,
                                 default_slots=args.slots_per_host)
    min_np = args.min_np or args.num_proc or 1
    max_np = args.max_np or args.num_proc or min_np

    server = RendezvousServer(secret=new_secret())
    port = server.start()
    addr = socket.gethostbyname(socket.gethostname())
    if getattr(args, "nics", None):
        from ..launch import _nic_addr

        addr = _nic_addr(args.nics.split(",")) or addr
    coordinator_port = args.coordinator_port

    pending_state = {"n": 0}

    def rendezvous_cb(slots: List[hosts_mod.SlotInfo], gen: int) -> None:
        import json as _json

        spec = "\n".join(
            f"{s.rank},{s.hostname},{s.local_rank},{s.cross_rank},"
            f"{s.size},{s.local_size},{s.cross_size},"
            f"{s.pod},{s.pod_index},{s.pod_rank}" for s in slots)
        server.put_local(f"/rendezvous/{gen}/spec", spec.encode())
        # Freeze the pending-updates counter as of this rendezvous so
        # generation-gen workers baseline against it (worker.py init):
        # membership changes during their boot window stay visible.
        server.put_local(f"/rendezvous/{gen}/pending_base",
                         str(pending_state["n"]).encode())
        # Two-level rendezvous: the (dcn, ici) pod layout next to the
        # flat spec — what a worker needs to build the hierarchical
        # mesh (parallel.mesh.pod_mesh_spec) whose cross-pod axis rides
        # the dcn transport policy.
        server.put_local(f"/rendezvous/{gen}/pods", _json.dumps(
            pods_mod.pod_layout(slots)).encode())
        server.put_local("/rendezvous/version", str(gen).encode())

    def hosts_updated_cb(n: int) -> None:
        pending_state["n"] = n
        server.put_local("/rendezvous/pending", str(n).encode())

    def spawn_fn(slot: hosts_mod.SlotInfo, gen: int) -> int:
        from ..launch import _build_command

        coord = slot.hostname if slot.rank != slot.rank else slot.hostname
        base_env = {
            "HVDT_RENDEZVOUS_ADDR": addr,
            "HVDT_RENDEZVOUS_PORT": str(port),
            "HVDT_SECRET": server.secret.hex(),
            "HVDT_COORDINATOR_ADDR": f"{coord}:{coordinator_port}",
            "HVDT_ELASTIC": "1",
            "HVDT_GENERATION": str(gen),
            **knob_env,
        }
        cmd, env = _build_command(args, slot, base_env, args.command)
        prefix = f"[{slot.rank}]" if args.verbose else ""
        return safe_execute(cmd, env=env, prefix=prefix)

    def _int_knob(name: str) -> int:
        raw = knob_env.get(name) or os.environ.get(name) or "0"
        try:
            return int(raw)
        except ValueError:
            return 0

    tracker = pods_mod.PodTracker(
        evict_windows=_int_knob("HVDT_POD_STRAGGLER_EVICT") or None)
    # kv_server wires the driver-side KV consumers: worker state
    # publishes (/registry), telemetry snapshot aggregation, and the
    # pod-straggler eviction rung those snapshots feed.
    driver = ElasticDriver(hm, min_np, max_np, spawn_fn,
                           reset_limit=args.reset_limit,
                           kv_server=server,
                           hosts_updated_cb=hosts_updated_cb,
                           elastic_timeout=getattr(args, "elastic_timeout",
                                                   600.0),
                           pod_slots=_int_knob("HVDT_POD_SIZE"),
                           pod_tracker=tracker)
    try:
        driver.start(rendezvous_cb)
        code = driver.wait()
        return code if code is not None else 1
    finally:
        driver.stop()
        trace_dir = knob_env.get("HVDT_TRACE_DIR") or \
            os.environ.get("HVDT_TRACE_DIR", "")
        if trace_dir:
            # Driver-side merge (hvdtrun --trace-dir): pull every rank's
            # published dump from the KV before the server dies and emit
            # the single rank-as-pid Chrome trace.
            try:
                from ...telemetry.trace import write_merged

                merged = write_merged(server, trace_dir)
                if merged:
                    print(f"elastic: merged trace written to {merged}",
                          file=sys.stderr)
            except Exception as e:
                print(f"elastic: trace merge failed: {e}", file=sys.stderr)
        server.stop()
