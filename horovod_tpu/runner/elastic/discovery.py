"""Host discovery + blacklisting for elastic mode.

Re-conception of ref: runner/elastic/discovery.py:1-186 (HostManager,
HostDiscoveryScript, blacklisting).  The discovery source is a user
executable printing one "host[:slots]" line per available host — on TPU
this typically wraps ``gcloud compute tpus tpu-vm list`` or a queued
-resource poll.
"""

from __future__ import annotations

import subprocess
import threading
import time
from typing import Callable, Dict, List, Optional, Set

from ...common import config
from ..hosts import HostInfo

__all__ = ["HostState", "HostManager", "DiscoveredHosts"]


class HostState:
    """Per-host blacklist state (ref: discovery.py HostState), with an
    optional cooldown (ref: the reference's cooldown_range blacklisting).

    ``HVDT_ELASTIC_BLACKLIST_COOLDOWN_S`` = 0 (default) keeps the
    permanent blacklist.  A positive cooldown makes a failed host
    *suspect* instead of dead: it re-enters discovery after the cooldown,
    which doubles per repeated failure (capped at 8x) so a genuinely bad
    host converges toward exclusion while a transient crash — the common
    case on preemptible fleets, and the only host of a small job — can
    rejoin."""

    def __init__(self, cooldown_s: Optional[float] = None) -> None:
        if cooldown_s is None:
            cooldown_s = config.get_float("HVDT_ELASTIC_BLACKLIST_COOLDOWN_S")
        self._cooldown_s = cooldown_s
        self._failures = 0
        self._until: Optional[float] = None   # None = not blacklisted
        self._lock = threading.Lock()

    def blacklist(self) -> None:
        with self._lock:
            self._failures += 1
            if self._cooldown_s <= 0:
                self._until = float("inf")
            else:
                backoff = min(2.0 ** (self._failures - 1), 8.0)
                self._until = time.monotonic() + self._cooldown_s * backoff

    @property
    def failures(self) -> int:
        with self._lock:
            return self._failures

    @property
    def is_blacklisted(self) -> bool:
        with self._lock:
            return self._until is not None and time.monotonic() < self._until


class DiscoveredHosts:
    """Immutable snapshot of discovery output minus blacklisted hosts."""

    def __init__(self, hosts: List[HostInfo]):
        self.hosts = hosts

    @property
    def available_slots(self) -> int:
        return sum(h.slots for h in self.hosts)

    def host_names(self) -> List[str]:
        return [h.hostname for h in self.hosts]

    def __eq__(self, other) -> bool:
        return isinstance(other, DiscoveredHosts) and \
            self.hosts == other.hosts

    def __repr__(self) -> str:
        return f"DiscoveredHosts({self.hosts})"


class HostManager:
    """Runs the discovery function, applies the blacklist, reports diffs
    (ref: discovery.py HostManager.update_available_hosts).

    Blacklisting is **pod-granular**: a pod (declared via the discovery
    script's ``@pod`` column, ``host[:slots][@pod]``) shares one
    :class:`HostState`, so one correlated pod loss costs one cooldown
    clock — N ranks of a dying slice must not double the cooldown N
    times.  Hosts with no declared pod key their state by hostname,
    which is exactly the PR-4 per-host behavior."""

    def __init__(self, discover: Callable[[], List[HostInfo]],
                 default_slots: int = 1):
        self._discover = discover
        self._default_slots = default_slots
        self._states: Dict[str, HostState] = {}   # keyed per pod
        self._pod_of: Dict[str, str] = {}         # hostname -> pod key
        self.current = DiscoveredHosts([])

    @classmethod
    def from_script(cls, script: str, default_slots: int = 1
                    ) -> "HostManager":
        def discover() -> List[HostInfo]:
            out = subprocess.run(
                script, shell=True, capture_output=True, text=True,
                timeout=60)
            if out.returncode != 0:
                raise RuntimeError(
                    f"discovery script failed ({out.returncode}): "
                    f"{out.stderr.strip()}")
            hosts = []
            for line in out.stdout.splitlines():
                line = line.strip()
                if line:
                    h = HostInfo.from_string(line)
                    if h.slots == 1 and ":" not in line:
                        h = HostInfo(h.hostname, default_slots, h.pod)
                    hosts.append(h)
            return hosts
        return cls(discover, default_slots)

    def pod_of(self, hostname: str) -> str:
        """The blacklist key for ``hostname``: its declared pod, or the
        hostname itself when no pod was declared."""
        return self._pod_of.get(hostname, hostname)

    def blacklist(self, hostname: str) -> None:
        self.blacklist_pod(self.pod_of(hostname))

    def blacklist_pod(self, pod: str) -> None:
        self._states.setdefault(pod, HostState()).blacklist()

    def is_blacklisted(self, hostname: str) -> bool:
        return self.is_pod_blacklisted(self.pod_of(hostname))

    def is_pod_blacklisted(self, pod: str) -> bool:
        st = self._states.get(pod)
        return st is not None and st.is_blacklisted

    def pod_failures(self, pod: str) -> int:
        """Blacklist entries recorded against ``pod`` — the audit the
        pod-removal correlation is judged by (one correlated pod loss
        must cost exactly one entry)."""
        st = self._states.get(pod)
        return st.failures if st is not None else 0

    def update_available_hosts(self) -> bool:
        """Re-run discovery; returns True if the usable host set changed."""
        raw = self._discover()
        for h in raw:
            if h.pod:
                self._pod_of[h.hostname] = h.pod
        usable = [h for h in raw if not self.is_blacklisted(h.hostname)]
        snapshot = DiscoveredHosts(usable)
        changed = snapshot != self.current
        self.current = snapshot
        return changed
