"""Elastic launcher: discovery, driver, worker registration.

Re-conception of ref: runner/elastic/ (driver.py, discovery.py,
registration.py, worker.py — SURVEY.md §2.5, §3.4, §5.3) for preemptible
TPU VMs: the driver discovers hosts with a user script, recomputes slot
assignments on change, publishes them to the rendezvous KV with a bumped
version, and workers re-rendezvous (re-initialize JAX distributed) around
the in-training State commit/restore machine (horovod_tpu.elastic).
"""

from .discovery import HostManager, HostState  # noqa: F401
from .driver import ElasticDriver, run_elastic  # noqa: F401
from .registration import WorkerStateRegistry  # noqa: F401
