"""Pod-granular topology planning + failure correlation for the elastic
driver.

At multi-pod scale the dominant failure mode is *correlated*: a pod (TPU
slice) going away takes every one of its hosts at once ("Scale MLPerf-0.6
models on Google TPU-v3 Pods" / "Exploring the limits of Concurrency in
ML Training on Google TPUs", PAPERS.md).  A driver that models a flat
host set sees N unrelated crashes and makes N independent
blacklist/recovery decisions; this module gives it the pod view:

* :func:`group_pods` — hosts → ordered pods, from the discovery
  script's ``@pod`` column, or chunked to ``HVDT_POD_SIZE`` slots, or
  (default) one pod per host — which degenerates to the PR-4 host
  semantics, so single-host jobs behave exactly as before.
* :func:`plan_assignments` — whole-pod slot assignment: the world size
  is always a multiple of the pod slot size, ranks are contiguous
  within a pod (the layout the hierarchical transport policies assume:
  pod-local ranks ride ICI, cross-pod hops ride DCN), and every slot
  carries the two-level ``(dcn, ici)`` contract
  (``HVDT_NUM_PODS``/``HVDT_POD_SIZE`` → ``parallel.mesh.pod_mesh_spec``).
* :class:`PodTracker` — the driver-side failure correlator: exits of one
  pod's ranks within ``HVDT_POD_EXIT_WINDOW_S`` collapse into ONE
  pod-removal event (one blacklist entry, one cooldown clock),
  preemption of any rank drains the whole pod, and per-pod step-time
  medians from the telemetry snapshots feed the straggler-eviction rung
  (``HVDT_POD_STRAGGLER_EVICT`` windows over
  ``HVDT_STRAGGLER_THRESHOLD`` → evict).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Tuple

from ...common import config
from ...common.logging_util import get_logger
from ..hosts import HostInfo, SlotInfo, get_host_assignments

__all__ = ["Pod", "group_pods", "plan_assignments", "usable_slots",
           "pod_layout", "PodTracker"]

log = get_logger(__name__)


@dataclasses.dataclass(frozen=True)
class Pod:
    """One pod: an ordered host group that joins/leaves as a unit."""
    name: str
    hosts: Tuple[HostInfo, ...]

    @property
    def slots(self) -> int:
        return sum(h.slots for h in self.hosts)


def group_pods(hosts: Sequence[HostInfo],
               pod_slots: int = 0) -> List[Pod]:
    """Group discovered hosts into pods, preserving discovery order.

    Precedence: a host's declared ``@pod`` column wins; with
    ``pod_slots`` > 0, undeclared hosts are chunked (in order) into pods
    of exactly that many slots (a partial trailing chunk forms an
    *incomplete* pod — selection skips it until the rest of the slice is
    discovered); otherwise each undeclared host is its own pod, keyed by
    hostname — the flat PR-4 behavior.
    """
    pods: Dict[str, List[HostInfo]] = {}
    order: List[str] = []
    chunk: List[HostInfo] = []
    chunk_slots = 0
    chunk_idx = 0

    def flush_chunk():
        nonlocal chunk, chunk_slots, chunk_idx
        if chunk:
            name = f"pod{chunk_idx}"
            chunk_idx += 1
            pods[name] = list(chunk)
            order.append(name)
            chunk, chunk_slots = [], 0

    for h in hosts:
        if h.pod:
            if h.pod not in pods:
                pods[h.pod] = []
                order.append(h.pod)
            pods[h.pod].append(h)
        elif pod_slots > 0:
            chunk.append(h)
            chunk_slots += h.slots
            if chunk_slots >= pod_slots:
                flush_chunk()
        else:
            name = h.hostname
            if name not in pods:
                pods[name] = []
                order.append(name)
            pods[name].append(h)
    flush_chunk()
    return [Pod(name, tuple(pods[name])) for name in order]


def _eligible(pods: List[Pod], pod_slots: int,
              exclude: Optional[set] = None) -> Tuple[List[Pod], int]:
    """Filter to same-size pods eligible for assignment.

    The uniform pod slot count is ``pod_slots`` when set, else the
    maximum observed (a pod never has MORE slots than the real slice, so
    a smaller group is a partially-discovered or degraded pod — skipped,
    with a log line, rather than allowed to break the world-size-
    multiple-of-pod-size invariant).  Heterogeneous per-host "pods"
    (nothing declared, no pod size) keep the flat legacy semantics via
    ``plan_assignments``'s fallback, not this path.
    """
    exclude = exclude or set()
    pods = [p for p in pods if p.name not in exclude]
    if not pods:
        return [], 0
    size = pod_slots if pod_slots > 0 else max(p.slots for p in pods)
    kept = [p for p in pods if p.slots == size]
    skipped = [p.name for p in pods if p.slots != size]
    if skipped:
        log.info("elastic: skipping incomplete pods %s (expected %d "
                 "slots each)", skipped, size)
    return kept, size


def usable_slots(hosts: Sequence[HostInfo], pod_slots: int = 0,
                 exclude: Optional[set] = None) -> int:
    """Slots available at pod granularity (whole same-size pods only) —
    what :meth:`ElasticDriver.wait_for_available_slots` should count so
    the wait doesn't end on a half-discovered pod."""
    pods = group_pods(hosts, pod_slots)
    if not _pods_declared(hosts, pod_slots):
        return sum(h.slots for h in hosts)
    kept, size = _eligible(pods, pod_slots, exclude)
    return len(kept) * size


def _pods_declared(hosts: Sequence[HostInfo], pod_slots: int) -> bool:
    return pod_slots > 0 or any(h.pod for h in hosts)


def plan_assignments(hosts: Sequence[HostInfo], min_np: int,
                     max_np: int = 0, pod_slots: int = 0,
                     exclude: Optional[set] = None) -> List[SlotInfo]:
    """Whole-pod slot assignment (the pod-granular
    ``get_host_assignments``).

    Selects the largest pod count whose total slots fit ``max_np``
    (never fewer than ``min_np`` rounded up to a pod multiple), assigns
    contiguous ranks pod-by-pod, and annotates every slot with the
    two-level contract.  Without declared pods (and no ``pod_slots``)
    this defers to the flat assignment and annotates each host as its
    own pod, so the driver's pod logic is uniform either way.
    """
    if not _pods_declared(hosts, pod_slots):
        flat = get_host_assignments(hosts, min_np, max_np)
        return _annotate_per_host(flat)
    pods = group_pods(hosts, pod_slots)
    kept, size = _eligible(pods, pod_slots, exclude)
    total = len(kept) * size
    if total < min_np:
        raise ValueError(
            f"requested {min_np} processes but only {total} slots "
            f"available in {len(kept)} complete pods "
            f"(pod size {size or '?'})")
    want_pods = max(1, min(len(kept), (max_np or min_np) // size))
    if want_pods * size < min_np:
        want_pods = -(-min_np // size)   # ceil to a pod multiple
    chosen = kept[:want_pods]
    flat = get_host_assignments(
        [h for p in chosen for h in p.hosts], want_pods * size)
    out: List[SlotInfo] = []
    for slot in flat:
        pi, pr = divmod(slot.rank, size)
        out.append(dataclasses.replace(
            slot, pod=chosen[pi].name, pod_index=pi, pod_rank=pr,
            num_pods=want_pods, pod_size=size))
    return out


def _annotate_per_host(slots: List[SlotInfo]) -> List[SlotInfo]:
    """Flat assignment with each host as its own pod (degenerate case:
    pod semantics == the PR-4 host semantics)."""
    return [dataclasses.replace(
        s, pod=s.hostname, pod_index=s.cross_rank, pod_rank=s.local_rank,
        num_pods=s.cross_size, pod_size=s.local_size) for s in slots]


def pod_layout(slots: Sequence[SlotInfo]) -> Dict[str, object]:
    """JSON-able two-level layout summary published to the rendezvous KV
    (``/rendezvous/<gen>/pods``) next to the flat spec: what a worker —
    or an operator scraping the KV — needs to build the ``(dcn, ici)``
    mesh (``parallel.mesh.pod_mesh_spec``)."""
    if not slots:
        return {"num_pods": 0, "pod_size": 0, "pods": []}
    pods: List[Dict[str, object]] = []
    for s in slots:
        if not pods or pods[-1]["name"] != s.pod:
            pods.append({"name": s.pod, "ranks": []})
        pods[-1]["ranks"].append(s.rank)
    return {"num_pods": slots[0].num_pods or len(pods),
            "pod_size": slots[0].pod_size or len(slots) // max(1, len(pods)),
            "mesh": {"dcn": slots[0].num_pods or len(pods),
                     "ici": slots[0].pod_size
                     or len(slots) // max(1, len(pods))},
            "pods": pods}


class PodTracker:
    """Driver-side pod state: exit correlation, preemption drains, and
    the straggler-eviction ladder."""

    def __init__(self,
                 exit_window_s: Optional[float] = None,
                 drain_grace_s: Optional[float] = None,
                 evict_windows: Optional[int] = None,
                 threshold: Optional[float] = None):
        self._exit_window_s = (
            exit_window_s if exit_window_s is not None
            else config.get_float("HVDT_POD_EXIT_WINDOW_S"))
        self._drain_grace_s = (
            drain_grace_s if drain_grace_s is not None
            else config.get_float("HVDT_POD_DRAIN_GRACE_S"))
        self.evict_windows = (
            evict_windows if evict_windows is not None
            else config.get_int("HVDT_POD_STRAGGLER_EVICT"))
        self.threshold = (
            threshold if threshold is not None
            else config.get_float("HVDT_STRAGGLER_THRESHOLD"))
        self._lock = threading.Lock()
        self._failure_events: Dict[str, float] = {}   # pod -> opened at
        self._drained: Dict[str, float] = {}          # pod -> drained at
        self._slow_windows: Dict[str, int] = {}       # pod -> consecutive
        self._last_fingerprint: Optional[tuple] = None
        self.removal_events = 0   # audit: collapsed pod-removal count

    # -- exit correlation ---------------------------------------------------

    def record_failure(self, pod: str, now: Optional[float] = None) -> bool:
        """Record one rank's failure exit for ``pod``.  Returns True when
        this OPENS a pod-removal event — the caller blacklists the pod
        exactly once; the pod's remaining ranks falling over inside the
        window are folded into the same event (no extra blacklist entry,
        no cooldown doubling for one correlated loss)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            opened = self._failure_events.get(pod)
            if opened is not None and now - opened < self._exit_window_s:
                return False
            self._failure_events[pod] = now
            self.removal_events += 1
            return True

    # -- preemption drains --------------------------------------------------

    def drain(self, pod: str, now: Optional[float] = None) -> bool:
        """Mark ``pod`` draining (a rank took the clean preemption exit:
        the platform is reclaiming the whole slice, so the next
        rendezvous must not re-place workers on its other hosts even if
        discovery still lists them).  Returns True the first time."""
        now = time.monotonic() if now is None else now
        with self._lock:
            fresh = pod not in self._drained
            self._drained[pod] = now
            return fresh

    def drained_pods(self, now: Optional[float] = None) -> set:
        """Pods currently excluded from assignment.  Drains expire after
        ``HVDT_POD_DRAIN_GRACE_S`` — if the platform never reclaims the
        hosts, the pod becomes placeable again rather than stranded."""
        now = time.monotonic() if now is None else now
        with self._lock:
            self._drained = {p: t for p, t in self._drained.items()
                             if now - t < self._drain_grace_s}
            return set(self._drained)

    # -- straggler eviction -------------------------------------------------

    def observe_step_medians(self, pod_medians: Dict[str, float]
                             ) -> List[str]:
        """Feed one window of per-pod median step times (driver-side,
        from the aggregated telemetry snapshots).  A pod whose median
        exceeds ``threshold`` x the cross-pod median for
        ``evict_windows`` consecutive windows is returned for eviction
        (at most once per streak).  Empty unless the rung is armed."""
        if self.evict_windows <= 0 or len(pod_medians) < 2:
            return []
        ordered = sorted(pod_medians.values())
        # Lower median, matching telemetry/straggler.py: with half the
        # pods slow the upper median can BE the straggler.
        baseline = ordered[(len(ordered) - 1) // 2]
        if baseline <= 0:
            return []
        evict: List[str] = []
        with self._lock:
            for pod, med in pod_medians.items():
                if med / baseline > self.threshold:
                    n = self._slow_windows.get(pod, 0) + 1
                    self._slow_windows[pod] = n
                    if n == self.evict_windows:
                        evict.append(pod)
                else:
                    self._slow_windows.pop(pod, None)
        return evict

    def snapshots_fingerprint(self, snaps: Dict[int, dict]) -> bool:
        """True when ``snaps`` carries NEW step data since the last call
        — the discovery loop ticks every second, but a straggler window
        should only be counted when workers actually published fresh
        step statistics."""
        fp = tuple(sorted((r, s.get("steps")) for r, s in snaps.items()))
        with self._lock:
            if fp == self._last_fingerprint:
                return False
            self._last_fingerprint = fp
            return True
