"""Worker-side host-update notification.

Re-conception of ref: runner/elastic/worker.py:1-119
(WorkerNotificationService/Manager — an RPC listener inside the worker).
TPU-native simplification: workers *poll* the rendezvous KV's
``/rendezvous/version`` key at commit points; a version newer than the
worker's generation means the driver re-keyed the cluster ⇒
``HostsUpdatedInterrupt`` (consumed by horovod_tpu.elastic.run).
"""

from __future__ import annotations

import os
import threading
import time
from typing import Optional

from ...common.exceptions import HostsUpdatedInterrupt
from ...common.logging_util import get_logger
from ..http_kv import KVClient

__all__ = ["WorkerNotificationManager"]

log = get_logger(__name__)

# Consecutive failed KV polls before the worker warns that it is flying
# blind on membership changes (each poll failure is individually benign —
# commit-point polling retries — but a long streak means rendezvous loss).
_POLL_FAIL_WARN_STREAK = 10


class WorkerNotificationManager:
    def __init__(self, client: Optional[KVClient] = None,
                 generation: Optional[int] = None):
        self._client = client
        self._generation = generation
        self._lock = threading.Lock()
        self._pending = False
        self._latest: Optional[int] = None
        self._last_pending: Optional[int] = None
        self._poll_failures = 0   # consecutive; reset on any success

    def init(self) -> None:
        if self._client is None and "HVDT_RENDEZVOUS_ADDR" in os.environ:
            self._client = KVClient.from_env()
        if self._generation is None:
            self._generation = int(os.environ.get("HVDT_GENERATION", 0))
        # Baseline the pending-updates counter: host changes that led to
        # OUR generation's rendezvous are already accounted for.  Prefer
        # the generation-scoped base the driver froze AT our rendezvous
        # (/rendezvous/<gen>/pending_base): baselining on the *current*
        # counter instead would swallow any membership change that lands
        # between our spawn and our first commit — e.g. a blacklisted
        # pod rejoining after cooldown while this generation is still
        # booting, which must trigger a scale-up, not be ignored.
        base = None
        if self._client is not None:
            try:
                raw = self._client.get(
                    f"/rendezvous/{self._generation}/pending_base")
            except (ConnectionError, OSError):
                raw = None
            if raw is not None:
                base = int(raw)
        self._last_pending = base if base is not None \
            else self._read_pending()

    def _read_pending(self) -> int:
        if self._client is None:
            return 0
        try:
            raw = self._client.get("/rendezvous/pending")
        except (ConnectionError, OSError):
            return 0
        return int(raw) if raw is not None else 0

    def poll(self) -> bool:
        """True when the driver published a newer generation OR a pending
        membership change (host added/removed since our rendezvous).

        A failed poll is individually benign (the next commit retries),
        but a long streak means the worker is blind to membership changes
        — warn once per streak so rendezvous loss is visible in logs."""
        if self._client is None:
            return False
        try:
            raw = self._client.get("/rendezvous/version")
        except (ConnectionError, OSError) as e:
            self._poll_failures += 1
            if self._poll_failures == _POLL_FAIL_WARN_STREAK:
                log.warning(
                    "elastic: %d consecutive rendezvous-KV poll failures "
                    "(last: %r) — membership changes are not being "
                    "observed", self._poll_failures, e)
            return False
        self._poll_failures = 0
        with self._lock:
            if raw is not None:
                version = int(raw)
                if version > (self._generation or 0):
                    self._latest = version
                    self._pending = True
            pending_now = self._read_pending()
            if pending_now > (self._last_pending or 0):
                self._last_pending = pending_now
                self._pending = True
            return self._pending

    def check_for_updates(self) -> None:
        """Raise HostsUpdatedInterrupt when a newer generation exists
        (called from State.commit — ref: common/elastic.py:73-97).

        Adopts the observed version as the new generation before raising,
        so after the re-rendezvous the next commits don't re-trigger on the
        same version (the env's HVDT_GENERATION is stale by then)."""
        if self.poll():
            with self._lock:
                self._pending = False
                if self._latest is not None:
                    self._generation = self._latest
            raise HostsUpdatedInterrupt()
