"""CLI-flag / YAML-config / env translation for the launcher.

Re-conception of ref: runner/common/util/config_parser.py:1-202 +
runner/launch.py:242-527 for the HVDT knob registry: every runtime knob
(fusion, cycle, cache, autotune, timeline, stall check, host data plane,
logging) is settable from

  1. a CLI flag on ``hvdtrun``            (highest precedence)
  2. the caller's environment             (HVDT_*)
  3. a ``--config-file`` YAML             (sections below)
  4. the knob's built-in default          (common/config.py)

and the launcher forwards the result to every worker as ``HVDT_*`` env —
the same precedence order the reference implements by writing CLI/file
values into the env it hands to workers.

YAML shape (mirrors the reference's config sections)::

    params:
      fusion_threshold_mb: 32
      cycle_time_ms: 3.5
      cache_capacity: 2048
    autotune:
      enabled: true
      log_file: /tmp/autotune.csv
      warmup_samples: 3
      steps_per_sample: 10
      bayes_opt_max_samples: 20
      gaussian_process_noise: 0.8
    timeline:
      filename: /tmp/timeline.json
      mark_cycles: true
    stall_check:
      disabled: false
      warning_time_seconds: 60
      shutdown_time_seconds: 0
    resilience:
      async_ckpt: true
      peer_store: true
      ckpt_snapshot_budget_s: 1.0
    elastic:
      pod_size: 4
      pod_straggler_evict: 3
    controller:
      enabled: on
      cooldown_s: 60.0
      recovery_window: 3
      max_actions: 8
    fleet:
      enabled: on
      cooldown_s: 60.0
      enter_ratio: 1.2
      exit_ratio: 1.05
      backfill_ratio: 0.5
      recovery_window: 3
      max_moves: 0
      min_train_pods: 1
    telemetry:
      enabled: true
      metrics_port: 9090
      straggler_window: 64
      trace_dir: /tmp/hvdt-trace
      flight_recorder: true
    serve:
      replicas: 2
      max_replicas: 4
      autoscale: true
      slo_p99_ms: 250
      heartbeat_s: 2.0
    library_options:
      cpu_operations: tcp
      tcp_port_stride: 128
      compilation_cache_dir: /var/cache/hvdt-xla
    logging:
      level: info
      hide_timestamp: false
"""

from __future__ import annotations

import argparse
import dataclasses
from typing import Any, Callable, Dict, List, Optional

__all__ = ["KNOB_FLAGS", "add_knob_arguments", "load_config_file",
           "apply_config_file", "env_from_args"]


@dataclasses.dataclass(frozen=True)
class _Flag:
    """One CLI flag ↔ one HVDT env var ↔ one YAML (section, key)."""
    flag: str                 # e.g. "--fusion-threshold-mb"
    dest: str                 # argparse dest
    env: str                  # HVDT_* var the value is forwarded as
    section: str              # YAML section
    key: str                  # YAML key within the section
    help: str
    type: Callable = str
    is_bool: bool = False     # store_true flag
    to_env: Callable[[Any], str] = staticmethod(lambda v: str(v))


def _mb_to_bytes(v) -> str:
    return str(int(float(v) * 1024 * 1024))


def _bool_env(v) -> str:
    return "1" if v else "0"


def _on_off_env(v) -> str:
    return "on" if v else "off"


KNOB_FLAGS: List[_Flag] = [
    # --- params (ref: config_parser.py set_args_from_config 'params') ---
    _Flag("--fusion-threshold-mb", "fusion_threshold_mb",
          "HVDT_FUSION_THRESHOLD", "params", "fusion_threshold_mb",
          "Tensor-fusion bucket size in MB.", type=float,
          to_env=_mb_to_bytes),
    _Flag("--cycle-time-ms", "cycle_time_ms", "HVDT_CYCLE_TIME",
          "params", "cycle_time_ms",
          "Eager background-cycle time in ms.", type=float),
    _Flag("--cache-capacity", "cache_capacity", "HVDT_CACHE_CAPACITY",
          "params", "cache_capacity",
          "Response-cache capacity.", type=int),
    _Flag("--overlap", "overlap", "HVDT_OVERLAP", "params", "overlap",
          "Overlapped gradient exchange on every worker (ops/overlap.py):"
          " reverse-topological bucket schedule with collectives issued "
          "as each segment's grads exist, pipelined int8 wire, fused-"
          "update latency hiding.", is_bool=True, to_env=_on_off_env),
    _Flag("--xla-latency-hiding", "xla_latency_hiding",
          "HVDT_XLA_LATENCY_HIDING", "params", "xla_latency_hiding",
          "XLA latency-hiding / async-collective-fusion flags "
          "(auto|on|off; ridden via LIBTPU_INIT_ARGS, engaged in "
          "hvd.init())."),
    _Flag("--transport", "transport", "HVDT_TRANSPORT", "params",
          "transport",
          "Per-mesh-axis transport policy on every worker "
          "(horovod_tpu/transport): axis:algorithm:wire[:threshold] "
          "entries, e.g. 'ici:ring:f32:64M,dcn:tree:int8:8M', or "
          "'auto' for the topology-derived default.  Multi-axis "
          "reduce groups then run the hierarchical allreduce "
          "(fast-axis reduce-scatter -> slow-axis shard exchange -> "
          "allgather); workers validate the grammar in hvd.init()."),
    _Flag("--zero", "zero", "HVDT_ZERO", "params", "zero",
          "ZeRO state-sharding stage on every worker (ops/zero.py): "
          "grads (reduce-scatter + allgather wire split), states "
          "(sharded optimizer moments, shard-local fused updates, "
          "parameter-delta allgather — optimizer HBM ~1/n), or params "
          "(parameters sharded between steps, gathered on demand).  "
          "Workers validate the stage in hvd.init()."),
    _Flag("--remat", "remat", "HVDT_REMAT", "params", "remat",
          "Activation rematerialization for the transformer block "
          "(none|full|dots): jax.checkpoint policy applied by "
          "models.remat_from_env — the memory-for-MFU trade next to "
          "--zero ('dots' falls back to 'full' on jax builds without "
          "the policy)."),
    # --- autotune ---
    _Flag("--autotune", "autotune", "HVDT_AUTOTUNE", "autotune", "enabled",
          "Enable Bayesian autotuning of fusion knobs.", is_bool=True,
          to_env=_bool_env),
    _Flag("--autotune-log-file", "autotune_log_file", "HVDT_AUTOTUNE_LOG",
          "autotune", "log_file", "CSV log for autotune samples."),
    _Flag("--autotune-warmup-samples", "autotune_warmup_samples",
          "HVDT_AUTOTUNE_WARMUP_SAMPLES", "autotune", "warmup_samples",
          "Autotune warmup discard count.", type=int),
    _Flag("--autotune-steps-per-sample", "autotune_steps_per_sample",
          "HVDT_AUTOTUNE_STEPS_PER_SAMPLE", "autotune", "steps_per_sample",
          "Steps per autotune sample.", type=int),
    _Flag("--autotune-bayes-opt-max-samples", "autotune_bayes_opt_max_samples",
          "HVDT_AUTOTUNE_BAYES_OPT_MAX_SAMPLES", "autotune",
          "bayes_opt_max_samples", "Max Bayesian-optimizer samples.",
          type=int),
    _Flag("--autotune-gaussian-process-noise", "autotune_gp_noise",
          "HVDT_AUTOTUNE_GAUSSIAN_PROCESS_NOISE", "autotune",
          "gaussian_process_noise", "GP noise alpha.", type=float),
    # --- timeline ---
    _Flag("--timeline-filename", "timeline_filename", "HVDT_TIMELINE",
          "timeline", "filename",
          "Write Chrome-tracing timeline JSON to this path."),
    _Flag("--timeline-mark-cycles", "timeline_mark_cycles",
          "HVDT_TIMELINE_MARK_CYCLES", "timeline", "mark_cycles",
          "Mark background cycles in the timeline.", is_bool=True,
          to_env=_bool_env),
    # --- stall check ---
    _Flag("--no-stall-check", "no_stall_check", "HVDT_STALL_CHECK_DISABLE",
          "stall_check", "disabled", "Disable the stall inspector.",
          is_bool=True, to_env=_bool_env),
    _Flag("--stall-check-warning-time-seconds", "stall_warning_time",
          "HVDT_STALL_CHECK_TIME_SECONDS", "stall_check",
          "warning_time_seconds", "Stall warning threshold.", type=int),
    _Flag("--stall-check-shutdown-time-seconds", "stall_shutdown_time",
          "HVDT_STALL_SHUTDOWN_TIME_SECONDS", "stall_check",
          "shutdown_time_seconds", "Stall abort threshold (0 = never).",
          type=int),
    _Flag("--stall-abort-time-seconds", "stall_abort_time",
          "HVDT_STALL_ABORT_TIME_SECONDS", "stall_check",
          "abort_time_seconds",
          "Escalation rung: abort a stalled negotiation past this age "
          "(waiters raise, elastic retry recovers; 0 = off).", type=int),
    _Flag("--stall-reset-time-seconds", "stall_reset_time",
          "HVDT_STALL_RESET_TIME_SECONDS", "stall_check",
          "reset_time_seconds",
          "Escalation rung: request an elastic re-rendezvous past this "
          "age (0 = off).", type=int),
    # --- resilience / chaos ---
    _Flag("--fault-plan", "fault_plan", "HVDT_FAULT_PLAN",
          "resilience", "fault_plan",
          "Deterministic fault-injection plan for chaos runs, e.g. "
          "'crash@step=12:rank=1,3' (rank sets/ranges), "
          "'pod_crash@step=10:pod=podB,kv_drop@p=0.1' "
          "(resilience/faults.py grammar)."),
    _Flag("--async-ckpt", "async_ckpt", "HVDT_ASYNC_CKPT",
          "resilience", "async_ckpt",
          "Asynchronous non-blocking checkpointing on every worker: "
          "commit-point device->host snapshot + background writer; "
          "LAST_GOOD advances only after manifest fsync "
          "(checkpoint.py save_async).", is_bool=True, to_env=_bool_env),
    _Flag("--peer-store", "peer_store", "HVDT_PEER_STORE",
          "resilience", "peer_store",
          "Peer-replicated in-memory snapshot tier: commit snapshots "
          "ride the rendezvous KV and mirror in peer RAM, so a lost "
          "rank/pod restores without touching the filesystem "
          "(resilience/peer_store.py).", is_bool=True, to_env=_bool_env),
    _Flag("--ckpt-snapshot-budget-s", "ckpt_snapshot_budget_s",
          "HVDT_CKPT_SNAPSHOT_BUDGET_S", "resilience",
          "ckpt_snapshot_budget_s",
          "Stall budget (seconds) for the commit-point checkpoint "
          "snapshot under --async-ckpt; overruns are warned and "
          "counted.", type=float),
    # --- elastic / pods ---
    _Flag("--pod-size", "pod_size", "HVDT_POD_SIZE",
          "elastic", "pod_size",
          "Slots per pod for the pod-granular elastic control plane: "
          "groups discovery hosts without an @pod column into pods of "
          "this many slots; resize/blacklist/recovery then happen at "
          "pod granularity and workers get the two-level (dcn, ici) "
          "mesh contract (HVDT_NUM_PODS/HVDT_POD_SIZE).", type=int),
    _Flag("--pod-straggler-evict", "pod_straggler_evict",
          "HVDT_POD_STRAGGLER_EVICT", "elastic", "pod_straggler_evict",
          "Evict a pod whose median step time exceeds the straggler "
          "threshold for this many consecutive telemetry windows "
          "(0 = off; needs --telemetry so workers publish snapshots).",
          type=int),
    _Flag("--blacklist-cooldown", "blacklist_cooldown",
          "HVDT_ELASTIC_BLACKLIST_COOLDOWN_S", "resilience",
          "blacklist_cooldown_s",
          "Seconds a failed host sits out of elastic discovery before "
          "becoming eligible again (0 = permanent blacklist).",
          type=float),
    # --- closed-loop policy controller (control/controller.py; runs in
    #     the elastic driver's discovery loop and prices sensor-plane
    #     events with the cost model before acting) ---
    _Flag("--controller", "controller", "HVDT_CONTROLLER",
          "controller", "enabled",
          "Enable the driver-side policy controller (on | observe | "
          "off): subscribes to the cluster anomaly event stream, prices "
          "candidate actions (transport flip, bucket retune, "
          "overlap/ZeRO toggle, pod evict, resize, replica scale) with "
          "the cost model offline, and applies the winner at a step "
          "boundary through the no-recompile autotune legs; 'observe' "
          "logs priced decisions without acting (needs --telemetry)."),
    _Flag("--controller-cooldown-s", "controller_cooldown_s",
          "HVDT_CONTROLLER_COOLDOWN_S", "controller", "cooldown_s",
          "Per-action-kind cooldown (seconds) between controller "
          "actions of the same kind; doubled after a rollback.",
          type=float),
    _Flag("--controller-recovery-window", "controller_recovery_window",
          "HVDT_CONTROLLER_RECOVERY_WINDOW", "controller",
          "recovery_window",
          "Telemetry ticks the controller waits for "
          "hvdt_perf_deviation_ratio to recover below the exit band "
          "before rolling a reversible action back.", type=int),
    _Flag("--controller-max-actions", "controller_max_actions",
          "HVDT_CONTROLLER_MAX_ACTIONS", "controller", "max_actions",
          "Lifetime cap on applied controller actions per run "
          "(0 = unlimited).", type=int),
    # --- fleet scheduler (fleet/scheduler.py; bin-packs one pod fleet
    #     between elastic training and SLO serving, pricing every
    #     reclaim/backfill with the cost model before committing) ---
    _Flag("--fleet", "fleet", "HVDT_FLEET", "fleet", "enabled",
          "Enable the fleet scheduler (on | observe | off): one "
          "bin-packing reconciler over the shared pod inventory that "
          "reclaims training pods for serving when SLO pressure "
          "crosses the enter band and backfills training from "
          "serving's trough, pricing each move with the cost model "
          "(training throughput at the candidate world size vs "
          "serving headroom); 'observe' logs priced decisions without "
          "moving a pod."),
    _Flag("--fleet-cooldown-s", "fleet_cooldown_s",
          "HVDT_FLEET_COOLDOWN_S", "fleet", "cooldown_s",
          "Seconds between fleet moves of the same kind; doubled "
          "after a rollback.", type=float),
    _Flag("--fleet-enter-ratio", "fleet_enter_ratio",
          "HVDT_FLEET_ENTER_RATIO", "fleet", "enter_ratio",
          "Serving-pressure ratio at which the scheduler starts "
          "reclaiming training pods for serving.", type=float),
    _Flag("--fleet-exit-ratio", "fleet_exit_ratio",
          "HVDT_FLEET_EXIT_RATIO", "fleet", "exit_ratio",
          "Serving-pressure ratio below which a pending reclaim "
          "counts as recovered (hysteresis exit band).", type=float),
    _Flag("--fleet-backfill-ratio", "fleet_backfill_ratio",
          "HVDT_FLEET_BACKFILL_RATIO", "fleet", "backfill_ratio",
          "Serving-pressure ratio below which serving's trough is "
          "backfilled into training.", type=float),
    _Flag("--fleet-recovery-window", "fleet_recovery_window",
          "HVDT_FLEET_RECOVERY_WINDOW", "fleet", "recovery_window",
          "Scheduler ticks a move has to prove itself before the "
          "never-worse check considers rolling it back.", type=int),
    _Flag("--fleet-min-gain", "fleet_min_gain",
          "HVDT_FLEET_MIN_GAIN", "fleet", "min_gain",
          "Minimum predicted gain for a fleet move to apply.",
          type=float),
    _Flag("--fleet-max-moves", "fleet_max_moves",
          "HVDT_FLEET_MAX_MOVES", "fleet", "max_moves",
          "Lifetime cap on applied fleet moves per run "
          "(0 = unlimited).", type=int),
    _Flag("--fleet-min-train-pods", "fleet_min_train_pods",
          "HVDT_FLEET_MIN_TRAIN_PODS", "fleet", "min_train_pods",
          "Floor on training pods the scheduler will never reclaim "
          "below.", type=int),
    # --- telemetry / observability ---
    _Flag("--telemetry", "telemetry", "HVDT_TELEMETRY",
          "telemetry", "enabled",
          "Enable the unified telemetry subsystem on every worker: "
          "per-collective metrics, step stats (MFU/goodput), straggler "
          "detection, and the /metrics HTTP exporter.", is_bool=True,
          to_env=_bool_env),
    _Flag("--metrics-port", "metrics_port", "HVDT_METRICS_PORT",
          "telemetry", "metrics_port",
          "Base port for each worker's /metrics + /healthz exporter "
          "(worker binds base + local_rank; 0 = ephemeral).", type=int),
    _Flag("--straggler-window", "straggler_window",
          "HVDT_STRAGGLER_WINDOW", "telemetry", "straggler_window",
          "Steps between cross-rank straggler checks (0 = off).",
          type=int),
    _Flag("--trace-dir", "trace_dir", "HVDT_TRACE_DIR",
          "telemetry", "trace_dir",
          "Enable distributed span tracing on every worker and collect "
          "per-rank Chrome-trace dumps (plus desync reports) in this "
          "directory; the elastic driver additionally merges per-rank "
          "dumps into trace_merged.json with rank as pid."),
    _Flag("--flight-recorder", "flight_recorder", "HVDT_FLIGHT_RECORDER",
          "telemetry", "flight_recorder",
          "Enable the per-rank collective flight recorder (ring buffer "
          "of recent collective events; dumped on stall-abort with a "
          "cross-rank desync report, on preemption, and via the "
          "exporter's /flightrecorder endpoint).", is_bool=True,
          to_env=_bool_env),
    # --- serving control plane (serve/autoscale.py + serve/router.py;
    #     `hvdtrun serve` reads the same HVDT_SERVE_* envs, so a YAML
    #     serve: section configures a fleet launch end to end) ---
    _Flag("--serve-replicas", "serve_replicas", "HVDT_SERVE_REPLICAS",
          "serve", "replicas",
          "Initial replica count for the elastic serving control plane "
          "(`hvdtrun serve --replicas` reads this default).", type=int),
    _Flag("--serve-max-replicas", "serve_max_replicas",
          "HVDT_SERVE_MAX_REPLICAS", "serve", "max_replicas",
          "Autoscaler replica ceiling / localhost slot count.",
          type=int),
    _Flag("--serve-autoscale", "serve_autoscale", "HVDT_SERVE_AUTOSCALE",
          "serve", "autoscale",
          "Enable the serving replica autoscaler (queue depth + "
          "p99-vs-SLO from the KV heartbeats).", is_bool=True,
          to_env=_bool_env),
    _Flag("--serve-slo-p99-ms", "serve_slo_p99_ms",
          "HVDT_SERVE_SLO_P99_MS", "serve", "slo_p99_ms",
          "Serving p99 SLO (ms): router ejection + autoscale-up "
          "threshold (0 = off).", type=float),
    _Flag("--serve-heartbeat-s", "serve_heartbeat_s",
          "HVDT_SERVE_HEARTBEAT_S", "serve", "heartbeat_s",
          "Replica heartbeat period (s); 2x this is the router's "
          "dead-replica bound.", type=float),
    # --- library options ---
    _Flag("--cpu-operations", "cpu_operations", "HVDT_CPU_OPERATIONS",
          "library_options", "cpu_operations",
          "Host-collective data plane: xla | tcp."),
    _Flag("--compilation-cache-dir", "compilation_cache_dir",
          "HVDT_COMPILATION_CACHE", "library_options",
          "compilation_cache_dir",
          "Persistent XLA compilation-cache directory for every worker "
          "(engaged inside hvd.init(); amortizes the multi-second step "
          "compile across runs)."),
    _Flag("--tcp-port-stride", "tcp_port_stride",
          "HVDT_TCP_SET_PORT_STRIDE", "library_options", "tcp_port_stride",
          "Port stride between process sets' TCP meshes.", type=int),
    # --- logging ---
    _Flag("--log-level", "log_level", "HVDT_LOG_LEVEL", "logging", "level",
          "trace|debug|info|warning|error|fatal."),
    _Flag("--log-hide-timestamp", "log_hide_timestamp",
          "HVDT_LOG_HIDE_TIME", "logging", "hide_timestamp",
          "Hide timestamps in worker log lines.", is_bool=True,
          to_env=_bool_env),
    # --- numerics ---
    _Flag("--allreduce-dtype", "allreduce_dtype", "HVDT_ALLREDUCE_DTYPE",
          "params", "allreduce_dtype",
          "Wire dtype for allreduce (e.g. bfloat16 for on-the-wire "
          "compression)."),
    _Flag("--compression", "compression", "HVDT_COMPRESSION",
          "params", "compression",
          "Gradient wire compressor by name: none|bf16|fp16|int8|int4 "
          "(int8/int4 = block-scaled quantized collectives, int4 packed "
          "two lanes per byte, horovod_tpu/"
          "quant).  Workers resolve it in hvd.init()/"
          "DistributedOptimizer; unknown names fail init with the "
          "valid list."),
    # --- mesh ---
    _Flag("--mesh-axes", "mesh_axes", "HVDT_MESH_AXES", "params",
          "mesh_axes", "Default mesh axes, e.g. 'dp=4,tp=2'."),
]


def add_knob_arguments(parser: argparse.ArgumentParser) -> None:
    """Add every knob flag (default=None so 'explicitly set on the CLI'
    is detectable — the precedence rules depend on it)."""
    g = parser.add_argument_group(
        "runtime knobs",
        "Forwarded to workers as HVDT_* env. Precedence: CLI > caller env "
        "> --config-file > default.")
    for f in KNOB_FLAGS:
        if f.is_bool:
            g.add_argument(f.flag, dest=f.dest, action="store_const",
                           const=True, default=None, help=f.help)
        else:
            g.add_argument(f.flag, dest=f.dest, type=f.type, default=None,
                           help=f.help)


def load_config_file(path: str) -> Dict[str, Dict[str, Any]]:
    """Parse the YAML config file into {section: {key: value}}."""
    import yaml

    with open(path) as fh:
        data = yaml.safe_load(fh) or {}
    if not isinstance(data, dict):
        raise ValueError(f"config file {path} must be a YAML mapping")
    return data


def apply_config_file(args: argparse.Namespace, path: Optional[str]
                      ) -> Dict[str, Any]:
    """Returns {dest: value} of file-provided knobs (file values NEVER
    overwrite args — CLI wins; env-vs-file precedence is resolved in
    :func:`env_from_args`)."""
    if not path:
        return {}
    data = load_config_file(path)
    out: Dict[str, Any] = {}
    known = {(f.section, f.key): f for f in KNOB_FLAGS}
    for section, body in data.items():
        if not isinstance(body, dict):
            raise ValueError(f"config section {section!r} must be a mapping")
        for key, value in body.items():
            f = known.get((section, key))
            if f is None:
                raise ValueError(
                    f"unknown config entry {section}.{key} "
                    f"(known: {sorted(k for k in known)})")
            out[f.dest] = value
    return out


def env_from_args(args: argparse.Namespace,
                  file_values: Dict[str, Any],
                  base_env: Optional[Dict[str, str]] = None
                  ) -> Dict[str, str]:
    """HVDT_* env to forward to workers, honoring
    CLI > caller env > config file > default.

    ``base_env`` defaults to ``os.environ``; a file value only applies
    when the var is absent there, while a CLI value always wins.
    """
    import os

    env = dict(os.environ) if base_env is None else dict(base_env)
    out: Dict[str, str] = {}
    for f in KNOB_FLAGS:
        cli_val = getattr(args, f.dest, None)
        if cli_val is not None:
            out[f.env] = f.to_env(cli_val)
        elif f.env in env:
            out[f.env] = env[f.env]
        elif f.dest in file_values:
            out[f.env] = f.to_env(file_values[f.dest])
    return out
