"""Process execution with process-group cleanup and output streaming.

Re-conception of ref: runner/common/util/safe_shell_exec.py:1-270 —
spawn in its own process group/session, stream stdout/stderr with an
optional per-line prefix (rank tagging), event-driven termination with a
graceful SIGTERM→SIGKILL window.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys
import threading
import time
from typing import Dict, IO, Optional

__all__ = ["safe_execute", "GRACEFUL_TERMINATION_TIME_S"]

GRACEFUL_TERMINATION_TIME_S = 5.0


def _stream(pipe: IO[bytes], out: IO, prefix: str) -> None:
    try:
        for line in iter(pipe.readline, b""):
            text = line.decode("utf-8", errors="replace")
            out.write(f"{prefix}{text}" if prefix else text)
            out.flush()
    except ValueError:
        pass  # pipe closed
    finally:
        try:
            pipe.close()
        except OSError:
            pass


def safe_execute(command: str,
                 env: Optional[Dict[str, str]] = None,
                 stdout: Optional[IO] = None,
                 stderr: Optional[IO] = None,
                 prefix: str = "",
                 terminate_event: Optional[threading.Event] = None,
                 graceful_s: float = GRACEFUL_TERMINATION_TIME_S) -> int:
    """Run ``command`` in a shell in its own session; return exit code.

    If ``terminate_event`` fires, the whole process group gets SIGTERM,
    then SIGKILL after ``graceful_s`` (ref: safe_shell_exec.py
    GRACEFUL_TERMINATION_TIME semantics).
    """
    proc = subprocess.Popen(
        command, shell=True, env=env, start_new_session=True,
        stdout=subprocess.PIPE, stderr=subprocess.PIPE)
    threads = [
        threading.Thread(target=_stream,
                         args=(proc.stdout, stdout or sys.stdout, prefix),
                         daemon=True),
        threading.Thread(target=_stream,
                         args=(proc.stderr, stderr or sys.stderr, prefix),
                         daemon=True),
    ]
    for t in threads:
        t.start()

    def _killer():
        terminate_event.wait()
        if proc.poll() is None:
            _terminate_group(proc, graceful_s)

    if terminate_event is not None:
        threading.Thread(target=_killer, daemon=True).start()

    proc.wait()
    for t in threads:
        t.join(timeout=1.0)
    return proc.returncode


def _terminate_group(proc: subprocess.Popen, graceful_s: float) -> None:
    try:
        pgid = os.getpgid(proc.pid)
    except ProcessLookupError:
        return
    try:
        os.killpg(pgid, signal.SIGTERM)
    except ProcessLookupError:
        return
    from ..resilience.retry import Backoff

    grace = Backoff(first=0.02, cap=0.25, deadline_s=graceful_s)
    while proc.poll() is None:
        if not grace.sleep():   # grace window exhausted -> SIGKILL
            break
    if proc.poll() is not None:
        return
    try:
        os.killpg(pgid, signal.SIGKILL)
    except ProcessLookupError:
        pass
