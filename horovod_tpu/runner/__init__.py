"""Launcher package: ``hvdtrun`` CLI, hosts/slots, rendezvous KV, elastic.

Re-conception of ref: horovod/runner/ (SURVEY.md §2.5) for the TPU process
model.  Programmatic API mirrors ref: runner/__init__.py:210 hvd.run().
"""

from .hosts import HostInfo, SlotInfo, parse_hosts, get_host_assignments  # noqa: F401
from .http_kv import RendezvousServer, KVClient, new_secret  # noqa: F401


def run(func, np: int = 1, hosts=None, verbose: bool = False, **kwargs):
    """Programmatic launch: run ``func`` on ``np`` local worker processes
    and return their results ordered by rank (ref: runner/__init__.py
    hvd.run — same contract, cloudpickle over the rendezvous KV)."""
    import pickle
    import sys

    from . import launch as launch_mod
    from .http_kv import RendezvousServer, new_secret

    try:
        import cloudpickle
        dumps = cloudpickle.dumps
    except ImportError:   # plain pickle works for module-level functions
        dumps = pickle.dumps

    server = RendezvousServer(secret=new_secret())
    port = server.start()
    server.put_local("/runfunc/fn", dumps(func))
    try:
        argv = ["-np", str(np)]
        if hosts:
            argv += ["-H", hosts]
        if verbose:
            argv += ["--verbose"]
        argv += ["--", sys.executable, "-m", "horovod_tpu.runner.run_task"]
        args = launch_mod.parse_args(argv)
        # Point workers at *this* server so they fetch fn and post results.
        import os

        env_patch = {
            "HVDT_RUNFUNC_ADDR": "127.0.0.1",
            "HVDT_RUNFUNC_PORT": str(port),
            "HVDT_RUNFUNC_SECRET": server.secret.hex(),
        }
        old = {k: os.environ.get(k) for k in env_patch}
        os.environ.update(env_patch)
        try:
            code = launch_mod.run_static(args)
        finally:
            for k, v in old.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        if code != 0:
            raise RuntimeError(f"hvd.run failed with exit code {code}")
        results = []
        for rank in range(np):
            blob = server.get_local(f"/runfunc/result/{rank}")
            results.append(pickle.loads(blob) if blob is not None else None)
        return results
    finally:
        server.stop()
