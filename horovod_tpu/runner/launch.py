"""``hvdtrun`` — the horovodrun-equivalent CLI.

Re-conception of ref: runner/launch.py:1-774 (parse_args :242-527,
_run_static :528, _run_elastic :621) + runner/gloo_run.py:240 launch_gloo
for the TPU process model: one worker process per TPU VM host, rendezvous
via our HTTP KV (bootstrap) + the JAX coordination service (runtime), no
MPI anywhere.

Flow (static):
  parse hosts → SlotInfo assignments (hosts.py) → start RendezvousServer →
  publish cluster spec → spawn one shell per slot (local exec or ssh) with
  the HVDT_* env contract → stream rank-prefixed output → first non-zero
  exit terminates the job (ref: gloo_run.py:134-197 terminate_all).
"""

from __future__ import annotations

import argparse
import os
import shlex
import socket
import sys
import threading
from typing import Dict, List, Optional

from . import hosts as hosts_mod
from .config_parser import add_knob_arguments, apply_config_file, env_from_args
from .http_kv import RendezvousServer, new_secret
from .safe_shell_exec import safe_execute

__all__ = ["main", "parse_args", "run_static"]

_LOCAL_NAMES = {"localhost", "127.0.0.1", "::1"}


def parse_args(argv: Optional[List[str]] = None) -> argparse.Namespace:
    p = argparse.ArgumentParser(
        prog="hvdtrun",
        description="Launch distributed training on TPU hosts "
                    "(horovodrun-equivalent).")
    p.add_argument("-V", "--version", action="store_true", dest="version",
                   help="Print the horovod_tpu version and exit.")
    p.add_argument("-cb", "--check-build", action="store_true",
                   help="Print build capabilities (native core, TCP data "
                        "plane, TPU visibility) and exit "
                        "(ref: horovodrun --check-build).")
    p.add_argument("-np", "--num-proc", type=int, default=None,
                   help="Total number of worker processes.")
    p.add_argument("--network-interface", "--nics", dest="nics",
                   default=None,
                   help="Comma-separated NIC allowlist: the launcher "
                        "advertises its rendezvous/KV address from the "
                        "first matching interface (static and elastic), "
                        "and exports HVDT_NICS to workers.")
    p.add_argument("--disable-cache", action="store_true",
                   help="Disable the controller response cache "
                        "(HVDT_CACHE_CAPACITY=0; every collective "
                        "renegotiates, ref: --disable-cache).")
    p.add_argument("-H", "--hosts", default=None,
                   help='Comma-separated "host:slots" list.')
    p.add_argument("--hostfile", default=None,
                   help='Hostfile with "host slots=N" lines.')
    p.add_argument("-p", "--ssh-port", type=int, default=None)
    p.add_argument("--ssh-identity-file", default=None)
    p.add_argument("--coordinator-port", type=int, default=29500,
                   help="Port for the JAX coordination service on rank 0's "
                        "host.")
    p.add_argument("--start-timeout", type=float, default=600.0)
    p.add_argument("--output-filename", default=None,
                   help="Mux per-rank output into <dir>/rank.<N> files.")
    p.add_argument("--verbose", "-v", action="store_true")
    p.add_argument("--config-file", default=None,
                   help="YAML file with runtime-knob sections (see "
                        "runner/config_parser.py). Precedence: CLI > "
                        "caller env > config file > default.")
    p.add_argument("--tcp-base-port", type=int, default=40000,
                   help="First listener port for the native TCP host data "
                        "plane (used when --cpu-operations tcp).")
    p.add_argument("--no-preflight", action="store_true",
                   help="Skip the host-reachability preflight probe.")
    add_knob_arguments(p)
    # Elastic flags (ref: launch.py elastic group)
    p.add_argument("--host-discovery-script", default=None,
                   help="Executable printing current 'host:slots' lines; "
                        "enables elastic mode.")
    p.add_argument("--min-np", type=int, default=None)
    p.add_argument("--max-np", type=int, default=None)
    p.add_argument("--slots-per-host", type=int, default=1)
    p.add_argument("--reset-limit", type=int, default=None,
                   help="Max worker resets before aborting the elastic job.")
    p.add_argument("--elastic-timeout", type=float, default=600.0,
                   help="Seconds to wait for min-np slots at each elastic "
                        "rendezvous (ref: --elastic-timeout).")
    p.add_argument("command", nargs=argparse.REMAINDER,
                   help="Training command, e.g. python train.py")
    args = p.parse_args(argv)
    if args.version or args.check_build:
        return args
    if not args.command:
        p.error("no training command given")
    if args.command and args.command[0] == "--":
        args.command = args.command[1:]
    return args


def _print_check_build() -> None:
    """--check-build / --version output (ref: horovodrun --check-build
    prints the framework/controller/transport capability table)."""
    import subprocess

    import horovod_tpu as hvd

    print(f"horovod_tpu v{hvd.__version__}")
    # TPU probe in a TIME-BOUNDED child: jax.devices() on a tunnelled/
    # remote TPU backend can claim the chip for minutes — --check-build
    # must stay snappy like the reference's link-time checks.
    try:
        r = subprocess.run(
            [sys.executable, "-c",
             "import jax;"
             "print(any(d.platform=='tpu' for d in jax.devices()))"],
            capture_output=True, text=True, timeout=30)
        tpu = "True" in r.stdout
    except Exception:
        tpu = False
    rows = [
        ("native C++ core", hvd.native_built()),
        ("TCP host data plane", hvd.tcp_enabled()),
        ("TPU visible", tpu),
    ]
    print("\nAvailable capabilities:")
    for name, ok in rows:
        print(f"    [{'X' if ok else ' '}] {name}")
    print("\nData planes: [X] XLA collectives (jit)  "
          "[X] host eager (grouped/fused)")


def _is_local(hostname: str) -> bool:
    return (hostname in _LOCAL_NAMES
            or hostname == socket.gethostname()
            or hostname == socket.getfqdn())


def _ssh_prefix(args, hostname: str) -> str:
    opts = "-o StrictHostKeyChecking=no -o BatchMode=yes"
    if args.ssh_port:
        opts += f" -p {args.ssh_port}"
    if args.ssh_identity_file:
        opts += f" -i {shlex.quote(args.ssh_identity_file)}"
    return f"ssh {opts} {shlex.quote(hostname)}"


def _build_command(args, slot: hosts_mod.SlotInfo, base_env: Dict[str, str],
                   command: List[str]) -> (str, Dict[str, str]):
    env = dict(os.environ)
    env.update(base_env)
    env.update(slot.to_env())
    cmd = " ".join(shlex.quote(c) for c in command)
    if _is_local(slot.hostname):
        return cmd, env
    # Remote: forward the contract env explicitly through ssh.
    exports = " ".join(
        f"{k}={shlex.quote(v)}" for k, v in {**base_env,
                                             **slot.to_env()}.items())
    return (f"{_ssh_prefix(args, slot.hostname)} "
            f"{shlex.quote(f'cd {os.getcwd()} && env {exports} {cmd}')}",
            dict(os.environ))


def knob_env_for(args) -> Dict[str, str]:
    """Resolve the runtime-knob env contract for workers (CLI > caller
    env > --config-file > default; ref: config_parser.py precedence)."""
    file_values = apply_config_file(args, getattr(args, "config_file", None))
    env = env_from_args(args, file_values)
    if getattr(args, "disable_cache", False):
        env["HVDT_CACHE_CAPACITY"] = "0"
    if getattr(args, "nics", None):
        env["HVDT_NICS"] = args.nics
    return env


def tcp_addrs_env(args, slots: List[hosts_mod.SlotInfo],
                  env: Dict[str, str]) -> Dict[str, str]:
    """Allocate the rank-ordered HVDT_TCP_ADDRS contract when the native
    TCP host data plane is selected and the operator didn't hand-set it.

    Each rank listens at ``tcp_base_port + local_rank`` on its host —
    a contiguous per-host block, as the per-set port striding requires
    (ops/tcp_backend.py)."""
    if env.get("HVDT_CPU_OPERATIONS", os.environ.get(
            "HVDT_CPU_OPERATIONS", "xla")).lower() != "tcp":
        return {}
    if env.get("HVDT_TCP_ADDRS") or os.environ.get("HVDT_TCP_ADDRS"):
        return {}
    addrs = []
    for slot in sorted(slots, key=lambda s: s.rank):
        host = "127.0.0.1" if _is_local(slot.hostname) else slot.hostname
        addrs.append(f"{host}:{args.tcp_base_port + slot.local_rank}")
    return {"HVDT_TCP_ADDRS": ",".join(addrs)}


def _nic_addr(nics: List[str]) -> Optional[str]:
    """IPv4 address of the first present interface in ``nics`` (the
    --network-interface allowlist; ref: driver_service NIC selection).
    Linux SIOCGIFADDR — returns None when none match."""
    import fcntl
    import struct

    s = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
    try:
        for nic in nics:
            try:
                packed = fcntl.ioctl(
                    s.fileno(), 0x8915,  # SIOCGIFADDR
                    struct.pack("256s", nic.strip()[:15].encode()))
                return socket.inet_ntoa(packed[20:24])
            except OSError:
                continue
    finally:
        s.close()
    return None


def preflight_reachability(args, slots: List[hosts_mod.SlotInfo],
                           addr: str, port: int) -> None:
    """Probe that every worker host can reach the launcher's rendezvous
    server before any rank is spawned — the analog of the reference's
    driver/NIC discovery (ref: runner/driver/driver_service.py:162-260,
    which probes mutually-routable interfaces).  On TPU VMs a single NIC
    carries DCN, so the failure mode worth catching is "this host can't
    reach the coordinator address at all" — fail fast, naming the host,
    instead of an opaque rendezvous timeout minutes later.
    """
    import subprocess

    probe_py = (f"import socket;"
                f"socket.create_connection(('{addr}',{port}),timeout=10);"
                f"print('ok')")
    seen = set()
    for slot in slots:
        host = slot.hostname
        if host in seen:
            continue
        seen.add(host)
        if _is_local(host):
            try:
                socket.create_connection(("127.0.0.1", port),
                                         timeout=10).close()
            except OSError as e:
                raise RuntimeError(
                    f"preflight: host {host!r} (local) cannot reach the "
                    f"rendezvous server at 127.0.0.1:{port} — {e!r}. "
                    f"Pass --no-preflight to skip.") from e
            continue
        cmd = (f"{_ssh_prefix(args, host)} "
               f"{shlex.quote(f'python3 -c {shlex.quote(probe_py)}')}")
        try:
            res = subprocess.run(cmd, shell=True, capture_output=True,
                                 text=True, timeout=30)
        except subprocess.TimeoutExpired:
            raise RuntimeError(
                f"preflight: host {host!r} did not answer the "
                f"reachability probe to {addr}:{port} within 30s")
        if res.returncode != 0 or "ok" not in res.stdout:
            raise RuntimeError(
                f"preflight: host {host!r} cannot reach the rendezvous "
                f"server at {addr}:{port} — "
                f"{(res.stderr or res.stdout).strip()[-300:]!r}. "
                f"Check that the launcher's address is routable from the "
                f"worker (wrong NIC?) or pass --no-preflight to skip.")


def run_static(args) -> int:
    """Static launch (ref: launch.py:528 _run_static + gloo_run.py:240)."""
    if args.hostfile:
        host_list = hosts_mod.parse_host_files(args.hostfile)
    elif args.hosts:
        host_list = hosts_mod.parse_hosts(args.hosts)
    else:
        host_list = [hosts_mod.HostInfo("localhost",
                                        args.num_proc or 1)]
    np_ = args.num_proc or sum(h.slots for h in host_list)
    slots = hosts_mod.get_host_assignments(host_list, np_)

    server = RendezvousServer(secret=new_secret())
    port = server.start()
    my_addr = socket.gethostbyname(socket.gethostname()) \
        if any(not _is_local(s.hostname) for s in slots) else "127.0.0.1"
    if getattr(args, "nics", None):
        # --network-interface: advertise the rendezvous on the allowed
        # NIC's address (workers then reach the coordinator over it).
        nic_addr = _nic_addr(args.nics.split(","))
        if nic_addr:
            my_addr = nic_addr
        else:
            print(f"hvdtrun: none of --network-interface {args.nics} "
                  "present on this host; using default address",
                  file=sys.stderr)
    coord_host = slots[0].hostname
    if _is_local(coord_host):
        coord_host = "127.0.0.1"
    base_env = {
        "HVDT_RENDEZVOUS_ADDR": my_addr,
        "HVDT_RENDEZVOUS_PORT": str(port),
        "HVDT_SECRET": server.secret.hex(),
        "HVDT_COORDINATOR_ADDR": f"{coord_host}:{args.coordinator_port}",
    }
    base_env.update(knob_env_for(args))
    base_env.update(tcp_addrs_env(args, slots, base_env))
    server.put_local("/cluster/size", str(np_).encode())
    if not getattr(args, "no_preflight", False):
        try:
            preflight_reachability(args, slots, my_addr, port)
        except RuntimeError:
            server.stop()
            raise

    terminate = threading.Event()
    exit_codes: Dict[int, int] = {}
    lock = threading.Lock()

    def _run_slot(slot: hosts_mod.SlotInfo):
        cmd, env = _build_command(args, slot, base_env, args.command)
        out = err = None
        if args.output_filename:
            os.makedirs(args.output_filename, exist_ok=True)
            out = open(os.path.join(args.output_filename,
                                    f"rank.{slot.rank}"), "w")
            err = out
        prefix = f"[{slot.rank}]<stdout>:" if args.verbose else ""
        code = safe_execute(cmd, env=env, stdout=out, stderr=err,
                            prefix=prefix, terminate_event=terminate)
        with lock:
            exit_codes[slot.rank] = code
        if code != 0:
            terminate.set()
        if out is not None:
            out.close()

    threads = [threading.Thread(target=_run_slot, args=(s,), daemon=True)
               for s in slots]
    for t in threads:
        t.start()
    try:
        for t in threads:
            t.join()
    except KeyboardInterrupt:
        terminate.set()
        for t in threads:
            t.join(timeout=10)
        return 130
    finally:
        server.stop()
    failed = {r: c for r, c in exit_codes.items() if c != 0}
    if failed:
        rank, code = sorted(failed.items())[0]
        print(f"hvdtrun: rank {rank} exited with code {code}",
              file=sys.stderr)
        return code
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:]) if argv is None else list(argv)
    if argv and argv[0] == "serve":
        # `hvdtrun serve ...` — the serving plane.  Bare: one replica,
        # direct HTTP.  With --replicas/--autoscale: the elastic serving
        # control plane (serve/autoscale.py) — rendezvous KV + replica
        # fleet + SLO router, sharing the training driver's discovery/
        # blacklist/drain machinery, e.g.
        #   hvdtrun serve --checkpoint /ckpts --replicas 3 --autoscale \
        #       --slo-p99-ms 250
        # `--engine continuous` (or HVDT_SERVE_ENGINE=continuous) swaps
        # each replica's static bucket engine for the paged-KV
        # continuous-batching LLM decode engine (serve/llm) — the fleet
        # flags compose unchanged.  Flags after `serve` are the serve
        # CLI's (see horovod_tpu/serve/__main__.py).
        from ..serve import main as serve_main

        return serve_main(argv[1:])
    if argv and argv[0] == "top":
        # `hvdtrun top ...` — live terminal view over worker
        # /timeseries endpoints (telemetry/top.py): per-rank step-time
        # sparklines, goodput, worst pod, last anomalies.  Flags after
        # `top` are the top CLI's (--endpoints/--interval/--once/
        # --event-log).
        from ..telemetry.top import main as top_main

        return top_main(argv[1:])
    if argv and argv[0] == "fleet":
        # `hvdtrun fleet <trace> ...` — trace-driven CPU simulation of
        # the bin-packing fleet scheduler (fleet/simulate.py): replay a
        # diurnal/flash-crowd/step-function traffic trace (or a trace
        # JSON) plus an optional resilience fault plan against the real
        # scheduler over a TopologySpec-priced pod fleet, e.g.
        #   hvdtrun fleet diurnal --pods 8 \
        #       --fault-plan pod_crash@step=40:pod=pod5
        # Prints the goodput-vs-SLO report as one JSON doc.
        from ..fleet.simulate import main as fleet_main

        return fleet_main(argv[1:])
    if argv and argv[0] == "lint":
        # `hvdtrun lint ...` — the static-analysis gate (collective-
        # schedule verifier + hvdt-lint rule registry + lock-order
        # graph; horovod_tpu/analysis).  Bare `hvdtrun lint` runs the
        # full --all gate; flags after `lint` are the analysis CLI's
        # (see python -m horovod_tpu.analysis --help).
        from ..analysis import main as analysis_main

        rest = argv[1:]
        return analysis_main(rest if rest else ["--all"])
    args = parse_args(argv)
    if args.version or args.check_build:
        _print_check_build()
        return 0
    if args.host_discovery_script:
        from .elastic.driver import run_elastic

        return run_elastic(args)
    return run_static(args)


if __name__ == "__main__":
    sys.exit(main())
