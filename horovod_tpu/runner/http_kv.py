"""Rendezvous key-value HTTP server + client.

Re-conception of ref: runner/http/http_server.py:1-259 (KVStoreHandler,
RendezvousServer with scoped KV per rank group) and http/http_client.py.
Used by the launcher to publish slot assignments, by elastic workers to
discover re-rendezvous info, and by the host-collective fallback backend
as its bootstrap store (the analog of gloo's HTTPStore,
ref: gloo/http_store.{h,cc}).

Security note: like the reference, requests carry an HMAC digest derived
from a per-launch secret key (ref: common/util/secret.py, network.py:58-99
Wire) so stray processes can't join the job.

Resilience: client polls use the shared exponential-backoff-with-jitter
primitive (``resilience.retry.Backoff``) instead of fixed-interval
sleeps, client ops carry the ``kv`` fault-injection point
(``HVDT_FAULT_PLAN=kv_drop@p=...``), and server shutdown is
deterministic (socket closed before the join; a leaked serve thread is
reported, not silently abandoned).
"""

from __future__ import annotations

import hashlib
import hmac
import http.client
import http.server
import os
import secrets as _secrets
import socket
import socketserver
import threading
import time
import urllib.parse
from typing import Dict, Optional, Tuple

from ..resilience import faults
from ..resilience.retry import Backoff

__all__ = ["RendezvousServer", "KVClient", "new_secret"]

_DIGEST_HEADER = "X-HVDT-Digest"

# KV-client observability: until now a flaky control network was
# *silent* — wait() retried under the hood and nothing counted the
# failures.  With telemetry on, hvdt_kv_errors_total{op} counts every
# failed client op and hvdt_kv_retries_total counts the bootstrap-wait
# retries that papered over them; both land in the worker's KV snapshot,
# so ElasticDriver.telemetry_snapshots() shows control-plane flakiness
# fleet-wide.  Telemetry off keeps the zero-overhead contract
# (_kv_metrics() is None — no registry, no counters, no labels).
_kv_metrics_cache = None


def _kv_metrics():
    global _kv_metrics_cache
    from ..telemetry import instrument
    from ..telemetry.metrics import default_registry

    if not instrument.enabled():
        _kv_metrics_cache = None
        return None
    if _kv_metrics_cache is None:
        reg = default_registry()
        _kv_metrics_cache = (
            reg.counter(
                "hvdt_kv_retries_total",
                "Rendezvous-KV bootstrap-wait retries after a failed or "
                "empty probe (KVClient.wait backoff loop)"),
            reg.counter(
                "hvdt_kv_errors_total",
                "Rendezvous-KV client op failures, labelled op="
                "put|get|delete (connection refused/reset, non-200, "
                "injected kv_drop faults)"))
    return _kv_metrics_cache


def _count_kv_error(op: str) -> None:
    m = _kv_metrics()
    if m is not None:
        m[1].inc(op=op)


def new_secret() -> bytes:
    return _secrets.token_bytes(32)


def _digest(secret: bytes, payload: bytes) -> str:
    return hmac.new(secret, payload, hashlib.sha256).hexdigest()


class _Handler(http.server.BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server: "RendezvousServer"

    def log_message(self, *args):   # silence default stderr noise
        pass

    def _check_auth(self, payload: bytes) -> bool:
        want = _digest(self.server.secret, payload)
        got = self.headers.get(_DIGEST_HEADER, "")
        return hmac.compare_digest(want, got)

    def do_PUT(self):
        length = int(self.headers.get("Content-Length", 0))
        payload = self.rfile.read(length)
        if not self._check_auth(payload):
            self.send_error(403)
            return
        key = urllib.parse.unquote(self.path)
        with self.server.lock:
            self.server.store[key] = payload
            self.server.cond.notify_all()
        self.send_response(200)
        self.send_header("Content-Length", "0")
        self.end_headers()

    def do_GET(self):
        if not self._check_auth(b""):
            self.send_error(403)
            return
        key = urllib.parse.unquote(self.path)
        with self.server.lock:
            val = self.server.store.get(key)
        if val is None:
            self.send_response(404)
            self.send_header("Content-Length", "0")
            self.end_headers()
            return
        self.send_response(200)
        self.send_header("Content-Length", str(len(val)))
        self.end_headers()
        self.wfile.write(val)

    def do_DELETE(self):
        if not self._check_auth(b""):
            self.send_error(403)
            return
        key = urllib.parse.unquote(self.path)
        with self.server.lock:
            removed = self.server.store.pop(key, None)
        self.send_response(200 if removed is not None else 404)
        self.send_header("Content-Length", "0")
        self.end_headers()


class RendezvousServer(socketserver.ThreadingMixIn, http.server.HTTPServer):
    """Threaded in-memory KV over HTTP (ref: RendezvousServer
    http_server.py:112-218).  start() binds an ephemeral (or given) port;
    the launcher passes addr/port to workers via HVDT_RENDEZVOUS_ADDR/PORT.
    """

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, secret: Optional[bytes] = None, port: int = 0,
                 addr: str = "0.0.0.0"):
        super().__init__((addr, port), _Handler)
        self.secret = secret if secret is not None else new_secret()
        self.store: Dict[str, bytes] = {}
        self.lock = threading.Lock()
        self.cond = threading.Condition(self.lock)
        self._thread: Optional[threading.Thread] = None

    @property
    def port(self) -> int:
        return self.server_address[1]

    def start(self) -> int:
        self._thread = threading.Thread(target=self.serve_forever,
                                        name="hvdt-rendezvous", daemon=True)
        self._thread.start()
        return self.port

    def stop(self) -> bool:
        """Deterministic teardown: stop the serve loop, close the listen
        socket FIRST (so no handler can block on a fresh accept), then
        join the serve thread.  Returns False — loudly — if the thread
        outlived the join instead of leaking it silently."""
        self.shutdown()
        self.server_close()
        t, self._thread = self._thread, None
        if t is not None:
            t.join(timeout=10)
            if t.is_alive():
                import sys

                print("hvdt-rendezvous thread leaked past shutdown",
                      file=sys.stderr)
                return False
        return True

    # Server-side convenience for the in-process driver.
    def put_local(self, key: str, value: bytes) -> None:
        with self.lock:
            self.store[key] = value
            self.cond.notify_all()

    def get_local(self, key: str) -> Optional[bytes]:
        with self.lock:
            return self.store.get(key)

    def wait_for(self, key: str, timeout: float) -> Optional[bytes]:
        deadline = time.monotonic() + timeout
        with self.lock:
            while key not in self.store:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return None
                self.cond.wait(remaining)
            return self.store[key]


class KVClient:
    """Worker-side client (ref: http/http_client.py read/write_data_from_kvstore)."""

    def __init__(self, addr: str, port: int, secret: bytes,
                 timeout: float = 30.0):
        self.addr, self.port, self.secret = addr, port, secret
        self.timeout = timeout

    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> "KVClient":
        e = env or os.environ
        return cls(e["HVDT_RENDEZVOUS_ADDR"],
                   int(e["HVDT_RENDEZVOUS_PORT"]),
                   bytes.fromhex(e["HVDT_SECRET"]))

    def _conn(self) -> http.client.HTTPConnection:
        return http.client.HTTPConnection(self.addr, self.port,
                                          timeout=self.timeout)

    @staticmethod
    def _fault(point: str) -> None:
        inj = faults.get_injector()
        if inj is not None:
            inj.fire(point)

    def put(self, key: str, value: bytes) -> None:
        try:
            self._fault("kv")
            c = self._conn()
            try:
                c.request("PUT", urllib.parse.quote(key), body=value,
                          headers={_DIGEST_HEADER: _digest(self.secret,
                                                           value)})
                r = c.getresponse()
                r.read()
                if r.status != 200:
                    raise ConnectionError(f"KV put {key}: HTTP {r.status}")
            finally:
                c.close()
        except (ConnectionError, OSError):
            _count_kv_error("put")
            raise

    def get(self, key: str) -> Optional[bytes]:
        try:
            self._fault("kv")
            c = self._conn()
            try:
                c.request("GET", urllib.parse.quote(key),
                          headers={_DIGEST_HEADER: _digest(self.secret,
                                                           b"")})
                r = c.getresponse()
                body = r.read()
                if r.status == 404:
                    return None
                if r.status != 200:
                    raise ConnectionError(f"KV get {key}: HTTP {r.status}")
                return body
            finally:
                c.close()
        except (ConnectionError, OSError):
            _count_kv_error("get")
            raise

    def delete(self, key: str) -> None:
        try:
            c = self._conn()
            try:
                c.request("DELETE", urllib.parse.quote(key),
                          headers={_DIGEST_HEADER: _digest(self.secret,
                                                           b"")})
                c.getresponse().read()
            finally:
                c.close()
        except (ConnectionError, OSError):
            _count_kv_error("delete")
            raise

    def wait(self, key: str, timeout: float = 60.0,
             poll: float = 0.5) -> bytes:
        """Poll until the key appears (bootstrap barrier helper).

        Backoff-with-jitter polling, not a fixed interval: every worker
        of a large job waits on the same bootstrap keys, and fixed-period
        polls synchronize into request storms on the single rendezvous
        server.  ``poll`` caps the delay between probes.  Transient
        connection errors (server restarting, injected ``kv_drop``
        faults) are retried within the same deadline instead of aborting
        the bootstrap."""
        b = Backoff(first=0.02, cap=max(poll, 0.02), deadline_s=timeout)
        while True:
            try:
                val = self.get(key)
            except (ConnectionError, OSError):
                val = None
            if val is not None:
                return val
            m = _kv_metrics()
            if m is not None:
                m[0].inc()
            if not b.sleep():
                raise TimeoutError(f"KV key {key!r} not published "
                                   f"within {timeout}s")
