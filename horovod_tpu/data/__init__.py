"""Data subsystem: loaders, async prefetch, device prefetch, samplers.

Ref analog: horovod/data/data_loader_base.py + torch/elastic/sampler.py
(SURVEY.md §2.6); the device-prefetch iterator is the TPU-native addition
(input pipeline overlap matters more than host threading on TPU).
"""

from .loader import (AsyncDataLoader, AsyncDataLoaderMixin, BaseDataLoader,
                     prefetch_to_device)
from .sampler import DistributedSampler, ElasticSampler, shard_batch_indices

__all__ = ["BaseDataLoader", "AsyncDataLoaderMixin", "AsyncDataLoader",
           "prefetch_to_device", "DistributedSampler", "ElasticSampler",
           "shard_batch_indices"]
