"""Per-process sharding samplers, including the elastic variant.

Re-conception of ref: torch/elastic/sampler.py (ElasticSampler — shard
indices across ranks, record progress, repartition remaining work after
an elastic reset) plus a plain DistributedSampler equivalent.  Built on
the framework topology (hvd.rank()/size()) rather than torch; index
streams feed any loader (numpy batches, tf.data, grain, ...).

On TPU the same machinery doubles as the *global batch* layout helper:
each process loads only its shard, and ``jax.make_array_from_process_local_data``
(or the data loader's sharding arg) assembles the global array.
"""

from __future__ import annotations

import math
import random
from typing import Dict, Iterator, List, Optional, Sequence

__all__ = ["DistributedSampler", "ElasticSampler", "shard_batch_indices"]


def _topo_rank_size(rank: Optional[int], size: Optional[int]):
    if rank is not None and size is not None:
        return rank, size
    from ..common import basics

    return basics.rank(), basics.size()


class DistributedSampler:
    """Deterministic per-rank shard of ``range(num_samples)``.

    Same contract as torch's DistributedSampler (shuffle per epoch with
    common seed; pad to a multiple of world size so every rank yields the
    same count — collective-safe)."""

    def __init__(self, num_samples: int, shuffle: bool = True, seed: int = 0,
                 rank: Optional[int] = None, size: Optional[int] = None,
                 drop_last: bool = False):
        self.num_samples_total = int(num_samples)
        self.shuffle = shuffle
        self.seed = seed
        self.drop_last = drop_last
        self.epoch = 0
        self._rank, self._size = _topo_rank_size(rank, size)

    def set_epoch(self, epoch: int) -> None:
        self.epoch = epoch

    def _indices(self) -> List[int]:
        idx = list(range(self.num_samples_total))
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(idx)
        if self.drop_last:
            total = (len(idx) // self._size) * self._size
            idx = idx[:total]
        else:
            total = int(math.ceil(len(idx) / self._size)) * self._size
            idx += idx[: total - len(idx)]
        return idx[self._rank:len(idx):self._size]

    def __iter__(self) -> Iterator[int]:
        return iter(self._indices())

    def __len__(self) -> int:
        if self.drop_last:
            return self.num_samples_total // self._size
        return int(math.ceil(self.num_samples_total / self._size))


class ElasticSampler:
    """Progress-tracking sampler that repartitions remaining work after an
    elastic reset (ref: torch/elastic/sampler.py:24-122, same API:
    set_epoch / record_batch / state_dict / load_state_dict / reset).

    Register it on the elastic ``State``; after a re-rendezvous the state
    machinery calls ``load_state_dict`` (or ``reset``) and the unprocessed
    tail of the epoch is re-split over the *new* world size.
    """

    def __init__(self, num_samples: int, shuffle: bool = True, seed: int = 0,
                 rank: Optional[int] = None, size: Optional[int] = None):
        self.dataset_size = int(num_samples)
        self.shuffle = shuffle
        self.seed = seed
        self.epoch = 0
        self.processed_num = 0
        self.batch_idx = 0
        self._rank_override = rank
        self._size_override = size
        self.reset()

    def set_epoch(self, epoch: int) -> None:
        """Advance the epoch and clear progress.  Call at the END of each
        epoch so a partially completed epoch is not reprocessed (ref
        docstring sampler.py:60-69)."""
        self.epoch = epoch
        self.processed_num = 0
        self.batch_idx = 0
        self.reset()

    def record_batch(self, batch_idx: int, batch_size: int) -> None:
        """Record one processed global batch (all replicas advance)."""
        self.processed_num += batch_size * self.num_replicas
        self.batch_idx = int(batch_idx) + 1

    def cursor(self) -> Dict[str, int]:
        """The ``(epoch, batch_idx)`` resume cursor that rides inside
        every checkpoint / peer snapshot: ``batch_idx`` is the next
        UNprocessed batch of ``epoch``, the position
        ``BaseDataLoader.seek`` fast-forwards to so recovery replays
        zero already-committed batches."""
        return {"epoch": self.epoch, "batch_idx": self.batch_idx}

    def state_dict(self) -> Dict[str, int]:
        return {"epoch": self.epoch, "processed_num": self.processed_num,
                "batch_idx": self.batch_idx}

    def load_state_dict(self, state: Dict[str, int]) -> None:
        self.epoch = int(state["epoch"])
        self.processed_num = int(state["processed_num"])
        # Pre-cursor checkpoints (PR <= 10) carry no batch_idx: resume
        # conservatively at 0 rather than refusing the state.
        self.batch_idx = int(state.get("batch_idx", 0))
        self.reset()

    def reset(self) -> None:
        """Re-read topology and repartition the remaining indices."""
        self.rank, self.num_replicas = _topo_rank_size(
            self._rank_override, self._size_override)
        all_indices = list(range(self.dataset_size))
        if self.shuffle:
            random.Random(self.seed + self.epoch).shuffle(all_indices)
        self.remaining_indices = all_indices[self.processed_num:]
        self.num_samples = int(
            math.ceil(len(self.remaining_indices) / max(self.num_replicas, 1)))
        self.total_size = self.num_samples * self.num_replicas

    def __iter__(self) -> Iterator[int]:
        indices = self.remaining_indices[:]
        indices += indices[: self.total_size - len(indices)]  # pad evenly
        return iter(indices[self.rank:self.total_size:self.num_replicas])

    def __len__(self) -> int:
        return self.num_samples


def shard_batch_indices(global_batch: int, rank: Optional[int] = None,
                        size: Optional[int] = None) -> slice:
    """Slice of a global batch owned by this process (equal split; global
    batch must divide by world size — the jit-path constraint)."""
    r, s = _topo_rank_size(rank, size)
    if global_batch % s:
        raise ValueError(
            f"global batch {global_batch} not divisible by world size {s}")
    per = global_batch // s
    return slice(r * per, (r + 1) * per)
