"""Data loading: base iterable, background-thread prefetch, device prefetch.

TPU-native re-conception of the reference's data-loading layer
(ref: data/data_loader_base.py — BaseDataLoader and AsyncDataLoaderMixin,
a background thread pushing batches through a bounded queue).  The
TPU-specific addition is ``prefetch_to_device``: while step N computes,
batch N+1 is already being transferred to HBM with its target sharding —
hiding host→device latency behind compute, which on TPU matters more than
the host-side thread (infeed is the usual input bottleneck).
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Iterable, Iterator, Optional

from ..common.logging_util import get_logger

__all__ = ["BaseDataLoader", "AsyncDataLoaderMixin", "AsyncDataLoader",
           "prefetch_to_device"]

log = get_logger(__name__)


class BaseDataLoader:
    """Iterable over batches (ref: data_loader_base.py BaseDataLoader).

    Subclasses implement ``_iterate``; ``_process_batch`` is the trainer
    hook applied to every batch (kept for API parity).

    ``seek(cursor)`` arms the deterministic-resume fast-forward: the
    NEXT iteration discards the first ``batch_idx`` batches unprocessed
    (no ``_process_batch``, no device transfer) so recovery replays zero
    already-committed batches.  The cursor is what
    ``ElasticSampler.cursor()`` rides inside every checkpoint / peer
    snapshot — ``epoch`` is the caller's to apply via ``set_epoch``
    before re-iterating; the loader consumes ``batch_idx``.  One-shot:
    the fast-forward applies to the next iteration only.
    """

    _seek_batches = 0

    def __len__(self) -> int:
        raise NotImplementedError

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def _process_batch(self, batch: Any) -> Any:
        return batch

    def seek(self, cursor) -> "BaseDataLoader":
        """Arm a fast-forward to ``cursor`` (``{"epoch": e, "batch_idx":
        b}``, an ``(epoch, batch_idx)`` tuple, or a bare batch index)
        for the next iteration.  Returns self for chaining."""
        if isinstance(cursor, dict):
            batch_idx = cursor.get("batch_idx", 0)
        elif isinstance(cursor, (tuple, list)):
            batch_idx = cursor[1] if len(cursor) > 1 else cursor[0]
        else:
            batch_idx = cursor
        batch_idx = int(batch_idx)
        if batch_idx < 0:
            raise ValueError(f"seek cursor batch_idx must be >= 0, "
                             f"got {batch_idx}")
        self._seek_batches = batch_idx
        return self

    def __iter__(self) -> Iterator[Any]:
        skip, self._seek_batches = self._seek_batches, 0
        if skip:
            t0 = time.perf_counter()
            it = self._iterate()
            skipped = 0
            for _ in range(skip):
                try:
                    next(it)
                except StopIteration:
                    log.warning(
                        "seek past the end of the loader: cursor asked "
                        "for batch %d but the stream held %d", skip,
                        skipped)
                    return
                skipped += 1
            _charge_replay(time.perf_counter() - t0)
            for batch in it:
                yield self._process_batch(batch)
            return
        for batch in self._iterate():
            yield self._process_batch(batch)


def _charge_replay(seconds: float) -> None:
    """Attribute fast-forward time to the recovery budget's ``replay``
    phase (None-check when telemetry is off)."""
    from ..telemetry import step_stats

    ledger = step_stats.recovery_ledger()
    if ledger is not None:
        ledger.charge_phase("replay", seconds)


class _Done:
    pass


class _Raised:
    def __init__(self, exc: BaseException):
        self.exc = exc


class AsyncDataLoaderMixin:
    """Background-thread prefetch mixin (ref: data_loader_base.py
    AsyncDataLoaderMixin; queue size 0 disables async, same contract).

    Use as ``class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader)``.  The
    producer thread runs ``super()._iterate()`` and pushes into a bounded
    queue; iteration pops.  Exceptions in the producer re-raise in the
    consumer; ``close()`` joins the thread.
    """

    def __init__(self, *args, async_loader_queue_size: int = 64,
                 close_timeout_s: float = 5.0, **kwargs):
        self._queue_size = async_loader_queue_size
        self._close_timeout_s = float(close_timeout_s)
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        super().__init__(*args, **kwargs)

    def close(self) -> None:
        thread = self._thread
        if thread is None:
            return
        self._stop.set()
        # Two safety nets against the close-mid-iteration hang: the
        # producer's puts are bounded (it re-checks the stop flag every
        # timeout, so it can never stay parked on a full queue), and the
        # drain below unblocks it immediately rather than after the put
        # timeout.  The join is bounded too — a producer wedged inside
        # the UPSTREAM iterator (not our queue) must not hang close();
        # it is a daemon thread and dies with the process.
        deadline = time.monotonic() + self._close_timeout_s
        while thread.is_alive() and time.monotonic() < deadline:
            if self._queue is not None:
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
            thread.join(0.01)
        if thread.is_alive():
            log.warning(
                "async loader producer did not exit within %.1fs of "
                "close() (blocked in the upstream iterator?); abandoning "
                "the daemon thread", self._close_timeout_s)
        self._thread = None

    def _put(self, item: Any) -> bool:
        """Bounded put: parks at most 50 ms at a time so a producer
        blocked on a full queue observes close()'s stop flag.  Returns
        False when shut down instead of delivering."""
        while not self._stop.is_set():
            try:
                self._queue.put(item, timeout=0.05)
                return True
            except queue.Full:
                continue
        return False

    def _producer(self) -> None:
        try:
            for batch in super()._iterate():
                if self._stop.is_set() or not self._put(batch):
                    return
            self._put(_Done())
        except BaseException as e:  # noqa: BLE001 - re-raised in consumer
            self._put(_Raised(e))

    def _iterate(self) -> Iterator[Any]:
        if self._queue_size == 0:  # async disabled (ref contract)
            yield from super()._iterate()
            return
        self.close()
        self._stop.clear()
        self._queue = queue.Queue(self._queue_size)
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()
        while True:
            item = self._queue.get()
            if isinstance(item, _Done):
                break
            if isinstance(item, _Raised):
                raise item.exc
            yield item


class _ListLoader(BaseDataLoader):
    def __init__(self, batches: Iterable[Any]):
        self._batches = list(batches)

    def __len__(self) -> int:
        return len(self._batches)

    def _iterate(self) -> Iterator[Any]:
        yield from self._batches


class AsyncDataLoader(AsyncDataLoaderMixin, _ListLoader):
    """Ready-made async loader over any finite iterable of batches."""


def prefetch_to_device(it: Iterable[Any], size: int = 2,
                       sharding: Optional[Any] = None,
                       put: Optional[Callable[[Any], Any]] = None
                       ) -> Iterator[Any]:
    """Double-buffer batches onto device ahead of consumption.

    Keeps ``size`` batches in flight: each is ``jax.device_put`` (with
    ``sharding`` — e.g. NamedSharding(mesh, P('dp'))) before the previous
    one is consumed, so the h2d transfer of batch N+1 overlaps step N.

    ``sharding`` may be a single Sharding (applied to every leaf) or a
    pytree of shardings matching the batch structure — per-leaf
    sharding-aware transfer, e.g. batch-sharded images next to a
    replicated step counter.  ``put`` overrides the transfer fn entirely.

    The returned generator cleans up after itself: abandoning it early
    (``close()`` / GeneratorExit / garbage collection) drops the queued
    in-flight device buffers — and deletes their device storage when the
    backend exposes ``.delete()`` — instead of pinning ``size`` batches
    of HBM until process exit.
    """
    if size < 1:
        raise ValueError(
            f"prefetch_to_device needs size >= 1 (got {size}); size "
            "batches are kept in flight, so 0 would never yield")
    return _prefetch_gen(iter(it), size, sharding, put)


def _prefetch_gen(it: Iterator[Any], size: int, sharding: Any,
                  put: Optional[Callable[[Any], Any]]) -> Iterator[Any]:
    import collections

    import jax

    if put is None:
        single = sharding is None or isinstance(
            sharding, getattr(jax.sharding, "Sharding", ()))
        if single:
            def put(batch):
                return jax.tree.map(
                    lambda x: jax.device_put(x, sharding), batch)
        else:
            # Pytree of shardings: per-leaf transfer placement.
            def put(batch):
                return jax.tree.map(
                    lambda x, s: jax.device_put(x, s), batch, sharding)

    buf: collections.deque = collections.deque()
    try:
        exhausted = False
        while True:
            while not exhausted and len(buf) < size:
                try:
                    buf.append(put(next(it)))
                except StopIteration:
                    exhausted = True
            if not buf:
                return
            yield buf.popleft()
    finally:
        # Early abandonment (close()/GeneratorExit/GC): drop queued
        # device buffers so they don't pin HBM; normal exhaustion hits
        # this with an empty deque.
        while buf:
            dropped = buf.popleft()
            for leaf in jax.tree.leaves(dropped):
                delete = getattr(leaf, "delete", None)
                if callable(delete):
                    try:
                        delete()
                    except Exception:  # freeing must never raise mid-close
                        pass
