"""Data loading: base iterable, background-thread prefetch, device prefetch.

TPU-native re-conception of the reference's data-loading layer
(ref: data/data_loader_base.py — BaseDataLoader and AsyncDataLoaderMixin,
a background thread pushing batches through a bounded queue).  The
TPU-specific addition is ``prefetch_to_device``: while step N computes,
batch N+1 is already being transferred to HBM with its target sharding —
hiding host→device latency behind compute, which on TPU matters more than
the host-side thread (infeed is the usual input bottleneck).
"""

from __future__ import annotations

import queue
import threading
from typing import Any, Callable, Iterable, Iterator, Optional

__all__ = ["BaseDataLoader", "AsyncDataLoaderMixin", "AsyncDataLoader",
           "prefetch_to_device"]


class BaseDataLoader:
    """Iterable over batches (ref: data_loader_base.py BaseDataLoader).

    Subclasses implement ``_iterate``; ``_process_batch`` is the trainer
    hook applied to every batch (kept for API parity)."""

    def __len__(self) -> int:
        raise NotImplementedError

    def _iterate(self) -> Iterator[Any]:
        raise NotImplementedError

    def _process_batch(self, batch: Any) -> Any:
        return batch

    def __iter__(self) -> Iterator[Any]:
        for batch in self._iterate():
            yield self._process_batch(batch)


class _Done:
    pass


class _Raised:
    def __init__(self, exc: BaseException):
        self.exc = exc


class AsyncDataLoaderMixin:
    """Background-thread prefetch mixin (ref: data_loader_base.py
    AsyncDataLoaderMixin; queue size 0 disables async, same contract).

    Use as ``class MyAsyncLoader(AsyncDataLoaderMixin, MyLoader)``.  The
    producer thread runs ``super()._iterate()`` and pushes into a bounded
    queue; iteration pops.  Exceptions in the producer re-raise in the
    consumer; ``close()`` joins the thread.
    """

    def __init__(self, *args, async_loader_queue_size: int = 64, **kwargs):
        self._queue_size = async_loader_queue_size
        self._queue: Optional[queue.Queue] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()
        super().__init__(*args, **kwargs)

    def close(self) -> None:
        if self._thread is not None:
            self._stop.set()
            # Drain so a blocked producer can observe the stop flag.
            while self._thread.is_alive():
                try:
                    self._queue.get_nowait()
                except queue.Empty:
                    pass
                self._thread.join(0.01)
            self._thread = None

    def _producer(self) -> None:
        try:
            for batch in super()._iterate():
                if self._stop.is_set():
                    break
                self._queue.put(batch)
            self._queue.put(_Done())
        except BaseException as e:  # noqa: BLE001 - re-raised in consumer
            self._queue.put(_Raised(e))

    def _iterate(self) -> Iterator[Any]:
        if self._queue_size == 0:  # async disabled (ref contract)
            yield from super()._iterate()
            return
        self.close()
        self._stop.clear()
        self._queue = queue.Queue(self._queue_size)
        self._thread = threading.Thread(target=self._producer, daemon=True)
        self._thread.start()
        while True:
            item = self._queue.get()
            if isinstance(item, _Done):
                break
            if isinstance(item, _Raised):
                raise item.exc
            yield item


class _ListLoader(BaseDataLoader):
    def __init__(self, batches: Iterable[Any]):
        self._batches = list(batches)

    def __len__(self) -> int:
        return len(self._batches)

    def _iterate(self) -> Iterator[Any]:
        yield from self._batches


class AsyncDataLoader(AsyncDataLoaderMixin, _ListLoader):
    """Ready-made async loader over any finite iterable of batches."""


def prefetch_to_device(it: Iterable[Any], size: int = 2,
                       sharding: Optional[Any] = None,
                       put: Optional[Callable[[Any], Any]] = None
                       ) -> Iterator[Any]:
    """Double-buffer batches onto device ahead of consumption.

    Keeps ``size`` batches in flight: each is ``jax.device_put`` (with
    ``sharding`` — e.g. NamedSharding(mesh, P('dp'))) before the previous
    one is consumed, so the h2d transfer of batch N+1 overlaps step N.
    ``put`` overrides the transfer fn (e.g. for pytrees of mixed
    shardings).
    """
    import collections

    import jax

    if put is None:
        def put(batch):
            return jax.tree.map(
                lambda x: jax.device_put(x, sharding), batch)

    buf: collections.deque = collections.deque()
    it = iter(it)
    try:
        while True:
            while len(buf) < size:
                buf.append(put(next(it)))
            yield buf.popleft()
    except StopIteration:
        while buf:
            yield buf.popleft()
