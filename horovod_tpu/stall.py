"""Coordinator-side stall detection.

TPU-native analog of the reference's StallInspector
(ref: common/stall_inspector.{h,cc}; check logic stall_inspector.cc:32-104):
warns when a tensor has been submitted on some-but-not-all ranks for longer
than the warning threshold, listing ready and missing ranks; optionally
shuts training down after a second threshold.  Even on TPU this matters —
host-side logic divergence (a rank skipping a step) hangs the negotiation
exactly as it does on GPU clusters.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional, Set

from .common import config
from .common.logging_util import get_logger

__all__ = ["StallInspector"]

log = get_logger(__name__)


class StallInspector:
    def __init__(self, world_size: int,
                 warn_seconds: Optional[int] = None,
                 shutdown_seconds: Optional[int] = None,
                 on_shutdown: Optional[Callable[[str], None]] = None,
                 escalator: Optional[object] = None):
        self.enabled = not config.get_bool("HVDT_STALL_CHECK_DISABLE")
        self.warn_s = (warn_seconds if warn_seconds is not None
                       else config.get_int("HVDT_STALL_CHECK_TIME_SECONDS"))
        self.shutdown_s = (shutdown_seconds if shutdown_seconds is not None
                           else config.get_int("HVDT_STALL_SHUTDOWN_TIME_SECONDS"))
        self.world_size = world_size
        self.on_shutdown = on_shutdown
        # Optional policy ladder (resilience/escalation.Escalator): every
        # check() feeds it pending ages; its abort/reset rungs let the
        # consumer (the eager controller) unwedge a hung negotiation
        # instead of warning forever.
        self.escalator = escalator
        # tensor name -> (first_seen_ts, ranks that reported)
        self._pending: Dict[str, tuple] = {}
        self._warned: Set[str] = set()
        # Permanent record of every op that EVER stalled (resolve() clears
        # _warned so a tensor can warn again, but post-hoc introspection —
        # tests, timeline annotations — needs the history).
        self.warned_ever: Set[str] = set()
        self._last_check = 0.0

    def record(self, name: str, rank: int) -> None:
        ts, ranks = self._pending.get(name, (time.monotonic(), set()))
        ranks.add(rank)
        self._pending[name] = (ts, ranks)

    def resolve(self, name: str) -> None:
        self._pending.pop(name, None)
        self._warned.discard(name)
        if self.escalator is not None:
            self.escalator.resolve(name)

    def check(self) -> List[str]:
        """Run the stall check; returns names of stalled tensors
        (ref: stall_inspector.cc:32-104).  Called from the controller's
        cycle loop on the coordinator rank."""
        if not self.enabled:
            return []
        now = time.monotonic()
        if now - self._last_check < 1.0:
            return []
        self._last_check = now
        stalled = []
        for name, (ts, ranks) in self._pending.items():
            age = now - ts
            if self.escalator is not None:
                self.escalator.observe(name, age)
            if age > self.warn_s and name not in self._warned:
                missing = sorted(set(range(self.world_size)) - ranks)
                log.warning(
                    "One or more tensors were submitted to be reduced/"
                    "gathered but were not ready on all ranks for %.0fs. "
                    "This may indicate diverged host-side control flow. "
                    "Stalled op: %s [ready ranks: %s] [missing ranks: %s]",
                    age, name, sorted(ranks), missing)
                self._warned.add(name)
                self.warned_ever.add(name)
                stalled.append(name)
            if self.shutdown_s and age > self.shutdown_s:
                msg = (f"Stalled tensor {name} exceeded shutdown threshold "
                       f"({self.shutdown_s}s)")
                log.error(msg)
                if self.on_shutdown:
                    self.on_shutdown(msg)
        return stalled
