"""Offline candidate pricing — every action is costed by the analytical
model BEFORE it is committed, never by live probing.

Comm-shaped actions (transport flip, bucket retune, overlap toggle)
are priced in predicted exposed-comm seconds per step: the candidate is
applied to a copy of the :class:`~.actions.ControllerState`, the
per-step gradient exchange is re-priced with
``CostModel.allreduce_seconds`` on the state's topology, and the delta
vs the current state is the predicted gain.  When the caller holds real
:class:`ScheduleFingerprint` objects per transport leg (the driver does
when ``HVDT_EXPECTED_SCHEDULE`` names one), those are priced with
``CostModel.evaluate`` instead — the controller then picks exactly what
the offline ranking picks on the same fingerprint (acceptance scenario
b pins this).

Membership actions (evict a straggler pod, resize) are priced from the
event's observed slowdown ratio: a synchronous step runs at the
straggler's pace, so removing a pod stepping at ``ratio``x the median
buys ``step_time * (1 - 1/ratio)`` per step, minus whatever the
exchange on the shrunken topology costs extra.  Replica scaling
(serving) has no cost-model term; it is priced from the ratio alone.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional

from .actions import Action, ControllerState

__all__ = ["PricedAction", "ActionPricer"]


@dataclasses.dataclass(frozen=True)
class PricedAction:
    """One candidate with its offline price tag."""

    action: Action
    predicted_s: float        # predicted exposed comm s/step after it
    predicted_delta_s: float  # baseline - predicted (positive = gain)

    def to_dict(self) -> Dict[str, Any]:
        return {"action": self.action.to_dict(),
                "predicted_s": round(self.predicted_s, 9),
                "predicted_delta_s": round(self.predicted_delta_s, 9)}


class ActionPricer:
    """CostModel-backed candidate pricing over a ControllerState.

    Args:
      model: a ``CostModel`` (default: from the checked-in
        calibration).  Scenario (b): hand in a model whose calibration
        reflects the CHANGED dcn bandwidth and the ranking moves with
        it — same code path offline and in the loop.
      fingerprints: optional ``{"flat"|"hier": ScheduleFingerprint}``;
        when both legs are present, transport candidates are priced by
        ``CostModel.evaluate`` on the real fingerprints instead of the
        closed-form allreduce.
    """

    def __init__(self, model=None, fingerprints: Optional[Dict[str, Any]]
                 = None):
        if model is None:
            from ..analysis.costmodel import CostModel

            model = CostModel()
        self.model = model
        self.fingerprints = dict(fingerprints or {})

    # -- state pricing -----------------------------------------------------

    def _topo(self, state: ControllerState):
        from ..analysis.topology import TopologySpec

        return TopologySpec(pods=max(1, int(state.pods)),
                            chips_per_pod=max(1, int(state.chips_per_pod)))

    def comm_seconds(self, state: ControllerState) -> float:
        """Predicted EXPOSED comm seconds of one step's gradient
        exchange under ``state``: n_buckets allreduces of
        grad_bytes/n_buckets each; an overlapped schedule hides every
        bucket but the last under compute (the same accounting
        ``CostModel.evaluate`` applies to barrier groups)."""
        leg = "hier" if (state.transport_hier and state.pods > 1) \
            else "flat"
        fp = self.fingerprints.get(leg)
        if fp is not None:
            return float(self.model.evaluate(
                fp, self._topo(state)).exposed_comm_s)
        n = state.n_buckets
        per_bytes = state.grad_bytes / n
        per = self.model.allreduce_seconds(
            per_bytes, self._topo(state),
            hierarchical=state.transport_hier and state.pods > 1,
            ici_wire=state.ici_wire, dcn_wire=state.dcn_wire)["seconds"]
        total = per * n
        return per if (state.overlap and n > 1) else total

    # -- action application (pure) ----------------------------------------

    def apply(self, state: ControllerState, action: Action
              ) -> ControllerState:
        """The candidate's effect on the knob state — pure, used both
        for pricing what-ifs and to advance the controller's state
        after a commit."""
        k = action.kind
        if k == "flip_transport":
            return dataclasses.replace(
                state, transport_hier=not state.transport_hier)
        if k == "retune_bucket":
            return dataclasses.replace(
                state, bucket_bytes=int(action.param(
                    "bucket_bytes", state.bucket_bytes)))
        if k == "toggle_overlap":
            return dataclasses.replace(state, overlap=not state.overlap)
        if k == "toggle_zero":
            return dataclasses.replace(state, zero=not state.zero)
        if k in ("evict_pod", "resize"):
            pods = int(action.param("pods", state.pods - 1))
            return dataclasses.replace(state, pods=max(1, pods))
        if k == "scale_replicas":
            return dataclasses.replace(
                state, replicas=int(action.param(
                    "target", state.replicas)))
        return state

    def inverse(self, state: ControllerState, action: Action
                ) -> Optional[Action]:
        """The rollback action undoing ``action`` from ``state`` (the
        state BEFORE the action), or None for one-way actions."""
        if not action.reversible:
            return None
        k = action.kind
        reason = f"rollback:{action.reason}"
        if k == "retune_bucket":
            return Action.make("retune_bucket", reason=reason,
                               bucket_bytes=state.bucket_bytes,
                               prev_bucket_bytes=int(action.param(
                                   "bucket_bytes", state.bucket_bytes)))
        if k == "flip_transport":
            return Action.make(
                "flip_transport", reason=reason,
                to="hier" if state.transport_hier else "flat")
        if k == "toggle_overlap":
            return Action.make("toggle_overlap", reason=reason,
                               to=state.overlap)
        return Action.make("toggle_zero", reason=reason, to=state.zero)

    # -- pricing -----------------------------------------------------------

    def price(self, state: ControllerState, action: Action
              ) -> PricedAction:
        base = self.comm_seconds(state)
        after = self.apply(state, action)
        if action.kind in ("flip_transport", "retune_bucket",
                           "toggle_overlap"):
            predicted = self.comm_seconds(after)
            return PricedAction(action, predicted, base - predicted)
        if action.kind == "toggle_zero":
            # ZeRO trades optimizer HBM for a reduce-scatter-shaped
            # wire; its step-time effect is second-order, so it prices
            # neutral and only wins when nothing else does.
            return PricedAction(action, base, 0.0)
        if action.kind in ("evict_pod", "resize"):
            ratio = max(1.0, float(action.param("ratio", 1.0)))
            step_s = state.step_time_s if state.step_time_s else base
            straggler_gain = step_s * (1.0 - 1.0 / ratio)
            predicted = self.comm_seconds(after)
            return PricedAction(action, predicted,
                                straggler_gain + (base - predicted))
        # scale_replicas — no comm term; gain scales with how far the
        # triggering series overshot its threshold.
        ratio = max(1.0, float(action.param("ratio", 1.0)))
        step_s = state.step_time_s if state.step_time_s else base
        return PricedAction(action, base,
                            step_s * (1.0 - 1.0 / ratio))

    def rank(self, state: ControllerState, actions: List[Action]
             ) -> List[PricedAction]:
        """All candidates priced, best predicted delta first; ties keep
        the mapping table's order (stable sort)."""
        priced = [self.price(state, a) for a in actions]
        return sorted(priced, key=lambda p: -p.predicted_delta_s)
