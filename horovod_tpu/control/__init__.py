"""Online policy controller — the actuator over the sensor plane.

PR-15 built the sensors (anomaly events, ``hvdt_perf_deviation_ratio``,
per-axis wire-byte series, straggler/pod attribution) and earlier PRs
built every actuator (state-compatible no-recompile autotune legs,
``ElasticDriver.resize``, pod blacklisting, the serve replica target);
nothing ACTED on the sensors mid-run.  This package closes the loop,
ROADMAP item 4: the static ``horovodrun`` control model (Sergeev & Del
Balso, 1802.05799) generalized into a self-tuning elastic driver.

The loop, one tick::

    anomaly event ──> candidates_for(event, state)       (actions.py)
                  ──> ActionPricer.rank(...)             (pricing.py)
                        offline CostModel pricing — no live probing
                  ──> guardrails: hysteresis band, per-action cooldown,
                      action budget                      (controller.py)
                  ──> applier(action) at a step boundary
                        transport/bucket/overlap/zero ride the autotune
                        leg machinery (AutotunedStep.apply_leg — one
                        optimizer state tree, re-jit only, memoized
                        flip-back = zero recompiles); evict/resize/
                        replica-scale ride the elastic driver seams
                  ──> verify hvdt_perf_deviation_ratio recovers within
                      HVDT_CONTROLLER_RECOVERY_WINDOW ticks, else the
                      never-worse rollback re-flips
                  ──> auditable decision record (event -> candidates ->
                      predicted deltas -> chosen -> observed outcome)
                      appended to the HVDT_EVENT_LOG JSONL

Zero-overhead contract: with ``HVDT_CONTROLLER`` unset,
:func:`get_controller` returns ``None`` from one cached env read and no
wrapper or thread exists anywhere — the same engagement idiom as
faults/telemetry/overlap.  The driver hook
(``ElasticDriver._check_controller``) and the worker-side leg listener
(:mod:`horovod_tpu.control.apply`) both gate on it.
"""

from .actions import (ACTION_KINDS, Action, ControllerState, EVENT_ACTIONS,
                      candidates_for)
from .pricing import ActionPricer, PricedAction
from .controller import (ControllerConfig, Decision, PolicyController,
                         get_controller, reset)
from . import apply

__all__ = [
    "ACTION_KINDS", "Action", "ControllerState", "EVENT_ACTIONS",
    "candidates_for", "ActionPricer", "PricedAction", "ControllerConfig",
    "Decision", "PolicyController", "get_controller", "reset", "apply",
]
