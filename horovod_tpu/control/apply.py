"""Leg application glue: how a driver-side decision reaches worker
step functions without a recompile storm.

The actuation channel is the rendezvous KV (the same channel autotune
uses to broadcast rank 0's knob point): the driver publishes the wanted
leg overrides under :data:`LEGS_KV_KEY` with a monotonically increasing
``seq``; each worker polls the key at its step boundary (one KV read
per commit cadence) and, when the seq advances, queues the legs on its
``AutotunedStep`` via :meth:`~horovod_tpu.autotune.AutotunedStep.
apply_leg`.  apply_leg adopts at the next ``__call__`` through the
same state-compatible rebuild the tuner uses — one optimizer state
tree, re-jit only, and a leg-memoizing builder flips back to an
already-compiled program with zero recompiles (the contract
tests/test_transport.py pins and tests/test_controller.py re-asserts
under controller-driven flips).
"""

from __future__ import annotations

import json
import logging
from typing import Any, Callable, Dict, Optional, Tuple

from .actions import Action

log = logging.getLogger("horovod_tpu.control")

__all__ = ["LEGS_KV_KEY", "legs_for_action", "publish_legs",
           "poll_legs", "LegListener"]

LEGS_KV_KEY = "/controller/legs"

# Action kind -> builder keyword the AutotunedStep rebuild understands.
_LEG_KW = {"flip_transport": "transport", "toggle_overlap": "overlap",
           "toggle_zero": "zero"}


def legs_for_action(action: Action) -> Dict[str, Any]:
    """Translate one comm-shaped action into AutotunedStep builder
    kwargs ({} for actions that don't move a leg)."""
    if action.kind == "retune_bucket":
        return {"threshold_bytes": int(action.param("bucket_bytes"))}
    kw = _LEG_KW.get(action.kind)
    if kw is None:
        return {}
    to = action.param("to")
    if action.kind == "flip_transport":
        return {kw: to == "hier"}
    return {kw: bool(to)}


def publish_legs(kv, legs: Dict[str, Any], seq: int) -> bool:
    """Driver side: write the override document to the rendezvous KV.
    Works against anything exposing either ``put(key, bytes)`` or the
    in-process ``lock``/``store`` pair the elastic KV server has."""
    doc = json.dumps({"seq": int(seq), "legs": dict(legs)},
                     sort_keys=True).encode()
    try:
        if hasattr(kv, "put"):
            kv.put(LEGS_KV_KEY, doc)
        else:
            with kv.lock:
                kv.store[LEGS_KV_KEY] = doc
        return True
    except Exception as e:    # actuation must never sink the driver
        log.warning("controller leg publish failed: %s", e)
        return False


def poll_legs(kv_get: Callable[[str], Optional[bytes]],
              last_seq: int) -> Tuple[int, Dict[str, Any]]:
    """Worker side: one KV read; returns ``(seq, legs)`` — legs is
    empty when nothing new was published since ``last_seq``."""
    try:
        raw = kv_get(LEGS_KV_KEY)
    except Exception:
        return last_seq, {}
    if not raw:
        return last_seq, {}
    try:
        doc = json.loads(raw.decode() if isinstance(raw, bytes) else raw)
        seq = int(doc.get("seq", 0))
        if seq <= last_seq:
            return last_seq, {}
        return seq, dict(doc.get("legs") or {})
    except (ValueError, AttributeError, TypeError):
        return last_seq, {}


class LegListener:
    """Per-worker adoption loop body: poll the KV override key and
    queue fresh legs on the wrapped :class:`AutotunedStep`.

    ::

        listener = control.apply.LegListener(step, kv_client.get_local)
        ...
        listener.poll()     # at each commit point / step boundary
    """

    def __init__(self, step, kv_get: Callable[[str], Optional[bytes]]):
        self._step = step
        self._kv_get = kv_get
        self._seq = 0

    def poll(self) -> Dict[str, Any]:
        """Returns the legs adopted this poll ({} when none)."""
        seq, legs = poll_legs(self._kv_get, self._seq)
        if seq == self._seq or not legs:
            return {}
        self._seq = seq
        self._step.apply_leg(**legs)
        log.info("controller legs adopted at seq %d: %s", seq, legs)
        return legs
