"""The policy controller: guardrailed decide/apply/verify over the
anomaly event stream.

One :meth:`PolicyController.tick` (the driver calls it every discovery
tick; tests drive it synthetically):

1. **verify** — every previously applied action is watched for
   ``HVDT_CONTROLLER_RECOVERY_WINDOW`` ticks: if the deviation ratio
   falls back under the hysteresis exit band the decision is marked
   ``recovered`` (observed delta recorded next to the predicted one);
   if the window expires the never-worse rollback re-applies the
   inverse action and the action kind goes on a doubled cooldown.
2. **decide** — each new event is expanded to candidates
   (:func:`~.actions.candidates_for`), priced offline
   (:class:`~.pricing.ActionPricer`), and the best candidate clearing
   the guardrails is applied through the bound applier — at a step
   boundary by construction, since appliers either queue on
   ``AutotunedStep.apply_leg`` (adopted at the next ``__call__``) or
   ride driver seams that only act at the next rendezvous/commit.

Guardrails, in suppression-precedence order (each suppression is an
auditable record too):

* **budget** — ``HVDT_CONTROLLER_MAX_ACTIONS`` total applies per run;
* **hysteresis** — a trigger series must overshoot the ENTER band to
  act and come back under the EXIT band before the same trigger key
  may act again (no flapping on an oscillating series);
* **cooldown** — ``HVDT_CONTROLLER_COOLDOWN_S`` per action kind
  (doubled after a rollback), so one bad actuator can't thrash;
* **min gain** — candidates must clear
  ``HVDT_CONTROLLER_MIN_GAIN_S`` predicted seconds.

Every decision — applied, suppressed, observed (dry-run), recovered,
or rolled back — is appended to the ``HVDT_EVENT_LOG`` JSONL as a
``controller_decision`` / ``controller_outcome`` record: event ->
candidates -> predicted deltas -> chosen action -> observed outcome,
replayable offline.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence

from .actions import Action, ControllerState, candidates_for
from .pricing import ActionPricer, PricedAction
from ..common import config

log = logging.getLogger("horovod_tpu.control")

__all__ = ["ControllerConfig", "Decision", "PolicyController",
           "get_controller", "reset"]


@dataclasses.dataclass
class ControllerConfig:
    """Knob bundle (``HVDT_CONTROLLER_*``; see docs/knobs.md)."""

    mode: str = "act"                 # act | observe (dry-run)
    cooldown_s: float = 60.0
    enter_ratio: float = 1.2          # hysteresis: act at/above this
    exit_ratio: float = 1.05          # ...re-arm/recover below this
    recovery_window: int = 3          # verification ticks before rollback
    min_gain_s: float = 0.0
    max_actions: int = 0              # 0 = unbounded

    @classmethod
    def from_env(cls) -> "ControllerConfig":
        raw = (config.get_str("HVDT_CONTROLLER") or "").strip().lower()
        mode = "observe" if raw in ("observe", "dry-run", "dryrun") \
            else "act"
        return cls(
            mode=mode,
            cooldown_s=config.get_float("HVDT_CONTROLLER_COOLDOWN_S"),
            enter_ratio=config.get_float("HVDT_CONTROLLER_ENTER_RATIO"),
            exit_ratio=config.get_float("HVDT_CONTROLLER_EXIT_RATIO"),
            recovery_window=config.get_int(
                "HVDT_CONTROLLER_RECOVERY_WINDOW"),
            min_gain_s=config.get_float("HVDT_CONTROLLER_MIN_GAIN_S"),
            max_actions=config.get_int("HVDT_CONTROLLER_MAX_ACTIONS"))


@dataclasses.dataclass
class Decision:
    """One decide() outcome — the in-memory twin of the JSONL record."""

    event: Dict[str, Any]
    candidates: List[PricedAction]
    chosen: Optional[PricedAction]
    outcome: str          # applied | observed | suppressed:<reason>
    step: Optional[int] = None
    ts: float = 0.0

    def to_record(self) -> Dict[str, Any]:
        return {
            "kind": "controller_decision",
            "event": {k: self.event.get(k) for k in
                      ("kind", "scope", "pod", "rank", "ratio", "step")
                      if k in self.event},
            "candidates": [p.to_dict() for p in self.candidates],
            "chosen": self.chosen.to_dict() if self.chosen else None,
            "outcome": self.outcome,
            "step": self.step,
        }


@dataclasses.dataclass
class _PendingVerify:
    """A committed action awaiting deviation recovery."""

    decision: Decision
    prior_state: ControllerState
    trigger_key: str
    deviation_at_decision: Optional[float]
    ticks_left: int
    rollback: Optional[Action]


class PolicyController:
    """See module docstring.  Thread-safe; the driver ticks it from the
    discovery thread while tests tick it inline."""

    def __init__(self, cfg: Optional[ControllerConfig] = None,
                 pricer: Optional[ActionPricer] = None,
                 state: Optional[ControllerState] = None,
                 event_log=None, registry=None,
                 clock: Callable[[], float] = time.monotonic):
        self.cfg = cfg or ControllerConfig.from_env()
        self.pricer = pricer or ActionPricer()
        self.state = state or ControllerState()
        self._explicit_log = event_log
        self._clock = clock
        self._lock = threading.Lock()
        self._appliers: Dict[str, Callable[[Action], bool]] = {}
        self._cooldown_until: Dict[str, float] = {}
        self._cooldown_s: Dict[str, float] = {}
        self._disarmed: set = set()     # trigger keys awaiting exit band
        self._pending: List[_PendingVerify] = []
        self._applied_total = 0
        reg = registry
        if reg is None:
            from ..telemetry.metrics import default_registry

            reg = default_registry()
        self._m_decisions = reg.counter(
            "hvdt_controller_decisions_total",
            "Controller decisions by action kind and outcome")
        self._m_suppressed = reg.counter(
            "hvdt_controller_suppressed_total",
            "Controller decisions suppressed by guardrail")
        self._m_rollbacks = reg.counter(
            "hvdt_controller_rollbacks_total",
            "Never-worse rollbacks (deviation failed to recover)")
        self._m_pending = reg.gauge(
            "hvdt_controller_pending",
            "Applied actions awaiting deviation-recovery verification")
        self._m_predicted = reg.gauge(
            "hvdt_controller_predicted_delta_s",
            "Predicted step-seconds delta of the last applied action")
        self._m_observed = reg.gauge(
            "hvdt_controller_observed_delta_s",
            "Observed deviation-ratio delta of the last verified action")

    # -- wiring ------------------------------------------------------------

    def bind(self, kind: str, fn: Callable[[Action], bool]) -> None:
        """Attach the applier for one action kind (driver seams or test
        stubs).  The applier returns True when the action took."""
        self._appliers[kind] = fn

    def bind_appliers(self, appliers: Dict[str, Callable[[Action], bool]]
                      ) -> None:
        for k, fn in appliers.items():
            self.bind(k, fn)

    @property
    def pending(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- event log ---------------------------------------------------------

    def _emit(self, doc: Dict[str, Any]) -> Dict[str, Any]:
        sink = self._explicit_log
        if sink is None:
            from ..telemetry import anomaly

            sink = anomaly.get_event_log()
        if sink is not None:
            return sink.emit(doc)
        return doc

    # -- the loop ----------------------------------------------------------

    def tick(self, events: Sequence[Dict[str, Any]] = (),
             deviation_ratio: Optional[float] = None,
             observed_step_s: Optional[float] = None,
             step: Optional[int] = None) -> List[Decision]:
        """One control tick: verify pending actions, then decide on the
        new events.  Returns the decisions made this tick."""
        if observed_step_s is not None:
            self.state.step_time_s = float(observed_step_s)
        self._verify(deviation_ratio, step)
        out = []
        for ev in events or ():
            d = self.decide(ev, deviation_ratio=deviation_ratio,
                            step=step)
            if d is not None:
                out.append(d)
        with self._lock:
            self._m_pending.set(len(self._pending))
        return out

    def _trigger_key(self, event: Dict[str, Any]) -> str:
        return (f"{event.get('kind', '')}:{event.get('scope', '')}:"
                f"{event.get('pod') or event.get('rank') or ''}")

    def decide(self, event: Dict[str, Any],
               deviation_ratio: Optional[float] = None,
               step: Optional[int] = None) -> Optional[Decision]:
        """Price one event's candidates and apply the best one that
        clears every guardrail.  Returns None for unmapped events."""
        now = self._clock()
        candidates = candidates_for(event, self.state)
        if not candidates:
            return None
        priced = self.pricer.rank(self.state, candidates)
        key = self._trigger_key(event)
        if step is None:
            step = event.get("step")
        decision = Decision(event=event, candidates=priced, chosen=None,
                            outcome="", step=step, ts=now)

        with self._lock:
            if (self.cfg.max_actions
                    and self._applied_total >= self.cfg.max_actions):
                return self._suppress(decision, "budget")
            ratio = float(event.get("ratio") or 0.0)
            if ratio and ratio < self.cfg.enter_ratio:
                return self._suppress(decision, "hysteresis")
            if key in self._disarmed:
                return self._suppress(decision, "hysteresis")
            chosen: Optional[PricedAction] = None
            cooled = False
            for p in priced:
                if p.predicted_delta_s < self.cfg.min_gain_s:
                    break   # ranked — nothing further clears the bar
                if now < self._cooldown_until.get(p.action.kind, 0.0):
                    cooled = True
                    continue
                chosen = p
                break
            if chosen is None:
                return self._suppress(
                    decision, "cooldown" if cooled else "no_gain")
            decision.chosen = chosen
            if self.cfg.mode == "observe":
                decision.outcome = "observed"
                self._m_decisions.inc(action=chosen.action.kind,
                                      outcome="observed")
                self._emit(decision.to_record())
                return decision
            applier = self._appliers.get(chosen.action.kind)

        ok = False
        if applier is not None:
            try:
                ok = bool(applier(chosen.action))
            except Exception as e:    # an actuator must never sink us
                log.warning("controller applier %s failed: %s",
                            chosen.action.kind, e)
        with self._lock:
            if not ok:
                return self._suppress(decision, "apply_failed")
            decision.outcome = "applied"
            self._applied_total += 1
            cd = self._cooldown_s.get(chosen.action.kind,
                                      self.cfg.cooldown_s)
            self._cooldown_until[chosen.action.kind] = now + cd
            self._disarmed.add(key)
            prior = self.state
            self.state = self.pricer.apply(prior, chosen.action)
            self._pending.append(_PendingVerify(
                decision=decision, prior_state=prior, trigger_key=key,
                deviation_at_decision=deviation_ratio,
                ticks_left=max(1, self.cfg.recovery_window),
                rollback=self.pricer.inverse(prior, chosen.action)))
            self._m_decisions.inc(action=chosen.action.kind,
                                  outcome="applied")
            self._m_predicted.set(chosen.predicted_delta_s)
        self._emit(decision.to_record())
        log.info("controller applied %s (predicted %.3gs/step) on %s",
                 chosen.action.kind, chosen.predicted_delta_s,
                 event.get("kind"))
        return decision

    def _suppress(self, decision: Decision, reason: str) -> Decision:
        """(lock held) Record a guardrail suppression."""
        decision.outcome = f"suppressed:{reason}"
        self._m_suppressed.inc(reason=reason)
        self._emit(decision.to_record())
        return decision

    # -- verification / rollback -------------------------------------------

    def _verify(self, deviation_ratio: Optional[float],
                step: Optional[int]) -> None:
        rollbacks: List[_PendingVerify] = []
        with self._lock:
            still: List[_PendingVerify] = []
            for p in self._pending:
                recovered = (deviation_ratio is not None
                             and deviation_ratio <= self.cfg.exit_ratio)
                if recovered:
                    before = p.deviation_at_decision
                    observed = ((before - deviation_ratio)
                                if before is not None else None)
                    self._disarmed.discard(p.trigger_key)
                    self._m_decisions.inc(
                        action=p.decision.chosen.action.kind,
                        outcome="recovered")
                    if observed is not None:
                        self._m_observed.set(observed)
                    self._emit({
                        "kind": "controller_outcome",
                        "outcome": "recovered",
                        "action": p.decision.chosen.action.to_dict(),
                        "predicted_delta_s":
                            p.decision.chosen.predicted_delta_s,
                        "deviation_before": before,
                        "deviation_after": deviation_ratio,
                        "observed_delta": observed,
                        "step": step,
                    })
                    continue
                p.ticks_left -= 1
                if p.ticks_left <= 0:
                    rollbacks.append(p)
                else:
                    still.append(p)
            self._pending = still
        for p in rollbacks:
            self._rollback(p, deviation_ratio, step)

    def _rollback(self, p: _PendingVerify,
                  deviation_ratio: Optional[float],
                  step: Optional[int]) -> None:
        """Never-worse: the deviation did not recover inside the window
        — re-apply the inverse leg (one-way actions just expire) and
        double this action kind's cooldown."""
        kind = p.decision.chosen.action.kind
        ok = None
        if p.rollback is not None:
            applier = self._appliers.get(kind)
            if applier is not None:
                try:
                    ok = bool(applier(p.rollback))
                except Exception as e:
                    log.warning("controller rollback %s failed: %s",
                                kind, e)
                    ok = False
            if ok:
                with self._lock:
                    self.state = self.pricer.apply(self.state,
                                                   p.rollback)
        with self._lock:
            now = self._clock()
            cd = 2 * self._cooldown_s.get(kind, self.cfg.cooldown_s)
            self._cooldown_s[kind] = cd
            self._cooldown_until[kind] = now + cd
            # The trigger stays disarmed until the series itself exits
            # the band — rollback is not permission to flap.
            self._m_rollbacks.inc()
            self._m_decisions.inc(action=kind, outcome="rolled_back")
        self._emit({
            "kind": "controller_outcome",
            "outcome": "rolled_back" if p.rollback is not None
            else "expired",
            "action": p.decision.chosen.action.to_dict(),
            "rollback": (p.rollback.to_dict()
                         if p.rollback is not None else None),
            "rollback_applied": ok,
            "predicted_delta_s": p.decision.chosen.predicted_delta_s,
            "deviation_before": p.deviation_at_decision,
            "deviation_after": deviation_ratio,
            "step": step,
        })
        log.warning("controller rolled back %s (deviation %.3s did not "
                    "recover)", kind, str(deviation_ratio))


# ---------------------------------------------------------------------------
# Zero-overhead engagement (the faults/telemetry/overlap idiom)
# ---------------------------------------------------------------------------


_lock = threading.Lock()
_cached_env: Optional[str] = "\0unset"
_cached: Optional[PolicyController] = None


def get_controller() -> Optional[PolicyController]:
    """The process-wide controller, or ``None`` when ``HVDT_CONTROLLER``
    is unset/empty/0 — one cached env read, no object, no thread."""
    global _cached_env, _cached
    raw = os.environ.get("HVDT_CONTROLLER")
    if raw != _cached_env:
        with _lock:
            if raw != _cached_env:
                val = (raw or "").strip().lower()
                if val and val not in ("0", "off", "false"):
                    _cached = PolicyController()
                else:
                    _cached = None
                _cached_env = raw
    return _cached


def reset() -> None:
    """Drop the cached controller (test isolation)."""
    global _cached_env, _cached
    with _lock:
        _cached_env = "\0unset"
        _cached = None
