"""Action vocabulary + the event-class -> candidate-actions mapping.

Every remediation the framework can perform mid-run is one
:class:`Action`: a kind from :data:`ACTION_KINDS` plus a small
parameter tuple (hashable, JSONable — decisions are replayed from the
event log).  :func:`candidates_for` turns one anomaly event (the JSONL
documents ``telemetry/anomaly.py`` emits) into the candidate set the
pricer ranks; the mapping is a plain table (:data:`EVENT_ACTIONS`) so
tests pin it and operators can read it.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

__all__ = ["ACTION_KINDS", "Action", "ControllerState", "EVENT_ACTIONS",
           "candidates_for"]


# The complete actuator set.  flip_transport / retune_bucket /
# toggle_overlap / toggle_zero apply through AutotunedStep.apply_leg
# (state-compatible rebuild, no recompile on flip-back); evict_pod /
# resize ride the elastic driver; scale_replicas rides the serve
# autoscaler's KV target override.
ACTION_KINDS = ("flip_transport", "retune_bucket", "toggle_overlap",
                "toggle_zero", "evict_pod", "resize", "scale_replicas")

# Actions with an exact inverse — eligible for the never-worse
# rollback.  Membership changes (evict/resize) and replica scaling are
# one-way: the evicted pod re-joins through the blacklist cooldown, not
# through the controller.
REVERSIBLE_KINDS = frozenset(
    {"flip_transport", "retune_bucket", "toggle_overlap", "toggle_zero"})


@dataclasses.dataclass(frozen=True)
class Action:
    """One candidate remediation.  ``params`` is a sorted key/value
    tuple so Action is hashable (cooldown bookkeeping keys on it)."""

    kind: str
    params: Tuple[Tuple[str, Any], ...] = ()
    reason: str = ""

    def __post_init__(self) -> None:
        if self.kind not in ACTION_KINDS:
            raise ValueError(f"unknown action kind {self.kind!r} "
                             f"(one of {ACTION_KINDS})")

    def param(self, key: str, default: Any = None) -> Any:
        for k, v in self.params:
            if k == key:
                return v
        return default

    @property
    def reversible(self) -> bool:
        return self.kind in REVERSIBLE_KINDS

    def to_dict(self) -> Dict[str, Any]:
        return {"kind": self.kind, "params": dict(self.params),
                "reason": self.reason}

    @staticmethod
    def make(kind: str, reason: str = "", **params: Any) -> "Action":
        return Action(kind=kind,
                      params=tuple(sorted(params.items())),
                      reason=reason)


@dataclasses.dataclass
class ControllerState:
    """The controller's picture of the knobs it may move — the pricing
    input and the thing appliers mutate.  Mirrors the autotune leg
    dimensions plus the elastic/serve geometry."""

    grad_bytes: float = 64 * 2 ** 20
    bucket_bytes: int = 32 * 2 ** 20
    transport_hier: bool = False
    ici_wire: str = "f32"
    dcn_wire: str = "f32"
    overlap: bool = True
    zero: bool = False
    pods: int = 1
    chips_per_pod: int = 4
    pod_size: int = 4
    replicas: int = 0
    max_replicas: int = 0
    step_time_s: Optional[float] = None

    @property
    def n_buckets(self) -> int:
        return max(1, int(round(self.grad_bytes
                                / max(1, self.bucket_bytes))))

    def to_dict(self) -> Dict[str, Any]:
        return dataclasses.asdict(self)


# Event kind -> ordered candidate action kinds.  Order is a tie-break
# only — the pricer ranks by predicted delta; equal-delta candidates
# keep this (most-specific-remedy-first) order.
EVENT_ACTIONS: Dict[str, Tuple[str, ...]] = {
    # A pod (or rank) stepping slower than the cluster: cut it loose,
    # or cheapen the exchange it is bottlenecking.
    "step_time_shift": ("evict_pod", "flip_transport", "retune_bucket"),
    "straggler_onset": ("evict_pod", "resize"),
    # Throughput sagging without a named culprit: shrink the world to
    # healthy pods, or (serving) add replicas.
    "goodput_drop": ("resize", "scale_replicas"),
    # Compute utilization down with comm exposed: move comm under
    # compute or re-bucket the exchange.
    "mfu_regression": ("toggle_overlap", "retune_bucket"),
    # Wire-byte series drifted off the predicted schedule: the
    # transport leg or bucketing no longer matches the topology.
    "wire_drift": ("flip_transport", "retune_bucket"),
    # Observed vs cost-model deviation: try every cheap leg.
    "perf_deviation": ("flip_transport", "toggle_overlap",
                       "toggle_zero", "retune_bucket"),
}


def _bucket_candidates(state: ControllerState, reason: str
                       ) -> List[Action]:
    """Retune candidates: halve and double the current threshold (the
    two adjacent log2 legs the autotuner itself would explore)."""
    out = []
    for factor in (2.0, 0.5):
        nb = int(state.bucket_bytes * factor)
        if 2 ** 20 <= nb <= 2 ** 31:
            out.append(Action.make("retune_bucket", reason=reason,
                                   bucket_bytes=nb,
                                   prev_bucket_bytes=state.bucket_bytes))
    return out


def candidates_for(event: Dict[str, Any],
                   state: ControllerState) -> List[Action]:
    """Expand one anomaly event into concrete candidate actions against
    the current knob state.  Unknown event kinds map to no candidates
    (the controller never guesses)."""
    kinds = EVENT_ACTIONS.get(str(event.get("kind", "")), ())
    reason = (f"{event.get('kind')}@"
              f"{event.get('scope', 'cluster')}")
    pod = str(event.get("pod") or "")
    ratio = float(event.get("ratio") or 1.0)
    out: List[Action] = []
    for kind in kinds:
        if kind == "flip_transport":
            if state.pods > 1:
                out.append(Action.make(
                    "flip_transport", reason=reason,
                    to="flat" if state.transport_hier else "hier",
                    ratio=ratio))
        elif kind == "retune_bucket":
            out.extend(_bucket_candidates(state, reason))
        elif kind == "toggle_overlap":
            out.append(Action.make("toggle_overlap", reason=reason,
                                   to=not state.overlap))
        elif kind == "toggle_zero":
            out.append(Action.make("toggle_zero", reason=reason,
                                   to=not state.zero))
        elif kind == "evict_pod":
            # Only a pod-attributed event names an evictee, and never
            # the last pod standing.
            if pod and state.pods > 1:
                out.append(Action.make("evict_pod", reason=reason,
                                       pod=pod, ratio=ratio))
        elif kind == "resize":
            if state.pods > 1:
                np_new = (state.pods - 1) * state.pod_size
                out.append(Action.make("resize", reason=reason,
                                       min_np=np_new, max_np=np_new,
                                       pods=state.pods - 1,
                                       ratio=ratio))
        elif kind == "scale_replicas":
            if state.replicas and state.replicas < state.max_replicas:
                out.append(Action.make("scale_replicas", reason=reason,
                                       target=state.replicas + 1,
                                       ratio=ratio))
    return out
