"""DistributedOptimizer — the gradient-averaging wrapper.

TPU-native re-conception of the reference's optimizer wrappers
(ref: torch/optimizer.py — _DistributedOptimizer grad-hooks :131-253,
synchronize :255-302, factory :516-605; tensorflow/__init__.py:627
DistributedOptimizer, _DistributedGradientTape :758-842;
gradient_aggregation*.py backward_passes_per_step).

Design translation: the reference hooks per-parameter gradient-ready events
and enqueues named async allreduces that the background thread fuses.  Under
jit there are no per-tensor ready events — the whole gradient pytree is
materialized by ``jax.grad`` — so the idiomatic equivalent is an optax
``GradientTransformation`` that buckets the gradient pytree into fused
collectives (ops/device.fused_allreduce) as the FIRST link of the optimizer
chain.  XLA then overlaps the bucketed all-reduces with the parameter
update and neighbouring compute (the async-dispatch analog of hook-driven
overlap).

``backward_passes_per_step`` maps to local gradient accumulation with the
collective executed only on boundary steps (ref:
gradient_aggregation.py) — expressed with ``optax.MultiSteps`` around the
communicating chain.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from .common.types import ReduceOp
from .ops import device as dev
from .ops.compression import Compression, Compressor

__all__ = ["DistributedOptimizer", "allreduce_gradients",
           "DistributedGradientTransformation", "microbatch_gradients"]


def microbatch_gradients(grad_fn, params, batch, num_microbatches: int,
                         axis="dp", op: ReduceOp = ReduceOp.AVERAGE,
                         compression=None,
                         threshold_bytes: Optional[int] = None):
    """Accumulate gradients over micro-batches, then communicate ONCE.

    The TPU-idiomatic equivalent of the reference's
    ``backward_passes_per_step`` bandwidth optimization
    (ref: gradient_aggregation.py — skip allreduce on non-boundary
    backward passes): instead of conditional collectives across optimizer
    steps, micro-batches are scanned *inside* one jitted step and a single
    fused collective reduces the accumulated gradient.

    Args:
      grad_fn: ``grad_fn(params, microbatch) -> grads`` pytree.
      batch: pytree whose leaves have a leading axis divisible by
        ``num_microbatches``; reshaped to (k, b/k, ...) and scanned.

    Returns the communicated (averaged by default) gradient pytree.
    """
    import jax
    import jax.numpy as jnp

    def reshape(leaf):
        return leaf.reshape((num_microbatches, -1) + leaf.shape[1:])

    micro = jax.tree.map(reshape, batch)

    # Accumulate float gradients in f32 regardless of the compute dtype:
    # summing k bf16 micro-gradients in bf16 loses low bits every add
    # (8 mantissa bits — by 8 microbatches the accumulated drift is
    # visible in the loss trajectory; tests/test_zero.py pins the
    # regression).  One widen per micro-step, one cast back at the end.
    def acc_dtype(t):
        return (jnp.float32
                if jnp.issubdtype(jnp.result_type(t), jnp.floating)
                else jnp.result_type(t))

    def body(acc, mb):
        g = grad_fn(params, mb)
        return jax.tree.map(
            lambda a, gg: a + gg.astype(a.dtype), acc, g), None

    zero = jax.tree.map(
        lambda t: jnp.zeros(t.shape, acc_dtype(t)), params)
    total, _ = jax.lax.scan(body, zero, micro)
    total = jax.tree.map(
        lambda t, p: (t / num_microbatches).astype(
            jnp.result_type(p)), total, params)
    from .ops.compression import Compression as _C

    return allreduce_gradients(total, axis=axis, op=op,
                               compression=compression or _C.none,
                               threshold_bytes=threshold_bytes)


def pvary_tree(tree, axis="dp"):
    """Mark a replicated pytree as per-rank *varying* over ``axis``.

    Differentiating w.r.t. unvarying params under shard_map inserts the
    gradient psum automatically — which destroys the per-rank gradients
    Adasum (and custom reductions) need.  Differentiate w.r.t. the
    *varying* params (pcast applied OUTSIDE the loss closure — its
    transpose is itself a psum)::

        loss, grads = jax.value_and_grad(loss_fn)(
            hvd.optimizer.pvary_tree(params, "dp"))

    then pass the varying grads to DistributedOptimizer(op=hvd.Adasum).
    """
    import jax
    from jax import lax

    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    pcast = getattr(lax, "pcast", None)
    if pcast is None:
        # jax builds without vma tracking (0.4.x): every value is
        # already treated as varying (ops.device.is_varying returns
        # True conservatively), so the mark is the identity.
        return tree
    return jax.tree.map(lambda t: pcast(t, axes, to="varying"), tree)


def _axis_bound(axis) -> bool:
    """True when ``axis`` is a bound manual mesh axis (i.e. we are inside a
    shard_map body).  Under plain auto-sharded jit/pjit there are no bound
    axes — gradients there are already globally correct and the comm link
    must be the identity.  Probed through the guarded size helper so JAX
    builds without ``lax.axis_size`` (<= 0.4.x) still detect bound axes
    instead of silently skipping the collective."""
    axes = (axis,) if isinstance(axis, str) else tuple(axis)
    try:
        for a in axes:
            dev._axis_size_static(a)
        return True
    except Exception:
        return False


def allreduce_gradients(grads, axis="dp", op: ReduceOp = ReduceOp.AVERAGE,
                        compression: Compressor = Compression.none,
                        threshold_bytes: Optional[int] = None,
                        prescale_factor: float = 1.0,
                        postscale_factor: float = 1.0,
                        _exchange: Optional[Any] = None):
    """Functional gradient allreduce for custom train steps.

    The building block DistributedOptimizer uses; exposed for users who
    write their own update loops (the analog of calling hvd.allreduce on
    each grad, but bucketed/fused).

    Gradient-aware semantics: "the update uses the average (or sum) of
    per-rank gradients" in every regime —

    * shard_map, grads varying over ``axis`` (params were per-shard /
      pvary'd): fused psum collectives, ÷n for Average.
    * shard_map, grads UNVARYING over ``axis``: modern JAX AD has already
      cross-shard-summed the cotangent of replicated params (see
      ops.device.is_varying), so Average is ÷n and Sum is the identity —
      no collective issued at all.
    * plain auto-sharded jit (no bound axis): gradients are already global;
      identity.
    """
    wire_dtype = compression.wire_dtype
    if wire_dtype == "bfloat16":
        wire_dtype = jnp.bfloat16
    if not _axis_bound(axis):
        return grads

    import jax

    leaves, treedef = jax.tree.flatten(grads)
    if not leaves:
        return grads
    n = 1
    for a in ((axis,) if isinstance(axis, str) else tuple(axis)):
        n *= dev._axis_size_static(a)

    varying_idx = [i for i, l in enumerate(leaves) if dev.is_varying(l, axis)]
    unvarying_idx = [i for i in range(len(leaves)) if i not in set(varying_idx)]

    out = list(leaves)
    if unvarying_idx:
        if op == ReduceOp.ADASUM:
            raise ValueError(
                "Adasum needs per-rank gradients, but these gradients are "
                "unvarying over the mesh axis (already summed by AD). "
                "Compute grads w.r.t. pvary'd params, e.g. "
                "jax.lax.pcast(params, to='varying').")
        scale = prescale_factor * postscale_factor
        if op == ReduceOp.AVERAGE:
            scale = scale / n
        elif op != ReduceOp.SUM:
            raise ValueError(f"Unsupported gradient reduce op: {op}")
        for i in unvarying_idx:
            out[i] = out[i] * scale if scale != 1.0 else out[i]
    if varying_idx:
        # Overlap routing (ops/overlap.py): HVDT_OVERLAP=on swaps the
        # monolithic fused_allreduce for the dependency-ordered bucket
        # schedule; off/unset returns fused_allreduce ITSELF (identity
        # contract — the pre-existing code object, zero wrappers).
        # ``_exchange`` is the ZeRO hook: the grads-stage comm
        # transformation passes ops.zero.rs_exchange here so the same
        # gradient-aware varying logic drives the reduce-scatter wire.
        from .ops.overlap import exchange_fn

        reduced = (_exchange or exchange_fn())(
            [leaves[i] for i in varying_idx], axis=axis, op=op,
            threshold_bytes=threshold_bytes,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor, wire_dtype=wire_dtype)
        for i, v in zip(varying_idx, reduced):
            out[i] = v
    # NOTE: reduced outputs are intentionally left unvarying (replicated) —
    # that is their true type after a psum, it lets users keep P() out_specs
    # for params/opt state, and it keeps optax.MultiSteps' internal lax.cond
    # type-stable.
    return jax.tree.unflatten(treedef, out)


def DistributedGradientTransformation(
        axis="dp", op: ReduceOp = ReduceOp.AVERAGE,
        compression: Compressor = Compression.none,
        threshold_bytes: Optional[int] = None,
        prescale_factor: float = 1.0,
        postscale_factor: float = 1.0,
        zero: Optional[Any] = None):
    """An optax transformation that allreduces incoming gradients.

    ``zero`` (default: the ``HVDT_ZERO`` env stage) at ``grads`` or
    beyond swaps the fused-allreduce wire for the explicit
    reduce-scatter + invariant-allgather split (ops/zero.rs_exchange —
    same reduced values, deferrable allgather); unset keeps the
    pre-existing replicated exchange as the identical code objects.
    """
    import optax

    from .ops import zero as _zero

    stage = _zero.resolve_stage(zero)
    exchange = None if stage is None else _zero.rs_exchange

    def init_fn(params):
        del params
        return optax.EmptyState()

    def update_fn(updates, state, params=None):
        del params
        updates = allreduce_gradients(
            updates, axis=axis, op=op, compression=compression,
            threshold_bytes=threshold_bytes,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor,
            _exchange=exchange)
        return updates, state

    return optax.GradientTransformation(init_fn, update_fn)


def DistributedOptimizer(optimizer,
                         *,
                         axis="dp",
                         op: ReduceOp = ReduceOp.AVERAGE,
                         compression: Optional[Compressor] = None,
                         backward_passes_per_step: int = 1,
                         threshold_bytes: Optional[int] = None,
                         prescale_factor: float = 1.0,
                         postscale_factor: float = 1.0,
                         zero: Optional[Any] = None,
                         pipeline: Optional[str] = None,
                         expert: Optional[str] = None):
    """Wrap an optax optimizer so gradients are averaged across the mesh
    axis before the update (ref: torch/optimizer.py:516 DistributedOptimizer
    factory; same call-shape philosophy: wrap and use as usual).

    Use inside a shard_map/pjit step function where ``axis`` is a bound mesh
    axis name::

        opt = hvd.DistributedOptimizer(optax.adam(1e-3))
        updates, opt_state = opt.update(grads, opt_state, params)

    The update side composes with the fused Pallas optimizer kernels
    unchanged — ``hvd.DistributedOptimizer(hvd.fused_adam(1e-3))`` runs
    the comm chain into a single-HBM-pass Adam update
    (ops/optim_kernels.py; ineligible leaves fall back to identical XLA
    math automatically).

    Args:
      optimizer: the optax GradientTransformation to wrap.
      axis: mesh axis to reduce over (data-parallel axis).
      op: Average (default), Sum, or Adasum.
      compression: Compression.none / .bf16 / .fp16 — wire dtype for the
        fused collectives — or Compression.int8 for the block-scaled
        quantized wire (horovod_tpu/quant; pair with
        ``hvd.quant.with_error_feedback`` for f32-parity convergence).
        None (default) resolves from the environment
        (``HVDT_COMPRESSION`` / ``HVDT_QUANT`` — Compression.from_env).
      backward_passes_per_step: accumulate this many micro-batch gradients
        locally between collectives (ref: gradient_aggregation.py).
      zero: ZeRO state-sharding stage (ops/zero.py) — ``"grads"`` (the
        reduce-scatter wire, any optax optimizer), ``"states"``
        (sharded moments + shard-local fused update + delta allgather;
        requires ``hvd.fused_adam``/``hvd.fused_sgd``), ``"params"``
        (params sharded between steps), a ``zero.ZeroSpec`` for explicit
        ``num_shards``/threshold, or None (default) to read
        ``HVDT_ZERO``.  Unset/off keeps the replicated chain as the
        identical pre-existing code objects (identity-tested).
      pipeline: mesh axis name the step's parameters are PIPELINE-sharded
        over (parallel.pipeline_1f1b stages).  A sharded axis is the
        opposite of a reduce axis — every rank owns different stage
        params, so their gradients must stay per-rank.  Declaring it
        here is the 4D-mesh contract: the wrapper refuses an ``axis``
        that overlaps it (reducing over ``pp`` would average unrelated
        stages' gradients into garbage), and ZeRO keeps sharding state
        WITHIN the remaining ``axis`` group only.
      expert: mesh axis name expert parameters are sharded over
        (parallel.moe_dispatch_combine).  Same contract as ``pipeline``:
        per-rank experts, per-rank gradients, excluded from the reduce
        group.
    """
    import optax

    from .ops import zero as _zero

    reduce_axes = (axis,) if isinstance(axis, str) else tuple(axis)
    for kind, sharded in (("pipeline", pipeline), ("expert", expert)):
        if sharded is not None and sharded in reduce_axes:
            raise ValueError(
                f"{kind}={sharded!r} names a parameter-SHARDED mesh axis "
                f"but axis={axis!r} would reduce gradients over it — "
                f"every {sharded} rank owns different parameters, so "
                "averaging across it destroys them.  Drop it from the "
                "reduce group (ZeRO then shards state within the "
                "remaining data-parallel group).")
    _stage = _zero.resolve_stage(zero)
    if compression is None:
        compression = Compression.from_env()
    if _stage in ("states", "params"):
        zspec = zero if isinstance(zero, _zero.ZeroSpec) else None
        return _zero.zero_from_optimizer(
            optimizer, stage=_stage, axis=axis, op=op,
            num_shards=(zspec.num_shards if zspec else None),
            threshold_bytes=(threshold_bytes if threshold_bytes is not None
                             else (zspec.threshold_bytes if zspec
                                   else None)),
            wire_dtype=compression.wire_dtype,
            prescale_factor=prescale_factor,
            postscale_factor=postscale_factor)
    from .telemetry.instrument import get_recorder

    _rec = get_recorder()
    if _rec is not None:
        # Construction-time config record: the jit-traced update can't
        # report per-step from inside the program, but which wire format
        # / reduce op the job trains with is the label every collective
        # series gets joined against.
        _rec.registry.counter(
            "hvdt_distributed_optimizer_builds_total",
            "DistributedOptimizer constructions, labelled op/compression"
        ).inc(op=ReduceOp(op).name.lower(),
              compression=getattr(compression, "__name__", "none"),
              backward_passes=str(backward_passes_per_step),
              pipeline=pipeline or "off", expert=expert or "off")
    comm = DistributedGradientTransformation(
        axis=axis, op=op, compression=compression,
        threshold_bytes=threshold_bytes, prescale_factor=prescale_factor,
        postscale_factor=postscale_factor, zero=_stage)
    if backward_passes_per_step > 1:
        # Communication precedes accumulation so every value MultiSteps
        # holds across its internal lax.cond is replicated (type-stable
        # under JAX's varying-manual-axes tracking).  To also SKIP
        # collectives on non-boundary micro-steps — the reference's
        # bandwidth optimization (gradient_aggregation.py) — use the
        # TPU-idiomatic microbatch_gradients() inside one jitted step,
        # which issues a single fused collective per k micro-batches.
        return optax.chain(
            comm,
            optax.MultiSteps(optimizer,
                             every_k_schedule=backward_passes_per_step))
    return optax.chain(comm, optimizer)
